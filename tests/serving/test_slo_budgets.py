"""The resource-budget SLO objectives (keystone_tpu/serving/slo.py):
per-tenant device-second spend and the device-memory watermark — and the
autoscaler's refusal to treat either as capacity evidence."""

from keystone_tpu.serving.slo import SloBreach, SloPolicy


def _row(**over):
    row = {"ts": 100.0, "counters": {}, "gauges": {}}
    row.update(over)
    return row


def test_tenant_budget_breach_names_the_overspender():
    policy = SloPolicy(tenant_device_s_budget=0.25)
    breaches = policy.evaluate(_row(costs={
        "gold": {"device_s": 0.4, "items": 7},
        "bronze": {"device_s": 0.1, "items": 2},
    }))
    (b,) = breaches
    assert b.objective == "tenant_device_s_budget"
    assert b.detail == "gold" and b.observed == 0.4 and b.budget == 0.25
    assert b.as_attrs()["detail"] == "gold"


def test_each_overspending_tenant_breaches_separately():
    policy = SloPolicy(tenant_device_s_budget=0.05)
    breaches = policy.evaluate(_row(costs={
        "a": {"device_s": 0.1}, "b": {"device_s": 0.2},
    }))
    assert sorted(b.detail for b in breaches) == ["a", "b"]


def test_rows_without_costs_never_breach_the_tenant_budget():
    policy = SloPolicy(tenant_device_s_budget=0.0)
    assert policy.evaluate(_row()) == []


def test_device_mem_budget_judges_the_watermark_gauge():
    policy = SloPolicy(device_mem_budget_bytes=1000)
    (b,) = policy.evaluate(_row(gauges={"device_mem_bytes": 2048.0}))
    assert b.objective == "device_mem_budget_bytes"
    assert b.observed == 2048.0
    assert policy.evaluate(_row(gauges={"device_mem_bytes": 512.0})) == []
    # no reading (accounting off / gauge absent): not judged
    assert policy.evaluate(_row()) == []


def test_fleet_wide_breaches_carry_no_detail():
    b = SloBreach("p99_budget_s", 0.5, 0.1, 100.0)
    assert b.detail == "" and "detail" not in b.as_attrs()


def test_resource_breaches_never_buy_scale_ups():
    from keystone_tpu.autoscale.policy import ScalePolicy
    from keystone_tpu.autoscale.scaler import (
        NON_CAPACITY_OBJECTIVES,
        Autoscaler,
    )

    assert NON_CAPACITY_OBJECTIVES == {
        "tenant_device_s_budget", "device_mem_budget_bytes",
    }

    class Actuator:
        service_estimate = 0.01

        def scale_view(self):
            return {"admitting": 1, "booting": 0, "draining": 0}

        def __init__(self):
            self.spawns = 0

        def scale_up_slot(self):
            self.spawns += 1
            raise RuntimeError("spawn refused (stub)")

        def pick_drain_candidate(self):
            return None

        def reap_slot(self, index):
            pass

    policy = ScalePolicy(min_workers=1, max_workers=4, up_breaches=1,
                         up_cooldown_s=0.0)
    actuator = Actuator()
    scaler = Autoscaler(policy, actuator)
    breaches = [
        SloBreach("tenant_device_s_budget", 9.0, 1.0, 100.0, detail="gold"),
        SloBreach("device_mem_budget_bytes", 2e9, 1e9, 100.0),
    ]
    assert scaler.tick(breaches=breaches, row=_row()) == []
    assert len(scaler._breach_window) == 0
    assert actuator.spawns == 0
    # ...while a capacity breach with the same plumbing DOES try to spawn
    capacity = [SloBreach("queue_age_p99_budget_s", 0.9, 0.1, 100.0)]
    decisions = scaler.tick(breaches=capacity, row=_row())
    assert actuator.spawns == 1
    assert [d.reason for d in decisions] == ["breach"]
