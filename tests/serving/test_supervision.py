"""Replica supervision: killed/quarantined workers requeue their work
(deadlines intact), restart within budget, and never strand a request —
plus the bounded-shutdown satellite (a wedged replica cannot block
shutdown forever) and the deadline-under-requeue semantics."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.serving import EngineStopped, ServingFleet, Shed
from keystone_tpu.serving.batching import BucketPolicy
from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.serving.replica import _Request
from keystone_tpu.serving.scheduler import FleetScheduler
from keystone_tpu.workflow.transformer import FunctionNode


def _fitted(label="double"):
    return FunctionNode(
        batch_fn=lambda X: X * 2.0, label=label
    ).to_pipeline().fit()


def _hammer(fleet, n=48, clients=8, timeout=30.0):
    with ThreadPoolExecutor(max_workers=clients) as pool:
        return list(pool.map(
            lambda i: float(np.asarray(
                fleet.predict(np.full(3, float(i)), timeout=timeout)
            ).ravel()[0]),
            range(n),
        ))


# ---------------------------------------------------------------------------
# kill / restart
# ---------------------------------------------------------------------------


def test_replica_kill_loses_zero_accepted_requests_and_restarts():
    """The chaos gate: a mid-load thread kill answers every accepted
    request correctly; the supervisor requeues and restarts."""
    faults.install(faults.parse_plan("replica.batch#1=kill@2"))
    fleet = ServingFleet(
        _fitted(), replicas=2, buckets=(4, 8), datum_shape=(3,),
        max_wait_ms=1.0,
    )
    with fleet:
        res = _hammer(fleet, 48)
    for i, r in enumerate(res):
        assert abs(r - 2.0 * i) < 1e-4
    c = fleet.metrics.snapshot()["counters"]
    assert c["completed"] == c["submitted"] == 48
    assert c["restarts"] >= 1
    assert c["requeues"] >= 1
    assert c.get("batch_errors", 0) == 0


def test_kill_and_restart_land_in_the_trace():
    from keystone_tpu.obs import tracer as obs_tracer

    faults.install(faults.parse_plan("replica.batch=kill@1"))
    tr = obs_tracer.install(obs_tracer.Tracer())
    try:
        fleet = ServingFleet(
            _fitted(), replicas=2, buckets=(4,), datum_shape=(3,),
            max_wait_ms=1.0,
        )
        with fleet:
            _hammer(fleet, 24)
    finally:
        obs_tracer.uninstall(tr)
    names = {s.name for s in tr.spans()}
    assert "fault.inject" in names
    assert "fault.replica_down" in names
    assert "fault.replica_restart" in names


def test_quarantine_after_consecutive_transient_failures():
    """K consecutive transient batch failures circuit-break the replica:
    its batches requeue to the peer, the breaker trips, the supervisor
    restarts it, and no request is lost."""
    faults.install(faults.parse_plan("replica.batch#0=transient@0,1,2"))
    fleet = ServingFleet(
        _fitted(), replicas=2, buckets=(4,), datum_shape=(3,),
        max_wait_ms=1.0, quarantine_after=3,
    )
    total = 0
    with fleet:
        # waves until replica 0 has pulled (and transiently failed) its
        # three scheduled batches — how the load interleaves across the
        # two workers is timing-dependent, the fault schedule is not
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            res = _hammer(fleet, 16)
            assert all(abs(r - 2.0 * i) < 1e-4 for i, r in enumerate(res))
            total += len(res)
            c = fleet.metrics.snapshot()["counters"]
            if c.get("quarantined", 0) >= 1:
                break
        else:
            pytest.fail("replica 0 never tripped its circuit breaker")
    c = fleet.metrics.snapshot()["counters"]
    assert c["completed"] == c["submitted"] == total  # nothing lost
    assert c["quarantined"] == 1
    assert c["restarts"] >= 1
    assert c["batch_transient"] == 3
    assert c["requeues"] >= 3


def test_restart_budget_exhaustion_leaves_the_peer_serving():
    """A replica that keeps dying exhausts its budget and stays down;
    the survivor serves everything (admission avoids the dead queue)."""
    faults.install(faults.parse_plan("replica.batch#0=kill@p1.0x9s1"))
    fleet = ServingFleet(
        _fitted(), replicas=2, buckets=(4,), datum_shape=(3,),
        max_wait_ms=1.0, max_restarts=1,
    )
    with fleet:
        res = _hammer(fleet, 32)
    assert all(abs(r - 2.0 * i) < 1e-4 for i, r in enumerate(res))
    c = fleet.metrics.snapshot()["counters"]
    assert c["completed"] == 32
    assert c["restarts"] == 1  # budget, not the kill count


def test_all_replicas_down_fails_typed_never_hangs():
    faults.install(faults.parse_plan("replica.batch=kill@p1.0x9s1"))
    fleet = ServingFleet(
        _fitted(), replicas=1, buckets=(4,), datum_shape=(3,),
        max_wait_ms=1.0, max_restarts=0,
    )
    fleet.start()
    try:
        f = fleet.submit(np.zeros(3, np.float32))
        with pytest.raises(EngineStopped):
            f.result(timeout=10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                fleet.submit(np.zeros(3, np.float32))
            except EngineStopped:
                break  # admission now refuses typed
            time.sleep(0.01)
        else:
            pytest.fail("admission kept accepting with no live replicas")
    finally:
        fleet.shutdown()


def test_supervise_off_requeues_but_never_restarts():
    faults.install(faults.parse_plan("replica.batch#0=kill@0"))
    fleet = ServingFleet(
        _fitted(), replicas=2, buckets=(4,), datum_shape=(3,),
        max_wait_ms=1.0, supervise=False,
    )
    with fleet:
        res = _hammer(fleet, 24)
    assert all(abs(r - 2.0 * i) < 1e-4 for i, r in enumerate(res))
    c = fleet.metrics.snapshot()["counters"]
    assert c["completed"] == 24
    assert c.get("restarts", 0) == 0  # work moved to the peer instead


# ---------------------------------------------------------------------------
# deadline semantics under requeue (satellite)
# ---------------------------------------------------------------------------


def _sched(n=2, buckets=(4,)):
    return FleetScheduler(
        n,
        BucketPolicy(buckets, datum_shape=(2,)),
        MetricsRegistry("supervision-test"),
    )


def test_requeued_request_keeps_its_original_deadline():
    s = _sched()
    now = time.monotonic()
    req = _Request(datum="d", deadline=now + 30.0, enqueued=now)
    req.future.set_running_or_notify_cancel()  # it was mid-batch
    moved = s.requeue_batch([req], SimpleNamespace(index=0))
    assert moved == 1
    depths = s.queue_depths()
    assert depths == [0, 1]  # rerouted to the peer, at the front
    clone = s._queues[1][0]
    assert clone.deadline == req.deadline  # deadline intact, not reset
    assert clone.enqueued == req.enqueued
    # the clone's outcome flows back to the original future
    clone.future.set_running_or_notify_cancel()
    clone.future.set_result("answer")
    assert req.future.result(timeout=1) == "answer"


def test_unmeetable_requeued_deadline_is_shed_typed_not_expired():
    s = _sched()
    s.observe_service(1.0)  # learned service time: 1s per batch
    now = time.monotonic()
    doomed = _Request(datum="d", deadline=now + 0.05, enqueued=now)
    doomed.future.set_running_or_notify_cancel()
    ok = _Request(datum="d", deadline=now + 30.0, enqueued=now)
    moved = s.requeue_batch([doomed, ok], SimpleNamespace(index=0))
    assert moved == 1  # only the meetable one re-entered
    with pytest.raises(Shed):
        doomed.future.result(timeout=1)
    assert s._metrics.count("shed") == 1


def test_queued_requeue_sheds_unmeetable_and_moves_the_rest():
    s = _sched()
    s.observe_service(1.0)
    tight = _Request(
        datum="d", deadline=time.monotonic() + 0.05, enqueued=time.monotonic()
    )
    loose = _Request(
        datum="d", deadline=time.monotonic() + 30.0, enqueued=time.monotonic()
    )
    with s._cond:
        s._queues[0].extend([tight, loose])
        s._depth = 2
    s.set_active(0, False)
    moved = s.requeue_replica(0)
    assert moved == 1
    assert s.queue_depths() == [0, 1]
    with pytest.raises(Shed):
        tight.future.result(timeout=1)
    assert s.depth == 1  # the shed request left the depth accounting


def test_requeue_hop_cap_answers_with_the_cause_instead_of_bouncing():
    """A deadline-less request rerouted off MAX_REQUEUE_HOPS failed
    replicas stops bouncing and is answered with the recurring failure."""
    s = _sched()
    req = _Request(
        datum="d", deadline=None, enqueued=time.monotonic(),
        hops=FleetScheduler.MAX_REQUEUE_HOPS,
    )
    req.future.set_running_or_notify_cancel()
    moved = s.requeue_batch(
        [req], SimpleNamespace(index=0), RuntimeError("recurring fault")
    )
    assert moved == 0
    with pytest.raises(RuntimeError, match="recurring fault"):
        req.future.result(timeout=1)


def test_requeue_clone_carries_the_hop_count():
    s = _sched()
    req = _Request(datum="d", deadline=None, enqueued=time.monotonic())
    req.future.set_running_or_notify_cancel()
    assert s.requeue_batch([req], SimpleNamespace(index=0)) == 1
    clone = s._queues[1][0]
    assert clone.hops == 1


def test_engine_worker_death_closes_admission_and_shutdown_returns():
    """The single-worker engine has no supervisor: a dead worker must
    close admission (no stranded futures, no drain deadlock)."""
    from keystone_tpu.serving import EngineClosed, ServingEngine

    faults.install(faults.parse_plan("replica.batch=kill@0"))
    eng = ServingEngine(_fitted(), buckets=(4,), datum_shape=(3,))
    eng.start()
    f = eng.submit(np.zeros(3, np.float32))
    with pytest.raises(EngineClosed):
        f.result(timeout=10)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            eng.submit(np.zeros(3, np.float32))
        except EngineClosed:
            break
        time.sleep(0.01)
    else:
        pytest.fail("admission stayed open after the worker died")
    t0 = time.monotonic()
    eng.shutdown(drain=True)  # must not deadlock on queue.join()
    assert time.monotonic() - t0 < 10.0


def test_requeue_with_no_live_peer_fails_typed():
    s = _sched()
    s.set_active(0, False)
    s.set_active(1, False)
    req = _Request(datum="d", deadline=None, enqueued=time.monotonic())
    req.future.set_running_or_notify_cancel()
    moved = s.requeue_batch([req], SimpleNamespace(index=0))
    assert moved == 0
    with pytest.raises(EngineStopped):
        req.future.result(timeout=1)


def test_admission_avoids_inactive_replicas():
    s = _sched()
    s.set_active(0, False)
    for _ in range(3):
        s.admit(_Request(datum="d", deadline=None, enqueued=time.monotonic()))
    assert s.queue_depths() == [0, 3]


# ---------------------------------------------------------------------------
# bounded shutdown (satellite)
# ---------------------------------------------------------------------------


def test_wedged_replica_shutdown_is_bounded():
    """A replica stuck inside its batch: shutdown drains with a timeout,
    joins with a timeout, WARNs, and still answers every admitted
    request typed — never blocks forever."""
    import jax

    release = threading.Event()

    def _stall(x):
        release.wait(timeout=30.0)
        return x

    def body(X):
        return jax.pure_callback(
            _stall, jax.ShapeDtypeStruct(X.shape, X.dtype), X
        )

    fitted = FunctionNode(batch_fn=body, label="wedge").to_pipeline().fit()
    fleet = ServingFleet(
        fitted, replicas=1, buckets=(1,), datum_shape=(3,),
        max_wait_ms=1.0, join_timeout_s=0.5, drain_timeout_s=0.5,
    )
    fleet.start(warmup=False)
    try:
        wedged = fleet.submit(np.zeros(3, np.float32))
        time.sleep(0.2)  # let the batch dispatch and wedge
        queued = fleet.submit(np.zeros(3, np.float32))
        t0 = time.monotonic()
        fleet.shutdown(drain=True)
        assert time.monotonic() - t0 < 10.0  # bounded, not forever
        with pytest.raises(EngineStopped):
            queued.result(timeout=1)
        with pytest.raises(EngineStopped):
            wedged.result(timeout=1)
    finally:
        release.set()
        time.sleep(0.1)  # let the wedged thread unwind before teardown
