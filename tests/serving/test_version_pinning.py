"""Per-replica version pinning during long rollouts (ISSUE 14
satellite): the fleet snapshot reports each replica's model version, and
a canary window that outlives a replica restart re-pins the restarted
replica to the OLD version until promotion."""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu import faults
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.serving import ServingFleet
from keystone_tpu.workflow.transformer import FunctionNode

D, K = 12, 3


def _fit(seed=0, n=256):
    r = np.random.RandomState(seed)
    X = (r.randn(n, D) + 1.0).astype(np.float32)
    Y = (np.tanh(X) @ r.randn(D, K).astype(np.float32)).astype(np.float32)
    return (
        FunctionNode(batch_fn=lambda A: jnp.tanh(A), label="feat")
        .to_pipeline()
        .and_then(
            LinearMapEstimator(lam=1e-2, snapshot=True),
            ChunkedDataset.from_array(X, 64),
            Dataset.of(Y),
        )
        .fit(),
        X,
    )


def _wait(pred, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_version_report_tracks_promotions():
    fitted, X = _fit()
    replacement = fitted.absorb(Dataset.of(X[:64]), Dataset.of(
        np.asarray(fitted.apply(Dataset.of(X[:64])).to_array())
    ))
    fleet = ServingFleet(
        fitted, replicas=2, buckets=(8,), datum_shape=(D,), max_wait_ms=1.0
    )
    with fleet:
        r = fleet.version_report()
        assert r["version"] == 1 and not r["skew"]
        assert {row["version"] for row in r["replicas"].values()} == {1}
        report = fleet.swap(replacement)  # no canary: straight promote
        assert report["version"] == 2
        r = fleet.version_report()
        assert r["version"] == 2 and not r["skew"]
        assert {row["version"] for row in r["replicas"].values()} == {2}
    assert fleet.model_version == 2


def test_restart_inside_canary_window_pins_old_version():
    """Kill a replica while a canaried swap's window is open: the
    supervisor restart must re-pin it to version 1 (the published
    model), and promotion afterwards moves EVERY replica to version 2 —
    never a mixed fleet."""
    fitted, X = _fit()
    labels = np.asarray(fitted.apply(Dataset.of(X[:64])).to_array())
    replacement = fitted.absorb(Dataset.of(X[:64]), Dataset.of(labels))
    fleet = ServingFleet(
        fitted, replicas=2, buckets=(8,), datum_shape=(D,), max_wait_ms=1.0
    )
    swap_result = {}

    def do_swap():
        try:
            # a WIDE window (many mirrored batches): promotion must not
            # be able to outrun the kill scheduled inside the window —
            # replica 1 sees a batch long before 48 mirror, the fleet
            # restarts it mid-window, and only then does the window
            # close and promote
            swap_result["report"] = fleet.swap(
                replacement,
                canary_fraction=1.0,
                canary_batches=48,
                canary_timeout_s=60.0,
                atol=0.5, rtol=0.5,
            )
        except Exception as e:  # surfaced by the final assert
            swap_result["error"] = e

    stop = threading.Event()
    failures = []

    def traffic():
        i = 0
        while not stop.is_set():
            try:
                fleet.predict(X[i % len(X)], timeout=15.0)
            except Exception as e:
                failures.append(repr(e))
            i += 1

    with fleet:
        swapper = threading.Thread(target=do_swap, daemon=True)
        swapper.start()
        # window open = shadow installed on the replicas. Traffic starts
        # only AFTER: with zero live batches nothing mirrors, so the
        # window cannot close before the kill is scheduled inside it.
        assert _wait(
            lambda: any(r._shadow is not None for r in fleet.replicas)
        )
        faults.install(faults.parse_plan("replica.batch#1=kill@0"))
        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        assert _wait(lambda: fleet.metrics.count("restarts") >= 1)
        mid = fleet.version_report()
        # the restarted replica is PINNED to the old version — the
        # candidate can never leak in before promotion
        assert mid["version"] == 1 and not mid["skew"], mid
        faults.clear()
        swapper.join(timeout=90.0)
        assert not swapper.is_alive()
        stop.set()
        t.join(timeout=5)
        final = fleet.version_report()
    assert "error" not in swap_result, swap_result
    assert swap_result["report"]["version"] == 2
    assert final["version"] == 2 and not final["skew"], final
    assert {row["version"] for row in final["replicas"].values()} == {2}
    assert not failures
