"""FleetScheduler unit tests: admission math, continuous batch forming,
work-stealing rebalance, and drain/stop bookkeeping — driven directly
(no replicas, no jax) so every behavior is deterministic."""

import threading
import time
from types import SimpleNamespace

import pytest

from keystone_tpu.serving.batching import BucketPolicy
from keystone_tpu.serving.errors import EngineStopped, QueueFull, Shed
from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.serving.replica import STOP, _Request
from keystone_tpu.serving.scheduler import FleetScheduler


def _req(deadline=None):
    now = time.monotonic()
    return _Request(
        datum=None,
        deadline=(now + deadline) if deadline is not None else None,
        enqueued=now,
    )


def _sched(n=2, buckets=(4, 8), max_queue=64, max_wait_ms=1.0, steal=True):
    return FleetScheduler(
        n,
        BucketPolicy(buckets, datum_shape=(2,)),
        MetricsRegistry("sched-test"),
        max_queue=max_queue,
        max_wait_ms=max_wait_ms,
        steal=steal,
    )


def _replica(index):
    return SimpleNamespace(index=index, last_exec_seconds=None)


# ---------------------------------------------------------------------------
# admission
# ---------------------------------------------------------------------------


def test_estimated_wait_scales_with_depth_and_evidence():
    s = _sched(n=2, buckets=(4, 8))
    assert s.estimated_wait() == 0.0  # cold: no evidence, no estimate
    s.observe_service(0.1)
    assert s.estimated_wait() == pytest.approx(0.1)
    # 16 queued = one full fleet round (2 replicas x 8-bucket) ahead
    for _ in range(16):
        s.admit(_req())
    assert s.estimated_wait() == pytest.approx(0.2)


def test_ewma_follows_observations():
    s = _sched()
    s.observe_service(0.1)
    for _ in range(50):
        s.observe_service(0.5)
    assert 0.45 < s.service_estimate <= 0.5


def test_admit_sheds_unmeetable_deadline_and_counts():
    s = _sched(n=1, buckets=(4,))
    s.observe_service(0.5)
    for _ in range(3):
        with pytest.raises(Shed):
            s.admit(_req(deadline=0.05))
    assert s._metrics.count("shed") == 3
    # the same deadline with slack admits
    s.admit(_req(deadline=5.0))
    assert s.depth == 1


def test_admit_respects_queue_bound_and_close():
    s = _sched(n=1, buckets=(4,), max_queue=2)
    s.admit(_req())
    s.admit(_req())
    with pytest.raises(QueueFull):
        s.admit(_req())
    s.close()
    with pytest.raises(EngineStopped):
        s.admit(_req())


def test_admission_balances_to_shallowest_queue():
    s = _sched(n=2, steal=False)
    for _ in range(6):
        s.admit(_req())
    assert s.queue_depths() == [3, 3]


# ---------------------------------------------------------------------------
# continuous batch forming
# ---------------------------------------------------------------------------


def test_next_batch_dispatches_exactly_full_bucket_without_waiting():
    s = _sched(n=1, buckets=(4, 8), max_wait_ms=10_000.0)
    for _ in range(4):
        s.admit(_req())
    t0 = time.monotonic()
    batch = s.next_batch(_replica(0))
    # bucket 4 exactly full => occupancy 1.0, no reason to wait out the
    # (enormous) max-wait window
    assert len(batch) == 4
    assert time.monotonic() - t0 < 1.0


def test_request_arriving_during_forming_joins_the_batch():
    """Continuous batching: the forming batch admits arrivals until its
    bucket fills — no gather-then-dispatch barrier."""
    s = _sched(n=1, buckets=(2,), max_wait_ms=5_000.0)
    s.admit(_req())

    def late_arrival():
        time.sleep(0.1)
        s.admit(_req())

    t = threading.Thread(target=late_arrival)
    t.start()
    batch = s.next_batch(_replica(0))
    t.join()
    assert len(batch) == 2  # the late request joined, filling the bucket


def test_tight_deadline_forces_dispatch_instead_of_waiting():
    """A known service time + a tight deadline => the scheduler dispatches
    a partial bucket rather than waiting the deadline away."""
    s = _sched(n=1, buckets=(8,), max_wait_ms=10_000.0)
    s.observe_service(0.05)
    s.admit(_req(deadline=0.2))
    t0 = time.monotonic()
    batch = s.next_batch(_replica(0))
    waited = time.monotonic() - t0
    assert len(batch) == 1
    # dispatched within the deadline's slack, nowhere near max_wait
    assert waited < 0.25


def test_batch_done_learns_service_time_from_replica():
    s = _sched(n=1, buckets=(4,))
    s.admit(_req())
    rep = _replica(0)
    batch = s.next_batch(rep)
    rep.last_exec_seconds = 0.123
    s.batch_done(batch, rep)
    assert s.service_estimate == pytest.approx(0.123)


# ---------------------------------------------------------------------------
# work stealing
# ---------------------------------------------------------------------------


def _preload(s, index, n):
    """Force-place n requests on one queue (bypassing balanced admission)
    to model a replica whose bucket mix stalled it mid-drain."""
    with s._lock:
        for _ in range(n):
            s._queues[index].append(_req())
            s._depth += 1


def test_idle_replica_steals_newest_half_from_deepest_peer():
    s = _sched(n=2, buckets=(4, 8), max_wait_ms=1.0)
    _preload(s, 0, 12)
    batch = s.next_batch(_replica(1))  # replica 1's own queue is empty
    assert batch is not STOP and len(batch) >= 1
    assert s._metrics.count("steals") == 6  # newest half of 12
    # the victim kept its oldest half minus nothing (thief served from
    # its own queue after the move)
    depths = s.queue_depths()
    assert depths[0] == 6


def test_steal_disabled_pins_requests_to_their_queue():
    s = _sched(n=2, buckets=(4,), max_wait_ms=1.0, steal=False)
    _preload(s, 0, 8)

    got = []

    def try_take():
        # replica 1 must NOT serve replica 0's queue; it waits until stop
        got.append(s.next_batch(_replica(1)))

    t = threading.Thread(target=try_take)
    t.start()
    time.sleep(0.3)
    s.stop()
    t.join(timeout=5)
    assert got == [STOP]
    assert s.queue_depths()[0] == 8


# ---------------------------------------------------------------------------
# drain / stop
# ---------------------------------------------------------------------------


def test_wait_idle_blocks_until_queues_and_inflight_clear():
    s = _sched(n=1, buckets=(4,))
    s.admit(_req())
    assert s.wait_idle(timeout=0.2) is False  # queued work: not idle
    rep = _replica(0)
    batch = s.next_batch(rep)
    assert s.wait_idle(timeout=0.2) is False  # in-flight: still not idle
    s.batch_done(batch, rep)
    assert s.wait_idle(timeout=5.0) is True


def test_fail_remaining_answers_everything_with_engine_stopped():
    s = _sched(n=2, buckets=(4,))
    reqs = [_req() for _ in range(5)]
    for r in reqs:
        s.admit(r)
    assert s.fail_remaining() == 5
    for r in reqs:
        with pytest.raises(EngineStopped):
            r.future.result(timeout=1)
    assert s.depth == 0
