"""BucketPolicy: bucket selection, padding, validation, warm-up inputs."""

import numpy as np
import pytest

from keystone_tpu.serving.batching import BucketPolicy
from keystone_tpu.serving.errors import InvalidRequest


def test_bucket_for_picks_smallest_fitting():
    p = BucketPolicy(batch_sizes=(32, 1, 8))  # unsorted on purpose
    assert p.batch_sizes == (1, 8, 32)
    assert p.bucket_for(1) == 1
    assert p.bucket_for(2) == 8
    assert p.bucket_for(8) == 8
    assert p.bucket_for(9) == 32
    assert p.max_size == 32
    with pytest.raises(ValueError):
        p.bucket_for(33)
    with pytest.raises(ValueError):
        p.bucket_for(0)


def test_invalid_bucket_sizes_rejected():
    with pytest.raises(ValueError):
        BucketPolicy(batch_sizes=())
    with pytest.raises(ValueError):
        BucketPolicy(batch_sizes=(0, 4))


def test_pad_repeats_first_row():
    p = BucketPolicy(batch_sizes=(4,), datum_shape=(2,))
    x = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    padded = p.pad(x, 4)
    assert padded.shape == (4, 2)
    np.testing.assert_array_equal(padded[2], x[0])
    np.testing.assert_array_equal(padded[3], x[0])
    # already-full batches pass through untouched
    assert p.pad(padded, 4) is padded
    with pytest.raises(ValueError):
        p.pad(padded, 2)


def test_validate_enforces_configured_shape():
    p = BucketPolicy(datum_shape=(3,))
    out = p.validate([1, 2, 3])
    assert out.dtype == np.float32 and out.shape == (3,)
    with pytest.raises(InvalidRequest):
        p.validate([1, 2])
    with pytest.raises(InvalidRequest):
        p.validate("not a number")


def test_validate_locks_shape_from_first_datum():
    p = BucketPolicy()
    assert p.datum_shape is None
    p.validate(np.zeros((5,)))
    assert p.datum_shape == (5,)
    with pytest.raises(InvalidRequest):
        p.validate(np.zeros((6,)))


def test_warmup_inputs_cover_every_bucket():
    p = BucketPolicy(batch_sizes=(2, 4), datum_shape=(3,), dtype=np.float32)
    inputs = list(p.warmup_inputs())
    assert [x.shape for x in inputs] == [(2, 3), (4, 3)]
    assert all(x.dtype == np.float32 for x in inputs)
    with pytest.raises(ValueError):
        list(BucketPolicy().warmup_inputs())
