"""QoS at the fleet's admission surface: weighted-fair tenancy (DRR),
deterministic priority shed ordering, QoS identity surviving requeues
and steals, and the cold-scheduler never-shed guarantee."""

import time

import pytest

from keystone_tpu.autoscale.qos import (
    SHED_BIAS,
    WeightedFairQueue,
    normalize_priority,
)
from keystone_tpu.serving.batching import BucketPolicy
from keystone_tpu.serving.errors import Shed
from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.serving.replica import _Request
from keystone_tpu.serving.scheduler import FleetScheduler


def req(priority="normal", tenant="default", deadline=None, hops=0):
    return _Request(
        datum=None, deadline=deadline, enqueued=time.monotonic(),
        hops=hops, priority=priority, tenant=tenant,
    )


def make_sched(n=1, weights=None, max_size=1, max_queue=1024):
    return FleetScheduler(
        n,
        BucketPolicy(batch_sizes=(max_size,)),
        MetricsRegistry(),
        max_queue=max_queue,
        tenant_weights=weights,
    )


# -- the weighted-fair queue ----------------------------------------------


def test_wfq_serves_tenants_in_weight_ratio():
    q = WeightedFairQueue({"a": 3.0, "b": 1.0})
    for i in range(12):
        q.append(req(tenant="a"))
        q.append(req(tenant="b"))
    assert len(q) == 24
    # DRR with quanta 1.0 / (1/3): three 'a' serves per 'b' serve,
    # deterministically — the exact schedule, not just the ratio
    order = [q.popleft().tenant for _ in range(16)]
    assert order[:8] == ["a", "a", "a", "b", "a", "a", "a", "b"]
    assert order.count("a") == 12 and order.count("b") == 4
    # 'a' exhausted: the sole remaining tenant drains directly
    assert [q.popleft().tenant for _ in range(8)] == ["b"] * 8
    assert len(q) == 0
    with pytest.raises(IndexError):
        q.popleft()


def test_wfq_priority_orders_within_one_tenant_only():
    q = WeightedFairQueue()
    a_low, a_high = req("low", "a"), req("high", "a")
    b_norm = req("normal", "b")
    q.append(a_low)
    q.append(b_norm)
    q.append(a_high)
    # within tenant 'a', high jumps low; across tenants the fairness
    # round still alternates — 'a' cannot pre-empt 'b' by going high
    assert q.popleft() is a_high
    assert q.popleft() is b_norm
    assert q.popleft() is a_low


def test_wfq_emptied_tenant_forfeits_deficit():
    q = WeightedFairQueue({"a": 1.0, "b": 1.0})
    q.append(req(tenant="a"))
    q.append(req(tenant="b"))
    assert q.popleft().tenant == "a"
    assert q.popleft().tenant == "b"
    # 'a' re-arrives after emptying: no banked credit, normal rotation
    q.append(req(tenant="a"))
    assert q.popleft().tenant == "a"


def test_wfq_steal_takes_lowest_class_newest_from_deepest():
    q = WeightedFairQueue()
    q.append(req("high", "a"))
    old_low = req("low", "b")
    new_low = req("low", "b")
    q.append(old_low)
    q.append(new_low)
    # the stealing thief gets the NEWEST request of the LOWEST populated
    # rank — the victim keeps its oldest work and its best class
    assert q.pop() is new_low
    assert q.pop() is old_low
    assert q.pop().priority == "high"


def test_wfq_appendleft_requeues_into_own_lane_front():
    q = WeightedFairQueue()
    first, second = req("normal", "a"), req("normal", "a")
    q.append(first)
    q.append(second)
    rerouted = req("normal", "a")
    q.appendleft(rerouted)
    assert q.popleft() is rerouted
    high = req("high", "a")
    q.appendleft(high)  # its own RANK's front — which dispatches first
    assert q.popleft() is high
    assert q.popleft() is first and q.popleft() is second


def test_wfq_introspection_and_validation():
    with pytest.raises(ValueError):
        WeightedFairQueue({"a": 0.0})
    q = WeightedFairQueue({"a": 2.0})
    q.append(req("high", "a"))
    q.append(req("low", "b"))
    q.append(req("low", "b"))
    assert q.rank_lens() == [1, 0, 2]
    assert q.tenant_depths() == {"a": 1, "b": 2}
    assert q.weight("a") == 2.0 and q.weight("b") == 1.0
    assert len(list(q)) == 3 and q[0].priority == "high"


# -- priority vocabulary ---------------------------------------------------


def test_priority_vocabulary_is_closed():
    assert normalize_priority(None) == "normal"
    assert normalize_priority("HIGH") == "high"
    with pytest.raises(ValueError):
        normalize_priority("urgent")
    assert SHED_BIAS["high"] < SHED_BIAS["normal"] < SHED_BIAS["low"]


# -- admission: deterministic shed ordering --------------------------------


def test_shed_ordering_low_before_high_at_equal_slack():
    sched = make_sched(n=1, max_size=1)
    sched.observe_service(0.1)  # learned: 0.1s per micro-batch
    for _ in range(4):
        sched.admit(req())  # four queued normals, no deadline
    # equal slack: the wait each class must pay differs — high prices
    # only its own (empty) class, low pays for everything queued
    slack = 0.3
    with pytest.raises(Shed):
        sched.admit(req("low", deadline=time.monotonic() + slack))
    with pytest.raises(Shed):
        sched.admit(req("normal", deadline=time.monotonic() + slack))
    sched.admit(req("high", deadline=time.monotonic() + slack))
    counters = sched._metrics.snapshot()["counters"]
    assert counters["shed"] == 2
    assert counters["shed.low"] == 1 and counters["shed.normal"] == 1
    assert "shed.high" not in counters
    snap = sched.qos_snapshot()
    assert snap["queued_by_priority"] == {"high": 1, "normal": 4, "low": 0}


def test_cold_scheduler_never_sheds():
    sched = make_sched(n=1, max_size=1)
    assert sched.service_estimate is None
    for _ in range(50):
        sched.admit(req())
    # deadline nearly NOW and 50 ahead in queue — but with no service
    # evidence the estimate is 0.0: admission cannot justify refusing
    sched.admit(req("low", deadline=time.monotonic() + 0.001))
    assert "shed" not in sched._metrics.snapshot()["counters"]


def test_estimated_wait_prices_same_or_better_class_only():
    sched = make_sched(n=1, max_size=1)
    sched.observe_service(0.1)
    sched.admit(req("normal"))
    sched.admit(req("normal"))
    sched.admit(req("low"))
    # rank 0 (high): nothing queued above it -> one batch service time
    assert sched.estimated_wait(0) == pytest.approx(0.1)
    # rank 1 (normal): pays the two normals
    assert sched.estimated_wait(1) == pytest.approx(0.1 * 3)
    # rank 2 (low): pays everything
    assert sched.estimated_wait(2) == pytest.approx(0.1 * 4)


# -- requeue / clone identity ----------------------------------------------


class _ReplicaStub:
    def __init__(self, index):
        self.index = index


def test_requeue_batch_clones_preserve_qos_identity():
    sched = make_sched(n=2, weights={"gold": 2.0})
    orig = req("high", "gold")
    moved = sched.requeue_batch([orig], _ReplicaStub(0))
    assert moved == 1
    clone = sched._queues[1][0]
    assert clone is not orig
    assert clone.priority == "high" and clone.tenant == "gold"
    assert clone.hops == orig.hops + 1
    assert clone.deadline == orig.deadline
    assert clone.enqueued == orig.enqueued


def test_requeue_replica_moves_queued_with_identity():
    sched = make_sched(n=2)
    r = req("low", "bronze")
    sched.admit(r)
    # admit placed it on the shallowest queue; force it onto 0 for the test
    if not sched._queues[0]:
        sched._queues[0].append(sched._queues[1].popleft())
    moved = sched.requeue_replica(0)
    assert moved == 1
    landed = sched._queues[1][0]
    assert landed is r  # queued (not in-flight) requests move, not clone
    assert landed.priority == "low" and landed.tenant == "bronze"


def test_requeued_unmeetable_deadline_sheds_typed_per_class():
    sched = make_sched(n=2, max_size=1)
    sched.observe_service(1.0)
    for _ in range(3):
        sched.admit(req())
    doomed = req("low", deadline=time.monotonic() + 0.5)
    moved = sched.requeue_batch([doomed], _ReplicaStub(0))
    assert moved == 0
    with pytest.raises(Shed):
        doomed.future.result(timeout=1)
    assert sched._metrics.snapshot()["counters"]["shed.low"] == 1
