"""ServingEngine: lifecycle, backpressure, deadlines, error isolation, and
the acceptance scenario — 64+ concurrent requests through a fitted
MNIST-style pipeline on the 8-device virtual CPU mesh with exactly one
compile per bucket and responses matching direct application."""

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from keystone_tpu.serving import (
    DeadlineExceeded,
    EngineClosed,
    InvalidRequest,
    QueueFull,
    ServingEngine,
)
from keystone_tpu.workflow.pipeline import NotTraceableError
from keystone_tpu.workflow.transformer import FunctionNode


def _toy_fitted():
    """A cheap transformer-only chain (row-wise, traceable)."""
    return (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="double")
        >> FunctionNode(batch_fn=lambda X: X.sum(axis=1), label="rowsum")
    ).fit()


# ---------------------------------------------------------------------------
# lifecycle / admission
# ---------------------------------------------------------------------------


def test_predict_before_start_raises_instead_of_hanging():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    with pytest.raises(RuntimeError):
        engine.predict(np.ones(2))
    # submit() still buffers pre-start; the future resolves once started
    fut = engine.submit(np.ones(2))
    engine.start()
    assert abs(fut.result(timeout=30) - 4.0) < 1e-6
    engine.shutdown()


def test_batch_coupled_chain_rejected_at_construction():
    fitted = FunctionNode(
        batch_fn=lambda X: X - X.mean(axis=0), label="batchmean"
    ).to_pipeline().fit()
    for node in fitted.graph.nodes:
        # mark the chain the way whole-batch-statistics transformers do
        fitted.graph.get_operator(node).batch_coupled = True
    with pytest.raises(ValueError, match="batch-coupled"):
        ServingEngine(fitted, datum_shape=(2,))


def test_engine_jit_is_private_to_the_engine():
    """Construction must not hijack the pipeline's own compiled state —
    a later fitted.compile()/apply_compiled cannot pollute the engine's
    compile accounting, nor discard its warm cache."""
    fitted = _toy_fitted()
    engine = ServingEngine(fitted, buckets=(4,), datum_shape=(2,))
    engine.start()
    assert engine.metrics.count("compiles") == 1
    # direct pipeline use traces its own jit; engine accounting unmoved
    fitted.compile()(np.zeros((7, 2), np.float32))
    assert fitted.compile_count == 1
    assert engine.metrics.count("compiles") == 1
    assert abs(engine.predict(np.ones(2), timeout=30.0) - 4.0) < 1e-6
    assert engine.metrics.count("compiles") == 1
    engine.shutdown()


def test_concurrent_shutdown_is_safe():
    import threading

    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    engine.start()
    errors = []

    def close():
        try:
            engine.shutdown(drain=True)
        except Exception as e:  # pragma: no cover - the regression itself
            errors.append(e)

    threads = [threading.Thread(target=close) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []


def test_unbounded_queue_config_rejected():
    with pytest.raises(ValueError, match="max_queue"):
        ServingEngine(_toy_fitted(), datum_shape=(2,), max_queue=0)


def test_worker_survives_failing_gauge():
    engine = ServingEngine(
        _toy_fitted(), buckets=(4,), datum_shape=(2,), log_interval_s=0.0
    )

    def bad_gauge():
        raise RuntimeError("gauge exploded")

    engine.metrics.set_gauge("bad", bad_gauge)
    engine.start()
    # maybe_log fires after every batch (interval 0) and its snapshot hits
    # the raising gauge; the worker must keep serving regardless
    assert abs(engine.predict(np.ones(2), timeout=30.0) - 4.0) < 1e-6
    assert abs(engine.predict(np.ones(2), timeout=30.0) - 4.0) < 1e-6
    engine.shutdown()


def test_construction_fails_fast_on_untraceable_pipeline():
    fitted = FunctionNode(item_fn=lambda x: x, label="host_only").to_pipeline().fit()
    with pytest.raises(NotTraceableError) as exc:
        ServingEngine(fitted, datum_shape=(2,))
    assert "host_only" in exc.value.labels


def test_queue_full_rejects_instead_of_growing():
    engine = ServingEngine(
        _toy_fitted(), buckets=(4,), datum_shape=(2,), max_queue=4
    )
    # worker not started: the queue fills and the 5th submit is shed
    futs = [engine.submit(np.ones(2)) for _ in range(4)]
    with pytest.raises(QueueFull):
        engine.submit(np.ones(2))
    assert engine.metrics.count("rejected") == 1
    # once the worker runs, the queued four complete normally
    engine.start()
    assert all(abs(f.result(timeout=30) - 4.0) < 1e-6 for f in futs)
    engine.shutdown()
    assert engine.metrics.count("completed") == 4


def test_submit_after_drain_raises_engine_closed():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    engine.start()
    engine.shutdown(drain=True)
    with pytest.raises(EngineClosed):
        engine.submit(np.ones(2))


def test_submit_under_shutdown_is_typed_engine_stopped():
    """The admission-vs-shutdown check-and-enqueue is atomic and the
    refusal is the TYPED EngineStopped (refining EngineClosed), so fleet
    callers can branch on an orderly stop without string-matching."""
    from keystone_tpu.serving import EngineStopped

    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    engine.start()
    engine.shutdown(drain=True)
    with pytest.raises(EngineStopped):
        engine.submit(np.ones(2))
    # a request swept at shutdown resolves to the same typed error
    engine2 = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    fut = engine2.submit(np.ones(2))
    engine2.shutdown()
    with pytest.raises(EngineStopped):
        fut.result(timeout=5)


def test_shutdown_without_start_rejects_queued_requests():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    fut = engine.submit(np.ones(2))
    engine.shutdown()  # must not hang waiting on a worker that never ran
    with pytest.raises(EngineClosed):
        fut.result(timeout=5)


def test_request_landing_during_shutdown_is_not_stranded():
    """A submit that slips its put past shutdown's drain (TOCTOU on the
    _closed check) must still reach a terminal state via the post-join
    queue sweep."""
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    engine.start()
    engine.shutdown(drain=True)
    # simulate the race: the request is enqueued after the worker exited
    import queue as _queue
    from keystone_tpu.serving.engine import _Request

    late = _Request(datum=np.ones(2), deadline=None, enqueued=time.monotonic())
    try:
        engine._queue.put_nowait(late)
    except _queue.Full:
        pytest.skip("queue unexpectedly full")
    engine.shutdown()  # idempotent; runs the sweep that catches racing puts
    with pytest.raises(EngineClosed):
        late.future.result(timeout=5)


def test_abortive_shutdown_fails_queued_requests():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    futs = [engine.submit(np.ones(2)) for _ in range(3)]
    engine.start()
    engine.shutdown(drain=False)
    # every fate is terminal: a result that landed before the abort, or
    # a typed EngineClosed — never a hang
    for f in futs:
        try:
            f.result(timeout=30)
        except EngineClosed:
            pass


# ---------------------------------------------------------------------------
# deadlines / error isolation
# ---------------------------------------------------------------------------


def test_expired_deadline_surfaces_typed_error_without_stalling():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    # enqueue with a deadline that expires before the worker exists
    doomed = engine.submit(np.ones(2), timeout=0.001)
    time.sleep(0.05)
    engine.start()
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=30)
    # the worker loop survived: later traffic is served
    assert abs(engine.predict(np.ones(2), timeout=30.0) - 4.0) < 1e-6
    engine.shutdown()
    assert engine.metrics.count("expired") == 1


def test_invalid_datum_isolated_from_rest_of_batch():
    engine = ServingEngine(_toy_fitted(), buckets=(8,), datum_shape=(2,))
    good = [engine.submit(np.full(2, float(i))) for i in range(3)]
    bad = engine.submit(np.ones(5))  # wrong shape, same micro-batch
    engine.start()
    with pytest.raises(InvalidRequest):
        bad.result(timeout=30)
    for i, f in enumerate(good):
        assert abs(f.result(timeout=30) - 4.0 * i) < 1e-6
    engine.shutdown()
    assert engine.metrics.count("invalid") == 1
    assert engine.metrics.count("completed") == 3


# ---------------------------------------------------------------------------
# acceptance: concurrent traffic over a fitted MNIST-style pipeline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mnist_fitted():
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.mnist_random_fft import (
        NUM_CLASSES,
        MnistRandomFFTConfig,
        build_featurizer,
        synthetic_mnist_device,
    )

    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=100.0)
    train, test = synthetic_mnist_device(n_train=2048, n_test=128)
    labels = ClassLabelIndicators(NUM_CLASSES).apply_batch(train.labels)
    fitted = (
        build_featurizer(conf)
        .and_then(
            BlockLeastSquaresEstimator(conf.block_size, 1, conf.lam or 0.0),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
        .fit()
    )
    return fitted, np.asarray(test.data.to_array())


def test_64_concurrent_requests_one_compile_per_bucket(mnist_fitted):
    import jax

    assert len(jax.devices()) == 8  # the virtual mesh the suite provisions
    from keystone_tpu.utils import timing

    fitted, data = mnist_fitted
    data = data[:64]
    buckets = (8, 32)
    batches_before = (
        timing.snapshot(prefix="serve.").get("serve.batch", {}).get("calls", 0)
    )
    engine = ServingEngine(
        fitted,
        buckets=buckets,
        datum_shape=data.shape[1:],
        max_queue=256,
        max_wait_ms=2.0,
    )
    with engine:
        # warm-up paid exactly one compile per configured bucket
        assert engine.metrics.count("compiles") == len(buckets)
        with ThreadPoolExecutor(max_workers=16) as pool:
            preds = list(
                pool.map(lambda row: engine.predict(row, timeout=60.0), data)
            )
        # steady state: ZERO additional compiles under 64 concurrent requests
        assert engine.metrics.count("compiles") == len(buckets)
        snap = engine.metrics.snapshot()

    # responses match whole-batch application...
    expected = np.asarray(fitted.apply(data).to_array())
    np.testing.assert_array_equal(np.asarray(preds).ravel(), expected.ravel())
    # ...and single-datum apply results
    for i in range(0, 64, 16):
        assert int(preds[i]) == int(np.asarray(fitted.apply_datum(data[i])))

    # metrics snapshot is internally consistent
    c = snap["counters"]
    assert c["submitted"] == 64
    assert c["completed"] == 64
    assert c.get("rejected", 0) == 0 and c.get("expired", 0) == 0
    assert snap["gauges"]["queue_depth"] == 0
    occ = snap["batch_occupancy"]
    assert occ["items"] == 64
    assert occ["capacity"] >= 64
    assert snap["latency"]["count"] == 64
    assert snap["latency"]["p50"] <= snap["latency"]["p99"]
    assert c["batches"] >= 2  # 64 requests cannot fit one 32-row bucket
    assert "serve.batch" in snap["phases"]
    # the phase registry is process-global; compare against this test's delta
    assert snap["phases"]["serve.batch"]["calls"] - batches_before == c["batches"]
    # the engine's private jit saw exactly the bucket shapes, nothing else
    assert len(engine.compiled_signatures) == len(buckets)
    assert {sig[0][0] for sig in engine.compiled_signatures} == set(buckets)
    # and the shared pipeline's own compiled state was never touched
    assert fitted.compile_count == 0


# ---------------------------------------------------------------------------
# warm-up contract: required-vs-best-effort + the fit-time datum hint
# ---------------------------------------------------------------------------


def test_warm_up_raises_when_explicitly_requested_but_impossible():
    engine = ServingEngine(_toy_fitted(), buckets=(4,))  # no shape anywhere
    with pytest.raises(ValueError, match="warm-up requested but impossible"):
        engine.warm_up()
    with pytest.raises(ValueError, match="warm-up requested but impossible"):
        engine.start(warmup=True)
    # best-effort default still boots (cold, with a warning)
    engine.start()
    assert abs(engine.predict(np.ones(2), timeout=30.0) - 4.0) < 1e-6
    engine.shutdown()


def test_datum_shape_recorded_at_fit_flows_into_the_engine(mnist_fitted):
    """and_then(estimator, data) records the per-item input contract on
    the FittedPipeline; an engine constructed WITHOUT datum_shape warms
    up from it instead of silently returning 0 buckets."""
    fitted, data = mnist_fitted
    assert fitted.datum_shape == (784,)
    assert fitted.datum_dtype == "float32"
    engine = ServingEngine(fitted, buckets=(8,))
    assert engine.policy.datum_shape == (784,)
    assert engine.warm_up() == 1  # required=True default: must not skip
    engine.start(warmup=False)
    preds = [engine.predict(row, timeout=60.0) for row in data[:4]]
    engine.shutdown()
    expected = np.asarray(fitted.apply(data[:4]).to_array())
    np.testing.assert_array_equal(np.asarray(preds).ravel(), expected.ravel())


def test_datum_hint_survives_pickle(mnist_fitted):
    from keystone_tpu.utils import serialization

    fitted, _data = mnist_fitted
    clone = serialization.loads(serialization.dumps(fitted))
    assert clone.datum_shape == (784,)
    assert clone.datum_dtype == "float32"


# ---------------------------------------------------------------------------
# hot swap: the publish step of an incremental refit
# ---------------------------------------------------------------------------


def _linear_fitted(scale):
    return FunctionNode(
        batch_fn=lambda X, s=scale: X * s, label="scale"
    ).to_pipeline().fit()


def test_swap_serves_new_model_with_no_dropped_requests():
    """Requests submitted continuously across a swap must ALL resolve —
    each to either the old or the new model's output, with everything
    after the swap returns on the new one."""
    engine = ServingEngine(
        _linear_fitted(2.0), buckets=(4,), datum_shape=(2,), max_wait_ms=1.0
    )
    with engine:
        stop = [False]
        results = []

        def hammer():
            while not stop[0]:
                results.append(
                    float(np.asarray(
                        engine.predict(np.ones(2), timeout=30.0)
                    ).ravel()[0])
                )

        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(hammer) for _ in range(4)]
            time.sleep(0.2)
            warmed = engine.swap(_linear_fitted(3.0))
            assert warmed == 1  # the one configured bucket, pre-warmed
            # post-swap: every new submission runs the new model
            post = float(np.asarray(
                engine.predict(np.ones(2), timeout=30.0)
            ).ravel()[0])
            time.sleep(0.2)
            stop[0] = True
            for f in futs:
                f.result(timeout=30)
        assert post == 3.0
        snap = engine.metrics.snapshot()

    # no request was dropped, rejected, or errored across the swap
    c = snap["counters"]
    assert c["completed"] == c["submitted"]
    assert c.get("rejected", 0) == 0 and c.get("failed", 0) == 0
    assert c["swaps"] == 1
    # every response is one of the two models' outputs, and both appeared
    assert set(results) <= {2.0, 3.0}
    assert 2.0 in results and 3.0 in results


def test_swap_rejects_mismatched_datum_shape():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    wrong = FunctionNode(
        batch_fn=lambda X: X * 1.0, label="id3"
    ).to_pipeline().fit()
    wrong.datum_shape = (3,)
    with pytest.raises(ValueError, match="does not match"):
        engine.swap(wrong)


def test_swap_rejects_batch_coupled_and_closed_engine():
    engine = ServingEngine(_toy_fitted(), buckets=(4,), datum_shape=(2,))
    coupled = FunctionNode(
        batch_fn=lambda X: X - X.mean(axis=0), label="batchmean"
    ).to_pipeline().fit()
    for node in coupled.graph.nodes:
        coupled.graph.get_operator(node).batch_coupled = True
    with pytest.raises(ValueError, match="batch-coupled"):
        engine.swap(coupled)
    engine.start()
    engine.shutdown()
    with pytest.raises(EngineClosed):
        engine.swap(_linear_fitted(3.0))
