"""Cost tables + gauge merge modes (keystone_tpu/serving/metrics.py):
per-(tenant, priority) accumulation, fleet-wide merge, the timeline's
windowed spend deltas, and the declared gauge fold semantics."""

import pytest

from keystone_tpu.serving.metrics import GAUGE_MERGE_MODES, MetricsRegistry


def test_observe_cost_accumulates_per_identity():
    m = MetricsRegistry("w0")
    m.observe_cost("gold", "high", device_s=0.2, queue_s=0.05,
                   payload_bytes=100, items=2)
    m.observe_cost("gold", "high", device_s=0.1, items=1)
    m.observe_cost("gold", "low", device_s=0.3, items=1)
    m.observe_cost("bronze", device_s=0.4, items=4)
    table = m.cost_table()
    assert table["gold"]["high"] == {
        "device_s": pytest.approx(0.3), "queue_s": pytest.approx(0.05),
        "payload_bytes": 100, "items": 3,
    }
    assert table["gold"]["low"]["device_s"] == pytest.approx(0.3)
    assert table["bronze"]["normal"]["items"] == 4
    assert m.snapshot()["costs"] == table


def test_merge_folds_cost_tables_across_workers():
    a, b = MetricsRegistry("w0"), MetricsRegistry("w1")
    a.observe_cost("gold", "high", device_s=0.2, payload_bytes=10, items=1)
    b.observe_cost("gold", "high", device_s=0.3, payload_bytes=20, items=2)
    b.observe_cost("bronze", device_s=0.1, items=1)
    merged = MetricsRegistry.merge([a.snapshot(), b.snapshot()])
    assert merged["costs"]["gold"]["high"] == {
        "device_s": pytest.approx(0.5), "queue_s": 0.0,
        "payload_bytes": 30, "items": 3,
    }
    assert merged["costs"]["bronze"]["normal"]["items"] == 1


def test_timeline_rows_carry_windowed_spend_deltas():
    m = MetricsRegistry("w0")
    m.observe_cost("gold", "high", device_s=0.2, items=2)
    m.observe_cost("gold", "low", device_s=0.1, items=1)
    row1 = m.sample_timeline(now=1.0)
    # deltas sum across priorities: the tenant budget judges the tenant
    assert row1["costs"]["gold"] == {
        "device_s": pytest.approx(0.3), "items": 3,
    }
    row2 = m.sample_timeline(now=2.0)
    assert "costs" not in row2  # quiet window: no spend, no key
    m.observe_cost("gold", "high", device_s=0.05, items=1)
    row3 = m.sample_timeline(now=3.0)
    assert row3["costs"]["gold"]["device_s"] == pytest.approx(0.05)


def test_set_gauge_rejects_unknown_merge_mode():
    m = MetricsRegistry("w0")
    with pytest.raises(ValueError):
        m.set_gauge("x", lambda: 0.0, merge="median")
    assert set(GAUGE_MERGE_MODES) == {"sum", "max", "mean"}


def test_gauges_fold_by_declared_mode():
    a, b = MetricsRegistry("w0"), MetricsRegistry("w1")
    for m, depth, peak, frac in ((a, 3.0, 100.0, 0.2), (b, 5.0, 80.0, 0.6)):
        m.set_gauge("queue_depth", lambda v=depth: v)  # default: sum
        m.set_gauge("peak_bytes", lambda v=peak: v, merge="max")
        m.set_gauge("mem_fraction", lambda v=frac: v, merge="mean")
    merged = MetricsRegistry.merge([a.snapshot(), b.snapshot()])
    assert merged["gauges"]["queue_depth"] == 8.0
    assert merged["gauges"]["peak_bytes"] == 100.0
    assert merged["gauges"]["mem_fraction"] == pytest.approx(0.4)
    # the modes survive the merge so a re-merge (router of routers)
    # folds identically
    assert merged["gauge_modes"]["peak_bytes"] == "max"


def test_undeclared_gauges_keep_the_historical_sum():
    # a pre-merge-mode worker snapshot (no gauge_modes key) still sums
    a = MetricsRegistry("w0")
    a.set_gauge("queue_depth", lambda: 2.0)
    snap_a = a.snapshot()
    del snap_a["gauge_modes"]
    b = MetricsRegistry("w1")
    b.set_gauge("queue_depth", lambda: 3.0)
    merged = MetricsRegistry.merge([snap_a, b.snapshot()])
    assert merged["gauges"]["queue_depth"] == 5.0
