"""Recompile accounting on FittedPipeline.compile: exactly one XLA trace
per distinct (bucketed) input shape, and an unbucketed shape change is a
counted recompile — the invariant the serving bucket policy protects."""

import numpy as np
import pytest

from keystone_tpu.workflow.pipeline import NotTraceableError
from keystone_tpu.workflow.transformer import FunctionNode


def _double(X):
    return X * 2.0


def _inc(X):
    return X + 1.0


def _fitted():
    # module-level batch fns (not lambdas) so the pickle round-trip test works
    return (
        FunctionNode(batch_fn=_double, label="double")
        >> FunctionNode(batch_fn=_inc, label="inc")
    ).fit()


def test_compiles_once_per_shape_and_counts_recompiles():
    fitted = _fitted()
    traces = []
    fn = fitted.compile(on_trace=traces.append)

    fn(np.zeros((8, 4), np.float32))
    fn(np.ones((8, 4), np.float32))  # same shape: cache hit, no trace
    assert fitted.compile_count == 1
    assert traces == [((8, 4), "float32")]

    fn(np.zeros((32, 4), np.float32))  # second bucket: one more compile
    assert fitted.compile_count == 2

    fn(np.zeros((8, 4), np.float32))  # steady state: still 2
    fn(np.zeros((32, 4), np.float32))
    assert fitted.compile_count == 2

    # an unbucketed shape change triggers — and is counted as — a recompile
    fn(np.zeros((13, 4), np.float32))
    assert fitted.compile_count == 3
    assert fitted.compiled_signatures[-1] == ((13, 4), "float32")
    assert traces == fitted.compiled_signatures


def test_compiled_matches_uncompiled_apply():
    fitted = _fitted()
    fn = fitted.compile()
    x = np.random.RandomState(0).randn(6, 4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(fn(x)),
        np.asarray(fitted.apply(x).to_array()),
        rtol=1e-6,
    )


def test_not_traceable_error_survives_pickle():
    import pickle

    from keystone_tpu.workflow.pipeline import NotTraceableError as NTE

    err = pickle.loads(pickle.dumps(NTE(["nodeA", "nodeB"])))
    assert err.labels == ["nodeA", "nodeB"]
    assert "nodeA" in str(err)


def test_untraceable_pipeline_raises_typed_error():
    fitted = (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="double")
        >> FunctionNode(item_fn=lambda x: x, label="host_only")
    ).fit()
    assert not fitted.is_traceable
    assert "host_only" in fitted.untraceable_nodes()
    with pytest.raises(NotTraceableError) as exc:
        fitted.compile()
    assert "host_only" in str(exc.value)
    assert "host_only" in exc.value.labels
    # NotTraceableError stays catchable as the ValueError it used to be
    with pytest.raises(ValueError):
        fitted.compile()
    # the escape hatch degrades to None instead of raising
    assert fitted.compile(strict=False) is None


def test_recompile_resets_signature_accounting():
    """compile() replaces the executable, so counts restart per live jit —
    a second engine over the same fitted pipeline must not see phantom
    recompiles from the first."""
    fitted = _fitted()
    fn1 = fitted.compile()
    fn1(np.zeros((8, 4), np.float32))
    fn1(np.zeros((16, 4), np.float32))
    assert fitted.compile_count == 2
    fn2 = fitted.compile()
    assert fitted.compile_count == 0
    fn2(np.zeros((8, 4), np.float32))
    assert fitted.compile_count == 1
    # a retrace on the superseded jit doesn't pollute the live accounting
    fn1(np.zeros((32, 4), np.float32))
    assert fitted.compile_count == 1


def test_signatures_reset_across_pickle(tmp_path):
    fitted = _fitted()
    fn = fitted.compile()
    fn(np.zeros((4, 2), np.float32))
    assert fitted.compile_count == 1
    path = str(tmp_path / "p.pkl")
    fitted.save(path)
    from keystone_tpu.workflow.pipeline import FittedPipeline

    loaded = FittedPipeline.load(path)
    assert loaded.compile_count == 0  # counts are per-live-jit
    loaded.compile()(np.zeros((4, 2), np.float32))
    assert loaded.compile_count == 1
