"""The metrics timeline (MetricsRegistry.sample_timeline) and its
interaction with merge — the satellite contract: merged timelines stay
per-process (never blended), sampler rows survive
``snapshot(sketches=True)`` round-trips, and quantile reservoirs are
deterministic under seeded fill."""

import pickle

import numpy as np

from keystone_tpu.serving.metrics import MetricsRegistry


def _filled(name, seed, n=64):
    reg = MetricsRegistry(name=name)
    rng = np.random.RandomState(seed)
    for v in rng.rand(n):
        reg.observe_latency(float(v))
        reg.observe_queue_age(float(v) / 2)
    reg.inc("submitted", n)
    reg.inc("completed", n)
    return reg


def test_rows_carry_counter_deltas_not_totals():
    reg = MetricsRegistry(name="w0")
    reg.inc("completed", 5)
    r1 = reg.sample_timeline(now=1.0)
    reg.inc("completed", 3)
    reg.inc("shed", 2)
    r2 = reg.sample_timeline(now=2.0)
    r3 = reg.sample_timeline(now=3.0)
    assert r1["counters"] == {"completed": 5}
    assert r2["counters"] == {"completed": 3, "shed": 2}
    assert r3["counters"] == {}  # quiet window: no deltas, row still lands
    assert [row["ts"] for row in reg.timeline()] == [1.0, 2.0, 3.0]


def test_timeline_ring_is_bounded():
    reg = MetricsRegistry(name="w0", timeline_window=4)
    for i in range(10):
        reg.sample_timeline(now=float(i))
    assert [r["ts"] for r in reg.timeline()] == [6.0, 7.0, 8.0, 9.0]


def test_rows_include_quantiles_gauges_and_occupancy():
    reg = _filled("w0", seed=0)
    reg.set_gauge("queue_depth", lambda: 7)
    reg.observe_batch(6, 8, replica=0)
    row = reg.sample_timeline(now=1.0)
    assert row["gauges"] == {"queue_depth": 7.0}
    assert row["latency"]["count"] == 64 and "p99" in row["latency"]
    assert "p99" in row["queue_age"]
    assert row["occupancy"] == 6 / 8


def test_failing_gauge_never_breaks_a_sample():
    reg = MetricsRegistry(name="w0")

    def boom():
        raise RuntimeError("gauge died")

    reg.set_gauge("bad", boom)
    reg.set_gauge("good", lambda: 1.0)
    row = reg.sample_timeline(now=1.0)
    assert row["gauges"] == {"good": 1.0}


def test_rows_survive_snapshot_sketch_round_trip():
    # the wire path: a worker snapshots (with sketches), the frame is
    # pickled across the process boundary, the router merges — rows must
    # arrive bit-identical
    reg = _filled("worker-0", seed=3)
    reg.sample_timeline(now=1.0)
    reg.inc("completed", 2)
    reg.sample_timeline(now=2.0)
    snap = pickle.loads(pickle.dumps(reg.snapshot(sketches=True)))
    assert snap["timeline"] == reg.timeline()
    merged = MetricsRegistry.merge([snap], name="cluster")
    assert merged["timelines"]["worker-0"] == reg.timeline()


def test_merged_timelines_stay_per_process_never_blended():
    a = _filled("worker-0", seed=1)
    b = _filled("worker-1", seed=2)
    a.sample_timeline(now=10.0)
    b.sample_timeline(now=11.0)
    b.sample_timeline(now=12.0)
    merged = MetricsRegistry.merge(
        [a.snapshot(sketches=True), b.snapshot(sketches=True)],
        name="cluster",
    )
    tl = merged["timelines"]
    assert set(tl) == {"worker-0", "worker-1"}
    assert [r["ts"] for r in tl["worker-0"]] == [10.0]
    assert [r["ts"] for r in tl["worker-1"]] == [11.0, 12.0]
    # counters inside rows are each process's own deltas, untouched by
    # the merge (the merged COUNTERS section is where summing happens)
    assert tl["worker-0"][0]["counters"]["completed"] == 64
    assert tl["worker-1"][0]["counters"]["completed"] == 64
    assert merged["counters"]["completed"] == 128
    # no blended top-level timeline is fabricated
    assert "timeline" not in merged


def test_quantile_reservoirs_deterministic_under_seeded_fill():
    snaps = []
    for _ in range(2):
        a = _filled("worker-0", seed=7)
        b = _filled("worker-1", seed=8)
        merged = MetricsRegistry.merge(
            [a.snapshot(sketches=True), b.snapshot(sketches=True)]
        )
        snaps.append((merged["latency"], merged["queue_age"],
                      a.latency_quantiles(), a.queue_age_quantiles()))
    assert snaps[0] == snaps[1]
    merged_lat = snaps[0][0]
    assert merged_lat["count"] == 128
    # exact recompute from the merged raw reservoirs, not an average of
    # per-process percentiles
    pool = sorted(
        list(np.random.RandomState(7).rand(64))
        + list(np.random.RandomState(8).rand(64))
    )
    expected = MetricsRegistry._quantiles([float(x) for x in pool])
    assert merged_lat == expected


def test_snapshot_without_timeline_rows_merges_clean():
    reg = _filled("worker-0", seed=5)  # never sampled
    merged = MetricsRegistry.merge([reg.snapshot(sketches=True)])
    assert merged["timelines"] == {}
