"""ServingFleet: the replicated continuous-batching serving layer —
hammer traffic with a mid-load fleet-wide swap (zero dropped/failed),
deterministic deadline shedding at admission, work-stealing rebalance,
and canary-mismatch auto-rollback."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from keystone_tpu.serving import (
    CanaryMismatch,
    EngineClosed,
    EngineStopped,
    QueueFull,
    ServingFleet,
    Shed,
)
from keystone_tpu.workflow.transformer import FunctionNode


def _linear_fitted(scale, label=None):
    return FunctionNode(
        batch_fn=lambda X, s=scale: X * s, label=label or f"scale{scale}"
    ).to_pipeline().fit()


def _toy_fitted():
    return (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="double")
        >> FunctionNode(batch_fn=lambda X: X.sum(axis=1), label="rowsum")
    ).fit()


# ---------------------------------------------------------------------------
# lifecycle / routing
# ---------------------------------------------------------------------------


def test_fleet_serves_correct_results_across_replicas():
    fleet = ServingFleet(
        _toy_fitted(), replicas=2, buckets=(4, 8), datum_shape=(3,),
        max_wait_ms=1.0,
    )
    with fleet:
        with ThreadPoolExecutor(max_workers=8) as pool:
            res = list(pool.map(
                lambda i: float(np.asarray(
                    fleet.predict(np.full(3, float(i)), timeout=30.0)
                ).ravel()[0]),
                range(48),
            ))
    for i, r in enumerate(res):
        assert abs(r - 6.0 * i) < 1e-4
    snap = fleet.metrics.snapshot()
    c = snap["counters"]
    assert c["completed"] == c["submitted"] == 48
    assert c.get("batch_errors", 0) == 0
    # both replica workers actually executed batches, and the snapshot
    # attributes occupancy per replica
    assert set(snap["replicas"]) == {"0", "1"}
    assert all(row["batches"] >= 1 for row in snap["replicas"].values())
    # queue-age quantiles observed for every dispatched request
    assert snap["queue_age"]["count"] == 48


def test_fleet_default_replica_count_is_one_per_device():
    import jax

    fleet = ServingFleet(_toy_fitted(), datum_shape=(3,))
    assert fleet.n_replicas == len(jax.devices())  # 8 on the test mesh
    # replica i is pinned to data-axis device i
    assert [r.device for r in fleet.replicas] == list(jax.devices())


def test_fleet_shares_one_executable_trace_across_replicas():
    """The fleet pays each bucket trace ONCE no matter the replica count."""
    fleet = ServingFleet(
        _toy_fitted(), replicas=4, buckets=(4, 8), datum_shape=(3,)
    )
    fleet.start()
    assert fleet.metrics.count("compiles") == 2  # one per bucket, not x4
    assert len(fleet.compiled_signatures) == 2
    fleet.shutdown()


def test_submit_after_shutdown_raises_typed_engine_stopped():
    fleet = ServingFleet(_toy_fitted(), replicas=2, datum_shape=(3,))
    fleet.start()
    fleet.shutdown()
    with pytest.raises(EngineStopped):
        fleet.submit(np.ones(3))
    # EngineStopped stays catchable as the EngineClosed it refines
    with pytest.raises(EngineClosed):
        fleet.submit(np.ones(3))


def test_shutdown_without_start_answers_queued_and_is_idempotent():
    fleet = ServingFleet(_toy_fitted(), replicas=2, datum_shape=(3,))
    fut = fleet.submit(np.ones(3))
    fleet.shutdown()
    fleet.shutdown()  # idempotent
    with pytest.raises(EngineStopped):
        fut.result(timeout=5)


def test_queue_full_is_typed_and_counted():
    fleet = ServingFleet(
        _toy_fitted(), replicas=2, datum_shape=(3,), max_queue=4
    )
    for _ in range(4):
        fleet.submit(np.ones(3))
    with pytest.raises(QueueFull):
        fleet.submit(np.ones(3))
    assert fleet.metrics.count("rejected") == 1
    fleet.start()  # queued four still drain normally
    fleet.shutdown(drain=True)
    assert fleet.metrics.count("completed") == 4


# ---------------------------------------------------------------------------
# deadline shedding at admission
# ---------------------------------------------------------------------------


def test_deadline_shed_is_deterministic_given_service_evidence():
    """With a seeded service estimate, a deadline below the floor is shed
    every single time; a deadline above it is admitted every time."""
    fleet = ServingFleet(
        _toy_fitted(), replicas=1, buckets=(4,), datum_shape=(3,)
    )
    fleet.scheduler.observe_service(0.5)  # every batch "takes" 500ms
    fleet.start()
    for _ in range(10):
        with pytest.raises(Shed):
            fleet.submit(np.ones(3), timeout=0.05)  # < the service floor
    assert fleet.metrics.count("shed") == 10
    # a meetable deadline is never shed on an empty fleet
    for _ in range(5):
        assert abs(fleet.predict(np.ones(3), timeout=30.0) - 6.0) < 1e-4
    assert fleet.metrics.count("shed") == 10
    fleet.shutdown()
    snap = fleet.metrics.snapshot()
    assert snap["counters"]["completed"] == 5


def test_cold_scheduler_never_sheds():
    """No service evidence => no shedding: the scheduler cannot justify
    refusing work it knows nothing about."""
    fleet = ServingFleet(
        _toy_fitted(), replicas=1, buckets=(4,), datum_shape=(3,)
    )
    assert fleet.scheduler.service_estimate is None
    assert fleet.scheduler.estimated_wait() == 0.0
    fleet.start()
    assert abs(fleet.predict(np.ones(3), timeout=0.5) - 6.0) < 1e-4
    fleet.shutdown()
    assert fleet.metrics.count("shed") == 0


# ---------------------------------------------------------------------------
# the hammer: concurrent submitters + mid-load fleet-wide swap
# ---------------------------------------------------------------------------


def test_fleet_hammer_with_mid_load_swap_zero_dropped_or_failed():
    """Concurrent submitters across a fleet-wide swap: every request
    resolves to one of the two models' outputs, nothing dropped, nothing
    failed, and everything after the swap returns runs the new model."""
    fleet = ServingFleet(
        _linear_fitted(2.0), replicas=2, buckets=(4,), datum_shape=(2,),
        max_wait_ms=1.0,
    )
    with fleet:
        stop = [False]
        results = []

        def hammer():
            while not stop[0]:
                results.append(float(np.asarray(
                    fleet.predict(np.ones(2), timeout=30.0)
                ).ravel()[0]))

        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(hammer) for _ in range(4)]
            time.sleep(0.2)
            report = fleet.swap(_linear_fitted(3.0))
            assert report["replicas_flipped"] == 2
            assert report["buckets_warmed"] == 1
            post = float(np.asarray(
                fleet.predict(np.ones(2), timeout=30.0)
            ).ravel()[0])
            time.sleep(0.2)
            stop[0] = True
            for f in futs:
                f.result(timeout=30)
        assert post == 3.0
        snap = fleet.metrics.snapshot()

    c = snap["counters"]
    assert c["completed"] == c["submitted"]
    assert c.get("rejected", 0) == 0 and c.get("batch_errors", 0) == 0
    assert c["swaps"] == 1
    assert set(results) <= {2.0, 3.0}
    assert 2.0 in results and 3.0 in results


def test_swap_rejects_contract_mismatch_and_closed_fleet():
    fleet = ServingFleet(_toy_fitted(), replicas=2, datum_shape=(2,))
    wrong = _linear_fitted(1.0, label="id3")
    wrong.datum_shape = (3,)
    with pytest.raises(ValueError, match="does not match"):
        fleet.swap(wrong)
    fleet.start()
    fleet.shutdown()
    with pytest.raises(EngineStopped):
        fleet.swap(_linear_fitted(3.0))


# ---------------------------------------------------------------------------
# canary: shadow-compare, promote or auto-rollback
# ---------------------------------------------------------------------------


def _with_traffic(fleet, fn):
    """Run ``fn()`` while hammer threads keep the fleet busy."""
    stop = [False]

    def hammer():
        while not stop[0]:
            fleet.predict(np.ones(2), timeout=30.0)

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)
        return fn()
    finally:
        stop[0] = True
        for t in threads:
            t.join()


def test_canary_mismatch_auto_rolls_back():
    fleet = ServingFleet(
        _linear_fitted(2.0), replicas=2, buckets=(4,), datum_shape=(2,),
        max_wait_ms=1.0,
    )
    with fleet:
        def do_swap():
            with pytest.raises(CanaryMismatch) as exc:
                fleet.swap(
                    _linear_fitted(5.0, label="bad"),
                    canary_fraction=1.0, canary_batches=2,
                    canary_timeout_s=20.0,
                )
            return exc.value

        err = _with_traffic(fleet, do_swap)
        # the report carries the mirrored-batch evidence
        assert err.report["mismatches"] >= 1
        assert err.report["batches_compared"] >= 1
        assert err.report["mismatch_details"][0]["max_abs_diff"] > 1.0
        # NOTHING was promoted: the fleet still serves the old model
        assert float(np.asarray(
            fleet.predict(np.ones(2), timeout=30.0)
        ).ravel()[0]) == 2.0
        assert fleet.metrics.count("swaps") == 0
        assert fleet.metrics.count("canary_fail") == 1
        assert fleet.metrics.count("canary_pass") == 0


def test_canary_pass_promotes_with_verdict():
    fleet = ServingFleet(
        _linear_fitted(2.0), replicas=2, buckets=(4,), datum_shape=(2,),
        max_wait_ms=1.0,
    )
    with fleet:
        report = _with_traffic(
            fleet,
            lambda: fleet.swap(
                _linear_fitted(2.0, label="equivalent"),
                canary_fraction=1.0, canary_batches=2,
                canary_timeout_s=20.0,
            ),
        )
        assert report["canary"]["mismatches"] == 0
        assert report["canary"]["batches_compared"] >= 2
        assert fleet.metrics.count("canary_pass") == 1
        assert fleet.metrics.count("swaps") == 1
        # latency comparison rode along with the output comparison
        assert report["canary"]["latency_ratio"] is not None


def test_canary_latency_gate_rolls_back_a_slow_candidate():
    """Identical outputs but a latency ratio above the gate still rolls
    back — the 'compare outputs/latency' promise, both halves."""
    fleet = ServingFleet(
        _linear_fitted(2.0), replicas=2, buckets=(4,), datum_shape=(2,),
        max_wait_ms=1.0,
    )

    def slow_double(X):
        import jax

        def _stall(x):
            time.sleep(0.05)
            return x

        return jax.pure_callback(
            _stall, jax.ShapeDtypeStruct(X.shape, X.dtype), X
        ) * 2.0

    slow = FunctionNode(batch_fn=slow_double, label="slow").to_pipeline().fit()
    with fleet:
        def do_swap():
            with pytest.raises(CanaryMismatch, match="latency"):
                fleet.swap(
                    slow, canary_fraction=1.0, canary_batches=3,
                    canary_timeout_s=20.0, max_latency_ratio=3.0,
                )

        _with_traffic(fleet, do_swap)
        assert fleet.metrics.count("swaps") == 0
        # old model still live
        assert float(np.asarray(
            fleet.predict(np.ones(2), timeout=30.0)
        ).ravel()[0]) == 2.0
