"""MetricsRegistry + the obs/timing exports it rides on."""

import numpy as np

from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.utils import timing
from keystone_tpu.utils.obs import every


def test_counters_and_gauges():
    m = MetricsRegistry("t")
    m.inc("submitted")
    m.inc("submitted", 3)
    m.set_gauge("queue_depth", lambda: 7)
    assert m.count("submitted") == 4
    snap = m.snapshot()
    assert snap["counters"]["submitted"] == 4
    assert snap["gauges"]["queue_depth"] == 7


def test_latency_quantiles_on_known_data():
    m = MetricsRegistry("t")
    for v in np.linspace(0.001, 0.1, 100):
        m.observe_latency(float(v))
    q = m.latency_quantiles()
    assert q["count"] == 100
    assert q["p50"] <= q["p95"] <= q["p99"]
    assert abs(q["p50"] - 0.0505) < 0.01
    # nearest-rank, not one-past: p99 of 100 samples is the 99th value,
    # NOT the maximum
    vals = sorted(np.linspace(0.001, 0.1, 100))
    assert q["p99"] == float(vals[98])
    assert q["p50"] == float(vals[49])
    # empty registry reports a bare count
    assert MetricsRegistry("e").latency_quantiles() == {"count": 0}


def test_batch_occupancy_ratio():
    m = MetricsRegistry("t")
    m.observe_batch(6, 8)
    m.observe_batch(2, 8)
    snap = m.snapshot()["batch_occupancy"]
    assert snap["items"] == 8 and snap["capacity"] == 16
    assert abs(snap["ratio"] - 0.5) < 1e-9
    assert m.count("batches") == 2


def test_snapshot_embeds_serve_phase_stats():
    timing.reset()
    timing.record("serve.batch", 0.25)
    timing.record("krr.local_solve", 1.0)  # another subsystem's phase
    try:
        phases = MetricsRegistry("t").snapshot()["phases"]
        assert phases == {"serve.batch": {"seconds": 0.25, "calls": 1}}
        # the unfiltered view still carries everything
        assert "krr.local_solve" in timing.snapshot()
    finally:
        timing.reset()


def test_obs_every_rate_limits():
    key = "test-every-unique-key"
    assert every(key, 60.0) is True
    assert every(key, 60.0) is False
    assert every(key, 0.0) is True  # window elapsed


def test_maybe_log_is_rate_limited(caplog):
    import logging

    m = MetricsRegistry("rate-limit-test")
    with caplog.at_level(logging.INFO, logger="keystone_tpu.serving.metrics"):
        assert m.maybe_log(60.0) is True
        assert m.maybe_log(60.0) is False
    assert len(caplog.records) == 1


def test_queue_age_quantiles_same_schema_as_latency():
    m = MetricsRegistry("t")
    for v in np.linspace(0.001, 0.1, 100):
        m.observe_queue_age(float(v))
    q = m.queue_age_quantiles()
    assert q["count"] == 100
    assert q["p50"] <= q["p95"] <= q["p99"]
    snap = m.snapshot()
    assert snap["queue_age"]["p99"] == q["p99"]
    # empty registry reports a bare count, exactly like latency
    assert MetricsRegistry("e").queue_age_quantiles() == {"count": 0}


def test_per_replica_occupancy_in_snapshot():
    m = MetricsRegistry("t")
    m.observe_batch(6, 8, replica=0)
    m.observe_batch(2, 8, replica=0)
    m.observe_batch(8, 8, replica=1)
    snap = m.snapshot()
    # fleet-wide occupancy still aggregates everything
    assert snap["batch_occupancy"]["items"] == 16
    per = snap["replicas"]
    assert per["0"]["batches"] == 2 and per["1"]["batches"] == 1
    assert abs(per["0"]["occupancy"] - 0.5) < 1e-9
    assert per["1"]["occupancy"] == 1.0
    # replica-less observations (the single engine) don't create rows
    m2 = MetricsRegistry("t2")
    m2.observe_batch(4, 8)
    assert m2.snapshot()["replicas"] == {}


def test_periodic_log_includes_shed_and_canary_verdicts(caplog):
    import logging

    m = MetricsRegistry("shed-log-test")
    m.inc("shed", 7)
    m.inc("canary_pass")
    m.inc("canary_fail", 2)
    with caplog.at_level(logging.INFO, logger="keystone_tpu.serving.metrics"):
        assert m.maybe_log(60.0) is True
    line = caplog.records[-1].getMessage()
    assert "shed=7" in line
    assert "canary=1pass/2fail" in line
