"""SLO policy evaluation + watchdog emission (keystone_tpu/serving/slo.py)."""

from keystone_tpu.obs import flight
from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.serving.slo import SloBreach, SloPolicy, SloWatchdog


def _row(**over):
    row = {
        "ts": 100.0,
        "counters": {},
        "gauges": {},
        "latency": {},
        "queue_age": {},
        "occupancy": None,
    }
    row.update(over)
    return row


def test_unset_objectives_are_not_evaluated():
    assert SloPolicy().evaluate(
        _row(latency={"p99": 99.0}, counters={"restarts": 50})
    ) == []


def test_p99_and_queue_age_budgets():
    pol = SloPolicy(p99_budget_s=0.5, queue_age_p99_budget_s=0.1)
    traffic = {"completed": 4}
    ok = pol.evaluate(
        _row(
            latency={"p99": 0.4}, queue_age={"p99": 0.05},
            counters=traffic,
        )
    )
    assert ok == []
    bad = pol.evaluate(
        _row(
            latency={"p99": 0.6}, queue_age={"p99": 0.2},
            counters=traffic,
        )
    )
    assert [b.objective for b in bad] == [
        "p99_budget_s", "queue_age_p99_budget_s"
    ]
    assert bad[0].observed == 0.6 and bad[0].budget == 0.5
    assert bad[0].ts == 100.0


def test_latency_budgets_judge_only_windows_with_traffic():
    # the reservoirs are cumulative: a quiet window re-showing a past
    # burst's p99 is stale evidence, not a fresh breach (it would hold
    # a breach-driven autoscaler at peak size forever)
    pol = SloPolicy(p99_budget_s=0.5, queue_age_p99_budget_s=0.1)
    assert pol.evaluate(
        _row(latency={"p99": 9.0}, queue_age={"p99": 9.0})
    ) == []
    (b,) = pol.evaluate(
        _row(latency={"p99": 9.0}, counters={"submitted": 1})
    )
    assert b.objective == "p99_budget_s"


def test_shed_rate_judged_only_with_traffic():
    pol = SloPolicy(max_shed_rate=0.25)
    assert pol.evaluate(_row()) == []  # quiet window: nothing to judge
    assert pol.evaluate(
        _row(counters={"submitted": 9, "shed": 1})
    ) == []  # 10% refused
    (b,) = pol.evaluate(
        _row(counters={"submitted": 4, "shed": 3, "rejected": 3})
    )
    assert b.objective == "max_shed_rate" and b.observed == 0.6


def test_restart_burn_counts_both_tiers():
    pol = SloPolicy(max_restart_burn=1)
    assert pol.evaluate(_row(counters={"restarts": 1})) == []
    (b,) = pol.evaluate(
        _row(counters={"restarts": 1, "trainer_restarts": 1})
    )
    assert b.objective == "max_restart_burn" and b.observed == 2


def test_trainer_staleness_and_drift_gauges():
    pol = SloPolicy(max_staleness_s=60.0, max_drift_score=3.0)
    assert pol.evaluate(
        _row(gauges={"staleness_s": 10.0, "drift_score": 1.0})
    ) == []
    bad = pol.evaluate(
        _row(gauges={"staleness_s": 120.0, "drift_score": 4.5})
    )
    assert [b.objective for b in bad] == [
        "max_staleness_s", "max_drift_score"
    ]
    # a registry with no trainer attached simply lacks the gauges
    assert pol.evaluate(_row()) == []


def test_watchdog_tick_samples_judges_and_emits():
    reg = MetricsRegistry(name="fleet")
    for _ in range(8):
        reg.observe_latency(2.0)
        reg.inc("completed")
    dog = SloWatchdog(reg, SloPolicy(p99_budget_s=1.0), source="test-tier")
    found = dog.tick()
    assert [b.objective for b in found] == ["p99_budget_s"]
    # emitted: counters (total + per-objective), flight instant, kept list
    assert reg.count("slo_breaches") == 1
    assert reg.count("slo_breach.p99_budget_s") == 1
    assert dog.breaches == found
    hits = [
        e for e in flight.recorder().entries()
        if e["name"] == "slo.breach"
    ]
    assert hits and hits[-1]["attrs"]["source"] == "test-tier"
    assert hits[-1]["attrs"]["objective"] == "p99_budget_s"
    # the breach rides the timeline row too (sample happened inside tick)
    assert len(reg.timeline()) == 1


def test_watchdog_quiet_tick_emits_nothing():
    reg = MetricsRegistry(name="fleet")
    reg.observe_latency(0.1)
    dog = SloWatchdog(reg, SloPolicy(p99_budget_s=1.0))
    assert dog.tick() == []
    assert reg.count("slo_breaches") == 0


def test_breach_is_a_typed_value():
    b = SloBreach("p99_budget_s", 0.7, 0.5, 1.0)
    assert b.as_attrs() == {
        "objective": "p99_budget_s", "observed": 0.7, "budget": 0.5,
    }
