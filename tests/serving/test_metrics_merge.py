"""MetricsRegistry.merge: worker-process snapshots fold into one
fleet-wide view (ISSUE 12 satellite — the cluster router's merged
report)."""

import numpy as np

from keystone_tpu.serving.metrics import MetricsRegistry


def _worker(name, n, base_latency):
    m = MetricsRegistry(name=name)
    m.inc("submitted", n)
    m.inc("completed", n)
    m.inc("shed", 2)
    for i in range(n):
        m.observe_latency(base_latency + i * 0.001)
        m.observe_queue_age(base_latency / 2 + i * 0.0005)
    m.observe_batch(6, 8, replica=0)
    m.observe_batch(8, 8, replica=1)
    m.set_gauge("queue_depth", lambda: 3.0)
    return m


def test_counters_sum_and_replicas_namespace():
    a = _worker("w0", 10, 0.010)
    b = _worker("w1", 20, 0.100)
    merged = MetricsRegistry.merge(
        [a.snapshot(sketches=True), b.snapshot(sketches=True)]
    )
    assert merged["counters"]["submitted"] == 30
    assert merged["counters"]["shed"] == 4
    assert merged["gauges"]["queue_depth"] == 6.0
    # per-replica rows survive, namespaced by worker name
    assert set(merged["replicas"]) == {"w0/0", "w0/1", "w1/0", "w1/1"}
    occ = merged["batch_occupancy"]
    assert occ["items"] == 28 and occ["capacity"] == 32
    assert abs(occ["ratio"] - 28 / 32) < 1e-9


def test_quantiles_recomputed_from_merged_sketches():
    a = _worker("w0", 50, 0.010)
    b = _worker("w1", 50, 0.100)
    merged = MetricsRegistry.merge(
        [a.snapshot(sketches=True), b.snapshot(sketches=True)]
    )
    lat = merged["latency"]
    assert lat["count"] == 100
    # exact nearest-rank over the union — NOT an average of per-worker
    # p99s: the merged p99 must come from the slow worker's tail
    union = sorted(
        [0.010 + i * 0.001 for i in range(50)]
        + [0.100 + i * 0.001 for i in range(50)]
    )
    assert abs(lat["p99"] - union[98]) < 1e-12
    assert abs(lat["p50"] - union[49]) < 1e-12
    assert merged["queue_age"]["count"] == 100


def test_snapshot_without_sketch_still_contributes_counters():
    a = _worker("w0", 10, 0.010)
    b = _worker("w1", 10, 0.020)
    merged = MetricsRegistry.merge(
        [a.snapshot(sketches=True), b.snapshot()]  # b ships no sketch
    )
    assert merged["counters"]["submitted"] == 20
    # only the sketch-bearing worker participates in quantiles
    assert merged["latency"]["count"] == 10


def test_merge_of_empty_inputs_is_well_formed():
    merged = MetricsRegistry.merge([])
    assert merged["counters"] == {}
    assert merged["latency"] == {"count": 0}
    assert merged["batch_occupancy"]["ratio"] is None
    merged2 = MetricsRegistry.merge([{}, None])
    assert merged2["counters"] == {}


def test_sketch_is_bounded_by_reservoir_window():
    m = MetricsRegistry(name="w", latency_window=16)
    for i in range(100):
        m.observe_latency(float(i))
    snap = m.snapshot(sketches=True)
    assert len(snap["sketch"]["latencies"]) == 16
    # default snapshot carries no sketch (nothing extra over the wire)
    assert "sketch" not in m.snapshot()
