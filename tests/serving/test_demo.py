"""The --serve-demo CLI path (what bin/serve-smoke.sh runs) and the
--log/--profile observability flags."""

import logging

from keystone_tpu.__main__ import main


def test_serve_demo_smoke(capsys):
    rc = main([
        "--serve-demo", "--backend", "cpu",
        "--requests", "16", "--nTrain", "512",
        "--numFFTs", "2", "--blockSize", "256", "--buckets", "8",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "SERVE PASS" in out
    assert "compiles=1" in out  # one bucket, one compile


def test_log_flag_levels_root_logger(capsys):
    prior = logging.getLogger().level
    try:
        rc = main([
            "--serve-demo", "--backend", "cpu", "--log", "error",
            "--requests", "8", "--nTrain", "256",
            "--numFFTs", "2", "--blockSize", "256", "--buckets", "8",
        ])
        assert rc == 0
        assert logging.getLogger().level == logging.ERROR
        # --logLevel stays as a back-compat alias of --log
        rc = main([
            "--serve-demo", "--backend", "cpu", "--logLevel", "warning",
            "--requests", "8", "--nTrain", "256",
            "--numFFTs", "2", "--blockSize", "256", "--buckets", "8",
        ])
        assert rc == 0
        assert logging.getLogger().level == logging.WARNING
    finally:
        logging.getLogger().setLevel(prior)
