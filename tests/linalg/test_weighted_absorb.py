"""WeightedSolverState: absorb beyond the Gram family (ISSUE 14
satellite) — the per-class weighted mixture solve from snapshot-able
accumulators, with the BCD families refusing typed."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.linalg import NotAbsorbable, WeightedSolverState
from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
from keystone_tpu.nodes.learning.weighted import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
)
from keystone_tpu.workflow.transformer import FunctionNode

D, K = 12, 4
LAM, MIX = 1e-2, 0.4


def _problem(n, seed=0, offset=1.5):
    r = np.random.RandomState(seed)
    X = (r.randn(n, D) + offset).astype(np.float32)
    yi = r.randint(0, K, n)
    Y = -np.ones((n, K), np.float32)
    Y[np.arange(n), yi] = 1.0
    return X, Y


def _est(snapshot=False):
    return PerClassWeightedLeastSquaresEstimator(
        5, 1, LAM, MIX, snapshot=snapshot
    )


def _W(mapper):
    return np.asarray(mapper._W)


def test_state_solve_matches_dense_oracle():
    """The accumulator solve equals the f32 dense per-class oracle on
    the same data — same objective, f64 state algebra."""
    X, Y = _problem(240)
    plain = _est().fit(Dataset.of(X), Dataset.of(Y))
    snap = _est(snapshot=True).fit(Dataset.of(X), Dataset.of(Y))
    assert np.max(np.abs(_W(plain) - _W(snap))) <= 1e-4
    assert np.max(np.abs(np.asarray(plain.b) - np.asarray(snap.b))) <= 1e-4
    st = snap.solver_state
    assert isinstance(st, WeightedSolverState)
    assert st.n == 240 and st.rows_folded == 0  # snapshot zeroes the gate


def test_chunked_fit_matches_in_memory():
    X, Y = _problem(300)
    whole = _est(snapshot=True).fit(Dataset.of(X), Dataset.of(Y))
    chunked = _est(snapshot=True).fit(
        ChunkedDataset.from_array(X, 64), Dataset.of(Y)
    )
    # both fold into f64 state; only chunk-local f32 products differ
    assert np.max(np.abs(_W(whole) - _W(chunked))) <= 1e-5


def test_weighted_absorb_matches_from_scratch():
    """absorb(new chunks) through a frozen featurizer prefix equals a
    from-scratch snapshot fit on the concatenated data — the Gram-family
    absorb contract, now for the weighted family."""
    X, Y = _problem(300)
    Xn, Yn = _problem(96, seed=1, offset=1.0)
    prefix = FunctionNode(
        batch_fn=lambda A: jnp.tanh(A), label="feat"
    ).to_pipeline()
    fitted = prefix.and_then(
        _est(snapshot=True), ChunkedDataset.from_array(X, 64), Dataset.of(Y)
    ).fit()
    updated = fitted.absorb(
        ChunkedDataset.from_array(Xn, 32), Dataset.of(Yn)
    )
    scratch = prefix.and_then(
        _est(snapshot=True),
        ChunkedDataset.from_array(np.concatenate([X, Xn]), 64),
        Dataset.of(np.concatenate([Y, Yn])),
    ).fit()

    def mapper_of(f):
        return [
            op for op in f.graph.operators.values() if hasattr(op, "_W")
        ][0]

    mu, ms = mapper_of(updated), mapper_of(scratch)
    assert np.max(np.abs(_W(mu) - _W(ms))) <= 1e-5
    assert np.max(np.abs(np.asarray(mu.b) - np.asarray(ms.b))) <= 1e-5
    assert mu.solver_state.n == 396
    # end-to-end predictions agree, and the original stayed frozen
    got = np.asarray(updated.apply(Dataset.of(Xn[:16])).to_array())
    want = np.asarray(scratch.apply(Dataset.of(Xn[:16])).to_array())
    np.testing.assert_allclose(got, want, atol=1e-4)
    assert mapper_of(fitted).solver_state.n == 300


def test_sequential_weighted_absorbs_compose():
    X, Y = _problem(200)
    Xb, Yb = _problem(64, seed=2)
    Xc, Yc = _problem(48, seed=3)
    fitted = _est(snapshot=True).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    twice = fitted.absorb(Dataset.of(Xb), Dataset.of(Yb)).absorb(
        Dataset.of(Xc), Dataset.of(Yc)
    )
    scratch = _est(snapshot=True).with_data(
        Dataset.of(np.concatenate([X, Xb, Xc])),
        Dataset.of(np.concatenate([Y, Yb, Yc])),
    ).fit()

    def mapper_of(f):
        return [
            op for op in f.graph.operators.values() if hasattr(op, "_W")
        ][0]

    assert np.max(
        np.abs(_W(mapper_of(twice)) - _W(mapper_of(scratch)))
    ) <= 1e-5


def test_state_moments_and_class_bookkeeping():
    X, Y = _problem(256)
    st = WeightedSolverState(lam=LAM, mixture_weight=MIX, block_size=5)
    for i in range(0, 256, 64):
        st.update(X[i : i + 64], Y[i : i + 64])
    m = st.moments()
    np.testing.assert_allclose(m.mean, X.mean(0), atol=1e-4)
    np.testing.assert_allclose(
        m.std(), X.astype(np.float64).std(0), rtol=1e-3
    )
    assert st.counts.sum() == 256
    with pytest.raises(ValueError, match="does not match"):
        st.update(np.zeros((8, D + 1), np.float32), Y[:8])


def test_bcd_families_refuse_typed():
    """BCD iterates are visitation-order-dependent — snapshot=True must
    raise the typed NotAbsorbable, never fit something absorb would
    silently get wrong."""
    with pytest.raises(NotAbsorbable, match="visitation order"):
        BlockWeightedLeastSquaresEstimator(5, 1, LAM, MIX, snapshot=True)
    with pytest.raises(NotAbsorbable, match="visitation order"):
        BlockLeastSquaresEstimator(5, 1, snapshot=True)


def test_absorb_without_state_is_typed_not_absorbable():
    """FittedPipeline.absorb on a BCD-fitted model raises the typed
    error (a ValueError subclass, so pre-existing callers keep
    working)."""
    X, Y = _problem(128)
    fitted = BlockLeastSquaresEstimator(5, 1, lam=LAM).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    with pytest.raises(NotAbsorbable, match="snapshot-able"):
        fitted.absorb(Dataset.of(X[:16]), Dataset.of(Y[:16]))
