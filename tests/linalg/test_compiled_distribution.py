"""Pin the COMPILED artifact's distribution (VERDICT r3 #5).

The mesh tests in test_mesh_solvers.py assert sharding specs on *inputs*
and single≈multi agreement — but a silent all-replicated regression (every
device computing the full problem) would pass those. These tests inspect
the lowered+compiled program itself on the 8-device CPU mesh:

* operands stay 1/N-sharded — the optimized HLO's parameter shapes are the
  per-device LOCAL shapes, and the executable's input shardings carry the
  data-axis spec;
* the Gram reduction is a cross-device collective — ``all-reduce`` appears
  in the optimized HLO.

Capability parity: SURVEY §2.7 treeReduce/broadcast rows — mlmatrix's
explicit tree all-reduce becomes an XLA-inserted collective; these tests
prove it is actually inserted.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.linalg.bcd import _bcd_scan
from keystone_tpu.linalg.normal_equations import _ne_solve
from keystone_tpu.nodes.learning.weighted import _chunk_grams
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    shard_batch,
    use_mesh,
)

N_DEV = 8


def _local_shape_pattern(n, *rest):
    dims = ",".join(str(d) for d in (n // N_DEV,) + rest)
    return f"f32[{dims}]"


@pytest.fixture
def data_mesh():
    return make_mesh()  # all 8 devices on the data axis


def test_scan_bcd_compiled_is_distributed(data_mesh):
    n, d, k, bs = 64, 16, 4, 8
    rng = np.random.default_rng(0)
    with use_mesh(data_mesh):
        A = shard_batch(rng.standard_normal((n, d)).astype(np.float32))
        y = shard_batch(rng.standard_normal((n, k)).astype(np.float32))
        compiled = _bcd_scan.lower(
            A, y, jnp.float32(1.0), None, block_size=bs, num_iter=1
        ).compile()
    txt = compiled.as_text()
    # Gram/cross reductions over the row-sharded operands must be collectives
    assert "all-reduce" in txt, "no cross-device reduction in compiled BCD"
    # operands arrive 1/N: local parameter shape present, global absent
    assert _local_shape_pattern(n, d) in txt
    assert f"f32[{n},{d}]{{1,0}} parameter" not in txt
    in_shardings = compiled.input_shardings[0]
    assert any(
        getattr(s, "spec", None) is not None and s.spec[0] == DATA_AXIS
        for s in in_shardings
    ), f"inputs not data-sharded: {in_shardings}"


def test_exact_solver_compiled_is_distributed(data_mesh):
    n, d, k = 64, 16, 4
    rng = np.random.default_rng(1)
    with use_mesh(data_mesh):
        A = shard_batch(rng.standard_normal((n, d)).astype(np.float32))
        b = shard_batch(rng.standard_normal((n, k)).astype(np.float32))
        compiled = _ne_solve.lower(A, b, jnp.float32(1.0)).compile()
    txt = compiled.as_text()
    assert "all-reduce" in txt
    assert _local_shape_pattern(n, d) in txt


def test_weighted_class_grams_compiled_is_distributed(data_mesh):
    """The masked per-class Gram einsum of the weighted solver reduces over
    the sharded row axis — must lower to a collective, with the descriptor
    operand arriving 1/N."""
    n, d, C = 64, 12, 4
    rng = np.random.default_rng(2)
    with use_mesh(data_mesh):
        A = shard_batch(rng.standard_normal((n, d)).astype(np.float32))
        mask = shard_batch(
            (rng.random((n, C)) < 0.3).astype(np.float32)
        )
        compiled = _chunk_grams.lower(A, mask).compile()
    txt = compiled.as_text()
    assert "all-reduce" in txt
    assert _local_shape_pattern(n, d) in txt


def test_replicated_inputs_compile_without_collectives(data_mesh):
    """Control for the assertions above: the SAME program lowered with
    replicated (unsharded) inputs must NOT contain a cross-device
    reduction — proving 'all-reduce' in the sharded lowerings comes from
    the 1/N distribution, not from something incidental."""
    n, d, k, bs = 64, 16, 4, 8
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32))
    with use_mesh(data_mesh):
        compiled = _bcd_scan.lower(
            A, y, jnp.float32(1.0), None, block_size=bs, num_iter=1
        ).compile()
    assert "all-reduce" not in compiled.as_text()


def test_block_solver_model_axis_sharding():
    """VERDICT r4 #6: the MAIN block solver's d dimension distributes over
    MODEL_AXIS — W comes out P(model)-sharded (each device owns a column
    slice of the model), the data-axis Gram reduction is still a
    collective, and the result agrees with the unsharded solve."""
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.linalg import solve_blockwise_l2_scan
    from keystone_tpu.parallel.mesh import MODEL_AXIS

    n, d, k, bs = 64, 16, 4, 4
    rng = np.random.default_rng(5)
    An = rng.standard_normal((n, d)).astype(np.float32)
    yn = rng.standard_normal((n, k)).astype(np.float32)
    means = An.mean(axis=0)

    W_rep = np.asarray(
        solve_blockwise_l2_scan(
            jnp.asarray(An), jnp.asarray(yn), reg=1.0, block_size=bs,
            num_iter=1, means=jnp.asarray(means),
        )
    )
    mesh = make_mesh(n_data=4, n_model=2)
    with use_mesh(mesh):
        W = solve_blockwise_l2_scan(
            jnp.asarray(An), jnp.asarray(yn), reg=1.0, block_size=bs,
            num_iter=1, means=jnp.asarray(means),
        )
        assert W.sharding.spec == P(MODEL_AXIS), W.sharding
        # per-device shard really is a 1/n_model column slice of the model
        shard_shapes = {s.data.shape for s in W.addressable_shards}
        assert shard_shapes == {(d // 2, k)}, shard_shapes

        from keystone_tpu.linalg.bcd import _bcd_scan_model_sharded

        jitted = _bcd_scan_model_sharded(n, d, bs, 1, True)
        txt = jitted.lower(
            jnp.asarray(An), jnp.asarray(yn), jnp.float32(1.0),
            jnp.asarray(means),
        ).compile().as_text()
        assert "all-reduce" in txt, "no cross-device Gram reduction"
    np.testing.assert_allclose(np.asarray(W), W_rep, rtol=2e-4, atol=2e-5)


def test_block_estimator_uses_model_axis_on_mixed_mesh():
    """BlockLeastSquaresEstimator.fit on a data×model mesh produces the
    same model as on a pure data mesh (the sharded compile is routed
    through transparently)."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    n, d, k = 64, 16, 4
    rng = np.random.default_rng(6)
    An = rng.standard_normal((n, d)).astype(np.float32)
    yn = rng.standard_normal((n, k)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=4, num_iter=1, lam=0.5)
    m_data = est.fit(Dataset.of(jnp.asarray(An)), Dataset.of(jnp.asarray(yn)))
    with use_mesh(make_mesh(n_data=4, n_model=2)):
        m_mixed = est.fit(
            Dataset.of(jnp.asarray(An)), Dataset.of(jnp.asarray(yn))
        )
    Xt = rng.standard_normal((7, d)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(m_mixed.trace_batch(jnp.asarray(Xt))),
        np.asarray(m_data.trace_batch(jnp.asarray(Xt))),
        rtol=2e-4, atol=2e-4,
    )


def test_sharded_and_replicated_results_agree(data_mesh):
    n, d, k, bs = 64, 16, 4, 8
    rng = np.random.default_rng(4)
    An = rng.standard_normal((n, d)).astype(np.float32)
    yn = rng.standard_normal((n, k)).astype(np.float32)
    with use_mesh(data_mesh):
        W_sharded = np.asarray(
            _bcd_scan(
                shard_batch(An), shard_batch(yn), jnp.float32(1.0), None,
                block_size=bs, num_iter=1,
            )
        )
    W_rep = np.asarray(
        _bcd_scan(
            jnp.asarray(An), jnp.asarray(yn), jnp.float32(1.0), None,
            block_size=bs, num_iter=1,
        )
    )
    np.testing.assert_allclose(W_sharded, W_rep, rtol=2e-4, atol=2e-5)
