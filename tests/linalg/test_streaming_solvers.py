"""Streaming fit paths: the out-of-core BCD and weighted solves must agree
with their in-memory counterparts (VERDICT r4 #1 — pipeline fit without
materializing the featurized design matrix)."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import ChunkedDataset, Dataset
from keystone_tpu.linalg import (
    solve_blockwise_l2_scan,
    solve_blockwise_l2_streaming,
    stream_column_means,
)


def _problem(n=96, d=12, k=3, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, d)).astype(np.float32)
    W = rng.standard_normal((d, k)).astype(np.float32)
    y = (A @ W + 0.01 * rng.standard_normal((n, k))).astype(np.float32)
    return A, y


@pytest.mark.parametrize("num_iter", [1, 2])
@pytest.mark.parametrize("chunk", [17, 32, 96])
def test_streaming_bcd_matches_scan(num_iter, chunk):
    A, y = _problem()
    means = jnp.asarray(A.mean(axis=0))
    W_mem = solve_blockwise_l2_scan(
        jnp.asarray(A), jnp.asarray(y), reg=0.1, block_size=4,
        num_iter=num_iter, means=means,
    )
    scan = lambda: iter(
        [A[i : i + chunk] for i in range(0, len(A), chunk)]
    )
    ws = solve_blockwise_l2_streaming(
        scan, jnp.asarray(y), reg=0.1, block_size=4, num_iter=num_iter,
        means=means,
    )
    W_stream = jnp.concatenate(ws, axis=0)
    np.testing.assert_allclose(
        np.asarray(W_stream), np.asarray(W_mem), rtol=2e-3, atol=2e-4
    )


def test_streaming_bcd_ragged_last_block():
    A, y = _problem(d=10)  # blocks of 4, 4, 2
    ws = solve_blockwise_l2_streaming(
        lambda: iter([A[:50], A[50:]]), jnp.asarray(y), reg=0.05,
        block_size=4,
    )
    assert [int(w.shape[0]) for w in ws] == [4, 4, 2]
    from keystone_tpu.linalg import solve_blockwise_l2

    blocks = [A[:, 0:4], A[:, 4:8], A[:, 8:10]]
    ws_mem = solve_blockwise_l2(
        [jnp.asarray(b) for b in blocks], jnp.asarray(y), reg=0.05
    )
    for a, b in zip(ws, ws_mem):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )


def test_stream_column_means():
    A, _ = _problem()
    means, n = stream_column_means(lambda: iter([A[:40], A[40:]]))
    assert n == len(A)
    np.testing.assert_allclose(
        np.asarray(means), A.mean(axis=0), rtol=1e-5, atol=1e-6
    )


def test_block_estimator_streaming_fit_matches_in_memory():
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    A, y = _problem(n=80, d=8, k=2)
    est = BlockLeastSquaresEstimator(block_size=4, num_iter=2, lam=0.1)
    m_mem = est.fit(Dataset.of(jnp.asarray(A)), Dataset.of(jnp.asarray(y)))
    m_str = est.fit(
        ChunkedDataset.from_array(A, 19), Dataset.of(jnp.asarray(y))
    )
    X_test = np.random.default_rng(7).standard_normal((5, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(m_str.trace_batch(jnp.asarray(X_test))),
        np.asarray(m_mem.trace_batch(jnp.asarray(X_test))),
        rtol=2e-3, atol=2e-4,
    )


def _weighted_problem(n=60, d=10, k=4, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    labels = rng.integers(0, k, size=n)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), labels] = 1.0
    return X, Y


@pytest.mark.parametrize("num_iter", [1, 2])
def test_weighted_streaming_matches_in_memory(num_iter):
    from keystone_tpu.nodes.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, Y = _weighted_problem()
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=num_iter, lam=1e-2, mixture_weight=0.25,
        class_chunk=2,
    )
    blocks = [jnp.asarray(X[:, i : i + 4]) for i in range(0, 10, 4)]
    m_mem = est.train_with_l2(blocks, jnp.asarray(Y))
    m_str = est.train_streaming(
        ChunkedDataset.from_array(X, 13), jnp.asarray(Y)
    )
    X_test = np.random.default_rng(9).standard_normal((7, 10)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(m_str.trace_batch(jnp.asarray(X_test))),
        np.asarray(m_mem.trace_batch(jnp.asarray(X_test))),
        rtol=5e-3, atol=5e-4,
    )


def test_weighted_fit_routes_chunked_by_budget(monkeypatch):
    """Under-budget chunked input materializes once and solves in-memory;
    over-budget input takes the streaming trainer. Both agree."""
    from keystone_tpu.nodes.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, Y = _weighted_problem()
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=1, lam=1e-2, mixture_weight=0.25,
        class_chunk=2,
    )
    labels = Dataset.of(jnp.asarray(Y))
    m_small = est.fit(ChunkedDataset.from_array(X, 13), labels)
    monkeypatch.setenv("KEYSTONE_CHUNK_CACHE_BUDGET", "1")
    m_big = est.fit(ChunkedDataset.from_array(X, 13), labels)
    X_test = np.random.default_rng(2).standard_normal((6, 10)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(m_big.trace_batch(jnp.asarray(X_test))),
        np.asarray(m_small.trace_batch(jnp.asarray(X_test))),
        rtol=5e-3, atol=5e-4,
    )
