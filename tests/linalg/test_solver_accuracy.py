"""Float64 agreement oracles for the solvers (VERDICT r3 #2).

Parity spec: the reference solves in float64 Breeze/LAPACK; its suites pin
distributed-vs-local agreement (BlockLinearMapperSuite.scala:19-56,
PCASuite.scala:85). Here the independent oracle is NumPy float64 running the
SAME algorithm (same block order, same updates), so any precision loss in
the TPU path — not algorithmic difference — is what the comparison measures.

The shapes are small enough for CPU but large enough (reduction depth in the
tens of thousands) that single-pass bf16 matmuls measurably fail: the last
test *injects* a bf16 Gram and asserts the agreement bar catches it, proving
the 1e-3 tolerance is a live signal, not a formality.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.linalg import solve_blockwise_l2, solve_least_squares
from keystone_tpu.linalg.bcd import solve_blockwise_l2_scan

RTOL = 1e-3  # the agreement bar from VERDICT r3 next-round item 2


def _bcd_f64(A, y, reg, block_size, num_iter):
    """NumPy float64 BCD — same update order as linalg/bcd.py."""
    A = np.asarray(A, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, d = A.shape
    k = y.shape[1]
    nblocks = d // block_size
    W = [np.zeros((block_size, k)) for _ in range(nblocks)]
    pred = np.zeros_like(y)
    for _ in range(num_iter):
        for j in range(nblocks):
            Aj = A[:, j * block_size : (j + 1) * block_size]
            r = y - pred + Aj @ W[j]
            G = Aj.T @ Aj + reg * np.eye(block_size)
            Wj = np.linalg.solve(G, Aj.T @ r)
            pred = pred + Aj @ (Wj - W[j])
            W[j] = Wj
    return np.concatenate(W, axis=0)


def _problem(n=16384, d=2048, k=16, seed=0, noise=0.1):
    """Ridge problem with a realistic (~30) condition number: feature columns
    span 1.5 decades of scale, like un-normalized featurizer outputs. A
    spherical iid Gaussian would damp precision loss in the solve and let a
    bf16 Gram slip under the bar — conditioning is what makes the tolerance
    a live signal."""
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, d)).astype(np.float32)
    A *= np.logspace(-0.75, 0.75, d, dtype=np.float32)
    w_star = rng.standard_normal((d, k)).astype(np.float32) / np.sqrt(d)
    y = (A @ w_star + noise * rng.standard_normal((n, k))).astype(np.float32)
    return A, y, w_star


def _rel(a, b):
    return float(np.linalg.norm(a - b) / np.linalg.norm(b))


def test_exact_solver_agrees_with_float64():
    A, y, _ = _problem()
    reg = 1e-2
    W = np.asarray(solve_least_squares(jnp.asarray(A), jnp.asarray(y), reg=reg))
    A64 = A.astype(np.float64)
    W64 = np.linalg.solve(
        A64.T @ A64 + reg * np.eye(A.shape[1]), A64.T @ y.astype(np.float64)
    )
    assert _rel(W, W64) < RTOL


@pytest.mark.parametrize("num_iter", [1, 2])
def test_scan_bcd_agrees_with_float64(num_iter):
    A, y, _ = _problem(n=8192, d=2048, k=8)
    reg, bs = 10.0, 512
    W = np.asarray(
        solve_blockwise_l2_scan(
            jnp.asarray(A), jnp.asarray(y), reg=reg, block_size=bs,
            num_iter=num_iter,
        )
    )
    W64 = _bcd_f64(A, y, reg, bs, num_iter)
    assert _rel(W, W64) < RTOL


def test_hostloop_bcd_agrees_with_float64():
    A, y, _ = _problem(n=8192, d=2048, k=8)
    reg, bs = 10.0, 512
    blocks = [jnp.asarray(A[:, i : i + bs]) for i in range(0, A.shape[1], bs)]
    Ws = solve_blockwise_l2(blocks, jnp.asarray(y), reg=reg, num_iter=1)
    W = np.concatenate([np.asarray(w) for w in Ws], axis=0)
    W64 = _bcd_f64(A, y, reg, bs, 1)
    assert _rel(W, W64) < RTOL


def test_scan_and_hostloop_paths_agree():
    """The two BCD paths are the same algorithm; they must agree to much
    tighter than the f64 bar (they share precision and order)."""
    A, y, _ = _problem(n=4096, d=1024, k=4)
    reg, bs = 5.0, 256
    blocks = [jnp.asarray(A[:, i : i + bs]) for i in range(0, A.shape[1], bs)]
    Ws = solve_blockwise_l2(blocks, jnp.asarray(y), reg=reg, num_iter=2)
    W_loop = np.concatenate([np.asarray(w) for w in Ws], axis=0)
    W_scan = np.asarray(
        solve_blockwise_l2_scan(
            jnp.asarray(A), jnp.asarray(y), reg=reg, block_size=bs, num_iter=2
        )
    )
    np.testing.assert_allclose(W_scan, W_loop, rtol=2e-4, atol=2e-5)


def test_scan_bcd_centering_matches_explicit():
    """means= fused centering ≡ solving the explicitly centered matrix."""
    A, y, _ = _problem(n=4096, d=1024, k=4, seed=3)
    A = A + 2.5  # give the columns real means
    reg, bs = 5.0, 256
    mean = A.mean(axis=0)
    W_fused = np.asarray(
        solve_blockwise_l2_scan(
            jnp.asarray(A), jnp.asarray(y), reg=reg, block_size=bs,
            num_iter=1, means=jnp.asarray(mean),
        )
    )
    W_explicit = np.asarray(
        solve_blockwise_l2_scan(
            jnp.asarray(A - mean), jnp.asarray(y), reg=reg, block_size=bs,
            num_iter=1,
        )
    )
    np.testing.assert_allclose(W_fused, W_explicit, rtol=2e-4, atol=2e-5)


def test_streaming_solver_agrees_with_float64():
    """Chunked Gram accumulation ≡ the one-shot float64 solve: the streaming
    path is how >HBM datasets solve exactly, so it gets the same bar."""
    from keystone_tpu.linalg import solve_least_squares_streaming

    A, y, _ = _problem(n=16384, d=1024, k=8, seed=1)
    reg = 1e-2
    chunk = 4096
    chunks = (
        (A[i : i + chunk], y[i : i + chunk]) for i in range(0, len(A), chunk)
    )
    W = np.asarray(solve_least_squares_streaming(chunks, reg=reg))
    A64 = A.astype(np.float64)
    W64 = np.linalg.solve(
        A64.T @ A64 + reg * np.eye(A.shape[1]), A64.T @ y.astype(np.float64)
    )
    assert _rel(W, W64) < RTOL


def test_injected_bf16_gram_fails_the_bar():
    """Teeth check: recompute the exact solve with a single-pass-bf16 Gram
    (the regression the agreement bar exists to catch) and assert it FAILS.
    If this test ever breaks, the bar has gone soft."""
    A, y, _ = _problem()
    reg = 1e-2

    @jax.jit
    def bf16_solve(A, y):
        Ab = A.astype(jnp.bfloat16)
        G = (Ab.T @ Ab).astype(jnp.float32)
        c = (Ab.T @ y.astype(jnp.bfloat16)).astype(jnp.float32)
        G = G + reg * jnp.eye(G.shape[0], dtype=jnp.float32)
        cho = jax.scipy.linalg.cho_factor(G, lower=True)
        return jax.scipy.linalg.cho_solve(cho, c)

    W_bf16 = np.asarray(bf16_solve(jnp.asarray(A), jnp.asarray(y)))
    A64 = A.astype(np.float64)
    W64 = np.linalg.solve(
        A64.T @ A64 + reg * np.eye(A.shape[1]), A64.T @ y.astype(np.float64)
    )
    assert _rel(W_bf16, W64) > RTOL
