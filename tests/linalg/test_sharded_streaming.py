"""Mesh-sharded streaming-solver parity (ISSUE 7): per-lane partial
accumulators reduced once per block / once at finalize must match the
single-lane scan to <= 1e-6 on the suite's virtual 8-device mesh —
including the intercept/centering path and a ragged final chunk — and the
cross-mesh collective count must be O(blocks), never O(chunks)."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import ChunkedDataset, Dataset
from keystone_tpu.linalg import (
    solve_blockwise_l2_streaming,
    solve_least_squares_streaming,
    stream_column_means,
    tsqr_r,
    tsqr_r_streaming,
)

TOL = 1e-6


def _problem(n=208, d=24, k=3, seed=0, scale=None):
    rng = np.random.default_rng(seed)
    s = scale if scale is not None else 1.0 / np.sqrt(n)
    A = (rng.standard_normal((n, d)) * s).astype(np.float32)
    y = (rng.standard_normal((n, k)) * s).astype(np.float32)
    return A, y


def _maxdiff(a, b):
    return float(np.abs(np.asarray(a) - np.asarray(b)).max())


# -- normal equations ---------------------------------------------------------


@pytest.mark.parametrize("lanes", [2, 8])
def test_streaming_normal_eq_lane_parity_with_ragged_tail(lanes):
    A, y = _problem()
    n = len(A)

    def pairs():
        # 7 chunks of 32 rows, then a ragged 16-row final chunk — more
        # chunks than lanes, so the per-lane reduction genuinely reorders
        return iter([(A[i : i + 32], y[i : i + 32]) for i in range(0, n, 32)])

    W1 = solve_least_squares_streaming(pairs(), reg=0.1, lanes=1)
    WN = solve_least_squares_streaming(pairs(), reg=0.1, lanes=lanes)
    assert _maxdiff(W1, WN) <= TOL


# -- BCD ----------------------------------------------------------------------


@pytest.mark.parametrize("lanes", [2, 8])
@pytest.mark.parametrize("num_iter", [1, 2])
def test_streaming_bcd_lane_parity_centered_ragged(lanes, num_iter):
    A, y = _problem(n=204, d=16)
    n = len(A)
    means = jnp.asarray(A.mean(axis=0))

    def scan():
        # ragged final chunk (204 = 5*36 + 24)
        return iter([A[i : i + 36] for i in range(0, n, 36)])

    kw = dict(reg=0.1, block_size=4, num_iter=num_iter, means=means)
    ws1 = solve_blockwise_l2_streaming(scan, jnp.asarray(y), lanes=1, **kw)
    wsN = solve_blockwise_l2_streaming(scan, jnp.asarray(y), lanes=lanes, **kw)
    for a, b in zip(ws1, wsN):
        assert _maxdiff(a, b) <= TOL


def test_streaming_bcd_tolerates_prestaged_passthrough_source():
    """Regression: a chunk_scan that hands back an already-pipelined (or
    otherwise pre-staged) iterator bypasses lane staging — the laned
    solver must co-locate those chunks with its resident slabs instead of
    mixing committed devices inside the lane program."""
    from keystone_tpu.data.pipeline_scan import scan_pipeline

    A, y = _problem(n=96, d=8)

    def raw():
        return iter([A[i : i + 24] for i in range(0, 96, 24)])

    kw = dict(reg=0.1, block_size=4, num_iter=1,
              means=jnp.asarray(A.mean(axis=0)))
    ws_ref = solve_blockwise_l2_streaming(raw, jnp.asarray(y), lanes=1, **kw)
    ws = solve_blockwise_l2_streaming(
        lambda: scan_pipeline(raw(), label="pre"),  # lanes=1 passthrough
        jnp.asarray(y), lanes=4, **kw,
    )
    for a, b in zip(ws_ref, ws):
        assert _maxdiff(a, b) <= TOL


def test_streaming_bcd_lane_boundary_change_rejected():
    A, y = _problem(n=96, d=8)
    boundaries = [[0, 48, 96], [0, 32, 64, 96]]

    def scan():
        cuts = boundaries.pop(0)
        return iter([A[a:b] for a, b in zip(cuts, cuts[1:])])

    with pytest.raises(ValueError, match="changed boundaries|produced"):
        solve_blockwise_l2_streaming(
            scan, jnp.asarray(y), reg=0.1, block_size=4, num_iter=1,
            means=jnp.asarray(A.mean(axis=0)), lanes=4,
        )


# -- centering / intercept path ----------------------------------------------


def test_block_estimator_streaming_intercept_lane_parity(monkeypatch):
    """The full centering/intercept path (stream_column_means + centered
    streaming BCD + label-mean intercept) through
    BlockLeastSquaresEstimator: an 8-lane fit must match the 1-lane fit
    to <= 1e-6 in weights, intercept, and predictions."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    A, y = _problem(n=208, d=16, k=2, seed=3)
    A = A + 0.5  # nonzero column means make centering do real work
    labels = Dataset.of(jnp.asarray(y))

    def fit(lanes):
        monkeypatch.setenv("KEYSTONE_SCAN_LANES", str(lanes))
        est = BlockLeastSquaresEstimator(block_size=4, num_iter=1, lam=0.1)
        return est.fit(ChunkedDataset.from_array(A, 36), labels)

    m1 = fit(1)
    m8 = fit(8)
    assert _maxdiff(m1._W, m8._W) <= TOL
    assert _maxdiff(m1.b, m8.b) <= TOL
    x = jnp.asarray(A[:16])
    assert _maxdiff(m1.trace_batch(x), m8.trace_batch(x)) <= TOL


def test_stream_column_means_lane_parity():
    A, _ = _problem(n=208, d=24, scale=1.0)

    def scan():
        return iter([A[i : i + 32] for i in range(0, len(A), 32)])

    mu1, n1 = stream_column_means(scan, lanes=1)
    mu8, n8 = stream_column_means(scan, lanes=8)
    assert n1 == n8 == len(A)
    assert _maxdiff(mu1, mu8) <= TOL


def test_standard_scaler_streaming_lane_parity(monkeypatch):
    from keystone_tpu.nodes.stats import StandardScaler

    rng = np.random.default_rng(17)
    X = (rng.standard_normal((208, 6)) * 3.0 + 50.0).astype(np.float32)

    def fit(lanes):
        monkeypatch.setenv("KEYSTONE_SCAN_LANES", str(lanes))
        return StandardScaler().fit(ChunkedDataset.from_array(X, 36))

    m1, m8 = fit(1), fit(8)
    assert _maxdiff(m1.mean, m8.mean) <= 1e-5
    assert _maxdiff(m1.std, m8.std) <= 1e-5


# -- collective schedule ------------------------------------------------------


def _bcd_collectives(A, y, chunk, lanes, block_size=4):
    from keystone_tpu.obs import SCAN_SPAN, Tracer, install
    from keystone_tpu.obs import tracer as trace_mod

    def scan():
        return iter([A[i : i + chunk] for i in range(0, len(A), chunk)])

    tracer = install(Tracer())
    try:
        solve_blockwise_l2_streaming(
            scan, jnp.asarray(y), reg=0.1, block_size=block_size,
            num_iter=1, means=jnp.asarray(A.mean(axis=0)), lanes=lanes,
        )
        spans = [
            sp
            for sp in tracer.spans()
            if sp.name == SCAN_SPAN and sp.attrs["label"] == "bcd.stream"
        ]
        return [sp.attrs.get("collectives", 0) for sp in spans]
    finally:
        trace_mod.reset()


def test_bcd_collectives_per_block_not_per_chunk():
    """The PAPERS.md #3 gate: per-scan cross-mesh transfers must not grow
    with the chunk count — halving the chunk size (2x the chunks) leaves
    every block step's collective count unchanged."""
    A, y = _problem(n=192, d=16)
    coarse = _bcd_collectives(A, y, chunk=48, lanes=4)  # 4 chunks/scan
    fine = _bcd_collectives(A, y, chunk=24, lanes=4)    # 8 chunks/scan
    assert len(coarse) == len(fine) == 4  # one scan per block step
    assert coarse == fine
    assert all(c > 0 for c in coarse)
    # and the single-lane path reports no cross-mesh traffic at all
    single = _bcd_collectives(A, y, chunk=48, lanes=1)
    assert all(c == 0 for c in single)


# -- class-weighted least squares ---------------------------------------------


def _weighted_problem(n=204, d=16, k=4, seed=3):
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((n, d)) / np.sqrt(n)).astype(np.float32)
    labels = rng.integers(0, k, size=n)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), labels] = 1.0
    return X, Y


@pytest.mark.parametrize("lanes", [2, 8])
@pytest.mark.parametrize("num_iter", [1, 2])
def test_weighted_streaming_lane_parity_ragged(lanes, num_iter):
    """ROADMAP PR-7 follow-on: the K-lane weighted solve (per-lane
    cross/Gram/class-sum partials reduced once per block) must match the
    single-lane scan to <= 1e-6, ragged final chunk included
    (204 = 5*36 + 24)."""
    from keystone_tpu.linalg import solve_weighted_streaming

    X, Y = _weighted_problem()

    def scan():
        return iter([X[i : i + 36] for i in range(0, len(X), 36)])

    kw = dict(
        block_size=4, num_iter=num_iter, lam=1e-2, mixture_weight=0.25,
        class_chunk=2,
    )
    ws1, b1 = solve_weighted_streaming(scan, jnp.asarray(Y), lanes=1, **kw)
    wsN, bN = solve_weighted_streaming(scan, jnp.asarray(Y), lanes=lanes, **kw)
    for a, b in zip(ws1, wsN):
        assert _maxdiff(a, b) <= TOL
    assert _maxdiff(b1, bN) <= TOL


def test_weighted_collectives_per_block_not_per_chunk():
    """Halving the chunk size (2x the chunks) must leave the weighted
    scan's per-block-step collective count unchanged."""
    from keystone_tpu.linalg import solve_weighted_streaming
    from keystone_tpu.obs import SCAN_SPAN, Tracer, install
    from keystone_tpu.obs import tracer as trace_mod

    X, Y = _weighted_problem(n=192)

    def run(chunk):
        def scan():
            return iter([X[i : i + chunk] for i in range(0, len(X), chunk)])

        tracer = install(Tracer())
        try:
            solve_weighted_streaming(
                scan, jnp.asarray(Y), block_size=8, num_iter=1, lam=1e-2,
                mixture_weight=0.25, class_chunk=2, lanes=4,
            )
            return [
                sp.attrs.get("collectives", 0)
                for sp in tracer.spans()
                if sp.name == SCAN_SPAN
                and sp.attrs["label"] == "wls.stream"
            ]
        finally:
            trace_mod.reset()

    coarse, fine = run(48), run(24)
    assert len(coarse) == len(fine) > 0
    assert coarse == fine


def test_weighted_estimator_streaming_lane_parity(monkeypatch):
    """Front door: a chunked BlockWeightedLeastSquaresEstimator fit at 8
    lanes must match the 1-lane fit to <= 1e-6 in predictions."""
    from keystone_tpu.nodes.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, Y = _weighted_problem(n=208)
    labels = Dataset.of(jnp.asarray(Y))

    def fit(lanes):
        monkeypatch.setenv("KEYSTONE_SCAN_LANES", str(lanes))
        monkeypatch.setenv("KEYSTONE_CHUNK_CACHE_BUDGET", "1")
        est = BlockWeightedLeastSquaresEstimator(
            block_size=4, num_iter=1, lam=1e-2, mixture_weight=0.25,
            class_chunk=2,
        )
        return est.fit(ChunkedDataset.from_array(X, 36), labels)

    m1, m8 = fit(1), fit(8)
    x = jnp.asarray(X[:16])
    assert _maxdiff(m1.trace_batch(x), m8.trace_batch(x)) <= TOL


# -- TSQR ---------------------------------------------------------------------


@pytest.mark.parametrize("lanes", [1, 8])
def test_tsqr_streaming_matches_mesh_tsqr(lanes):
    A, _ = _problem(n=192, d=8, scale=1.0)

    def scan():
        return iter([A[i : i + 36] for i in range(0, len(A), 36)])

    R_mesh = tsqr_r(jnp.asarray(A))
    R_stream = tsqr_r_streaming(scan, lanes=lanes)
    assert R_stream.shape == (8, 8)
    assert _maxdiff(R_mesh, R_stream) <= 5e-5  # f32 QR, different orders
