"""Multi-device solver tests on the 8-device CPU mesh: the framework's
solver entry points must (a) run on sharded inputs, (b) distribute the
intended dimension, and (c) agree with their single-device results
(VERDICT r2 #4: mesh-asserting tests through the framework code paths).
"""

import jax
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning.lbfgs import SparseLBFGSwithL2
from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
from keystone_tpu.nodes.learning.weighted import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
    _batched_solve,
)
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    shard_batch,
    shard_classes,
    use_mesh,
)


@pytest.fixture
def dm_mesh():
    """4 (data) × 2 (model) mesh over the 8 virtual CPU devices."""
    return make_mesh(n_data=4, n_model=2)


def _weighted_problem(n=96, d=12, k=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y_idx = rng.integers(0, k, n)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), y_idx] = 1.0
    return X, Y


def test_shard_classes_distributes_model_axis(dm_mesh):
    with use_mesh(dm_mesh):
        G = np.zeros((8, 6, 6), dtype=np.float32)
        Gs = shard_classes(G)
        assert len(Gs.sharding.device_set) == 8
        # class dim (axis 0) split over the 2-wide model axis
        spec = Gs.sharding.spec
        assert spec[0] == MODEL_AXIS
        # non-divisible class dims fall back to replication, not crash
        Gr = shard_classes(np.zeros((7, 6, 6), dtype=np.float32))
        assert Gr.sharding.spec == jax.sharding.PartitionSpec()


def test_weighted_solver_per_class_solve_is_model_sharded(dm_mesh):
    """The batched per-class Cholesky consumes MODEL_AXIS-sharded operands
    and its per-class output stays distributed (the reference capability:
    executor-parallel per-class solves, BlockWeightedLeastSquares.scala
    :177-313)."""
    with use_mesh(dm_mesh):
        rng = np.random.default_rng(1)
        C, d = 8, 6
        base = rng.standard_normal((C, d, d)).astype(np.float32)
        G = np.einsum("cde,cfe->cdf", base, base) + 3 * np.eye(d, dtype=np.float32)
        rhs = rng.standard_normal((C, d)).astype(np.float32)
        Gs, rs = shard_classes(G), shard_classes(rhs)
        out = _batched_solve(Gs, rs, 0.1)
        jax.block_until_ready(out)
        assert len(out.sharding.device_set) == 8
        expect = np.stack(
            [np.linalg.solve(G[c] + 0.1 * np.eye(d), rhs[c]) for c in range(C)]
        )
        np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-3, atol=2e-3)


def test_weighted_estimator_on_mesh_matches_per_class_oracle(dm_mesh):
    X, Y = _weighted_problem()
    with use_mesh(dm_mesh):
        Xs = shard_batch(X)
        assert len(Xs.sharding.device_set) == 8
        block = BlockWeightedLeastSquaresEstimator(
            block_size=12, num_iter=8, lam=1e-2, mixture_weight=0.25,
            class_chunk=8,
        ).fit(Dataset.of(Xs), Dataset.of(Y))
        oracle = PerClassWeightedLeastSquaresEstimator(
            block_size=12, num_iter=1, lam=1e-2, mixture_weight=0.25
        ).fit(Dataset.of(X), Dataset.of(Y))
        got = np.asarray(block.trace_batch(Xs))
        want = np.asarray(oracle.trace_batch(X))
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


def test_weighted_dual_path_on_mesh_matches_per_class_oracle(dm_mesh):
    """The few-shot/many-class DUAL solve (n + 3 < d: QR + sample-span
    systems) on the mesh, with its per-class systems sharded over
    MODEL_AXIS — the ImageNet 1000-class regime's multi-chip story."""
    rng = np.random.default_rng(9)
    n, d, k = 24, 48, 8  # n + 3 < d → dual path engages
    y = np.repeat(np.arange(k), n // k)
    rng.shuffle(y)
    X = (rng.standard_normal((n, d)) + 0.7 * rng.standard_normal((d, k)).T[y]
         ).astype(np.float32)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), y] = 1.0
    X_test = rng.standard_normal((16, d)).astype(np.float32)
    with use_mesh(dm_mesh):
        Xs = shard_batch(X)
        assert len(Xs.sharding.device_set) == 8
        dual = BlockWeightedLeastSquaresEstimator(
            block_size=d, num_iter=1, lam=1e-3, mixture_weight=0.25,
            class_chunk=k,
        ).fit(Dataset.of(Xs), Dataset.of(Y))
        oracle = PerClassWeightedLeastSquaresEstimator(
            block_size=d, num_iter=1, lam=1e-3, mixture_weight=0.25
        ).fit(Dataset.of(X), Dataset.of(Y))
        # held-out rows: train rows cannot see span-orthogonal error
        got = np.asarray(dual.trace_batch(X_test))
        want = np.asarray(oracle.trace_batch(X_test))
        scale = np.abs(want).max()
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2 * scale)


def test_block_ls_estimator_fit_on_sharded_rows(dm_mesh):
    rng = np.random.default_rng(2)
    n, d, k = 64, 16, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    W_true = rng.standard_normal((d, k)).astype(np.float32)
    Y = X @ W_true
    with use_mesh(dm_mesh):
        Xs = shard_batch(X)
        assert len(Xs.sharding.device_set) == 8
        model = BlockLeastSquaresEstimator(8, 20, 1e-6).fit(
            Dataset.of(Xs), Dataset.of(Y)
        )
        pred = np.asarray(model.trace_batch(X))
    np.testing.assert_allclose(pred, Y, rtol=5e-3, atol=5e-3)


def test_sparse_lbfgs_fit_on_mesh(dm_mesh):
    """Sparse LBFGS consumes mesh-sharded dense fallback + SparseRows paths
    and reproduces the dense solution."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseRows

    rng = np.random.default_rng(3)
    n, d, k = 64, 10, 3
    dense = (rng.random((n, d)) < 0.3) * rng.standard_normal((n, d))
    dense = dense.astype(np.float32)
    Y = np.sign(rng.standard_normal((n, k))).astype(np.float32)
    sparse = SparseRows.from_scipy(sp.csr_matrix(dense))
    with use_mesh(dm_mesh):
        est = SparseLBFGSwithL2(reg_param=1e-2, num_iterations=25)
        m_sparse = est.fit(Dataset(sparse, batched=True), Dataset.of(Y))
        m_dense = SparseLBFGSwithL2(reg_param=1e-2, num_iterations=25).fit(
            Dataset.of(shard_batch(dense)), Dataset.of(Y)
        )
        out_s = np.asarray(
            m_sparse.apply_batch(Dataset(sparse, batched=True)).to_array()
        )
        out_d = np.asarray(m_dense.apply_batch(Dataset.of(dense)).to_array())
    np.testing.assert_allclose(out_s, out_d, rtol=1e-2, atol=1e-2)
