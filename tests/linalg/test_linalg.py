"""Distributed-vs-local agreement tests for the linalg substrate — the same
oracle family the reference uses (e.g. DistributedPCA vs local PCA,
nodes/learning/PCASuite.scala:85), with the 8-device CPU mesh standing in for
the cluster."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.linalg import (
    RowShardedMatrix,
    solve_blockwise_l2,
    solve_blockwise_l2_scan,
    solve_least_squares,
    solve_least_squares_with_intercept,
    tsqr_r,
)
from keystone_tpu.parallel import make_mesh, shard_batch, use_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _rand(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


def test_gram_matches_numpy(mesh):
    rng = np.random.default_rng(0)
    A = _rand(rng, 64, 16)
    with use_mesh(mesh):
        M = RowShardedMatrix(A)
        G = np.asarray(M.gram())
    np.testing.assert_allclose(G, A.T @ A, rtol=1e-4, atol=1e-4)


def test_normal_equations_vs_numpy_lstsq(mesh):
    rng = np.random.default_rng(1)
    A = _rand(rng, 128, 10)
    W_true = _rand(rng, 10, 3)
    b = A @ W_true
    with use_mesh(mesh):
        W = np.asarray(solve_least_squares(shard_batch(A), shard_batch(b)))
    np.testing.assert_allclose(W, W_true, rtol=1e-2, atol=1e-3)


def test_normal_equations_l2_matches_closed_form(mesh):
    rng = np.random.default_rng(2)
    A = _rand(rng, 96, 8)
    b = _rand(rng, 96, 2)
    lam = 0.5
    with use_mesh(mesh):
        W = np.asarray(solve_least_squares(shard_batch(A), shard_batch(b), reg=lam))
    expected = np.linalg.solve(A.T @ A + lam * np.eye(8), A.T @ b)
    np.testing.assert_allclose(W, expected, rtol=1e-3, atol=1e-3)


def test_intercept_solver(mesh):
    rng = np.random.default_rng(3)
    A = _rand(rng, 80, 6)
    W_true = _rand(rng, 6, 2)
    intercept_true = np.array([1.5, -2.0], dtype=np.float32)
    b = A @ W_true + intercept_true
    with use_mesh(mesh):
        W, c = solve_least_squares_with_intercept(shard_batch(A), shard_batch(b))
    np.testing.assert_allclose(np.asarray(W), W_true, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(c), intercept_true, rtol=1e-2, atol=1e-2)


def test_bcd_one_block_equals_normal_equations(mesh):
    rng = np.random.default_rng(4)
    A = _rand(rng, 64, 12)
    b = _rand(rng, 64, 3)
    lam = 0.1
    with use_mesh(mesh):
        (W,) = solve_blockwise_l2([shard_batch(A)], shard_batch(b), reg=lam)
    expected = np.linalg.solve(A.T @ A + lam * np.eye(12), A.T @ b)
    np.testing.assert_allclose(np.asarray(W), expected, rtol=1e-3, atol=1e-3)


def test_bcd_converges_to_ridge_solution(mesh):
    """Multi-block BCD with enough epochs must reach the joint ridge optimum
    (parity: BlockWeightedLeastSquaresSuite gradient-at-optimum checks)."""
    rng = np.random.default_rng(5)
    n, d, k, bs = 128, 24, 4, 8
    A = _rand(rng, n, d)
    b = _rand(rng, n, k)
    lam = 0.3
    blocks = [A[:, i : i + bs] for i in range(0, d, bs)]
    with use_mesh(mesh):
        Ws = solve_blockwise_l2(
            [shard_batch(x) for x in blocks], shard_batch(b), reg=lam, num_iter=50
        )
    W = np.concatenate([np.asarray(w) for w in Ws], axis=0)
    expected = np.linalg.solve(A.T @ A + lam * np.eye(d), A.T @ b)
    np.testing.assert_allclose(W, expected, rtol=1e-2, atol=1e-2)


def test_bcd_scan_matches_host_loop(mesh):
    rng = np.random.default_rng(6)
    n, d, k, bs = 64, 16, 2, 4
    A = _rand(rng, n, d)
    b = _rand(rng, n, k)
    lam = 0.2
    blocks = [A[:, i : i + bs] for i in range(0, d, bs)]
    with use_mesh(mesh):
        Ws = solve_blockwise_l2(
            [shard_batch(x) for x in blocks], shard_batch(b), reg=lam, num_iter=3
        )
        W_host = np.concatenate([np.asarray(w) for w in Ws], axis=0)
        W_scan = np.asarray(
            solve_blockwise_l2_scan(A, b, reg=lam, block_size=bs, num_iter=3)
        )
    np.testing.assert_allclose(W_scan, W_host, rtol=1e-4, atol=1e-4)


def test_tsqr_r_matches_local_qr(mesh):
    rng = np.random.default_rng(7)
    A = _rand(rng, 256, 12)
    with use_mesh(mesh):
        R = np.asarray(tsqr_r(A, mesh=mesh))
    R_local = np.linalg.qr(A, mode="r")
    s = np.sign(np.diag(R_local))
    s[s == 0] = 1
    R_local = R_local * s[:, None]
    assert R.shape == (12, 12)
    np.testing.assert_allclose(np.abs(R), np.abs(R_local), rtol=1e-3, atol=1e-3)
    # R must reproduce the Gram matrix: RᵀR = AᵀA
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-3, atol=1e-3)


def test_gram_is_actually_sharded(mesh):
    """The input really is distributed over 8 devices (regression guard for
    the mesh substrate silently replicating)."""
    A = np.ones((64, 4), dtype=np.float32)
    with use_mesh(mesh):
        X = shard_batch(A)
    assert len(X.sharding.device_set) == 8
