"""Snapshot-able streaming accumulators: chunk-order associativity,
snapshot isolation, and agreement with direct dense computation."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.linalg import GramSolverState, MomentsState, TsqrRState


def _data(n=200, d=16, k=3, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(n, d)).astype(np.float32) + 0.7
    y = rng.normal(size=(n, k)).astype(np.float32) - 1.2
    return A, y


def test_gram_state_matches_dense_ridge():
    """solve(lam) from folded chunks == the centered normal-equations
    solution computed directly."""
    A, y = _data()
    state = GramSolverState()
    for i in range(0, 200, 64):  # ragged tail: 8 rows
        state.update(A[i : i + 64], y[i : i + 64])
    assert state.n == 200 and state.rows_folded == 200
    W, b, mean = state.solve(0.1)

    Ac = A - A.mean(axis=0)
    yc = y - y.mean(axis=0)
    G = Ac.T @ Ac + 0.1 * np.eye(16, dtype=np.float32)
    W_ref = np.linalg.solve(G.astype(np.float64), (Ac.T @ yc).astype(np.float64))
    np.testing.assert_allclose(np.asarray(W), W_ref, atol=1e-3)
    np.testing.assert_allclose(np.asarray(mean), A.mean(axis=0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(b), y.mean(axis=0), atol=1e-5)


def test_gram_state_snapshot_isolates_and_zeroes_work_counter():
    A, y = _data()
    state = GramSolverState().update(A[:100], y[:100])
    snap = state.snapshot()
    assert snap.n == 100 and snap.rows_folded == 0
    snap.update(A[100:], y[100:])
    assert snap.rows_folded == 100  # only post-snapshot work counted
    # the original never saw the second fold
    assert state.n == 100
    assert np.max(np.abs(state.gram - snap.gram)) > 1e-3


def test_gram_state_merge_is_the_two_chunk_fold():
    """Merging two independently-built states == folding both chunk
    ranges into one state. The raw sums are exactly equal; the products
    are held against each state's own provisional shift (b's differs
    from the fold's), so they compare after translation through solve —
    per-path f32 rounding only."""
    A, y = _data()
    a = GramSolverState().update(A[:120], y[:120])
    b = GramSolverState().update(A[120:], y[120:])
    merged = a.merge(b)
    whole = GramSolverState().update(A[:120], y[:120]).update(A[120:], y[120:])
    np.testing.assert_allclose(merged.sum_x, whole.sum_x, atol=1e-5)
    np.testing.assert_allclose(merged.sum_y, whole.sum_y, atol=1e-5)
    assert merged.n == whole.n == 200
    Wm, bm, mm = merged.solve(0.1)
    Ww, bw, mw = whole.solve(0.1)
    np.testing.assert_allclose(np.asarray(Wm), np.asarray(Ww), atol=1e-4)
    np.testing.assert_allclose(np.asarray(mm), np.asarray(mw), atol=1e-6)
    np.testing.assert_allclose(np.asarray(bm), np.asarray(bw), atol=1e-6)


def test_gram_state_merge_into_empty_mutates_in_place():
    """The per-lane reduce pattern (total = empty; total.merge(p) per
    partial) must work in place: merging into an empty state adopts the
    other's sums INTO self and counts the rows as folded work."""
    A, y = _data()
    p1 = GramSolverState().update(A[:120], y[:120])
    p2 = GramSolverState().update(A[120:], y[120:])
    total = GramSolverState()
    total.merge(p1)
    total.merge(p2)
    assert total.n == 200 and total.rows_folded == 200
    whole = GramSolverState().update(A[:120], y[:120]).update(A[120:], y[120:])
    Wt, bt, mt = total.solve(0.1)
    Ww, bw, mw = whole.solve(0.1)
    np.testing.assert_allclose(np.asarray(Wt), np.asarray(Ww), atol=1e-4)
    np.testing.assert_allclose(np.asarray(mt), np.asarray(mw), atol=1e-6)
    # p1 is isolated from the adopting copy
    total.update(A[:10], y[:10])
    assert p1.n == 120


def test_gram_state_shape_mismatch_raises():
    A, y = _data()
    state = GramSolverState().update(A, y)
    with pytest.raises(ValueError, match="does not match"):
        state.update(A[:, :8], y)
    with pytest.raises(ValueError):
        GramSolverState().solve(0.1)


def test_gram_state_survives_large_offset_means():
    """mean/std = 1000 at n=50k: raw f32 sums lose the centered signal
    entirely (σ²/μ² = 1e-6 is below f32 epsilon); the shifted f64
    accumulation must track the f64 direct solve."""
    rng = np.random.default_rng(1)
    n, d, k = 50_000, 8, 2
    A = (rng.standard_normal((n, d)) * 0.1 + 100.0).astype(np.float32)
    W0 = rng.standard_normal((d, k)).astype(np.float32)
    y = ((A - 100.0) @ W0).astype(np.float32)
    state = GramSolverState()
    for i in range(0, n, 8192):
        state.update(A[i : i + 8192], y[i : i + 8192])
    W, _, _ = state.solve(1e-3)
    Ac = (A - A.mean(axis=0)).astype(np.float64)
    yc = (y - y.mean(axis=0)).astype(np.float64)
    W_ref = np.linalg.solve(Ac.T @ Ac + 1e-3 * np.eye(d), Ac.T @ yc)
    rel = np.max(np.abs(np.asarray(W, dtype=np.float64) - W_ref)) / np.max(
        np.abs(W_ref)
    )
    assert rel <= 1e-3, rel


def test_tsqr_state_resumes_the_fold():
    """Folding chunks [a, b] then appending c == folding [a, b, c] from
    scratch == the direct QR of the stacked matrix (R is unique up to
    signs, which finalize fixes)."""
    A, _ = _data(n=300, d=12)
    state = TsqrRState()
    for i in range(0, 200, 64):  # ragged tail: 8 rows
        state.update(A[i : min(i + 64, 200)])
    resumed = state.snapshot()
    resumed.update(A[200:])
    scratch = TsqrRState()
    for i in range(0, 300, 64):
        scratch.update(A[i : i + 64])
    np.testing.assert_allclose(
        np.asarray(resumed.finalize()), np.asarray(scratch.finalize()),
        atol=1e-4,
    )
    R_direct = np.linalg.qr(A, mode="r")
    R_direct *= np.sign(np.diag(R_direct))[:, None]
    np.testing.assert_allclose(
        np.asarray(resumed.finalize()), R_direct, atol=1e-3
    )


def test_moments_state_matches_numpy_and_merges():
    A, _ = _data(n=257)
    state = MomentsState()
    for i in range(0, 257, 50):  # ragged tail: 7 rows
        state.update(A[i : i + 50])
    np.testing.assert_allclose(state.mean, A.mean(axis=0), atol=1e-6)
    np.testing.assert_allclose(state.std(), A.std(axis=0), atol=1e-6)

    left = MomentsState().update(A[:100])
    right = MomentsState().update(A[100:])
    left.merge(right)
    np.testing.assert_allclose(left.mean, A.mean(axis=0), atol=1e-6)
    np.testing.assert_allclose(left.std(), A.std(axis=0), atol=1e-6)


def test_gram_state_device_chunks_accepted():
    """Device-resident chunks (the staged-scan case) fold identically to
    host arrays."""
    A, y = _data(n=64)
    host = GramSolverState().update(A, y)
    dev = GramSolverState().update(jnp.asarray(A), jnp.asarray(y))
    np.testing.assert_allclose(host.gram, dev.gram, atol=1e-5)
