"""TSQR least-squares estimator + streaming normal equations: parity with
the in-memory exact solve, out-of-core paths included, plus the linalg
cost-signature contract the chooser prices from."""

import numpy as np
import pytest

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning.linear import (
    LinearMapEstimator,
    TSQRLeastSquaresEstimator,
)

rng = np.random.default_rng(3)
N, D, K = 240, 10, 3
X = rng.standard_normal((N, D)).astype(np.float32)
W_TRUE = rng.standard_normal((D, K)).astype(np.float32)
Y = (X @ W_TRUE + 0.01 * rng.standard_normal((N, K))).astype(np.float32)


def _w(model):
    return np.asarray(model.W)


@pytest.mark.parametrize("lam", [0.0, 0.5])
def test_tsqr_matches_normal_equations(lam):
    ne = LinearMapEstimator(lam=lam).fit(Dataset.of(X), Dataset.of(Y))
    ts = TSQRLeastSquaresEstimator(lam=lam).fit(Dataset.of(X), Dataset.of(Y))
    np.testing.assert_allclose(_w(ne), _w(ts), atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(ne.b).ravel(), np.asarray(ts.b).ravel(), atol=2e-5
    )
    out_ne = np.asarray(ne.trace_batch(X[:7]))
    out_ts = np.asarray(ts.trace_batch(X[:7]))
    np.testing.assert_allclose(out_ne, out_ts, atol=2e-5)


@pytest.mark.parametrize("chunk_rows", [32, 100, 240])
def test_tsqr_streaming_matches_in_memory(chunk_rows):
    lam = 0.3
    in_mem = TSQRLeastSquaresEstimator(lam=lam).fit(Dataset.of(X), Dataset.of(Y))
    streamed = TSQRLeastSquaresEstimator(lam=lam).fit(
        ChunkedDataset.from_array(X, chunk_rows), Dataset.of(Y)
    )
    np.testing.assert_allclose(_w(in_mem), _w(streamed), atol=2e-5)


def test_streaming_normal_equations_matches_in_memory():
    lam = 0.2
    in_mem = LinearMapEstimator(lam=lam).fit(Dataset.of(X), Dataset.of(Y))
    streamed = LinearMapEstimator(lam=lam).fit(
        ChunkedDataset.from_array(X, 64), Dataset.of(Y)
    )
    np.testing.assert_allclose(_w(in_mem), _w(streamed), atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(in_mem.feature_mean), np.asarray(streamed.feature_mean),
        atol=1e-6,
    )


def test_streaming_row_count_mismatch_raises():
    with pytest.raises(ValueError, match="rows"):
        LinearMapEstimator().fit(
            ChunkedDataset.from_array(X, 64), Dataset.of(Y[:-5])
        )
    with pytest.raises(ValueError, match="rows"):
        TSQRLeastSquaresEstimator().fit(
            ChunkedDataset.from_array(X, 64), Dataset.of(Y[:-5])
        )


def test_tsqr_handles_ill_conditioning_better_than_gram():
    """The reason TSQR is in the option set: on a nearly collinear design
    the Gram route squares the condition number (f32 Cholesky degrades or
    fails); the QR route keeps it. Residuals tell the story."""
    base = rng.standard_normal((400, 1)).astype(np.float32)
    # columns nearly identical: condition number ~1e4 (squares to 1e8 —
    # at the edge of f32 for the Gram route)
    A = np.concatenate([base + 1e-4 * rng.standard_normal((400, 6)).astype(np.float32)
                        for _ in range(1)] + [base], axis=1).astype(np.float32)
    w = rng.standard_normal((A.shape[1], 1)).astype(np.float32)
    y = A @ w
    ts = TSQRLeastSquaresEstimator(lam=0.0).fit(Dataset.of(A), Dataset.of(y))
    pred = np.asarray(ts.trace_batch(A))
    resid_ts = float(np.linalg.norm(pred - y) / np.linalg.norm(y))
    assert np.isfinite(pred).all()
    assert resid_ts < 1e-2


# -- cost signatures --------------------------------------------------------


def test_cost_signatures_shapes_and_monotonicity():
    from keystone_tpu.linalg.bcd import cost_signature as bcd_sig
    from keystone_tpu.linalg.normal_equations import cost_signature as ne_sig
    from keystone_tpu.linalg.tsqr import cost_signature as tsqr_sig

    for sig in (
        ne_sig(1000, 64, 8),
        bcd_sig(1000, 64, 8, 256, 3),
        tsqr_sig(1000, 64, 8),
    ):
        assert set(sig) == {"flops", "bytes", "network", "passes"}
        assert all(v > 0 for v in sig.values())
    # scaling n scales the data terms linearly
    assert ne_sig(2000, 64, 8)["flops"] == 2 * ne_sig(1000, 64, 8)["flops"]
    # TSQR pays ~2x the Gram flops at the same shape (the analytic reason
    # it is not the cold default)
    assert tsqr_sig(10_000, 64, 8)["flops"] > ne_sig(10_000, 64, 8)["flops"]
    # more machines shrink per-device work
    assert (
        ne_sig(1000, 64, 8, machines=8)["flops"]
        < ne_sig(1000, 64, 8, machines=1)["flops"]
    )


def test_estimator_cost_methods_delegate_to_signatures():
    from keystone_tpu.nodes.learning.cost import combine_cost
    from keystone_tpu.linalg.normal_equations import cost_signature as ne_sig
    from keystone_tpu.linalg.tsqr import cost_signature as tsqr_sig

    args = (5000, 128, 16, 1.0, 8)
    weights = (3.8e-4, 2.9e-1, 1.32)
    assert LinearMapEstimator().cost(*args, *weights) == pytest.approx(
        combine_cost(ne_sig(5000, 128, 16, 8), *weights)
    )
    assert TSQRLeastSquaresEstimator().cost(*args, *weights) == pytest.approx(
        combine_cost(tsqr_sig(5000, 128, 16, 8), *weights)
    )
