"""Solver selection: synthetic shapes where each solver provably wins,
streaming restriction on chunked inputs, and evidence flipping a
borderline case — the cost model's decision surface, pinned."""

import numpy as np
import pytest

import keystone_tpu.cost as cost
from keystone_tpu.cost import CostEstimator, ProfileStore, ShapeSignature
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LeastSquaresEstimator
from keystone_tpu.nodes.learning.lbfgs import DenseLBFGSwithL2
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
    TSQRLeastSquaresEstimator,
)

TALL_SKINNY = ShapeSignature(n=200_000, d=64, k=8, machines=8)
WIDE = ShapeSignature(n=100_000, d=16_384, k=8, machines=8)


def test_tall_skinny_picks_exact_gram_family():
    """n >> d: the one-pass exact solve (Gram/TSQR family) must beat the
    iterative solvers — BCD pays 3 passes, LBFGS 20."""
    auto = LeastSquaresEstimator(lam=1e-2)
    choice = auto.choose_solver(TALL_SKINNY)
    assert choice.label in ("LinearMapEstimator", "TSQRLeastSquaresEstimator")
    assert choice.source == "cold"
    # and the family ordering is strict: both exact solvers beat both
    # iterative ones in analytic units
    units = {lbl: row["units"] for lbl, row in choice.costs.items()}
    assert max(
        units["LinearMapEstimator"], units["TSQRLeastSquaresEstimator"]
    ) < min(units["BlockLeastSquaresEstimator"], units["DenseLBFGSwithL2"])


def test_wide_picks_bcd():
    """d in the tens of thousands: the d×d Gram route explodes while BCD
    touches one (block, k) slab per step."""
    auto = LeastSquaresEstimator(lam=1e-2)
    choice = auto.choose_solver(WIDE)
    assert choice.label == "BlockLeastSquaresEstimator"
    units = {lbl: row["units"] for lbl, row in choice.costs.items()}
    assert units["BlockLeastSquaresEstimator"] < units["LinearMapEstimator"]
    assert units["BlockLeastSquaresEstimator"] < units["TSQRLeastSquaresEstimator"]


def test_chunked_input_restricts_to_streaming_solvers():
    """Out-of-core inputs must never pick a solver that materializes the
    design matrix (the LBFGS pair)."""
    auto = LeastSquaresEstimator(lam=1e-2)
    for shape in (TALL_SKINNY, WIDE, ShapeSignature(n=4096, d=128, k=2)):
        chunked = ShapeSignature(
            n=shape.n, d=shape.d, k=shape.k, chunked=True, machines=shape.machines
        )
        choice = auto.choose_solver(chunked)
        assert getattr(choice.chosen, "supports_streaming", False), choice.label
        # the LBFGS options were priced out, not silently dropped
        assert choice.costs["DenseLBFGSwithL2"]["units"] == float("inf")


def test_streaming_flags():
    assert LinearMapEstimator().supports_streaming
    assert TSQRLeastSquaresEstimator().supports_streaming
    assert BlockLeastSquaresEstimator(256, 1).supports_streaming
    assert not DenseLBFGSwithL2().supports_streaming


def test_cold_choice_matches_analytic_argmin():
    """Without evidence the chooser must reproduce the reference's
    argmin-over-cost exactly (backward compatibility bar)."""
    auto = LeastSquaresEstimator(lam=1e-2)
    for shape in (TALL_SKINNY, WIDE, ShapeSignature(n=512, d=16, k=4, machines=8)):
        expected = min(
            auto.options,
            key=lambda s: s.cost(
                shape.n, shape.d, shape.k, shape.sparsity, shape.machines,
                auto.cpu_weight, auto.mem_weight, auto.network_weight,
            ),
        )
        assert type(auto.choose_solver(shape).chosen) is type(expected)


# -- evidence ---------------------------------------------------------------


def _seed_spu(store, cls_name, spu):
    store.store(f"op/{cls_name}", {"spu": spu, "solver_observations": 3})


def test_seeded_profiles_flip_borderline_case(tmp_path):
    """Tall-skinny is borderline between the Gram and TSQR exact solves
    (~1.2× apart in units). Seeded evidence that the Gram route runs slow
    per unit (conditioning retries, say) must flip the pick to TSQR —
    while the un-evidenced iterative solvers stay un-picked."""
    cost.configure(str(tmp_path))
    store = cost.get_store()
    auto = LeastSquaresEstimator(lam=1e-2)
    assert auto.choose_solver(TALL_SKINNY).label == "LinearMapEstimator"
    _seed_spu(store, "LinearMapEstimator", 5e-6)
    _seed_spu(store, "TSQRLeastSquaresEstimator", 1e-6)
    choice = auto.choose_solver(TALL_SKINNY)
    assert choice.source == "learned"
    assert choice.label == "TSQRLeastSquaresEstimator"
    # predicted seconds exist once evidence is in play
    assert choice.est_seconds is not None and choice.est_seconds > 0


def test_evidence_confirming_the_pick_keeps_it(tmp_path):
    """One observed run of the chosen solver alone (the natural loop:
    only the winner gets observed) must NOT flip the choice: unknown
    classes borrow the known spu scale, preserving the analytic order."""
    cost.configure(str(tmp_path))
    store = cost.get_store()
    _seed_spu(store, "LinearMapEstimator", 2e-6)
    choice = LeastSquaresEstimator(lam=1e-2).choose_solver(TALL_SKINNY)
    assert choice.label == "LinearMapEstimator"
    assert choice.source == "learned"


def test_solver_costs_fallback_spu_geometric_mean(tmp_path):
    store = ProfileStore(str(tmp_path))
    _seed_spu(store, "LinearMapEstimator", 1e-6)
    _seed_spu(store, "BlockLeastSquaresEstimator", 4e-6)
    est = CostEstimator(store)
    costs = est.solver_costs(
        LeastSquaresEstimator(lam=1e-2).options, TALL_SKINNY,
        3.8e-4, 2.9e-1, 1.32,
    )
    # unknown classes price at the geometric mean of known spus (2e-6)
    row = costs["DenseLBFGSwithL2"]
    assert not row["learned"]
    assert row["seconds"] == pytest.approx(row["units"] * 2e-6, rel=1e-6)


# -- graph-level integration ------------------------------------------------


# -- KRR / weighted families (ROADMAP PR-8 follow-on) -----------------------


def test_weighted_family_cold_matches_argmin():
    """The weighted front door must reproduce the analytic argmin over
    its three physical solvers when no evidence exists."""
    from keystone_tpu.nodes.learning import WeightedLeastSquaresEstimator

    auto = WeightedLeastSquaresEstimator(
        block_size=128, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    for shape in (
        ShapeSignature(n=50_000, d=512, k=64),
        ShapeSignature(n=2_000, d=4_096, k=50),
        ShapeSignature(n=200_000, d=256, k=100),
    ):
        expected = min(
            auto.options,
            key=lambda s: s.cost(
                shape.n, shape.d, shape.k, shape.sparsity, shape.machines,
                auto.cpu_weight, auto.mem_weight, auto.network_weight,
            ),
        )
        choice = auto.choose_solver(shape)
        assert choice.source == "cold"
        assert type(choice.chosen) is type(expected)


def test_weighted_chunked_restricts_to_streaming_block_solver():
    """Out-of-core weighted fits can only take the block solver — it is
    the family's one streaming member."""
    from keystone_tpu.nodes.learning import WeightedLeastSquaresEstimator

    auto = WeightedLeastSquaresEstimator(
        block_size=128, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    choice = auto.choose_solver(
        ShapeSignature(n=500_000, d=512, k=64, chunked=True)
    )
    assert choice.label == "BlockWeightedLeastSquaresEstimator"
    assert choice.costs["PerClassWeightedLeastSquaresEstimator"]["units"] == (
        float("inf")
    )


def test_seeded_profiles_flip_weighted_borderline(tmp_path):
    """n=200k, d=256, k=100 is borderline between the block solver and
    the per-class oracle (~1.3x apart in units). Seeded evidence that the
    block solver runs slow per unit must flip the pick."""
    from keystone_tpu.nodes.learning import WeightedLeastSquaresEstimator

    cost.configure(str(tmp_path))
    store = cost.get_store()
    shape = ShapeSignature(n=200_000, d=256, k=100)
    auto = WeightedLeastSquaresEstimator(
        block_size=128, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    assert auto.choose_solver(shape).label == (
        "BlockWeightedLeastSquaresEstimator"
    )
    _seed_spu(store, "BlockWeightedLeastSquaresEstimator", 5e-6)
    _seed_spu(store, "PerClassWeightedLeastSquaresEstimator", 1e-6)
    choice = auto.choose_solver(shape)
    assert choice.source == "learned"
    assert choice.label == "PerClassWeightedLeastSquaresEstimator"


def test_seeded_profiles_flip_krr_borderline(tmp_path):
    """n=8000 sits near the crossover between the exact full-kernel
    Cholesky and the epoch-bounded Gauss-Seidel sweeps (~1.2x apart).
    Evidence that the iterative solver underperforms its analytic units
    must flip the pick to the exact solve."""
    from keystone_tpu.nodes.learning import KernelRidgeEstimator

    cost.configure(str(tmp_path))
    store = cost.get_store()
    shape = ShapeSignature(n=8_000, d=128, k=10)
    auto = KernelRidgeEstimator(
        gamma=1e-3, lam=1e-2, block_size=512, num_epochs=5
    )
    assert auto.choose_solver(shape).label == "KernelRidgeRegression"
    _seed_spu(store, "KernelRidgeRegression", 4e-6)
    _seed_spu(store, "ExactKernelRidge", 1e-6)
    choice = auto.choose_solver(shape)
    assert choice.source == "learned"
    assert choice.label == "ExactKernelRidge"
    # the crossover shape itself is otherwise untouched: small n still
    # takes the exact solve cold
    assert auto.choose_solver(
        ShapeSignature(n=2_000, d=128, k=10)
    ).label == "ExactKernelRidge"


def test_krr_exact_and_gauss_seidel_agree():
    """The two KRR physical solvers are interchangeable: on a small
    well-conditioned problem their fitted mappers predict alike (the
    iterative solver to its convergence tolerance, not bit-exact)."""
    from keystone_tpu.nodes.learning import (
        ExactKernelRidge,
        KernelRidgeRegression,
    )

    rng = np.random.default_rng(5)
    X = rng.standard_normal((96, 6)).astype(np.float32)
    W = rng.standard_normal((6, 2)).astype(np.float32)
    Y = (X @ W + 0.01 * rng.standard_normal((96, 2))).astype(np.float32)
    args = dict(gamma=0.05, lam=0.5, block_size=32)
    exact = ExactKernelRidge(**args).fit(Dataset.of(X), Dataset.of(Y))
    gs = KernelRidgeRegression(num_epochs=60, **args).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    import jax.numpy as jnp

    x = jnp.asarray(X[:16])
    np.testing.assert_allclose(
        np.asarray(exact.apply_batch(Dataset.of(x)).to_array()),
        np.asarray(gs.apply_batch(Dataset.of(x)).to_array()),
        atol=1e-2,
    )


def test_rule_swaps_streaming_solver_for_chunked_leaf():
    """NodeOptimizationRule must detect the chunked leaf and hand the
    chooser a chunked shape, so the swapped-in solver can stream."""
    from keystone_tpu.workflow.executor import GraphExecutor

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 16)).astype(np.float32)
    Y = rng.standard_normal((256, 4)).astype(np.float32)
    auto = LeastSquaresEstimator(lam=1e-2)
    pipe = auto.with_data(ChunkedDataset.from_array(X, 64), Dataset.of(Y))
    optimized = GraphExecutor(pipe.graph).graph  # triggers the rule stack
    swapped = [
        optimized.get_operator(n)
        for n in optimized.nodes
        if isinstance(
            optimized.get_operator(n),
            (LinearMapEstimator, TSQRLeastSquaresEstimator,
             BlockLeastSquaresEstimator, DenseLBFGSwithL2),
        )
    ]
    assert swapped, "auto-solver was not swapped"
    assert all(op.supports_streaming for op in swapped)
