"""ProfileStore contract: round-trip, corruption tolerance, concurrent
multi-process updates, environment isolation — the same discipline bar as
the AOT executable cache (tests/compile/test_cache.py)."""

import json
import os
import subprocess
import sys

import pytest

from keystone_tpu.cost.store import ProfileStore, profile_environment


@pytest.fixture()
def store(tmp_path):
    return ProfileStore(str(tmp_path))


def test_round_trip(store):
    rec = {"spu": 1.25, "seconds_per_item": 3e-6, "solver_observations": 2}
    store.store("op/LinearMapEstimator", rec)
    assert store.load("op/LinearMapEstimator") == rec
    assert store.keys() == ["op/LinearMapEstimator"]


def test_miss_returns_none(store):
    assert store.load("op/Nothing") is None


def test_update_read_modify_write(store):
    store.update("op/X", lambda r: {"n": 1} if r is None else {"n": r["n"] + 1})
    store.update("op/X", lambda r: {"n": 1} if r is None else {"n": r["n"] + 1})
    assert store.load("op/X") == {"n": 2}


def test_overwrite_replaces(store):
    store.store("op/X", {"v": 1})
    store.store("op/X", {"v": 2})
    assert store.load("op/X") == {"v": 2}


def test_distinct_keys_distinct_files(store):
    store.store("op/A", {"v": 1})
    store.store("plan/A", {"v": 2})
    assert store.load("op/A") == {"v": 1}
    assert store.load("plan/A") == {"v": 2}


def test_invalid_key_rejected(store):
    with pytest.raises(ValueError):
        store.path("")


# -- corruption tolerance ---------------------------------------------------


def _path_of(store, key):
    store.store(key, {"v": 1})
    return store.path(key)


def test_truncated_file_degrades_to_miss(store):
    path = _path_of(store, "op/T")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    assert store.load("op/T") is None
    assert not os.path.exists(path)  # corrupt entries are discarded


def test_garbage_file_degrades_to_miss(store):
    path = _path_of(store, "op/G")
    with open(path, "wb") as f:
        f.write(b"\x00\xffnot json at all")
    assert store.load("op/G") is None


def test_checksum_mismatch_degrades_to_miss(store):
    path = _path_of(store, "op/C")
    with open(path) as f:
        doc = json.load(f)
    doc["record"]["v"] = 999  # doctor the payload, keep the old checksum
    with open(path, "w") as f:
        json.dump(doc, f)
    assert store.load("op/C") is None


def test_renamed_foreign_file_degrades_to_miss(store):
    src = _path_of(store, "op/Src")
    dst = store.path("op/Dst")
    os.replace(src, dst)  # embedded key says op/Src
    assert store.load("op/Dst") is None


def test_corrupt_then_rewrite_recovers(store):
    path = _path_of(store, "op/R")
    with open(path, "wb") as f:
        f.write(b"junk")
    assert store.load("op/R") is None
    store.store("op/R", {"v": 7})
    assert store.load("op/R") == {"v": 7}


# -- environment isolation --------------------------------------------------


def test_env_mismatch_isolated(tmp_path):
    tpu_like = ProfileStore(
        str(tmp_path), env={"backend": "tpu", "device_kind": "v5e"}
    )
    cpu_like = ProfileStore(
        str(tmp_path), env={"backend": "cpu", "device_kind": "cpu0"}
    )
    tpu_like.store("op/X", {"spu": 9.0})
    # different env digest => different file => clean miss, no clobber
    assert cpu_like.load("op/X") is None
    cpu_like.store("op/X", {"spu": 2.0})
    assert tpu_like.load("op/X") == {"spu": 9.0}
    assert cpu_like.load("op/X") == {"spu": 2.0}


def test_env_payload_validated_on_handcopied_file(tmp_path):
    a = ProfileStore(str(tmp_path), env={"backend": "tpu", "device_kind": "a"})
    b = ProfileStore(str(tmp_path), env={"backend": "cpu", "device_kind": "b"})
    a.store("op/X", {"spu": 9.0})
    # simulate an operator copying the file onto the other env's filename
    os.replace(a.path("op/X"), b.path("op/X"))
    assert b.load("op/X") is None  # payload env mismatch


def test_profile_environment_shape():
    env = profile_environment()
    assert set(env) == {"backend", "device_kind"}


# -- concurrency ------------------------------------------------------------


_WORKER = """
import sys
from keystone_tpu.cost.store import ProfileStore

store = ProfileStore(sys.argv[1], env={"backend": "cpu", "device_kind": "t"})
me = sys.argv[2]
for i in range(40):
    store.update(
        "op/Shared",
        lambda r: {
            "count": (0 if r is None else r.get("count", 0)) + 1,
            "last": me,
        },
    )
    store.store(f"op/Only{me}", {"i": i})
print("done", me)
"""


def test_two_process_concurrent_update(tmp_path):
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(tmp_path), name],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for name in ("A", "B")
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()
    store = ProfileStore(str(tmp_path), env={"backend": "cpu", "device_kind": "t"})
    # the shared record survived the interleaving intact (atomic replace:
    # last-writer-wins per write, never a torn file)...
    shared = store.load("op/Shared")
    assert shared is not None
    assert shared["last"] in ("A", "B")
    assert 1 <= shared["count"] <= 80
    # ...and each process's private records are fully present
    assert store.load("op/OnlyA") == {"i": 39}
    assert store.load("op/OnlyB") == {"i": 39}
    # no stray temp files left behind
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp")]
