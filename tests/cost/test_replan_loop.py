"""The closed loop end-to-end: a traced fit writes evidence, the second
fit of the same pipeline plans from it with ZERO sampling executions and
reproduces the model; the audit covers solver nodes; per-node calibration
ratios correct the sampled extrapolation."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import keystone_tpu.cost as cost
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LeastSquaresEstimator
from keystone_tpu.obs import tracer as tracer_mod
from keystone_tpu.workflow.autocache import profile_nodes
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.optimizers import AutoCachingOptimizer
from keystone_tpu.workflow.transformer import FunctionNode, Transformer

rng = np.random.default_rng(7)
X = rng.standard_normal((384, 12)).astype(np.float32)
Y = rng.standard_normal((384, 3)).astype(np.float32)
R = rng.standard_normal((12, 12)).astype(np.float32)


def _build_pipeline():
    # fresh instances per run: identity-keyed prefixes must not let the
    # fit-once state table short-circuit the second fit
    feat = FunctionNode(batch_fn=lambda A: jnp.tanh(jnp.asarray(A) @ R),
                        label="feat")
    auto = LeastSquaresEstimator(lam=1e-2)
    return feat.and_then(auto, Dataset.of(X), Dataset.of(Y))


def _fit_and_apply():
    cost.reset_sampling()
    fitted = _build_pipeline().fit()
    out = np.asarray(fitted.apply(Dataset.of(X[:16])).to_array())
    return out, cost.sampling_executions()["total"]


def test_second_fit_plans_from_evidence_with_zero_sampling(tmp_path):
    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    out1, sampled1 = _fit_and_apply()
    assert sampled1 > 0  # the cold run pays sampling
    out2, sampled2 = _fit_and_apply()
    assert sampled2 == 0  # the warm run plans entirely from the store
    np.testing.assert_allclose(out1, out2, atol=1e-6)
    keys = cost.get_store().keys()
    assert any(k.startswith("op/") for k in keys)
    assert any(k.startswith("solver/") for k in keys)
    assert any(k.startswith("plan/") for k in keys)


def test_plan_record_carries_observed_costs_and_ratios(tmp_path):
    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    _fit_and_apply()
    store = cost.get_store()
    plan_keys = [
        k for k in store.keys()
        if k.startswith("plan/") and not k.startswith("plan/segment/")
    ]
    # one evidence plan for the fit graph, plus sampled plans for any
    # prefix subgraph optimized at pipeline construction (plan/segment/
    # records carry segment compile-vs-run evidence, a different shape)
    assert plan_keys
    recs = [store.load(k) for k in plan_keys]
    rows = [r for rec in recs for r in rec["nodes"].values()]
    assert rows and all("label" in r and "seconds" in r for r in rows)
    observed = [r for r in rows if r["observed"]]
    assert observed, "no node observation made it into the plan records"
    # the per-node measured sample-to-full ratio is recorded where both
    # an estimate and an observation exist
    assert any(
        isinstance(r.get("ratio"), float) and r["ratio"] > 0 for r in observed
    )
    solver_keys = [k for k in store.keys() if k.startswith("solver/")]
    rec = store.load(solver_keys[0])
    assert rec["chosen"] in (
        "LinearMapEstimator", "TSQRLeastSquaresEstimator",
        "BlockLeastSquaresEstimator", "DenseLBFGSwithL2",
    )
    assert rec["shape"]["d"] == 12 and rec["shape"]["k"] == 3


def test_traced_fit_emits_cost_spans_and_solver_audit(tmp_path):
    from keystone_tpu.obs.audit import cache_audit

    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        _fit_and_apply()
        names = [sp.name for sp in tracer.spans()]
        assert "cost.estimate" in names
        assert "cost.replan" in names
        rows = cache_audit(tracer)
        solver_rows = [r for r in rows if r["kind"] == "solver"]
        assert len(solver_rows) == 1
        (row,) = solver_rows
        assert row["solver"] == row["label"]
        assert row["observed"] and row["obs_seconds"] > 0
        assert row["alternatives"] and len(row["alternatives"]) == 5
    finally:
        tracer_mod.reset()


def test_second_traced_fit_predicts_solver_seconds(tmp_path):
    """Run 2 prices the solver from evidence: the audit row carries a
    real estimate-vs-observed ratio for the solver node."""
    from keystone_tpu.obs.audit import cache_audit

    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    _fit_and_apply()
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        _fit_and_apply()
        (row,) = [r for r in cache_audit(tracer) if r["kind"] == "solver"]
        assert row["source"] == "learned"
        assert row["solver_est_seconds"] is not None
        assert row["solver_seconds_ratio"] is not None
    finally:
        tracer_mod.reset()


def test_changed_pipeline_falls_back_to_sampling(tmp_path):
    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    _fit_and_apply()
    cost.reset_sampling()
    # a structurally different pipeline: extra featurizer stage
    extra = FunctionNode(batch_fn=lambda A: jnp.asarray(A) * 2.0, label="x2")
    feat = FunctionNode(batch_fn=lambda A: jnp.tanh(jnp.asarray(A) @ R),
                        label="feat")
    auto = LeastSquaresEstimator(lam=1e-2)
    (extra.and_then(feat).and_then(auto, Dataset.of(X), Dataset.of(Y))).fit()
    assert cost.sampling_executions()["total"] > 0


def test_default_optimizer_solver_record_skips_sampling(tmp_path):
    """Even without the autocache batch (DefaultOptimizer), the solver
    shape record alone removes run 2's NodeOptimizationRule sampling."""
    cost.configure(str(tmp_path))
    _, sampled1 = _fit_and_apply()
    assert sampled1 > 0
    _, sampled2 = _fit_and_apply()
    assert sampled2 == 0


def test_no_store_means_no_files_and_unchanged_behavior(tmp_path):
    assert cost.get_store() is None
    out1, sampled1 = _fit_and_apply()
    assert sampled1 > 0
    out2, sampled2 = _fit_and_apply()
    assert sampled2 > 0  # nothing persists without a store
    np.testing.assert_allclose(out1, out2, atol=1e-6)


# -- per-node calibration of the sampled extrapolation ----------------------


class _Sleepy(Transformer):
    def apply_batch(self, data):
        time.sleep(0.01)
        return Dataset.of(data)

    def apply(self, x):
        return x


def _sleepy_graph():
    g = Graph()
    g, leaf = g.add_node(
        DatasetOperator(Dataset.of(np.ones((32, 4), np.float32))), []
    )
    g, t = g.add_node(_Sleepy(), [leaf])
    g, sink = g.add_sink(t)
    return g, t


def test_calibration_scales_one_nodes_estimate():
    g, t = _sleepy_graph()
    base = profile_nodes(g, full_size=32)
    scaled = profile_nodes(g, full_size=32, calibration={t: 8.0})
    # the sleepy node's wall time is ~10ms per pull, stable enough that an
    # 8x calibrated estimate clears 3x the uncalibrated one despite noise
    assert scaled[t].ns > 3.0 * base[t].ns


def test_calibration_ratio_is_clamped():
    g, t = _sleepy_graph()
    lo = profile_nodes(g, full_size=32, calibration={t: 1e-12})
    base = profile_nodes(g, full_size=32)
    # 1/64 clamp: a corrupt near-zero ratio cannot erase a node's cost
    assert lo[t].ns > base[t].ns / 200.0


def test_observed_by_node_windows_out_prior_fits():
    """A long-lived process tracer holds every fit's spans and NodeIds are
    small per-graph ints — the finalize join must see only the current
    fit's window (replan.PendingPlan.span_watermark), or a second fit of
    the same pipeline folds doubled seconds into the stored evidence."""
    from keystone_tpu.obs.audit import observed_by_node

    tracer = tracer_mod.Tracer()
    with tracer.span("node", node_id="3", op_type="Op"):
        time.sleep(0.01)
    watermark = len(tracer.spans())
    with tracer.span("node", node_id="3", op_type="Op"):
        time.sleep(0.01)

    merged = observed_by_node(tracer)
    windowed = observed_by_node(tracer, start=watermark)
    assert merged["3"]["computes"] == 2
    assert windowed["3"]["computes"] == 1
    assert windowed["3"]["seconds"] < merged["3"]["seconds"]


def test_repeat_traced_fits_do_not_accumulate_observed_seconds(tmp_path):
    """Two fits of one pipeline under ONE global tracer: the plan record
    after fit 2 must hold fit-2-window seconds, not fit1+fit2 sums.

    The assertion is a wall-clock ratio over ~10ms of measured work on
    shared vCPUs, so one OS-scheduling hiccup in fit 2 can breach the
    margin without any accumulation bug; a genuine unwindowed join fails
    EVERY attempt (it deterministically sums both fits' spans), so the
    scenario retries in a fresh store before failing."""

    def attempt(store_dir):
        cost.configure(str(store_dir))
        PipelineEnv.get_or_create().reset()
        PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
        tracer = tracer_mod.install(tracer_mod.Tracer())
        try:
            _fit_and_apply()
            fp = [
                k for k in cost.get_store().keys() if k.startswith("plan/")
            ][0]
            rec1 = cost.get_store().load(fp)
            PipelineEnv.get_or_create().reset()
            PipelineEnv.get_or_create().set_optimizer(
                AutoCachingOptimizer()
            )
            _fit_and_apply()
            rec2 = cost.get_store().load(fp)
        finally:
            tracer_mod.stop()
        s1 = sum(r["seconds"] for r in rec1["nodes"].values())
        s2 = sum(r["seconds"] for r in rec2["nodes"].values())
        return s1, s2

    # fit 2 is evidence-planned (no sampling) so it can be faster, but an
    # unwindowed join would sum both fits' spans: >= ~2x fit 1's seconds
    for trial in range(3):
        s1, s2 = attempt(tmp_path / f"store{trial}")
        if s2 < 1.5 * s1:
            return
    assert s2 < 1.5 * s1, (s1, s2)


def test_estimate_rows_do_not_inherit_stale_extras_across_passes():
    """NodeIds are per-graph small ints: after a new optimizer pass (new
    epoch), a colliding id's row must be replaced wholesale — a plain node
    in pipeline B must not inherit pipeline A's solver extras in the
    audit. Within one pass, extras still merge (chooser records kind=
    "solver" first, the cache planner re-records base fields after)."""
    tracer = tracer_mod.Tracer()
    tracer.begin_plan_epoch()
    tracer.record_node_estimate(
        "3", "auto-solver", kind="solver", solver="TSQRLeastSquares",
    )
    tracer.record_node_estimate("3", "auto-solver", est_seconds=0.5)
    row = tracer.estimates["3"]
    assert row["kind"] == "solver" and row["est_seconds"] == 0.5

    tracer.begin_plan_epoch()
    tracer.record_node_estimate("3", "plain-feat", est_seconds=0.1)
    row = tracer.estimates["3"]
    assert row["label"] == "plain-feat"
    assert "kind" not in row and "solver" not in row
    assert "_epoch" not in row
