"""Static-vs-dynamic agreement over the example pipeline families
(keystone_tpu/pipelines/): for every fitted chain,

* the static untraceable set equals EXACTLY the labels
  ``NotTraceableError`` reports (zero false positives/negatives), and a
  clean verdict means ``compile(strict=True)`` actually builds;
* the static export verdict agrees with ``jax.export`` reality:
  statically-exportable chains export, statically-flagged ones
  (host callbacks) refuse.

Cheap fits are real fits at tiny configs; the expensive image families
(VOC SIFT-Fisher, ImageNet SIFT+LCS, RandomPatchCifar) are exercised as
fitted transformer chains built from their real node classes with random
parameters — the verdict is a property of the NODE SET, and this keeps
the agreement sweep off the multi-minute e2e fit paths their own tests
already cover.
"""

import numpy as np
import pytest

from keystone_tpu.workflow.pipeline import NotTraceableError


def _fit_mnist():
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer,
        synthetic_mnist,
    )

    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=100.0)
    train, _ = synthetic_mnist(n_train=128, n_test=16)
    labels = ClassLabelIndicators(10).apply_batch(train.labels)
    pipe = (
        build_featurizer(conf)
        .and_then(
            BlockLeastSquaresEstimator(512, 1, 100.0), train.data, labels
        )
        .and_then(MaxClassifier())
    )
    return pipe.fit(), (784,), "float32"


def _fit_timit():
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.timit import TimitConfig, build_featurizer

    conf = TimitConfig(num_cosines=2, num_classes=5)
    rng = np.random.RandomState(0)
    X = rng.randn(96, 440).astype(np.float32)
    y = ClassLabelIndicators(5).apply_batch(
        rng.randint(0, 5, size=96).astype(np.int32)
    )
    pipe = (
        build_featurizer(conf)
        .and_then(BlockLeastSquaresEstimator(1024, 1, 1.0), X, y)
        .and_then(MaxClassifier())
    )
    return pipe.fit(), (440,), "float32"


def _fit_linear_pixels():
    from keystone_tpu.nodes.images.core import GrayScaler, ImageVectorizer
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier

    rng = np.random.RandomState(0)
    imgs = rng.rand(48, 8, 8, 3).astype(np.float32)
    y = ClassLabelIndicators(4).apply_batch(
        rng.randint(0, 4, size=48).astype(np.int32)
    )
    pipe = (
        GrayScaler()
        .and_then(ImageVectorizer())
        .and_then(LinearMapEstimator(1.0), imgs, y)
        .and_then(MaxClassifier())
    )
    return pipe.fit(), (8, 8, 3), "float32"


def _chain_random_patch_cifar():
    from keystone_tpu.nodes.images.core import (
        Convolver,
        ImageVectorizer,
        Pooler,
        SymmetricRectifier,
    )
    from keystone_tpu.nodes.learning.linear import BlockLinearMapper
    from keystone_tpu.nodes.stats import StandardScalerModel
    from keystone_tpu.nodes.util import MaxClassifier

    rng = np.random.RandomState(0)
    filters = rng.randn(4, 6 * 6 * 3).astype(np.float32)
    conv = Convolver(filters, 16, 16, 3, normalize_patches=True)
    feat_dim = 4 * 6 * 6  # pooled map → vectorized; exact value probed
    pipe = (
        conv
        .and_then(SymmetricRectifier(alpha=0.25))
        .and_then(Pooler(6, 6, None, "sum"))
        .and_then(ImageVectorizer())
        .to_pipeline()
    )
    probe = pipe.fit()
    rep = probe.check(datum_spec=((16, 16, 3), "float32"), span=False)
    d = int(rep.sink_spec.item_shape[0])
    full = probe.to_pipeline().and_then(
        StandardScalerModel(np.zeros(d, np.float32))
    ).and_then(
        BlockLinearMapper(
            [rng.randn(d, 4).astype(np.float32)], d,
            b=np.zeros(4, np.float32),
        )
    ).and_then(MaxClassifier())
    return full.fit(), (16, 16, 3), "float32"


def _chain_voc_sift_fisher():
    from keystone_tpu.nodes.images.core import GrayScaler, PixelScaler
    from keystone_tpu.nodes.images.fisher_vector import FisherVector
    from keystone_tpu.nodes.images.sift import SIFTExtractor
    from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel
    from keystone_tpu.nodes.learning.linear import BlockLinearMapper
    from keystone_tpu.nodes.learning.pca import BatchPCATransformer
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.nodes.util import Cacher
    from keystone_tpu.nodes.util.core import MatrixVectorizer

    rng = np.random.RandomState(0)
    k, pdim = 3, 8
    gmm = GaussianMixtureModel(
        rng.rand(pdim, k).astype(np.float32),
        rng.rand(pdim, k).astype(np.float32) + 0.5,
        np.full(k, 1.0 / k, np.float32),
    )
    fv_dim = 2 * pdim * k
    pipe = (
        PixelScaler()
        .and_then(GrayScaler())
        .and_then(Cacher())
        .and_then(SIFTExtractor(step=8, num_scales=1))
        .and_then(BatchPCATransformer(
            rng.randn(128, pdim).astype(np.float32)  # (d, dims)
        ))
        .and_then(FisherVector(gmm))
        .and_then(MatrixVectorizer())
        .and_then(NormalizeRows())
        .and_then(SignedHellingerMapper())
        .and_then(NormalizeRows())
        .and_then(BlockLinearMapper(
            [rng.randn(fv_dim, 2).astype(np.float32)], fv_dim,
            b=np.zeros(2, np.float32),
        ))
        .to_pipeline()
    )
    return pipe.fit(), (32, 32, 3), "float32"


def _chain_imagenet_sift_lcs():
    from keystone_tpu.nodes.images.core import GrayScaler, PixelScaler
    from keystone_tpu.nodes.images.lcs import LCSExtractor
    from keystone_tpu.nodes.images.sift import SIFTExtractor
    from keystone_tpu.nodes.learning.pca import BatchPCATransformer
    from keystone_tpu.nodes.util.core import MatrixVectorizer
    from keystone_tpu.workflow.pipeline import Pipeline
    from keystone_tpu.nodes.util import VectorCombiner

    rng = np.random.RandomState(0)
    sift = (
        PixelScaler()
        .and_then(GrayScaler())
        .and_then(SIFTExtractor(step=8, num_scales=1))
        .and_then(BatchPCATransformer(
            rng.randn(128, 8).astype(np.float32)  # (d, dims)
        ))
        .and_then(MatrixVectorizer())
    )
    lcs = (
        PixelScaler()
        .and_then(LCSExtractor(4, 4, 2))
        .and_then(MatrixVectorizer())
    )
    pipe = Pipeline.gather([sift, lcs]).and_then(VectorCombiner())
    return pipe.fit(), (32, 32, 3), "float32"


def _fit_newsgroups():
    from keystone_tpu.pipelines.newsgroups import (
        NewsgroupsConfig,
        build_predictor,
        synthetic_newsgroups,
    )

    train = synthetic_newsgroups(64, num_classes=3, seed=1)
    conf = NewsgroupsConfig(n_grams=1, common_features=300, num_classes=3)
    pipe = build_predictor(train.data, train.labels, conf)
    return pipe.fit(), None, None


def _fit_amazon():
    from keystone_tpu.pipelines.amazon_reviews import (
        AmazonReviewsConfig,
        build_predictor,
        synthetic_reviews,
    )

    train = synthetic_reviews(64, seed=1)
    conf = AmazonReviewsConfig(n_grams=1, common_features=300, num_iters=2)
    pipe = build_predictor(train.data, train.labels, conf)
    return pipe.fit(), None, None


def _fit_stupid_backoff():
    from keystone_tpu.pipelines.stupid_backoff_pipeline import (
        synthetic_corpus,
        train_language_model,
    )

    model = train_language_model(synthetic_corpus(40, seed=0), n=2)
    return model.to_pipeline().fit(), None, None


def _fit_stall_callback():
    from keystone_tpu.cluster.demo import build_stall_model

    return build_stall_model(d=16, k=4, stall_s=0.0), (16,), "float32"


FAMILIES = {
    "MnistRandomFFT": _fit_mnist,
    "TimitPipeline": _fit_timit,
    "LinearPixels": _fit_linear_pixels,
    "RandomPatchCifar": _chain_random_patch_cifar,
    "VOCSIFTFisher": _chain_voc_sift_fisher,
    "ImageNetSiftLcsFV": _chain_imagenet_sift_lcs,
    "NewsgroupsPipeline": _fit_newsgroups,
    "AmazonReviewsPipeline": _fit_amazon,
    "StupidBackoffPipeline": _fit_stupid_backoff,
    "HostCallbackServe": _fit_stall_callback,
}

#: families whose chains are expected untraceable (text/host per-item)
EXPECT_UNTRACEABLE = {
    "NewsgroupsPipeline", "AmazonReviewsPipeline", "StupidBackoffPipeline",
}
#: families that jit but must NOT export (host callbacks)
EXPECT_NO_EXPORT = {"HostCallbackServe"}


def _dynamic_untraceable(fitted):
    """Ground truth for NotTraceableError's node set, computed the way
    the pre-checker code did: trace_batch attribute presence."""
    from keystone_tpu.workflow.operators import GatherTransformerOperator

    labels = []
    for node in fitted.graph.nodes:
        op = fitted.graph.get_operator(node)
        if isinstance(op, GatherTransformerOperator):
            continue
        if getattr(op, "trace_batch", None) is None:
            labels.append(op.label)
    return labels


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_static_verdicts_agree_with_dynamic_reality(family):
    import jax

    fitted, item_shape, dtype = FAMILIES[family]()
    report = fitted.check(span=False)
    static_untraceable = report.untraceable_labels()

    # 1. exact agreement with the attribute-level ground truth
    assert sorted(static_untraceable) == sorted(
        _dynamic_untraceable(fitted)
    ), family

    if family in EXPECT_UNTRACEABLE:
        assert static_untraceable, f"{family} unexpectedly traceable"
    elif family not in EXPECT_NO_EXPORT:
        assert not static_untraceable, (
            f"{family} unexpectedly blocked: {static_untraceable}"
        )

    # 2. NotTraceableError reports EXACTLY the statically-flagged nodes
    if static_untraceable:
        with pytest.raises(NotTraceableError) as ei:
            fitted.compile(strict=True, cache=None)
        assert sorted(ei.value.labels) == sorted(static_untraceable)
        return

    # 3. a clean verdict actually jit-compiles
    assert fitted.compile(strict=True, cache=None) is not None

    # 4. export verdict agrees with jax.export reality
    if item_shape is None:
        return
    from jax import export as jax_export

    spec = jax.ShapeDtypeStruct((2, *item_shape), np.dtype(dtype))
    exported_jit = jax.jit(fitted.trace_fn())
    if report.exportable:
        jax_export.export(exported_jit)(spec)  # must not raise
    else:
        assert family in EXPECT_NO_EXPORT
        with pytest.raises(Exception):
            jax_export.export(exported_jit)(spec)
