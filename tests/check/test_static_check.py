"""Unit tests for the static pipeline checker (keystone_tpu/check/):
abstract spec propagation, the traceability lattice, segment planning,
the zero-execution guarantee, and the construction/fit-entry wiring."""

import numpy as np
import pytest

import keystone_tpu.cost as cost_mod
from keystone_tpu.check import (
    BATCH_COUPLED,
    CheckOnlyExit,
    ContractMismatchError,
    HOST_CALLBACK,
    OPAQUE,
    PipelineCheckError,
    STATEFUL,
    TRACEABLE,
    Spec,
    SpecTuple,
    check_graph,
    classify,
)
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.nodes.stats import (
    LinearRectifier,
    PaddedFFT,
    RandomSignNode,
    StandardScaler,
)
from keystone_tpu.nodes.util import (
    ClassLabelIndicators,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow.pipeline import Pipeline
from keystone_tpu.workflow.transformer import FunctionNode, Identity


def _small_pipe(d=32, k=4, n=64, est=None):
    X = np.random.RandomState(0).randn(n, d).astype(np.float32)
    y = ClassLabelIndicators(k).apply_batch(
        np.random.RandomState(1).randint(0, k, size=n)
    )
    est = est or LinearMapEstimator(lam=1.0)
    return (
        RandomSignNode.create(d, seed=0)
        .and_then(est, X, y)
        .and_then(MaxClassifier())
    )


# ---------------------------------------------------------------------------
# lattice
# ---------------------------------------------------------------------------


def test_pure_jax_node_traceable():
    assert classify(LinearRectifier(0.0)) == TRACEABLE
    assert classify(PaddedFFT()) == TRACEABLE


def test_host_node_opaque():
    from keystone_tpu.nodes.nlp.hashing import HashingTF

    assert classify(HashingTF(64)) == OPAQUE


def test_pure_callback_detected_statically():
    import functools
    import jax

    def stall(x):
        return x

    def body(X):
        return jax.pure_callback(
            functools.partial(stall),
            jax.ShapeDtypeStruct(X.shape, X.dtype), X,
        )

    assert classify(FunctionNode(batch_fn=body)) == HOST_CALLBACK


def test_callback_detected_through_closure_helper():
    import jax

    def helper(X):
        return jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct(X.shape, X.dtype), X
        )

    def body(X):
        return helper(X) * 2.0

    assert classify(FunctionNode(batch_fn=body)) == HOST_CALLBACK


def test_shared_code_object_distinct_closures_not_memo_confused():
    """Two batch_fns from one factory share a code object but close over
    different helpers — a pure-jax one and a callback-routed one. The
    classification memo must not serve one's verdict to the other."""
    import functools
    import jax

    def cb(a):
        return a

    def callback_helper(X):
        return jax.pure_callback(
            functools.partial(cb), jax.ShapeDtypeStruct(X.shape, X.dtype), X
        )

    def pure_helper(X):
        return X * 2.0

    def make(f):
        return FunctionNode(batch_fn=lambda X: f(X), label="made")

    assert classify(make(pure_helper)) == TRACEABLE
    assert classify(make(callback_helper)) == HOST_CALLBACK
    # and in the other evaluation order, from a fresh pair
    assert classify(make(callback_helper)) == HOST_CALLBACK
    assert classify(make(pure_helper)) == TRACEABLE


def test_batch_coupled_verdict_and_instance_mutation():
    class Coupled(Identity):
        batch_coupled = True

    assert classify(Coupled()) == BATCH_COUPLED
    # post-construction instance mutation is seen (tests do this)
    node = Identity()
    node.batch_coupled = True
    assert classify(node) == BATCH_COUPLED


def test_stateful_mutation_detected():
    class Sneaky(Identity):
        def trace_batch(self, X):
            self.count = getattr(self, "count", 0) + 1
            return X

    assert classify(Sneaky()) == STATEFUL


def test_explicit_verdict_pin():
    class Pinned(Identity):
        check_verdict = STATEFUL

    assert classify(Pinned()) == STATEFUL


def test_fused_chain_is_worst_of_steps():
    from keystone_tpu.workflow.fusion import FusedTransformerOperator

    fused = FusedTransformerOperator(
        [(Identity(), (0,)), (LinearRectifier(0.0), (1,))], 1
    )
    assert classify(fused) == TRACEABLE

    class Coupled(Identity):
        batch_coupled = True

    fused2 = FusedTransformerOperator(
        [(Identity(), (0,)), (Coupled(), (1,))], 1
    )
    assert classify(fused2) == BATCH_COUPLED


# ---------------------------------------------------------------------------
# abstract interpretation
# ---------------------------------------------------------------------------


def test_specs_propagate_from_array_leaf_to_sink():
    pipe = _small_pipe(d=16, k=3)
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((16,), "float32")
    )
    sink = rep.sink_spec
    assert isinstance(sink, Spec)
    assert sink.item_shape == ()  # MaxClassifier: per-item class index
    assert sink.dtype in ("int32", "int64")
    assert sink.sym  # lead dim symbolic: derived from the per-item hint


def test_gather_produces_tuple_spec_and_combiner_concats():
    branches = [
        RandomSignNode.create(8, seed=i).and_then(LinearRectifier(0.0))
        for i in range(3)
    ]
    pipe = Pipeline.gather(branches).and_then(VectorCombiner())
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((8,), "float32")
    )
    assert isinstance(rep.sink_spec, Spec)
    assert rep.sink_spec.item_shape == (3 * 8,)


def test_chunked_leaf_carries_item_spec_without_production():
    produced = []

    def chunk(i):
        produced.append(i)
        return np.zeros((16, 8), np.float32)

    ds = ChunkedDataset.from_chunk_fn(chunk, 4, 64)
    ds._item_spec = ((8,), "float32")
    pipe = Identity().and_then(LinearMapEstimator(lam=1.0), ds, np.zeros(
        (64, 2), np.float32
    ))
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((8,), "float32")
    )
    assert produced == []  # the whole check produced ZERO chunks
    assert isinstance(rep.sink_spec, Spec)
    assert rep.sink_spec.item_shape == (2,)  # labels dim via fitted_out_spec


def test_from_array_records_item_spec():
    ds = ChunkedDataset.from_array(np.zeros((100, 7), np.float32), 32)
    assert ds.item_spec == ((7,), "float32")


def test_shape_mismatch_raises_node_attributed_at_and_then():
    """The acceptance gate: a mismatched composition fails AT
    CONSTRUCTION, names the offending node, and produces zero chunks."""
    produced = []

    def chunk(i):
        produced.append(i)
        return np.zeros((16, 100), np.float32)

    ds = ChunkedDataset.from_chunk_fn(chunk, 4, 64)
    ds._item_spec = ((100,), "float32")  # pipeline expects 784
    labels = np.zeros((64, 10), np.float32)

    feat = (
        RandomSignNode.create(784, seed=0)
        .and_then(PaddedFFT())
        .and_then(LinearRectifier(0.0))
    )
    with pytest.raises(PipelineCheckError) as ei:
        feat.and_then(BlockLeastSquaresEstimator(512, 1, 1.0), ds, labels)
    assert "RandomSignNode" in str(ei.value)
    assert ei.value.node is not None
    assert produced == []  # nothing scanned before the refusal


def test_dtype_mismatch_weaker_than_shape_does_not_false_positive():
    # float64 data through a float32-param chain PROMOTES, it does not
    # error — the checker must not invent failures eval_shape allows
    X = np.random.RandomState(0).randn(32, 16).astype(np.float64)
    y = ClassLabelIndicators(3).apply_batch(
        np.random.RandomState(1).randint(0, 3, size=32)
    )
    pipe = RandomSignNode.create(16, seed=0).and_then(
        LinearMapEstimator(lam=1.0), X, y
    )
    assert pipe is not None


def test_batch_coupled_on_chunked_stream_raises():
    class Coupled(Identity):
        batch_coupled = True

        def trace_batch(self, X):
            return X - X.mean(axis=0)

    ds = ChunkedDataset.from_array(np.zeros((64, 8), np.float32), 16)
    # composition graph: Coupled consumes the chunked leaf on the
    # estimator-data path — refused AT and_then, before any scan
    with pytest.raises(PipelineCheckError, match="batch-coupled"):
        Coupled().and_then(
            LinearMapEstimator(lam=1.0), ds,
            np.zeros((64, 2), np.float32),
        )


def test_cacher_materializes_chunked_stream_for_coupled_node():
    from keystone_tpu.nodes.util import Cacher

    class Coupled(Identity):
        batch_coupled = True

        def trace_batch(self, X):
            return X - X.mean(axis=0)

    ds = ChunkedDataset.from_array(np.zeros((64, 8), np.float32), 16)
    pipe = (
        Cacher()
        .and_then(Coupled())
        .and_then(LinearMapEstimator(lam=1.0), ds, np.zeros(
            (64, 2), np.float32
        ))
    )
    check_graph(pipe.graph, source=pipe.source)  # no error


def test_out_spec_declaration_consumed():
    from keystone_tpu.nodes.util.core import MultiClassLabelIndicators

    node = MultiClassLabelIndicators(7)
    pipe = node.to_pipeline()
    rep = check_graph(pipe.graph, source=pipe.source)
    assert isinstance(rep.sink_spec, Spec)
    assert rep.sink_spec.item_shape == (7,)
    assert rep.sink_spec.dtype == "float32"


def test_vector_splitter_declares_tuple_spec():
    from keystone_tpu.nodes.util.core import VectorSplitter

    pipe = VectorSplitter(3).to_pipeline()
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((8,), "float32")
    )
    assert isinstance(rep.sink_spec, SpecTuple)
    widths = [e.item_shape[-1] for e in rep.sink_spec.elems]
    assert widths == [3, 3, 2]


def test_standard_scaler_fitted_out_spec_preserves():
    X = np.random.RandomState(0).randn(32, 12).astype(np.float32)
    pipe = Identity().and_then(StandardScaler(), X).and_then(
        MaxClassifier()
    )
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((12,), "float32")
    )
    assert isinstance(rep.sink_spec, Spec)


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------


def test_segment_plan_splits_at_cacher_and_estimator():
    from keystone_tpu.nodes.util import Cacher

    pipe = _small_pipe(d=16, k=3)
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((16,), "float32")
    )
    assert rep.segment_count >= 2  # estimator-path + serve-path segments
    assert any(r == "estimator" for r in rep.barriers.values())

    fitted = pipe.fit()
    frep = fitted.check(span=False)
    assert frep.segment_count == 1  # fitted chain: one compilable unit

    # a Cacher in the (unfused) graph splits the plan around it — the
    # raw composition graph keeps the Cacher node (the optimizer may
    # later fuse an unannotated one, which legitimately merges segments)
    capped = (
        RandomSignNode.create(16, seed=0)
        .and_then(Cacher())
        .and_then(LinearRectifier(0.0))
        .to_pipeline()
    )
    crep = check_graph(
        capped.graph, source=capped.source, datum_spec=((16,), "float32")
    )
    assert crep.segment_count == 2
    assert "cacher" in crep.barriers.values()


def test_segment_bytes_priced_from_specs():
    pipe = RandomSignNode.create(16, seed=0).and_then(
        LinearRectifier(0.0)
    ).to_pipeline()
    rep = check_graph(
        pipe.graph, source=pipe.source, datum_spec=((16,), "float32")
    )
    (seg,) = rep.segments
    # two (16,)-float32 node outputs → 64 + 64 bytes per item
    assert seg.est_item_bytes == 16 * 4 * 2


# ---------------------------------------------------------------------------
# zero-execution guarantee + wiring
# ---------------------------------------------------------------------------


def test_check_executes_zero_samples():
    cost_mod.reset_sampling()
    pipe = _small_pipe(d=16, k=3)
    pipe.check(span=False)
    pipe.fit()  # the fit MAY sample (autocache); reset and re-check
    cost_mod.reset_sampling()
    pipe.check(span=False)
    assert cost_mod.sampling_executions()["total"] == 0


def test_kill_switch_disables_implicit_checks(monkeypatch):
    monkeypatch.setenv("KEYSTONE_STATIC_CHECK", "0")
    ds = ChunkedDataset.from_chunk_fn(
        lambda i: np.zeros((16, 100), np.float32), 4, 64
    )
    ds._item_spec = ((100,), "float32")
    feat = RandomSignNode.create(784, seed=0).and_then(PaddedFFT())
    # with the switch off, the bad composition constructs (the defect
    # would surface at execution, as before this subsystem existed)
    pipe = feat.and_then(
        BlockLeastSquaresEstimator(512, 1, 1.0), ds,
        np.zeros((64, 10), np.float32),
    )
    # the EXPLICIT check still runs and still raises
    with pytest.raises(PipelineCheckError):
        pipe.check(span=False)


def test_fit_entry_raises_before_any_chunk(monkeypatch):
    produced = []

    def chunk(i):
        produced.append(i)
        return np.zeros((16, 100), np.float32)

    ds = ChunkedDataset.from_chunk_fn(chunk, 4, 64)
    # no item_spec recorded → and_then cannot prove the mismatch...
    feat = RandomSignNode.create(784, seed=0).and_then(PaddedFFT())
    pipe = feat.and_then(
        BlockLeastSquaresEstimator(512, 1, 1.0), ds,
        np.zeros((64, 10), np.float32),
    )
    # ...but once the spec IS known (say, recorded later), fit() refuses
    ds._item_spec = ((100,), "float32")
    with pytest.raises(PipelineCheckError, match="RandomSignNode"):
        pipe.fit()
    assert produced == []


def test_check_report_span_emitted():
    from keystone_tpu.obs import tracer as obs_tracer

    t = obs_tracer.Tracer()
    installed = obs_tracer.install(t)
    try:
        pipe = _small_pipe(d=16, k=3)
        pipe.check()
        spans = [s for s in t.spans() if s.name == "check.report"]
        assert spans, "no check.report span"
        sp = spans[-1]
        assert sp.attrs["segments"] >= 2
        assert sp.attrs["sampling_total"] == 0
        assert sp.attrs["nodes"] > 0
    finally:
        obs_tracer.uninstall(installed)


def test_check_only_mode_via_cli(capsys):
    from keystone_tpu.__main__ import main as cli_main

    rc = cli_main([
        "mnist", "--backend", "cpu", "--numFFTs", "1",
        "--blockSize", "256", "--lambda", "10", "--check",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "CHECK OK" in out and "0 executions" in out
    # mode must not leak to later fits in this process
    from keystone_tpu import check as check_pkg

    assert not check_pkg.check_only_mode()


# ---------------------------------------------------------------------------
# serving-contract validation
# ---------------------------------------------------------------------------


def test_swap_contract_mismatch_is_typed_with_node_attribution():
    fitted = _small_pipe(d=16, k=3).fit()
    rep = fitted.check(span=False)
    with pytest.raises(ContractMismatchError, match="does not match"):
        rep.require_contract((8,), "float32", verb="swap")
    with pytest.raises(ContractMismatchError, match="does not match"):
        rep.require_contract((16,), "float64", verb="swap")
    rep.require_contract((16,), "float32", verb="swap")  # clean


def test_swap_contract_batch_coupled_names_node():
    fitted = _small_pipe(d=16, k=3).fit()
    node = next(iter(fitted.graph.nodes))
    fitted.graph.get_operator(node).batch_coupled = True
    rep = fitted.check(span=False)
    with pytest.raises(ContractMismatchError) as ei:
        rep.require_contract(None, None, verb="swap")
    assert ei.value.node is not None
    assert ei.value.label is not None


def test_coupling_refused_even_with_worse_lattice_trait():
    """Coupling is orthogonal to the verdict: a batch-coupled node that
    ALSO routes through a host callback classifies host_callback in the
    lattice, but the pad-and-slice serving paths must still refuse it."""
    import functools
    import jax

    def body(X):
        X = jax.pure_callback(
            functools.partial(lambda a: a),
            jax.ShapeDtypeStruct(X.shape, X.dtype), X,
        )
        return X - X.mean(axis=0)

    node = FunctionNode(batch_fn=body, label="coupled_callback")
    node.batch_coupled = True
    assert classify(node) == HOST_CALLBACK  # verdict: the worse trait
    fitted = node.to_pipeline().fit()
    rep = fitted.check(span=False)
    assert rep.batch_coupled_labels() == ["coupled_callback"]
    with pytest.raises(ContractMismatchError, match="batch-coupled"):
        rep.require_contract(None, None, verb="serve")


def test_worker_boot_contract_validation():
    fitted = _small_pipe(d=16, k=3).fit()
    rep = fitted.check(span=False)
    # the worker-boot call shape (cluster/worker.py): spec'd contract
    with pytest.raises(ContractMismatchError, match="boot"):
        rep.require_contract((99,), "float32", verb="boot")


def test_check_error_pickles_with_attribution():
    import pickle

    e = PipelineCheckError("bad spec", node="node[3]", label="PaddedFFT")
    e2 = pickle.loads(pickle.dumps(e))
    assert e2.node == "node[3]" and e2.label == "PaddedFFT"
    assert str(e2) == str(e)
