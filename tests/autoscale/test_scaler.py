"""The breach-driven autoscaler control plane, unit-tested against a
stub actuator: policy validation, breach hysteresis, cooldowns,
idle-driven scale-down, the cold-fleet guard, and the chaos seam —
a kill injected mid-scale-up reaps the half-born slot, lands a
``scale.abort`` instant, and the next tick converges the fleet."""

import time
from dataclasses import FrozenInstanceError

import pytest

import keystone_tpu.faults as faults
from keystone_tpu.autoscale import Autoscaler, ScalePolicy
from keystone_tpu.obs import flight
from keystone_tpu.serving.metrics import MetricsRegistry
from keystone_tpu.serving.slo import SloBreach


class StubActuator:
    """The five actuator verbs, recording every call. ``admitting`` /
    ``booting`` / ``draining`` are plain counters the verbs move, so a
    tick sequence drives a tiny fleet simulation with no processes."""

    def __init__(self, admitting=1, estimate=0.01):
        self.service_estimate = estimate
        self.admitting = admitting
        self.booting = 0
        self.draining = 0
        self.next_index = admitting
        self.calls = []

    def scale_view(self):
        return {
            "admitting": self.admitting,
            "booting": self.booting,
            "draining": self.draining,
        }

    def scale_up_slot(self):
        idx = self.next_index
        self.next_index += 1
        self.booting += 1
        self.calls.append(("scale_up_slot", idx))
        return idx

    def pick_drain_candidate(self):
        return self.admitting - 1 if self.admitting > 0 else None

    def begin_drain(self, index):
        self.calls.append(("begin_drain", index))
        self.admitting -= 1
        self.draining += 1

    def reap_slot(self, index):
        self.calls.append(("reap_slot", index))
        self.booting = max(0, self.booting - 1)

    # test conveniences
    def finish_boots(self):
        self.admitting += self.booting
        self.booting = 0

    def finish_drains(self):
        self.draining = 0


def breach(observed=2.0, budget=1.0):
    return SloBreach(
        objective="queue_age_budget_s", observed=observed, budget=budget,
        ts=time.time(),
    )


def idle_row(depth=0.0):
    return {"gauges": {"queue_depth": depth}}


FAST = dict(up_cooldown_s=0.0, down_cooldown_s=0.0, breach_window_s=60.0)


# -- policy ---------------------------------------------------------------


def test_policy_validates_bounds():
    with pytest.raises(ValueError):
        ScalePolicy(min_workers=0)
    with pytest.raises(ValueError):
        ScalePolicy(min_workers=3, max_workers=2)
    with pytest.raises(ValueError):
        ScalePolicy(up_breaches=0)
    with pytest.raises(ValueError):
        ScalePolicy(down_after_idle_ticks=0)


def test_policy_is_frozen_plain_data():
    p = ScalePolicy(min_workers=2, max_workers=8)
    with pytest.raises(FrozenInstanceError):
        p.max_workers = 99
    d = p.as_dict()
    assert d["min_workers"] == 2 and d["max_workers"] == 8
    assert p.clamp(0) == 2 and p.clamp(100) == 8 and p.clamp(5) == 5


# -- cold guard -----------------------------------------------------------


def test_cold_fleet_never_scales():
    act = StubActuator(admitting=0, estimate=None)
    scaler = Autoscaler(ScalePolicy(min_workers=2, **FAST), act)
    # below min AND breaching — but no learned service estimate, so the
    # scaler must not move (same contract as cold admission: no pricing
    # evidence, no action)
    assert scaler.tick([breach(), breach()]) == []
    assert act.calls == []
    assert scaler.target_workers is None


# -- breach hysteresis ----------------------------------------------------


def test_one_breach_is_not_enough_two_buy_a_worker():
    act = StubActuator(admitting=1)
    scaler = Autoscaler(ScalePolicy(up_breaches=2, **FAST), act)
    assert scaler.tick([breach()]) == []
    decisions = scaler.tick([breach()])
    assert [d.action for d in decisions] == ["up"]
    d = decisions[0]
    assert d.ok and d.reason == "breach"
    assert (d.from_workers, d.to_workers) == (1, 2)
    assert d.worker == 1
    assert d.trigger["objective"] == "queue_age_budget_s"
    assert ("scale_up_slot", 1) in act.calls
    assert scaler.target_workers == 2


def test_scale_up_clears_the_breach_window():
    act = StubActuator(admitting=1)
    scaler = Autoscaler(ScalePolicy(up_breaches=2, **FAST), act)
    assert len(scaler.tick([breach(), breach()])) == 1
    act.finish_boots()
    # the old evidence was spent on worker 1; a single fresh breach must
    # not buy worker 2
    assert scaler.tick([breach()]) == []
    assert scaler.tick([breach()]) != []


def test_up_cooldown_blocks_a_repeat_up():
    act = StubActuator(admitting=1)
    scaler = Autoscaler(
        ScalePolicy(up_breaches=1, up_cooldown_s=3600.0, down_cooldown_s=0.0),
        act,
    )
    assert len(scaler.tick([breach()])) == 1
    act.finish_boots()
    assert scaler.tick([breach()]) == []  # still cooling down
    assert len(act.calls) == 1


def test_max_workers_is_a_hard_ceiling():
    act = StubActuator(admitting=3)
    scaler = Autoscaler(ScalePolicy(max_workers=3, up_breaches=1, **FAST), act)
    assert scaler.tick([breach(), breach()]) == []
    assert act.calls == []


def test_below_min_restores_without_breaches():
    act = StubActuator(admitting=1)
    scaler = Autoscaler(ScalePolicy(min_workers=2, **FAST), act)
    decisions = scaler.tick()
    assert [d.reason for d in decisions] == ["below_min"]
    assert decisions[0].trigger == {}


# -- idle scale-down ------------------------------------------------------


def test_consecutive_idle_ticks_drain_one_worker():
    act = StubActuator(admitting=3)
    scaler = Autoscaler(
        ScalePolicy(down_after_idle_ticks=3, **FAST), act
    )
    assert scaler.tick(row=idle_row()) == []
    assert scaler.tick(row=idle_row()) == []
    decisions = scaler.tick(row=idle_row())
    assert [d.action for d in decisions] == ["down"]
    d = decisions[0]
    assert d.ok and d.reason == "idle" and d.worker == 2
    assert (d.from_workers, d.to_workers) == (3, 2)
    assert ("begin_drain", 2) in act.calls


def test_a_loaded_tick_resets_the_idle_run():
    act = StubActuator(admitting=3)
    scaler = Autoscaler(ScalePolicy(down_after_idle_ticks=2, **FAST), act)
    assert scaler.tick(row=idle_row()) == []
    # queue depth above the idle threshold: the run restarts
    assert scaler.tick(row=idle_row(depth=5.0)) == []
    assert scaler.tick(row=idle_row()) == []
    assert len(scaler.tick(row=idle_row())) == 1


def test_min_workers_is_a_hard_floor_for_drains():
    act = StubActuator(admitting=1)
    scaler = Autoscaler(ScalePolicy(down_after_idle_ticks=1, **FAST), act)
    for _ in range(5):
        assert scaler.tick(row=idle_row()) == []
    assert act.calls == []


def test_down_cooldown_spaces_out_drains():
    act = StubActuator(admitting=4)
    scaler = Autoscaler(
        ScalePolicy(
            down_after_idle_ticks=1, up_cooldown_s=0.0,
            down_cooldown_s=3600.0,
        ),
        act,
    )
    assert len(scaler.tick(row=idle_row())) == 1
    act.finish_drains()
    for _ in range(5):
        assert scaler.tick(row=idle_row()) == []
    assert len(act.calls) == 1


# -- evidence -------------------------------------------------------------


def test_decisions_land_as_counters_instants_and_rows():
    flight.reset()
    metrics = MetricsRegistry()
    act = StubActuator(admitting=2)
    scaler = Autoscaler(
        ScalePolicy(up_breaches=1, down_after_idle_ticks=1, **FAST),
        act, metrics=metrics,
    )
    scaler.tick([breach()])
    act.finish_boots()
    scaler.tick(row=idle_row())
    counters = metrics.snapshot()["counters"]
    assert counters["scale_ups"] == 1 and counters["scale_downs"] == 1
    names = [e["name"] for e in flight.recorder().entries()]
    assert "scale.up" in names and "scale.down" in names
    rows = [d.as_row() for d in scaler.decisions]
    assert [r["action"] for r in rows] == ["up", "down"]
    assert all(
        {"ok", "reason", "from_workers", "to_workers", "ts"} <= set(r)
        for r in rows
    )
    desc = scaler.describe()
    assert desc["policy"]["up_breaches"] == 1
    assert len(desc["decisions"]) == 2


# -- chaos: kill mid-scale-up ---------------------------------------------


def test_kill_mid_scale_up_reaps_and_converges():
    flight.reset()
    metrics = MetricsRegistry()
    act = StubActuator(admitting=1)
    scaler = Autoscaler(
        ScalePolicy(up_breaches=1, **FAST), act, metrics=metrics
    )
    faults.install(faults.parse_plan("scale.spawn=kill@0"))
    try:
        decisions = scaler.tick([breach()])
    finally:
        faults.clear()
    # the apply was aborted: half-born slot 1 reaped, fleet unchanged
    assert [d.ok for d in decisions] == [False]
    d = decisions[0]
    assert d.action == "up" and d.worker == 1
    assert (d.from_workers, d.to_workers) == (1, 1)
    assert "cause" in d.trigger
    assert ("reap_slot", 1) in act.calls
    assert act.booting == 0 and act.admitting == 1
    assert metrics.snapshot()["counters"]["scale_aborts"] == 1
    # the recovery instant the lint pairs with the scale.spawn site
    names = [e["name"] for e in flight.recorder().entries()]
    assert "scale.abort" in names
    # fresh evidence converges the fleet back toward the policy target
    decisions = scaler.tick([breach()])
    assert [d.ok for d in decisions] == [True]
    assert act.booting == 1
    assert scaler.target_workers == 2


def test_kill_mid_drain_reaps_the_half_drained_slot():
    flight.reset()
    act = StubActuator(admitting=2)
    scaler = Autoscaler(
        ScalePolicy(down_after_idle_ticks=1, **FAST), act
    )
    faults.install(faults.parse_plan("scale.drain=kill@0"))
    try:
        decisions = scaler.tick(row=idle_row())
    finally:
        faults.clear()
    assert [d.ok for d in decisions] == [False]
    assert ("reap_slot", 1) in act.calls
    names = [e["name"] for e in flight.recorder().entries()]
    assert "scale.abort" in names
