"""Image node oracle tests: the conv/pool/rectifier nodes must agree with a
naive numpy im2col implementation of the reference algorithms
(parity with ConvolverSuite's scipy golden files, SURVEY §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.images.core import (
    CenterCornerPatcher,
    Convolver,
    Cropper,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    SymmetricRectifier,
    Windower,
    images_from_vectors,
    pack_filter_images,
    vectorize_images,
)
from keystone_tpu.nodes.learning.zca import ZCAWhitenerEstimator
from keystone_tpu.utils.stats import normalize_rows


def _patches_naive(img, S):
    """All S×S patches of (X, Y, C) img in the reference layout
    c + px*C + py*C*S (Convolver.makePatches)."""
    X, Y, C = img.shape
    rw, rh = X - S + 1, Y - S + 1
    out = np.zeros((rw * rh, S * S * C))
    for y in range(rh):
        for x in range(rw):
            row = x + y * rw
            for py in range(S):
                for px in range(S):
                    for c in range(C):
                        out[row, c + px * C + py * C * S] = img[
                            x + px, y + py, c
                        ]
    return out


def _norm_rows_np(mat, alpha):
    means = mat.mean(axis=1, keepdims=True)
    var = ((mat - means) ** 2).sum(axis=1, keepdims=True) / (mat.shape[1] - 1)
    return (mat - means) / np.sqrt(var + alpha)


def test_convolver_matches_naive_im2col():
    rng = np.random.default_rng(0)
    n, X, Y, C, S, K = 3, 8, 7, 2, 3, 5
    imgs = rng.standard_normal((n, X, Y, C)).astype(np.float32)
    filters = rng.standard_normal((K, S * S * C)).astype(np.float32)

    conv = Convolver(filters, X, Y, C, normalize_patches=False)
    out = np.asarray(conv.apply_batch(Dataset.of(imgs)).to_array())
    assert out.shape == (n, X - S + 1, Y - S + 1, K)

    for i in range(n):
        pm = _patches_naive(imgs[i], S)
        expected = pm @ filters.T  # (rw*rh, K)
        rw = X - S + 1
        for y in range(Y - S + 1):
            for x in range(rw):
                np.testing.assert_allclose(
                    out[i, x, y], expected[x + y * rw], rtol=1e-3, atol=1e-3
                )


def test_convolver_normalized_matches_naive():
    rng = np.random.default_rng(1)
    n, X, Y, C, S, K = 2, 6, 6, 3, 3, 4
    imgs = rng.standard_normal((n, X, Y, C)).astype(np.float32)
    filters = rng.standard_normal((K, S * S * C)).astype(np.float32)

    conv = Convolver(filters, X, Y, C, normalize_patches=True, var_constant=10.0)
    out = np.asarray(conv.apply_batch(Dataset.of(imgs)).to_array())

    for i in range(n):
        pm = _norm_rows_np(_patches_naive(imgs[i], S), 10.0)
        expected = pm @ filters.T
        rw = X - S + 1
        got = np.stack(
            [out[i, x, y] for y in range(X - S + 1) for x in range(rw)]
        )
        np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-3)


def test_convolver_whitened_matches_naive():
    """Full reference path: normalize patches, subtract whitener means,
    multiply whitened filters."""
    rng = np.random.default_rng(2)
    n, X, Y, C, S, K = 2, 6, 6, 2, 3, 4
    imgs = rng.standard_normal((n, X, Y, C)).astype(np.float32)
    sample = rng.standard_normal((50, S * S * C)).astype(np.float32)
    whitener = ZCAWhitenerEstimator(0.1).fit_single(sample)
    filters = rng.standard_normal((K, S * S * C)).astype(np.float32)

    conv = Convolver(filters, X, Y, C, whitener=whitener, normalize_patches=True)
    out = np.asarray(conv.apply_batch(Dataset.of(imgs)).to_array())

    means = np.asarray(whitener.means)
    for i in range(n):
        pm = _norm_rows_np(_patches_naive(imgs[i], S), 10.0) - means
        expected = pm @ filters.T
        rw = X - S + 1
        got = np.stack(
            [out[i, x, y] for y in range(Y - S + 1) for x in range(rw)]
        )
        np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-3)


def test_symmetric_rectifier():
    X = np.array([[[[1.0, -2.0]]]], dtype=np.float32)
    out = np.asarray(
        SymmetricRectifier(alpha=0.25).apply_batch(Dataset.of(X)).to_array()
    )
    np.testing.assert_allclose(out[0, 0, 0], [0.75, 0.0, 0.0, 1.75])


def test_pooler_matches_naive():
    """Sum pooling with clipped edge windows (Pooler.scala:21-84)."""
    rng = np.random.default_rng(3)
    n, X, Y, C = 2, 27, 27, 4
    imgs = rng.standard_normal((n, X, Y, C)).astype(np.float32)
    stride, ps = 13, 14
    out = np.asarray(
        Pooler(stride, ps, None, "sum").apply_batch(Dataset.of(imgs)).to_array()
    )
    start = ps // 2
    xs = list(range(start, X, stride))
    assert out.shape == (n, len(xs), len(xs), C)
    for i in range(n):
        for xi, x in enumerate(xs):
            for yi, y in enumerate(xs):
                x0, x1 = x - ps // 2, min(x + ps // 2, X)
                y0, y1 = y - ps // 2, min(y + ps // 2, Y)
                expected = imgs[i, x0:x1, y0:y1, :].sum(axis=(0, 1))
                np.testing.assert_allclose(
                    out[i, xi, yi], expected, rtol=1e-3, atol=1e-3
                )


def test_pooler_abs_pixel_fn():
    imgs = -np.ones((1, 4, 4, 1), dtype=np.float32)
    out = np.asarray(
        Pooler(2, 2, jnp.abs, "sum").apply_batch(Dataset.of(imgs)).to_array()
    )
    assert (out > 0).all()


def test_windower_matches_naive():
    rng = np.random.default_rng(4)
    n, X, Y, C, w, st = 2, 5, 5, 2, 3, 2
    imgs = rng.standard_normal((n, X, Y, C)).astype(np.float32)
    out = np.asarray(
        Windower(st, w).apply_batch(Dataset.of(imgs)).to_array()
    )
    xs = list(range(0, X - w + 1, st))
    assert out.shape == (n * len(xs) * len(xs), w, w, C)
    k = 0
    for i in range(n):
        for x in xs:
            for y in xs:
                np.testing.assert_allclose(
                    out[k], imgs[i, x : x + w, y : y + w, :]
                )
                k += 1


def test_vectorize_images_channel_major_layout():
    img = np.zeros((1, 2, 2, 2), dtype=np.float32)
    # value encodes (x, y, c) as x*100 + y*10 + c
    for x in range(2):
        for y in range(2):
            for c in range(2):
                img[0, x, y, c] = x * 100 + y * 10 + c
    v = np.asarray(vectorize_images(jnp.asarray(img)))[0]
    # layout index = c + x*C + y*X*C
    for x in range(2):
        for y in range(2):
            for c in range(2):
                assert v[c + x * 2 + y * 4] == x * 100 + y * 10 + c
    back = np.asarray(images_from_vectors(v[None], 2, 2, 2))
    np.testing.assert_allclose(back, img)


def test_zca_whitener_decorrelates():
    rng = np.random.default_rng(5)
    A = rng.standard_normal((500, 6)).astype(np.float32)
    A = A @ rng.standard_normal((6, 6)).astype(np.float32)  # correlate
    w = ZCAWhitenerEstimator(eps=1e-6).fit_single(A)
    out = np.asarray(w.transform(A))
    cov = out.T @ out / (A.shape[0] - 1)
    np.testing.assert_allclose(cov, np.eye(6), atol=0.05)


def test_normalize_rows_matches_numpy():
    rng = np.random.default_rng(6)
    A = rng.standard_normal((10, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(normalize_rows(A, 10.0)),
        _norm_rows_np(A, 10.0),
        rtol=1e-4,
        atol=1e-4,
    )


def test_cropper_and_patcher_and_grayscale():
    rng = np.random.default_rng(7)
    imgs = rng.uniform(0, 255, (2, 8, 8, 3)).astype(np.float32)
    crop = np.asarray(
        Cropper(1, 2, 5, 6).apply_batch(Dataset.of(imgs)).to_array()
    )
    np.testing.assert_allclose(crop, imgs[:, 1:5, 2:6, :])
    cc = np.asarray(
        CenterCornerPatcher(4, 4).apply_batch(Dataset.of(imgs)).to_array()
    )
    assert cc.shape == (10, 4, 4, 3)
    # per-image grouping: cc[0] is img0's first crop, cc[5] img1's first
    np.testing.assert_allclose(cc[0], imgs[0, :4, :4, :])
    np.testing.assert_allclose(cc[5], imgs[1, :4, :4, :])
    # center crop is the 5th of each image's group
    np.testing.assert_allclose(cc[4], imgs[0, 2:6, 2:6, :])
    gray = np.asarray(GrayScaler().apply_batch(Dataset.of(imgs)).to_array())
    assert gray.shape == (2, 8, 8, 1)
    scaled = np.asarray(PixelScaler().apply_batch(Dataset.of(imgs)).to_array())
    assert scaled.max() <= 1.0
