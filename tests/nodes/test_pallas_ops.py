"""Pallas kernel correctness vs the XLA lowering (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.nodes.learning.kernel import _gaussian_block_xla
from keystone_tpu.ops.gaussian_kernel import (
    gaussian_kernel_block_pallas,
    pallas_block_supported,
)


def test_pallas_gaussian_block_matches_xla_interpret():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((700, 128)).astype(np.float32)  # non-tile-multiple n
    Xb = rng.standard_normal((256, 128)).astype(np.float32)
    want = np.asarray(_gaussian_block_xla(jnp.asarray(X), jnp.asarray(Xb), 0.03))
    got = np.asarray(
        gaussian_kernel_block_pallas(X, Xb, 0.03, interpret=True)
    )
    assert got.shape == (700, 256)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pallas_support_gate():
    # CPU backend in tests: never claims support
    assert not pallas_block_supported(4096, 512, 1024)
