"""Solver tests: weighted BCD vs per-class oracle, LBFGS vs exact, kernel
ridge exact interpolation, NB/logistic/LDA sanity, auto-solver selection —
mirroring the reference suites (BlockWeightedLeastSquaresSuite:115,
KernelModelSuite, LeastSquaresEstimatorSuite)."""

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning.classifiers import (
    LeastSquaresEstimator,
    LinearDiscriminantAnalysis,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
)
from keystone_tpu.nodes.learning.kernel import (
    KernelBlockLinearMapper,
    KernelRidgeRegression,
)
from keystone_tpu.nodes.learning.lbfgs import (
    DenseLBFGSwithL2,
    LocalLeastSquaresEstimator,
    SparseLBFGSwithL2,
)
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.nodes.learning.weighted import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
    ReWeightedLeastSquaresEstimator,
)


def _class_data(rng, n=120, d=10, k=3):
    y = rng.integers(0, k, n)
    W = rng.standard_normal((d, k))
    X = rng.standard_normal((n, d)).astype(np.float32) + 0.5 * W.T[y]
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), y] = 1.0
    return X.astype(np.float32), Y, y


def test_block_weighted_agrees_with_per_class():
    """parity: BlockWeightedLeastSquaresSuite.scala:115."""
    rng = np.random.default_rng(0)
    X, Y, _ = _class_data(rng)
    block = BlockWeightedLeastSquaresEstimator(
        4, 20, lam=0.5, mixture_weight=0.3
    ).fit(Dataset.of(X), Dataset.of(Y))
    per_class = PerClassWeightedLeastSquaresEstimator(
        4, 1, lam=0.5, mixture_weight=0.3
    ).fit(Dataset.of(X), Dataset.of(Y))
    pb = np.asarray(block.apply_batch(Dataset.of(X)).to_array())
    pc = np.asarray(per_class.apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_allclose(pb, pc, rtol=5e-2, atol=5e-2)


def test_weighted_family_three_way_agreement_mixed_balance():
    """block ≈ exact per-class ≈ iterative reweighted BCD at heavily mixed
    class balance (VERDICT r3 #8; parity: the reference validates its block
    solver against the per-class path, whose inner solver is
    internal/ReWeightedLeastSquares.scala:18 — here all three are compared
    pairwise on one problem)."""
    rng = np.random.default_rng(7)
    n, d, k = 160, 12, 4
    # mixed balance: class sizes roughly 8 / 24 / 48 / 80
    y = np.repeat(np.arange(k), [8, 24, 48, 80])
    rng.shuffle(y)
    W = rng.standard_normal((d, k))
    X = (rng.standard_normal((n, d)) + 0.5 * W.T[y]).astype(np.float32)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), y] = 1.0

    args = dict(lam=0.5, mixture_weight=0.3)
    block = BlockWeightedLeastSquaresEstimator(4, 25, **args).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    exact = PerClassWeightedLeastSquaresEstimator(4, 1, **args).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    reweighted = ReWeightedLeastSquaresEstimator(4, 25, **args).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    pb = np.asarray(block.apply_batch(Dataset.of(X)).to_array())
    pe = np.asarray(exact.apply_batch(Dataset.of(X)).to_array())
    pr = np.asarray(reweighted.apply_batch(Dataset.of(X)).to_array())
    # the iterative BCD converges to the exact per-class solution
    np.testing.assert_allclose(pr, pe, rtol=2e-2, atol=2e-2)
    # and the block solver agrees with both (its iteration path differs)
    np.testing.assert_allclose(pb, pe, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(pb, pr, rtol=5e-2, atol=5e-2)


def test_block_weighted_dual_path_agrees_with_per_class():
    """n + 3 < d engages the Woodbury/dual sample-space solve (the
    reference's 1000-class ImageNet regime: few samples per class, wide
    features). With a single block and one iteration the block update IS
    the exact per-class system, so the dual result must match the
    independent dense per-class implementation tightly — at both a
    benign λ and the ImageNet-scale tiny λ that stresses the Woodbury
    cancellation."""
    rng = np.random.default_rng(11)
    n, d, k = 48, 64, 6
    y = np.repeat(np.arange(k), n // k)
    rng.shuffle(y)
    W = rng.standard_normal((d, k))
    X = (rng.standard_normal((n, d)) + 0.5 * W.T[y]).astype(np.float32)
    Y = -np.ones((n, k), dtype=np.float32)
    Y[np.arange(n), y] = 1.0

    # HELD-OUT rows are the load-bearing check: training rows lie in
    # span(Q) and annihilate any weight-error component orthogonal to
    # the data span — the exact error mode a 1/λ-amplified ⊥ term
    # produces (invisible on train, near-random held-out).
    X_test = rng.standard_normal((32, d)).astype(np.float32)
    for lam in (0.5, 1e-4):
        args = dict(lam=lam, mixture_weight=0.25)
        dual = BlockWeightedLeastSquaresEstimator(d, 1, **args).fit(
            Dataset.of(X), Dataset.of(Y)
        )
        exact = PerClassWeightedLeastSquaresEstimator(d, 1, **args).fit(
            Dataset.of(X), Dataset.of(Y)
        )
        for batch in (X, X_test):
            pd_ = np.asarray(dual.apply_batch(Dataset.of(batch)).to_array())
            pe = np.asarray(exact.apply_batch(Dataset.of(batch)).to_array())
            scale = np.abs(pe).max()
            np.testing.assert_allclose(pd_, pe, rtol=2e-2, atol=2e-2 * scale)


def test_reweighted_solver_single_block_is_exact():
    """With one block and one iteration the reweighted update IS the closed
    form (Gram cache + rhs reduce to the normal equations), pinning the
    weighted algebra itself."""
    from keystone_tpu.nodes.learning.weighted import solve_reweighted_l2

    rng = np.random.default_rng(3)
    n, d, k = 64, 6, 2
    A = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    b = rng.random(n).astype(np.float32) + 0.1
    reg = 0.3
    Ws = solve_reweighted_l2([A], y, b, reg=reg, num_iter=1)
    A64, y64, b64 = (
        A.astype(np.float64), y.astype(np.float64), b.astype(np.float64)
    )
    want = np.linalg.solve(
        A64.T @ (A64 * b64[:, None]) + reg * np.eye(d),
        A64.T @ (y64 * b64[:, None]),
    )
    np.testing.assert_allclose(np.asarray(Ws[0]), want, rtol=1e-3, atol=1e-3)


def test_block_weighted_learns_class_structure():
    """w=0.5, single block sanity: classifies far above chance."""
    rng = np.random.default_rng(1)
    X, Y, y = _class_data(rng)
    model = BlockWeightedLeastSquaresEstimator(
        10, 10, lam=0.1, mixture_weight=0.5
    ).fit(Dataset.of(X), Dataset.of(Y))
    pred = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    assert (pred.argmax(axis=1) == y).mean() > 0.6  # chance = 1/3


def test_dense_lbfgs_matches_exact_ols():
    rng = np.random.default_rng(2)
    n, d, k = 200, 12, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    W = rng.standard_normal((d, k)).astype(np.float32)
    Y = X @ W
    model = DenseLBFGSwithL2(reg_param=0.0, num_iterations=100).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=1e-2, atol=1e-2)


def test_sparse_lbfgs_accepts_scipy_items():
    rng = np.random.default_rng(3)
    n, d = 80, 20
    dense = (rng.random((n, d)) < 0.2) * rng.standard_normal((n, d))
    items = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]
    W = rng.standard_normal((d, 2)).astype(np.float32)
    Y = dense.astype(np.float32) @ W
    model = SparseLBFGSwithL2(reg_param=0.0, num_iterations=100).fit(
        Dataset.from_items(items), Dataset.of(Y)
    )
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=5e-2, atol=5e-2)


def test_local_least_squares_dual_matches_primal():
    """d >> n regime (parity: LocalLeastSquaresEstimator d>>n dual form)."""
    rng = np.random.default_rng(4)
    n, d, k = 30, 100, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    lam = 1.0
    model = LocalLeastSquaresEstimator(lam).fit(Dataset.of(X), Dataset.of(Y))
    # primal ridge on centered data
    Xc = X - X.mean(axis=0)
    Yc = Y - Y.mean(axis=0)
    W = np.linalg.solve(Xc.T @ Xc + lam * np.eye(d), Xc.T @ Yc)
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=1e-2, atol=1e-2)


def test_kernel_ridge_multiblock_matches_closed_form():
    """Multi-block Gauss-Seidel converges to (K+λI)⁻¹Y
    (parity: KernelModelSuite agreement checks)."""
    rng = np.random.default_rng(5)
    n, d, k = 64, 4, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    gamma, lam = 0.5, 1.0
    model = KernelRidgeRegression(
        gamma=gamma, lam=lam, block_size=16, num_epochs=25
    ).fit(Dataset.of(X), Dataset.of(Y))
    diff = X[:, None, :] - X[None, :, :]
    K = np.exp(-gamma * (diff ** 2).sum(-1))
    W = np.linalg.solve(K + lam * np.eye(n), Y)
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=0.02, atol=0.02)


def test_kernel_ridge_one_block_matches_closed_form():
    rng = np.random.default_rng(6)
    n, d, k = 40, 3, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    gamma, lam = 0.3, 0.5
    model = KernelRidgeRegression(
        gamma=gamma, lam=lam, block_size=n, num_epochs=1
    ).fit(Dataset.of(X), Dataset.of(Y))
    # closed form: W = (K + λI)⁻¹ Y
    diff = X[:, None, :] - X[None, :, :]
    K = np.exp(-gamma * (diff ** 2).sum(-1))
    W = np.linalg.solve(K + lam * np.eye(n), Y)
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=1e-3, atol=1e-3)


def test_naive_bayes_classifies_counts():
    rng = np.random.default_rng(7)
    # two classes with disjoint dominant features
    n = 100
    X0 = rng.poisson(5, (n, 4)) * np.array([1, 1, 0, 0])
    X1 = rng.poisson(5, (n, 4)) * np.array([0, 0, 1, 1])
    X = np.concatenate([X0, X1]).astype(np.float32)
    y = np.concatenate([np.zeros(n), np.ones(n)]).astype(np.int32)
    model = NaiveBayesEstimator(2).fit(Dataset.of(X), Dataset.of(y))
    scores = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    preds = scores.argmax(axis=1)
    assert (preds == y).mean() > 0.95


def test_logistic_regression_separable():
    rng = np.random.default_rng(8)
    n = 100
    X = np.concatenate(
        [rng.standard_normal((n, 2)) + 3, rng.standard_normal((n, 2)) - 3]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(n), np.ones(n)]).astype(np.int32)
    model = LogisticRegressionEstimator(2, reg_param=0.01, num_iters=50).fit(
        Dataset.of(X), Dataset.of(y)
    )
    preds = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    assert (preds == y).mean() > 0.97


def test_lda_projects_classes_apart():
    rng = np.random.default_rng(9)
    n = 60
    X = np.concatenate(
        [
            rng.standard_normal((n, 5)) + np.array([4, 0, 0, 0, 0]),
            rng.standard_normal((n, 5)),
            rng.standard_normal((n, 5)) - np.array([4, 0, 0, 0, 0]),
        ]
    ).astype(np.float32)
    y = np.repeat([0, 1, 2], n).astype(np.int32)
    mapper = LinearDiscriminantAnalysis(2).fit(Dataset.of(X), Dataset.of(y))
    Z = np.asarray(mapper.apply_batch(Dataset.of(X)).to_array())
    assert Z.shape == (3 * n, 2)
    # class means well separated along the first discriminant
    m = [Z[y == c, 0].mean() for c in range(3)]
    s = [Z[y == c, 0].std() for c in range(3)]
    gaps = sorted(m)
    assert (gaps[1] - gaps[0]) > 2 * max(s) and (gaps[2] - gaps[1]) > 2 * max(s)


def test_least_squares_auto_selection_regimes():
    """Cost model picks the expected solver per regime
    (parity: LeastSquaresEstimatorSuite)."""
    est = LeastSquaresEstimator(lam=0.1, num_machines=16)
    rng = np.random.default_rng(10)

    # dense small-d: exact/normal-equations family should win over 20-iter
    # LBFGS at huge n, small d
    dense_sample = Dataset.of(rng.standard_normal((100, 8)).astype(np.float32))
    labels = Dataset.of(rng.standard_normal((100, 2)).astype(np.float32))
    chosen = est.optimize(dense_sample, labels)
    assert chosen is not None

    # very sparse data → sparse LBFGS wins
    items = [sp.csr_matrix(np.eye(1, 10000, k=i % 100)) for i in range(50)]
    sparse_sample = Dataset.from_items(items)
    chosen_sparse = est.optimize(
        sparse_sample, Dataset.of(rng.standard_normal((50, 2)))
    )
    from keystone_tpu.nodes.learning.lbfgs import SparseLBFGSwithL2 as S

    assert isinstance(chosen_sparse, S)


def test_lbfgs_with_l2_matches_closed_form_ridge():
    rng = np.random.default_rng(11)
    n, d, k = 150, 10, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    lam = 0.5
    model = DenseLBFGSwithL2(reg_param=lam, num_iterations=200).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    # loss = ||XW−Y||²/(2n) + λ/2‖W‖² → (XᵀX/n + λI) W = XᵀY/n
    W = np.linalg.solve(X.T @ X / n + lam * np.eye(d), X.T @ Y / n)
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=2e-2, atol=2e-2)


def test_sparse_lbfgs_strategies_agree():
    """The two sparse-LBFGS execution strategies — precomputed-Gram
    quadratic and the gather/scatter path — must fit the same model
    (gram_budget_bytes picks the strategy)."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseRows
    from keystone_tpu.nodes.learning.lbfgs import SparseLBFGSwithL2

    rng = np.random.default_rng(21)
    n, d, k = 256, 96, 2
    dense = (rng.random((n, d)) < 0.1) * rng.standard_normal((n, d))
    X = SparseRows.from_scipy(sp.csr_matrix(dense.astype(np.float32)))
    Y = np.sign(rng.standard_normal((n, k))).astype(np.float32)

    def fit(budget):
        # tight tolerance: both strategies must reach the same optimum,
        # not just wander near it on different trajectories
        est = SparseLBFGSwithL2(
            reg_param=1e-3, num_iterations=200, convergence_tol=1e-9,
            gram_budget_bytes=budget,
        )
        m = est.fit(Dataset(X, batched=True), Dataset.of(Y))
        return np.asarray(m.W)

    w_gram = fit(1e9)   # d x d Gram fits easily
    w_gather = fit(0)   # Gram disabled -> gather/scatter path
    np.testing.assert_allclose(w_gather, w_gram, rtol=2e-2, atol=2e-3)


def test_minimize_lbfgs_quadratic_exact():
    """On a strictly convex quadratic the compiled L-BFGS must reach the
    analytic optimum (pins the two-loop recursion + line search)."""
    from keystone_tpu.nodes.learning.lbfgs import minimize_lbfgs

    rng = np.random.default_rng(5)
    d = 24
    M = rng.standard_normal((d, d)).astype(np.float32)
    H = M @ M.T + 0.5 * np.eye(d, dtype=np.float32)
    b = rng.standard_normal(d).astype(np.float32)

    def vag(w, H, b):
        Hw = H @ w
        return 0.5 * jnp.vdot(w, Hw) - jnp.vdot(b, w), Hw - b

    w = minimize_lbfgs(
        vag, np.zeros(d, np.float32), max_iterations=100,
        convergence_tol=1e-12, vag_args=(jnp.asarray(H), jnp.asarray(b)),
    )
    want = np.linalg.solve(H.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(np.asarray(w), want, rtol=1e-3, atol=1e-3)


def test_minimize_lbfgs_ill_scaled_and_badly_started():
    """Poor scaling exercises the memory/γ machinery; a far-off start
    exercises backtracking (step 1 overshoots badly at first)."""
    from keystone_tpu.nodes.learning.lbfgs import minimize_lbfgs

    # condition number 1e2: curvature-aware enough to stress the memory
    # while staying above the f32 |Δf| convergence floor
    scales = jnp.asarray(
        np.logspace(0, 2, 16).astype(np.float32)
    )

    def vag(w, scales):
        return 0.5 * jnp.sum(scales * w * w), scales * w

    w0 = np.full(16, 50.0, np.float32)
    w = minimize_lbfgs(
        vag, w0, max_iterations=200, convergence_tol=1e-12,
        vag_args=(scales,),
    )
    assert float(jnp.max(jnp.abs(w))) < 5e-2


def test_minimize_lbfgs_handles_flat_objective():
    """A constant objective (zero gradient everywhere) must terminate
    and return the start point, not NaN or loop forever."""
    from keystone_tpu.nodes.learning.lbfgs import minimize_lbfgs

    def vag(w):
        return jnp.float32(1.0), jnp.zeros_like(w)

    w0 = np.ones(4, np.float32)
    w = minimize_lbfgs(vag, w0, max_iterations=30)
    np.testing.assert_allclose(np.asarray(w), w0)
    assert np.all(np.isfinite(np.asarray(w)))
