"""NLP stack tests, mirroring the reference suites:
StringUtilsSuite, NGramSuite, HashingTFSuite, NGramsHashingTFSuite,
NGramIndexerSuite, WordFrequencyEncoderSuite, StupidBackoffSuite,
CommonSparseFeaturesSuite — plus SparseRows numeric oracles."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.data.sparse import SparseRows
from keystone_tpu.nodes.nlp import (
    HashingTF,
    LowerCase,
    NaiveBitPackIndexer,
    NGramIndexerImpl,
    NGramsCounts,
    NGramsFeaturizer,
    NGramsHashingTF,
    StupidBackoffEstimator,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
    java_string_hash,
)
from keystone_tpu.nodes.stats import TermFrequency
from keystone_tpu.nodes.util import (
    AllSparseFeatures,
    CommonSparseFeatures,
)


# ---- StringUtilsSuite ----------------------------------------------------

STRINGS = ["  The quick BROWN fo.X ", " ! !.,)JumpeD. ovER the LAZy DOG.. ! "]


def test_trim():
    out = [Trim().apply(s) for s in STRINGS]
    assert out == ["The quick BROWN fo.X", "! !.,)JumpeD. ovER the LAZy DOG.. !"]


def test_lower_case():
    out = [LowerCase().apply(s) for s in STRINGS]
    assert out == [
        "  the quick brown fo.x ",
        " ! !.,)jumped. over the lazy dog.. ! ",
    ]


def test_tokenizer():
    # parity: StringUtilsSuite "tokenizer" — leading empty token kept,
    # trailing separators dropped (Java String.split semantics)
    out = [Tokenizer().apply(s) for s in STRINGS]
    assert out == [
        ["", "The", "quick", "BROWN", "fo", "X"],
        ["", "JumpeD", "ovER", "the", "LAZy", "DOG"],
    ]


# ---- NGramSuite ----------------------------------------------------------

DOCS = ["Pipelines are awesome", "NLP is awesome"]


def _tokens(doc):
    return Tokenizer().apply(doc)


def test_ngrams_featurizer():
    uni = [NGramsFeaturizer([1]).apply(_tokens(d)) for d in DOCS]
    assert uni == [
        [("Pipelines",), ("are",), ("awesome",)],
        [("NLP",), ("is",), ("awesome",)],
    ]
    bt = [NGramsFeaturizer([2, 3]).apply(_tokens(d)) for d in DOCS]
    assert bt == [
        [("Pipelines", "are"), ("Pipelines", "are", "awesome"),
         ("are", "awesome")],
        [("NLP", "is"), ("NLP", "is", "awesome"), ("is", "awesome")],
    ]
    assert [NGramsFeaturizer([6]).apply(_tokens(d)) for d in DOCS] == [[], []]


def test_ngrams_counts():
    grams = Dataset.from_items(
        [NGramsFeaturizer([1]).apply(_tokens(d)) for d in DOCS]
    )
    counts = dict(NGramsCounts().apply_batch(grams).collect())
    assert counts == {
        ("awesome",): 2, ("Pipelines",): 1, ("are",): 1,
        ("NLP",): 1, ("is",): 1,
    }
    # sorted descending by count
    ordered = NGramsCounts().apply_batch(grams).collect()
    assert ordered[0] == (("awesome",), 2)
    grams23 = Dataset.from_items(
        [NGramsFeaturizer([2, 3]).apply(_tokens(d)) for d in DOCS]
    )
    assert all(c == 1 for _, c in NGramsCounts().apply_batch(grams23).collect())


# ---- HashingTFSuite ------------------------------------------------------

def test_java_string_hash():
    # golden values from java.lang.String.hashCode
    assert java_string_hash("") == 0
    assert java_string_hash("a") == 97
    assert java_string_hash("abc") == 96354
    assert java_string_hash("hello") == 99162322
    # the famous Integer.MIN_VALUE string (32-bit overflow behavior)
    assert java_string_hash("polygenelubricants") == -2147483648


def test_hashing_tf_no_collisions():
    dims = 4000
    row = HashingTF(dims).apply(["1", "2", "4", "4", "4", "4", "2"])
    assert len(row) == 3
    assert sorted(v for _, v in row) == [1.0, 2.0, 4.0]


def test_hashing_tf_collisions():
    row = HashingTF(2).apply(["1", "2", "4", "4", "4", "4", "2"])
    assert len(row) <= 2
    assert sum(v for _, v in row) == 7.0


def test_ngrams_hashing_tf_equals_composed():
    # parity: NGramsHashingTFSuite — rolling hash must equal
    # NGramsFeaturizer andThen HashingTF exactly
    line = Tokenizer().apply("a quick brown fox jumped over a lazy dog a a")
    for orders in ([1], [1, 2], [2, 3], [1, 2, 3, 4]):
        for dims in (64, 4096):
            composed = HashingTF(dims).apply(
                NGramsFeaturizer(orders).apply(line)
            )
            rolling = NGramsHashingTF(orders, dims).apply(line)
            assert rolling == composed, (orders, dims)


# ---- NGramIndexerSuite ---------------------------------------------------

def test_bitpack_pack():
    assert NaiveBitPackIndexer.pack([1]) == 2**40
    assert NaiveBitPackIndexer.pack([1, 1]) == 2**40 + 2**20 + 2**60
    assert NaiveBitPackIndexer.pack([1, 1, 1]) == 1 + 2**40 + 2**20 + 2**61
    assert NGramIndexerImpl.pack(range(1, 6)) == (1, 2, 3, 4, 5)


@pytest.mark.parametrize("indexer", [NaiveBitPackIndexer, NGramIndexerImpl])
def test_remove_farthest_word(indexer):
    assert indexer.remove_farthest_word(indexer.pack([1, 2, 3])) == \
        indexer.pack([2, 3])
    assert indexer.remove_farthest_word(indexer.pack([1, 2])) == \
        indexer.pack([2])


@pytest.mark.parametrize("indexer", [NaiveBitPackIndexer, NGramIndexerImpl])
def test_remove_current_word(indexer):
    assert indexer.remove_current_word(indexer.pack([1, 2, 3])) == \
        indexer.pack([1, 2])
    assert indexer.remove_current_word(indexer.pack([1, 2])) == \
        indexer.pack([1])


def test_bitpack_batch_roundtrip():
    rng = np.random.default_rng(0)
    for order in (1, 2, 3):
        words = rng.integers(0, 2**20, size=(100, order))
        packed = NaiveBitPackIndexer.pack_batch(words, order)
        scalar = np.array(
            [NaiveBitPackIndexer.pack(list(w)) for w in words]
        )
        assert np.array_equal(packed, scalar)
        unpacked, orders = NaiveBitPackIndexer.unpack_batch(packed)
        assert np.all(orders == order)
        assert np.array_equal(unpacked[:, :order], words)


# ---- WordFrequencyEncoderSuite -------------------------------------------

def test_word_frequency_encoder():
    text = ["Winter coming", "Winter Winter is coming"]
    docs = Dataset.from_items([_tokens(t) for t in text])
    encoder = WordFrequencyEncoder().fit(docs)
    assert [encoder.apply(_tokens(t)) for t in text] == [[0, 1], [0, 0, 2, 1]]
    assert encoder.unigram_counts == {0: 3, 1: 2, 2: 1}
    assert encoder.apply(["hi"]) == [-1]


# ---- StupidBackoffSuite --------------------------------------------------

def _stupid_backoff_lm():
    data = ["Winter is coming", "Finals are coming",
            "Summer is coming really soon"]
    docs = [_tokens(d) for d in data]
    ngrams = NGramsCounts("noadd").apply_batch(
        Dataset.from_items(
            [NGramsFeaturizer(list(range(2, 6))).apply(d) for d in docs]
        )
    )
    unigrams = {
        gram[0]: c
        for gram, c in NGramsCounts().apply_batch(
            Dataset.from_items(
                [NGramsFeaturizer([1]).apply(d) for d in docs]
            )
        ).collect()
    }
    return StupidBackoffEstimator(unigrams).fit(ngrams)


def test_stupid_backoff_scores():
    lm = _stupid_backoff_lm()
    assert lm.score(("is", "coming")) == 2.0 / 2.0
    assert lm.score(("is", "coming", "really")) == 1.0 / 2.0
    assert lm.score(("is", "unseen-coming")) == 0.0
    assert lm.score(("is-unseen", "coming")) == \
        lm.alpha * 3.0 / lm.num_tokens


def test_stupid_backoff_fitted_scores_in_unit_interval():
    lm = _stupid_backoff_lm()
    assert lm.scores
    assert all(0.0 <= s <= 1.0 for s in lm.scores.values())


def test_packed_stupid_backoff_agrees_with_dict_path():
    """The packed-int64 array form scores bit-identically to the dict
    recursion on every fitted n-gram AND on out-of-corpus queries that
    exercise each backoff depth (unseen trigram → bigram → unigram →
    zero)."""
    import numpy as np

    from keystone_tpu.nodes.nlp.stupid_backoff import (
        PackedStupidBackoffModel,
    )
    from keystone_tpu.pipelines.stupid_backoff_pipeline import (
        synthetic_corpus,
        train_language_model,
    )

    lm = train_language_model(synthetic_corpus(80, seed=3), n=3)
    packed = PackedStupidBackoffModel.from_model(lm)

    queries = list(lm.ngram_counts)  # every fitted 2-/3-gram
    vocab = sorted(lm.unigram_counts)
    hi = max(vocab) + 7  # ids never seen in the corpus
    queries += [
        (v,) for v in vocab[:5]
    ] + [
        (hi,),                        # OOV unigram → score 0
        (hi, vocab[0]),               # backoff to seen unigram
        (vocab[0], hi),               # unseen current word
        (hi, hi + 1, hi + 2),         # fully OOV trigram (depth 2)
        (hi, vocab[0], vocab[1]) if len(vocab) > 1 else (hi, vocab[0]),
    ]
    want = np.asarray([lm.score(q) for q in queries])
    got = packed.score_batch(queries)
    assert np.allclose(got, want, rtol=1e-12, atol=0.0)
    assert packed.score(queries[0]) == want[0]


def test_packed_stupid_backoff_backoff_reads_unigram_table_only():
    """Dict-path parity in the corner the recursion makes subtle: a
    backed-off unigram reads ONLY the unigram table, even when the n-gram
    table also holds an order-1 entry for the same word (the pre-loop
    lookup consults the table; the in-loop one does not)."""
    import numpy as np

    from keystone_tpu.nodes.nlp.stupid_backoff import (
        PackedStupidBackoffModel,
        StupidBackoffModel,
    )

    ngram_counts = {(1, 2): 4, (2,): 7}
    unigram_counts = {1: 10, 2: 3}
    lm = StupidBackoffModel({}, ngram_counts, unigram_counts, 13)
    packed = PackedStupidBackoffModel.from_model(lm)
    for q in [(99, 2), (2,), (1, 2), (99,)]:
        assert packed.score(q) == lm.score(q), q


def test_packed_stupid_backoff_empty_and_zero_context():
    import numpy as np
    import pytest as _pytest

    from keystone_tpu.nodes.nlp.stupid_backoff import (
        PackedStupidBackoffModel,
        StupidBackoffModel,
    )

    # empty tables score 0 everywhere instead of crashing
    empty = PackedStupidBackoffModel(
        np.zeros(0, np.int64), np.zeros(0, np.int64),
        np.zeros(0, np.int64), np.zeros(0, np.int64), num_tokens=1,
    )
    assert empty.score((3, 4)) == 0.0
    # a fitted n-gram whose context is missing fails fast (dict-path
    # parity: ZeroDivisionError), not inf
    lm = StupidBackoffModel({}, {(1, 2): 4}, {2: 3}, 7)
    packed = PackedStupidBackoffModel.from_model(lm)
    with _pytest.raises(ZeroDivisionError):
        packed.score((1, 2))


def test_packed_stupid_backoff_rejects_high_orders():
    import pytest as _pytest

    from keystone_tpu.nodes.nlp.stupid_backoff import (
        PackedStupidBackoffModel,
    )

    lm = _stupid_backoff_lm()  # fits orders 2..5 over string tokens
    with _pytest.raises(ValueError):
        PackedStupidBackoffModel.from_model(lm)


# ---- sparse features -----------------------------------------------------

def test_term_frequency():
    tf = dict(TermFrequency().apply(["a", "b", "a", "a", "c", "b"]))
    assert tf == {"a": 3.0, "b": 2.0, "c": 1.0}
    tf_log = dict(
        TermFrequency(lambda x: x * 10).apply(["a", "b", "a"])
    )
    assert tf_log == {"a": 20.0, "b": 10.0}


def test_common_sparse_features_ordering():
    # count desc, ties broken by first appearance in the stream
    docs = Dataset.from_items([
        [("x", 1.0), ("y", 2.0)],
        [("y", 1.0), ("z", 5.0)],
        [("w", 1.0)],
    ])
    vec = CommonSparseFeatures(2).fit(docs)
    # y appears twice; x/z/w once each — x is earliest
    assert vec.feature_space == {"y": 0, "x": 1}
    row = vec.apply([("z", 9.0), ("y", 4.0), ("x", 3.0)])
    assert row == [(0, 4.0), (1, 3.0)]  # z filtered out


def test_all_sparse_features_first_appearance_order():
    docs = Dataset.from_items([
        [("b", 1.0)], [("a", 1.0), ("b", 2.0)], [("c", 3.0)],
    ])
    vec = AllSparseFeatures().fit(docs)
    assert vec.feature_space == {"b": 0, "a": 1, "c": 2}


def test_sparse_rows_numeric_oracle():
    rng = np.random.default_rng(0)
    n, d, k = 12, 37, 5
    dense = np.zeros((n, d), dtype=np.float32)
    rows = []
    for i in range(n):
        nnz = rng.integers(0, 9)
        idx = rng.choice(d, size=nnz, replace=False)
        vals = rng.standard_normal(nnz).astype(np.float32)
        dense[i, idx] = vals
        rows.append(list(zip(idx.tolist(), vals.tolist())))
    sr = SparseRows.from_pairs(rows, d)
    assert sr.shape == (n, d)
    np.testing.assert_allclose(np.asarray(sr.to_dense()), dense, atol=1e-6)

    W = rng.standard_normal((d, k)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(sr.matmul(W)), dense @ W, rtol=1e-4, atol=1e-5
    )
    R = rng.standard_normal((n, k)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(sr.rmatmul(R)), dense.T @ R, rtol=1e-4, atol=1e-5
    )
    y = rng.integers(0, 4, size=n)
    onehot = np.eye(4, dtype=np.float32)[y]
    np.testing.assert_allclose(
        np.asarray(sr.class_sums(onehot)), onehot.T @ dense,
        rtol=1e-4, atol=1e-5,
    )
    # hard-label fast path: one (n, m) scatter, same oracle
    np.testing.assert_allclose(
        np.asarray(sr.label_sums(y, 4)), onehot.T @ dense,
        rtol=1e-4, atol=1e-5,
    )


def test_sparse_rows_scipy_roundtrip():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.default_rng(1)
    mat = sp.random(20, 50, density=0.1, random_state=2, format="csr")
    sr = SparseRows.from_scipy(mat)
    np.testing.assert_allclose(
        np.asarray(sr.to_dense()), mat.toarray(), atol=1e-6
    )


# ---- sparse solver agreement (distributed-vs-local oracle family) --------

def _random_sparse_problem(seed=0, n=64, d=40, k=3):
    rng = np.random.default_rng(seed)
    dense = np.zeros((n, d), dtype=np.float32)
    rows = []
    for i in range(n):
        nnz = rng.integers(2, 10)
        idx = rng.choice(d, size=nnz, replace=False)
        vals = rng.uniform(0.5, 2.0, nnz).astype(np.float32)
        dense[i, idx] = vals
        rows.append(list(zip(idx.tolist(), vals.tolist())))
    sr = SparseRows.from_pairs(rows, d)
    y = rng.integers(0, k, size=n)
    return sr, dense, y


def test_naive_bayes_sparse_equals_dense():
    from keystone_tpu.nodes.learning import NaiveBayesEstimator

    sr, dense, y = _random_sparse_problem()
    m_sparse = NaiveBayesEstimator(3).fit(
        Dataset(sr, batched=True), Dataset.of(np.asarray(y))
    )
    m_dense = NaiveBayesEstimator(3).fit(
        Dataset.of(dense), Dataset.of(np.asarray(y))
    )
    np.testing.assert_allclose(
        np.asarray(m_sparse.theta), np.asarray(m_dense.theta),
        rtol=1e-5, atol=1e-6,
    )
    # sparse apply path agrees with dense scoring
    out_sparse = np.asarray(
        m_sparse.apply_batch(Dataset(sr, batched=True)).to_array()
    )
    out_dense = np.asarray(m_dense.trace_batch(dense))
    np.testing.assert_allclose(out_sparse, out_dense, rtol=1e-4, atol=1e-4)


def test_sparse_lbfgs_matches_dense_lbfgs():
    from keystone_tpu.nodes.learning import (
        DenseLBFGSwithL2,
        SparseLBFGSwithL2,
    )

    sr, dense, y = _random_sparse_problem(seed=3)
    B = np.eye(3, dtype=np.float32)[y] * 2 - 1
    m_sparse = SparseLBFGSwithL2(reg_param=0.1, num_iterations=60).fit(
        Dataset(sr, batched=True), Dataset.of(B)
    )
    m_dense = DenseLBFGSwithL2(reg_param=0.1, num_iterations=60).fit(
        Dataset.of(dense), Dataset.of(B)
    )
    np.testing.assert_allclose(
        np.asarray(m_sparse.W), np.asarray(m_dense.W), rtol=5e-2, atol=5e-3
    )
    # SparseLinearMapper apply == dense LinearMapper apply
    out_sparse = np.asarray(
        m_sparse.apply_batch(Dataset(sr, batched=True)).to_array()
    )
    np.testing.assert_allclose(
        out_sparse, dense @ np.asarray(m_sparse.W), rtol=1e-4, atol=1e-4
    )


def test_logistic_regression_sparse_learns():
    from keystone_tpu.nodes.learning import LogisticRegressionEstimator

    rng = np.random.default_rng(5)
    n, d = 200, 30
    y = rng.integers(0, 2, size=n)
    rows = []
    for i in range(n):
        # class signal: feature y*3 present with high value
        idx = [int(y[i]) * 3, int(rng.integers(6, d))]
        rows.append([(idx[0], 3.0), (idx[1], 1.0)])
    sr = SparseRows.from_pairs(rows, d)
    model = LogisticRegressionEstimator(2, num_iters=40).fit(
        Dataset(sr, batched=True), Dataset.of(np.asarray(y))
    )
    pred = np.asarray(
        model.apply_batch(Dataset(sr, batched=True)).to_array()
    )
    assert (pred == y).mean() > 0.95


def test_packed_stupid_backoff_rejects_oov_sentinel_keys():
    """score_packed must REFUSE keys carrying the -1 OOV sentinel
    (ADVICE r4 medium): pack_batch skips validation, the sentinel
    sign-extends to control bits 0xF, and the backoff arithmetic then
    aliases a REAL bigram key — a silently wrong score, not a miss. The
    dict-form model scores the same query correctly via backoff."""
    import numpy as np
    import pytest as _pytest

    from keystone_tpu.nodes.nlp.indexers import NaiveBitPackIndexer
    from keystone_tpu.nodes.nlp.stupid_backoff import (
        PackedStupidBackoffModel,
        StupidBackoffModel,
    )

    lm = StupidBackoffModel({}, {(5, 7): 4, (7,): 2}, {5: 3, 7: 6}, 11)
    packed = PackedStupidBackoffModel.from_model(lm)
    bad = NaiveBitPackIndexer.pack_batch(np.asarray([[-1, 5, 7]]), 3)
    with _pytest.raises(ValueError, match="OOV"):
        packed.score_packed(bad)
    # valid keys still score
    ok = np.asarray([NaiveBitPackIndexer.pack((5, 7))])
    assert packed.score_packed(ok)[0] > 0
