"""Oracle tests for PCA family, k-means++, GMM EM and Fisher Vectors —
cross-implementation (numpy/scipy) and distributed-vs-local agreement
(parity: PCASuite.scala:85, GMMSuite, FisherVectorSuite patterns)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.images.fisher_vector import (
    FisherVector,
    GMMFisherVectorEstimator,
)
from keystone_tpu.nodes.learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.nodes.learning.kmeans import (
    KMeansModel,
    KMeansPlusPlusEstimator,
)
from keystone_tpu.nodes.learning.pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
)


def _low_rank_data(rng, n=300, d=10, rank=3, noise=0.01):
    U = rng.standard_normal((n, rank))
    V = rng.standard_normal((rank, d))
    return (U @ V + noise * rng.standard_normal((n, d))).astype(np.float32)


def _subspace_agrees(P1, P2, atol=0.05):
    """Two orthonormal bases span the same subspace iff P1 P1ᵀ == P2 P2ᵀ."""
    return np.allclose(P1 @ P1.T, P2 @ P2.T, atol=atol)


def test_local_pca_matches_numpy_svd():
    rng = np.random.default_rng(0)
    X = _low_rank_data(rng)
    pca = PCAEstimator(3).fit(Dataset.of(X))
    P = np.asarray(pca.pca_mat)
    Xc = X - X.mean(axis=0)
    _, _, vt = np.linalg.svd(Xc, full_matrices=False)
    assert _subspace_agrees(P, vt[:3].T)
    # sign convention: each column's max-|coeff| entry is positive
    for j in range(3):
        assert P[np.abs(P[:, j]).argmax(), j] > 0


def test_distributed_pca_agrees_with_local():
    rng = np.random.default_rng(1)
    X = _low_rank_data(rng, n=512)
    local = np.asarray(PCAEstimator(3).fit(Dataset.of(X)).pca_mat)
    dist = np.asarray(DistributedPCAEstimator(3).fit(Dataset.of(X)).pca_mat)
    assert _subspace_agrees(local, dist)


def test_approximate_pca_agrees_with_local():
    rng = np.random.default_rng(2)
    X = _low_rank_data(rng, n=400, d=12, rank=4)
    local = np.asarray(PCAEstimator(4).fit(Dataset.of(X)).pca_mat)
    approx = np.asarray(
        ApproximatePCAEstimator(4, q=5).fit(Dataset.of(X)).pca_mat
    )
    assert _subspace_agrees(local, approx, atol=0.1)


def test_column_pca_on_descriptor_matrices():
    rng = np.random.default_rng(3)
    # 6 items of (d=8, m=50) descriptors
    items = rng.standard_normal((6, 8, 50)).astype(np.float32)
    t = LocalColumnPCAEstimator(4).fit(Dataset.of(items))
    assert isinstance(t, BatchPCATransformer)
    out = np.asarray(t.apply_batch(Dataset.of(items)).to_array())
    assert out.shape == (6, 4, 50)
    # chooser returns one of the two implementations and fit works
    chooser = ColumnPCAEstimator(4)
    t2 = chooser.fit(Dataset.of(items))
    assert np.asarray(t2.pca_mat).shape == (8, 4)


def test_kmeans_recovers_separated_clusters():
    rng = np.random.default_rng(4)
    centers = np.array([[0, 0], [10, 10], [-10, 10]], dtype=np.float32)
    X = np.concatenate(
        [c + 0.5 * rng.standard_normal((100, 2)) for c in centers]
    ).astype(np.float32)
    model = KMeansPlusPlusEstimator(3, 20, seed=0).fit(Dataset.of(X))
    means = np.asarray(model.means)
    # every true center has a learned center nearby
    for c in centers:
        assert np.min(np.linalg.norm(means - c, axis=1)) < 1.0
    assign = np.asarray(model.trace_batch(jnp.asarray(X)))
    assert assign.shape == (300, 3)
    np.testing.assert_allclose(assign.sum(axis=1), 1.0)
    # points in one true cluster share an assignment column
    assert (assign[:100].argmax(axis=1) == assign[0].argmax()).all()


def test_gmm_em_recovers_mixture():
    rng = np.random.default_rng(5)
    means_true = np.array([[0.0, 0.0], [6.0, 6.0]])
    X = np.concatenate(
        [
            means_true[0] + rng.standard_normal((200, 2)),
            means_true[1] + 0.5 * rng.standard_normal((200, 2)),
        ]
    ).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(
        2, max_iterations=50, seed=0
    ).fit_matrix(X)
    means = np.asarray(gmm.means).T  # (k, d)
    for c in means_true:
        assert np.min(np.linalg.norm(means - c, axis=1)) < 0.5
    w = np.asarray(gmm.weights)
    np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(w, [0.5, 0.5], atol=0.1)
    # posteriors: rows sum to 1, cluster structure respected
    q = np.asarray(gmm.trace_batch(jnp.asarray(X)))
    np.testing.assert_allclose(q.sum(axis=1), 1.0, rtol=1e-5)
    assert (q[:200].argmax(axis=1) == q[0].argmax()).all()


def test_fisher_vector_matches_naive_numpy():
    rng = np.random.default_rng(6)
    d, k, m = 4, 3, 30
    means = rng.standard_normal((d, k))
    variances = rng.uniform(0.5, 2.0, (d, k))
    weights = np.array([0.5, 0.3, 0.2])
    gmm = GaussianMixtureModel(means, variances, weights)
    X = rng.standard_normal((2, d, m)).astype(np.float32)

    fv = np.asarray(FisherVector(gmm).apply_batch(Dataset.of(X)).to_array())
    assert fv.shape == (2, d, 2 * k)

    for i in range(2):
        x = X[i].astype(np.float64)  # (d, m)
        q = np.asarray(gmm.trace_batch(jnp.asarray(x.T, dtype=jnp.float32)))
        s0 = q.mean(axis=0)
        s1 = x @ q / m
        s2 = (x * x) @ q / m
        fv1 = (s1 - means * s0) / (np.sqrt(variances) * np.sqrt(weights))
        fv2 = (s2 - 2 * means * s1 + (means ** 2 - variances) * s0) / (
            variances * np.sqrt(2 * weights)
        )
        expected = np.concatenate([fv1, fv2], axis=1)
        np.testing.assert_allclose(fv[i], expected, rtol=1e-2, atol=1e-2)


def test_gmm_fisher_vector_estimator_end_to_end():
    rng = np.random.default_rng(7)
    items = rng.standard_normal((4, 6, 40)).astype(np.float32)
    est = GMMFisherVectorEstimator(2, max_iterations=5, min_cluster_size=1)
    fv = est.fit(Dataset.of(items))
    out = np.asarray(fv.apply_batch(Dataset.of(items)).to_array())
    assert out.shape == (4, 6, 4)
    assert np.isfinite(out).all()


def test_gmm_csv_load_roundtrip(tmp_path):
    rng = np.random.default_rng(8)
    means = rng.standard_normal((4, 2))
    variances = rng.uniform(0.5, 1.5, (4, 2))
    weights = np.array([0.4, 0.6])
    np.savetxt(tmp_path / "m.csv", means, delimiter=",")
    np.savetxt(tmp_path / "v.csv", variances, delimiter=",")
    np.savetxt(tmp_path / "w.csv", weights, delimiter=",")
    gmm = GaussianMixtureModel.load(
        str(tmp_path / "m.csv"), str(tmp_path / "v.csv"), str(tmp_path / "w.csv")
    )
    np.testing.assert_allclose(np.asarray(gmm.means), means)
    assert gmm.k == 2 and gmm.dim == 4
