"""Nested-jit safety (utils/jit.py).

On the tunneled TPU backend this repo targets, a ``jax.jit``-decorated
helper CALLED INSIDE another jitted computation was observed to miscompile:
GMM posteriors from `_posteriors` flipped 0↔1 (an 18-llh-unit error against
a float64 oracle) when nested, while the same body inlined — or the
decorated function called at top level — was correct to f32 noise. The
pipeline-visible symptom: `jax.jit(fitted.trace_fn())` predicted different
labels than the eager executor on identical inputs.

``nestable_jit`` inlines the body when already tracing. These tests pin the
agreement contract on every backend (the CPU test backend never had the
bug, but the contract — traced == eager == f64 oracle — must hold
everywhere).
"""

import numpy as np

import jax
import jax.numpy as jnp

from keystone_tpu.nodes.learning.gmm import _posteriors
from keystone_tpu.utils.jit import nestable_jit


def _fixture(m=512, d=8, k=2, seed=0):
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((m, d)) * 5).astype(np.float32)
    # give one descriptor a large-magnitude coordinate like real PCA'd SIFT
    X[0, 0] = -36.6
    means = rng.standard_normal((k, d)).astype(np.float32)
    var = (2.0 * (1 + rng.random((k, d)))).astype(np.float32)
    w = np.array([0.7, 0.3], dtype=np.float32)
    return jnp.asarray(X), jnp.asarray(means), jnp.asarray(var), jnp.asarray(w)


def test_nestable_jit_inlines_under_trace():
    calls = {"n": 0}

    def body(x):
        calls["n"] += 1
        return x * 2.0

    f = nestable_jit(body)
    x = jnp.ones((4,))
    f(x)  # eager → jitted path traces body once
    n_after_eager = calls["n"]
    jax.jit(lambda x: f(x))(x)  # nested → body re-traced inline
    assert calls["n"] == n_after_eager + 1


def test_posteriors_agree_nested_vs_eager():
    X, means, var, w = _fixture()
    thr = 1e-4
    q_eager = np.asarray(_posteriors(X, means, var, w, thr))
    q_nested = np.asarray(
        jax.jit(lambda x: _posteriors(x, means, var, w, thr))(X)
    )
    np.testing.assert_allclose(q_nested, q_eager, atol=1e-4)


def test_posteriors_match_float64_oracle():
    X, means, var, w = _fixture()
    thr = 1e-4
    x64 = np.asarray(X, dtype=np.float64)
    m64 = np.asarray(means, dtype=np.float64)
    v64 = np.asarray(var, dtype=np.float64)
    w64 = np.asarray(w, dtype=np.float64)
    ll = np.stack(
        [
            -0.5 * np.sum((x64 - m64[j]) ** 2 / v64[j], axis=1)
            - 0.5 * np.sum(np.log(2 * np.pi * v64[j]))
            + np.log(w64[j])
            for j in range(len(w64))
        ],
        axis=1,
    )
    ll -= ll.max(axis=1, keepdims=True)
    q = np.exp(ll)
    q /= q.sum(axis=1, keepdims=True)
    q = np.where(q > thr, q, 0.0)
    q /= q.sum(axis=1, keepdims=True)

    for q_got in (
        np.asarray(_posteriors(X, means, var, w, thr)),
        np.asarray(jax.jit(lambda x: _posteriors(x, means, var, w, thr))(X)),
    ):
        np.testing.assert_allclose(q_got, q, atol=1e-3)
