"""Numerical-oracle tests for stats/util nodes vs numpy/scipy — the reference's
cross-implementation oracle family (SURVEY §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.stats import (
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
)
from keystone_tpu.nodes.util import (
    ClassLabelIndicators,
    MatrixVectorizer,
    MaxClassifier,
    MultiClassLabelIndicators,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)


def test_padded_fft_matches_numpy():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((4, 784)).astype(np.float32)
    out = np.asarray(PaddedFFT().apply_batch(Dataset.of(X)).to_array())
    # oracle: numpy full FFT of zero-padded input, real part of first half
    padded = np.zeros((4, 1024), dtype=np.float32)
    padded[:, :784] = X
    expected = np.real(np.fft.fft(padded, axis=1))[:, :512]
    assert out.shape == (4, 512)
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-2)


def test_padded_fft_pow2_input_not_padded():
    X = np.ones((2, 512), dtype=np.float32)
    out = PaddedFFT().apply_batch(Dataset.of(X)).to_array()
    assert out.shape == (2, 256)


def test_random_sign_node():
    node = RandomSignNode.create(16, seed=3)
    signs = np.asarray(node.signs)
    assert set(np.unique(signs)) <= {-1.0, 1.0}
    X = np.arange(16, dtype=np.float32)[None]
    np.testing.assert_allclose(
        np.asarray(node.apply_batch(Dataset.of(X)).to_array()), X * signs
    )


def test_linear_rectifier():
    X = np.array([[-1.0, 0.5, 2.0]], dtype=np.float32)
    out = LinearRectifier(0.0, 1.0).apply_batch(Dataset.of(X)).to_array()
    np.testing.assert_allclose(np.asarray(out), [[0.0, 0.0, 1.0]])


def test_cosine_random_features():
    rng = np.random.default_rng(1)
    W = rng.standard_normal((8, 5)).astype(np.float32)
    b = rng.standard_normal(8).astype(np.float32)
    X = rng.standard_normal((6, 5)).astype(np.float32)
    out = CosineRandomFeatures(W, b).apply_batch(Dataset.of(X)).to_array()
    np.testing.assert_allclose(
        np.asarray(out), np.cos(X @ W.T + b), rtol=1e-4, atol=1e-5
    )


def test_standard_scaler_matches_numpy():
    rng = np.random.default_rng(2)
    X = rng.standard_normal((40, 7)).astype(np.float32) * 3 + 1
    model = StandardScaler().fit(Dataset.of(X))
    out = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    expected = (X - X.mean(axis=0)) / X.std(axis=0, ddof=1)
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-3)


def test_standard_scaler_zero_variance_column():
    X = np.ones((10, 3), dtype=np.float32)
    model = StandardScaler().fit(Dataset.of(X))
    out = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_allclose(out, 0.0)


def test_normalize_rows_and_hellinger():
    X = np.array([[3.0, -4.0], [0.0, 0.0]], dtype=np.float32)
    out = np.asarray(NormalizeRows().apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_allclose(out[0], [0.6, -0.8], rtol=1e-5)
    np.testing.assert_allclose(out[1], [0.0, 0.0])
    h = np.asarray(
        SignedHellingerMapper().apply_batch(Dataset.of(X)).to_array()
    )
    np.testing.assert_allclose(h[0], [np.sqrt(3), -2.0], rtol=1e-5)


def test_class_label_indicators():
    y = np.array([0, 2], dtype=np.int32)
    out = np.asarray(
        ClassLabelIndicators(3).apply_batch(Dataset.of(y)).to_array()
    )
    np.testing.assert_allclose(out, [[1, -1, -1], [-1, -1, 1]])


def test_multi_class_label_indicators():
    out = np.asarray(MultiClassLabelIndicators(4).apply([1, 3]))
    np.testing.assert_allclose(out, [-1, 1, -1, 1])


def test_max_and_topk_classifier():
    X = np.array([[0.1, 0.9, 0.5], [2.0, -1.0, 0.0]], dtype=np.float32)
    preds = np.asarray(MaxClassifier().apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_array_equal(preds, [1, 0])
    topk = np.asarray(TopKClassifier(2).apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_array_equal(topk, [[1, 2], [0, 2]])


def test_vector_splitter_and_combiner_roundtrip():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((5, 10)).astype(np.float32)
    blocks = VectorSplitter(4).split_batch(X)
    assert [b.shape[1] for b in blocks] == [4, 4, 2]
    ds = Dataset(tuple(blocks), batched=True)
    out = np.asarray(VectorCombiner().apply_batch(ds).to_array())
    np.testing.assert_allclose(out, X)


def test_matrix_vectorizer_column_major():
    X = np.arange(6, dtype=np.float32).reshape(1, 2, 3)
    out = np.asarray(MatrixVectorizer().apply_batch(Dataset.of(X)).to_array())
    # column-major flatten of [[0,1,2],[3,4,5]] is [0,3,1,4,2,5]
    np.testing.assert_allclose(out, [[0, 3, 1, 4, 2, 5]])
