"""Tests for the dense featurizers (SIFT, DAISY, LCS, HOG) — the TPU-native
replacements for the reference's VLFeat/enceval native code and ported
MATLAB. Oracles: naive numpy reimplementations on tiny inputs plus
structural/invariance properties."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.nodes.images.daisy import DaisyExtractor
from keystone_tpu.nodes.images.hog import HogExtractor
from keystone_tpu.nodes.images.lcs import LCSExtractor
from keystone_tpu.nodes.images.sift import SIFTExtractor


def _rand_gray(rng, n=2, x=48, y=48):
    return rng.random((n, x, y, 1)).astype(np.float32)


# --------------------------------------------------------------- SIFT


def test_sift_shapes_and_range():
    rng = np.random.default_rng(0)
    imgs = _rand_gray(rng)
    ext = SIFTExtractor(step=4, bin_size=4, num_scales=2)
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    assert out.shape[1] == 128
    assert out.shape[0] == 2
    assert out.min() >= 0.0 and out.max() <= 255.0
    # descriptors quantized to integers
    np.testing.assert_allclose(out, np.round(out))


def test_sift_flat_image_zeroed_by_contrast_threshold():
    imgs = 0.5 * np.ones((1, 40, 40, 1), dtype=np.float32)
    out = np.asarray(SIFTExtractor(step=4, bin_size=4, num_scales=1).trace_batch(jnp.asarray(imgs)))
    np.testing.assert_allclose(out, 0.0)


def test_sift_translation_consistency():
    """Shifting the image by one grid step shifts descriptors accordingly —
    dense grid extraction is translation-covariant (up to edges)."""
    rng = np.random.default_rng(1)
    base = rng.random((56, 56)).astype(np.float32)
    step = 4
    ext = SIFTExtractor(step=step, bin_size=4, num_scales=1)
    a = np.asarray(ext.descriptors_batch(jnp.asarray(base[None, :, :, None])))
    shifted = np.roll(base, -step, axis=0)
    b = np.asarray(ext.descriptors_batch(jnp.asarray(shifted[None, :, :, None])))
    # grid is (gx, gy) x-major; dropping the first row of a's grid should
    # match b's all-but-last row
    extent = 4 * 4
    gx = len(range(0, 56 - extent + 1, step))
    gy = gx
    a_grid = a.reshape(gx, gy, 128)
    b_grid = b.reshape(gx, gy, 128)
    # interior rows only (borders differ from roll wraparound)
    close = np.isclose(a_grid[2:-1], b_grid[1:-2], atol=2.0)
    assert close.mean() > 0.95


# --------------------------------------------------------------- DAISY


def test_daisy_shape_and_normalization():
    rng = np.random.default_rng(2)
    imgs = _rand_gray(rng, n=2, x=48, y=48)
    ext = DaisyExtractor()
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    assert out.shape[0] == 2
    assert out.shape[1] == ext.feature_size == 8 * (8 * 3 + 1)
    # each histogram sub-block is unit-norm or zero
    h = ext.H
    for block in range(out.shape[1] // h):
        norms = np.linalg.norm(
            out[0, block * h : (block + 1) * h, :], axis=0
        )
        ok = (np.abs(norms - 1.0) < 1e-3) | (norms < 1e-6)
        assert ok.all()


def test_daisy_desc_count_matches_grid():
    imgs = np.zeros((1, 64, 52, 1), dtype=np.float32)
    ext = DaisyExtractor(pixel_border=16, stride=4)
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    nx = len(range(16, 64 - 16, 4))
    ny = len(range(16, 52 - 16, 4))
    assert out.shape[2] == nx * ny


# --------------------------------------------------------------- LCS


def test_lcs_matches_naive_numpy():
    rng = np.random.default_rng(3)
    img = rng.random((1, 40, 40, 2)).astype(np.float32)
    sp = 3
    ext = LCSExtractor(stride=6, stride_start=12, sub_patch_size=sp)
    out = np.asarray(ext.trace_batch(jnp.asarray(img)))

    # naive box mean/std ("same" zero-padded box filter of 1/sp per axis)
    def box_same(a):
        k = np.full(sp, 1.0 / sp)
        pad = (sp - 1) // 2, sp - 1 - (sp - 1) // 2
        ap = np.pad(a, ((pad[0], pad[1]), (0, 0)))
        col = np.stack(
            [ap[i : i + a.shape[0]] for i in range(sp)], axis=0
        ).transpose(1, 2, 0) @ k
        ap2 = np.pad(col, ((0, 0), (pad[0], pad[1])))
        return np.stack(
            [ap2[:, i : i + a.shape[1]] for i in range(sp)], axis=0
        ).transpose(1, 2, 0) @ k

    kx = list(range(12, 40 - 12, 6))
    offsets = list(range(-2 * sp + sp // 2 - 1, sp + sp // 2, sp))
    c, nx, ny = 0, offsets[0], offsets[1]
    m = box_same(img[0, :, :, c])
    sq = box_same(img[0, :, :, c] ** 2)
    sd = np.sqrt(np.maximum(sq - m * m, 0))
    # feature row index for (c=0, nx_idx=0, ny_idx=1, mean) = (0*16+0*4+1)*2
    row_mean = (0 * len(offsets) ** 2 + 0 * len(offsets) + 1) * 2
    desc0 = 0  # keypoint (kx[0], kx[0])
    np.testing.assert_allclose(
        out[0, row_mean, desc0], m[kx[0] + nx, kx[0] + ny], rtol=1e-4,
        atol=1e-4,
    )
    np.testing.assert_allclose(
        out[0, row_mean + 1, desc0], sd[kx[0] + nx, kx[0] + ny], rtol=1e-3,
        atol=1e-3,
    )
    assert out.shape == (1, len(offsets) ** 2 * 2 * 2, len(kx) ** 2)


# --------------------------------------------------------------- HOG


def _hog_naive(img, b):
    """Direct transcription of HogExtractor.scala for the oracle."""
    xd, yd, nc = img.shape
    n_x, n_y = round(xd / b), round(yd / b)
    hist = np.zeros(n_x * n_y * 18)
    uu = np.array([1.0, 0.9397, 0.766, 0.5, 0.1736,
                   -0.1736, -0.5, -0.766, -0.9397])
    vv = np.array([0.0, 0.342, 0.6428, 0.866, 0.9848,
                   0.9848, 0.866, 0.6428, 0.342])
    for x in range(1, n_x * b - 1):
        for y in range(1, n_y * b - 1):
            best = (-np.inf, 0, 0)
            for c in reversed(range(nc)):
                dx = img[x + 1, y, c] - img[x - 1, y, c]
                dy = img[x, y + 1, c] - img[x, y - 1, c]
                if dx * dx + dy * dy > best[0]:
                    best = (dx * dx + dy * dy, dx, dy)
            msq, dx, dy = best
            mag = math.sqrt(msq)
            bo, bi = 0.0, 0
            for o in range(9):
                dot = uu[o] * dy + vv[o] * dx
                if dot > bo:
                    bo, bi = dot, o
                elif -dot > bo:
                    bo, bi = -dot, o + 9
            xp = (x + 0.5) / b - 0.5
            yp = (y + 0.5) / b - 0.5
            ixp, iyp = math.floor(xp), math.floor(yp)
            vx0, vy0 = xp - ixp, yp - iyp
            for (cx, cy, w) in [
                (ixp, iyp, (1 - vx0) * (1 - vy0)),
                (ixp, iyp + 1, (1 - vx0) * vy0),
                (ixp + 1, iyp, vx0 * (1 - vy0)),
                (ixp + 1, iyp + 1, vx0 * vy0),
            ]:
                if 0 <= cx < n_x and 0 <= cy < n_y:
                    hist[cx + cy * n_x + bi * n_x * n_y] += w * mag
    return hist, n_x, n_y


def test_hog_hist_matches_naive():
    rng = np.random.default_rng(4)
    img = rng.random((16, 16, 3)).astype(np.float32)
    b = 4
    hist_naive, n_x, n_y = _hog_naive(img.astype(np.float64), b)

    ext = HogExtractor(b)
    out = np.asarray(ext.trace_batch(jnp.asarray(img[None])))
    nxf, nyf = n_x - 2, n_y - 2
    assert out.shape == (1, nxf * nyf, 32)

    # oracle the full feature pipeline from the naive hist
    hist = hist_naive
    norm = np.zeros(n_x * n_y)
    for o in range(9):
        v = hist[o * n_x * n_y : (o + 1) * n_x * n_y] + hist[
            (o + 9) * n_x * n_y : (o + 10) * n_x * n_y
        ]
        norm += v * v
    feats = np.zeros((nxf * nyf, 32))
    for x in range(nxf):
        for y in range(nyf):
            row = y + x * nyf

            def blocknorm(ox, oy):
                base = (y + oy) * n_x + (x + ox)
                return 1.0 / math.sqrt(
                    norm[base] + norm[base + 1] + norm[base + n_x]
                    + norm[base + n_x + 1] + 1e-4
                )

            n1, n2 = blocknorm(1, 1), blocknorm(0, 1)
            n3, n4 = blocknorm(1, 0), blocknorm(0, 0)
            t = [0.0] * 4
            for o in range(18):
                hv = hist[(y + 1) * n_x + (x + 1) + o * n_x * n_y]
                hs = [min(hv * nn, 0.2) for nn in (n1, n2, n3, n4)]
                feats[row, o] = 0.5 * sum(hs)
                for i in range(4):
                    t[i] += hs[i]
            for o in range(9):
                hv = (
                    hist[(y + 1) * n_x + (x + 1) + o * n_x * n_y]
                    + hist[(y + 1) * n_x + (x + 1) + (o + 9) * n_x * n_y]
                )
                feats[row, 18 + o] = 0.5 * sum(
                    min(hv * nn, 0.2) for nn in (n1, n2, n3, n4)
                )
            for i in range(4):
                feats[row, 27 + i] = 0.2357 * t[i]
    np.testing.assert_allclose(out[0], feats, rtol=1e-3, atol=1e-3)
