"""Tests for the dense featurizers (SIFT, DAISY, LCS, HOG) — the TPU-native
replacements for the reference's VLFeat/enceval native code and ported
MATLAB. Oracles: naive numpy reimplementations on tiny inputs plus
structural/invariance properties."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.nodes.images.daisy import DaisyExtractor
from keystone_tpu.nodes.images.hog import HogExtractor
from keystone_tpu.nodes.images.lcs import LCSExtractor
from keystone_tpu.nodes.images.sift import SIFTExtractor


def _rand_gray(rng, n=2, x=48, y=48):
    return rng.random((n, x, y, 1)).astype(np.float32)


# --------------------------------------------------------------- SIFT


def test_sift_shapes_and_range():
    rng = np.random.default_rng(0)
    imgs = _rand_gray(rng)
    ext = SIFTExtractor(step=4, bin_size=4, num_scales=2)
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    assert out.shape[1] == 128
    assert out.shape[0] == 2
    assert out.min() >= 0.0 and out.max() <= 255.0
    # descriptors quantized to integers
    np.testing.assert_allclose(out, np.round(out))


def test_sift_flat_image_zeroed_by_contrast_threshold():
    imgs = 0.5 * np.ones((1, 40, 40, 1), dtype=np.float32)
    out = np.asarray(SIFTExtractor(step=4, bin_size=4, num_scales=1).trace_batch(jnp.asarray(imgs)))
    np.testing.assert_allclose(out, 0.0)


def test_sift_translation_consistency():
    """Shifting the image by one grid step shifts descriptors accordingly —
    dense grid extraction is translation-covariant (up to edges)."""
    rng = np.random.default_rng(1)
    base = rng.random((56, 56)).astype(np.float32)
    step = 4
    ext = SIFTExtractor(step=step, bin_size=4, num_scales=1)
    a = np.asarray(ext.descriptors_batch(jnp.asarray(base[None, :, :, None])))
    shifted = np.roll(base, -step, axis=0)
    b = np.asarray(ext.descriptors_batch(jnp.asarray(shifted[None, :, :, None])))
    # grid is (gx, gy) x-major; dropping the first row of a's grid should
    # match b's all-but-last row
    extent = 4 * 4
    gx = len(range(0, 56 - extent + 1, step))
    gy = gx
    a_grid = a.reshape(gx, gy, 128)
    b_grid = b.reshape(gx, gy, 128)
    # interior rows only (borders differ from roll wraparound)
    close = np.isclose(a_grid[2:-1], b_grid[1:-2], atol=2.0)
    assert close.mean() > 0.95


# --------------------------------------------------------------- DAISY


def test_daisy_shape_and_normalization():
    rng = np.random.default_rng(2)
    imgs = _rand_gray(rng, n=2, x=48, y=48)
    ext = DaisyExtractor()
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    assert out.shape[0] == 2
    assert out.shape[1] == ext.feature_size == 8 * (8 * 3 + 1)
    # each histogram sub-block is unit-norm or zero
    h = ext.H
    for block in range(out.shape[1] // h):
        norms = np.linalg.norm(
            out[0, block * h : (block + 1) * h, :], axis=0
        )
        ok = (np.abs(norms - 1.0) < 1e-3) | (norms < 1e-6)
        assert ok.all()


def test_daisy_desc_count_matches_grid():
    imgs = np.zeros((1, 64, 52, 1), dtype=np.float32)
    ext = DaisyExtractor(pixel_border=16, stride=4)
    out = np.asarray(ext.trace_batch(jnp.asarray(imgs)))
    nx = len(range(16, 64 - 16, 4))
    ny = len(range(16, 52 - 16, 4))
    assert out.shape[2] == nx * ny


# --------------------------------------------------------------- LCS


def test_lcs_matches_naive_numpy():
    rng = np.random.default_rng(3)
    img = rng.random((1, 40, 40, 2)).astype(np.float32)
    sp = 3
    ext = LCSExtractor(stride=6, stride_start=12, sub_patch_size=sp)
    out = np.asarray(ext.trace_batch(jnp.asarray(img)))

    # naive box mean/std ("same" zero-padded box filter of 1/sp per axis)
    def box_same(a):
        k = np.full(sp, 1.0 / sp)
        pad = (sp - 1) // 2, sp - 1 - (sp - 1) // 2
        ap = np.pad(a, ((pad[0], pad[1]), (0, 0)))
        col = np.stack(
            [ap[i : i + a.shape[0]] for i in range(sp)], axis=0
        ).transpose(1, 2, 0) @ k
        ap2 = np.pad(col, ((0, 0), (pad[0], pad[1])))
        return np.stack(
            [ap2[:, i : i + a.shape[1]] for i in range(sp)], axis=0
        ).transpose(1, 2, 0) @ k

    kx = list(range(12, 40 - 12, 6))
    offsets = list(range(-2 * sp + sp // 2 - 1, sp + sp // 2, sp))
    c, nx, ny = 0, offsets[0], offsets[1]
    m = box_same(img[0, :, :, c])
    sq = box_same(img[0, :, :, c] ** 2)
    sd = np.sqrt(np.maximum(sq - m * m, 0))
    # feature row index for (c=0, nx_idx=0, ny_idx=1, mean) = (0*16+0*4+1)*2
    row_mean = (0 * len(offsets) ** 2 + 0 * len(offsets) + 1) * 2
    desc0 = 0  # keypoint (kx[0], kx[0])
    np.testing.assert_allclose(
        out[0, row_mean, desc0], m[kx[0] + nx, kx[0] + ny], rtol=1e-4,
        atol=1e-4,
    )
    np.testing.assert_allclose(
        out[0, row_mean + 1, desc0], sd[kx[0] + nx, kx[0] + ny], rtol=1e-3,
        atol=1e-3,
    )
    assert out.shape == (1, len(offsets) ** 2 * 2 * 2, len(kx) ** 2)


# --------------------------------------------------------------- HOG


def _hog_naive(img, b):
    """Direct transcription of HogExtractor.scala for the oracle."""
    xd, yd, nc = img.shape
    n_x, n_y = round(xd / b), round(yd / b)
    hist = np.zeros(n_x * n_y * 18)
    uu = np.array([1.0, 0.9397, 0.766, 0.5, 0.1736,
                   -0.1736, -0.5, -0.766, -0.9397])
    vv = np.array([0.0, 0.342, 0.6428, 0.866, 0.9848,
                   0.9848, 0.866, 0.6428, 0.342])
    for x in range(1, n_x * b - 1):
        for y in range(1, n_y * b - 1):
            best = (-np.inf, 0, 0)
            for c in reversed(range(nc)):
                dx = img[x + 1, y, c] - img[x - 1, y, c]
                dy = img[x, y + 1, c] - img[x, y - 1, c]
                if dx * dx + dy * dy > best[0]:
                    best = (dx * dx + dy * dy, dx, dy)
            msq, dx, dy = best
            mag = math.sqrt(msq)
            bo, bi = 0.0, 0
            for o in range(9):
                dot = uu[o] * dy + vv[o] * dx
                if dot > bo:
                    bo, bi = dot, o
                elif -dot > bo:
                    bo, bi = -dot, o + 9
            xp = (x + 0.5) / b - 0.5
            yp = (y + 0.5) / b - 0.5
            ixp, iyp = math.floor(xp), math.floor(yp)
            vx0, vy0 = xp - ixp, yp - iyp
            for (cx, cy, w) in [
                (ixp, iyp, (1 - vx0) * (1 - vy0)),
                (ixp, iyp + 1, (1 - vx0) * vy0),
                (ixp + 1, iyp, vx0 * (1 - vy0)),
                (ixp + 1, iyp + 1, vx0 * vy0),
            ]:
                if 0 <= cx < n_x and 0 <= cy < n_y:
                    hist[cx + cy * n_x + bi * n_x * n_y] += w * mag
    return hist, n_x, n_y


def test_hog_hist_matches_naive():
    rng = np.random.default_rng(4)
    img = rng.random((16, 16, 3)).astype(np.float32)
    b = 4
    hist_naive, n_x, n_y = _hog_naive(img.astype(np.float64), b)

    ext = HogExtractor(b)
    out = np.asarray(ext.trace_batch(jnp.asarray(img[None])))
    nxf, nyf = n_x - 2, n_y - 2
    assert out.shape == (1, nxf * nyf, 32)

    # oracle the full feature pipeline from the naive hist
    hist = hist_naive
    norm = np.zeros(n_x * n_y)
    for o in range(9):
        v = hist[o * n_x * n_y : (o + 1) * n_x * n_y] + hist[
            (o + 9) * n_x * n_y : (o + 10) * n_x * n_y
        ]
        norm += v * v
    feats = np.zeros((nxf * nyf, 32))
    for x in range(nxf):
        for y in range(nyf):
            row = y + x * nyf

            def blocknorm(ox, oy):
                base = (y + oy) * n_x + (x + ox)
                return 1.0 / math.sqrt(
                    norm[base] + norm[base + 1] + norm[base + n_x]
                    + norm[base + n_x + 1] + 1e-4
                )

            n1, n2 = blocknorm(1, 1), blocknorm(0, 1)
            n3, n4 = blocknorm(1, 0), blocknorm(0, 0)
            t = [0.0] * 4
            for o in range(18):
                hv = hist[(y + 1) * n_x + (x + 1) + o * n_x * n_y]
                hs = [min(hv * nn, 0.2) for nn in (n1, n2, n3, n4)]
                feats[row, o] = 0.5 * sum(hs)
                for i in range(4):
                    t[i] += hs[i]
            for o in range(9):
                hv = (
                    hist[(y + 1) * n_x + (x + 1) + o * n_x * n_y]
                    + hist[(y + 1) * n_x + (x + 1) + (o + 9) * n_x * n_y]
                )
                feats[row, 18 + o] = 0.5 * sum(
                    min(hv * nn, 0.2) for nn in (n1, n2, n3, n4)
                )
            for i in range(4):
                feats[row, 27 + i] = 0.2357 * t[i]
    np.testing.assert_allclose(out[0], feats, rtol=1e-3, atol=1e-3)


# ------------------------------------------------- SIFT numerical oracle


def _sift_naive_one_scale(g, bin_size, step):
    """Slow, readable per-descriptor SIFT for one scale on an already
    smoothed grayscale image ``g`` (float64). Independent of the batched
    implementation: explicit per-pixel gradient/orientation accumulation
    and per-bin box sums. Spec: VLFeat dense SIFT with flat window
    (VLFeat.cxx:40-210) — 8 orientation bins with linear interpolation,
    4x4 spatial bins of side bin_size, window = round(1.5*bin_size)
    box sums clipped to the image, L2-norm -> clamp 0.2 -> renorm.
    Returns (num_desc, 128) unquantized descriptors + pre-clamp norms."""
    X, Y = g.shape
    gx = np.zeros_like(g)
    gy = np.zeros_like(g)
    for x in range(X):
        for y in range(Y):
            if x == 0:
                gx[x, y] = g[1, y] - g[0, y]
            elif x == X - 1:
                gx[x, y] = g[X - 1, y] - g[X - 2, y]
            else:
                gx[x, y] = 0.5 * (g[x + 1, y] - g[x - 1, y])
            if y == 0:
                gy[x, y] = g[x, 1] - g[x, 0]
            elif y == Y - 1:
                gy[x, y] = g[x, Y - 1] - g[x, Y - 2]
            else:
                gy[x, y] = 0.5 * (g[x, y + 1] - g[x, y - 1])
    omaps = np.zeros((X, Y, 8))
    for x in range(X):
        for y in range(Y):
            mag = math.hypot(gx[x, y], gy[x, y])
            theta = math.atan2(gy[x, y], gx[x, y]) % (2 * math.pi)
            t = theta / (2 * math.pi) * 8
            t0 = int(math.floor(t))
            frac = t - t0
            omaps[x, y, t0 % 8] += mag * (1 - frac)
            omaps[x, y, (t0 + 1) % 8] += mag * frac

    window = max(1, int(round(bin_size * 1.5)))
    off = (window - bin_size) // 2
    extent = 4 * bin_size
    descs, norms = [], []
    for x0 in range(0, X - extent + 1, step):
        for y0 in range(0, Y - extent + 1, step):
            vec = np.zeros(128)
            for j in range(4):
                for i in range(4):
                    ax = min(max(x0 + i * bin_size - off, 0), X - window)
                    ay = min(max(y0 + j * bin_size - off, 0), Y - window)
                    box = omaps[ax : ax + window, ay : ay + window].sum((0, 1))
                    for t in range(8):
                        vec[t + 8 * i + 32 * j] = box[t]
            nrm = np.linalg.norm(vec)
            norms.append(nrm)
            v = vec / max(nrm, 1e-12)
            v = np.minimum(v, 0.2)
            v = v / max(np.linalg.norm(v), 1e-12)
            descs.append(v)
    return np.asarray(descs), np.asarray(norms)


def test_sift_one_scale_matches_naive_oracle():
    from keystone_tpu.nodes.images.sift import _sift_one_scale

    rng = np.random.default_rng(11)
    g = rng.random((26, 30)).astype(np.float32)
    bin_size, step = 4, 5
    want, want_norms = _sift_naive_one_scale(g.astype(np.float64), bin_size, step)
    got, got_norms = _sift_one_scale(jnp.asarray(g[None]), bin_size, step)
    got = np.asarray(got[0])
    got_norms = np.asarray(got_norms[0])
    assert got.shape == want.shape
    np.testing.assert_allclose(got_norms, want_norms, rtol=1e-4, atol=1e-5)
    # VLFeatSuite-style tolerance: >=99.5% of elements within 1/512 of the
    # quantization scale (VLFeatSuite.scala:34-51), plus a tight allclose
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)
    frac_close = np.mean(np.abs(got * 512 - want * 512) <= 1.0)
    assert frac_close >= 0.995


def test_sift_end_to_end_quantization_and_contrast():
    """Full extractor (1 scale) vs oracle incl. the gaussian pre-smooth,
    x512 short quantization and the 0.005 contrast zeroing."""
    from keystone_tpu.nodes.images.sift import (
        SIFTExtractor,
        _gaussian_kernel1d,
    )

    rng = np.random.default_rng(12)
    # flat (sub-threshold noise) everywhere except the bottom-right corner,
    # so descriptors anchored near (0,0) are entirely flat and must be
    # zeroed by the 0.005 contrast test while corner ones survive
    img = np.full((1, 32, 32, 1), 0.5, dtype=np.float32)
    img[0, :, :, 0] += 1e-5 * rng.random((32, 32)).astype(np.float32)
    img[0, 24:, 24:, 0] = rng.random((8, 8)).astype(np.float32)
    bin_size, step = 4, 4

    k = _gaussian_kernel1d(bin_size / 6.0).astype(np.float64)
    r = len(k) // 2
    g = img[0, :, :, 0].astype(np.float64)
    gp = np.pad(g, r, mode="edge")
    sm = np.zeros_like(g)
    for x in range(g.shape[0]):
        for y in range(g.shape[1]):
            sm[x, y] = (gp[x : x + 2 * r + 1, y + r] * k).sum()
    gp2 = np.pad(sm, r, mode="edge")
    sm2 = np.zeros_like(g)
    for x in range(g.shape[0]):
        for y in range(g.shape[1]):
            sm2[x, y] = (gp2[x + r, y : y + 2 * r + 1] * k).sum()

    want, norms = _sift_naive_one_scale(sm2, bin_size, step)
    want[norms <= 0.005] = 0.0
    want = np.minimum(np.floor(want * 512.0), 255.0)

    ext = SIFTExtractor(step=step, bin_size=bin_size, num_scales=1)
    got = np.asarray(ext.trace_batch(jnp.asarray(img)))[0].T  # (N, 128)
    assert got.shape == want.shape
    # integer-quantized values: exact match on >=99.5% (floor at bin edges
    # can differ by 1 from float32 vs float64 rounding)
    frac_equal = np.mean(np.abs(got - want) <= 1.0)
    assert frac_equal >= 0.995
    # the flat-region descriptors really got zeroed
    assert (norms <= 0.005).any()
    np.testing.assert_array_equal(got[norms <= 0.005], 0.0)


# ------------------------------------------------ DAISY numerical oracle


def _conv2_same_zero(a, kx, ky):
    """Naive zero-padded 'same' separable convolution (spec:
    ImageUtils.conv2D:226-344): correlate rows with kx then cols with ky."""
    X, Y = a.shape
    rx = (len(kx) - 1) // 2
    ry = (len(ky) - 1) // 2
    tmp = np.zeros_like(a)
    for x in range(X):
        for y in range(Y):
            s = 0.0
            for i, w in enumerate(kx):
                xi = x + i - rx
                if 0 <= xi < X:
                    s += a[xi, y] * w
            tmp[x, y] = s
    out = np.zeros_like(a)
    for x in range(X):
        for y in range(Y):
            s = 0.0
            for i, w in enumerate(ky):
                yi = y + i - ry
                if 0 <= yi < Y:
                    s += tmp[x, yi] * w
            out[x, y] = s
    return out


def _daisy_naive(g, T, Q, R, H, border, stride):
    """Slow readable DAISY (spec: DaisyExtractor.scala:28-201): Sobel-style
    gradients, H rectified directional maps, Q-level gaussian cascade with
    the sigma^2-increment kernels, ring sampling at radius R*(l+1)/Q with
    the reference's theta = 2pi(a-1)/T convention, per-histogram L2 norm."""
    conv_threshold = 1e-6
    sigma_sq = [(R * n / (2.0 * Q)) ** 2 for n in range(Q + 1)]
    kernels = []
    for t in [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]:
        rad = int(
            math.ceil(
                math.sqrt(
                    -2 * t * math.log(conv_threshold)
                    - t * math.log(2 * math.pi * t)
                )
            )
        )
        xs = np.arange(-rad, rad + 1, dtype=np.float64)
        kernels.append(np.exp(-(xs**2) / (2 * t)) / math.sqrt(2 * math.pi * t))

    f1 = np.array([1.0, 0.0, -1.0])
    f2 = np.array([1.0, 2.0, 1.0])
    ix = _conv2_same_zero(g, f1, f2)
    iy = _conv2_same_zero(g, f2, f1)

    X, Y = g.shape
    layers = []
    first = []
    for a in range(H):
        ang = 2 * math.pi * a / H
        m = np.maximum(math.cos(ang) * ix + math.sin(ang) * iy, 0.0)
        first.append(_conv2_same_zero(m, kernels[0], kernels[0]))
    layers.append(first)
    for l in range(1, Q):
        layers.append(
            [_conv2_same_zero(p, kernels[l], kernels[l]) for p in layers[l - 1]]
        )

    kx = list(range(border, X - border, stride))
    ky = list(range(border, Y - border, stride))
    feature_size = H * (T * Q + 1)

    def hist(level, px, py):
        h = np.array([layers[level][a][px, py] for a in range(H)])
        nrm = np.linalg.norm(h)
        return h / nrm if nrm > 1e-8 else np.zeros(H)

    out = np.zeros((feature_size, len(kx) * len(ky)))
    for xi, x in enumerate(kx):
        for yi, y in enumerate(ky):
            d = xi * len(ky) + yi
            out[:H, d] = hist(0, x, y)
            for l in range(Q):
                rad = R * (1.0 + l) / Q
                for a in range(T):
                    theta = 2 * math.pi * (a - 1) / T
                    dx = int(round(rad * math.sin(theta)))
                    dy = int(round(rad * math.cos(theta)))
                    px = min(max(x + dx, 0), X - 1)
                    py = min(max(y + dy, 0), Y - 1)
                    col = H + a * Q * H + l * H
                    out[col : col + H, d] = hist(l, px, py)
    return out


def test_daisy_matches_naive_oracle():
    rng = np.random.default_rng(13)
    g = rng.random((30, 30)).astype(np.float32)
    T, Q, R, H, border, stride = 4, 2, 6, 4, 8, 6
    want = _daisy_naive(g.astype(np.float64), T, Q, R, H, border, stride)
    ext = DaisyExtractor(
        daisy_t=T, daisy_q=Q, daisy_r=R, daisy_h=H,
        pixel_border=border, stride=stride,
    )
    got = np.asarray(ext.trace_batch(jnp.asarray(g[None, :, :, None])))[0]
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
