"""Behavioral parity with the reference's CoreNLPFeatureExtractorSuite
(src/test/scala/keystoneml/nodes/nlp/CoreNLPFeatureExtractorSuite.scala):
the same lemmatization / entity-extraction / n-gram assertions."""

from keystone_tpu.nodes.nlp.corenlp_lite import (
    CoreNLPFeatureExtractor,
    lemmatize,
)


def test_lemmatization():
    text = "jumping snakes lakes oceans hunted"
    tokens = set(CoreNLPFeatureExtractor(range(1, 4)).apply(text))
    for lemma in ("jump", "snake", "lake", "ocean", "hunt"):
        assert lemma in tokens
    for raw in ("jumping", "snakes", "lakes", "oceans", "hunted"):
        assert raw not in tokens


def test_entity_extraction():
    text = "John likes cake and he lives in Florida"
    tokens = set(CoreNLPFeatureExtractor(range(1, 4)).apply(text))
    assert "PERSON" in tokens
    assert "LOCATION" in tokens
    assert "John" not in tokens and "john" not in tokens
    assert "Florida" not in tokens and "florida" not in tokens


def test_1_2_3_grams():
    tokens = set(CoreNLPFeatureExtractor(range(1, 4)).apply("a b c d"))
    assert {"a", "b", "c", "d"} <= tokens
    assert {"a b", "b c", "c d"} <= tokens
    assert {"a b c", "b c d"} <= tokens


def test_grams_respect_sentence_boundaries():
    tokens = CoreNLPFeatureExtractor([2]).apply("a b. c d")
    assert "b c" not in tokens
    assert "a b" in tokens and "c d" in tokens


def test_lemmatizer_rules():
    assert lemmatize("running") == "run"
    assert lemmatize("making") == "make"
    assert lemmatize("cities") == "city"
    assert lemmatize("children") == "child"
    assert lemmatize("glasses") == "glass"
    assert lemmatize("sing") == "sing"  # no vowel before suffix: untouched
