"""Native hashing accelerator: bit-exactness against the pure-Python
reference implementation (which is itself bit-exact with the Scala
`.##`/seqHash family — the known-value tests live in test_nlp.py), plus
the fallback contract.
"""

import numpy as np
import pytest

from keystone_tpu import native
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.nlp.hashing import (
    HashingTF,
    NGramsHashingTF,
    java_string_hash,
)


def _random_tokens(rng, n):
    pieces = ["a", "bc", "ω", "λx", "naïve", "日本",
              "", "Z" * 40, "0", "\x00x"]
    return [
        "".join(rng.choice(pieces, size=rng.integers(1, 4)))
        for _ in range(n)
    ]


def _rows(sr):
    """padded SparseRows → per-row sorted (index, value) pair lists (the
    HashingTF counts are >= 1, so value != 0 exactly marks real entries)."""
    idx = np.asarray(sr.indices)
    val = np.asarray(sr.values)
    out = []
    for i in range(idx.shape[0]):
        keep = val[i] != 0
        out.append(
            sorted(zip(idx[i][keep].tolist(), val[i][keep].tolist()))
        )
    return out


needs_native = pytest.mark.skipif(
    native.get_lib() is None, reason="no C++ toolchain available"
)


@needs_native
def test_native_java_hash_bit_exact():
    rng = np.random.default_rng(0)
    tokens = _random_tokens(rng, 500) + ["", "a", "\x00"]
    got = native.java_string_hash_batch(tokens)
    want = np.asarray([java_string_hash(t) for t in tokens], dtype=np.int32)
    np.testing.assert_array_equal(got, want)


@needs_native
def test_native_hashing_tf_matches_python():
    rng = np.random.default_rng(1)
    docs = [
        _random_tokens(rng, int(rng.integers(0, 30))) for _ in range(40)
    ]
    tf = HashingTF(257)
    batch = tf.apply_batch(Dataset.from_items(docs))  # native path
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)  # pure-Python per-doc path


@needs_native
@pytest.mark.parametrize("orders", [(1, 1), (1, 3), (2, 3)])
def test_native_ngrams_hashing_tf_matches_python(orders):
    rng = np.random.default_rng(2)
    docs = [
        _random_tokens(rng, int(rng.integers(0, 12))) for _ in range(30)
    ]
    mn, mx = orders
    tf = NGramsHashingTF(list(range(mn, mx + 1)), 1023)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_non_string_terms_take_python_path():
    # int/tuple terms use scala_hash's type dispatch — the native batch
    # must decline, and results still match the per-doc path
    docs = [[1, 2, ("a", "b")], ["x", 3]]
    tf = HashingTF(97)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_fallback_when_native_disabled(monkeypatch):
    monkeypatch.setenv("KEYSTONE_NO_NATIVE", "1")
    assert native.get_lib() is None
    docs = [["a", "b", "a"], ["c"]]
    tf = NGramsHashingTF([1, 2], 64)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_lone_surrogate_tokens_fall_back_to_python():
    """Tokens with lone surrogates (surrogateescape-decoded bytes) cannot
    be UTF-32-encoded — the native batch must decline, not raise, and the
    ord()-based Python path must produce the row."""
    bad = b"caf\xff".decode("utf-8", errors="surrogateescape")
    docs = [["ok", bad], [bad]]
    tf = HashingTF(101)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)
