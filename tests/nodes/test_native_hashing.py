"""Native hashing accelerator: bit-exactness against the pure-Python
reference implementation (which is itself bit-exact with the Scala
`.##`/seqHash family — the known-value tests live in test_nlp.py), plus
the fallback contract.
"""

import numpy as np
import pytest

from keystone_tpu import native
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.nlp.hashing import (
    HashingTF,
    NGramsHashingTF,
    java_string_hash,
)


def _random_tokens(rng, n):
    pieces = ["a", "bc", "ω", "λx", "naïve", "日本",
              "", "Z" * 40, "0", "\x00x"]
    return [
        "".join(rng.choice(pieces, size=rng.integers(1, 4)))
        for _ in range(n)
    ]


def _rows(sr):
    """padded SparseRows → per-row sorted (index, value) pair lists (the
    HashingTF counts are >= 1, so value != 0 exactly marks real entries)."""
    idx = np.asarray(sr.indices)
    val = np.asarray(sr.values)
    out = []
    for i in range(idx.shape[0]):
        keep = val[i] != 0
        out.append(
            sorted(zip(idx[i][keep].tolist(), val[i][keep].tolist()))
        )
    return out


needs_native = pytest.mark.skipif(
    native.get_lib() is None, reason="no C++ toolchain available"
)


@needs_native
def test_native_java_hash_bit_exact():
    rng = np.random.default_rng(0)
    tokens = _random_tokens(rng, 500) + ["", "a", "\x00"]
    got = native.java_string_hash_batch(tokens)
    want = np.asarray([java_string_hash(t) for t in tokens], dtype=np.int32)
    np.testing.assert_array_equal(got, want)


@needs_native
def test_native_hashing_tf_matches_python():
    rng = np.random.default_rng(1)
    docs = [
        _random_tokens(rng, int(rng.integers(0, 30))) for _ in range(40)
    ]
    tf = HashingTF(257)
    batch = tf.apply_batch(Dataset.from_items(docs))  # native path
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)  # pure-Python per-doc path


@needs_native
@pytest.mark.parametrize("orders", [(1, 1), (1, 3), (2, 3)])
def test_native_ngrams_hashing_tf_matches_python(orders):
    rng = np.random.default_rng(2)
    docs = [
        _random_tokens(rng, int(rng.integers(0, 12))) for _ in range(30)
    ]
    mn, mx = orders
    tf = NGramsHashingTF(list(range(mn, mx + 1)), 1023)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_non_string_terms_take_python_path():
    # int/tuple terms use scala_hash's type dispatch — the native batch
    # must decline, and results still match the per-doc path
    docs = [[1, 2, ("a", "b")], ["x", 3]]
    tf = HashingTF(97)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_fallback_when_native_disabled(monkeypatch):
    monkeypatch.setenv("KEYSTONE_NO_NATIVE", "1")
    assert native.get_lib() is None
    docs = [["a", "b", "a"], ["c"]]
    tf = NGramsHashingTF([1, 2], 64)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


def test_lone_surrogate_tokens_fall_back_to_python():
    """Tokens with lone surrogates (surrogateescape-decoded bytes) cannot
    be UTF-32-encoded — the native batch must decline, not raise, and the
    ord()-based Python path must produce the row."""
    bad = b"caf\xff".decode("utf-8", errors="surrogateescape")
    docs = [["ok", bad], [bad]]
    tf = HashingTF(101)
    batch = tf.apply_batch(Dataset.from_items(docs))
    for row_pairs, doc in zip(_rows(batch.payload), docs):
        assert row_pairs == tf.apply(doc)


# ---------------------------------------------------------------------------
# Fused native text frontend (trim → lower → tokenize → first-seen ids)
# ---------------------------------------------------------------------------

_FRONTEND_DOCS = [
    "  Hello, World!  ",
    "+leading separators keep ONE empty token",
    "trailing separators drop!!!",
    "",
    "   ",
    "++--++",
    "a+b a_b a1b 0x7F under_score__double",
    "repeat repeat REPEAT rePEAT",
    "tab\tnewline\nmixed \x0b\x0c\r whitespace",
]


def _py_frontend_reference(docs, trim=True, lower=True):
    from keystone_tpu.nodes.nlp.packed_features import (
        _py_tokenize_raw,
        _token_ids,
    )

    vocab = {}
    ids = _token_ids(_py_tokenize_raw(docs, trim, lower), vocab, grow=True)
    return ids, vocab


def test_text_frontend_matches_python_chain():
    from keystone_tpu.native import text_frontend_batch

    res = text_frontend_batch(_FRONTEND_DOCS, [], grow=True)
    if res is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    ids_flat, tok_off, new_tokens = res
    want_ids, want_vocab = _py_frontend_reference(_FRONTEND_DOCS)
    got_ids = np.split(ids_flat, tok_off[1:-1])
    assert len(got_ids) == len(want_ids)
    for g, w in zip(got_ids, want_ids):
        np.testing.assert_array_equal(g, w)
    want_by_id = [None] * len(want_vocab)
    for t, i in want_vocab.items():
        want_by_id[i] = t
    assert new_tokens == want_by_id


def test_text_frontend_lookup_mode_marks_oov():
    from keystone_tpu.native import text_frontend_batch

    fit = text_frontend_batch(["alpha beta gamma"], [], grow=True)
    if fit is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    _, _, vocab_tokens = fit
    res = text_frontend_batch(
        ["beta unknown alpha"], vocab_tokens, grow=False
    )
    ids_flat, tok_off, new_tokens = res
    assert new_tokens == []
    np.testing.assert_array_equal(ids_flat, [1, -1, 0])


def test_text_frontend_declines_non_ascii():
    from keystone_tpu.native import text_frontend_batch

    assert text_frontend_batch(["héllo wörld"], [], grow=True) is None


def test_packed_features_raw_strings_match_token_list_path():
    """PackedTextFeatures fed raw strings (fused frontend) must produce
    IDENTICAL features to the same estimator fed the Python-tokenized
    lists — on fit-train apply AND on fresh serve docs, native or not."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.nlp.packed_features import (
        PackedTextFeatures,
        _py_tokenize_raw,
    )

    train_raw = _FRONTEND_DOCS * 3
    serve_raw = ["Hello under_score unknownTOKEN b a", "+a b!!"]
    est_raw = PackedTextFeatures([1, 2], 32, lambda x: 1)
    est_tok = PackedTextFeatures([1, 2], 32, lambda x: 1)
    v_raw = est_raw.fit(Dataset.from_items(train_raw))
    v_tok = est_tok.fit(
        Dataset.from_items(_py_tokenize_raw(train_raw, True, True))
    )
    np.testing.assert_array_equal(v_raw.selected, v_tok.selected)
    np.testing.assert_array_equal(v_raw.columns, v_tok.columns)
    for raw_docs, tok_docs in (
        (train_raw, _py_tokenize_raw(train_raw, True, True)),
        (serve_raw, _py_tokenize_raw(serve_raw, True, True)),
    ):
        r = v_raw.apply_batch(Dataset.from_items(raw_docs)).payload
        t = v_tok.apply_batch(Dataset.from_items(tok_docs)).payload
        np.testing.assert_array_equal(
            np.asarray(r.indices), np.asarray(t.indices)
        )
        np.testing.assert_array_equal(
            np.asarray(r.values), np.asarray(t.values)
        )


def test_packed_grams_unique_matches_numpy_path():
    """Native doc-local gram counting == the numpy corpus-lexsort path,
    including OOV (-1) drops, orders {1,2,3}, empty docs, and the
    first-emission uid order the feature selection tie-breaks on."""
    from keystone_tpu.native import packed_grams_unique
    from keystone_tpu.nodes.nlp.packed_features import (
        _corpus_grams,
        _per_doc_unique,
    )

    rng = np.random.default_rng(11)
    ids_list = [
        rng.integers(-1, 6, size=rng.integers(0, 30)).astype(np.int64)
        for _ in range(50)
    ] + [np.empty(0, dtype=np.int64)]
    for orders in ([1], [1, 2], [1, 2, 3], [2, 3]):
        res = packed_grams_unique(ids_list, orders)
        if res is None:
            import pytest

            pytest.skip("native toolchain unavailable")
        want = _per_doc_unique(*_corpus_grams(ids_list, orders))
        for got_a, want_a in zip(res, want):
            np.testing.assert_array_equal(got_a, want_a)


def test_text_frontend_strips_ascii_separator_controls():
    """\\x1c-\\x1f are str.strip() whitespace AND pure ASCII — the native
    trim must remove them like the Python spec does."""
    from keystone_tpu.native import text_frontend_batch

    res = text_frontend_batch(["\x1chello world\x1f"], [], grow=True)
    if res is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    _, _, new_tokens = res
    assert new_tokens == ["hello", "world"]


def test_packed_grams_unique_rejects_order_4_like_numpy():
    from keystone_tpu.native import packed_grams_unique

    assert packed_grams_unique([np.arange(5, dtype=np.int64)], [4]) is None
