"""PackedTextFeatures must be output-identical to the composed chain
NGramsFeaturizer → TermFrequency → CommonSparseFeatures it fuses
(including the (df desc, first-seen asc) ranking tie-breaks)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.nlp import NGramsFeaturizer
from keystone_tpu.nodes.nlp.packed_features import PackedTextFeatures
from keystone_tpu.nodes.stats import TermFrequency
from keystone_tpu.nodes.util import CommonSparseFeatures


def _random_docs(n_docs, vocab_size, seed, min_len=3, max_len=40):
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(vocab_size)]
    docs = []
    for _ in range(n_docs):
        ln = int(rng.integers(min_len, max_len))
        docs.append([words[i] for i in rng.integers(0, vocab_size, ln)])
    return docs


def _composed(docs_tr, docs_te, orders, k, tf):
    feats = [
        TermFrequency(tf).apply(NGramsFeaturizer(orders).apply(d))
        for d in docs_tr
    ]
    vec = CommonSparseFeatures(k).fit(Dataset.from_items(feats))
    te_feats = [
        TermFrequency(tf).apply(NGramsFeaturizer(orders).apply(d))
        for d in docs_te
    ]
    tr = vec.apply_batch(Dataset.from_items(feats)).payload
    te = vec.apply_batch(Dataset.from_items(te_feats)).payload
    return vec, tr, te


def _dense(sr):
    return np.asarray(sr.to_dense())


@pytest.mark.parametrize("orders,tf", [
    ([1, 2], lambda x: 1),
    ([1, 2, 3], None),
    ([2], lambda x: 1 + np.log(x)),
])
def test_packed_equals_composed(orders, tf):
    docs_tr = _random_docs(60, 30, seed=1)
    docs_te = _random_docs(25, 35, seed=2)  # some OOV tokens
    k = 100
    vec_c, tr_c, te_c = _composed(docs_tr, docs_te, orders, k, tf)
    est = PackedTextFeatures(orders, k, tf)
    vec_p = est.fit(Dataset.from_items(docs_tr))
    tr_p = vec_p.apply_batch(Dataset.from_items(docs_tr)).payload
    te_p = vec_p.apply_batch(Dataset.from_items(docs_te)).payload
    assert vec_p.num_features == vec_c.num_features
    np.testing.assert_allclose(_dense(tr_p), _dense(tr_c), rtol=1e-6)
    np.testing.assert_allclose(_dense(te_p), _dense(te_c), rtol=1e-6)


def test_packed_feature_identity_not_just_values():
    """Column assignment must match the composed chain exactly: the chosen
    grams get columns in rank order."""
    docs = [["a", "b", "a"], ["b", "c"], ["a", "b"]]
    tf = lambda x: 1
    feats = [
        TermFrequency(tf).apply(NGramsFeaturizer([1, 2]).apply(d))
        for d in docs
    ]
    vec_c = CommonSparseFeatures(4).fit(Dataset.from_items(feats))
    vec_p = PackedTextFeatures([1, 2], 4, tf).fit(Dataset.from_items(docs))
    # composed feature space: gram tuple -> column
    for gram, col in vec_c.feature_space.items():
        pairs = vec_p.apply(list(gram))
        # a doc that IS the gram contains it; find its column
        assert any(c == col for c, _ in pairs), (gram, col, pairs)


def test_apply_keeps_zero_tf_pairs():
    """Per-item apply must emit (col, 0.0) pairs exactly like
    SparseFeatureVectorizer.apply when the tf function maps a count to 0
    (e.g. log(1) = 0) — zeros are features here, not padding."""
    tf = lambda x: float(np.log(x))  # count 1 -> 0.0
    docs = [["a", "b", "a", "c"], ["b", "c", "c"]]
    feats = [
        TermFrequency(tf).apply(NGramsFeaturizer([1, 2]).apply(d))
        for d in docs
    ]
    vec_c = CommonSparseFeatures(20).fit(Dataset.from_items(feats))
    vec_p = PackedTextFeatures([1, 2], 20, tf).fit(Dataset.from_items(docs))
    for d, f in zip(docs, feats):
        want = vec_c.apply(f)
        got = vec_p.apply(d)
        assert [c for c, _ in got] == [c for c, _ in want]
        # f32 tf table vs the composed chain's f64 pair values
        np.testing.assert_allclose(
            [v for _, v in got], [v for _, v in want], rtol=1e-6
        )
        assert any(v == 0.0 for _, v in got)  # the case under test


def test_packed_rejects_high_orders_and_big_vocab():
    with pytest.raises(ValueError):
        PackedTextFeatures([1, 2, 3, 4], 10)
    est = PackedTextFeatures([1], 10)
    # vocab guard is enforced at fit time via the id width check
    from keystone_tpu.nodes.nlp import packed_features as pf

    old = pf._MAX_VOCAB
    pf._MAX_VOCAB = 3
    try:
        with pytest.raises(ValueError):
            est.fit(Dataset.from_items([["a", "b", "c", "d"]]))
    finally:
        pf._MAX_VOCAB = old


def test_packed_empty_docs_and_short_docs():
    docs = [["a"], [], ["a", "b", "c"]]
    est = PackedTextFeatures([1, 2], 10, lambda x: 1)
    vec = est.fit(Dataset.from_items(docs))
    sr = vec.apply_batch(Dataset.from_items(docs)).payload
    dense = _dense(sr)
    assert dense.shape[0] == 3
    assert dense[1].sum() == 0  # empty doc -> empty row


def test_fit_then_apply_on_generator_payload_serves_cache():
    """Docs without __len__ (generators, consumed by fit) can't be
    re-featurized — the fit→apply identity hit must serve the cached
    grams instead of crashing on len() or re-iterating exhausted
    iterators."""
    docs = _random_docs(20, 12, seed=7)
    baseline_vec = PackedTextFeatures([1, 2], 50, lambda x: 1)
    bv = baseline_vec.fit(Dataset.from_items(docs))
    want = _dense(bv.apply_batch(Dataset.from_items(docs)).payload)

    gen_ds = Dataset.from_items([iter(d) for d in docs])
    est = PackedTextFeatures([1, 2], 50, lambda x: 1)
    vec = est.fit(gen_ds)
    got = _dense(vec.apply_batch(gen_ds).payload)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_size_changing_mutation_refeaturizes():
    docs = [list(d) for d in _random_docs(10, 8, seed=9)]
    est = PackedTextFeatures([1], 30, lambda x: 1)
    vec = est.fit(Dataset.from_items(docs))
    ds = Dataset.from_items(docs)
    vec2 = PackedTextFeatures([1], 30, lambda x: 1).fit(ds)
    ds.payload[0].append(ds.payload[1][0])  # size-changing mutation
    got = _dense(vec2.apply_batch(ds).payload)
    fresh = _dense(vec2.apply_batch(Dataset.from_items(ds.payload)).payload)
    np.testing.assert_allclose(got, fresh, rtol=1e-6)
