"""Tier-1 enforcement of the repo invariants lint (tools/lint_invariants.py).

The headline test runs the real lint over ``keystone_tpu/`` — a PR that
reintroduces a silent broad except, a raw env truthiness read, or a bare
lock acquire fails CI here, with file:line attribution in the failure
message. The unit tests pin the rule semantics on synthetic sources so a
lint regression (rule silently matching nothing) is also caught.
"""

from __future__ import annotations

import os
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

from lint_invariants import Violation, lint_file, lint_tree  # noqa: E402


def _lint_source(tmp_path, source: str, rel: str = "keystone_tpu/mod.py"):
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    return lint_file(str(path), rel)


# ---------------------------------------------------------------------------
# the enforcement test
# ---------------------------------------------------------------------------


def test_package_passes_lint():
    violations = lint_tree(os.path.join(REPO_ROOT, "keystone_tpu"))
    assert not violations, "\n".join(str(v) for v in violations)


def test_tools_and_tests_parse():
    # the lint must at least parse its own tree without crashing
    assert isinstance(lint_tree(os.path.join(REPO_ROOT, "tools")), list)


# ---------------------------------------------------------------------------
# rule 1: silent broad excepts
# ---------------------------------------------------------------------------


def test_silent_broad_except_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except Exception:
            pass
    """)
    assert [v.rule for v in vs] == ["silent-except"]


def test_bare_except_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except:
            x = 2
    """)
    assert [v.rule for v in vs] == ["silent-except"]


def test_logged_except_passes(tmp_path):
    vs = _lint_source(tmp_path, """
        import logging
        logger = logging.getLogger(__name__)
        try:
            x = 1
        except Exception:
            logger.warning("boom", exc_info=True)
    """)
    assert vs == []


def test_reraising_except_passes(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except Exception:
            raise RuntimeError("wrapped")
    """)
    assert vs == []


def test_consumed_exception_passes(tmp_path):
    # `except Exception as e:` with e referenced: encoded, not swallowed
    vs = _lint_source(tmp_path, """
        def f(handle):
            try:
                x = 1
            except Exception as e:
                handle(e)
    """)
    assert vs == []


def test_narrow_except_exempt(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except ValueError:
            pass
    """)
    assert vs == []


def test_broad_tuple_except_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except (ValueError, Exception):
            pass
    """)
    assert [v.rule for v in vs] == ["silent-except"]


def test_silent_pragma_allows(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except Exception:  # lint: allow-silent -- teardown path
            pass
    """)
    assert vs == []


def test_pragma_without_justification_ignored(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except Exception:  # lint: allow-silent
            pass
    """)
    assert [v.rule for v in vs] == ["silent-except"]


def test_pragma_in_string_literal_ignored(tmp_path):
    vs = _lint_source(tmp_path, """
        MARKER = "lint: allow-silent -- not a comment"
        try:
            x = MARKER
        except Exception:
            pass
    """)
    # marker inside a string on another line must not suppress; and the
    # handler line itself carries no comment
    assert [v.rule for v in vs] == ["silent-except"]


# ---------------------------------------------------------------------------
# rule 2: env reads
# ---------------------------------------------------------------------------


def test_env_truthiness_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        import os
        if os.environ.get("KEYSTONE_THING"):
            x = 1
    """)
    assert [v.rule for v in vs] == ["env-truthiness"]


def test_env_boolop_and_getenv_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        import os
        a = os.environ.get("SOME_PATH") or None
        b = not os.getenv("OTHER")
    """)
    assert sorted(v.rule for v in vs) == ["env-truthiness", "env-truthiness"]


def test_keystone_knob_read_flagged_outside_utils(tmp_path):
    vs = _lint_source(tmp_path, """
        import os
        n = int(os.environ.get("KEYSTONE_WIDGETS", "4"))
    """)
    assert [v.rule for v in vs] == ["env-knob-routing"]


def test_non_keystone_value_read_allowed(tmp_path):
    vs = _lint_source(tmp_path, """
        import os
        home = os.environ.get("HOME", "/root")
    """)
    assert vs == []


def test_utils_package_exempt(tmp_path):
    vs = _lint_source(
        tmp_path,
        """
        import os
        if os.environ.get("KEYSTONE_THING"):
            x = 1
        """,
        rel="keystone_tpu/utils/helpers.py",
    )
    assert vs == []


# ---------------------------------------------------------------------------
# rule 3: bare acquire
# ---------------------------------------------------------------------------


def test_bare_acquire_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        import threading
        lock = threading.Lock()
        lock.acquire()
        try:
            x = 1
        finally:
            lock.release()
    """)
    assert [v.rule for v in vs] == ["bare-acquire"]


def test_with_lock_passes(tmp_path):
    vs = _lint_source(tmp_path, """
        import threading
        lock = threading.Lock()
        with lock:
            x = 1
    """)
    assert vs == []


def test_trylock_expression_allowed(tmp_path):
    # acquire() used as an expression (timeout polling) must branch on the
    # result; `with` cannot express it — allowed
    vs = _lint_source(tmp_path, """
        import threading
        lock = threading.Lock()
        if lock.acquire(timeout=0.1):
            try:
                x = 1
            finally:
                lock.release()
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# rule 4: fault-site observability
# ---------------------------------------------------------------------------


def _fault_tree(tmp_path, *, mapping, emit, reference=True):
    """A minimal keystone_tpu-shaped tree: one fault site, a SITE_INSTANTS
    mapping, and (optionally) an emission + reference of the site."""
    root = tmp_path / "keystone_tpu"
    (root / "faults").mkdir(parents=True)
    (root / "obs").mkdir()
    (root / "faults" / "plan.py").write_text(
        'SCAN_CHUNK = "scan.chunk"\n'
    )
    (root / "obs" / "flight.py").write_text(
        f"SITE_INSTANTS = {mapping!r}\n"
    )
    body = "def f(tracer):\n    pass\n"
    if reference:
        body += "from .faults.plan import SCAN_CHUNK\n"
    if emit:
        body += (
            "def g(tracer):\n"
            f'    tracer.instant({emit!r}, site=1)\n'
        )
    (root / "uses.py").write_text(body)
    return str(root)


def test_fault_site_without_mapping_flagged(tmp_path):
    root = _fault_tree(tmp_path, mapping={}, emit="retry.attempt")
    vs = [v for v in lint_tree(root) if v.rule == "fault-instant"]
    assert len(vs) == 1 and "no recovery instant" in vs[0].message
    assert vs[0].path.endswith("plan.py")


def test_mapped_but_never_emitted_instant_flagged(tmp_path):
    root = _fault_tree(
        tmp_path, mapping={"scan.chunk": "retry.attempt"}, emit=None
    )
    vs = [v for v in lint_tree(root) if v.rule == "fault-instant"]
    assert len(vs) == 1 and "never" in vs[0].message
    assert vs[0].path.endswith("flight.py")


def test_unreferenced_site_flagged(tmp_path):
    root = _fault_tree(
        tmp_path, mapping={"scan.chunk": "retry.attempt"},
        emit="retry.attempt", reference=False,
    )
    vs = [v for v in lint_tree(root) if v.rule == "fault-instant"]
    assert len(vs) == 1 and "never referenced" in vs[0].message


def test_mapped_emitted_and_referenced_passes(tmp_path):
    root = _fault_tree(
        tmp_path, mapping={"scan.chunk": "retry.attempt"},
        emit="retry.attempt",
    )
    assert [v for v in lint_tree(root) if v.rule == "fault-instant"] == []


def test_trees_without_the_contract_files_skip_rule4(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    assert [
        v for v in lint_tree(str(tmp_path)) if v.rule == "fault-instant"
    ] == []


# ---------------------------------------------------------------------------
# rule 5: counter coverage
# ---------------------------------------------------------------------------


def _counter_tree(tmp_path, *, known, rendered, inc_lines):
    """A minimal keystone_tpu-shaped tree for rule 5: a KNOWN_COUNTERS
    tuple in obs/prom.py, a format_status reading ``rendered`` names in
    cluster/router.py, and ``inc_lines`` of increment-site source."""
    root = tmp_path / "keystone_tpu"
    (root / "obs").mkdir(parents=True)
    (root / "cluster").mkdir()
    (root / "obs" / "prom.py").write_text(
        "KNOWN_COUNTERS = (\n"
        + "".join(f"    {n!r},\n" for n in known)
        + ")\n"
    )
    reads = "".join(f"    x += c.get({n!r}, 0)\n" for n in rendered)
    (root / "cluster" / "router.py").write_text(
        "def format_status(status):\n"
        "    c = status['counters']\n"
        "    x = 0\n" + reads + "    return str(x)\n"
    )
    (root / "sites.py").write_text(
        "def work(metrics, self_counters, who):\n"
        + "".join(f"    {line}\n" for line in inc_lines)
        or "    pass\n"
    )
    return str(root)


def _coverage(root):
    return [v for v in lint_tree(root) if v.rule == "counter-coverage"]


def test_known_counter_without_inc_site_flagged(tmp_path):
    root = _counter_tree(
        tmp_path, known=["submitted", "ghost"], rendered=[],
        inc_lines=['metrics.inc("submitted")'],
    )
    vs = _coverage(root)
    assert len(vs) == 1 and "'ghost'" in vs[0].message
    assert vs[0].path.endswith("prom.py")


def test_rendered_counter_without_inc_site_flagged(tmp_path):
    root = _counter_tree(
        tmp_path, known=[], rendered=["restarts"], inc_lines=[],
    )
    vs = _coverage(root)
    assert len(vs) == 1 and "'restarts'" in vs[0].message
    assert vs[0].path.endswith("router.py")


def test_dotted_family_covered_by_fstring_prefix(tmp_path):
    root = _counter_tree(
        tmp_path, known=["shed.", "tenant.served."], rendered=[],
        inc_lines=[
            'metrics.inc(f"shed.{who}")',
            'metrics.inc(f"tenant.served.{who}")',
        ],
    )
    assert _coverage(root) == []


def test_dotted_family_not_covered_by_exact_literal(tmp_path):
    # the family promises per-identity series; a literal "shed." inc
    # (no identity appended) doesn't produce them
    root = _counter_tree(
        tmp_path, known=["shed."], rendered=[],
        inc_lines=['metrics.inc("shed.")'],
    )
    vs = _coverage(root)
    assert len(vs) == 1 and "'shed.'" in vs[0].message


def test_augassign_counter_site_counts(tmp_path):
    # MetricsRegistry increments "batches" via _counters["batches"] += 1
    root = _counter_tree(
        tmp_path, known=["batches"], rendered=[],
        inc_lines=['self_counters["batches"] += 1'],
    )
    assert _coverage(root) == []


def test_rendered_counter_judged_once_when_also_known(tmp_path):
    root = _counter_tree(
        tmp_path, known=["completed"], rendered=["completed"], inc_lines=[],
    )
    vs = _coverage(root)
    assert len(vs) == 1 and vs[0].path.endswith("prom.py")


def test_trees_without_the_export_plane_skip_rule5(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    assert _coverage(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# rule 6: pickle containment in the cluster package
# ---------------------------------------------------------------------------


def test_pickle_in_cluster_module_flagged(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        def handle(payload):
            return pickle.loads(payload)
    """, rel="keystone_tpu/cluster/router.py")
    assert [v.rule for v in vs] == ["pickle-containment"]
    vs = _lint_source(tmp_path, """
        import pickle

        def ship(msg):
            return pickle.dumps(msg)
    """, rel="keystone_tpu/cluster/worker.py")
    assert [v.rule for v in vs] == ["pickle-containment"]


def test_pickle_in_wire_py_exempt(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        def encode(msg):
            return pickle.dumps(msg)
    """, rel="keystone_tpu/cluster/wire.py")
    assert vs == []


def test_pickle_outside_cluster_not_rule6s_business(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        def save(obj):
            return pickle.dumps(obj)
    """, rel="keystone_tpu/serving/engine.py")
    assert [v.rule for v in vs] == []


def test_pickle_pragma_on_call_line_allows(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        def ship_spec(spec):
            # boot path, not wire-frame data
            return pickle.dumps(spec)  # lint: allow-pickle -- boot spec
    """, rel="keystone_tpu/cluster/router.py")
    assert vs == []


def test_pickle_pragma_without_justification_ignored(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        def ship_spec(spec):
            return pickle.dumps(spec)  # lint: allow-pickle
    """, rel="keystone_tpu/cluster/router.py")
    assert [v.rule for v in vs] == ["pickle-containment"]


def test_pickle_pragma_on_wrong_line_ignored(tmp_path):
    vs = _lint_source(tmp_path, """
        import pickle

        # lint: allow-pickle -- the pragma must ride the CALL line
        def ship_spec(spec):
            return pickle.dumps(spec)
    """, rel="keystone_tpu/cluster/router.py")
    assert [v.rule for v in vs] == ["pickle-containment"]


def test_violation_str_carries_location(tmp_path):
    vs = _lint_source(tmp_path, """
        try:
            x = 1
        except Exception:
            pass
    """)
    (v,) = vs
    assert isinstance(v, Violation)
    assert f":{v.line}:" in str(v) and "silent-except" in str(v)
