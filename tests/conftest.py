"""Test harness: an 8-device CPU jax backend stands in for the cluster, the
same way Spark local[n] does in the reference's PipelineContext
(src/test/scala/keystoneml/workflow/PipelineContext.scala:9-25)."""

# Must happen before any test imports jax-using code. Force CPU even when
# the outer environment points at a real accelerator (JAX_PLATFORMS=axon):
# tests need the 8-device virtual mesh, and the single real chip can't
# provide it. Handles sitecustomize pre-importing jax.
from keystone_tpu.parallel.virtual import provision_virtual_devices

provision_virtual_devices(8)

# Belt to the provisioner's braces: the XLA:CPU thunk runtime's
# collective rendezvous can hang the whole suite on the oversubscribed
# virtual mesh (see provision_virtual_devices, which opts back into the
# legacy runtime); pinning dispatch synchronous additionally removes
# the async-dispatch reordering the same jaxlib era is known for.
# Compute results and thread-level overlap (scan pipelines, fleets)
# are unaffected — this is the TEST harness configuration.
import jax  # noqa: E402

jax.config.update("jax_cpu_enable_async_dispatch", False)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def pipeline_env():
    """Reset global pipeline state around every test (parity:
    PipelineContext.afterEach resetting PipelineEnv)."""
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.workflow.optimizers import clear_memo

    import keystone_tpu.cost as cost
    import keystone_tpu.faults as faults
    import keystone_tpu.obs.flight as flight

    env = PipelineEnv.get_or_create()
    env.reset()
    clear_memo()  # memoized plans pin operator objects; start each test cold
    cost.reset()  # profile store is env-var-memoized like the AOT cache
    faults.clear()  # no fault plan (or stale invocation counters) leaks
    flight.reset()  # each test judges its own bounded flight window
    yield env
    env.reset()
    clear_memo()
    cost.reset()
    faults.clear()
    flight.reset()
