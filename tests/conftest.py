"""Test harness: an 8-device CPU jax backend stands in for the cluster, the
same way Spark local[n] does in the reference's PipelineContext
(src/test/scala/keystoneml/workflow/PipelineContext.scala:9-25)."""

import os

# Must happen before jax is imported anywhere. Force CPU even when the outer
# environment points at a real accelerator (JAX_PLATFORMS=axon): tests need
# the 8-device virtual mesh, and the single real chip can't provide it.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
# Force exactly 8 virtual devices (tests assert on the mesh size); strip any
# pre-existing count the outer environment may have set.
flags = " ".join(
    f for f in flags.split() if "xla_force_host_platform_device_count" not in f
)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

# sitecustomize pre-imports jax before this conftest runs, so the env var
# alone is too late — update the live config as well.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def pipeline_env():
    """Reset global pipeline state around every test (parity:
    PipelineContext.afterEach resetting PipelineEnv)."""
    from keystone_tpu.workflow.env import PipelineEnv

    env = PipelineEnv.get_or_create()
    env.reset()
    yield env
    env.reset()
