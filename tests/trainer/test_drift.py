"""DriftMonitor unit coverage (ISSUE 14 satellite): seeded shifts trip
at the documented thresholds, stationary streams never do (the
false-positive bound), and the monitor works with and without labels."""

import numpy as np
import pytest

from keystone_tpu.linalg.accumulators import MomentsState
from keystone_tpu.trainer import DriftMonitor

D = 8


def _baseline(n=4096, seed=0, std=1.0):
    r = np.random.RandomState(seed)
    m = MomentsState()
    m.update((r.randn(n, D) * std + 2.0).astype(np.float64))
    return m


def _stream(monitor, chunks, rows, seed, shift=0.0, std=1.0, mse=None):
    r = np.random.RandomState(seed)
    for _ in range(chunks):
        monitor.observe(
            r.randn(rows, D) * std + 2.0 + shift,
            None if mse is None else mse,
        )


def test_stationary_stream_never_trips():
    """The false-positive bound: max-z over d=8 columns exceeds 6 with
    probability ~ 8·2Φ(−6) ≈ 1.6e-8 per check — a seeded stationary
    stream of 50 chunks must never trigger."""
    mon = DriftMonitor(_baseline(), min_rows=64)
    for i in range(50):
        _stream(mon, 1, 64, seed=100 + i)
        assert mon.should_refit() is None, mon.score()
    s = mon.score()
    assert s["z_max"] < 6.0
    assert s["var_ratio_max"] < 4.0


def test_mean_shift_trips_at_documented_threshold():
    """A 1σ mean shift over 256 recent rows gives z ≈ √256 = 16 ≫ 6;
    a 0.1σ shift over the same rows gives z ≈ 1.6 and must not trip."""
    mon = DriftMonitor(_baseline(), min_rows=256)
    _stream(mon, 4, 64, seed=1, shift=0.1)
    assert mon.should_refit() is None
    mon.rebaseline(_baseline())
    _stream(mon, 4, 64, seed=2, shift=1.0)
    reason = mon.should_refit()
    assert reason is not None and "mean shift" in reason
    assert mon.score()["z_max"] > 6.0


def test_variance_shift_trips():
    """std×3 ⇒ variance ratio ≈ 9 > 4 (mean unchanged, so this exercises
    the variance trigger, not the mean one); std×1.2 ⇒ ratio ≈ 1.44
    stays quiet."""
    mon = DriftMonitor(_baseline(), min_rows=256, z_threshold=50.0)
    _stream(mon, 4, 64, seed=3, std=1.2)
    assert mon.should_refit() is None
    mon.rebaseline(_baseline())
    _stream(mon, 4, 64, seed=4, std=3.0)
    reason = mon.should_refit()
    assert reason is not None and "variance" in reason


def test_min_rows_gates_every_trigger():
    mon = DriftMonitor(_baseline(), min_rows=256)
    _stream(mon, 1, 64, seed=5, shift=5.0)  # huge shift, tiny sample
    assert mon.should_refit() is None  # gated below min_rows
    _stream(mon, 3, 64, seed=5, shift=5.0)  # same stream, enough rows
    assert mon.should_refit() is not None


def test_residual_trigger_with_labels():
    """Residual ratio: warmup establishes the baseline level; a later
    sustained blow-up past the documented 2.0 ratio trips even though
    the feature moments stay stationary."""
    mon = DriftMonitor(_baseline(), min_rows=64, residual_warmup=2)
    _stream(mon, 2, 64, seed=6, mse=1.0)  # warmup: baseline mse = 1.0
    _stream(mon, 2, 64, seed=7, mse=1.1)
    assert mon.should_refit() is None
    _stream(mon, 4, 64, seed=8, mse=5.0)
    reason = mon.should_refit()
    assert reason is not None and "residual" in reason
    assert mon.score()["residual_ratio"] > 2.0


def test_works_without_labels():
    """Label-free appends: residual evidence stays None, the moment
    triggers carry the decision alone."""
    mon = DriftMonitor(_baseline(), min_rows=256)
    _stream(mon, 4, 64, seed=9)  # no mse ever observed
    assert mon.score()["residual_ratio"] is None
    assert mon.should_refit() is None
    _stream(mon, 4, 64, seed=10, shift=1.0)
    assert mon.should_refit() is not None  # moments alone trigger


def test_rebaseline_resets_recent_and_residual():
    mon = DriftMonitor(_baseline(), min_rows=64, residual_warmup=1)
    _stream(mon, 4, 64, seed=11, shift=1.0, mse=1.0)
    assert mon.should_refit() is not None
    mon.rebaseline(_baseline())
    s = mon.score()
    assert s["rows"] == 0 and s["residual_ratio"] is None
    assert mon.should_refit() is None


def test_empty_baseline_rejected():
    with pytest.raises(ValueError, match="fitted moments"):
        DriftMonitor(MomentsState())


def test_zero_baseline_residual_still_triggers():
    """A perfectly-fitting warmup (baseline mse exactly 0.0) must not
    disable the residual trigger — the ratio floors the denominator."""
    mon = DriftMonitor(_baseline(), min_rows=64, residual_warmup=2)
    _stream(mon, 2, 64, seed=20, mse=0.0)  # noise-free warmup
    _stream(mon, 4, 64, seed=21, mse=1.0)
    assert mon.score()["residual_ratio"] is not None
    reason = mon.should_refit()
    assert reason is not None and "residual" in reason
