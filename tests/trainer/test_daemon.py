"""TrainerDaemon: the closed loop under normal operation — promotes on
cadence and on drift, absorbed models match from-scratch fits, poisoned
batches quarantine, and the old model keeps serving throughout."""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.serving import ServingFleet
from keystone_tpu.trainer import ChunkLog, TrainerDaemon
from keystone_tpu.workflow.transformer import FunctionNode

D, K = 12, 3
_W_TRUE = np.random.RandomState(42).randn(D, K).astype(np.float32)


def make_chunk(n, seed, shift=0.0, scale=1.0):
    r = np.random.RandomState(seed)
    X = (r.randn(n, D) * scale + 1.0 + shift).astype(np.float32)
    Y = (np.tanh(X) @ _W_TRUE + 0.05 * r.randn(n, K)).astype(np.float32)
    return X, Y


def fit_initial(n=384, chunk_rows=64, lam=1e-2):
    X0, Y0 = make_chunk(n, 0)
    fitted = (
        FunctionNode(batch_fn=lambda A: jnp.tanh(A), label="feat")
        .to_pipeline()
        .and_then(
            LinearMapEstimator(lam=lam, snapshot=True),
            ChunkedDataset.from_array(X0, chunk_rows),
            Dataset.of(Y0),
        )
        .fit()
    )
    return fitted, X0, Y0


def make_fleet(fitted, replicas=2):
    return ServingFleet(
        fitted, replicas=replicas, buckets=(8,), datum_shape=(D,),
        max_wait_ms=1.0, max_queue=1024,
    )


def make_daemon(fleet, log, **kw):
    kw.setdefault("poll_interval_s", 0.01)
    kw.setdefault("refit_interval_s", 0.05)
    kw.setdefault("min_refit_chunks", 2)
    kw.setdefault("canary_fraction", 1.0)
    kw.setdefault("canary_batches", 1)
    kw.setdefault("canary_timeout_s", 3.0)
    kw.setdefault("canary_atol", 0.5)
    kw.setdefault("canary_rtol", 0.5)
    kw.setdefault("max_batch_retries", 0)
    return TrainerDaemon(fleet, log, **kw)


class Traffic:
    """Closed-loop submitters; every failure is recorded (the gates
    assert zero)."""

    def __init__(self, fleet, data, clients=3):
        self._fleet = fleet
        self._data = data
        self._stop = threading.Event()
        self.failures = []
        self._threads = [
            threading.Thread(target=self._run, args=(t, clients), daemon=True)
            for t in range(clients)
        ]

    def _run(self, tid, step):
        i = tid
        while not self._stop.is_set():
            try:
                self._fleet.predict(self._data[i % len(self._data)], timeout=15.0)
            except Exception as e:
                self.failures.append(repr(e))
            i += step

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


def wait_until(pred, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def model_state(fitted):
    ops = [
        op
        for op in fitted.graph.operators.values()
        if getattr(op, "solver_state", None) is not None
    ]
    assert len(ops) == 1
    return ops[0]


def test_cadence_promote_matches_from_scratch_fit():
    """Two appended chunks promote on the cadence trigger; the promoted
    model's solver state equals a from-scratch fit on the concatenated
    data (same chunk boundaries) to 1e-6, and the fleet now serves it."""
    fitted, X0, Y0 = fit_initial()
    fleet = make_fleet(fitted)
    log = ChunkLog()
    Xa, Ya = make_chunk(64, 1)
    Xb, Yb = make_chunk(64, 2)
    with fleet, Traffic(fleet, X0) as traffic:
        with make_daemon(fleet, log) as daemon:
            log.append(Xa, Ya)
            log.append(Xb, Yb)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
            promoted = daemon.fitted
        assert not traffic.failures
        assert fleet.fitted is promoted
        assert fleet.model_version == 2

    def factory():
        for i in range(0, 384, 64):
            yield X0[i : i + 64]
        yield Xa
        yield Xb

    scratch = (
        FunctionNode(batch_fn=lambda A: jnp.tanh(A), label="feat")
        .to_pipeline()
        .and_then(
            LinearMapEstimator(lam=1e-2, snapshot=True),
            ChunkedDataset(factory, 512, label="concat"),
            Dataset.of(np.concatenate([Y0, Ya, Yb])),
        )
        .fit()
    )
    got = model_state(promoted)
    want = model_state(scratch)
    assert np.max(np.abs(np.asarray(got.W) - np.asarray(want.W))) <= 1e-6
    assert got.solver_state.n == 512


def test_drift_trigger_refits_without_cadence():
    """With the cadence off, a seeded mean shift in the appended stream
    trips the drift trigger and promotes; a stationary stream does not."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted)
    log = ChunkLog()
    with fleet, Traffic(fleet, X0) as traffic:
        daemon = make_daemon(
            fleet, log,
            refit_interval_s=None,  # drift-only
            min_refit_chunks=1,
            canary_atol=5.0, canary_rtol=5.0,  # the shift moves outputs
            drift_kwargs={"min_rows": 128},
        )
        with daemon:
            # stationary appends: no trigger
            for s in (1, 2, 3):
                X, Y = make_chunk(64, 10 + s)
                log.append(X, Y)
            time.sleep(0.5)
            assert fleet.metrics.count("refits") == 0
            # shifted appends: z = |shift|/sqrt(var/n) ≈ 16 over 256 rows
            for s in (1, 2, 3, 4):
                X, Y = make_chunk(64, 20 + s, shift=1.0)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
        assert not traffic.failures


def test_poisoned_batch_rolls_back_parks_and_old_model_serves():
    """The quarantine discipline: a poisoned batch canary-fails, is
    parked after its bounded retries, the old executable keeps serving
    (bit-equal outputs), and a later good batch still promotes."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted)
    log = ChunkLog()
    probe = X0[:8]
    with fleet, Traffic(fleet, X0) as traffic:
        with make_daemon(fleet, log, max_batch_retries=1) as daemon:
            before = np.asarray(
                [fleet.predict(row, timeout=15.0) for row in probe]
            )
            log.append(
                np.full((64, D), 1e4, np.float32),
                np.full((64, K), -1e4, np.float32),
            )
            log.append(
                np.full((64, D), 1e4, np.float32),
                np.full((64, K), -1e4, np.float32),
            )
            assert wait_until(lambda: bool(daemon.parked_batches))
            assert daemon.parked_batches == [(0, 2)]
            # bounded retry: 1 retry allowed => exactly 2 rollbacks
            assert fleet.metrics.count("rollbacks") == 2
            assert fleet.metrics.count("refits") == 0
            assert fleet.model_version == 1
            after = np.asarray(
                [fleet.predict(row, timeout=15.0) for row in probe]
            )
            np.testing.assert_array_equal(before, after)
            # the loop is not poisoned: a good batch still promotes
            for s in (1, 2):
                X, Y = make_chunk(64, 30 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
        assert not traffic.failures


def test_metrics_and_staleness():
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    with fleet, Traffic(fleet, X0, clients=1) as traffic:
        with make_daemon(fleet, log) as daemon:
            stale_before = daemon.staleness_s()
            for s in (1, 2):
                X, Y = make_chunk(64, 40 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
            assert daemon.staleness_s() < stale_before + 30
            snap = fleet.metrics.snapshot()
        assert not traffic.failures
    assert snap["counters"]["absorbed_chunks"] == 2
    assert snap["counters"]["absorbed_rows"] == 128
    g = snap["gauges"]
    assert "drift_score" in g and "staleness_s" in g
    assert g["trainer_backlog"] == 0


def test_absorb_through_daemon_scans_only_new_chunks():
    """The O(new) work gate at the daemon level: a promoted refresh
    produced each of its chunks exactly once, and a SECOND refresh never
    re-produces the first one's chunks."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    with fleet:
        with make_daemon(fleet, log, canary_fraction=0.0) as daemon:
            for s in (1, 2):
                X, Y = make_chunk(64, 50 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
            assert log.production_counts == {0: 1, 1: 1}
            for s in (3, 4):
                X, Y = make_chunk(64, 50 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 2)
            assert log.production_counts == {0: 1, 1: 1, 2: 1, 3: 1}
    assert model_state(daemon.fitted).solver_state.n == 384 + 256
