"""ChunkLog: the trainer's append-only chunk feed."""

import numpy as np
import pytest

from keystone_tpu.trainer import ChunkLog


def _chunk(n=8, d=4, seed=0):
    r = np.random.RandomState(seed)
    return r.randn(n, d).astype(np.float32), r.randn(n, 2).astype(np.float32)


def test_append_and_tail_are_strictly_forward():
    log = ChunkLog()
    X0, Y0 = _chunk(seed=0)
    X1, Y1 = _chunk(seed=1)
    assert log.append(X0, Y0) == 0
    assert log.append(X1, Y1) == 1
    got = log.tail(0)
    assert [c.index for c in got] == [0, 1]
    assert log.tail(2) == []
    np.testing.assert_array_equal(log.tail(1)[0].data, X1)
    assert len(log) == 2
    assert log.total_rows == 16


def test_append_validates_shape_and_dtype():
    log = ChunkLog()
    X, Y = _chunk()
    log.append(X, Y)
    with pytest.raises(ValueError, match="item shape"):
        log.append(np.zeros((4, 9), np.float32))
    with pytest.raises(ValueError, match="dtype"):
        log.append(np.zeros((4, 4), np.float64))
    with pytest.raises(ValueError, match="rows"):
        log.append(np.zeros((4, 4), np.float32), np.zeros((3, 2)))
    with pytest.raises(ValueError, match="batched"):
        log.append(np.zeros((4,), np.float32))


def test_as_chunked_counts_productions_and_skips_without_producing():
    log = ChunkLog()
    parts = []
    for s in range(4):
        X, Y = _chunk(seed=s)
        parts.append((X, Y))
        log.append(X, Y)
    ds, labels = log.as_chunked(1, 4)
    assert len(ds) == 24
    chunks = list(ds.raw_chunks())
    np.testing.assert_array_equal(chunks[0], parts[1][0])
    np.testing.assert_array_equal(labels[:8], parts[1][1])
    assert log.production_counts == {1: 1, 2: 1, 3: 1}
    # checkpoint-resume semantics: skip=2 must NOT produce the prefix
    resumed = list(log.as_chunked(1, 4)[0].raw_chunks(skip=2))
    assert len(resumed) == 1
    np.testing.assert_array_equal(resumed[0], parts[3][0])
    assert log.production_counts == {1: 1, 2: 1, 3: 2}


def test_as_chunked_rejects_unlabeled_and_bad_ranges():
    log = ChunkLog()
    X, _ = _chunk()
    log.append(X)  # unlabeled append is fine for monitoring...
    with pytest.raises(ValueError, match="unlabeled"):
        log.as_chunked(0, 1)  # ...but cannot absorb
    with pytest.raises(ValueError, match="range"):
        log.as_chunked(0, 5)
