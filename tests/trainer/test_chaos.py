"""Chaos coverage for every trainer failure path (ISSUE 14 satellite):
each driven by a deterministic KEYSTONE_FAULTS-style plan, each ending
with the OLD model serving.

* kill during absorb → the daemon supervisor restarts the loop and the
  retried absorb RESUMES from the checkpoint, folding state
  bit-identical to an uninterrupted absorb, never re-producing the
  folded prefix;
* injected canary failure → rollback + bounded retry, then chunk-batch
  quarantine, old model still serving;
* replica kill mid-swap (inside an open canary window) → supervision
  restarts the replica re-pinned to the OLD version; after promotion
  there is zero version skew and zero failed requests.
"""

import time

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.trainer import ChunkLog, TrainerDaemon

from .test_daemon import (
    Traffic,
    fit_initial,
    make_chunk,
    make_daemon,
    make_fleet,
    model_state,
    wait_until,
)

D, K = 12, 3


def test_kill_during_absorb_resumes_bit_identical(tmp_path):
    """trainer.absorb=kill@2: the 3rd folded chunk kills the loop
    thread. The supervisor restarts it (budget), the retried absorb
    resumes from the checkpoint at chunk 2, and the promoted state is
    BIT-identical to an uninterrupted absorb — with the already-folded
    chunks 0 and 1 never produced again."""
    fitted, X0, _ = fit_initial()
    # the uninterrupted reference: same batch, no chaos
    batch = [make_chunk(32, 60 + s) for s in range(4)]
    ref_log = ChunkLog()
    for X, Y in batch:
        ref_log.append(X, Y)
    ds, labels = ref_log.as_chunked(0, 4)
    ref_state = model_state(fitted.absorb(ds, labels)).solver_state

    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    faults.install(faults.parse_plan("trainer.absorb=kill@2"))
    with fleet:
        daemon = make_daemon(
            fleet, log,
            min_refit_chunks=4,
            canary_fraction=0.0,
            checkpoint_dir=str(tmp_path),
            max_restarts=1,
        )
        with daemon:
            for X, Y in batch:
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
            got_state = model_state(daemon.fitted).solver_state
    assert faults.active_plan().injected.get("trainer.absorb") == 1
    faults.clear()
    assert fleet.metrics.count("trainer_restarts") == 1
    assert np.array_equal(got_state.gram, ref_state.gram)
    assert np.array_equal(got_state.cross, ref_state.cross)
    assert np.array_equal(got_state.sum_x, ref_state.sum_x)
    assert got_state.n == ref_state.n
    # the work gate: chunks 0/1 were folded before the kill and must
    # never re-produce; chunk 2 (killed mid-on_chunk) produced twice
    assert log.production_counts == {0: 1, 1: 1, 2: 2, 3: 1}


def test_injected_canary_failure_rolls_back_then_quarantines():
    """trainer.canary=transient@0,1 with ONE allowed retry: both
    attempts fail the canary gate, the batch parks, the old model keeps
    serving bit-equal outputs, and nothing was ever promoted."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    faults.install(faults.parse_plan("trainer.canary=transient@0,1"))
    probe = X0[:8]
    with fleet:
        before = np.asarray(
            [fleet.predict(row, timeout=15.0) for row in probe]
        )
        with make_daemon(fleet, log, max_batch_retries=1) as daemon:
            for s in (1, 2):
                X, Y = make_chunk(64, 70 + s)
                log.append(X, Y)
            assert wait_until(lambda: bool(daemon.parked_batches))
        after = np.asarray(
            [fleet.predict(row, timeout=15.0) for row in probe]
        )
    faults.clear()
    assert daemon.parked_batches == [(0, 2)]
    assert fleet.metrics.count("rollbacks") == 2
    assert fleet.metrics.count("batch_retries") == 1
    assert fleet.metrics.count("refits") == 0
    assert fleet.model_version == 1
    np.testing.assert_array_equal(before, after)


def test_injected_canary_failure_then_clean_retry_promotes():
    """trainer.canary=transient@0 only: the first attempt rolls back,
    the bounded retry passes, and the SAME batch promotes — rollback is
    reversible, not a poison-pill."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    faults.install(faults.parse_plan("trainer.canary=transient@0"))
    with fleet:
        with make_daemon(fleet, log, max_batch_retries=1) as daemon:
            for s in (1, 2):
                X, Y = make_chunk(64, 80 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
            assert not daemon.parked_batches
    faults.clear()
    assert fleet.metrics.count("rollbacks") == 1
    assert fleet.model_version == 2


def test_ingest_transient_faults_are_retried():
    """trainer.ingest=transient@0,1: two flaky tails are absorbed by the
    bounded ingest retry — the loop neither dies nor loses chunks."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=1)
    log = ChunkLog()
    faults.install(faults.parse_plan("trainer.ingest=transient@0,1"))
    with fleet:
        with make_daemon(fleet, log, canary_fraction=0.0) as daemon:
            for s in (1, 2):
                X, Y = make_chunk(64, 90 + s)
                log.append(X, Y)
            assert wait_until(lambda: fleet.metrics.count("refits") >= 1)
    faults.clear()
    assert fleet.metrics.count("ingest_failures") == 2
    assert fleet.metrics.count("trainer_restarts") == 0


def test_replica_kill_mid_swap_no_version_skew():
    """A replica dies INSIDE an open canary window: supervision requeues
    its work and restarts it pinned to the OLD version; the canary
    completes on live traffic, promotion flips every replica, and the
    rollout ends with zero skew and zero failed requests."""
    fitted, X0, _ = fit_initial()
    fleet = make_fleet(fitted, replicas=2)
    log = ChunkLog()
    with fleet:
        # a WIDE canary window: promotion must not outrun the kill that
        # is scheduled inside it (replica 1 executes a batch long before
        # 32 batches mirror)
        daemon = make_daemon(
            fleet, log,
            canary_batches=32, canary_timeout_s=45.0,
        )
        with daemon:
            for s in (1, 2):
                X, Y = make_chunk(64, 95 + s)
                log.append(X, Y)
            # the canary window is open once the shadow hook installs;
            # traffic starts only AFTER, so nothing can mirror (and
            # close the window) before the kill is scheduled inside it
            assert wait_until(
                lambda: any(r._shadow is not None for r in fleet.replicas),
                timeout=20.0,
            )
            faults.install(faults.parse_plan("replica.batch#1=kill@0"))
            with Traffic(fleet, X0) as traffic:
                assert wait_until(
                    lambda: fleet.metrics.count("restarts") >= 1,
                    timeout=20.0,
                )
                report = fleet.version_report()
                # re-pinned to the OLD model
                assert not report["skew"], report
                assert wait_until(
                    lambda: fleet.metrics.count("refits") >= 1,
                    timeout=30.0,
                )
        faults.clear()
        assert not traffic.failures
        report = fleet.version_report()
    assert report["version"] == 2
    assert not report["skew"], report
    assert {row["version"] for row in report["replicas"].values()} == {2}
    assert fleet.metrics.count("restarts") >= 1
