"""The --sweep-demo CLI path: λ grid as one merged DAG, absorb, hot swap."""

from keystone_tpu.__main__ import main


def test_sweep_demo_smoke(capsys):
    rc = main([
        "--sweep-demo", "--backend", "cpu",
        "--grid", "1e-2,1e-1", "--nTrain", "512", "--nAppend", "64",
        "--dim", "32", "--classes", "4", "--requests", "8",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "SWEEP PASS" in out
    assert "prefix_full_executions=1" in out
    assert "gram_reuse_solves=2" in out
    assert "failed=0" in out


def test_demo_flag_prefixes_stay_unambiguous():
    """--serve… and --sweep… abbreviations must route to the right demo;
    the shared prefix --s matches neither and errors out in argparse."""
    import pytest

    with pytest.raises(SystemExit):
        main(["--s", "--backend", "cpu"])
