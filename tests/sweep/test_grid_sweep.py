"""GridSweep: a λ/config grid fit as ONE merged DAG — the shared featurize
prefix executes exactly once, Gram/TSQR families solve their whole λ group
from one accumulation pass, and every member matches its independently-fit
counterpart."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    TSQRLeastSquaresEstimator,
)
from keystone_tpu.nodes.util import MaxClassifier
from keystone_tpu.sweep import GridSweep, SweepResult
from keystone_tpu.sweep.grid import expand_grid
from keystone_tpu.workflow.transformer import Transformer

LAMS = [1e-3, 1e-2, 1e-1, 1.0]


class CountingFeaturize(Transformer):
    """A featurize stage that counts FULL-SIZE executions (optimizer
    sampling runs on ~24-row probes and must not trip the gate)."""

    def __init__(self, full_rows: int):
        self.full_rows = full_rows
        self.full_calls = 0

    def trace_batch(self, X):
        if int(X.shape[0]) == self.full_rows:
            self.full_calls += 1
        return jnp.tanh(X) * 2.0


def _problem(n=600, d=32, k=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) + 0.5
    W = rng.normal(size=(d, k)).astype(np.float32)
    Y = ((np.tanh(X) * 2.0) @ W + 0.05 * rng.normal(size=(n, k)) + 1.0)
    return X, Y.astype(np.float32)


def _model_W(fitted):
    ws = [
        np.concatenate([np.asarray(w) for w in op.xs], axis=0)
        if hasattr(op, "xs") else np.asarray(op.W)
        for op in fitted.graph.operators.values()
        if hasattr(op, "W") or hasattr(op, "xs")
    ]
    assert len(ws) == 1
    return ws[0]


# ---------------------------------------------------------------------------
# grid expansion
# ---------------------------------------------------------------------------


def test_expand_grid_cartesian_deterministic():
    pts = expand_grid({"lam": [1, 2], "dim": ["a", "b", "c"]})
    assert len(pts) == 6
    assert pts[0] == {"lam": 1, "dim": "a"}
    assert pts[-1] == {"lam": 2, "dim": "c"}
    # key-then-value order is stable
    assert pts == expand_grid({"lam": [1, 2], "dim": ["a", "b", "c"]})


def test_expand_grid_rejects_empty():
    with pytest.raises(ValueError):
        expand_grid({})
    with pytest.raises(ValueError):
        expand_grid({"lam": []})


# ---------------------------------------------------------------------------
# the tentpole gates: prefix-once + grouped solves + parity
# ---------------------------------------------------------------------------


def test_lambda_sweep_prefix_executes_once_and_members_match():
    """The acceptance gate: a G-member λ sweep executes the shared
    featurize prefix exactly once, reuses one Gram accumulation for all G
    solves, and every member's model is within 1e-6 of (here: identical
    to) its independently-fit counterpart."""
    X, Y = _problem()
    feat = CountingFeaturize(len(X))
    prefix = feat.to_pipeline()
    res = GridSweep(
        prefix,
        lambda lam: LinearMapEstimator(lam=lam),
        {"lam": LAMS},
        Dataset.of(X),
        Dataset.of(Y),
    ).fit()

    assert isinstance(res, SweepResult) and len(res) == len(LAMS)
    assert feat.full_calls == 1, "shared prefix must execute exactly once"
    assert res.stats["groups"] == 1
    assert res.stats["gram_reuse_solves"] == len(LAMS)

    for member in res:
        lam = member.params["lam"]
        independent = prefix.and_then(
            LinearMapEstimator(lam=lam, snapshot=True),
            Dataset.of(X), Dataset.of(Y),
        ).fit()
        dW = np.max(np.abs(_model_W(member.fitted) - _model_W(independent)))
        assert dW <= 1e-6, (lam, dW)

    # distinct λ produce distinct models (the solves really happened per λ)
    assert (
        np.max(np.abs(_model_W(res.members[0].fitted)
                      - _model_W(res.members[-1].fitted))) > 1e-3
    )


def test_sweep_members_serve_independently():
    """Extracted members are ordinary FittedPipelines: applying one runs
    prefix + its model, matching the independent fit's predictions."""
    X, Y = _problem()
    prefix = CountingFeaturize(len(X)).to_pipeline()
    res = GridSweep(
        prefix, lambda lam: LinearMapEstimator(lam=lam), {"lam": [1e-2, 1e-1]},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    m = res.fitted_for(lam=1e-1)
    independent = prefix.and_then(
        LinearMapEstimator(lam=1e-1, snapshot=True),
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    got = np.asarray(m.apply(Dataset.of(X[:48])).to_array())
    want = np.asarray(independent.apply(Dataset.of(X[:48])).to_array())
    np.testing.assert_allclose(got, want, atol=1e-6)
    with pytest.raises(KeyError):
        res.fitted_for(lam=123.0)


def test_multi_axis_grid_forms_separate_families():
    """A λ × snapshot grid: the two snapshot settings are different
    ``grid_family`` keys, so the sweep forms two Gram groups — and every
    member still matches its independent fit."""
    X, Y = _problem()
    res = GridSweep(
        CountingFeaturize(len(X)).to_pipeline(),
        lambda lam, snapshot: LinearMapEstimator(lam=lam, snapshot=snapshot),
        {"lam": [1e-2, 1.0], "snapshot": [False, True]},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    assert len(res) == 4
    assert res.stats["groups"] == 2
    assert res.stats["gram_reuse_solves"] == 4
    ref = {}
    for member in res:
        W = _model_W(member.fitted)
        lam = member.params["lam"]
        # same λ, different snapshot setting → same solve
        if lam in ref:
            np.testing.assert_allclose(W, ref[lam], atol=1e-6)
        ref[lam] = W


def test_tsqr_family_grid_matches_independent_fits():
    """The TSQR family folds per-λ √λ·I rows into one shared R factor;
    members must match independent TSQR fits (same augmented algebra)."""
    X, Y = _problem()
    prefix = CountingFeaturize(len(X)).to_pipeline()
    res = GridSweep(
        prefix,
        lambda lam: TSQRLeastSquaresEstimator(lam=lam),
        {"lam": LAMS},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    assert res.stats["grouped_solves"].get("tsqr") == len(LAMS)
    for member in res:
        independent = prefix.and_then(
            TSQRLeastSquaresEstimator(lam=member.params["lam"]),
            Dataset.of(X), Dataset.of(Y),
        ).fit()
        dW = np.max(np.abs(_model_W(member.fitted) - _model_W(independent)))
        assert dW <= 1e-5, (member.params, dW)


def test_ungrouped_members_still_share_the_prefix():
    """Estimators without a grid family (here: cold BCD — its grid hook
    only engages under warm_start) fit independently, but the merged DAG
    still executes the featurize prefix once and every member matches its
    independent fit bit-for-bit (same code path, same featurized input)."""
    X, Y = _problem(n=512, d=32)
    feat = CountingFeaturize(len(X))
    prefix = feat.to_pipeline()
    res = GridSweep(
        prefix,
        lambda lam: BlockLeastSquaresEstimator(16, num_iter=2, lam=lam),
        {"lam": [1e-2, 1e-1, 1.0]},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    assert feat.full_calls == 1
    assert res.stats["groups"] == 0
    for member in res:
        independent = prefix.and_then(
            BlockLeastSquaresEstimator(16, num_iter=2, lam=member.params["lam"]),
            Dataset.of(X), Dataset.of(Y),
        ).fit()
        got = np.asarray(member.fitted.apply(Dataset.of(X[:32])).to_array())
        want = np.asarray(independent.apply(Dataset.of(X[:32])).to_array())
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_warm_started_bcd_grid():
    """GridSweep(warm_start=True) groups the BCD members: λ's solve in
    ascending order, each warm-started from its neighbor. Warm iterates
    differ from cold ones but descend the same objective — so each member
    must fit at least as well as its cold counterpart (up to noise)."""
    X, Y = _problem(n=512, d=32)
    prefix = CountingFeaturize(len(X)).to_pipeline()
    res = GridSweep(
        prefix,
        lambda lam: BlockLeastSquaresEstimator(16, num_iter=2, lam=lam),
        {"lam": [1e-2, 1e-1, 1.0]},
        Dataset.of(X), Dataset.of(Y),
        warm_start=True,
    ).fit()
    assert res.stats["groups"] == 1
    assert res.stats["warm_starts"] == 2
    feats = np.tanh(X) * 2.0
    for member in res:
        lam = member.params["lam"]
        cold = prefix.and_then(
            BlockLeastSquaresEstimator(16, num_iter=2, lam=lam),
            Dataset.of(X), Dataset.of(Y),
        ).fit()
        def objective(fitted):
            pred = np.asarray(fitted.apply(Dataset.of(X)).to_array())
            W = _model_W(fitted)
            return (
                float(np.sum((pred - Y) ** 2))
                + lam * float(np.sum(W * W))
            )
        assert objective(member.fitted) <= objective(cold) * 1.02, lam


def test_chunked_data_sweep_streams_once():
    """Out-of-core sweep: the Gram family accumulates the chunk stream
    once for all members, matching chunked independent fits."""
    X, Y = _problem(n=500)
    res = GridSweep(
        None,
        lambda lam: LinearMapEstimator(lam=lam),
        {"lam": [1e-2, 1.0]},
        ChunkedDataset.from_array(X, 128),
        Dataset.of(Y),
    ).fit()
    assert res.stats["gram_reuse_solves"] == 2
    for member in res:
        independent = LinearMapEstimator(
            lam=member.params["lam"], snapshot=True
        ).with_data(ChunkedDataset.from_array(X, 128), Dataset.of(Y)).fit()
        dW = np.max(np.abs(_model_W(member.fitted) - _model_W(independent)))
        assert dW <= 1e-6, (member.params, dW)


def test_final_stage_is_appended_to_every_member():
    X, Y = _problem()
    res = GridSweep(
        CountingFeaturize(len(X)).to_pipeline(),
        lambda lam: LinearMapEstimator(lam=lam),
        {"lam": [1e-2]},
        Dataset.of(X), Dataset.of(Y),
        final=MaxClassifier(),
    ).fit()
    out = np.asarray(res.members[0].fitted.apply(Dataset.of(X[:16])).to_array())
    assert out.shape == (16,)
    assert np.issubdtype(out.dtype, np.integer)


def test_sweep_under_autocaching_optimizer_keeps_prefix_once():
    """With the budgeted AutoCacheRule active the executor only retains
    planned nodes across pulls — the sweep must pin the shared prefix so
    it still executes exactly once."""
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.workflow.optimizers import AutoCachingOptimizer

    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    X, Y = _problem()
    feat = CountingFeaturize(len(X))
    res = GridSweep(
        feat.to_pipeline(),
        lambda lam: LinearMapEstimator(lam=lam),
        {"lam": LAMS},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    assert len(res) == len(LAMS)
    assert feat.full_calls == 1
    assert res.stats["gram_reuse_solves"] == len(LAMS)


def test_second_sweep_plans_with_zero_sampling(tmp_path):
    """Sweep-aware plan reuse: the merged DAG rides the same cost-model
    loop as a single fit, so the SECOND run of an identical sweep loads
    the persisted plan and pays zero sampling executions — with every
    member still matching the first run's models."""
    import keystone_tpu.cost as cost
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.workflow.optimizers import AutoCachingOptimizer

    PipelineEnv.get_or_create().set_optimizer(AutoCachingOptimizer())
    cost.configure(str(tmp_path))
    X, Y = _problem()

    def run():
        cost.reset_sampling()
        res = GridSweep(
            CountingFeaturize(len(X)).to_pipeline(),
            lambda lam: LinearMapEstimator(lam=lam),
            {"lam": LAMS},
            Dataset.of(X), Dataset.of(Y),
        ).fit()
        return res, cost.sampling_executions()["total"]

    res1, sampled1 = run()
    res2, sampled2 = run()
    assert sampled1 > 0, "the cold sweep should pay sampled profiling"
    assert sampled2 == 0, f"second sweep sampled {sampled2} executions"
    for m1, m2 in zip(res1, res2):
        np.testing.assert_allclose(
            _model_W(m1.fitted), _model_W(m2.fitted), atol=1e-6
        )
    keys = cost.get_store().keys()
    assert any(k.startswith("plan/") for k in keys)


def test_make_estimator_must_return_an_estimator():
    X, Y = _problem(n=64)
    sweep = GridSweep(
        None, lambda lam: MaxClassifier(), {"lam": [0.1]},
        Dataset.of(X), Dataset.of(Y),
    )
    with pytest.raises(TypeError, match="make_estimator"):
        sweep.fit()


def test_sweep_members_carry_absorbable_state():
    """Every Gram-family sweep member snapshots the shared accumulator
    with its own λ — any of them can absorb appended chunks later."""
    X, Y = _problem()
    res = GridSweep(
        None, lambda lam: LinearMapEstimator(lam=lam), {"lam": [1e-2, 1.0]},
        Dataset.of(X), Dataset.of(Y),
    ).fit()
    for member in res:
        nodes = member.fitted.absorbable_nodes()
        assert len(nodes) == 1
        state = member.fitted.graph.get_operator(nodes[0]).solver_state
        assert state.n == len(X)
        assert state.lam == pytest.approx(member.params["lam"])
