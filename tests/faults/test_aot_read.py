"""The aot.read fault point: a transient cache-read fault degrades to a
miss (the caller traces live), a fatal one propagates, and with no plan
the read path is untouched."""

import pytest

from keystone_tpu import faults
from keystone_tpu.compile.cache import ExecutableCache

ENV = {"jax": "x"}


def _store(cache, key="k1"):
    return cache.store(key, b"payload-bytes", {"env": dict(ENV)})


def test_transient_read_fault_degrades_to_miss_then_recovers(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    _store(cache)
    faults.install(faults.parse_plan("aot.read=transient@0"))
    assert cache.load("k1", expect_env=ENV) is None  # injected: a miss
    entry = cache.load("k1", expect_env=ENV)  # next read is fine
    assert entry is not None and entry.payload == b"payload-bytes"


def test_fatal_read_fault_propagates(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    _store(cache)
    faults.install(faults.parse_plan("aot.read=fatal@0"))
    with pytest.raises(faults.FatalFaultInjected):
        cache.load("k1", expect_env=ENV)


def test_no_plan_reads_normally(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    _store(cache)
    assert cache.load("k1", expect_env=ENV) is not None
