"""Retrying scans: injected transient faults at the chunk/stage sites
recover under the per-scan budget with exact parity, and exhaustion
propagates the original error — plus a full fit under a chunk-fault
schedule matching the clean fit to 1e-6 (the chaos gate)."""

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.faults import FaultInjected, TransientError


@pytest.fixture(autouse=True)
def _retries_on(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SCAN_RETRIES", "8")
    monkeypatch.setenv("KEYSTONE_SCAN_RETRY_BACKOFF", "0.001")
    yield


def _dataset(n=48, d=6, chunk_rows=8, label="retry"):
    rng = np.random.RandomState(3)
    X = rng.randn(n, d).astype(np.float32)
    chunks = [X[i : i + chunk_rows] for i in range(0, n, chunk_rows)]
    return X, ChunkedDataset.from_chunk_fn(
        lambda i: chunks[i], len(chunks), n, label=label
    )


def test_injected_chunk_faults_retry_with_bitwise_parity():
    X, ds = _dataset()
    clean = [np.asarray(c) for c in ds.chunks()]
    faults.install(faults.parse_plan("scan.chunk=transient@1,3,4"))
    got = [np.asarray(c) for c in ds.chunks()]
    assert len(got) == len(clean)
    for a, b in zip(clean, got):
        assert np.array_equal(a, b)
    assert faults.active_plan().injected["scan.chunk"] == 3


def test_injected_staging_faults_retry_in_place():
    X, ds = _dataset()
    faults.install(faults.parse_plan("scan.stage=transient@0,2,4"))
    got = np.concatenate([np.asarray(c) for c in ds.chunks()], axis=0)
    assert np.array_equal(got, X)
    assert faults.active_plan().injected["scan.stage"] == 3


def test_raw_scan_is_injected_too():
    _, ds = _dataset()
    faults.install(faults.parse_plan("scan.chunk=transient@2"))
    got = list(ds.raw_chunks())
    assert len(got) == 6
    assert faults.active_plan().injected["scan.chunk"] == 1


def test_budget_exhaustion_propagates_the_original_error(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SCAN_RETRIES", "2")
    _, ds = _dataset()
    # 4 faults at one site > 2 retries: the third re-raise surfaces
    faults.install(faults.parse_plan("scan.chunk=transient@0,1,2,3"))
    with pytest.raises(FaultInjected):
        list(ds.chunks())


def test_retries_default_off(monkeypatch):
    monkeypatch.delenv("KEYSTONE_SCAN_RETRIES")
    _, ds = _dataset()
    faults.install(faults.parse_plan("scan.chunk=transient@0"))
    with pytest.raises(FaultInjected):
        list(ds.chunks())


def test_transient_chunk_fn_failures_retry_for_real_sources():
    """A re-callable source whose production flakes (typed
    TransientError) retries per index — the real-I/O recovery path."""
    rng = np.random.RandomState(0)
    chunks = [rng.randn(8, 4).astype(np.float32) for _ in range(5)]
    failures = {1: 2, 3: 1}  # chunk index -> times it flakes first

    def chunk_fn(i):
        if failures.get(i, 0) > 0:
            failures[i] -= 1
            raise TransientError(f"flaky read of chunk {i}")
        return chunks[i]

    ds = ChunkedDataset.from_chunk_fn(chunk_fn, 5, 40, label="flaky")
    got = [np.asarray(c) for c in ds.chunks()]
    assert len(got) == 5
    for a, b in zip(chunks, got):
        assert np.array_equal(a, b)
    assert all(v == 0 for v in failures.values())


def test_nontransient_chunk_fn_failure_is_not_retried():
    calls = []

    def chunk_fn(i):
        calls.append(i)
        raise ValueError("deterministic bug")

    ds = ChunkedDataset.from_chunk_fn(chunk_fn, 3, 12, label="bug")
    with pytest.raises(ValueError, match="deterministic bug"):
        list(ds.chunks())
    assert calls == [0]  # exactly one attempt, no retry


def test_fit_under_chunk_fault_schedule_matches_clean_to_1e6():
    """The tentpole chaos gate: a streaming fit under an injected
    chunk/staging fault schedule completes and matches the clean fit."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator

    rng = np.random.RandomState(11)
    n, d, k = 128, 10, 2
    X = rng.randn(n, d).astype(np.float32)
    W_true = rng.randn(d, k).astype(np.float32)
    Y = X @ W_true + 0.01 * rng.randn(n, k).astype(np.float32)
    chunks = [X[i : i + 16] for i in range(0, n, 16)]
    ds = ChunkedDataset.from_chunk_fn(
        lambda i: chunks[i], len(chunks), n, label="fitfault"
    )
    labels = Dataset(Y, batched=True)

    clean = LinearMapEstimator(lam=0.1).fit(ds, labels)
    faults.install(
        faults.parse_plan(
            "scan.chunk=transient@p0.3x5s13;scan.stage=transient@2"
        )
    )
    faulted = LinearMapEstimator(lam=0.1).fit(ds, labels)
    injected = dict(faults.active_plan().injected)
    assert sum(injected.values()) >= 1, injected
    diff = float(np.max(np.abs(np.asarray(clean.W) - np.asarray(faulted.W))))
    assert diff <= 1e-6, diff


def test_fault_and_retry_land_in_the_trace():
    from keystone_tpu.obs import tracer as obs_tracer

    _, ds = _dataset()
    faults.install(faults.parse_plan("scan.chunk=transient@1"))
    tr = obs_tracer.install(obs_tracer.Tracer())
    try:
        list(ds.chunks())
    finally:
        obs_tracer.uninstall(tr)
    names = {s.name for s in tr.spans()}
    assert "scan.pipeline" in names
    assert "fault.inject" in names
    assert "retry.attempt" in names
    # the pipeline adopted the injection seam's budget, so the chunk
    # retry is visible on the scan span itself
    scan = [s for s in tr.spans() if s.name == "scan.pipeline"][-1]
    assert scan.attrs.get("retries", 0) >= 1
