"""FaultPlan grammar, determinism, and the fault_point hook."""

import pytest

from keystone_tpu import faults
from keystone_tpu.faults import (
    FatalFaultInjected,
    FaultInjected,
    ReplicaKilled,
    TransientError,
    fault_point,
    parse_plan,
)


def test_parse_index_clause_defaults_to_first_invocation():
    plan = parse_plan("scan.chunk=transient")
    spec = plan._by_site["scan.chunk"][0]
    assert spec.kind == "transient"
    assert spec.at == frozenset((0,))


def test_parse_multi_clause_with_indices_match_and_probabilistic():
    plan = parse_plan(
        "scan.chunk=transient@2,5;replica.batch#1=kill@3;"
        "scan.stage=fatal@p0.25x2s7"
    )
    assert set(plan.sites) == {"scan.chunk", "replica.batch", "scan.stage"}
    chunk = plan._by_site["scan.chunk"][0]
    assert chunk.at == frozenset((2, 5))
    rb = plan._by_site["replica.batch"][0]
    assert rb.kind == "kill" and rb.match == 1 and rb.at == frozenset((3,))
    st = plan._by_site["scan.stage"][0]
    assert st.at is None
    assert (st.rate, st.limit, st.seed) == (0.25, 2, 7)


@pytest.mark.parametrize(
    "bad",
    [
        "scan.chunk",                 # no '='
        "scan.chunk=explode",         # unknown kind
        "scan.chunk=transient@p1.5",  # rate out of range
        "scan.chunk=transient@x,y",   # non-integer indices
        "",                           # empty plan
        "scan.chunk#a=kill",          # non-integer match
    ],
)
def test_parse_rejects_bad_clauses_loudly(bad):
    with pytest.raises(ValueError):
        parse_plan(bad)


def test_index_clause_fires_exactly_at_its_invocations():
    plan = parse_plan("s=transient@1,3")
    hits = [plan.check("s", {}) for _ in range(6)]
    assert hits == [None, "transient", None, "transient", None, None]
    assert plan.injected["s"] == 2


def test_probabilistic_clause_is_seeded_and_bounded():
    runs = []
    for _ in range(2):
        plan = parse_plan("s=transient@p0.5x3s42")
        runs.append([plan.check("s", {}) is not None for _ in range(40)])
    assert runs[0] == runs[1]  # same seed => identical schedule
    assert sum(runs[0]) == 3  # the x3 bound
    plan = parse_plan("s=transient@p0.5x3s43")
    assert [plan.check("s", {}) is not None for _ in range(40)] != runs[0]


def test_match_clause_counts_only_matching_invocations():
    plan = parse_plan("replica.batch#0=transient@1")
    # replica 1's invocations do not advance replica 0's clause counter
    assert plan.check("replica.batch", {"replica": 1}) is None
    assert plan.check("replica.batch", {"replica": 0}) is None  # index 0
    assert plan.check("replica.batch", {"replica": 1}) is None
    assert plan.check("replica.batch", {"replica": 0}) == "transient"


def test_reset_replays_the_identical_schedule():
    plan = parse_plan("s=transient@p0.4x5s9")
    first = [plan.check("s", {}) for _ in range(30)]
    plan.reset()
    assert [plan.check("s", {}) for _ in range(30)] == first


def test_fault_point_raises_typed_errors_and_noop_without_plan():
    # no plan installed (conftest cleared): a pure no-op
    fault_point("scan.chunk")

    faults.install(parse_plan("a=transient@0;b=fatal@0;c=kill@0"))
    with pytest.raises(FaultInjected) as ei:
        fault_point("a")
    assert isinstance(ei.value, TransientError)
    with pytest.raises(FatalFaultInjected):
        fault_point("b")
    assert not faults.is_transient(FatalFaultInjected("b", 0))
    with pytest.raises(ReplicaKilled) as ki:
        fault_point("c")
    # kill must bypass `except Exception` backstops
    assert not isinstance(ki.value, Exception)
    faults.clear()
    fault_point("a")  # cleared: no-op again


def test_env_plan_is_cached_on_raw_string(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "x=transient@0")
    p1 = faults.active_plan()
    assert p1 is faults.active_plan()  # same string -> same plan object
    with pytest.raises(FaultInjected):
        fault_point("x")
    fault_point("x")  # invocation 1: already fired, counters persist
    monkeypatch.setenv("KEYSTONE_FAULTS", "x=transient@1")
    p2 = faults.active_plan()
    assert p2 is not p1  # new string -> fresh parse, fresh counters
    fault_point("x")  # invocation 0 of the new plan: no fault
    with pytest.raises(FaultInjected):
        fault_point("x")
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert faults.active_plan() is None


def test_transient_classification_covers_stdlib_families():
    assert faults.is_transient(ConnectionResetError())
    assert faults.is_transient(TimeoutError())
    assert not faults.is_transient(ValueError())
    assert not faults.is_transient(ReplicaKilled("k"))
