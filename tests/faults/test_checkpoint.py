"""Resumable fits: FitCheckpoint store integrity + kill-and-resume
parity (bit-identical solver state, only-remaining-chunks work gate)."""

import os

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.faults import FatalFaultInjected, FitCheckpoint
from keystone_tpu.linalg.accumulators import (
    GramSolverState,
    MomentsState,
    TsqrRState,
)
from keystone_tpu.nodes.learning.linear import (
    LinearMapEstimator,
    TSQRLeastSquaresEstimator,
)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


def test_round_trip_preserves_every_accumulator_bit_for_bit(tmp_path):
    rng = np.random.RandomState(0)
    gram = GramSolverState()
    gram.update(rng.randn(8, 4).astype(np.float32),
                rng.randn(8, 2).astype(np.float32))
    tsqr = TsqrRState()
    tsqr.update(rng.randn(8, 4).astype(np.float32))
    mom = MomentsState()
    mom.update(rng.randn(8, 4))

    ck = FitCheckpoint(str(tmp_path), "k1")
    ck.save({"gram": gram, "tsqr": tsqr, "mom": mom}, 3, 24)
    state, chunk, rows = ck.load()
    assert (chunk, rows) == (3, 24)
    assert np.array_equal(state["gram"].gram, gram.gram)
    assert np.array_equal(state["gram"].cross, gram.cross)
    assert np.array_equal(state["tsqr"].r, tsqr.r)
    assert np.array_equal(state["mom"].m2, mom.m2)


def test_missing_corrupt_and_truncated_degrade_to_fresh(tmp_path):
    ck = FitCheckpoint(str(tmp_path), "k")
    assert ck.load() is None  # missing
    ck.save(MomentsState(), 1, 8)
    with open(ck.path, "r+b") as f:
        f.seek(30)
        f.write(b"\xff\xff\xff")
    assert ck.load() is None  # corrupt: checksum fails
    assert not os.path.exists(ck.path)  # and the entry was deleted
    ck.save(MomentsState(), 1, 8)
    blob = open(ck.path, "rb").read()
    with open(ck.path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert ck.load() is None  # truncated


def test_foreign_key_is_ignored_not_resumed(tmp_path):
    a = FitCheckpoint(str(tmp_path), "fit-a")
    a.save(MomentsState(), 2, 16)
    # same file, different key (simulates a hash collision / misuse)
    b = FitCheckpoint(str(tmp_path), "fit-b")
    b.path = a.path
    assert b.load() is None
    assert os.path.exists(a.path)  # foreign entries are kept, not deleted


def test_save_is_atomic_no_tmp_left_and_complete_removes(tmp_path):
    ck = FitCheckpoint(str(tmp_path), "k")
    for i in range(4):
        ck.save(MomentsState(), i, i * 8)
    leftovers = [f for f in os.listdir(tmp_path) if f.startswith(".tmp")]
    assert leftovers == []
    assert ck.exists()
    ck.complete()
    assert not ck.exists()
    ck.complete()  # idempotent


def test_unpicklable_header_is_a_miss(tmp_path):
    ck = FitCheckpoint(str(tmp_path), "k")
    import hashlib

    payload = b"not a pickle"
    blob = b"KSFITCKPT1\n" + hashlib.sha256(payload).digest() + payload
    with open(ck.path, "wb") as f:
        f.write(blob)
    assert ck.load() is None


# ---------------------------------------------------------------------------
# kill-and-resume fits
# ---------------------------------------------------------------------------


def _fit_problem(n=96, d=12, k=3, chunk_rows=16, label="ckfit"):
    rng = np.random.RandomState(4)
    X = rng.randn(n, d).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    chunks = [X[i : i + chunk_rows] for i in range(0, n, chunk_rows)]
    produced = []

    def chunk_fn(i):
        produced.append(i)
        return chunks[i]

    ds = ChunkedDataset.from_chunk_fn(chunk_fn, len(chunks), n, label=label)
    return ds, Dataset(Y, batched=True), produced


def test_killed_gram_fit_resumes_bit_identical_and_skips_folded(tmp_path):
    ds, labels, produced = _fit_problem()
    ref = LinearMapEstimator(lam=0.5, snapshot=True).fit(ds, labels)

    produced.clear()
    faults.install(faults.parse_plan("scan.chunk=fatal@3"))
    with pytest.raises(FatalFaultInjected):
        LinearMapEstimator(
            lam=0.5, snapshot=True, checkpoint=str(tmp_path)
        ).fit(ds, labels)
    assert sorted(set(produced)) == [0, 1, 2]
    faults.clear()

    produced.clear()
    resumed = LinearMapEstimator(
        lam=0.5, snapshot=True, checkpoint=str(tmp_path)
    ).fit(ds, labels)
    # the work gate: resume produced ONLY the unfolded chunks
    assert sorted(set(produced)) == [3, 4, 5]
    # bit-for-bit state parity with the uninterrupted fit
    for attr in ("gram", "cross", "sum_x", "sum_y", "shift", "shift_y"):
        assert np.array_equal(
            getattr(ref.solver_state, attr), getattr(resumed.solver_state, attr)
        ), attr
    assert ref.solver_state.n == resumed.solver_state.n
    assert np.array_equal(np.asarray(ref.W), np.asarray(resumed.W))
    # the finished fit removed its checkpoint
    assert os.listdir(tmp_path) == []


def test_killed_tsqr_fit_resumes_without_refolding(tmp_path):
    ds, labels, produced = _fit_problem(label="cktsqr")
    ref = TSQRLeastSquaresEstimator(
        lam=0.5, checkpoint=str(tmp_path / "ref")
    ).fit(ds, labels)

    produced.clear()
    faults.install(faults.parse_plan("scan.chunk=fatal@10"))  # during fold
    with pytest.raises(FatalFaultInjected):
        TSQRLeastSquaresEstimator(
            lam=0.5, checkpoint=str(tmp_path / "kill")
        ).fit(ds, labels)
    faults.clear()
    killed_at = sorted(set(produced))

    produced.clear()
    resumed = TSQRLeastSquaresEstimator(
        lam=0.5, checkpoint=str(tmp_path / "kill")
    ).fit(ds, labels)
    # the means pass was checkpointed too: resume re-produced strictly
    # fewer chunks than the killed run's two passes
    assert len(set(produced)) < len(killed_at) + 6
    assert np.array_equal(np.asarray(ref.W), np.asarray(resumed.W))
    assert np.array_equal(
        np.asarray(ref.feature_mean), np.asarray(resumed.feature_mean)
    )


def test_tsqr_checkpoint_path_matches_laned_path():
    ds, labels, _ = _fit_problem(label="cmp")
    laned = TSQRLeastSquaresEstimator(lam=0.25).fit(ds, labels)
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        ck = TSQRLeastSquaresEstimator(lam=0.25, checkpoint=tmp).fit(
            ds, labels
        )
    diff = float(np.max(np.abs(np.asarray(laned.W) - np.asarray(ck.W))))
    assert diff <= 1e-5, diff


def test_sweep_grouped_fit_keeps_the_checkpoint_contract(tmp_path):
    """A checkpointed estimator fitted THROUGH a GridSweep's shared
    accumulation pass stays resumable: the sweep forwards checkpoint
    args to the family's grouped fit, and a killed sweep re-run resumes
    from the cursor instead of rescanning."""
    from keystone_tpu.sweep import GridSweep
    from keystone_tpu.workflow.transformer import FunctionNode

    ds, labels, produced = _fit_problem(label="sweepck")
    prefix = FunctionNode(batch_fn=lambda x: x, label="ident").to_pipeline()

    def sweep():
        return GridSweep(
            prefix,
            lambda lam: LinearMapEstimator(lam=lam, checkpoint=str(tmp_path)),
            {"lam": [0.1, 1.0]},
            ds, labels,
        ).fit()

    faults.install(faults.parse_plan("scan.chunk=fatal@3"))
    with pytest.raises(faults.FatalFaultInjected):
        sweep()
    faults.clear()
    produced.clear()
    res = sweep()
    assert len(res) == 2
    assert sorted(set(produced)) == [3, 4, 5]  # resumed, not rescanned


def test_checkpoint_key_change_starts_fresh(tmp_path):
    """A different λ grid is a different fit: its checkpoint must not be
    resumed (the key binds solver family, shapes, and λ)."""
    ds, labels, produced = _fit_problem(label="ckkey")
    faults.install(faults.parse_plan("scan.chunk=fatal@3"))
    with pytest.raises(FatalFaultInjected):
        LinearMapEstimator(
            lam=0.5, snapshot=True, checkpoint=str(tmp_path)
        ).fit(ds, labels)
    faults.clear()
    produced.clear()
    # different lam -> different key -> full fresh pass
    LinearMapEstimator(
        lam=2.0, snapshot=True, checkpoint=str(tmp_path)
    ).fit(ds, labels)
    assert sorted(set(produced)) == [0, 1, 2, 3, 4, 5]
