"""The cost-table wire codec (keystone_tpu/cluster/wire.py): compact
pong-delta rows, zero-row suppression, and malformed-frame tolerance."""

from keystone_tpu.cluster.wire import costs_from_wire, costs_to_wire


def test_round_trip_preserves_the_charges():
    table = {
        "gold": {
            "high": {"device_s": 0.25, "queue_s": 0.0625,
                     "payload_bytes": 1024, "items": 3},
        },
        "bronze": {
            "normal": {"device_s": 0.125, "queue_s": 0.0,
                       "payload_bytes": 0, "items": 1},
        },
    }
    rows = costs_from_wire(costs_to_wire(table))
    assert sorted(r[:2] for r in rows) == [
        ("bronze", "normal"), ("gold", "high"),
    ]
    by_key = {(t, p): c for t, p, c in rows}
    assert by_key[("gold", "high")] == table["gold"]["high"]
    assert by_key[("bronze", "normal")]["device_s"] == 0.125


def test_all_zero_rows_and_empty_tables_ship_as_none():
    assert costs_to_wire({}) is None
    assert costs_to_wire(None) is None
    assert costs_to_wire({
        "idle": {"normal": {"device_s": 0.0, "queue_s": 0.0,
                            "payload_bytes": 0, "items": 0}},
    }) is None


def test_malformed_payloads_decode_empty():
    assert costs_from_wire(None) == []
    assert costs_from_wire({"t": "not-a-dict"}) == []
    assert costs_from_wire({"t": {"p": [0.1, 0.2]}}) == []  # short row
    assert costs_from_wire({"t": {"p": ["x", 0, 0, 0]}}) == []
    # one good row among garbage still decodes
    rows = costs_from_wire({
        "bad": {"p": None},
        "good": {"normal": [0.5, 0.0, 10, 1]},
    })
    assert [r[0] for r in rows] == ["good"]
