"""Front-door coalescing tests: the priced wait window (unit), and the
integration contract — coalesced members keep their individual identity
(answers, deadlines, QoS, requeue-on-worker-death) while sharing frames.

One module-scoped coalescing router serves the integration tests (worker
boots pay a fresh interpreter + jax import each); tests run in
definition order and are sequenced so state they leave behind — warmed
estimates, a killed worker — never invalidates a later assertion.
"""

import os
import signal
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from keystone_tpu.cluster import ClusterRouter
from keystone_tpu.serving.scheduler import ServiceEstimate

D = 32
STALL_S = 0.004


# ---------------------------------------------------------------------------
# the priced window (unit)
# ---------------------------------------------------------------------------


def test_cold_estimator_never_delays():
    est = ServiceEstimate()
    assert est.coalesce_window(now=100.0) == 0.0
    assert est.coalesce_window(now=100.0, tightest_deadline=200.0) == 0.0


def test_window_is_a_fraction_of_learned_service():
    est = ServiceEstimate()
    est.observe(0.004)
    w = est.coalesce_window(now=0.0, cap=1.0)
    assert w == pytest.approx(ServiceEstimate.COALESCE_FRACTION * 0.004)


def test_operator_cap_bounds_the_window():
    est = ServiceEstimate()
    est.observe(10.0)  # enormous service time
    assert est.coalesce_window(now=0.0, cap=0.002) == 0.002


def test_tight_deadline_shrinks_then_zeroes_the_window():
    est = ServiceEstimate()
    est.observe(0.01)
    now = 50.0
    # frame must still be servable: deadline - now - one service time
    w = est.coalesce_window(now, tightest_deadline=now + 0.011, cap=1.0)
    assert w == pytest.approx(0.001)
    # an unmeetable member means the frame goes NOW, not never
    assert est.coalesce_window(now, tightest_deadline=now + 0.005) == 0.0
    assert est.coalesce_window(now, tightest_deadline=now - 1.0) == 0.0


# ---------------------------------------------------------------------------
# integration: identity through shared frames (and worker death)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def router():
    r = ClusterRouter(
        ("factory", "keystone_tpu.cluster.demo:build_stall_model",
         {"d": D, "stall_s": STALL_S}),
        workers=2,
        replicas_per_worker=1,
        buckets=(16,),
        datum_shape=(D,),
        max_wait_ms=2.0,
        spawn_timeout_s=180,
        health_interval_s=3600.0,
        drain_timeout_s=5.0,
        join_timeout_s=2.0,
        max_restarts=2,
    )
    r.start()
    yield r
    r.shutdown(drain=False)


@pytest.fixture(scope="module")
def data():
    return np.random.RandomState(3).randn(64, D).astype(np.float32)


@pytest.fixture(scope="module")
def expected(data):
    from keystone_tpu.cluster.demo import build_stall_model

    local = build_stall_model(d=D, stall_s=0.0)
    return np.asarray(local.apply(data).to_array())


def test_a_concurrent_burst_coalesces_with_per_member_answers(
    router, data, expected
):
    n = 48
    with ThreadPoolExecutor(max_workers=n) as pool:
        outs = list(pool.map(
            lambda i: np.asarray(router.predict(data[i], timeout=60.0)),
            range(n),
        ))
    # every member got ITS answer, not its frame-mates'
    for i, out in enumerate(outs):
        np.testing.assert_allclose(out, expected[i], atol=1e-5)
    c = router.snapshot()["counters"]
    # the burst shared frames: strictly fewer req frames than requests
    assert 0 < c["wire.frames.req"] < n, c
    assert c["coalesce.frames"] >= 1, c
    assert c["coalesce.members"] > c["coalesce.frames"], c
    assert c["wire.bytes_sent.req"] > 0, c


def test_b_lone_request_dispatches_without_waiting(router, data):
    # quiet router + a warmed estimate: a single request must not sit
    # out a coalescing window it can never fill
    router.observe_service(0.5)  # window would be ~max_wait_ms if waited
    t0 = time.monotonic()
    router.predict(data[0], timeout=30.0)
    # far below the 125ms a COALESCE_FRACTION * 0.5s wait would cost
    assert time.monotonic() - t0 < 0.4
    router.observe_service(STALL_S)  # re-seed something sane


def test_c_members_requeue_individually_on_worker_death(
    router, data, expected
):
    """SIGKILL a worker with coalesced frames in flight: every member of
    its frames must be re-placed individually (deadline/QoS/trace
    intact) and answer with ITS result — zero admitted failures."""
    before = router.snapshot()["counters"]
    victim = router.worker_pids[0]
    n = 96
    # SIGSTOP first: the victim's share of the burst piles up outstanding
    # (it can neither answer nor close its socket), so the later SIGKILL
    # is GUARANTEED to strand coalesced members in flight
    os.kill(victim, signal.SIGSTOP)
    try:
        with ThreadPoolExecutor(max_workers=24) as pool:

            def one(i):
                return np.asarray(
                    router.predict(data[i % 64], timeout=120.0)
                )

            futs = [pool.submit(one, i) for i in range(n)]
            time.sleep(0.3)  # let frames land on the stopped victim
            os.kill(victim, signal.SIGKILL)
            outs = [f.result(timeout=120) for f in futs]
    finally:
        try:
            os.kill(victim, signal.SIGKILL)
        except ProcessLookupError:
            pass
    for i, out in enumerate(outs):
        np.testing.assert_allclose(out, expected[i % 64], atol=1e-5)
    after = router.snapshot()["counters"]
    assert after["restarts"] >= before.get("restarts", 0) + 1
    # the kill stranded at least one coalesced frame's members
    assert after["requeues"] > before.get("requeues", 0), after
    assert after["coalesce.frames"] > before.get("coalesce.frames", 0)
    # the respawned worker rejoins (fresh interpreter: generous budget)
    deadline = time.monotonic() + 120
    while router.live_workers < 2 and time.monotonic() < deadline:
        time.sleep(0.25)
    assert router.live_workers == 2, "killed worker was not respawned"


def test_d_coalescing_off_is_frame_per_request(data):
    r = ClusterRouter(
        ("factory", "keystone_tpu.cluster.demo:build_stall_model",
         {"d": D, "stall_s": 0.0}),
        workers=1,
        replicas_per_worker=1,
        buckets=(8,),
        datum_shape=(D,),
        max_wait_ms=1.0,
        spawn_timeout_s=180,
        health_interval_s=3600.0,
        coalesce=False,
    )
    with r:
        for i in range(6):
            r.predict(data[i], timeout=30.0)
        c = r.snapshot()["counters"]
    assert c["wire.frames.req"] == 6, c
    assert "coalesce.frames" not in c or c["coalesce.frames"] == 0, c
