"""Binary hot-codec unit tests: round-trips over the whole dtype table,
member identity (deadline/QoS/trace) preservation, shm slot placement,
and — the security half — typed degradation on corrupt, truncated, or
version-skewed frames with hot bytes NEVER reaching the unpickler.
"""

import pickle

import numpy as np
import pytest

from keystone_tpu.cluster import codec as codec_mod
from keystone_tpu.cluster.codec import (
    _CODE_TO_DTYPE,
    MAGIC,
    VERSION,
    CodecError,
    decode,
    encode,
)
from keystone_tpu.cluster.shm import ShmRing
from keystone_tpu.cluster.wire import ConnectionClosed, decode_payload


class _Counters:
    """Minimal metrics stand-in: the codec only calls ``inc``."""

    def __init__(self):
        self.counts = {}

    def inc(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n


def _roundtrip(msg, **kw):
    payload = encode(msg, **kw)
    assert payload is not None, msg
    assert payload[0] == MAGIC  # never a pickle frame
    return decode(payload)


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "dtype", sorted(_CODE_TO_DTYPE.values(), key=str), ids=str
)
def test_req_round_trip_every_wire_dtype(dtype):
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 2, size=(3, 5)).astype(dtype)
    got = _roundtrip({
        "type": "req",
        "members": [{"id": 11, "datum": arr, "deadline_rem": 0.25}],
    })
    assert got["type"] == "req" and len(got["members"]) == 1
    m = got["members"][0]
    assert m["id"] == 11 and m["deadline_rem"] == 0.25
    assert m["datum"].dtype == dtype and m["datum"].shape == arr.shape
    assert m["datum"].tobytes() == arr.tobytes()


@pytest.mark.parametrize(
    "arr",
    [
        np.float32(3.5) * np.ones(()),  # 0-d
        np.zeros((0,), np.float64),  # empty 1-d
        np.zeros((4, 0, 2), np.int32),  # empty with interior zero dim
        np.arange(24, dtype=np.int16).reshape(2, 3, 4),
    ],
    ids=["zero-d", "empty", "zero-dim", "three-d"],
)
def test_req_round_trip_shapes(arr):
    m = _roundtrip({"type": "req", "members": [{"id": 1, "datum": arr}]})[
        "members"
    ][0]
    np.testing.assert_array_equal(m["datum"], arr)
    assert m["datum"].shape == arr.shape and m["datum"].dtype == arr.dtype


def test_req_members_keep_individual_identity():
    members = [
        {
            "id": 1,
            "datum": np.ones((2,), np.float32),
            "deadline_rem": 0.5,
            "priority": "high",
            "tenant": "acme",
            "trace": {"id": "t-1", "hop": "router", "sent_unix": 123.5},
        },
        {"id": 2, "datum": np.zeros((2,), np.float32)},
        {
            "id": 3,
            "datum": np.full((2,), 7, np.float32),
            "priority": "low",
            "tenant": "beta",
        },
    ]
    got = _roundtrip({"type": "req", "members": members})["members"]
    assert [m["id"] for m in got] == [1, 2, 3]
    assert got[0]["priority"] == "high" and got[0]["tenant"] == "acme"
    assert got[0]["trace"] == {
        "id": "t-1", "hop": "router", "sent_unix": 123.5,
    }
    # member 2 shipped defaults: no spurious keys materialize
    assert "priority" not in got[1] and "tenant" not in got[1]
    assert "deadline_rem" not in got[1] and "trace" not in got[1]
    assert got[2]["priority"] == "low" and got[2]["tenant"] == "beta"


def test_res_round_trip_values_and_typed_errors():
    msg = {
        "type": "res",
        "t_unix": 1700000000.25,
        "members": [
            {"id": 5, "ok": True, "value": np.arange(4, dtype=np.float64)},
            {
                "id": 6,
                "ok": False,
                "error": {"kind": "Shed", "message": "late"},
            },
            {
                "id": 7,
                "ok": False,
                "error": {
                    "kind": "WorkerError",
                    "message": "odd",
                    "original": "Weird",
                },
            },
        ],
    }
    got = _roundtrip(msg)
    assert got["t_unix"] == msg["t_unix"]
    ok, shed, weird = got["members"]
    np.testing.assert_array_equal(ok["value"], np.arange(4, dtype=np.float64))
    assert shed == {
        "id": 6, "ok": False,
        "error": {"kind": "Shed", "message": "late"},
    }
    assert weird["error"]["original"] == "Weird"


def test_non_describable_frames_return_none():
    # object arrays, unknown priorities, non-array payloads, foreign
    # frame types: all fall back to the pickle path (None), never raise
    assert encode({"type": "req", "members": [
        {"id": 1, "datum": np.array([object()])},
    ]}) is None
    assert encode({"type": "req", "members": [
        {"id": 1, "datum": np.ones(2, np.float32), "priority": "vip"},
    ]}) is None
    assert encode({"type": "req", "members": [{"id": 1, "datum": 3.5}]}) \
        is None
    assert encode({"type": "res", "members": [
        {"id": 1, "ok": True, "value": "not an array"},
    ]}) is None
    assert encode({"type": "res", "members": [
        {"id": 1, "ok": False, "error": "not a dict"},
    ]}) is None
    assert encode({"type": "hello"}) is None


def test_non_contiguous_input_round_trips():
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    view = base[:, ::2]  # non-contiguous
    m = _roundtrip({"type": "req", "members": [{"id": 1, "datum": view}]})[
        "members"
    ][0]
    np.testing.assert_array_equal(m["datum"], view)


# ---------------------------------------------------------------------------
# typed degradation: corrupt / truncated / version skew, never unpickled
# ---------------------------------------------------------------------------


def _valid_req_payload():
    payload = encode({
        "type": "req",
        "members": [{"id": 9, "datum": np.arange(8, dtype=np.float32)}],
    })
    assert payload is not None
    return payload


def test_truncated_frame_degrades_typed():
    payload = _valid_req_payload()
    for cut in (1, 3, len(payload) // 2, len(payload) - 1):
        with pytest.raises(CodecError):
            decode(payload[:cut])


def test_trailing_bytes_degrade_typed():
    with pytest.raises(CodecError, match="trailing"):
        decode(_valid_req_payload() + b"\x00")


def test_version_skew_degrades_typed():
    payload = bytearray(_valid_req_payload())
    payload[1] = VERSION + 1
    with pytest.raises(CodecError, match="version skew"):
        decode(bytes(payload))


def test_corrupt_fields_degrade_typed():
    base = _valid_req_payload()
    # header(6) + member id(8) + flags(1) + priority(1) + tenant len(4)
    dtype_code_off = 6 + 8 + 1 + 1 + 4
    for offset, value in [
        (2, 99),  # unknown frame kind
        (dtype_code_off, 200),  # unknown dtype code
        (dtype_code_off + 1, 40),  # ndim past _MAX_NDIM
    ]:
        payload = bytearray(base)
        payload[offset] = value
        with pytest.raises(CodecError):
            decode(bytes(payload))


def test_codec_error_is_connection_closed():
    # the supervision contract: a desynced hot stream is handled exactly
    # like a dead peer — requeue on peers, typed
    assert issubclass(CodecError, ConnectionClosed)
    with pytest.raises(ConnectionClosed):
        decode(b"\xb5garbage")


def test_binary_bytes_never_reach_the_unpickler(monkeypatch):
    """A malformed MAGIC-led payload must raise CodecError out of
    decode_payload without pickle.loads ever being consulted."""
    calls = []
    real_loads = pickle.loads

    def spy(data, *a, **kw):
        calls.append(data[:1])
        return real_loads(data, *a, **kw)

    monkeypatch.setattr(
        "keystone_tpu.cluster.wire.pickle.loads", spy
    )
    evil = bytes([MAGIC]) + b"\x00" * 32  # version 0 -> skew
    with pytest.raises(CodecError):
        decode_payload(evil)
    assert calls == [], "binary payload was handed to pickle.loads"
    # while a genuine pickle control frame still decodes
    assert decode_payload(pickle.dumps({"type": "ping"})) == {"type": "ping"}
    assert calls, "control frame bypassed the (spied) unpickler"


def test_magic_collides_with_no_pickle_protocol():
    # protocol >= 2 pickles open with 0x80; the magic must differ so the
    # per-frame dispatch in decode_payload is unambiguous
    for proto in range(2, pickle.HIGHEST_PROTOCOL + 1):
        assert pickle.dumps({"x": 1}, protocol=proto)[0] == 0x80
    assert MAGIC != 0x80


# ---------------------------------------------------------------------------
# shm placement
# ---------------------------------------------------------------------------


def _ring(name, slots=2, slot_bytes=1 << 12):
    return ShmRing(name, slots, slot_bytes, create=True)


def test_shm_placement_and_copying_decode_frees_slots():
    ring = _ring("kstcodec1")
    try:
        metrics = _Counters()
        arr = np.arange(256, dtype=np.float32)  # 1 KiB >= threshold
        payload = encode(
            {"type": "req", "members": [{"id": 1, "datum": arr}]},
            shm=ring, min_shm_bytes=1024, metrics=metrics,
        )
        assert metrics.counts["shm.payloads"] == 1
        assert metrics.counts["shm.bytes"] == arr.nbytes
        assert ring.in_use == 1
        # frame carries only the descriptor, not the array bytes
        assert len(payload) < arr.nbytes
        got = decode(payload, shm=ring, copy=True)
        np.testing.assert_array_equal(got["members"][0]["datum"], arr)
        assert "_shm_slots" not in got  # copied out: freed immediately
        assert ring.in_use == 0
    finally:
        ring.close()
        ring.unlink()


def test_shm_zero_copy_decode_defers_slot_free():
    ring = _ring("kstcodec2")
    try:
        arr = np.arange(512, dtype=np.float64)
        payload = encode(
            {"type": "req", "members": [{"id": 1, "datum": arr}]},
            shm=ring, min_shm_bytes=1024,
        )
        got = decode(payload, shm=ring, copy=False)
        slots = got.pop("_shm_slots")
        datum = got["members"][0]["datum"]
        np.testing.assert_array_equal(datum, arr)
        assert len(slots) == 1 and ring.in_use == 1
        del got, datum  # release the zero-copy view before reclaiming
        for s in slots:
            ring.free(s)
        assert ring.in_use == 0
    finally:
        ring.close()
        ring.unlink()


def test_shm_exhaustion_degrades_inline_and_counts():
    ring = _ring("kstcodec3", slots=1)
    try:
        metrics = _Counters()
        arrs = [np.arange(512, dtype=np.float32) + i for i in range(3)]
        payload = encode(
            {
                "type": "req",
                "members": [
                    {"id": i, "datum": a} for i, a in enumerate(arrs)
                ],
            },
            shm=ring, min_shm_bytes=1024, metrics=metrics,
        )
        assert metrics.counts["shm.payloads"] == 1
        assert metrics.counts["shm.fallback"] == 2
        got = decode(payload, shm=ring, copy=True)
        for m, a in zip(got["members"], arrs):
            np.testing.assert_array_equal(m["datum"], a)  # bit-equal both ways
        assert ring.in_use == 0
    finally:
        ring.close()
        ring.unlink()


def test_small_payloads_stay_inline():
    ring = _ring("kstcodec4")
    try:
        metrics = _Counters()
        payload = encode(
            {"type": "req", "members": [
                {"id": 1, "datum": np.ones(4, np.float32)},
            ]},
            shm=ring, min_shm_bytes=1024, metrics=metrics,
        )
        assert ring.in_use == 0 and not metrics.counts
        # an inline frame decodes without any ring attached
        got = decode(payload, shm=None, copy=True)
        np.testing.assert_array_equal(
            got["members"][0]["datum"], np.ones(4, np.float32)
        )
    finally:
        ring.close()
        ring.unlink()


def test_shm_reference_without_ring_degrades_typed():
    ring = _ring("kstcodec5")
    try:
        payload = encode(
            {"type": "req", "members": [
                {"id": 1, "datum": np.arange(512, dtype=np.float32)},
            ]},
            shm=ring, min_shm_bytes=1024,
        )
        with pytest.raises(CodecError, match="no ring"):
            decode(payload, shm=None)
    finally:
        ring.close()
        ring.unlink()
