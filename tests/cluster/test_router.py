"""Cluster router integration tests (ISSUE 12): real worker processes.

One module-scoped 2-worker router serves every test (worker boots pay a
fresh interpreter + jax import each, so the fixture is shared); tests
run in definition order (tier-1 disables random ordering) and are
sequenced so state they leave behind — a warmed service estimate, a
killed-and-respawned worker — never invalidates a later assertion.
"""

import os
import signal
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from keystone_tpu.cluster import ClusterRouter
from keystone_tpu.serving.errors import (
    DeadlineExceeded,
    EngineStopped,
    Shed,
)

D = 32
STALL_S = 0.002


@pytest.fixture(scope="module")
def router():
    r = ClusterRouter(
        ("factory", "keystone_tpu.cluster.demo:build_stall_model",
         {"d": D, "stall_s": STALL_S}),
        workers=2,
        replicas_per_worker=1,
        buckets=(8,),
        datum_shape=(D,),
        max_wait_ms=1.0,
        spawn_timeout_s=180,
        # long health interval: worker pongs must not warm the router's
        # service estimate behind the deterministic tests' backs
        health_interval_s=3600.0,
        # bounded-shutdown test budget: keep the wedged-worker path fast
        drain_timeout_s=3.0,
        join_timeout_s=2.0,
        max_restarts=2,
    )
    r.start()
    yield r
    r.shutdown(drain=False)


@pytest.fixture(scope="module")
def data():
    return np.random.RandomState(0).randn(32, D).astype(np.float32)


@pytest.fixture(scope="module")
def expected(data):
    from keystone_tpu.cluster.demo import build_stall_model

    local = build_stall_model(d=D, stall_s=0.0)
    return np.asarray(local.apply(data).to_array())


def test_a_predict_parity_and_load_spread(router, data, expected):
    n = 64
    with ThreadPoolExecutor(max_workers=16) as pool:
        outs = list(pool.map(
            lambda i: router.predict(data[i % len(data)]), range(n)
        ))
    for i, out in enumerate(outs):
        np.testing.assert_allclose(
            np.asarray(out), expected[i % len(data)], atol=1e-5
        )
    snap = router.snapshot()
    c = snap["counters"]
    assert c["submitted"] == c["completed"] == n
    # concurrent load reached both worker processes
    workers_with_batches = {
        key.split("/")[0] for key, row in snap["replicas"].items()
        if row.get("batches")
    }
    assert len(workers_with_batches) == 2, snap["replicas"]
    # merged quantiles came from worker sketches as well as the router
    assert snap["latency"]["count"] >= n


def test_b_deadline_crosses_the_process_boundary(router, data):
    # the router's estimate is COLD (no observe_service, health pongs
    # disabled), so the front door cannot shed — an already-expired
    # deadline must be enforced on the WORKER side and come back typed:
    # its fleet admission sheds it (warm worker estimate) or its replica
    # expires it (DeadlineExceeded); either proves the deadline survived
    # the hop as a remaining budget.
    assert router.service_estimate is None
    with pytest.raises((Shed, DeadlineExceeded)):
        router.predict(data[0], timeout=1e-9)
    # a generous deadline sails through end to end
    out = router.predict(data[0], timeout=30.0)
    assert np.asarray(out).shape == (16,)


def test_c_shed_determinism_with_seeded_estimate(router, data):
    # seed the front door exactly like the fleet-scheduler tests seed
    # theirs: 10s per batch makes every 100ms deadline unmeetable
    router.observe_service(10.0)
    before_shed = router.metrics.count("shed")
    before_submitted = router.metrics.count("submitted")
    for _ in range(10):
        with pytest.raises(Shed):
            router.submit(data[0], timeout=0.1)
    assert router.metrics.count("shed") == before_shed + 10
    # shed at the front door: nothing was admitted, nothing crossed to
    # a worker
    assert router.metrics.count("submitted") == before_submitted
    # deadline-less traffic is never shed, whatever the estimate says
    assert router.predict(data[0]) is not None


def test_d_worker_kill_mid_load_zero_admitted_failures(router, data):
    pids = router.worker_pids
    victim_pid = pids[0]
    stop = [False]
    failures = []
    served = [0]

    def hammer(tid):
        while not stop[0]:
            try:
                router.predict(data[served[0] % len(data)])
                served[0] += 1
            except Exception as e:  # pragma: no cover - the assertion
                failures.append(e)

    threads = ThreadPoolExecutor(max_workers=6)
    futs = [threads.submit(hammer, t) for t in range(6)]
    time.sleep(0.4)
    os.kill(victim_pid, signal.SIGKILL)  # a worker process dies mid-load
    time.sleep(1.0)
    stop[0] = True
    for f in futs:
        f.result(timeout=60)
    threads.shutdown(wait=True)
    assert not failures, f"admitted requests failed: {failures[:3]}"
    assert served[0] > 0
    assert router.metrics.count("restarts") >= 1
    # the respawned worker rejoins within its budget (fresh interpreter
    # + jax import: allow generous wall clock)
    deadline = time.monotonic() + 120
    while router.live_workers < 2 and time.monotonic() < deadline:
        time.sleep(0.25)
    assert router.live_workers == 2, "killed worker was not respawned"
    assert router.worker_pids[0] != victim_pid
    # routing still works through the respawned worker
    with ThreadPoolExecutor(max_workers=8) as pool:
        list(pool.map(lambda i: router.predict(data[i % 8]), range(24)))


def test_e_bounded_shutdown_with_wedged_worker(router, data):
    # SIGSTOP a worker: its socket stays open but it answers nothing —
    # the worst wedge shape. Shutdown must stay bounded (drain timeout,
    # per-process join timeouts, terminate→kill escalation) and answer
    # every stranded request typed.
    victim_pid = router.worker_pids[0]
    os.kill(victim_pid, signal.SIGSTOP)
    try:
        futs = [router.submit(data[i % 8]) for i in range(8)]
        t0 = time.monotonic()
        router.shutdown(drain=True)
        elapsed = time.monotonic() - t0
        # drain 3s + join 2s (+ terminate/kill escalation ~4s) per the
        # fixture budgets, times some slack — never a hang
        assert elapsed < 30.0, f"shutdown took {elapsed:.1f}s"
        from concurrent.futures import TimeoutError as FutureTimeout

        from keystone_tpu.serving.errors import ServingError

        for f in futs:
            # a stranded future must be SETTLED (typed serving error or
            # a result) — a FutureTimeout here means shutdown left it
            # unanswered, which is exactly the bug this test exists for
            try:
                f.result(timeout=5.0)
            except FutureTimeout:
                raise AssertionError(
                    "shutdown left an admitted request unanswered"
                )
            except (ServingError, ConnectionError):
                pass  # typed answer: the contract held
        with pytest.raises(EngineStopped):
            router.submit(data[0])
    finally:
        try:
            os.kill(victim_pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
