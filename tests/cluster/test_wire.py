"""Wire protocol unit tests: framing, deadline transport, typed errors."""

import socket
import threading
import time

import numpy as np
import pytest

from keystone_tpu.cluster.wire import (
    ConnectionClosed,
    WorkerError,
    deadline_from_wire,
    deadline_to_wire,
    decode_error,
    encode_error,
    recv_msg,
    send_msg,
    send_payload,
)
from keystone_tpu.serving.errors import (
    DeadlineExceeded,
    EngineStopped,
    QueueFull,
    Shed,
)


def _pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_round_trip_with_arrays():
    a, b = _pair()
    try:
        msg = {
            "type": "req", "id": 7,
            "datum": np.arange(12, dtype=np.float32).reshape(3, 4),
        }
        send_msg(a, msg)
        got = recv_msg(b)
        assert got["type"] == "req" and got["id"] == 7
        np.testing.assert_array_equal(got["datum"], msg["datum"])
    finally:
        a.close()
        b.close()


def test_interleaved_frames_stay_ordered():
    a, b = _pair()
    try:
        lock = threading.Lock()

        def sender(lo, hi):
            for i in range(lo, hi):
                with lock:
                    send_msg(a, {"i": i})

        ts = [
            threading.Thread(target=sender, args=(k * 50, k * 50 + 50))
            for k in range(2)
        ]
        for t in ts:
            t.start()
        seen = sorted(recv_msg(b)["i"] for _ in range(100))
        for t in ts:
            t.join()
        assert seen == list(range(100))
    finally:
        a.close()
        b.close()


def test_eof_raises_connection_closed():
    a, b = _pair()
    a.close()
    with pytest.raises(ConnectionClosed):
        recv_msg(b)
    b.close()


def test_mid_frame_eof_raises_connection_closed():
    a, b = _pair()
    # a length prefix promising more bytes than ever arrive
    a.sendall(b"\x00\x00\x01\x00partial")
    a.close()
    with pytest.raises(ConnectionClosed, match="mid-frame"):
        recv_msg(b)
    b.close()


def test_deadline_travels_as_remaining_budget():
    deadline = time.monotonic() + 5.0
    rem = deadline_to_wire(deadline)
    assert 4.9 < rem <= 5.0
    rebuilt = deadline_from_wire(rem)
    # re-anchored on (this) clock: remaining budget is preserved, the
    # hop can only shrink it, never extend it
    assert rebuilt - time.monotonic() <= 5.0
    assert deadline_to_wire(None) is None
    assert deadline_from_wire(None) is None
    # an expired deadline stays expired (clamped, no wrap)
    assert deadline_to_wire(time.monotonic() - 10.0) == 0.0


@pytest.mark.parametrize(
    "exc", [Shed("late"), DeadlineExceeded("x"), QueueFull("full"),
            EngineStopped("bye")],
)
def test_typed_errors_round_trip(exc):
    back = decode_error(encode_error(exc))
    assert type(back) is type(exc)
    assert str(exc) in str(back)


def test_unknown_error_degrades_to_worker_error():
    class Weird(Exception):
        pass

    back = decode_error(encode_error(Weird("odd")))
    assert isinstance(back, WorkerError)
    assert "Weird" in str(back)


def test_send_timeout_knob(monkeypatch):
    from keystone_tpu.cluster.wire import _resolve_send_timeout

    monkeypatch.delenv("KEYSTONE_WIRE_SEND_TIMEOUT", raising=False)
    assert _resolve_send_timeout() == 15.0
    monkeypatch.setenv("KEYSTONE_WIRE_SEND_TIMEOUT", "7.5")
    assert _resolve_send_timeout() == 7.5
    # floored: a zero timeout would turn every full kernel buffer into
    # an instant false death
    monkeypatch.setenv("KEYSTONE_WIRE_SEND_TIMEOUT", "0")
    assert _resolve_send_timeout() == 0.1
    # unparsable degrades to the default (env_float WARNs once)
    monkeypatch.setenv("KEYSTONE_WIRE_SEND_TIMEOUT", "soon")
    assert _resolve_send_timeout() == 15.0


def test_stalled_send_degrades_typed():
    # the peer stops reading: sendall must hit the socket timeout and
    # surface as ConnectionClosed, not hold the send lock forever
    a, b = _pair()
    try:
        a.settimeout(0.2)
        chunk = b"\x00" * (1 << 20)
        with pytest.raises(ConnectionClosed, match="stopped reading"):
            for _ in range(256):  # far beyond any kernel buffer
                send_payload(a, chunk)
    finally:
        a.close()
        b.close()
