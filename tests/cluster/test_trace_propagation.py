"""Cross-process trace propagation (ISSUE 15): a traced request through
the real 2-worker router stitches into one span tree with per-hop
wire/queue attribution and real per-pid process tracks.

One module-scoped traced router serves every test (worker boots pay a
fresh interpreter each); tests run in definition order and only ever ADD
spans, so earlier traffic never invalidates a later assertion.
"""

import time
from collections import defaultdict

import numpy as np
import pytest

from keystone_tpu.cluster import ClusterRouter
from keystone_tpu.obs import tracer as trace_mod
from keystone_tpu.obs.context import Sampler

D = 32
STALL_S = 0.002

#: the hop span names each tier contributes to a stitched request
ROUTER_HOPS = {"rpc.admission", "rpc.send", "rpc.request"}
WORKER_HOPS = {"cluster.handle", "serve.queue", "serve.replica"}


@pytest.fixture(scope="module")
def tracer():
    prev = trace_mod.stop()  # nothing else should be installed, but be safe
    t = trace_mod.install(trace_mod.Tracer())
    yield t
    trace_mod.stop()
    if prev is not None:
        trace_mod.install(prev)


@pytest.fixture(scope="module")
def router(tracer):
    r = ClusterRouter(
        ("factory", "keystone_tpu.cluster.demo:build_stall_model",
         {"d": D, "stall_s": STALL_S}),
        workers=2,
        replicas_per_worker=1,
        buckets=(8,),
        datum_shape=(D,),
        max_wait_ms=1.0,
        spawn_timeout_s=180,
        # a fast health loop: worker pings drive their timeline sampling
        health_interval_s=0.25,
        drain_timeout_s=5.0,
        join_timeout_s=3.0,
    )
    r.start()
    yield r
    r.shutdown(drain=False)


@pytest.fixture(scope="module")
def data():
    return np.random.RandomState(0).randn(16, D).astype(np.float32)


def _events_by_trace(span_sets):
    by_trace = defaultdict(list)
    for spans in span_sets:
        for s in spans:
            tid = (s.get("args") or {}).get("trace_id")
            if tid:
                by_trace[tid].append(s)
    return by_trace


def test_a_one_request_stitches_across_three_hops(router, data):
    router.predict(data[0], timeout=30.0)
    # the worker records its spans as the reply leaves: a stats
    # round-trip racing the reply can miss them, and collection
    # ACCUMULATES, so poll until the fullest trace is whole (a lost
    # race only means the spans arrive on a later round-trip)
    deadline = time.monotonic() + 10.0
    while True:
        span_sets = router.collect_trace(timeout=10.0)
        by_trace = _events_by_trace(span_sets)
        if by_trace:
            tid, spans = max(
                by_trace.items(),
                key=lambda kv: len({s["name"] for s in kv[1]}),
            )
            names = {s["name"] for s in spans}
            if names >= ROUTER_HOPS | WORKER_HOPS:
                break
        if time.monotonic() >= deadline:
            break
        time.sleep(0.1)
    assert by_trace, "no trace ids propagated"
    pids = {s["pid"] for s in spans}
    assert names >= ROUTER_HOPS | WORKER_HOPS, names
    assert len(pids) >= 2, pids  # router + worker process tracks
    # per-hop attribution: wire transport on the worker-residency hop,
    # queue wait on the scheduler hop, reply transport on the round-trip
    handle = next(s for s in spans if s["name"] == "cluster.handle")
    assert handle["args"]["transport_s"] >= 0.0
    queue = next(s for s in spans if s["name"] == "serve.queue")
    assert queue["args"]["queue_age_s"] >= 0.0
    rpc = next(s for s in spans if s["name"] == "rpc.request")
    assert rpc["args"]["reply_transport_s"] >= 0.0
    assert rpc["args"]["ok"] is True
    # the round-trip bounds every hop: each hop fits inside it (unix
    # clocks are shared on-host; 50ms slack absorbs clock fuzz)
    lo = rpc["start_unix"] - 0.05
    hi = rpc["start_unix"] + rpc["dur_s"] + 0.05
    for s in spans:
        assert lo <= s["start_unix"] <= hi, (s["name"], s, rpc)


def test_b_stitched_export_has_process_tracks(router, data, tmp_path):
    router.predict(data[1], timeout=30.0)
    import json

    path = router.export_trace(str(tmp_path / "stitched.json"))
    doc = json.loads(open(path).read())
    ev = doc["traceEvents"]
    proc_meta = {
        e["pid"]: e["args"]["name"]
        for e in ev if e["name"] == "process_name"
    }
    # distinct pids: the router and both workers announce themselves
    assert len(proc_meta) >= 3, proc_meta
    assert any("router" in n for n in proc_meta.values())
    assert sum("worker" in n for n in proc_meta.values()) >= 2
    # thread metadata rides per process too
    named_threads = {
        (e["pid"], e["tid"]) for e in ev if e["name"] == "thread_name"
    }
    assert len({p for p, _ in named_threads}) >= 2
    ts = [e["ts"] for e in ev]
    assert all(b >= a for a, b in zip(ts, ts[1:])), "non-monotonic ts"
    assert all(e["ts"] >= 0.0 for e in ev)


def test_c_status_renders_per_process_timelines(router, data):
    router.predict(data[2], timeout=30.0)
    # let the health loop tick: router timeline samples + worker pings
    deadline = time.monotonic() + 15.0
    status = router.status(timeout=10.0)
    while time.monotonic() < deadline:
        tl = status["timelines"]
        if {"worker-0", "worker-1", "cluster-router"} <= set(tl) and all(
            tl[k] for k in ("worker-0", "worker-1", "cluster-router")
        ):
            break
        time.sleep(0.3)
        status = router.status(timeout=10.0)
    tl = status["timelines"]
    assert {"worker-0", "worker-1", "cluster-router"} <= set(tl), tl.keys()
    row = tl["cluster-router"][-1]
    assert {"ts", "counters", "gauges", "latency", "queue_age"} <= set(row)
    assert status["live_workers"] == 2
    assert [w["index"] for w in status["workers"]] == [0, 1]
    assert status["counters"].get("completed", 0) >= 1
    # the text rendering never crashes and shows the timeline lines
    from keystone_tpu.cluster import format_status

    text = format_status(status)
    assert "timeline [worker-0]" in text and "workers 2/2" in text


def test_d_sampling_knob_bounds_span_production(router, data, tracer):
    # rate 0.5 => exactly every 2nd submit mints a trace context
    router._sampler = Sampler(0.5)
    try:
        _, cursor = tracer.spans_since(0)
        for i in range(4):
            router.predict(data[3 + i], timeout=30.0)
        fresh, _ = tracer.spans_since(cursor)
        rpc = [s for s in fresh if s.name == "rpc.request"]
        assert len(rpc) == 2, [s.name for s in fresh]
    finally:
        router._sampler = Sampler(1.0)
