"""Shared-memory ring unit tests: slot lifecycle, exhaustion, bounds,
cross-mapping visibility, and creator-owned unlink semantics.
"""

from multiprocessing import resource_tracker

import numpy as np
import pytest

from keystone_tpu.cluster.codec import CodecError
from keystone_tpu.cluster.shm import ShmRing, make_ring_pair


def _attach(name, slots, slot_bytes):
    """Attach a second mapping in THIS process. In production the
    attacher is a different process, so dropping its tracker claim
    (ShmRing's 3.10 double-unlink guard) is free; here creator and
    attacher share one tracker, so restore the creator's claim to keep
    the exit-time ledger balanced."""
    ring = ShmRing(name, slots, slot_bytes, create=False)
    resource_tracker.register(f"/{name}", "shared_memory")
    return ring


@pytest.fixture
def ring():
    r = ShmRing("kstshmtest", slots=3, slot_bytes=256, create=True)
    yield r
    r.close()
    r.unlink()


def test_alloc_write_view_free_cycle(ring):
    data = bytes(range(200))
    slot = ring.alloc(len(data))
    assert slot is not None
    ring.write(slot, data)
    assert ring.in_use == 1
    view = ring.view(slot, len(data))
    assert bytes(view) == data
    del view  # release the buffer before reclaiming
    ring.free(slot)
    assert ring.in_use == 0


def test_exhaustion_returns_none_then_recovers(ring):
    slots = [ring.alloc(10) for _ in range(3)]
    assert all(s is not None for s in slots)
    assert len(set(slots)) == 3  # distinct slots, no double-alloc
    assert ring.alloc(10) is None  # full: caller degrades inline
    ring.free(slots[1])
    assert ring.alloc(10) == slots[1]  # freed slot is reusable


def test_oversized_payload_returns_none(ring):
    assert ring.alloc(257) is None  # bigger than any slot: inline
    assert ring.alloc(256) is not None  # exactly a slot fits


def test_out_of_range_descriptor_raises_codec_error(ring):
    # a corrupt frame's slot descriptor must degrade typed, not index
    # out of the segment
    with pytest.raises(CodecError):
        ring.view(7, 10)
    with pytest.raises(CodecError):
        ring.view(0, 1 << 20)
    ring.free(99)  # out-of-range free is ignored, not an error


def test_attached_mapping_sees_writers_bytes(ring):
    # same-host second mapping (what the worker does with the spec's
    # names): bytes written through one mapping are simply THERE in the
    # other, and the state table is shared too
    arr = np.arange(32, dtype=np.float64)
    slot = ring.alloc(arr.nbytes)
    ring.write(slot, memoryview(arr).cast("B"))
    peer = _attach("kstshmtest", slots=3, slot_bytes=256)
    try:
        assert peer.in_use == 1
        got = np.frombuffer(
            bytes(peer.view(slot, arr.nbytes)), dtype=np.float64
        )
        np.testing.assert_array_equal(got, arr)
        peer.free(slot)  # reader-side reclamation...
        assert ring.in_use == 0  # ...visible to the creator
    finally:
        peer.close()


def test_closed_ring_stops_allocating(ring):
    ring.close()
    assert ring.alloc(10) is None


def test_unlink_is_creator_only_and_idempotent():
    creator = ShmRing("kstshmunlink", slots=1, slot_bytes=64, create=True)
    attached = _attach("kstshmunlink", slots=1, slot_bytes=64)
    attached.close()
    attached.unlink()  # attach side: a no-op, the segment survives
    still = _attach("kstshmunlink", slots=1, slot_bytes=64)
    still.close()
    creator.close()
    creator.unlink()
    creator.unlink()  # idempotent
    with pytest.raises(FileNotFoundError):
        ShmRing("kstshmunlink", slots=1, slot_bytes=64, create=False)


def test_make_ring_pair_creates_both_directions():
    c2w, w2c = make_ring_pair("kstshmpair", slots=2, slot_bytes=128)
    try:
        assert c2w is not None and w2c is not None
        assert c2w.name == "kstshmpairc" and w2c.name == "kstshmpairr"
        assert c2w.alloc(64) is not None and w2c.alloc(64) is not None
    finally:
        for r in (c2w, w2c):
            r.close()
            r.unlink()


def test_degenerate_geometry_rejected():
    with pytest.raises(ValueError):
        ShmRing("kstshmbad", slots=0, slot_bytes=64, create=True)
    with pytest.raises(ValueError):
        ShmRing("kstshmbad", slots=1, slot_bytes=0, create=True)
