"""The examples/ launcher scripts (SURVEY §1 layer 7: the reference ships
per-workload canned configs) must stay in sync with the CLI: every app
name they dispatch is registered, and every flag they pass exists in the
target pipeline's argparse. Static checks — the pipelines themselves are
exercised by their own e2e tests."""

import importlib
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
EXAMPLES = os.path.join(REPO, "examples")

_APP_MODULES = {
    "MnistRandomFFT": "mnist_random_fft",
    "RandomPatchCifar": "random_patch_cifar",
    "VOCSIFTFisher": "voc_sift_fisher",
    "ImageNetSiftLcsFV": "imagenet_sift_lcs_fv",
    "TimitPipeline": "timit",
    "NewsgroupsPipeline": "newsgroups",
    "AmazonReviewsPipeline": "amazon_reviews",
    "StupidBackoffPipeline": "stupid_backoff_pipeline",
}


def _scripts():
    out = []
    for root, _, files in os.walk(EXAMPLES):
        out += [os.path.join(root, f) for f in files if f.endswith(".sh")]
    return sorted(out)


def test_examples_exist():
    assert len(_scripts()) >= 8


@pytest.mark.parametrize("path", _scripts())
def test_example_script_app_and_flags_exist(path):
    src = open(path).read()
    m = re.search(r'run-pipeline\.sh"\s+(\w+)', src)
    assert m, f"no app dispatch in {path}"
    app = m.group(1)

    from keystone_tpu.__main__ import PIPELINES

    assert app in PIPELINES, f"{path}: unknown app {app}"
    module = importlib.import_module(
        f"keystone_tpu.pipelines.{_APP_MODULES[app]}"
    )
    pipeline_src = open(module.__file__).read()
    for flag in set(re.findall(r"(--[A-Za-z][A-Za-z0-9]*)", src)):
        assert f'"{flag}"' in pipeline_src, (
            f"{path}: flag {flag} not in {module.__name__}'s argparse"
        )
