"""End-to-end: VOCSIFTFisher and ImageNetSiftLcsFV run from real tar-of-JPEG
paths through their ``main()`` CLIs (VERDICT r2 missing #1 — previously these
pipelines had only ever seen synthetic gratings)."""

import os

import pytest

REF = "/root/reference/src/test/resources/images"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not mounted"
)


def test_voc_sift_fisher_from_tar(capsys):
    from keystone_tpu.pipelines.voc_sift_fisher import main

    rc = main([
        "--trainLocation", os.path.join(REF, "voc"),
        "--labelPath", os.path.join(REF, "voclabels.csv"),
        "--imageSize", "64",
        "--vocabSize", "2",
        "--descDim", "4",
        "--numPcaSamples", "2000",
        "--numGmmSamples", "2000",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Mean Average Precision" in out


def test_imagenet_sift_lcs_fv_from_tar(capsys):
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import main

    rc = main([
        "--trainLocation", os.path.join(REF, "imagenet"),
        "--labelsFile", os.path.join(REF, "imagenet-test-labels"),
        "--imageSize", "64",
        "--numClasses", "13",
        "--vocabSize", "2",
        "--descDim", "4",
        "--numPcaSamples", "2000",
        "--numGmmSamples", "2000",
        "--lcsBorder", "8",
        "--lcsStride", "6",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "TEST Error" in out
