"""TimitPipeline + remaining CIFAR apps (parity slices:
TimitPipeline.scala, LinearPixels.scala, RandomCifar.scala,
RandomPatchCifarAugmented.scala, RandomPatchCifarKernel.scala) and the
KRR streaming/checkpoint mechanics the kernel app forces."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.loaders.cifar import synthetic_cifar
from keystone_tpu.nodes.learning.kernel import (
    BlockKernelMatrix,
    KernelRidgeRegression,
)
from keystone_tpu.nodes.util import ClassLabelIndicators


def test_timit_pipeline_synthetic():
    from keystone_tpu.pipelines.timit import (
        TimitConfig,
        run,
        synthetic_timit,
    )

    conf = TimitConfig(
        num_cosines=3, num_epochs=2, lam=10.0, num_classes=12,
        cosine_features=256, gamma=0.02,
    )
    train = synthetic_timit(768, conf.num_classes, seed=1)
    test = synthetic_timit(256, conf.num_classes, seed=2)
    _, evaluation, _ = run(train, test, conf)
    # 12 Gaussian prototype classes: random errs ~92%
    assert evaluation.total_error < 0.2, evaluation.summary()


def test_timit_cauchy_branch_shapes():
    from keystone_tpu.pipelines.timit import TimitConfig, build_featurizer

    conf = TimitConfig(num_cosines=2, rf_type="cauchy",
                       cosine_features=64, input_dim=20)
    X = np.random.default_rng(0).standard_normal((8, 20)).astype(np.float32)
    out = np.asarray(build_featurizer(conf)(X).get().to_array())
    assert out.shape == (8, 2 * 64)


def test_linear_pixels():
    from keystone_tpu.pipelines.cifar_extras import run_linear_pixels

    train = synthetic_cifar(512, seed=1)
    test = synthetic_cifar(128, seed=2)
    _, tr, te, _ = run_linear_pixels(train, test, lam=10.0)
    assert te < 0.5  # grayscale pixels alone beat the 90% random error


def test_random_cifar():
    from keystone_tpu.pipelines.cifar_extras import run_random_cifar
    from keystone_tpu.pipelines.random_patch_cifar import RandomCifarConfig

    conf = RandomCifarConfig(num_filters=32, lam=10.0)
    train = synthetic_cifar(256, seed=3)
    test = synthetic_cifar(96, seed=4)
    _, tr, te, _ = run_random_cifar(train, test, conf)
    assert te < 0.6


def test_random_patch_cifar_augmented():
    from keystone_tpu.pipelines.cifar_extras import (
        AugmentedCifarConfig,
        run_random_patch_cifar_augmented,
    )

    conf = AugmentedCifarConfig(
        num_filters=24, lam=50.0, whitener_size=3000,
        num_random_images_augment=2, pool_size=8, pool_stride=7,
    )
    train = synthetic_cifar(192, seed=5)
    test = synthetic_cifar(48, seed=6)
    _, evaluation, _ = run_random_patch_cifar_augmented(train, test, conf)
    assert evaluation.total_error < 0.6


def test_random_patch_cifar_kernel_streaming():
    from keystone_tpu.pipelines.cifar_extras import (
        KernelCifarConfig,
        run_random_patch_cifar_kernel,
    )

    conf = KernelCifarConfig(
        num_filters=16, lam=1.0, gamma=1e-3, block_size=64,
        num_epochs=1, cache_kernel=False, whitener_size=2000,
        pool_size=8, pool_stride=7,
    )
    train = synthetic_cifar(192, seed=7)
    test = synthetic_cifar(48, seed=8)
    _, tr, te, _ = run_random_patch_cifar_kernel(train, test, conf)
    assert te < 0.7


# ---- KRR streaming + checkpoint mechanics --------------------------------

def _krr_problem(n=180, d=10, k=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.integers(0, k, size=n)
    Y = np.asarray(
        ClassLabelIndicators(k).apply_batch(Dataset.of(y)).to_array()
    )
    return X, Y


def test_krr_cache_blocks_false_matches_cached():
    X, Y = _krr_problem()
    common = dict(gamma=0.1, lam=1.0, block_size=48, num_epochs=2,
                  block_permuter=3)
    m_cached = KernelRidgeRegression(cache_kernel=True, **common).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    m_stream = KernelRidgeRegression(cache_kernel=False, **common).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    np.testing.assert_allclose(
        np.asarray(m_cached.W), np.asarray(m_stream.W), rtol=1e-4, atol=1e-5
    )


def test_krr_streaming_mode_frees_blocks():
    X, Y = _krr_problem()
    kernel = BlockKernelMatrix(X, 0.1, cache_blocks=False)
    _ = kernel.block(np.arange(0, 48))
    assert kernel._cache == {}
    kernel_cached = BlockKernelMatrix(X, 0.1, cache_blocks=True)
    _ = kernel_cached.block(np.arange(0, 48))
    assert len(kernel_cached._cache) == 1
    kernel_cached.unpersist(np.arange(0, 48))
    assert kernel_cached._cache == {}


def test_krr_checkpoint_resume(tmp_path, monkeypatch):
    """A fit killed mid-solve resumes from the last checkpoint and lands on
    the same model as an uninterrupted run (the truncateLineage-analogue
    restart story, KernelRidgeRegression.scala:204-208)."""
    X, Y = _krr_problem(n=200)
    common = dict(gamma=0.1, lam=1.0, block_size=40, num_epochs=2,
                  block_permuter=5)
    ref = KernelRidgeRegression(**common).fit(Dataset.of(X), Dataset.of(Y))

    est = KernelRidgeRegression(
        checkpoint_dir=str(tmp_path), checkpoint_interval=1, **common
    )
    # the kill seam is the fused fit path's kernel-block generation
    import keystone_tpu.nodes.learning.kernel as kernel_mod

    orig_gen = kernel_mod._kernel_block_slice
    calls = {"n": 0}

    def dying_gen(X_, start, gamma, bs):
        calls["n"] += 1
        if calls["n"] > 4:
            raise RuntimeError("simulated preemption")
        return orig_gen(X_, start, gamma, bs)

    monkeypatch.setattr(kernel_mod, "_kernel_block_slice", dying_gen)
    with pytest.raises(RuntimeError):
        est.fit(Dataset.of(X), Dataset.of(Y))
    monkeypatch.setattr(kernel_mod, "_kernel_block_slice", orig_gen)
    assert (tmp_path / "krr_state.npz").exists()

    resumed = est.fit(Dataset.of(X), Dataset.of(Y))
    np.testing.assert_allclose(
        np.asarray(resumed.W), np.asarray(ref.W), rtol=1e-4, atol=1e-5
    )
    # completed fit removes the restart state
    assert not (tmp_path / "krr_state.npz").exists()
