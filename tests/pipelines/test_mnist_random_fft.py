"""End-to-end MnistRandomFFT on synthetic data — the minimum slice of
SURVEY §7 step 3 and BASELINE metric #1, run small on the CPU mesh."""

import numpy as np

from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.pipelines.mnist_random_fft import (
    MnistRandomFFTConfig,
    run,
    synthetic_mnist,
)


def test_mnist_random_fft_end_to_end():
    # The synthetic task has a calibrated ~4% Bayes error (overlapping
    # classes — VERDICT r3 #2), so n_train must exceed the d=1024 feature
    # dim for the test error to mean anything (at n=d the interpolating
    # solve memorizes noise).
    train, test = synthetic_mnist(n_train=4096, n_test=512, seed=7)
    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=10.0)
    pipeline, train_err, test_err, seconds = run(train, test, conf)
    assert train_err < 0.15, f"train error {train_err}"
    assert test_err < 0.35, f"test error {test_err}"


def test_block_solver_multiblock_agrees_with_exact():
    """BlockLeastSquares with several blocks and iterations ≈ exact OLS
    (parity: BlockLinearMapperSuite.scala:19-56)."""
    rng = np.random.default_rng(0)
    n, d, k = 256, 32, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    W = rng.standard_normal((d, k)).astype(np.float32)
    Y = X @ W + 0.01 * rng.standard_normal((n, k)).astype(np.float32)

    exact = LinearMapEstimator(lam=0.01).fit(Dataset.of(X), Dataset.of(Y))
    block = BlockLeastSquaresEstimator(8, 20, lam=0.01).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    pe = np.asarray(exact.apply_batch(Dataset.of(X)).to_array())
    pb = np.asarray(block.apply_batch(Dataset.of(X)).to_array())
    np.testing.assert_allclose(pb, pe, rtol=1e-2, atol=1e-2)


def test_block_solver_apply_blocks_matches_fused():
    rng = np.random.default_rng(1)
    n, d, k = 64, 12, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    model = BlockLeastSquaresEstimator(4, 2, lam=0.1).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    fused = np.asarray(model.apply_batch(Dataset.of(X)).to_array())
    blocks = [X[:, i : i + 4] for i in range(0, d, 4)]
    via_blocks = np.asarray(model.apply_blocks(blocks))
    np.testing.assert_allclose(via_blocks, fused, rtol=1e-4, atol=1e-4)


def test_multiclass_evaluator_metrics():
    ev = MulticlassClassifierEvaluator(3)
    preds = [0, 1, 2, 2, 1, 0]
    actual = [0, 1, 1, 2, 1, 2]
    m = ev.evaluate(preds, actual)
    assert m.confusion_matrix.sum() == 6
    assert m.confusion_matrix[0, 0] == 1  # actual 0 predicted 0
    assert m.confusion_matrix[1, 2] == 1  # actual 1 predicted 2
    assert abs(m.total_accuracy - 4 / 6) < 1e-9
    assert abs(m.total_error - 2 / 6) < 1e-9
