"""RandomPatchCifar end-to-end on synthetic CIFAR-shaped data (SURVEY §7
step 4 parity slice)."""

import numpy as np

from keystone_tpu.loaders.cifar import load_cifar, synthetic_cifar
from keystone_tpu.pipelines.random_patch_cifar import (
    RandomCifarConfig,
    run,
)


def test_cifar_loader_binary_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    n = 5
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    imgs = rng.integers(0, 256, (n, 3, 32, 32), dtype=np.uint8)
    rec = np.concatenate([labels[:, None], imgs.reshape(n, -1)], axis=1)
    f = tmp_path / "data_batch_1.bin"
    rec.astype(np.uint8).tofile(f)

    ld = load_cifar(str(f))
    assert len(ld) == n
    np.testing.assert_array_equal(
        np.asarray(ld.labels.to_array()), labels.astype(np.int32)
    )
    X = np.asarray(ld.data.to_array())
    assert X.shape == (n, 32, 32, 3)
    # X[n, row, col, chan] == raw plane value
    np.testing.assert_allclose(X[0, 2, 3, 1], float(imgs[0, 1, 2, 3]))


def test_random_patch_cifar_end_to_end():
    train = synthetic_cifar(512, seed=1)
    test = synthetic_cifar(128, seed=2)
    conf = RandomCifarConfig(
        num_filters=32,
        patch_steps=2,
        whitener_size=2000,
        lam=100.0,
        seed=0,
    )
    _, train_err, test_err, _ = run(train, test, conf)
    # chance is 90% error; synthetic prototypes are easily separable
    assert train_err < 0.1, f"train error {train_err}"
    assert test_err < 0.3, f"test error {test_err}"
