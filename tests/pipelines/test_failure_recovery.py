"""Restart recovery across a REAL process death (VERDICT r2 §104): a KRR
fit hard-killed mid-solve (os._exit — no finally blocks, no atexit) must
resume in a fresh process from the on-disk checkpoint and land on the same
model as an uninterrupted run. This is the process-level counterpart of
the in-process simulated-preemption test in test_timit_cifar_extras.py —
it additionally proves the checkpoint is durably on disk at kill time."""

import os
import subprocess
import sys

import numpy as np

_WORKER = r"""
import os, sys
import numpy as np

import keystone_tpu  # noqa: F401  (registers compile cache)
import keystone_tpu.nodes.learning.kernel as kernel_mod
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning.kernel import KernelRidgeRegression

ckpt_dir = sys.argv[1]
out_file = sys.argv[2]
kill_after = int(sys.argv[3])

rng = np.random.default_rng(7)
X = rng.standard_normal((200, 16)).astype(np.float32)
W_true = rng.standard_normal((16, 3)).astype(np.float32)
Y = (X @ W_true + 0.01 * rng.standard_normal((200, 3))).astype(np.float32)

if kill_after > 0:
    # the kill seam is the fused fit path's kernel-block generation
    orig = kernel_mod._kernel_block_slice
    calls = {"n": 0}

    def dying(X, start, gamma, bs):
        calls["n"] += 1
        if calls["n"] > kill_after:
            os._exit(42)  # hard death: no finally, no atexit
        return orig(X, start, gamma, bs)

    kernel_mod._kernel_block_slice = dying

est = KernelRidgeRegression(
    gamma=0.1, lam=1.0, block_size=40, num_epochs=2, block_permuter=5,
    checkpoint_dir=ckpt_dir, checkpoint_interval=1,
)
model = est.fit(Dataset.of(X), Dataset.of(Y))
np.savez(out_file, W=np.asarray(model.W))
"""


def _run_worker(tmp_path, ckpt_dir, out_file, kill_after):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, str(worker), str(ckpt_dir), str(out_file),
         str(kill_after)],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_krr_survives_process_kill_and_resumes(tmp_path):
    ckpt = tmp_path / "ckpt"
    # uninterrupted reference run (no checkpoint dir interference)
    ref_out = tmp_path / "ref.npz"
    r = _run_worker(tmp_path, tmp_path / "ckpt_ref", ref_out, kill_after=0)
    assert r.returncode == 0, r.stderr[-2000:]

    # killed run: process dies hard mid-solve
    out = tmp_path / "out.npz"
    r = _run_worker(tmp_path, ckpt, out, kill_after=4)
    assert r.returncode == 42, (r.returncode, r.stderr[-2000:])
    assert not out.exists()  # it really died before finishing
    assert (ckpt / "krr_state.npz").exists()  # durable state at death

    # fresh process resumes from disk and completes
    r = _run_worker(tmp_path, ckpt, out, kill_after=0)
    assert r.returncode == 0, r.stderr[-2000:]
    W_res = np.load(out)["W"]
    W_ref = np.load(ref_out)["W"]
    np.testing.assert_allclose(W_res, W_ref, rtol=1e-4, atol=1e-5)
    # completed fit removes the restart state
    assert not (ckpt / "krr_state.npz").exists()
