"""CLI front door: python -m keystone_tpu <PipelineName> dispatches to the
pipeline mains (parity: bin/run-pipeline.sh:34-56)."""

import pytest

from keystone_tpu.__main__ import PIPELINES, main


def test_registry_covers_reference_applications():
    expected = {
        "MnistRandomFFT", "LinearPixels", "RandomCifar", "RandomPatchCifar",
        "RandomPatchCifarAugmented", "RandomPatchCifarKernel",
        "VOCSIFTFisher", "ImageNetSiftLcsFV", "TimitPipeline",
        "NewsgroupsPipeline", "AmazonReviewsPipeline", "StupidBackoffPipeline",
    }
    assert set(PIPELINES) == expected


def test_dispatch_runs_mnist(capsys):
    rc = main(["MnistRandomFFT", "--numFFTs", "2", "--blockSize", "512",
               "--lambda", "100"])
    assert rc == 0
    assert "TEST Error" in capsys.readouterr().out


def test_unknown_pipeline_rejected():
    with pytest.raises(SystemExit):
        main(["NoSuchPipeline"])
