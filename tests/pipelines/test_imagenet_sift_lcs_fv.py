"""ImageNetSiftLcsFV end-to-end on synthetic textured images
(parity slice: ImageNetSiftLcsFV.scala:19-204, BASELINE metric #2)."""

import numpy as np

from keystone_tpu.nodes.learning.weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    build_predictor,
    run,
    synthetic_imagenet,
    top_k_err_percent,
)
from keystone_tpu.workflow.pipeline import FittedPipeline


def test_top_k_err_percent_oracle():
    topk = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]])
    actual = np.array([1, 9, 8])  # hit, miss, hit
    assert abs(top_k_err_percent(topk, actual) - 100.0 / 3.0) < 1e-9


def test_imagenet_sift_lcs_fv_end_to_end():
    num_classes = 16
    tr_i, tr_l = synthetic_imagenet(96, num_classes, size=48, seed=1)
    te_i, te_l = synthetic_imagenet(48, num_classes, size=48, seed=2)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=16,
        vocab_size=4,
        num_pca_samples=20_000,
        num_gmm_samples=20_000,
        num_classes=num_classes,
        lam=1e-4,
    )
    predictor, err, _ = run(tr_i, tr_l, te_i, te_l, conf)
    # top-5 of 16 classes: random scoring errs ~68.75%; the gratings are
    # separable so the gathered SIFT+LCS FV features must do far better.
    assert err < 25.0, f"top-5 error {err}%"
    # predictions are a (n, 5) int index matrix
    out = np.asarray(predictor(te_i).get().to_array())
    assert out.shape == (48, 5)


def test_calibrated_gradient_signal_gates(monkeypatch):
    """VERDICT r4 #5: a quality signal that (a) has a computable Bayes
    error, (b) REWARDS the featurizer — raw pixels are near chance because
    the class signal is a second-order (gradient) statistic — and (c) has
    teeth: a SIFT whose orientation layer is collapsed must blow the gate."""
    import jax.numpy as jnp

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.sift import SIFTExtractor
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        synthetic_gradient_imagenet,
    )
    from keystone_tpu.workflow.env import PipelineEnv

    num_classes = 16
    gen = dict(num_classes=num_classes, size=48, theta_sigma=0.12,
               logf_sigma=0.10)
    tr_i, tr_l, bayes = synthetic_gradient_imagenet(256, seed=1, **gen)
    te_i, te_l, _ = synthetic_gradient_imagenet(128, seed=2, **gen)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=16, vocab_size=8, num_pca_samples=40_000,
        num_gmm_samples=40_000, num_classes=num_classes, lam=1e-4,
    )
    gate = 2.5 * bayes  # achievable-for-a-working-featurizer band

    pred = build_predictor(tr_i, tr_l, conf)
    topk = np.asarray(pred(te_i).get().to_array())
    top1 = 100.0 * float((topk[:, 0] != te_l).mean())
    assert bayes * 0.5 <= top1 <= gate, (top1, bayes)

    # raw pixels: the same data through a plain linear solve — near chance
    Xtr = jnp.asarray(tr_i.reshape(len(tr_i), -1), jnp.float32) / 255.0
    Xte = jnp.asarray(te_i.reshape(len(te_i), -1), jnp.float32) / 255.0
    Y = ClassLabelIndicators(num_classes).apply_batch(
        Dataset.of(tr_l)
    ).to_array()
    m = LinearMapEstimator(lam=10.0).fit(
        Dataset.of(Xtr), Dataset.of(jnp.asarray(Y))
    )
    raw_err = 100.0 * float(
        (np.asarray(jnp.argmax(m.trace_batch(Xte), axis=1)) != te_l).mean()
    )
    assert raw_err > 2 * top1 and raw_err > 40.0, (raw_err, top1)

    # broken featurizer: average away the 8 orientation bins (layout
    # t + 8·i + 32·j, sift.py:16) — the gate must catch it
    PipelineEnv.get_or_create().reset()
    orig = SIFTExtractor.trace_batch

    def broken(self, X):
        D = orig(self, X)  # (n, 128, N)
        n, d, m_ = D.shape
        D4 = D.reshape(n, d // 8, 8, m_)
        return jnp.broadcast_to(
            D4.mean(axis=2, keepdims=True), D4.shape
        ).reshape(n, d, m_)

    monkeypatch.setattr(SIFTExtractor, "trace_batch", broken)
    # the fused-executable and segment-dispatcher caches key on op
    # type+params (not code), so a monkeypatched trace_batch would
    # otherwise be served the healthy compiled program
    from keystone_tpu.compile.segment import reset_dispatchers
    from keystone_tpu.workflow.fusion import _FUSED_JIT_CACHE

    _FUSED_JIT_CACHE.clear()
    reset_dispatchers()
    broken_topk = np.asarray(
        build_predictor(tr_i, tr_l, conf)(te_i).get().to_array()
    )
    broken_err = 100.0 * float((broken_topk[:, 0] != te_l).mean())
    assert broken_err > gate, (broken_err, gate)


def test_imagenet_fit_from_chunked_source(monkeypatch):
    """Out-of-core fit (VERDICT r4 #1): train images arrive as a
    ChunkedDataset; both featurizer branches run chunk-by-chunk (one
    combined sampling scan per branch), the gathered FV features zip
    per-chunk, and the solver consumes them without the full descriptor
    stacks ever materializing. Run twice — once with the featurized set
    under the HBM budget (materialize+solve) and once forced over budget
    (the streaming weighted trainer) — both must produce a working model."""
    from keystone_tpu.data import ChunkedDataset

    num_classes = 8
    tr_i, tr_l = synthetic_imagenet(48, num_classes, size=48, seed=1)
    te_i, te_l = synthetic_imagenet(24, num_classes, size=48, seed=2)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8,
        vocab_size=4,
        num_pca_samples=20_000,
        num_gmm_samples=20_000,
        num_classes=num_classes,
        lam=1e-4,
    )
    chunked = ChunkedDataset.from_array(tr_i, 13)  # ragged chunk boundaries
    predictor, err, _ = run(chunked, tr_l, te_i, te_l, conf)
    assert err < 40.0, f"top-5 error {err}%"

    from keystone_tpu.workflow.env import PipelineEnv

    PipelineEnv.get_or_create().reset()
    monkeypatch.setenv("KEYSTONE_CHUNK_CACHE_BUDGET", "1")
    predictor2, err2, _ = run(chunked, tr_l, te_i, te_l, conf)
    assert err2 < 40.0, f"top-5 error (streaming solver) {err2}%"


def test_fitted_apply_reproduces_fit_time_features(monkeypatch):
    """Regression: FittedPipeline.apply must execute the exact program
    partitioning fit() used. Re-fusing the transformer chain after fit
    compiled the Fisher-Vector posterior math into a new XLA program whose
    reassociated f32 arithmetic flipped near-tied component assignments —
    apply-time features silently diverged from what the solver trained on
    (train top-5 error went 0% → 40%)."""
    cap = {}
    orig = BlockWeightedLeastSquaresEstimator.fit

    def spy(self, data, labels):
        cap["X"] = np.asarray(data.to_array())
        return orig(self, data, labels)

    monkeypatch.setattr(BlockWeightedLeastSquaresEstimator, "fit", spy)
    num_classes = 8
    tr_i, tr_l = synthetic_imagenet(32, num_classes, size=48, seed=1)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8,
        vocab_size=4,
        num_pca_samples=20_000,
        num_gmm_samples=20_000,
        num_classes=num_classes,
        lam=1e-4,
    )
    fitted = build_predictor(tr_i, tr_l, conf).fit()

    # cut the fitted graph at the solver's input and re-apply to train data
    g = fitted.graph
    topk = [
        n for n in g.nodes
        if type(g.get_operator(n)).__name__ == "TopKClassifier"
    ][0]
    solver = g.get_dependencies(topk)[0]
    feat = g.get_dependencies(solver)[0]
    g2, sink2 = g.add_sink(feat)
    sub = FittedPipeline(g2, fitted._source, sink2)
    X_apply = np.asarray(sub.apply(tr_i).to_array())
    np.testing.assert_array_equal(X_apply, cap["X"])


def test_imagenet_pca_gmm_checkpoint_load(tmp_path):
    """Both branches loadable from CSV checkpoints
    (parity: ImageNetSiftLcsFV.scala:40-66)."""
    rng = np.random.default_rng(0)
    dims, k = 8, 4
    num_classes = 8
    paths = {}
    # LCS feature rows with the default patch=6: 3 channels × 4×4
    # neighborhood offsets × (mean, std) = 96.
    for branch, d_in in (("sift", 128), ("lcs", 96)):
        pca = rng.standard_normal((dims, d_in)).astype(np.float32)
        means = rng.standard_normal((dims, k))
        variances = rng.uniform(0.5, 1.5, (dims, k))
        weights = np.full(k, 1.0 / k)
        for name, arr in (
            ("pca", pca), ("m", means), ("v", variances), ("w", weights)
        ):
            f = tmp_path / f"{branch}_{name}.csv"
            np.savetxt(f, arr, delimiter=",")
            paths[f"{branch}_{name}"] = str(f)

    tr_i, tr_l = synthetic_imagenet(24, num_classes, size=48, seed=3)
    te_i, te_l = synthetic_imagenet(12, num_classes, size=48, seed=4)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=dims,
        vocab_size=k,
        num_classes=num_classes,
        lam=1e-2,
        sift_pca_file=paths["sift_pca"],
        sift_gmm_mean_file=paths["sift_m"],
        sift_gmm_var_file=paths["sift_v"],
        sift_gmm_wts_file=paths["sift_w"],
        lcs_pca_file=paths["lcs_pca"],
        lcs_gmm_mean_file=paths["lcs_m"],
        lcs_gmm_var_file=paths["lcs_v"],
        lcs_gmm_wts_file=paths["lcs_w"],
    )
    _, err, _ = run(tr_i, tr_l, te_i, te_l, conf)
    assert np.isfinite(err)
