"""VOCSIFTFisher end-to-end on synthetic multi-label textured images
(SURVEY §7 step 5 parity slice) + MAP evaluator oracle."""

import numpy as np

from keystone_tpu.evaluation.mean_average_precision import (
    MeanAveragePrecisionEvaluator,
)
from keystone_tpu.pipelines.voc_sift_fisher import (
    SIFTFisherConfig,
    run,
    synthetic_voc,
)


def test_map_evaluator_oracle():
    # 2 classes, 4 items; class 0 perfectly ranked, class 1 inverted
    preds = np.array(
        [[0.9, 0.1], [0.8, 0.9], [0.2, 0.8], [0.1, 0.7]]
    )
    actuals = [[0], [0], [1], [0, 1]]
    aps = MeanAveragePrecisionEvaluator(2).evaluate(preds, actuals)
    assert aps.shape == (2,)
    # class 0: positives are items 0,1,3 with scores .9,.8,.1 → ranked
    # 1,2,4 of 4 → AP high
    assert aps[0] > 0.8
    assert 0 < aps[1] <= 1.0


def test_voc_sift_fisher_end_to_end():
    tr_i, tr_l = synthetic_voc(64, seed=1)
    te_i, te_l = synthetic_voc(32, seed=2)
    conf = SIFTFisherConfig(
        num_pca_samples=20_000,
        num_gmm_samples=20_000,
        vocab_size=4,
        desc_dim=16,
        lam=10.0,
    )
    aps, _ = run(tr_i, tr_l, te_i, te_l, conf)
    assert aps.shape == (20,)
    # random scoring gives MAP ≈ mean positive rate ≈ 0.1; textured classes
    # must do meaningfully better
    assert aps.mean() > 0.3, f"MAP {aps.mean()}"


def test_voc_pca_gmm_checkpoint_load(tmp_path):
    """PCA/GMM loadable from CSV (parity: VOCSIFTFisher.scala:49-66)."""
    rng = np.random.default_rng(0)
    d, dims, k = 128, 8, 4
    pca = rng.standard_normal((dims, d)).astype(np.float32)  # file: dims×d
    np.savetxt(tmp_path / "pca.csv", pca, delimiter=",")
    means = rng.standard_normal((dims, k))
    variances = rng.uniform(0.5, 1.5, (dims, k))
    weights = np.full(k, 1.0 / k)
    np.savetxt(tmp_path / "m.csv", means, delimiter=",")
    np.savetxt(tmp_path / "v.csv", variances, delimiter=",")
    np.savetxt(tmp_path / "w.csv", weights, delimiter=",")

    tr_i, tr_l = synthetic_voc(24, seed=3)
    te_i, te_l = synthetic_voc(12, seed=4)
    conf = SIFTFisherConfig(
        vocab_size=k,
        desc_dim=dims,
        lam=10.0,
        pca_file=str(tmp_path / "pca.csv"),
        gmm_mean_file=str(tmp_path / "m.csv"),
        gmm_var_file=str(tmp_path / "v.csv"),
        gmm_wts_file=str(tmp_path / "w.csv"),
    )
    aps, _ = run(tr_i, tr_l, te_i, te_l, conf)
    assert aps.shape == (20,)
    assert np.isfinite(aps).all()
