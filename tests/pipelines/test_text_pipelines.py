"""Text pipelines end-to-end (parity slices: NewsgroupsPipeline.scala,
AmazonReviewsPipeline.scala, StupidBackoffPipeline.scala) + the loaders
and evaluators they exercise."""

import json
import os

import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
)
from keystone_tpu.loaders.text import (
    load_amazon_reviews,
    load_newsgroups,
    load_timit_features,
)


def test_newsgroups_pipeline_synthetic():
    from keystone_tpu.pipelines.newsgroups import (
        NewsgroupsConfig,
        run,
        synthetic_newsgroups,
    )

    train = synthetic_newsgroups(256, num_classes=6, seed=1)
    test = synthetic_newsgroups(96, num_classes=6, seed=2)
    conf = NewsgroupsConfig(n_grams=2, common_features=2000, num_classes=6)
    _, evaluation, _ = run(train, test, conf)
    # keyword classes are separable; random would err ~83%
    assert evaluation.total_error < 0.15, evaluation.summary()


def test_newsgroups_loader_and_pipeline_from_dirs(tmp_path):
    from keystone_tpu.pipelines.newsgroups import (
        NewsgroupsConfig,
        run,
        synthetic_newsgroups,
    )

    # write a small 2-class on-disk corpus in the expected layout
    data = synthetic_newsgroups(60, num_classes=2, seed=3)
    classes = ["comp.graphics", "comp.os.ms-windows.misc"]
    for split in ("train", "test"):
        for c in classes:
            os.makedirs(tmp_path / split / c, exist_ok=True)
    docs = data.data.collect()
    labels = np.asarray(data.labels.to_array())
    for i, (doc, lab) in enumerate(zip(docs, labels)):
        split = "train" if i < 40 else "test"
        with open(tmp_path / split / classes[lab] / f"{i}.txt", "w") as f:
            f.write(doc)
    train = load_newsgroups(str(tmp_path / "train"))
    test = load_newsgroups(str(tmp_path / "test"))
    assert len(train.data) == 40 and len(test.data) == 20
    conf = NewsgroupsConfig(n_grams=1, common_features=500, num_classes=2)
    _, evaluation, _ = run(train, test, conf)
    assert evaluation.total_error < 0.25


def test_amazon_reviews_pipeline_synthetic():
    from keystone_tpu.pipelines.amazon_reviews import (
        AmazonReviewsConfig,
        run,
        synthetic_reviews,
    )

    train = synthetic_reviews(256, seed=1)
    test = synthetic_reviews(96, seed=2)
    conf = AmazonReviewsConfig(n_grams=2, common_features=2000, num_iters=30)
    _, evaluation, _ = run(train, test, conf)
    assert evaluation.accuracy > 0.9, evaluation.summary()


def test_amazon_loader(tmp_path):
    recs = [
        {"overall": 5.0, "reviewText": "great product love it"},
        {"overall": 1.0, "reviewText": "terrible broken refund"},
        {"overall": 4.0, "reviewText": "pretty good"},
        {"overall": 2.0, "reviewText": "not great"},
    ]
    path = tmp_path / "reviews.json"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    ld = load_amazon_reviews(str(path), threshold=3.5)
    assert np.asarray(ld.labels.to_array()).tolist() == [1, 0, 1, 0]
    assert ld.data.collect()[0] == "great product love it"


def test_timit_loader(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((6, 8)).astype(np.float32)
    np.savetxt(tmp_path / "train.csv", X, delimiter=",")
    with open(tmp_path / "train.labels", "w") as f:
        for i in range(6):
            f.write(f"{i + 1} {(i % 3) + 1}\n")  # 1-indexed rows and labels
    data = load_timit_features(
        str(tmp_path / "train.csv"), str(tmp_path / "train.labels"),
        str(tmp_path / "train.csv"), str(tmp_path / "train.labels"),
    )
    assert np.asarray(data.train.labels.to_array()).tolist() == \
        [0, 1, 2, 0, 1, 2]
    np.testing.assert_allclose(
        np.asarray(data.train.data.to_array()), X, rtol=1e-5
    )


def test_stupid_backoff_pipeline():
    from keystone_tpu.pipelines.stupid_backoff_pipeline import (
        synthetic_corpus,
        train_language_model,
    )

    lm = train_language_model(synthetic_corpus(100, seed=4), n=3)
    assert lm.num_tokens > 0
    assert len(lm.scores) > 0
    assert all(0.0 <= s <= 1.0 for s in lm.scores.values())
    # scoring an in-corpus bigram of encoded ids works
    some_bigram = next(g for g in lm.scores if len(g) == 2)
    assert lm.score(some_bigram) > 0


def test_binary_evaluator_oracle():
    preds = np.array([True, True, False, False, True])
    acts = np.array([True, False, False, True, True])
    m = BinaryClassifierEvaluator().evaluate(preds, acts)
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert m.accuracy == 0.6
    assert abs(m.f_score() - 2 * 2 / (2 * 2 + 1 + 1)) < 1e-12


def test_augmented_evaluator_average_and_borda():
    # two sources, two augmented copies each, 3 classes
    names = ["a", "a", "b", "b"]
    preds = np.array([
        [0.9, 0.1, 0.0],
        [0.5, 0.3, 0.2],   # "a" → class 0 under both policies
        [0.0, 0.4, 0.6],
        [0.1, 0.2, 0.7],   # "b" → class 2 under both policies
    ])
    actuals = np.array([0, 0, 2, 2])
    m = AugmentedExamplesEvaluator(names, 3, "average").evaluate(preds, actuals)
    assert m.total_error == 0.0
    m2 = AugmentedExamplesEvaluator(names, 3, "borda").evaluate(preds, actuals)
    assert m2.total_error == 0.0


def test_newsgroups_per_datum_apply():
    """The fitted sparse chain must work per-item too (the reference's
    SparseVector single-apply path)."""
    from keystone_tpu.pipelines.newsgroups import (
        NewsgroupsConfig,
        build_predictor,
        synthetic_newsgroups,
    )

    train = synthetic_newsgroups(128, num_classes=4, seed=9)
    conf = NewsgroupsConfig(n_grams=2, common_features=800, num_classes=4)
    predictor = build_predictor(train.data, train.labels, conf)
    batch_preds = np.asarray(predictor(train.data).get().to_array())
    doc = train.data.collect()[0]
    datum_pred = int(np.asarray(predictor.apply_datum(doc).get()))
    assert datum_pred == batch_preds[0]
