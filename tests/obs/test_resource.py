"""Resource accounting (keystone_tpu/obs/resource.py): the equal-split
attribution arithmetic, the KEYSTONE_ACCOUNTING gate, and the memory
watermark's throttle/merge-mode contract."""

from types import SimpleNamespace

import pytest

from keystone_tpu.obs import resource
from keystone_tpu.serving.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh_gate():
    resource.reset()
    yield
    resource.reset()


def _req(tenant=None, priority=None, enqueued=None, nbytes=0):
    datum = SimpleNamespace(nbytes=nbytes) if nbytes else None
    return SimpleNamespace(
        tenant=tenant, priority=priority, enqueued=enqueued, datum=datum
    )


# -- attribution -------------------------------------------------------------


def test_split_is_equal_and_sums_reconstruct_device_seconds():
    reqs = [
        _req("gold", "high", enqueued=9.0),
        _req("gold", "high", enqueued=9.5),
        _req("bronze", enqueued=8.0),
    ]
    table = resource.split_batch_cost(reqs, device_seconds=0.3, now=10.0)
    gold = table[("gold", "high")]
    bronze = table[("bronze", "normal")]
    assert gold["device_s"] == pytest.approx(0.2)
    assert bronze["device_s"] == pytest.approx(0.1)
    assert gold["items"] == 2 and bronze["items"] == 1
    total = sum(row["device_s"] for row in table.values())
    assert total == pytest.approx(0.3)
    # queue seconds are per-member waits, summed per identity
    assert gold["queue_s"] == pytest.approx(1.0 + 0.5)
    assert bronze["queue_s"] == pytest.approx(2.0)


def test_missing_identity_defaults_and_clamped_queue_wait():
    table = resource.split_batch_cost(
        [_req(enqueued=99.0)], device_seconds=0.05, now=10.0
    )
    ((key, row),) = table.items()
    assert key == ("default", "normal")
    assert row["queue_s"] == 0.0  # clock skew never charges negative wait


def test_payload_bytes_prefer_validated_rows():
    reqs = [_req("t", nbytes=100), _req("t", nbytes=100)]
    payloads = [SimpleNamespace(nbytes=64), SimpleNamespace(nbytes=32)]
    table = resource.split_batch_cost(
        reqs, device_seconds=0.0, now=0.0, payloads=payloads
    )
    assert table[("t", "normal")]["payload_bytes"] == 96


def test_payload_bytes_fall_back_to_the_datum():
    table = resource.split_batch_cost(
        [_req("t", nbytes=128)], device_seconds=0.0, now=0.0
    )
    assert table[("t", "normal")]["payload_bytes"] == 128
    assert resource.payload_nbytes(b"abcd") == 4
    assert resource.payload_nbytes(None) == 0


def test_empty_batch_charges_nothing():
    assert resource.split_batch_cost([], 1.0, 0.0) == {}


# -- the gate ----------------------------------------------------------------


def test_accounting_gate_defaults_on(monkeypatch):
    monkeypatch.delenv("KEYSTONE_ACCOUNTING", raising=False)
    resource.reset()
    assert resource.accounting_enabled() is True


def test_accounting_off_disables_sampling_and_gauges(monkeypatch):
    monkeypatch.setenv("KEYSTONE_ACCOUNTING", "0")
    resource.reset()
    assert resource.accounting_enabled() is False
    assert resource.sample_memory() == 0
    m = MetricsRegistry("w0")
    resource.install_memory_gauges(m)
    assert "device_mem_bytes" not in m.snapshot()["gauges"]


# -- the watermark -----------------------------------------------------------


def test_watermark_tracks_peak_and_throttles(monkeypatch):
    wm = resource.MemoryWatermark()
    monkeypatch.setattr(resource, "device_memory_bytes", lambda: (100, 1000))
    assert wm.sample() == 100
    assert wm.peak == 100 and wm.fraction() == pytest.approx(0.1)
    monkeypatch.setattr(resource, "device_memory_bytes", lambda: (40, 1000))
    # inside the throttle window the stale reading is returned
    assert wm.sample(min_interval_s=3600.0) == 100
    assert wm.sample() == 40
    assert wm.peak == 100  # the high-water mark survives the drop


def test_fraction_unknown_without_a_limit(monkeypatch):
    wm = resource.MemoryWatermark()
    monkeypatch.setattr(resource, "device_memory_bytes", lambda: (100, 0))
    wm.sample()
    assert wm.fraction() is None


def test_device_memory_bytes_never_raises():
    live, limit = resource.device_memory_bytes()
    assert live >= 0 and limit >= 0


def test_install_memory_gauges_declares_honest_merge_modes():
    m = MetricsRegistry("w0")
    resource.install_memory_gauges(m)
    modes = m.snapshot()["gauge_modes"]
    assert modes["device_mem_bytes"] == "sum"
    assert modes["device_mem_peak_bytes"] == "max"
    assert modes["device_mem_fraction"] == "mean"
