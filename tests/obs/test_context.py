"""Trace context propagation + sampling (keystone_tpu/obs/context.py)
and the cross-process stitcher (obs/export.py) — pure in-process tests;
the real two-process path is tests/cluster/test_trace_propagation.py."""

import os
import time

from keystone_tpu.obs.context import (
    Sampler,
    TraceContext,
    new_trace_id,
    sample_rate,
)


def test_trace_id_is_process_namespaced():
    a, b = new_trace_id(0), new_trace_id(1)
    assert a != b
    assert a.startswith(f"{os.getpid():x}-")


def test_wire_round_trip_stamps_send_time():
    ctx = TraceContext("abc-1", hop="rpc.request")
    before = time.time()
    enc = ctx.to_wire()
    back = TraceContext.from_wire(enc)
    assert back.trace_id == "abc-1" and back.hop == "rpc.request"
    assert before <= back.sent_unix <= time.time()
    # transport is measured against the shared unix clock, clamped >= 0
    assert 0.0 <= back.transport_seconds() < 1.0


def test_from_wire_tolerates_absence():
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire({}) is None
    assert TraceContext.from_wire({"hop": "x"}) is None


def test_sampler_is_deterministic_every_nth():
    s = Sampler(0.25)
    draws = [s.admit() for _ in range(12)]
    assert draws == [True, False, False, False] * 3
    # a fresh sampler at the same rate draws the SAME positions —
    # traced/untraced comparison runs sample identical request indices
    fresh = Sampler(0.25)
    assert [fresh.admit() for _ in range(12)] == draws


def test_sampler_extremes():
    assert all(Sampler(1.0).admit() for _ in range(8))
    assert not any(Sampler(0.0).admit() for _ in range(8))


def test_sample_rate_env_knob(monkeypatch):
    monkeypatch.delenv("KEYSTONE_TRACE_SAMPLE", raising=False)
    assert sample_rate() == 1.0
    monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "0.1")
    assert sample_rate() == 0.1
    monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "7")
    assert sample_rate() == 1.0  # clamped


def test_stitch_builds_per_process_tracks():
    from keystone_tpu.obs.export import stitch_chrome_trace

    base = 1000.0
    router = [{
        "name": "rpc.request", "start_unix": base, "dur_s": 0.010,
        "instant": False, "pid": 100, "tid": 1,
        "thread_name": "main", "process_name": "keystone:router/100",
        "args": {"trace_id": "64-0"},
    }]
    worker = [
        {
            "name": "serve.replica", "start_unix": base + 0.004,
            "dur_s": 0.005, "instant": False, "pid": 200, "tid": 9,
            "thread_name": "replica-0",
            "process_name": "keystone:worker-0/200",
            "args": {"trace_id": "64-0"},
        },
        {
            "name": "fault.replica_down", "start_unix": base + 0.009,
            "dur_s": 0.0, "instant": True, "pid": 200, "tid": 9,
            "thread_name": "replica-0",
            "process_name": "keystone:worker-0/200",
            "args": {},
        },
    ]
    doc = stitch_chrome_trace([router, worker])
    ev = doc["traceEvents"]
    meta = [e for e in ev if e["ph"] == "M"]
    assert {
        (e["name"], e["pid"]) for e in meta
    } >= {("process_name", 100), ("process_name", 200),
          ("thread_name", 100), ("thread_name", 200)}
    xs = [e for e in ev if e["ph"] == "X"]
    # distinct pids per process track, one shared trace id across them
    assert {e["pid"] for e in xs} == {100, 200}
    assert {e["args"]["trace_id"] for e in xs} == {"64-0"}
    # rebased to the earliest span; monotonic ts
    assert min(e["ts"] for e in xs) == 0.0
    ts = [e["ts"] for e in ev]
    assert all(b >= a for a, b in zip(ts, ts[1:]))
    inst = [e for e in ev if e["ph"] == "i"]
    assert inst and inst[0]["name"] == "fault.replica_down"


def test_wire_spans_rebase_onto_unix_clock():
    from keystone_tpu.obs.export import wire_spans
    from keystone_tpu.obs.span import Span

    epoch, epoch_unix = 500.0, 2000.0
    sp = Span(
        name="serve.queue", start=501.5, end=501.75,
        tid=7, thread_name="w", attrs={"trace_id": "a-1"},
    )
    (w,) = wire_spans([sp], epoch, epoch_unix, process_name="p")
    assert w["start_unix"] == 2001.5
    assert abs(w["dur_s"] - 0.25) < 1e-9
    assert w["args"]["trace_id"] == "a-1"
    assert w["pid"] == os.getpid() and w["process_name"] == "p"
