"""NDJSON ledgers (keystone_tpu/obs/ledger.py): the never-raising sink,
the compile ledger schema, and the KEYSTONE_EVENTS structured-event
stream the flight recorder feeds."""

import json
import os

import pytest

from keystone_tpu.obs import ledger
from keystone_tpu.obs.ledger import (
    COMPILE_LEDGER_NAME,
    CompileLedger,
    NdjsonSink,
    emit_event,
    read_ndjson,
    sink_for,
)


@pytest.fixture
def events_env(tmp_path, monkeypatch):
    """Point KEYSTONE_EVENTS at a tmp file for the test, restoring the
    unresolved state afterwards so other tests see no sink."""
    path = tmp_path / "events.ndjson"
    monkeypatch.setenv("KEYSTONE_EVENTS", str(path))
    ledger.reset_events()
    yield path
    ledger.reset_events()


# -- the sink primitive ------------------------------------------------------


def test_sink_round_trip_one_line_per_record(tmp_path):
    sink = NdjsonSink(str(tmp_path / "a.ndjson"))
    assert sink.append({"event": "x", "n": 1})
    assert sink.append({"event": "y", "n": 2})
    rows = read_ndjson(sink.path)
    assert [r["event"] for r in rows] == ["x", "y"]
    assert open(sink.path).read().count("\n") == 2


def test_reader_skips_torn_lines(tmp_path):
    path = tmp_path / "a.ndjson"
    path.write_text('{"event":"ok"}\n{"event":"torn', encoding="utf-8")
    rows = read_ndjson(str(path))
    assert [r["event"] for r in rows] == ["ok"]


def test_missing_file_reads_empty(tmp_path):
    assert read_ndjson(str(tmp_path / "nope.ndjson")) == []


def test_sink_disables_itself_on_write_failure(tmp_path):
    # a directory path cannot be opened for append: the first failure
    # disables the sink instead of raising (or re-warning per append)
    sink = NdjsonSink(str(tmp_path))
    assert sink.append({"event": "x"}) is False
    assert sink._dead
    assert sink.append({"event": "y"}) is False


def test_unserializable_record_dropped_without_killing_sink(tmp_path):
    sink = NdjsonSink(str(tmp_path / "a.ndjson"))
    assert sink.append({"bad": object()}) is True  # default=str coerces
    assert sink.append({"worse": {1j: "x"}}) is False  # unkeyable
    assert sink.append({"event": "still-alive"}) is True


def test_sink_for_shares_one_instance_per_path(tmp_path):
    p = str(tmp_path / "shared.ndjson")
    assert sink_for(p) is sink_for(p)


# -- the compile ledger ------------------------------------------------------


def test_compile_ledger_lives_in_the_cache_root(tmp_path):
    led = CompileLedger.for_cache_root(str(tmp_path))
    assert led.path == str(tmp_path / COMPILE_LEDGER_NAME)


def test_record_stamps_envelope_rounds_floats_skips_none(tmp_path):
    led = CompileLedger(str(tmp_path / "l.ndjson"))
    assert led.record(
        "trace", key="k1", seconds=0.123456789, label=None, nbytes=42
    )
    (row,) = led.entries()
    assert row["event"] == "trace" and row["pid"] == os.getpid()
    assert row["ts"] > 0
    assert row["seconds"] == 0.123457
    assert row["nbytes"] == 42
    assert "label" not in row


def test_entries_filter_by_event(tmp_path):
    led = CompileLedger(str(tmp_path / "l.ndjson"))
    led.record("trace", key="a")
    led.record("load", key="a")
    led.record("load", key="b")
    assert [r["key"] for r in led.entries("load")] == ["a", "b"]
    assert len(led.entries()) == 3


def test_cache_store_hit_evict_land_in_the_ledger(tmp_path):
    from keystone_tpu.compile.cache import ExecutableCache

    cache = ExecutableCache(str(tmp_path), max_bytes=1 << 20)
    cache.store("k1", b"x" * 64, {"env": {}})
    assert cache.load("k1") is not None
    events = [r["event"] for r in cache.ledger.entries()]
    assert events == ["store", "hit"]
    assert cache.ledger.entries("store")[0]["nbytes"] == 64


# -- the events sink ---------------------------------------------------------


def test_emit_event_without_env_is_a_noop(monkeypatch):
    monkeypatch.delenv("KEYSTONE_EVENTS", raising=False)
    ledger.reset_events()
    try:
        assert emit_event("instant", "x.y", worker=1) is False
    finally:
        ledger.reset_events()


def test_emit_event_writes_envelope_with_nested_attrs(events_env):
    assert emit_event("instant", "scale.up", worker=3, skipped=None)
    (row,) = read_ndjson(str(events_env))
    assert row["event"] == "instant" and row["name"] == "scale.up"
    assert row["attrs"] == {"worker": 3}
    assert row["pid"] == os.getpid() and row["ts"] > 0


def test_attr_names_cannot_shadow_the_envelope(events_env):
    # regression: fleet restart instants carry kind=/name=-style attrs;
    # they must nest rather than collide with emit_event's own params
    assert emit_event("instant", "fault.replica_down", kind="transient",
                      name="replica-0")
    (row,) = read_ndjson(str(events_env))
    assert row["event"] == "instant"
    assert row["name"] == "fault.replica_down"
    assert row["attrs"] == {"kind": "transient", "name": "replica-0"}


def test_flight_instants_stream_into_the_events_sink(events_env):
    from keystone_tpu.obs import flight

    flight.record_instant("slo.breach", objective="p99_budget_s",
                          kind="breach")
    rows = [
        r for r in read_ndjson(str(events_env))
        if r.get("name") == "slo.breach"
    ]
    assert rows and rows[-1]["attrs"]["objective"] == "p99_budget_s"


def test_events_sink_is_resolved_once(events_env, monkeypatch):
    emit_event("instant", "first")
    # changing the env mid-process does not silently retarget the stream
    monkeypatch.setenv("KEYSTONE_EVENTS", "/nonexistent/other.ndjson")
    emit_event("instant", "second")
    names = [r["name"] for r in read_ndjson(str(events_env))]
    assert names == ["first", "second"]
