"""The always-on flight recorder (keystone_tpu/obs/flight.py): bounded
ring semantics, atomic JSON dumps, and the fault-site → recovery-instant
contract the invariant lint enforces."""

import json
import os
import threading

from keystone_tpu.obs.flight import (
    SITE_INSTANTS,
    FlightRecorder,
    dump,
    record_instant,
    record_span,
    recorder,
)


def test_ring_is_bounded_and_ordered():
    rec = FlightRecorder(ring=8)
    for i in range(20):
        rec.record_span("serve.replica", 0.001 * i, seq=i)
    entries = rec.entries()
    assert len(entries) == 8
    # the ring keeps the NEWEST window, oldest first
    assert [e["attrs"]["seq"] for e in entries] == list(range(12, 20))
    assert all(e["kind"] == "span" for e in entries)


def test_instants_and_spans_interleave_with_timestamps():
    rec = FlightRecorder(ring=16)
    rec.record_span("rpc.request", 0.5, worker=1)
    rec.record_instant("fault.worker_down", worker=1)
    a, b = rec.entries()
    assert a["kind"] == "span" and a["seconds"] == 0.5
    assert b["kind"] == "instant" and b["name"] == "fault.worker_down"
    assert b["t"] >= a["t"] > 0


def test_dump_is_valid_json_and_atomic(tmp_path):
    rec = FlightRecorder(ring=64)
    for i in range(70):
        rec.record_span("serve.replica", 0.002, replica=i % 2)
    rec.record_instant("fault.replica_down", replica=0)
    path = rec.dump("replica_quarantine", path=str(tmp_path / "f.json"))
    assert path is not None and os.path.exists(path)
    # no torn tmp file left behind
    assert [p for p in os.listdir(tmp_path)] == ["f.json"]
    doc = json.loads(open(path).read())
    assert doc["trigger"] == "replica_quarantine"
    assert doc["pid"] == os.getpid()
    assert doc["ring_capacity"] == 64
    assert doc["dropped_before_window"] == 7  # 71 records into 64 slots
    assert len(doc["entries"]) == 64
    assert doc["entries"][-1]["name"] == "fault.replica_down"


def test_dump_default_dir_honors_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    rec = FlightRecorder(ring=4)
    rec.record_instant("slo.breach", objective="p99_budget_s")
    path = rec.dump("test_trigger")
    assert path is not None
    assert os.path.dirname(path) == str(tmp_path)
    assert "test_trigger" in os.path.basename(path)


def test_dump_failure_never_raises(tmp_path):
    rec = FlightRecorder(ring=4)
    rec.record_instant("x")
    missing = tmp_path / "no" / "such" / "dir" / "f.json"
    assert rec.dump("t", path=str(missing)) is None


def test_module_recorder_is_process_global_and_always_on():
    # no install step: recording works immediately (the always-on
    # contract), and the module helpers hit one shared ring
    record_span("serve.replica", 0.001, replica=0)
    record_instant("fault.inject", site="scan.chunk")
    names = [e["name"] for e in recorder().entries()]
    assert "serve.replica" in names and "fault.inject" in names


def test_concurrent_writers_never_lose_the_bound():
    rec = FlightRecorder(ring=32)

    def hammer(k):
        for i in range(200):
            rec.record_span("s", 0.0, k=k, i=i)

    threads = [threading.Thread(target=hammer, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec.entries()) == 32


def test_site_instants_covers_every_registered_fault_site():
    # the python-side mirror of lint rule 4: every fault site constant
    # in faults/plan.py must map to a recovery instant
    import keystone_tpu.faults.plan as plan

    sites = {
        v for k, v in vars(plan).items()
        if k.isupper() and isinstance(v, str) and "." in v
        and k not in ("MAX_BACKOFF_S",)
    }
    assert sites, "no fault sites found — the reflection broke"
    assert sites <= set(SITE_INSTANTS), (
        sites - set(SITE_INSTANTS)
    )


def test_fault_point_records_into_flight_ring():
    from keystone_tpu import faults

    faults.install(faults.parse_plan("scan.chunk=transient@0"))
    try:
        try:
            faults.fault_point("scan.chunk")
        except faults.FaultInjected:
            pass
        entries = recorder().entries()
        hits = [
            e for e in entries
            if e["name"] == "fault.inject"
            and e.get("attrs", {}).get("site") == "scan.chunk"
        ]
        assert hits, entries
    finally:
        faults.clear()


def test_global_dump_writes_through_module_helper(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    record_instant("trainer.park", batch_start=0, batch_stop=2)
    path = dump("trainer_park")
    doc = json.loads(open(path).read())
    assert any(e["name"] == "trainer.park" for e in doc["entries"])


def test_auto_named_dumps_are_retention_bounded(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("KEYSTONE_FLIGHT_KEEP", "3")
    rec = FlightRecorder(ring=4)
    rec.record_instant("x")
    paths = [rec.dump(f"trigger{i}") for i in range(6)]
    assert all(p is not None for p in paths)
    kept = sorted(os.listdir(tmp_path))
    assert len(kept) == 3
    # the newest dumps survive; the oldest were pruned oldest-first
    for p in paths[-3:]:
        assert os.path.basename(p) in kept
    for p in paths[:3]:
        assert os.path.basename(p) not in kept


def test_explicit_path_dumps_are_never_pruned(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("KEYSTONE_FLIGHT_KEEP", "2")
    rec = FlightRecorder(ring=4)
    rec.record_instant("x")
    # a caller-chosen destination is an operator's deliberate artifact:
    # retention only manages the auto-named files in the managed dir
    for i in range(4):
        rec.dump("kept", path=str(tmp_path / f"keystone-flight-op{i}.json"))
    assert len(os.listdir(tmp_path)) == 4
