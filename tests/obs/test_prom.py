"""Prometheus exposition (keystone_tpu/obs/prom.py): text-format
validity, name/label escaping, counter monotonicity across scrapes, and
the HTTP scrape server round-trip."""

import re
import threading
import urllib.request

from keystone_tpu.obs.prom import (
    CONTENT_TYPE,
    PrometheusExporter,
    escape_label_value,
    render_prometheus,
    sanitize_metric_name,
)
from keystone_tpu.serving.metrics import MetricsRegistry

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\")*\})?"
    r" (-?[0-9.e+-]+|nan|inf)$"
)


def parse_exposition(text: str) -> dict:
    """Validate text-format 0.0.4 structurally and return
    ``{sample_line_name{labels}: float}``. Every sample's family must
    carry a preceding ``# TYPE`` line; any malformed line asserts."""
    samples = {}
    typed = set()
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4 and parts[3] in (
                "counter", "gauge", "summary", "histogram", "untyped"
            ), line
            typed.add(parts[2])
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP "), line
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group(1)
        base = name
        for suffix in ("_count", "_sum", "_bucket"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed:
                base = name[: -len(suffix)]
        assert base in typed, f"sample {name} has no # TYPE family"
        key, value = line.rsplit(" ", 1)
        samples[key] = float(value)
    return samples


def _loaded_registry() -> MetricsRegistry:
    m = MetricsRegistry("w0")
    m.inc("submitted", 10)
    m.inc("completed", 9)
    m.inc("shed.low", 2)
    m.inc("tenant.served.acme", 4)
    m.inc("slo_breach.p99_budget_s", 1)
    m.set_gauge("queue_depth", lambda: 3.0)
    m.observe_cost("acme", "high", device_s=0.5, queue_s=0.1,
                   payload_bytes=2048, items=4)
    m.observe_latency(0.01, priority="high")
    m.observe_queue_age(0.002)
    m.observe_batch(6, 8, replica=0)
    return m


# -- name / label hygiene ----------------------------------------------------


def test_sanitize_metric_name():
    assert sanitize_metric_name("tenant.served.acme") == "tenant_served_acme"
    assert sanitize_metric_name("a-b c") == "a_b_c"
    assert sanitize_metric_name("9lives") == "_9lives"
    assert sanitize_metric_name("") == "_"
    assert sanitize_metric_name("ok_name:x") == "ok_name:x"


def test_escape_label_value():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    assert escape_label_value(7) == "7"


def test_hostile_identity_values_render_validly():
    m = MetricsRegistry("w0")
    m.observe_cost('ten"ant\n\\evil', "nor mal", device_s=0.1, items=1)
    text = render_prometheus(m.snapshot())
    parse_exposition(text)  # asserts structural validity
    assert '\\"' in text and "\\n" in text


# -- rendering ---------------------------------------------------------------


def test_counters_render_as_total_families_with_type_lines():
    text = render_prometheus(_loaded_registry().snapshot())
    samples = parse_exposition(text)
    assert samples["keystone_submitted_total"] == 10
    assert samples["keystone_completed_total"] == 9
    assert "# TYPE keystone_submitted_total counter" in text
    assert "# TYPE keystone_queue_depth gauge" in text


def test_dotted_counters_become_labeled_families():
    samples = parse_exposition(
        render_prometheus(_loaded_registry().snapshot())
    )
    assert samples['keystone_shed_by_priority_total{priority="low"}'] == 2
    assert samples['keystone_tenant_served_total{tenant="acme"}'] == 4
    assert samples[
        'keystone_slo_breach_total{objective="p99_budget_s"}'
    ] == 1


def test_cost_table_renders_four_labeled_families():
    samples = parse_exposition(
        render_prometheus(_loaded_registry().snapshot())
    )
    labels = '{tenant="acme",priority="high"}'
    assert samples[f"keystone_tenant_device_seconds_total{labels}"] == 0.5
    assert samples[f"keystone_tenant_queue_seconds_total{labels}"] == 0.1
    assert samples[f"keystone_tenant_payload_bytes_total{labels}"] == 2048
    assert samples[f"keystone_tenant_items_total{labels}"] == 4


def test_summaries_carry_quantiles_count_and_sum():
    text = render_prometheus(_loaded_registry().snapshot())
    samples = parse_exposition(text)
    assert "# TYPE keystone_latency_seconds summary" in text
    assert samples['keystone_latency_seconds{quantile="0.99"}'] == 0.01
    assert samples["keystone_latency_seconds_count"] == 1
    assert samples[
        'keystone_priority_latency_seconds{priority="high",quantile="0.5"}'
    ] == 0.01


def test_merged_snapshot_renders_with_merge_width():
    m = _loaded_registry()
    merged = MetricsRegistry.merge(
        [m.snapshot(sketches=True), m.snapshot(sketches=True)]
    )
    samples = parse_exposition(render_prometheus(merged))
    assert samples["keystone_merged_processes"] == 2
    assert samples["keystone_submitted_total"] == 20


def test_counters_are_monotone_across_scrapes():
    m = _loaded_registry()
    seen = []
    for _ in range(5):
        samples = parse_exposition(render_prometheus(m.snapshot()))
        seen.append({
            k: v for k, v in samples.items() if k.endswith("_total")
        })
        m.inc("submitted")
        m.observe_cost("acme", "high", device_s=0.25, items=1)
    for before, after in zip(seen, seen[1:]):
        for key, value in before.items():
            assert after.get(key, 0.0) >= value, key


def test_scrape_under_concurrent_mutation_stays_valid():
    m = _loaded_registry()
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            m.inc("submitted")
            m.observe_cost(f"t{i % 3}", device_s=0.001, items=1)
            m.observe_latency(0.001)
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(20):
            parse_exposition(render_prometheus(m.snapshot()))
    finally:
        stop.set()
        for t in threads:
            t.join()


# -- the scrape server -------------------------------------------------------


def test_http_exporter_round_trip():
    m = _loaded_registry()
    exporter = PrometheusExporter(lambda: m.snapshot(), port=0)
    host, port = exporter.start()
    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            body = resp.read().decode("utf-8")
        samples = parse_exposition(body)
        assert samples["keystone_submitted_total"] == 10
        # starting twice is idempotent (same address back)
        assert exporter.start() == (host, port)
    finally:
        exporter.stop()
    assert exporter.address is None


def test_http_exporter_404_off_path_and_500_on_snapshot_failure():
    import urllib.error

    good = MetricsRegistry("w0")
    exporter = PrometheusExporter(good.snapshot, port=0)
    host, port = exporter.start()
    try:
        try:
            urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        exporter.stop()

    def broken():
        raise RuntimeError("stats hub down")

    exporter = PrometheusExporter(broken, port=0)
    host, port = exporter.start()
    try:
        try:
            urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5
            )
            assert False, "expected 500"
        except urllib.error.HTTPError as e:
            assert e.code == 500
    finally:
        exporter.stop()
