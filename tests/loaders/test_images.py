"""Image ingestion tests.

Two layers, matching the reference's two fixture sources:
 * self-generated tar-of-JPEGs fixtures (PIL-encoded in-test), covering the
   decode rules, size policies, and label mapping;
 * the reference checkout's real fixture tars when mounted — the same
   oracle assertions as VOCLoaderSuite.scala / ImageNetLoaderSuite.
"""

import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders.images import (
    MIN_DIM,
    decode_image_bytes,
    iter_tar_images,
    load_imagenet,
    load_voc,
)

REF = "/root/reference/src/test/resources/images"


def _jpeg_bytes(arr: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr.astype(np.uint8)).save(buf, format="JPEG")
    return buf.getvalue()


def _make_tar(path, entries):
    """entries: {name: bytes}"""
    with tarfile.open(path, "w") as tf:
        for name, data in entries.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


@pytest.fixture
def image_tar(tmp_path):
    rng = np.random.default_rng(0)
    entries = {
        "classA/img0.jpg": _jpeg_bytes(rng.integers(0, 255, (48, 40, 3))),
        "classA/img1.jpg": _jpeg_bytes(rng.integers(0, 255, (64, 48, 3))),
        "classB/img2.jpg": _jpeg_bytes(rng.integers(0, 255, (40, 56, 3))),
        # too small on one side: must be skipped (ImageUtils.scala:20-23)
        "classB/small.jpg": _jpeg_bytes(rng.integers(0, 255, (20, 80, 3))),
        # not an image at all: must be skipped, not crash
        "classB/junk.txt": b"not an image",
    }
    p = tmp_path / "imgs.tar"
    _make_tar(p, entries)
    return str(p)


def test_decode_rules():
    rng = np.random.default_rng(1)
    ok = decode_image_bytes(_jpeg_bytes(rng.integers(0, 255, (50, 40, 3))))
    # uint8 ingestion: pixels stay bytes until the device casts
    assert ok.shape == (50, 40, 3) and ok.dtype == np.uint8
    assert decode_image_bytes(b"garbage") is None
    small = _jpeg_bytes(rng.integers(0, 255, (MIN_DIM - 1, 100, 3)))
    assert decode_image_bytes(small) is None
    gray = decode_image_bytes(
        _jpeg_bytes(rng.integers(0, 255, (40, 40)))
    )
    assert gray.shape == (40, 40, 1)
    resized = decode_image_bytes(
        _jpeg_bytes(rng.integers(0, 255, (50, 40, 3))), size=(44, 36)
    )
    assert resized.shape == (44, 36, 3)


def test_tar_stream_skips_bad_entries(image_tar):
    items = list(iter_tar_images(image_tar))
    names = [n for n, _ in items]
    assert names == ["classA/img0.jpg", "classA/img1.jpg", "classB/img2.jpg"]
    assert items[0][1].shape == (48, 40, 3)


def test_imagenet_loader_ragged_and_canonical(image_tar, tmp_path):
    labels_file = tmp_path / "labels"
    labels_file.write_text("classA 3\nclassB 7\n")

    ragged = load_imagenet(image_tar, str(labels_file))
    assert len(ragged) == 3
    assert list(ragged.labels) == [3, 3, 7]
    assert not ragged.data.is_batched  # native sizes stay per-item

    canon = load_imagenet(image_tar, str(labels_file), size=(32, 32))
    assert canon.data.is_batched
    assert canon.data.to_array().shape == (3, 32, 32, 3)


def test_voc_loader_multilabel(image_tar, tmp_path):
    csv = tmp_path / "voclabels.csv"
    csv.write_text(
        '"id","class","classname","traintesteval","filename"\n'
        '1,7,"car",1,"classA/img0.jpg"\n'
        '2,13,"horse",1,"classA/img1.jpg"\n'
        '2,15,"person",1,"classA/img1.jpg"\n'
    )
    voc = load_voc(image_tar, str(csv), name_prefix="classA/")
    assert len(voc) == 2
    assert voc.labels == [[6], [12, 14]]  # 1-indexed CSV → 0-indexed
    Y = voc.label_matrix(20)
    assert Y.shape == (2, 20)
    assert Y[1, 12] == 1.0 and Y[1, 14] == 1.0 and Y[1, 0] == -1.0


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_voc_reference_fixture_oracle():
    """Same assertions as the reference's VOCLoaderSuite.scala:9-31."""
    voc = load_voc(
        os.path.join(REF, "voc"),
        os.path.join(REF, "voclabels.csv"),
        name_prefix="VOCdevkit/VOC2007/JPEGImages/",
    )
    assert len(voc) == 10
    (idx,) = [i for i, n in enumerate(voc.names) if n.endswith("000104.jpg")]
    assert 14 in voc.labels[idx] and 19 in voc.labels[idx]
    flat = [l for ls in voc.labels for l in ls]
    assert len(flat) == 13
    assert len(set(flat)) == 9


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_imagenet_reference_fixture_oracle():
    imgs = load_imagenet(
        os.path.join(REF, "imagenet"),
        os.path.join(REF, "imagenet-test-labels"),
        size=(64, 64),
    )
    assert len(imgs) > 0
    assert set(imgs.labels.tolist()) == {12}
    assert imgs.data.to_array().shape[1:] == (64, 64, 3)
