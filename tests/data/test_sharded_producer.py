"""Sharded chunk production (ISSUE 12): N producer shards partition the
chunk index space, and the merged stream is bit-identical to the single
producer's — same chunks, same order, same values."""

import hashlib
import threading
import time

import numpy as np
import pytest

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.shards import ShardedChunkProducer, maybe_shard


def _chunk_fn(i, rows=8, d=4):
    rng = np.random.RandomState(1000 + i)
    return rng.randn(rows, d).astype(np.float32)


def _dataset(n_chunks=12, rows=8, d=4):
    return ChunkedDataset.from_chunk_fn(
        lambda i: _chunk_fn(i, rows, d), n_chunks, n_chunks * rows,
        label="shardtest",
    )


def _digest(chunks):
    h = hashlib.sha256()
    for c in chunks:
        h.update(np.ascontiguousarray(np.asarray(c)).tobytes())
    return h.hexdigest()


def test_stream_bit_identical_across_shard_counts(monkeypatch):
    ds = _dataset()
    monkeypatch.delenv("KEYSTONE_SCAN_SHARDS", raising=False)
    base = _digest(ds.raw_chunks())
    for shards in (2, 3, 5):
        monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", str(shards))
        assert _digest(ds.raw_chunks()) == base, f"shards={shards}"
        # the pipelined front door too
        assert _digest(ds.chunks()) == base, f"chunks() shards={shards}"


def test_sharded_production_through_map_chain(monkeypatch):
    ds = _dataset().map_batch(lambda c: c * 2.0 + 1.0)
    monkeypatch.delenv("KEYSTONE_SCAN_SHARDS", raising=False)
    base = _digest(ds.raw_chunks())
    monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", "3")
    assert _digest(ds.raw_chunks()) == base


def test_shard_counts_partition_index_space():
    prod = ShardedChunkProducer(
        lambda start, step: iter(
            _chunk_fn(i) for i in range(start, 10, step)
        ),
        3,
        label="t",
    )
    got = list(prod)
    assert len(got) == 10
    # shard s produced indices s, s+3, ... — 4/3/3 of 10
    assert sorted(prod.shard_chunks, reverse=True) == [4, 3, 3]


def test_skip_and_shards_compose(monkeypatch):
    ds = _dataset()
    expect = _digest(list(ds.raw_chunks())[4:])
    monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", "2")
    assert _digest(ds.raw_chunks(skip=4)) == expect


def test_shard_error_surfaces_at_its_index():
    def fn(i):
        if i == 5:
            raise RuntimeError("boom at 5")
        return _chunk_fn(i)

    ds = ChunkedDataset.from_chunk_fn(fn, 8, 64, label="errtest")
    it = maybe_shard(
        ds._skip_factory, lambda: iter(ds._payload()), shards=3,
        label="errtest",
    )
    got = []
    with pytest.raises(RuntimeError, match="boom at 5"):
        for c in it:
            got.append(c)
    # every chunk BEFORE the failing index was delivered, in order
    assert len(got) == 5
    assert _digest(got) == _digest(_chunk_fn(i) for i in range(5))


def test_early_close_joins_shard_threads():
    before = {t.name for t in threading.enumerate()}
    prod = ShardedChunkProducer(
        lambda start, step: iter(
            _chunk_fn(i) for i in range(start, 100, step)
        ),
        4,
        label="close",
    )
    next(prod)
    prod.close()
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t.name.startswith("ks-shard[close]") and t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.02)
    assert not leaked, f"shard threads leaked: {leaked}"
    assert {t.name for t in threading.enumerate()} - before <= set()


def test_opaque_factory_falls_back_to_single_producer(monkeypatch):
    # a plain generator factory has no stride seam: sharding must
    # degrade to the single producer, never fail
    def factory():
        for i in range(6):
            yield _chunk_fn(i)

    ds = ChunkedDataset(factory, 48, label="opaque")
    monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", "4")
    got = list(ds.raw_chunks())
    assert _digest(got) == _digest(_chunk_fn(i) for i in range(6))


def test_fit_parity_streaming_solver_at_2_shards(monkeypatch):
    from keystone_tpu.linalg.normal_equations import (
        solve_least_squares_streaming,
    )

    n_chunks, rows, d, k = 8, 16, 6, 3
    rng = np.random.RandomState(7)
    W = rng.randn(d, k).astype(np.float32)

    def xy(i):
        X = _chunk_fn(i, rows, d)
        return X, X @ W

    ds = ChunkedDataset.from_chunk_fn(xy, n_chunks, n_chunks * rows)
    monkeypatch.delenv("KEYSTONE_SCAN_SHARDS", raising=False)
    w1 = np.asarray(
        solve_least_squares_streaming(ds.raw_chunks(), reg=1e-3, lanes=1)
    )
    monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", "2")
    w2 = np.asarray(
        solve_least_squares_streaming(ds.raw_chunks(), reg=1e-3, lanes=1)
    )
    np.testing.assert_allclose(w1, w2, atol=1e-6, rtol=1e-6)


def test_scan_span_carries_shard_attrs(monkeypatch):
    from keystone_tpu.obs import tracer as trace_mod

    monkeypatch.setenv("KEYSTONE_SCAN_SHARDS", "3")
    tracer = trace_mod.Tracer()
    installed = trace_mod.install_if_absent(tracer)
    try:
        ds = _dataset(n_chunks=9)
        list(ds.chunks())
        spans = [
            s for s in tracer.spans() if s.name == "scan.pipeline"
            and s.attrs.get("label") == "shardtest"
        ]
        assert spans, "no scan.pipeline span recorded"
        sp = spans[-1]
        assert sp.attrs["shards"] == 3
        assert sum(sp.attrs["shard_chunks"]) == 9
    finally:
        if installed is not None:
            trace_mod.uninstall(tracer)
