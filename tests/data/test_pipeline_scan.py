"""Pipelined scan runtime: ordering, bounded buffering, failure modes
(producer-exception propagation, early-exit join), kill switch, shape
bucketing, and the streaming consumers routed through it
(data/pipeline_scan.py)."""

import threading
import time
import traceback

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import ChunkedDataset, scan_pipeline
from keystone_tpu.data.pipeline_scan import (
    ChunkPadder,
    ScanPipeline,
    bucket_ladder,
    payload_nbytes,
)


def _chunks(n=7, rows=5, d=3, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((rows, d)).astype(np.float32) for _ in range(n)]


def _scan_threads():
    return [t for t in threading.enumerate() if t.name.startswith("ks-scan")]


# -- core pipeline contract --------------------------------------------------


def test_order_and_content_preserved_under_slow_consumer():
    chunks = _chunks(9)
    it = scan_pipeline(iter(chunks), depth=2, label="t")
    out = []
    for c in it:
        time.sleep(0.005)  # slow consumer: producer fills the buffer
        out.append(np.asarray(c))
    assert len(out) == len(chunks)
    for got, want in zip(out, chunks):
        np.testing.assert_array_equal(got, want)
    assert not _scan_threads()  # producer joined at exhaustion


def test_bounded_buffer_never_exceeds_depth():
    depth = 2
    state = {"produced": 0, "consumed": 0, "max_ahead": 0}

    def source():
        for c in _chunks(12):
            state["produced"] += 1
            ahead = state["produced"] - state["consumed"]
            state["max_ahead"] = max(state["max_ahead"], ahead)
            yield c

    it = scan_pipeline(source(), depth=depth, label="t")
    for _ in it:
        state["consumed"] += 1
        time.sleep(0.01)  # slow consumer forces maximal readahead
    # lookahead bound: queue (depth) + staging ring (depth) + the chunk in
    # the producer's hand + the one being consumed
    assert state["max_ahead"] <= 2 * depth + 2, state
    assert isinstance(it, ScanPipeline)
    assert it.stats.occupancy_max <= depth


def test_producer_exception_surfaces_with_original_traceback():
    def boom_source():
        yield np.zeros((2, 2), np.float32)
        raise RuntimeError("chunk 1 exploded")

    it = scan_pipeline(boom_source(), label="t")
    first = next(it)
    assert np.asarray(first).shape == (2, 2)
    with pytest.raises(RuntimeError, match="chunk 1 exploded") as ei:
        list(it)
    tb = "".join(traceback.format_exception(ei.type, ei.value, ei.tb))
    assert "boom_source" in tb  # the producer frame is in the traceback
    assert not _scan_threads()


def test_early_consumer_exit_joins_producer_within_timeout():
    def slow_source():
        for c in _chunks(100):
            time.sleep(0.001)
            yield c

    it = scan_pipeline(slow_source(), depth=2, label="t")
    assert isinstance(it, ScanPipeline)
    next(it)
    thread = it._thread
    assert thread.is_alive()
    it.close()  # early exit: must drain + join, not deadlock
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    # closed iterator is exhausted, not wedged
    assert list(it) == []


def test_abandoned_iterator_is_reaped_by_gc():
    it = scan_pipeline(iter(_chunks(50)), label="t")
    next(it)
    thread = it._thread
    del it  # no explicit close: __del__ must join the producer
    thread.join(timeout=5.0)
    assert not thread.is_alive()


def test_generator_exit_in_wrapping_generator_does_not_deadlock():
    def consumer_gen():
        for c in scan_pipeline(iter(_chunks(50)), label="t"):
            yield c

    g = consumer_gen()
    next(g)
    g.close()  # GeneratorExit unwinds the for loop; pipeline must be reaped
    deadline = time.monotonic() + 5.0
    while _scan_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not _scan_threads()


def test_kill_switch_disables_thread_but_preserves_results(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SCAN_PIPELINE", "0")
    chunks = _chunks(5)
    before = threading.active_count()
    it = scan_pipeline(iter(chunks), label="t")
    assert not isinstance(it, ScanPipeline)
    out = list(it)
    assert threading.active_count() == before
    for got, want in zip(out, chunks):
        np.testing.assert_array_equal(np.asarray(got), want)


def test_scan_pipeline_is_idempotent():
    it = scan_pipeline(iter(_chunks(3)), label="t")
    assert scan_pipeline(it) is it
    list(it)


def test_chunked_dataset_scans_through_pipeline_and_matches():
    rng = np.random.default_rng(7)
    X = rng.standard_normal((41, 4)).astype(np.float32)
    ds = ChunkedDataset.from_array(X, 8).map_batch(lambda c: c * 2.0)
    it = ds.chunks()
    assert isinstance(it, ScanPipeline)
    it.close()
    np.testing.assert_allclose(np.asarray(ds.to_array()), X * 2.0, rtol=1e-6)
    assert not _scan_threads()


# -- tracer integration ------------------------------------------------------


def test_scan_records_span_with_stall_counters():
    from keystone_tpu.obs import SCAN_SPAN, Tracer, install
    from keystone_tpu.obs import tracer as trace_mod

    tracer = install(Tracer())
    try:
        ds = ChunkedDataset.from_array(np.ones((20, 3), np.float32), 6)
        ds.to_array()
        spans = [sp for sp in tracer.spans() if sp.name == SCAN_SPAN]
        assert spans, [sp.name for sp in tracer.spans()]
        sp = spans[-1]
        assert sp.attrs["chunks"] == 4
        for key in (
            "producer_seconds",
            "producer_stall_seconds",
            "consumer_stall_seconds",
            "staged_bytes",
            "occupancy_max",
            "depth",
        ):
            assert key in sp.attrs
        assert sp.seconds >= 0.0
    finally:
        trace_mod.reset()


# -- payload byte accounting -------------------------------------------------


def test_payload_nbytes_honors_dtypes():
    assert payload_nbytes(np.zeros((4, 2), np.float64)) == 64
    assert payload_nbytes(np.zeros((4, 2), np.int8)) == 8
    assert payload_nbytes(
        (np.zeros((2, 2), np.float32), np.zeros((2,), np.float16))
    ) == 20
    # leaves without .dtype are measured, not assumed float32
    assert payload_nbytes([1.0, 2.0]) == 16  # two python floats -> f64


# -- shape bucketing ---------------------------------------------------------


def test_bucket_ladder_shape():
    assert bucket_ladder(512) == (64, 128, 256, 512)
    assert bucket_ladder(1000) == (125, 250, 500, 1000)
    assert bucket_ladder(1, levels=4) == (1,)


def test_chunk_padder_compiles_per_bucket_and_is_exact():
    calls = []

    def fn(x):
        calls.append(int(x.shape[0]))
        return np.asarray(x) + 1.0

    padder = ChunkPadder(fn)
    sizes = [512, 480, 500, 300, 450, 200]
    rng = np.random.default_rng(3)
    for r in sizes:
        x = rng.standard_normal((r, 4)).astype(np.float32)
        out = padder(x)
        assert out.shape == (r, 4)
        np.testing.assert_allclose(np.asarray(out), x + 1.0, rtol=1e-6)
    # every call shape is a bucket, and distinct shapes <= ladder size
    ladder = set(bucket_ladder(512))
    assert set(calls) <= ladder
    assert len(set(calls)) <= len(ladder)
    assert len(set(calls)) < len(set(sizes))  # strictly fewer than raw shapes


def test_chunk_padder_kill_switch(monkeypatch):
    monkeypatch.setenv("KEYSTONE_CHUNK_BUCKETS", "0")
    shapes = []

    def fn(x):
        shapes.append(int(x.shape[0]))
        return x

    padder = ChunkPadder(fn)
    padder(np.zeros((512, 2), np.float32))
    padder(np.zeros((300, 2), np.float32))
    assert shapes == [512, 300]  # pass-through, no padding


def test_fused_chain_over_ragged_chunks_buckets_compiles():
    """End-to-end: a fused 2-node chain over a ragged chunked scan traces
    once per bucket (trace-time counter), not once per distinct shape,
    and the output is exact."""
    from keystone_tpu.workflow.transformer import FunctionNode

    sizes = [64, 60, 62, 40, 25, 64]
    total = sum(sizes)
    rng = np.random.default_rng(11)
    parts = [rng.standard_normal((r, 5)).astype(np.float32) for r in sizes]

    def gen(i):
        return parts[i]

    ds = ChunkedDataset.from_chunk_fn(gen, len(sizes), total)
    traces = []

    def f1(x):
        traces.append(int(x.shape[0]))  # runs once per jit trace
        return x * 2.0

    pipe = FunctionNode(batch_fn=f1).and_then(
        FunctionNode(batch_fn=lambda x: x + 1.0)
    )
    out = pipe.apply(ds).get()
    got = np.asarray(out.to_array())
    want = np.concatenate(parts) * 2.0 + 1.0
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert len(traces) <= len(bucket_ladder(64))
    assert len(traces) < len(set(sizes))


# -- routed consumers --------------------------------------------------------


def test_chunked_map_thread_pool_preserves_order(monkeypatch):
    monkeypatch.setenv("KEYSTONE_MAP_WORKERS", "4")
    rng = np.random.default_rng(13)
    X = rng.standard_normal((33, 6)).astype(np.float32)
    ds = ChunkedDataset.from_array(X, 7)
    out = ds.map(lambda row: row * 3.0)
    np.testing.assert_allclose(np.asarray(out.to_array()), X * 3.0, rtol=1e-6)
    monkeypatch.setenv("KEYSTONE_MAP_WORKERS", "1")
    out_serial = ds.map(lambda row: row * 3.0)
    np.testing.assert_allclose(
        np.asarray(out_serial.to_array()), X * 3.0, rtol=1e-6
    )


def test_standard_scaler_streams_chunked_without_materializing():
    from keystone_tpu.nodes.stats import StandardScaler

    rng = np.random.default_rng(17)
    X = rng.standard_normal((57, 4)).astype(np.float32) * 3.0 + 1.0
    dense = StandardScaler().fit(
        __import__("keystone_tpu.data", fromlist=["Dataset"]).Dataset(
            jnp.asarray(X), batched=True
        )
    )
    chunked = StandardScaler().fit(ChunkedDataset.from_array(X, 9))
    np.testing.assert_allclose(
        np.asarray(chunked.mean), np.asarray(dense.mean), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(chunked.std), np.asarray(dense.std), rtol=1e-3, atol=1e-4
    )


def test_standard_scaler_streaming_survives_large_mean_small_var():
    """The one-pass E[x²]−mean² form cancels catastrophically in f32 at
    |mean| ≫ std (std silently became 1.0); the Chan/Welford chunk merge
    must recover the real std."""
    from keystone_tpu.nodes.stats import StandardScaler

    rng = np.random.default_rng(29)
    X = (rng.standard_normal((64, 3)) * 0.01 + 1000.0).astype(np.float32)
    model = StandardScaler().fit(ChunkedDataset.from_array(X, 9))
    want = X.astype(np.float64).std(axis=0, ddof=1)
    np.testing.assert_allclose(np.asarray(model.std), want, rtol=0.05)


def test_streaming_solver_still_exact_through_pipeline():
    """The BCD streaming solver (routed through scan_pipeline) matches the
    in-memory block solve."""
    from keystone_tpu.linalg import (
        solve_blockwise_l2,
        solve_blockwise_l2_streaming,
    )

    rng = np.random.default_rng(23)
    n, d, bs, k = 96, 8, 4, 3
    A = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)

    def scan():
        for i in range(0, n, 32):
            yield A[i : i + 32]

    ws = solve_blockwise_l2_streaming(
        scan, jnp.asarray(y), reg=1e-2, block_size=bs
    )
    blocks = [jnp.asarray(A[:, i : i + bs]) for i in range(0, d, bs)]
    ws_ref = solve_blockwise_l2(blocks, jnp.asarray(y), reg=1e-2)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(ws, axis=0)),
        np.asarray(jnp.concatenate(ws_ref, axis=0)),
        rtol=1e-3,
        atol=1e-4,
    )
    assert not _scan_threads()
