"""Mesh-distributed scan pipeline: lane round-robin placement, per-lane
staging rings and byte accounting (straggler detection), ragged-tail
bucket rounding to the lane multiple, collectives/span attribution, and
the mesh-of-1 fallback (data/pipeline_scan.py + parallel/lanes.py).
Runs on the suite's 8-device virtual CPU mesh (tests/conftest.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from keystone_tpu.data.pipeline_scan import (
    ChunkPadder,
    ScanPipeline,
    bucket_ladder,
    scan_pipeline,
    serial_staged,
)
from keystone_tpu.parallel.lanes import (
    lane_devices,
    reduce_lane_partials,
    scan_lanes,
)


def _chunks(n=8, rows=4, d=3, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((rows, d)).astype(np.float32) for _ in range(n)]


# -- lane resolution ----------------------------------------------------------


def test_scan_lanes_defaults_to_data_axis_size():
    assert scan_lanes() == 8  # conftest provisions an 8-device mesh


def test_scan_lanes_env_override_and_clamp(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SCAN_LANES", "4")
    assert scan_lanes() == 4
    monkeypatch.setenv("KEYSTONE_SCAN_LANES", "1")
    assert scan_lanes() == 1  # the sharded-scan kill switch
    monkeypatch.setenv("KEYSTONE_SCAN_LANES", "64")
    assert scan_lanes() == 8  # clamped to the data-axis size


# -- lane round-robin placement ----------------------------------------------


def test_lane_round_robin_places_chunk_i_on_lane_i_mod_k():
    devs = lane_devices(4)
    chunks = _chunks(8)
    it = scan_pipeline(iter(chunks), lanes=4, label="t")
    assert isinstance(it, ScanPipeline) and it.lanes == 4
    for i, c in enumerate(it):
        assert c.devices() == {devs[i % 4]}, (i, c.devices())
        np.testing.assert_array_equal(np.asarray(c), chunks[i])
    assert it.stats.lane_chunks == [2, 2, 2, 2]
    assert it.stats.lane_devices == [str(d) for d in devs]


def test_serial_fallback_preserves_lane_placement(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SCAN_PIPELINE", "0")
    devs = lane_devices(4)
    chunks = _chunks(8)
    it = scan_pipeline(iter(chunks), lanes=4, label="t")
    assert not isinstance(it, ScanPipeline)
    for i, c in enumerate(it):
        assert c.devices() == {devs[i % 4]}, (i, c.devices())
        np.testing.assert_array_equal(np.asarray(c), chunks[i])


def test_lane_staging_gathers_committed_device_chunks():
    # a featurized chunk already committed elsewhere (e.g. mesh-sharded by
    # the fused chain) must still land on its lane's device
    devs = lane_devices(2)
    src = [jax.device_put(c, devs[1]) for c in _chunks(4)]
    it = scan_pipeline(iter(src), lanes=2, label="t")
    placed = list(it)
    assert placed[0].devices() == {devs[0]}  # gathered D2D to lane 0
    assert placed[1].devices() == {devs[1]}  # already home: passthrough
    assert it.stats.lane_chunks == [2, 2]
    # lane 1 chunks were already resident — no bytes counted for them
    assert it.stats.lane_bytes[0] > 0 and it.stats.lane_bytes[1] == 0


def test_single_lane_scan_keeps_todays_contract():
    it = scan_pipeline(iter(_chunks(3)), label="t")
    assert it.lanes == 1 and it.lane_devices is None
    out = list(it)
    assert len(out) == 3
    # single-lane stats carry no lane schema (span stays the old shape)
    assert it.stats.lanes == 1
    assert it.stats.lane_chunks == [] and it.stats.lane_bytes == []
    assert it.stats.collectives == 0


def test_per_lane_ring_keeps_depth_chunks_in_flight_per_lane():
    # a 2-lane depth-2 scan may stage up to depth*lanes chunks ahead
    it = scan_pipeline(iter(_chunks(12)), depth=2, lanes=2, label="t")
    next(it)
    assert len(it._staged) <= 4
    list(it)


# -- straggler / byte accounting ---------------------------------------------


def test_lane_bytes_expose_skewed_chunk_sizes():
    """Deliberately skewed chunk sizes: lane 0 receives the fat chunks, so
    its staged-byte total must dominate and the span's imbalance attr must
    say so (satellite: the obs audit can spot lane stragglers)."""
    from keystone_tpu.obs import SCAN_LANE_SPAN, SCAN_SPAN, Tracer, install
    from keystone_tpu.obs import tracer as trace_mod

    def skewed():
        for i in range(8):
            rows = 64 if i % 4 == 0 else 4
            yield np.ones((rows, 8), np.float32)

    tracer = install(Tracer())
    try:
        it = scan_pipeline(skewed(), lanes=4, label="skew")
        list(it)
        assert it.stats.lane_bytes[0] == 2 * 64 * 8 * 4
        assert it.stats.lane_bytes[1] == 2 * 4 * 8 * 4
        assert it.stats.lane_chunks == [2, 2, 2, 2]
        spans = [sp for sp in tracer.spans() if sp.name == SCAN_SPAN]
        attrs = spans[-1].attrs
        assert attrs["lane_bytes"] == it.stats.lane_bytes
        assert attrs["lane_imbalance"] > 2.0  # max lane ≫ mean lane
        lane_spans = [
            sp for sp in tracer.spans() if sp.name == SCAN_LANE_SPAN
        ]
        assert len(lane_spans) == 4
        assert {sp.attrs["lane"] for sp in lane_spans} == {0, 1, 2, 3}
        for sp in lane_spans:
            assert sp.parent_id == spans[-1].span_id
            assert sp.attrs["device"]  # device attribution present
    finally:
        trace_mod.reset()


def test_collectives_stamp_after_exhaustion_lands_on_span():
    from keystone_tpu.obs import SCAN_SPAN, Tracer, install
    from keystone_tpu.obs import tracer as trace_mod

    devs = lane_devices(4)
    tracer = install(Tracer())
    try:
        it = scan_pipeline(iter(_chunks(8)), lanes=4, label="t")
        partials = [None] * 4
        for i, c in enumerate(it):
            lane = i % 4
            s = jnp.sum(c, axis=0)
            partials[lane] = s if partials[lane] is None else partials[lane] + s
        # finalize-time reduction, AFTER the span was recorded
        total = reduce_lane_partials(partials, scan=it)
        assert total.devices() == {devs[0]}
        spans = [sp for sp in tracer.spans() if sp.name == SCAN_SPAN]
        assert spans[-1].attrs["collectives"] == 3  # 3 lanes hopped to lane 0
        assert it.stats.collectives == 3
    finally:
        trace_mod.reset()


# -- bucket-ladder lane rounding ---------------------------------------------


def test_bucket_ladder_rounds_to_multiple():
    assert bucket_ladder(20, multiple=4) == (4, 8, 12, 20)
    assert bucket_ladder(512, multiple=8) == (64, 128, 256, 512)
    # colliding rungs collapse
    assert bucket_ladder(7, multiple=8) == (8,)
    # multiple=1 is the historical ladder
    assert bucket_ladder(20) == (3, 5, 10, 20)


def test_chunk_padder_pads_ragged_tail_to_lane_multiple():
    """Regression (ISSUE 7 satellite): a 7-row tail on a 4-device axis
    must pad to 8, not 7 — otherwise the sharded fused program can't span
    the mesh for the tail chunk."""
    calls = []

    def fn(x):
        calls.append(int(x.shape[0]))
        return np.asarray(x) + 1.0

    padder = ChunkPadder(fn, multiple=4)
    lead = np.zeros((16, 2), np.float32)
    tail = np.arange(14, dtype=np.float32).reshape(7, 2)
    np.testing.assert_allclose(np.asarray(padder(lead)), lead + 1.0)
    out = padder(tail)
    assert out.shape == (7, 2)
    np.testing.assert_allclose(np.asarray(out), tail + 1.0)
    assert 8 in calls, calls
    assert all(c % 4 == 0 for c in calls), calls


def test_chunk_padder_default_multiple_follows_mesh():
    # on the suite's 8-device mesh every padded bucket divides by 8
    calls = []

    def fn(x):
        calls.append(int(x.shape[0]))
        return x

    padder = ChunkPadder(fn)
    padder(np.zeros((20, 2), np.float32))
    padder(np.zeros((7, 2), np.float32))
    assert all(c % 8 == 0 for c in calls), calls


def test_chunk_padder_sharded_run_spans_mesh_and_is_exact():
    from keystone_tpu.parallel.mesh import DATA_AXIS, default_mesh

    seen = []

    def fn(x):
        seen.append(x.sharding)
        return x * 2.0

    padder = ChunkPadder(fn, shard=True)
    x = np.random.default_rng(0).standard_normal((16, 4)).astype(np.float32)
    tail = x[:6]
    np.testing.assert_allclose(np.asarray(padder(x)), x * 2.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(padder(tail)), tail * 2.0, rtol=1e-6)
    for sh in seen:
        # every (padded) chunk was committed row-sharded over the mesh
        assert sh.spec[0] == DATA_AXIS, sh
        assert len(sh.mesh.devices.flat) == len(default_mesh().devices.flat)


def test_fused_chunked_chain_output_matches_under_sharding():
    """End-to-end: the fused chain over a ragged chunked scan (now
    mesh-sharded per chunk) still produces exact values."""
    from keystone_tpu.data import ChunkedDataset
    from keystone_tpu.workflow.transformer import FunctionNode

    sizes = [64, 60, 25, 64, 7]
    total = sum(sizes)
    rng = np.random.default_rng(11)
    parts = [rng.standard_normal((r, 5)).astype(np.float32) for r in sizes]
    ds = ChunkedDataset.from_chunk_fn(lambda i: parts[i], len(sizes), total)
    pipe = FunctionNode(batch_fn=lambda x: x * 2.0).and_then(
        FunctionNode(batch_fn=lambda x: x + 1.0)
    )
    got = np.asarray(pipe.apply(ds).get().to_array())
    np.testing.assert_allclose(
        got, np.concatenate(parts) * 2.0 + 1.0, rtol=1e-6
    )


def test_chunk_padder_sharded_with_narrow_lane_knob(monkeypatch):
    """Regression: KEYSTONE_SCAN_LANES narrower than the data axis makes
    the ladder multiple 2 while batch_sharding spans all 8 devices — a
    6-row bucket must fall back to the unsharded call, not crash XLA
    with an indivisible dim."""
    monkeypatch.setenv("KEYSTONE_SCAN_LANES", "2")
    padder = ChunkPadder(lambda x: x * 2.0, shard=True)
    x = np.random.default_rng(1).standard_normal((6, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(padder(x)), x * 2.0, rtol=1e-6)
    tail = x[:5]  # pads to 6 (multiple of 2, not of 8) — unsharded path
    np.testing.assert_allclose(np.asarray(padder(tail)), tail * 2.0, rtol=1e-6)


def test_serial_staged_single_lane_unchanged():
    chunks = _chunks(5)
    out = list(serial_staged(iter(chunks), depth=2))
    for got, want in zip(out, chunks):
        np.testing.assert_array_equal(np.asarray(got), want)
