"""ChunkedDataset: lazy per-chunk composition, lineage recompute, cache
budget policy, and zip alignment (the RDD analogue, data/chunked.py)."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.data import ChunkedDataset, Dataset


def _src(n=37, d=5, chunk=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    return X, ChunkedDataset.from_array(X, chunk)


def test_len_iter_first_to_array():
    X, ds = _src()
    assert len(ds) == 37
    assert ds.is_batched and ds.is_chunked
    np.testing.assert_allclose(np.asarray(ds.to_array()), X)
    np.testing.assert_allclose(np.asarray(ds.first()), X[0])
    items = list(ds)
    assert len(items) == 37
    np.testing.assert_allclose(np.asarray(items[11]), X[11])


def test_map_batch_is_lazy_and_recomputes_per_scan():
    X, ds = _src()
    calls = []

    def fn(chunk):
        calls.append(1)
        return chunk * 2.0

    mapped = ds.map_batch(fn)
    assert not calls  # nothing ran yet
    np.testing.assert_allclose(np.asarray(mapped.to_array()), X * 2)
    first_scan = len(calls)
    assert first_scan == 5  # ceil(37/8)
    mapped.to_array()
    assert len(calls) == 2 * first_scan  # lineage: recompute per scan


def test_map_per_item_matches_dataset_map():
    X, ds = _src()
    out = ds.map(lambda row: row.sum())
    np.testing.assert_allclose(
        np.asarray(out.to_array()), X.sum(axis=1), rtol=1e-6
    )


def test_cache_materializes_under_budget_only():
    X, ds = _src()
    cached = ds.cache(budget_bytes=1 << 20)
    assert not isinstance(cached, ChunkedDataset)
    np.testing.assert_allclose(np.asarray(cached.to_array()), X)
    still = ds.cache(budget_bytes=16)
    assert isinstance(still, ChunkedDataset)


def test_zip_chunks_aligned_and_misaligned():
    X, a = _src(seed=1)
    Y, b = _src(seed=2)
    zipped = ChunkedDataset.zip_chunks([a, b])
    chunks = list(zipped.chunks())
    assert all(isinstance(c, tuple) and len(c) == 2 for c in chunks)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([c[1] for c in chunks])), Y
    )
    bad = ChunkedDataset.from_array(Y, 7)
    with pytest.raises(ValueError):
        list(ChunkedDataset.zip_chunks([a, bad]).chunks())


def test_transformer_chain_composes_per_chunk():
    from keystone_tpu.workflow.transformer import FunctionNode

    X, ds = _src()
    node = FunctionNode(batch_fn=lambda x: x + 1.0)
    out = node.apply_batch(ds)
    assert isinstance(out, ChunkedDataset)
    np.testing.assert_allclose(np.asarray(out.to_array()), X + 1)


def test_gather_and_vector_combiner_zip_chunked_branches():
    from keystone_tpu.nodes.util import VectorCombiner
    from keystone_tpu.workflow.pipeline import Pipeline
    from keystone_tpu.workflow.transformer import FunctionNode

    X, ds = _src()
    b1 = FunctionNode(batch_fn=lambda x: x * 2.0)
    b2 = FunctionNode(batch_fn=lambda x: x - 1.0)
    pipe = Pipeline.gather([b1, b2]).and_then(VectorCombiner())
    out = pipe.apply(ds).get()
    assert isinstance(out, ChunkedDataset)
    np.testing.assert_allclose(
        np.asarray(out.to_array()),
        np.concatenate([X * 2, X - 1], axis=-1),
        rtol=1e-6,
    )


def test_from_chunk_fn_deterministic_regeneration():
    def chunk_fn(i):
        rng = np.random.default_rng(100 + i)
        return rng.standard_normal((4, 3)).astype(np.float32)

    ds = ChunkedDataset.from_chunk_fn(chunk_fn, num_chunks=3, num_rows=12)
    a = np.asarray(ds.to_array())
    b = np.asarray(ds.to_array())
    np.testing.assert_array_equal(a, b)


def test_align_and_zip_mixed_materialized_branch():
    """A gather where one branch is chunked and another already
    materialized (e.g. its Cacher fit the budget): the materialized side
    is sliced at the chunked side's boundaries as ONE scan runs (no
    probing scan — counted), same rows."""
    from keystone_tpu.data.chunked import align_and_zip

    X, base = _src(seed=3)
    scans = []
    counted = ChunkedDataset(
        lambda: (scans.append(1) or iter(p for p in base._payload())),
        len(base),
    )
    b = Dataset(jnp.asarray(X * 3.0), batched=True)
    zipped = align_and_zip([counted, b])
    assert len(zipped) == len(base)
    assert not scans  # lazy until scanned
    chunks = list(zipped.chunks())
    assert len(scans) == 1  # exactly one scan of the chunked side
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([c[0] for c in chunks])), X, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([c[1] for c in chunks])), X * 3.0,
        rtol=1e-6,
    )
    # per-chunk row counts line up
    for c in chunks:
        assert c[0].shape[0] == c[1].shape[0]


def test_align_and_zip_error_paths():
    import pytest

    from keystone_tpu.data.chunked import align_and_zip

    X, a = _src(seed=3)
    with pytest.raises(ValueError):  # no chunked branch at all
        align_and_zip([Dataset(jnp.asarray(X), batched=True)])
    short = Dataset(jnp.asarray(X[:-1]), batched=True)
    with pytest.raises(ValueError):  # length mismatch
        align_and_zip([a, short])
    # misaligned boundaries between two chunked branches, caught mid-scan
    other = ChunkedDataset.from_array(X, 7)
    with pytest.raises(ValueError):
        list(align_and_zip([a, other]).chunks())
    # three-way: two chunked in lockstep + one materialized slice
    twin = ChunkedDataset.from_array(X * 2.0, 8)
    tri = list(
        align_and_zip(
            [a, twin, Dataset(jnp.asarray(X * 3.0), batched=True)]
        ).chunks()
    )
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([c[1] for c in tri])), X * 2.0, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([c[2] for c in tri])), X * 3.0, rtol=1e-6
    )


def test_prefetch_to_device_bounded_lookahead_and_device_output():
    import jax

    from keystone_tpu.data.chunked import prefetch_to_device

    rng = np.random.default_rng(4)
    chunks = [rng.standard_normal((5, 3)).astype(np.float32) for _ in range(7)]
    pulled = []

    def source():
        for c in chunks:
            pulled.append(1)
            yield c

    it = prefetch_to_device(source(), depth=3)
    first = next(it)
    # bounded lookahead: at most depth source chunks consumed so far
    # (+1 for the generator's own readahead slack)
    assert len(pulled) <= 4, pulled
    out = [first] + list(it)
    assert len(out) == 7
    for got, want in zip(out, chunks):
        assert isinstance(got, jax.Array)  # really placed on device
        np.testing.assert_array_equal(np.asarray(got), want)
