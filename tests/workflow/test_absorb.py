"""FittedPipeline.absorb: incremental refit that folds appended chunks into
the saved accumulator state — parity with a from-scratch fit on the
concatenated data, O(new chunks) work, frozen prefix."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.workflow.transformer import FunctionNode


def _featurize():
    return FunctionNode(batch_fn=lambda X: jnp.tanh(X) * 2.0, label="feat")


def _problem(n, d=24, k=3, seed=0, offset=1.5):
    """Nonzero feature AND label means, so centering + intercept carry
    real information through the refit."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) + offset
    W = rng.normal(size=(d, k)).astype(np.float32)
    Y = ((np.tanh(X) * 2.0) @ W + 0.1 * rng.normal(size=(n, k)) + 3.0)
    return X, Y.astype(np.float32)


def _counting_chunked(arr, chunk_rows, counter):
    """ChunkedDataset whose factory counts every chunk production."""
    n = int(arr.shape[0])

    def factory():
        for i in range(0, n, chunk_rows):
            counter[0] += 1
            yield arr[i : i + chunk_rows]

    return ChunkedDataset(factory, n, label=f"counting[{n}]")


def _concat_chunked(a, a_rows, b, b_rows):
    """The concatenated dataset with the SAME chunk boundaries the
    fit-then-absorb sequence saw — parity against it is exact, not
    summation-order-limited."""
    def factory():
        for i in range(0, int(a.shape[0]), a_rows):
            yield a[i : i + a_rows]
        for i in range(0, int(b.shape[0]), b_rows):
            yield b[i : i + b_rows]

    return ChunkedDataset(
        factory, int(a.shape[0]) + int(b.shape[0]), label="concat"
    )


def _model_W(fitted):
    ws = [
        op for op in fitted.graph.operators.values() if hasattr(op, "W")
    ]
    assert len(ws) == 1
    return ws[0]


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------


def test_absorb_matches_from_scratch_with_centering_and_ragged_chunks():
    """The acceptance gate: absorb(new_chunks) ≤ 1e-6 of a from-scratch
    fit on the concatenated data. Original data ends on a ragged 24-row
    chunk, the appended stream on a ragged 1-row chunk, and both feature
    and label means are far from zero."""
    X, Y = _problem(600)
    Xn, Yn = _problem(97, seed=1)
    prefix = _featurize().to_pipeline()

    fitted = prefix.and_then(
        LinearMapEstimator(lam=1e-2, snapshot=True),
        ChunkedDataset.from_array(X, 64), Dataset.of(Y),
    ).fit()
    updated = fitted.absorb(ChunkedDataset.from_array(Xn, 32), Dataset.of(Yn))

    scratch = prefix.and_then(
        LinearMapEstimator(lam=1e-2, snapshot=True),
        _concat_chunked(X, 64, Xn, 32),
        Dataset.of(np.concatenate([Y, Yn])),
    ).fit()

    mu, ms = _model_W(updated), _model_W(scratch)
    assert np.max(np.abs(np.asarray(mu.W) - np.asarray(ms.W))) <= 1e-6
    assert np.max(np.abs(np.asarray(mu.b) - np.asarray(ms.b))) <= 1e-6
    assert np.max(
        np.abs(np.asarray(mu.feature_mean) - np.asarray(ms.feature_mean))
    ) <= 1e-6
    # end-to-end predictions agree too
    got = np.asarray(updated.apply(Dataset.of(Xn[:32])).to_array())
    want = np.asarray(scratch.apply(Dataset.of(Xn[:32])).to_array())
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_absorb_actually_moves_the_model():
    """Appending differently-distributed data must change W/b — absorb is
    a refit, not a no-op."""
    X, Y = _problem(400)
    Xn = np.random.default_rng(7).normal(size=(200, 24)).astype(np.float32) - 2.0
    Yn = np.zeros((200, 3), np.float32)
    fitted = LinearMapEstimator(lam=1e-2, snapshot=True).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    updated = fitted.absorb(Dataset.of(Xn), Dataset.of(Yn))
    assert np.max(np.abs(
        np.asarray(_model_W(updated).W) - np.asarray(_model_W(fitted).W)
    )) > 1e-3


def test_sequential_absorbs_compose():
    """absorb(b) then absorb(c) == from-scratch on a+b+c (matched
    chunking): the state the second absorb starts from is exactly the
    first absorb's output state."""
    X, Y = _problem(300)
    Xb, Yb = _problem(64, seed=2)
    Xc, Yc = _problem(50, seed=3)
    est = lambda: LinearMapEstimator(lam=0.1, snapshot=True)  # noqa: E731

    fitted = est().with_data(
        ChunkedDataset.from_array(X, 100), Dataset.of(Y)
    ).fit()
    twice = fitted.absorb(
        ChunkedDataset.from_array(Xb, 64), Dataset.of(Yb)
    ).absorb(ChunkedDataset.from_array(Xc, 50), Dataset.of(Yc))

    def factory():
        for i in range(0, 300, 100):
            yield X[i : i + 100]
        yield Xb
        yield Xc

    scratch = est().with_data(
        ChunkedDataset(factory, 414, label="abc"),
        Dataset.of(np.concatenate([Y, Yb, Yc])),
    ).fit()
    assert np.max(np.abs(
        np.asarray(_model_W(twice).W) - np.asarray(_model_W(scratch).W)
    )) <= 1e-6


def test_absorb_leaves_the_original_pipeline_untouched():
    X, Y = _problem(300)
    Xn, Yn = _problem(100, seed=4)
    fitted = LinearMapEstimator(lam=1e-2, snapshot=True).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    W_before = np.asarray(_model_W(fitted).W).copy()
    state_n = _model_W(fitted).solver_state.n
    fitted.absorb(Dataset.of(Xn), Dataset.of(Yn))
    np.testing.assert_array_equal(np.asarray(_model_W(fitted).W), W_before)
    assert _model_W(fitted).solver_state.n == state_n == 300


# ---------------------------------------------------------------------------
# the work gate: O(new chunks), never a rescan of the original data
# ---------------------------------------------------------------------------


def test_absorb_scans_only_the_appended_chunks():
    X, Y = _problem(600)
    Xn, Yn = _problem(97, seed=1)
    old_count, new_count = [0], [0]

    fitted = _featurize().to_pipeline().and_then(
        LinearMapEstimator(lam=1e-2, snapshot=True),
        _counting_chunked(X, 64, old_count), Dataset.of(Y),
    ).fit()
    scans_for_fit = old_count[0]
    assert scans_for_fit >= 10  # 600/64 → 10 chunks, ≥ 1 scan

    updated = fitted.absorb(
        _counting_chunked(Xn, 32, new_count), Dataset.of(Yn)
    )
    assert old_count[0] == scans_for_fit, (
        "absorb re-scanned the original training data"
    )
    assert new_count[0] == 4  # ceil(97/32): exactly one scan of the new data
    assert _model_W(updated).solver_state.n == 697


# ---------------------------------------------------------------------------
# contract errors
# ---------------------------------------------------------------------------


def test_absorb_without_snapshot_state_raises():
    X, Y = _problem(200)
    fitted = LinearMapEstimator(lam=1e-2).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    with pytest.raises(ValueError, match="snapshot-able"):
        fitted.absorb(Dataset.of(X[:10]), Dataset.of(Y[:10]))


def test_absorb_row_mismatch_raises():
    X, Y = _problem(200)
    fitted = LinearMapEstimator(lam=1e-2, snapshot=True).with_data(
        Dataset.of(X), Dataset.of(Y)
    ).fit()
    with pytest.raises(ValueError):
        fitted.absorb(
            ChunkedDataset.from_array(X[:64], 32), Dataset.of(Y[:50])
        )


def test_absorb_checkpoint_never_resumes_foreign_data(tmp_path):
    """A crashed absorb's checkpoint binds the appended data's identity
    (labels digest in the default key): a later absorb of DIFFERENT
    same-shaped data must start fresh, never resume the foreign fold."""
    X, Y = _problem(300)
    Xa, Ya = _problem(96, seed=7)
    Xb, Yb = _problem(96, seed=8)
    fitted = _featurize().to_pipeline().and_then(
        LinearMapEstimator(lam=0.1, snapshot=True),
        ChunkedDataset.from_array(X, 64), Dataset.of(Y),
    ).fit()

    class Boom(Exception):
        pass

    def killer(i, chunk):
        if i == 2:
            raise Boom()

    with pytest.raises(Boom):
        fitted.absorb(
            ChunkedDataset.from_array(Xa, 32), Dataset.of(Ya),
            checkpoint=str(tmp_path), on_chunk=killer,
        )
    resumed_b = fitted.absorb(
        ChunkedDataset.from_array(Xb, 32), Dataset.of(Yb),
        checkpoint=str(tmp_path),
    )
    clean_b = fitted.absorb(
        ChunkedDataset.from_array(Xb, 32), Dataset.of(Yb)
    )
    sa, sb = _model_W(resumed_b).solver_state, _model_W(clean_b).solver_state
    assert np.array_equal(sa.gram, sb.gram)
    assert np.array_equal(sa.cross, sb.cross)
    assert sa.n == sb.n == 396

    # and the SAME data crashed-then-retried DOES resume (bit-identical)
    with pytest.raises(Boom):
        fitted.absorb(
            ChunkedDataset.from_array(Xa, 32), Dataset.of(Ya),
            checkpoint=str(tmp_path), on_chunk=killer,
        )
    resumed_a = fitted.absorb(
        ChunkedDataset.from_array(Xa, 32), Dataset.of(Ya),
        checkpoint=str(tmp_path),
    )
    clean_a = fitted.absorb(
        ChunkedDataset.from_array(Xa, 32), Dataset.of(Ya)
    )
    assert np.array_equal(
        _model_W(resumed_a).solver_state.gram,
        _model_W(clean_a).solver_state.gram,
    )
