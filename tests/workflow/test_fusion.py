"""TraceFusionRule grouping invariants + FusedTransformerOperator semantics.

The rule rewrites every fit/apply execution path, so its constraints get
direct coverage: multi-consumer exclusion, sink-consumed exclusion, Cacher
(untraceable) boundaries, annotated-node exclusion, external-dep splicing,
and the non-batched Dataset fallback.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.workflow.fusion import FusedTransformerOperator, TraceFusionRule
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.pipeline import Pipeline
from keystone_tpu.workflow.transformer import FunctionNode, Transformer


class _Mul(Transformer):
    def __init__(self, k):
        self.k = k

    def trace_batch(self, X):
        return X * self.k


class _HostOnly(Transformer):
    """No trace_batch — a fusion boundary, like Cacher/Shuffler."""

    def apply(self, x):
        return x + 1.0


def _fused_ops(graph):
    return [
        graph.get_operator(n)
        for n in graph.nodes
        if isinstance(graph.get_operator(n), FusedTransformerOperator)
    ]


def test_linear_chain_fuses_to_one_node_with_same_output():
    pipe = _Mul(2.0).and_then(_Mul(3.0)).and_then(_Mul(0.5))
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    ops = _fused_ops(fused_graph)
    assert len(ops) == 1 and len(ops[0].steps) == 3
    # remaining node count: just the fused node
    assert len(fused_graph.nodes) == 1

    X = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = Pipeline(fused_graph, pipe.source, pipe.sink)(X).get().to_array()
    np.testing.assert_allclose(np.asarray(out), X * 3.0)


def test_host_node_bounds_groups():
    pipe = _Mul(2.0).and_then(_Mul(3.0)).and_then(_HostOnly()).and_then(_Mul(4.0))
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    ops = _fused_ops(fused_graph)
    # upstream pair fuses; the single node after the host boundary stays bare
    assert len(ops) == 1 and len(ops[0].steps) == 2
    X = np.ones((2, 2), dtype=np.float32)
    out = Pipeline(fused_graph, pipe.source, pipe.sink)(X).get().to_array()
    np.testing.assert_allclose(np.asarray(out), (X * 6.0 + 1.0) * 4.0)


def test_diamond_with_all_consumers_traceable_fuses_whole():
    # shared feeds two traceable branches re-joined by gather: the whole
    # diamond is ONE legal group (every consumer of every member is inside)
    shared = _Mul(2.0)
    b1 = shared.and_then(_Mul(3.0)).and_then(_Mul(5.0))
    b2 = shared.and_then(_Mul(7.0)).and_then(_Mul(11.0))
    pipe = Pipeline.gather([b1, b2])
    from keystone_tpu.workflow.rules import EquivalentNodeMergeRule

    graph, _ = EquivalentNodeMergeRule().apply(pipe.graph, {})
    fused_graph, _ = TraceFusionRule().apply(graph, {})
    assert len(_fused_ops(fused_graph)) == 1
    X = np.ones((2, 2), dtype=np.float32)
    out = Pipeline(fused_graph, pipe.source, pipe.sink)(X).get()
    got = [np.asarray(a) for a in out.payload]
    np.testing.assert_allclose(got[0], X * 30.0)
    np.testing.assert_allclose(got[1], X * 154.0)


def test_node_with_consumer_outside_group_not_absorbed():
    # shared feeds a traceable chain AND a host-only node: the group built
    # around the chain cannot absorb shared (host consumer is outside it)
    shared = _Mul(2.0)
    b1 = shared.and_then(_Mul(3.0)).and_then(_Mul(5.0))
    b2 = shared.and_then(_HostOnly())
    pipe = Pipeline.gather([b1, b2])
    from keystone_tpu.workflow.rules import EquivalentNodeMergeRule

    graph, _ = EquivalentNodeMergeRule().apply(pipe.graph, {})
    fused_graph, _ = TraceFusionRule().apply(graph, {})
    for op in _fused_ops(fused_graph):
        assert shared not in [s[0] for s in op.steps], (
            "node with an out-of-group consumer was absorbed"
        )
    X = np.ones((2, 2), dtype=np.float32)
    out = Pipeline(fused_graph, pipe.source, pipe.sink)(X).get()
    got = [np.asarray(a) for a in out.payload]
    np.testing.assert_allclose(got[0], X * 30.0)
    np.testing.assert_allclose(got[1], X * 2.0 + 1.0)


def test_sink_consumed_interior_node_not_absorbed():
    # graph with two sinks: one at the chain end, one at an interior node
    a, b = _Mul(2.0), _Mul(3.0)
    graph = Graph()
    graph, source = graph.add_source()
    graph, na = graph.add_node(a, [source])
    graph, nb = graph.add_node(b, [na])
    graph, sink_mid = graph.add_sink(na)
    graph, sink_end = graph.add_sink(nb)
    fused_graph, _ = TraceFusionRule().apply(graph, {})
    # na is sink-consumed: no group may absorb it, so nothing fuses (groups
    # of one are left alone)
    assert _fused_ops(fused_graph) == []


def test_annotated_node_not_fused():
    pipe = _Mul(2.0).and_then(_Mul(3.0))
    # annotate the first node (as if it were a saveable prefix)
    first = sorted(pipe.graph.nodes)[0]
    fused_graph, ann = TraceFusionRule().apply(pipe.graph, {first: "prefix"})
    assert _fused_ops(fused_graph) == []
    assert ann == {first: "prefix"}


def test_item_dataset_fallback_matches_batched():
    pipe = _Mul(2.0).and_then(_Mul(3.0))
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    (fused,) = _fused_ops(fused_graph)
    ragged = Dataset.from_items(
        [np.ones((2,), np.float32), np.zeros((3,), np.float32)]
    )
    from keystone_tpu.workflow.expressions import DatasetExpression

    out = fused.batch_transform([DatasetExpression.now(ragged)])
    got = out.collect()
    np.testing.assert_allclose(np.asarray(got[0]), np.full((2,), 6.0))
    np.testing.assert_allclose(np.asarray(got[1]), np.zeros((3,)))


def test_fused_single_datum_path():
    pipe = _Mul(2.0).and_then(_Mul(3.0))
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    p = Pipeline(fused_graph, pipe.source, pipe.sink)
    out = p.apply_datum(np.ones((3,), np.float32)).get()
    np.testing.assert_allclose(np.asarray(out), np.full((3,), 6.0))


def test_gather_and_combiner_fuse_and_agree():
    from keystone_tpu.nodes.util import VectorCombiner

    branches = [_Mul(float(i + 1)) for i in range(3)]
    pipe = Pipeline.gather(branches).and_then(VectorCombiner())
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    ops = _fused_ops(fused_graph)
    assert len(ops) == 1 and len(ops[0].steps) == 5  # 3 muls + gather + combiner
    X = np.ones((2, 2), dtype=np.float32)
    out = Pipeline(fused_graph, pipe.source, pipe.sink)(X).get().to_array()
    expect = np.concatenate([X * 1, X * 2, X * 3], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_fusion_idempotent_and_picklable():
    import pickle

    pipe = _Mul(2.0).and_then(_Mul(3.0))
    g1, _ = TraceFusionRule().apply(pipe.graph, {})
    g2, _ = TraceFusionRule().apply(g1, {})
    assert len(_fused_ops(g2)) == 1
    (fused,) = _fused_ops(g1)
    fused._jitted()  # populate the non-picklable cache
    clone = pickle.loads(pickle.dumps(fused))
    assert clone._jit is None and len(clone.steps) == len(fused.steps)


def test_no_fuse_marker_respected():
    marked = _Mul(3.0)
    marked.no_fuse = True
    pipe = _Mul(2.0).and_then(marked).and_then(_Mul(4.0))
    fused_graph, _ = TraceFusionRule().apply(pipe.graph, {})
    for op in _fused_ops(fused_graph):
        assert marked not in [s[0] for s in op.steps]
