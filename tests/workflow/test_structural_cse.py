"""Structural CSE (VERDICT r3 #6).

Parity: ``EquivalentNodeMergeRule.scala:13`` — the reference's operators are
Scala case classes, so *separately constructed* equal nodes compare equal and
merge. Here :func:`keystone_tpu.workflow.operators.structural_key` recovers
that: class + canonicalized parameters (numpy arrays by content digest),
with object-identity fallback for closures and arbitrary state.
"""

import numpy as np

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.workflow.operators import structural_key
from keystone_tpu.workflow.pipeline import Pipeline
from keystone_tpu.workflow.rules import EquivalentNodeMergeRule
from keystone_tpu.workflow.transformer import LabelEstimator, Transformer


class _Scale(Transformer):
    def __init__(self, s):
        self.s = s

    def trace_batch(self, X):
        return X * self.s


class _Shift(Transformer):
    def __init__(self, offset):
        self.offset = np.asarray(offset, dtype=np.float32)

    def trace_batch(self, X):
        return X + self.offset


class _Closure(Transformer):
    def __init__(self, fn):
        self.fn = fn

    def apply_batch(self, data):
        return Dataset.of(data).map_batch(self.fn)


class _CountingEstimator(LabelEstimator):
    def __init__(self, s):
        self.s = s
        self.num_fits = 0

    def fit(self, data, labels):
        self.num_fits += 1
        return _Scale(self.s)


def _n_nodes(graph):
    return len(list(graph.nodes))


def test_structural_key_equal_for_equal_params():
    assert structural_key(_Scale(2.0)) == structural_key(_Scale(2.0))
    assert structural_key(_Scale(2.0)) != structural_key(_Scale(3.0))
    # array params compare by content
    a = structural_key(_Shift([1.0, 2.0]))
    b = structural_key(_Shift([1.0, 2.0]))
    c = structural_key(_Shift([1.0, 2.5]))
    assert a == b and a != c


def test_structural_key_closure_falls_back_to_identity():
    f = lambda X: X  # noqa: E731
    t1, t2 = _Closure(f), _Closure(f)
    # even sharing the same callable, separately built nodes keep identity
    assert structural_key(t1) is t1
    assert structural_key(t2) is t2


def test_independently_built_equal_prefixes_merge():
    """The reference suite's scenario: two branches that independently
    construct the same PixelScaler→GrayScaler-style prefix collapse to
    one (EquivalentNodeMergeRule.scala merge-equal-nodes)."""
    b1 = _Scale(2.0).and_then(_Shift([1.0]))
    b2 = _Scale(2.0).and_then(_Shift([1.0]))  # separate, equal objects
    pipe = Pipeline.gather([b1.and_then(_Scale(3.0)), b2.and_then(_Scale(5.0))])
    before = _n_nodes(pipe.graph)
    graph, _ = EquivalentNodeMergeRule().apply(pipe.graph, {})
    # the two-node equal prefix merged; the distinct tails did not
    assert _n_nodes(graph) == before - 2
    X = np.ones((2, 3), dtype=np.float32)
    out = Pipeline(graph, pipe.source, pipe.sink)(X).get()
    got = [np.asarray(a) for a in out.payload]
    np.testing.assert_allclose(got[0], (X * 2.0 + 1.0) * 3.0)
    np.testing.assert_allclose(got[1], (X * 2.0 + 1.0) * 5.0)


def test_unequal_params_do_not_merge():
    b1 = _Scale(2.0)
    b2 = _Scale(2.0000001)
    pipe = Pipeline.gather([b1, b2])
    before = _n_nodes(pipe.graph)
    graph, _ = EquivalentNodeMergeRule().apply(pipe.graph, {})
    assert _n_nodes(graph) == before


def test_closure_nodes_do_not_merge():
    f = lambda X: np.asarray(X) * 2.0  # noqa: E731
    pipe = Pipeline.gather([_Closure(f), _Closure(f)])
    before = _n_nodes(pipe.graph)
    graph, _ = EquivalentNodeMergeRule().apply(pipe.graph, {})
    assert _n_nodes(graph) == before


def test_equal_estimators_fit_once_after_merge():
    """Fit-once survives: two structurally-equal estimators over the same
    data merge into one estimator node, so exactly one fit runs."""
    X = np.arange(12, dtype=np.float32).reshape(4, 3)
    y = np.ones((4, 1), dtype=np.float32)
    data = Dataset.of(X)
    labels = Dataset.of(y)
    e1 = _CountingEstimator(2.0)
    e2 = _CountingEstimator(2.0)
    p1 = _Scale(1.0).and_then(e1, data, labels)
    p2 = _Scale(1.0).and_then(e2, data, labels)
    pipe = Pipeline.gather([p1, p2])
    out = pipe(X).get()
    got = [np.asarray(a) for a in out.payload]
    np.testing.assert_allclose(got[0], X * 2.0)
    np.testing.assert_allclose(got[1], X * 2.0)
    # exactly one of the two estimator objects fit, exactly once
    assert e1.num_fits + e2.num_fits == 1
