"""Graph surgery tests (parity: workflow/GraphSuite.scala — every op including
argument-check failure paths)."""

import pytest

from keystone_tpu.workflow.graph import Graph, GraphError, NodeId, SinkId, SourceId
from keystone_tpu.workflow.operators import Operator


class Op(Operator):
    """Minimal identity-distinct operator for structural tests."""

    def __init__(self, name):
        self.name = name

    @property
    def label(self):
        return self.name


def build_simple():
    """source -> a -> b -> sink, plus c hanging off a."""
    g = Graph()
    g, s = g.add_source()
    a, b, c = Op("a"), Op("b"), Op("c")
    g, na = g.add_node(a, [s])
    g, nb = g.add_node(b, [na])
    g, nc = g.add_node(c, [na])
    g, snk = g.add_sink(nb)
    return g, s, na, nb, nc, snk


def test_add_node_and_accessors():
    g, s, na, nb, nc, snk = build_simple()
    assert g.nodes == {na, nb, nc}
    assert g.sources == {s}
    assert g.sinks == {snk}
    assert g.get_dependencies(nb) == (na,)
    assert g.get_sink_dependency(snk) == nb
    assert g.get_operator(na).label == "a"


def test_add_node_missing_dep_fails():
    g = Graph()
    with pytest.raises(GraphError):
        g.add_node(Op("x"), [NodeId(99)])
    with pytest.raises(GraphError):
        g.add_node(Op("x"), [SourceId(0)])


def test_add_sink_missing_dep_fails():
    g = Graph()
    with pytest.raises(GraphError):
        g.add_sink(NodeId(0))


def test_get_missing_node_fails():
    g, *_ = build_simple()
    with pytest.raises(GraphError):
        g.get_operator(NodeId(99))
    with pytest.raises(GraphError):
        g.get_dependencies(NodeId(99))
    with pytest.raises(GraphError):
        g.get_sink_dependency(SinkId(99))


def test_set_dependencies_and_operator():
    g, s, na, nb, nc, snk = build_simple()
    g2 = g.set_dependencies(nb, [nc])
    assert g2.get_dependencies(nb) == (nc,)
    assert g.get_dependencies(nb) == (na,)  # original untouched (immutability)
    new_op = Op("b2")
    g3 = g.set_operator(nb, new_op)
    assert g3.get_operator(nb) is new_op
    assert g.get_operator(nb).label == "b"


def test_set_on_missing_node_fails():
    g, *_ = build_simple()
    with pytest.raises(GraphError):
        g.set_operator(NodeId(99), Op("x"))
    with pytest.raises(GraphError):
        g.set_dependencies(NodeId(99), [])
    with pytest.raises(GraphError):
        g.set_sink_dependency(SinkId(99), NodeId(0))


def test_remove_node_referenced_fails():
    g, s, na, nb, nc, snk = build_simple()
    with pytest.raises(GraphError):
        g.remove_node(na)  # b and c depend on it
    with pytest.raises(GraphError):
        g.remove_node(nb)  # sink depends on it
    g2 = g.remove_node(nc)
    assert nc not in g2.nodes


def test_remove_source_referenced_fails():
    g, s, na, *_ = build_simple()
    with pytest.raises(GraphError):
        g.remove_source(s)


def test_remove_sink_then_node():
    g, s, na, nb, nc, snk = build_simple()
    g = g.remove_sink(snk)
    g = g.remove_node(nb)
    g = g.remove_node(nc)
    g = g.remove_node(na)
    g = g.remove_source(s)
    assert not g.nodes and not g.sources and not g.sinks


def test_replace_dependency():
    g, s, na, nb, nc, snk = build_simple()
    g2 = g.replace_dependency(nb, nc)  # sink now reads c
    assert g2.get_sink_dependency(snk) == nc


def test_add_graph_disjoint_union():
    g1, s1, na1, nb1, nc1, snk1 = build_simple()
    g2, s2, na2, nb2, nc2, snk2 = build_simple()
    merged, source_map, sink_map = g1.add_graph(g2)
    assert len(merged.nodes) == 6
    assert len(merged.sources) == 2
    assert len(merged.sinks) == 2
    # remapped ids don't collide
    assert source_map[s2] != s1
    assert sink_map[snk2] != snk1
    # structure preserved under remap
    new_sink_dep = merged.get_sink_dependency(sink_map[snk2])
    assert merged.get_operator(new_sink_dep).label == "b"


def test_connect_graph_splices_sink_to_source():
    g1 = Graph()
    g1, s1 = g1.add_source()
    a = Op("a")
    g1, na = g1.add_node(a, [s1])
    g1, snk1 = g1.add_sink(na)

    g2 = Graph()
    g2, s2 = g2.add_source()
    b = Op("b")
    g2, nb = g2.add_node(b, [s2])
    g2, snk2 = g2.add_sink(nb)

    merged, source_map, sink_map = g1.connect_graph(g2, {snk1: s2})
    # spliced source and sink are gone
    assert len(merged.sources) == 1
    assert len(merged.sinks) == 1
    # b's dependency is now a
    (new_b,) = [n for n in merged.nodes if merged.get_operator(n) is b]
    (new_a,) = [n for n in merged.nodes if merged.get_operator(n) is a]
    assert merged.get_dependencies(new_b) == (new_a,)


def test_connect_graph_bad_splice_fails():
    g1, s1, na1, nb1, nc1, snk1 = build_simple()
    g2, s2, *_ = build_simple()
    with pytest.raises(GraphError):
        g1.connect_graph(g2, {SinkId(99): s2})
    with pytest.raises(GraphError):
        g1.connect_graph(g2, {snk1: SourceId(99)})


def test_replace_nodes():
    # source -> a -> b -> sink; replace b with subgraph (x -> y)
    g = Graph()
    g, s = g.add_source()
    a, b = Op("a"), Op("b")
    g, na = g.add_node(a, [s])
    g, nb = g.add_node(b, [na])
    g, snk = g.add_sink(nb)

    rep = Graph()
    rep, rs = rep.add_source()
    x, y = Op("x"), Op("y")
    rep, nx = rep.add_node(x, [rs])
    rep, ny = rep.add_node(y, [nx])
    rep, rsnk = rep.add_sink(ny)

    out = g.replace_nodes(frozenset([nb]), rep, {rs: na}, {nb: rsnk})
    labels = sorted(out.get_operator(n).label for n in out.nodes)
    assert labels == ["a", "x", "y"]
    final = out.get_sink_dependency(snk)
    assert out.get_operator(final) is y
    (x_node,) = [n for n in out.nodes if out.get_operator(n) is x]
    (a_node,) = [n for n in out.nodes if out.get_operator(n) is a]
    assert out.get_dependencies(x_node) == (a_node,)


def test_to_dot_contains_structure():
    g, s, na, nb, nc, snk = build_simple()
    dot = g.to_dot()
    assert "digraph" in dot
    assert "a" in dot and "b" in dot
    assert "->" in dot
