"""Pipeline tracing subsystem (keystone_tpu/obs): span tree, executor
cache hit/miss attribution, Chrome-trace export, the autocache
estimate-vs-observed audit, serving micro-batch spans, and the CLI
``--trace`` wiring."""

import json
import threading

import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.obs import tracer as trace_mod
from keystone_tpu.obs.audit import cache_audit, log_cache_audit
from keystone_tpu.obs.export import (
    format_top_spans,
    to_chrome_trace,
    write_chrome_trace,
)
from keystone_tpu.workflow.executor import GraphExecutor
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.transformer import FunctionNode, Transformer


@pytest.fixture(autouse=True)
def clean_tracer():
    """Tracing must never leak across tests — a leaked tracer would add a
    device sync to every executor pull in the rest of the suite."""
    trace_mod.reset()
    yield
    trace_mod.reset()


def _installed():
    return trace_mod.install(trace_mod.Tracer())


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_span_tree_nesting_and_ids():
    t = trace_mod.Tracer()
    with t.span("outer") as outer:
        with t.span("inner") as inner:
            pass
    spans = {sp.name: sp for sp in t.spans()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner"].depth == 1
    assert spans["outer"].parent_id is None
    assert spans["outer"].end >= spans["inner"].end
    assert outer.span_id != inner.span_id


def test_span_stacks_are_per_thread():
    t = trace_mod.Tracer()
    started = threading.Barrier(2)

    def work(name):
        with t.span(name):
            started.wait(timeout=5)

    threads = [threading.Thread(target=work, args=(f"t{i}",)) for i in (0, 1)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    # both spans overlapped in time yet neither parents the other
    assert all(sp.parent_id is None for sp in t.spans())
    assert {sp.name for sp in t.spans()} == {"t0", "t1"}


def test_disabled_tracing_records_nothing():
    t = trace_mod.Tracer()
    trace_mod.install(t)
    trace_mod.stop()
    fitted = (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="double")
        .to_pipeline()
        .fit()
    )
    fitted.apply(np.ones((3, 2), np.float32))
    assert trace_mod.current() is None
    assert t.spans() == []


def test_suspended_reinstalls_tracer():
    t = _installed()
    with trace_mod.suspended():
        assert trace_mod.current() is None
    assert trace_mod.current() is t


# ---------------------------------------------------------------------------
# executor instrumentation
# ---------------------------------------------------------------------------


class _Scale(Transformer):
    def __init__(self, factor):
        self.factor = factor

    def apply(self, x):
        return x * self.factor


def _chain_graph():
    g = Graph()
    g, leaf = g.add_node(
        DatasetOperator(Dataset(np.ones((4, 2), np.float32), batched=True)), []
    )
    g, n1 = g.add_node(_Scale(2.0), [leaf])
    g, n2 = g.add_node(_Scale(3.0), [n1])
    g, sink = g.add_sink(n2)
    return g, (leaf, n1, n2), sink


def test_executor_records_miss_then_hit_spans():
    g, (leaf, n1, n2), sink = _chain_graph()
    t = _installed()
    ex = GraphExecutor(g, optimize=False)
    ex.execute(sink).get()
    misses = [sp for sp in t.spans() if sp.cache == "miss"]
    assert {sp.node_id for sp in misses} == {
        str(leaf.id), str(n1.id), str(n2.id)
    }
    for sp in misses:
        assert sp.op_type in ("DatasetOperator", "_Scale")
        assert sp.sync_seconds >= 0.0
    # a second pull returns the memoized sink expression: hit, no recompute
    before = len(t.spans())
    ex.execute(sink).get()
    new = t.spans()[before:]
    assert [sp.cache for sp in new] == ["hit"]
    assert new[0].node_id == str(n2.id)
    assert new[0].instant


def test_executor_span_reports_output_bytes():
    g, (leaf, n1, n2), sink = _chain_graph()
    t = _installed()
    GraphExecutor(g, optimize=False).execute(sink).get()
    sp = next(s for s in t.spans() if s.node_id == str(n2.id))
    assert sp.output_bytes == 4 * 2 * 4  # (4,2) float32


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_chrome_trace_well_formed(tmp_path):
    g, _, sink = _chain_graph()
    t = _installed()
    GraphExecutor(g, optimize=False).execute(sink).get()
    GraphExecutor(g, optimize=False).execute(sink).get()  # fresh miss spans
    doc = to_chrome_trace(t)
    events = doc["traceEvents"]
    assert events
    ts = [e["ts"] for e in events]
    assert all(b >= a for a, b in zip(ts, ts[1:])), "ts must be monotonic"
    complete = [e for e in events if e["ph"] == "X"]
    assert all("dur" in e and e["dur"] >= 0 for e in complete)
    assert any(e["args"].get("cache") == "miss" for e in complete)
    path = tmp_path / "trace.json"
    write_chrome_trace(t, str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_top_summary_and_schema():
    g, _, sink = _chain_graph()
    t = _installed()
    GraphExecutor(g, optimize=False).execute(sink).get()
    summary = t.span_summary()
    assert summary
    for row in summary.values():
        # the one shape shared with timing.snapshot / metrics "phases"
        assert {"seconds", "calls"} <= set(row)
    text = format_top_spans(t, n=3)
    assert "node." in text and "seconds" in text


# ---------------------------------------------------------------------------
# autocache audit
# ---------------------------------------------------------------------------


def _reused_dag():
    """leaf → a → b → (c, d): b is consumed twice, so greedy caches it."""
    g = Graph()
    g, leaf = g.add_node(
        DatasetOperator(Dataset(np.ones((4, 2), np.float32), batched=True)), []
    )
    g, a = g.add_node(_Scale(2.0), [leaf])
    g, b = g.add_node(_Scale(3.0), [a])
    g, c = g.add_node(_Scale(4.0), [b])
    g, d = g.add_node(_Scale(5.0), [b])
    g, s1 = g.add_sink(c)
    g, s2 = g.add_sink(d)
    return g, (a, b, c, d), (s1, s2)


def test_cache_audit_covers_every_cacher_annotated_node(caplog):
    from keystone_tpu.workflow.autocache import AutoCacheRule, Profile

    g, (a, b, c, d), (s1, s2) = _reused_dag()
    profiles = {
        a: Profile(ns=1e6, mem_bytes=100),
        b: Profile(ns=5e6, mem_bytes=200),  # expensive + reused → cached
        c: Profile(ns=1e3, mem_bytes=50),
        d: Profile(ns=1e3, mem_bytes=50),
    }
    t = _installed()
    g2, ann = AutoCacheRule("greedy", 10_000, profiles).apply(g, {})
    ex = GraphExecutor(g2, optimize=False)
    ex._annotations = ann
    ex.execute(s1).get()
    ex.execute(s2).get()

    rows = cache_audit(t)
    by_node = {r["node"]: r for r in rows}
    cachers = {
        str(g2.get_dependencies(n)[0].id)
        for n in g2.nodes
        if type(g2.get_operator(n)).__name__ == "Cacher"
    }
    assert cachers, "greedy must have inserted at least one Cacher"
    # the audit covers every Cacher-annotated node, with estimate AND
    # observation joined (the feedback loop the reference never closed)
    for node in cachers:
        row = by_node[node]
        assert row["cacher"] is True
        assert row["observed"] is True
        assert row["est_seconds"] > 0 and row["obs_seconds"] is not None
        assert row["est_bytes"] > 0 and row["obs_bytes"] is not None
    # every profiled node is audited, cached or not
    assert {str(n.id) for n in profiles} <= set(by_node)

    import logging

    with caplog.at_level(logging.INFO, logger="keystone_tpu.obs.audit"):
        assert log_cache_audit(t) == rows
    assert "autocache audit" in caplog.text


def test_observed_seconds_are_exclusive_of_children():
    """Lazy evaluation nests upstream spans inside downstream ones; the
    audit's observations must subtract child time or every downstream
    node reads as mis-estimated (inclusive-vs-exclusive mismatch)."""
    import time

    from keystone_tpu.obs.audit import observed_by_node

    t = trace_mod.Tracer()
    with t.span("node.parent", node_id="1", cache="miss"):
        with t.span("node.child", node_id="2", cache="miss"):
            time.sleep(0.05)
    obs = observed_by_node(t)
    assert obs["2"]["seconds"] >= 0.045
    assert obs["1"]["seconds"] < 0.04, "child time must not count twice"


def test_profiling_runs_do_not_pollute_the_trace():
    from keystone_tpu.workflow.autocache import profile_nodes

    g, _, _ = _reused_dag()
    t = _installed()
    profile_nodes(g, sample_sizes=(2,), full_size=4)
    assert t.spans() == [], "sampled-scale profiling pulls must be suspended"


# ---------------------------------------------------------------------------
# serving spans
# ---------------------------------------------------------------------------


def test_serving_microbatch_span_and_metrics_alignment():
    from keystone_tpu.serving.engine import ServingEngine

    fitted = (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="double")
        >> FunctionNode(batch_fn=lambda X: X.sum(axis=1), label="rowsum")
    ).fit()
    t = _installed()
    engine = ServingEngine(fitted, buckets=(4,), datum_shape=(2,))
    with engine:
        engine.predict(np.ones(2, np.float32), timeout=30.0)
    spans = [sp for sp in t.spans() if sp.name == "serve.microbatch"]
    assert spans and spans[0].attrs["bucket"] == 4
    snap = engine.metrics.snapshot()
    assert "serve.microbatch" in snap["spans"]
    # phases and spans share one {name: {seconds, calls, ...}} schema and
    # disjoint names, so they concatenate without collisions
    merged = {**snap["phases"], **snap["spans"]}
    assert len(merged) == len(snap["phases"]) + len(snap["spans"])
    for row in merged.values():
        assert {"seconds", "calls"} <= set(row)


def test_metrics_spans_empty_without_tracer():
    from keystone_tpu.serving.metrics import MetricsRegistry

    assert MetricsRegistry("t").snapshot()["spans"] == {}


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------


def test_cli_trace_flag_writes_chrome_trace(tmp_path, capsys):
    from keystone_tpu.__main__ import main

    path = tmp_path / "t.json"
    rc = main([
        "mnist", "--numFFTs", "2", "--blockSize", "512", "--lambda", "100",
        "--trace", str(path),
    ])
    assert rc == 0
    assert "TEST Error" in capsys.readouterr().out
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert events
    ts = [e["ts"] for e in events]
    assert all(b >= a for a, b in zip(ts, ts[1:]))
    node_events = [e for e in events if e.get("args", {}).get("node")]
    assert node_events, "expected per-DAG-node spans"
    assert any(e["args"].get("cache") for e in node_events)
    assert any(e["name"] == "pipeline.fit" for e in events)


def test_cli_alias_rejects_unknown_name():
    from keystone_tpu.__main__ import main

    with pytest.raises(SystemExit):
        main(["mnits"])
