"""Pipeline semantics (parity: workflow/PipelineSuite.scala — laziness,
fit-once state reuse, chaining, gather, FittedPipeline) plus the TPU-specific
whole-chain compilation."""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu import (
    Dataset,
    Estimator,
    FunctionNode,
    Identity,
    LabelEstimator,
    Pipeline,
    Transformer,
)


class Doubler(Transformer):
    def trace_batch(self, X):
        return X * 2


class AddOne(Transformer):
    def trace_batch(self, X):
        return X + 1


class Shift(Transformer):
    def __init__(self, mu):
        self.mu = mu

    def trace_batch(self, X):
        return X - self.mu


class CountingMeanCenter(Estimator):
    """Estimator counting fits, for fit-once semantics."""

    def __init__(self):
        self.num_fits = 0

    def fit(self, data):
        self.num_fits += 1
        return Shift(jnp.mean(data.to_array(), axis=0))


class CountingLinear(LabelEstimator):
    def __init__(self):
        self.num_fits = 0

    def fit(self, data, labels):
        self.num_fits += 1
        X = data.to_array()
        y = labels.to_array()
        w, *_ = jnp.linalg.lstsq(X, y, rcond=None)
        return FunctionNode(batch_fn=lambda A: A @ w, label="linmap")


def test_transformer_chain_lazy_and_correct():
    pipe = Doubler().and_then(AddOne())
    data = jnp.ones((4, 3))
    result = pipe(data)  # lazy
    out = result.get().to_array()
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_rshift_sugar():
    pipe = Doubler() >> AddOne() >> Doubler()
    out = pipe(jnp.ones((2, 2))).get().to_array()
    np.testing.assert_allclose(np.asarray(out), 6.0)


def test_apply_datum():
    pipe = Doubler().to_pipeline()
    out = pipe.apply_datum(jnp.asarray([1.0, 2.0])).get()
    np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])


def test_estimator_fit_once_across_applications():
    """Parity: PipelineSuite 'only fit once' (numFits === 1)."""
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
    pipe = est.with_data(data)
    assert est.num_fits == 0  # lazy
    out1 = pipe(data).get().to_array()
    assert est.num_fits == 1
    out2 = pipe(data).get().to_array()
    assert est.num_fits == 1  # saved state reused, not refit
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


def test_estimator_fit_once_shared_between_pipelines():
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
    pipe_a = est.with_data(data)
    pipe_b = est.with_data(data)
    pipe_a(data).get()
    pipe_b(data).get()
    assert est.num_fits == 1  # same estimator instance + same data => one fit


def test_chain_with_estimator_trains_on_chained_data():
    """and_then(est, raw_data): estimator must see raw data pushed through the
    upstream chain (parity: Chainable.scala estimator overloads)."""

    seen = {}

    class Probe(Estimator):
        def fit(self, data):
            seen["data"] = np.asarray(data.to_array())
            return Identity()

    raw = jnp.ones((2, 2))
    pipe = Doubler().and_then(Probe(), raw)
    pipe(raw).get()
    np.testing.assert_allclose(seen["data"], 2.0)


def test_label_estimator_pipeline():
    X = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0], [2.0, 1.0]])
    w_true = jnp.asarray([[1.0], [2.0]])
    y = X @ w_true
    est = CountingLinear()
    pipe = est.with_data(X, y)
    pred = pipe(X).get().to_array()
    np.testing.assert_allclose(np.asarray(pred), np.asarray(y), atol=1e-4)
    assert est.num_fits == 1


def test_gather():
    pipe = Pipeline.gather([Doubler(), AddOne()])
    out = pipe(jnp.ones((3, 2))).get()
    assert out.is_batched
    doubled, plus1 = out.payload
    np.testing.assert_allclose(np.asarray(doubled), 2.0)
    np.testing.assert_allclose(np.asarray(plus1), 2.0)


def test_fit_produces_estimator_free_pipeline():
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    pipe = Doubler().and_then(est, data)
    fitted = pipe.fit()
    assert est.num_fits == 1
    # applying the fitted pipeline does not refit
    out = fitted.apply(jnp.asarray([[1.0, 1.0]])).to_array()
    assert est.num_fits == 1
    # doubled to 2, mean of doubled train data is 2 => 0
    np.testing.assert_allclose(np.asarray(out), 0.0)
    single = fitted.apply_datum(jnp.asarray([1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(single), 0.0)


def test_fitted_pipeline_save_load(tmp_path):
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    fitted = Doubler().and_then(est, data).fit()
    path = str(tmp_path / "pipe.pkl")
    fitted.save(path)
    from keystone_tpu.workflow.pipeline import FittedPipeline

    loaded = FittedPipeline.load(path)
    out = loaded.apply(jnp.asarray([[1.0, 1.0]])).to_array()
    np.testing.assert_allclose(np.asarray(out), 0.0)


def test_fitted_pipeline_compiles_to_one_jaxpr():
    """The flagship TPU behavior: the whole andThen chain jits into a single
    XLA computation."""
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    fitted = (Doubler() >> AddOne()).and_then(est, data).fit()
    fn = fitted.trace_fn()
    assert fn is not None
    jitted = jax.jit(fn)
    out = jitted(jnp.asarray([[1.0, 1.0]]))
    expected = fitted.apply(jnp.asarray([[1.0, 1.0]])).to_array()
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected))
    # and it really is one traced computation
    jaxpr = jax.make_jaxpr(fn)(jnp.ones((1, 2)))
    assert jaxpr is not None


def test_common_subexpression_merged():
    """Two branches sharing the same upstream transformer instance execute it
    once (parity: EquivalentNodeMergeRule)."""
    calls = []

    def counting(X):
        calls.append(1)
        return X * 2

    shared = FunctionNode(batch_fn=counting, label="shared")
    pipe = Pipeline.gather([shared.to_pipeline(), shared.to_pipeline()])
    pipe(jnp.ones((2, 2))).get()
    assert len(calls) == 1


def test_apply_chunked_matches_apply_any_batch_size():
    """apply_chunked pads the tail chunk and slices it off: results match
    apply() exactly for sizes below, equal to, straddling, and far above
    the chunk size — all through ONE compiled executable."""
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    fitted = (Doubler() >> AddOne()).and_then(est, data).fit()
    rng = np.random.default_rng(0)
    for n in (1, 3, 4, 7, 13):
        X = jnp.asarray(rng.standard_normal((n, 2)), dtype=jnp.float32)
        want = np.asarray(fitted.apply(X).to_array())
        got = np.asarray(fitted.apply_chunked(X, chunk_size=4).to_array())
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert got.shape[0] == n


def test_apply_chunked_empty_input_matches_apply():
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    fitted = Doubler().and_then(est, data).fit()
    empty = jnp.zeros((0, 2), dtype=jnp.float32)
    got = np.asarray(fitted.apply_chunked(empty, chunk_size=4).to_array())
    want = np.asarray(fitted.apply(empty).to_array())
    assert got.shape == want.shape == (0, 2)


def test_apply_chunked_rejects_batch_coupled_chain():
    """A transformer declaring batch_coupled=True (its output depends on
    batch statistics) must be refused by apply_chunked — the padded tail
    chunk would silently corrupt those statistics (ADVICE r4)."""
    import pytest

    class BatchZScore(Transformer):
        batch_coupled = True

        def trace_batch(self, X):
            return (X - X.mean(axis=0)) / (X.std(axis=0) + 1e-6)

    fitted = (Doubler() >> BatchZScore()).to_pipeline().fit()
    X = jnp.asarray(np.random.default_rng(0).standard_normal((5, 2)),
                    dtype=jnp.float32)
    with pytest.raises(ValueError, match="batch-coupled"):
        fitted.apply_chunked(X, chunk_size=4)
    # apply() still serves it
    out = np.asarray(fitted.apply(X).to_array())
    assert out.shape == (5, 2)


def test_apply_chunked_host_input_double_buffered_matches_device():
    """The host-resident (numpy) input path double-buffers uploads
    (VERDICT r4 #4) — results must be identical to the device-resident
    path and to apply(), including the padded tail chunk."""
    est = CountingMeanCenter()
    data = Dataset.from_array(jnp.asarray([[0.0, 0.0], [2.0, 2.0]]))
    fitted = (Doubler() >> AddOne()).and_then(est, data).fit()
    X_host = np.random.default_rng(3).standard_normal((11, 2)).astype(np.float32)
    want = np.asarray(fitted.apply(jnp.asarray(X_host)).to_array())
    got_host = np.asarray(
        fitted.apply_chunked(X_host, chunk_size=4).to_array()
    )
    got_dev = np.asarray(
        fitted.apply_chunked(jnp.asarray(X_host), chunk_size=4).to_array()
    )
    np.testing.assert_allclose(got_host, want, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_dev, want, rtol=1e-5, atol=1e-6)
