"""Concurrent DAG executor (workflow/executor.py): dependency-scheduled
branch parallelism. Covers parallel-vs-serial output equality on the real
gather pipelines (mnist_random_fft, timit featurizers), exactly-once diamond
computation under contention, branch-exception propagation with sibling
cancellation, the ``KEYSTONE_PAR_EXEC=0`` kill switch, and queue-wait /
worker span attribution with explicit cross-thread parent linking."""

import threading
import time
import traceback

import numpy as np
import pytest

from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.obs import tracer as trace_mod
from keystone_tpu.pipelines.mnist_random_fft import (
    MnistRandomFFTConfig,
    build_featurizer as build_mnist_featurizer,
)
from keystone_tpu.pipelines.timit import (
    TimitConfig,
    build_featurizer as build_timit_featurizer,
)
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.pipeline import Pipeline
from keystone_tpu.workflow.transformer import FunctionNode


def _run(pipeline_factory, data, monkeypatch, parallel, workers=2):
    """Apply a freshly-built pipeline with the executor mode pinned.

    A fresh build per run (plus a PipelineEnv reset) keeps the two modes
    honest: saved-state prefixes from the first application must not hand
    the second one precomputed results."""
    PipelineEnv.get_or_create().reset()
    monkeypatch.setenv("KEYSTONE_PAR_EXEC", "1" if parallel else "0")
    monkeypatch.setenv("KEYSTONE_EXEC_WORKERS", str(workers))
    out = pipeline_factory().apply(data).get()
    return np.asarray(out.to_array())


# ---------------------------------------------------------------------------
# parallel-vs-serial equality on the real gather pipelines
# ---------------------------------------------------------------------------


def test_mnist_random_fft_gather_parallel_matches_serial(monkeypatch):
    conf = MnistRandomFFTConfig(num_ffts=4, seed=3)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 784)).astype(np.float32)
    serial = _run(lambda: build_mnist_featurizer(conf), X, monkeypatch, False)
    parallel = _run(lambda: build_mnist_featurizer(conf), X, monkeypatch, True)
    assert serial.shape[0] == 16 and serial.shape[1] % 4 == 0
    np.testing.assert_array_equal(serial, parallel)


def test_timit_gather_parallel_matches_serial(monkeypatch):
    conf = TimitConfig(num_cosines=3, input_dim=64, cosine_features=32)
    rng = np.random.default_rng(1)
    X = rng.standard_normal((12, 64)).astype(np.float32)
    serial = _run(lambda: build_timit_featurizer(conf), X, monkeypatch, False)
    parallel = _run(lambda: build_timit_featurizer(conf), X, monkeypatch, True)
    assert serial.shape == (12, 3 * 32)
    np.testing.assert_array_equal(serial, parallel)


# ---------------------------------------------------------------------------
# host-bound branches genuinely overlap
# ---------------------------------------------------------------------------


def _host_branch(label, record=None, stall=0.0, boom=False):
    """An UNTRACEABLE per-item branch (no trace_batch): fusion cannot
    collapse it, so it stays a distinct DAG node forced on the pool."""

    def feat(x):
        if record is not None:
            record.append((label, threading.current_thread().name))
        if boom:
            raise RuntimeError(f"boom in {label}")
        if stall:
            time.sleep(stall)
        return np.asarray(x) * 2.0

    return FunctionNode(item_fn=feat, label=label)


def test_host_branches_use_multiple_workers(monkeypatch):
    record = []
    X = np.ones((3, 4), np.float32)
    out = _run(
        lambda: Pipeline.gather(
            [_host_branch(f"b{i}", record, stall=0.02) for i in range(4)]
        ),
        X,
        monkeypatch,
        parallel=True,
        workers=2,
    )
    threads = {t for _, t in record}
    assert len(threads) >= 2, threads
    assert all(t.startswith("keystone-exec") for t in threads), threads
    serial = _run(
        lambda: Pipeline.gather(
            [_host_branch(f"b{i}", stall=0.0) for i in range(4)]
        ),
        X,
        monkeypatch,
        parallel=False,
    )
    np.testing.assert_array_equal(np.asarray(out), serial)


def test_kill_switch_keeps_everything_on_the_calling_thread(monkeypatch):
    record = []
    X = np.ones((3, 4), np.float32)
    _run(
        lambda: Pipeline.gather(
            [_host_branch(f"b{i}", record) for i in range(4)]
        ),
        X,
        monkeypatch,
        parallel=False,
    )
    threads = {t for _, t in record}
    assert threads == {threading.current_thread().name}


# ---------------------------------------------------------------------------
# diamonds compute exactly once under contention
# ---------------------------------------------------------------------------


def test_diamond_computes_exactly_once_under_contention(monkeypatch):
    calls = []
    lock = threading.Lock()

    def shared_fn(x):
        with lock:
            calls.append(threading.current_thread().name)
        time.sleep(0.01)
        return np.asarray(x) + 1.0

    # ONE shared instance fanned into every branch: CSE merges the four
    # structurally-identical nodes into a diamond apex whose expression
    # all branches race to force
    shared = FunctionNode(item_fn=shared_fn, label="shared")
    n_items = 3
    X = np.ones((n_items, 4), np.float32)
    out = _run(
        lambda: Pipeline.gather(
            [
                shared.and_then(_host_branch(f"b{i}", stall=0.01))
                for i in range(4)
            ]
        ),
        X,
        monkeypatch,
        parallel=True,
        workers=4,
    )
    # once per item of ONE pass — a re-computed diamond would double this
    assert len(calls) == n_items, calls
    # gather payload: one (n_items, 4) array per branch, all (x+1)*2 = 4
    np.testing.assert_array_equal(
        np.asarray(out), np.full((4, n_items, 4), 4.0, np.float32)
    )


# ---------------------------------------------------------------------------
# failure semantics
# ---------------------------------------------------------------------------


def test_branch_exception_propagates_and_cancels_unstarted_siblings(
    monkeypatch,
):
    record = []
    X = np.ones((2, 4), np.float32)

    def build():
        # boom is branch 0 — first in topological submission order; with
        # one worker it fails before any sibling is submitted
        return Pipeline.gather(
            [_host_branch("boom", boom=True)]
            + [_host_branch(f"b{i}", record) for i in range(1, 4)]
        )

    with pytest.raises(RuntimeError, match="boom in boom") as excinfo:
        _run(build, X, monkeypatch, parallel=True, workers=1)
    # original traceback survives the scheduler hop: the raising frame
    # (the branch's item fn) is visible to the caller
    frames = [
        f.name for f in traceback.extract_tb(excinfo.value.__traceback__)
    ]
    assert "feat" in frames, frames
    assert record == [], f"cancelled siblings still ran: {record}"


def test_branch_exception_propagates_with_concurrent_siblings(monkeypatch):
    # with a wide pool the failure must still surface (siblings may run)
    X = np.ones((2, 4), np.float32)

    def build():
        return Pipeline.gather(
            [_host_branch(f"b{i}", stall=0.01) for i in range(3)]
            + [_host_branch("boom", boom=True)]
        )

    with pytest.raises(RuntimeError, match="boom"):
        _run(build, X, monkeypatch, parallel=True, workers=4)


# ---------------------------------------------------------------------------
# span attribution: queue wait, worker identity, cross-thread parenting
# ---------------------------------------------------------------------------


def test_scheduled_node_spans_carry_queue_wait_and_nest_under_pull(
    monkeypatch,
):
    trace_mod.reset()
    tracer = trace_mod.install(trace_mod.Tracer())
    try:
        X = np.ones((3, 4), np.float32)
        _run(
            lambda: Pipeline.gather(
                [_host_branch(f"b{i}", stall=0.01) for i in range(4)]
            ),
            X,
            monkeypatch,
            parallel=True,
            workers=2,
        )
        spans = tracer.spans()
        by_id = {sp.span_id: sp for sp in spans}
        # well-formed tree: every parent id resolves
        assert all(
            sp.parent_id is None or sp.parent_id in by_id for sp in spans
        )
        pull = [sp for sp in spans if sp.name == "pipeline.pull"]
        assert len(pull) == 1
        scheduled = [
            sp for sp in spans if sp.attrs.get("worker") is not None
        ]
        assert len(scheduled) >= 2, [sp.name for sp in spans]
        for sp in scheduled:
            assert sp.attrs["queue_wait_seconds"] >= 0.0
            assert sp.attrs["worker"].startswith("keystone-exec")
            # cross-thread parent linking: the worker's node span nests
            # under the pull root opened on the caller thread
            assert sp.parent_id == pull[0].span_id
            assert sp.tid != pull[0].tid
    finally:
        trace_mod.reset()


def test_serial_pull_has_no_scheduler_attrs(monkeypatch):
    trace_mod.reset()
    tracer = trace_mod.install(trace_mod.Tracer())
    try:
        X = np.ones((3, 4), np.float32)
        _run(
            lambda: Pipeline.gather(
                [_host_branch(f"b{i}") for i in range(2)]
            ),
            X,
            monkeypatch,
            parallel=False,
        )
        assert all(
            sp.attrs.get("worker") is None for sp in tracer.spans()
        )
    finally:
        trace_mod.reset()


# ---------------------------------------------------------------------------
# Dataset.take (the sampling path the optimizer's profiling pulls use)
# ---------------------------------------------------------------------------


def test_take_batched_slices_without_unstacking():
    ds = Dataset.of(np.arange(40, dtype=np.float32).reshape(10, 4))
    t = ds.take(3)
    assert t.is_batched and len(t) == 3
    np.testing.assert_array_equal(
        np.asarray(t.payload), np.arange(12, dtype=np.float32).reshape(3, 4)
    )


def test_take_items_slices_the_list():
    ds = Dataset.from_items(["a", "b", "c", "d"])
    assert ds.take(2).collect() == ["a", "b"]
    assert ds.take(0).collect() == []


def test_chunked_take_peeks_only_leading_chunks():
    produced = []

    def factory():
        for i in range(5):
            produced.append(i)
            yield np.full((4, 2), float(i), np.float32)

    ds = ChunkedDataset(factory, 20)
    t = ds.take(6)  # 4 + 2 rows -> exactly two chunks produced
    assert len(t) == 6
    assert produced == [0, 1], produced
    np.testing.assert_array_equal(
        np.asarray(t.payload)[:, 0], [0, 0, 0, 0, 1, 1]
    )
    produced.clear()
    assert float(np.asarray(ds.first())[0]) == 0.0
    assert produced == [0], produced


def test_chunked_take_and_first_empty_parity():
    empty = ChunkedDataset(lambda: iter(()), 0)
    # parity with Dataset.take on an empty payload: empty dataset, no raise
    assert len(empty.take(0)) == 0
    assert len(empty.take(5)) == 0
    with pytest.raises(IndexError):
        empty.first()
