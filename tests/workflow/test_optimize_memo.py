"""Construction-time prefix optimization is memoized (ISSUE 12 satellite).

Before this change, ``and_then(estimator, data)`` spliced the LAZY
result's graph via ``PipelineResult.graph``, which forced the executor's
optimize — re-running the full rule stack on the growing prefix subgraph
at every composition step (L runs for an L-stage chain). Composition now
splices the raw graph (zero rule-stack runs until fit/get), and
``Optimizer.execute`` memoizes by graph fingerprint + operator identity
so repeated optimizations of the same graph (re-applied pipelines,
rebuilt sweeps) run the stack once.
"""

import numpy as np
import pytest

from keystone_tpu.workflow import optimizers as opt_mod
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.rules import RuleExecutor
from keystone_tpu.workflow.transformer import Estimator, FunctionNode


class _CenterEstimator(Estimator):
    def fit(self, data):
        m = np.mean(np.asarray(data.to_array()))
        return FunctionNode(batch_fn=lambda X, m=m: X - m, label="center")


@pytest.fixture
def rule_stack_runs(monkeypatch):
    """Count REAL rule-stack executions (memo hits don't reach this)."""
    calls = []
    orig = RuleExecutor.execute

    def spy(self, graph, annotations=None):
        calls.append(len(graph.nodes))
        return orig(self, graph, annotations)

    monkeypatch.setattr(RuleExecutor, "execute", spy)
    return calls


def _chain(X, stages=4):
    p = FunctionNode(batch_fn=lambda X: X * 2.0, label="f0").to_pipeline()
    for _ in range(stages):
        p = p.and_then(_CenterEstimator(), X)
    return p


def test_composition_runs_zero_rule_stacks(rule_stack_runs):
    X = np.random.RandomState(0).randn(16, 3).astype(np.float32)
    _chain(X, stages=4)
    assert rule_stack_runs == [], (
        "and_then composition must not run the optimizer; "
        f"saw runs over graphs of sizes {rule_stack_runs}"
    )


def test_fit_optimizes_once_and_matches_eager_semantics(rule_stack_runs):
    X = np.random.RandomState(1).randn(16, 3).astype(np.float32)
    p = _chain(X, stages=3)
    fitted = p.fit()
    # fit runs the stack: once for the pipeline graph itself, plus the
    # estimator-data pulls inside fit run optimize=False (not counted)
    assert len(rule_stack_runs) == 1
    out = np.asarray(fitted.apply(X).to_array())
    # 3x centering after doubling: centered data has zero mean each step
    expect = X * 2.0
    for _ in range(3):
        expect = expect - expect.mean()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_repeated_optimize_of_same_graph_hits_memo(rule_stack_runs):
    X = np.random.RandomState(2).randn(8, 3).astype(np.float32)
    p = (
        FunctionNode(batch_fn=lambda X: X + 1.0, label="g0")
        .to_pipeline()
        .and_then(FunctionNode(batch_fn=lambda X: X * 3.0, label="g1"))
    )
    a = np.asarray(p.apply(X).get().to_array())
    runs_after_first = len(rule_stack_runs)
    assert runs_after_first >= 1
    b = np.asarray(p.apply(X).get().to_array())
    assert len(rule_stack_runs) == runs_after_first, (
        "second apply of the same pipeline over the same data must be "
        "a memo hit"
    )
    np.testing.assert_array_equal(a, b)
    assert opt_mod.memo_stats["hits"] >= 1


def test_state_mutation_invalidates_memo(rule_stack_runs):
    X = np.random.RandomState(3).randn(8, 3).astype(np.float32)
    p = (
        FunctionNode(batch_fn=lambda X: X - 1.0, label="h0")
        .to_pipeline()
        .and_then(FunctionNode(batch_fn=lambda X: X * 0.5, label="h1"))
    )
    p.apply(X).get()
    runs = len(rule_stack_runs)
    # a saved-state mutation (fit persisting a prefix, a test reset)
    # must invalidate the plan: SavedStateLoadRule bakes state into it
    PipelineEnv.get_or_create().state.clear()
    p.apply(X).get()
    assert len(rule_stack_runs) == runs + 1


def test_distinct_estimator_instances_do_not_share_plans(rule_stack_runs):
    X = np.random.RandomState(4).randn(8, 3).astype(np.float32)
    head = FunctionNode(batch_fn=lambda X: X * 2.0, label="k0").to_pipeline()
    a = head.and_then(_CenterEstimator(), X)
    b = head.and_then(_CenterEstimator(), X)
    fa, fb = a.fit(), b.fit()
    # both must fit their OWN estimator instance (identity-keyed plans)
    np.testing.assert_allclose(
        np.asarray(fa.apply(X).to_array()),
        np.asarray(fb.apply(X).to_array()),
        rtol=1e-6, atol=1e-6,
    )


def test_memo_kill_switch(rule_stack_runs, monkeypatch):
    monkeypatch.setenv("KEYSTONE_OPT_MEMO", "0")
    X = np.random.RandomState(5).randn(8, 3).astype(np.float32)
    p = (
        FunctionNode(batch_fn=lambda X: X + 2.0, label="m0")
        .to_pipeline()
        .and_then(FunctionNode(batch_fn=lambda X: X * 2.0, label="m1"))
    )
    p.apply(X).get()
    runs = len(rule_stack_runs)
    p.apply(X).get()
    assert len(rule_stack_runs) == runs + 1
