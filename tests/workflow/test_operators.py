"""Operator semantics (parity: workflow/OperatorSuite.scala)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.workflow.expressions import (
    DatasetExpression,
    DatumExpression,
    TransformerExpression,
)
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    ExpressionOperator,
    GatherTransformerOperator,
)
from keystone_tpu.workflow.transformer import FunctionNode


def test_dataset_operator():
    ds = Dataset.from_array(jnp.arange(6).reshape(3, 2))
    op = DatasetOperator(ds)
    out = op.execute([])
    assert isinstance(out, DatasetExpression)
    assert len(out.get()) == 3
    with pytest.raises(ValueError):
        op.execute([DatumExpression.now(1)])


def test_datum_operator():
    op = DatumOperator(42)
    assert op.execute([]).get() == 42


def test_function_transformer_batch_and_single():
    t = FunctionNode(batch_fn=lambda X: X * 2)
    ds_expr = DatasetExpression.now(Dataset.from_array(jnp.ones((4, 3))))
    out = t.execute([ds_expr])
    assert isinstance(out, DatasetExpression)
    np.testing.assert_allclose(np.asarray(out.get().to_array()), 2.0)

    datum_expr = DatumExpression.now(jnp.ones(3))
    single = t.execute([datum_expr])
    assert isinstance(single, DatumExpression)
    np.testing.assert_allclose(np.asarray(single.get()), 2.0)


def test_transformer_laziness():
    calls = []

    def f(X):
        calls.append(1)
        return X

    t = FunctionNode(batch_fn=f)
    expr = t.execute([DatasetExpression.now(Dataset.from_array(jnp.ones((2, 2))))])
    assert calls == []  # nothing ran yet
    expr.get()
    expr.get()
    assert calls == [1]  # memoized


def test_estimator_operator_laziness_and_memoization():
    fits = []

    class MeanShift(EstimatorOperator):
        def fit(self, data):
            fits.append(1)
            mu = jnp.mean(data.to_array(), axis=0)
            return FunctionNode(batch_fn=lambda X: X - mu)

    est = MeanShift()
    data = DatasetExpression.now(Dataset.from_array(jnp.asarray([[1.0, 3.0], [3.0, 5.0]])))
    texpr = est.execute([data])
    assert isinstance(texpr, TransformerExpression)
    assert fits == []
    fitted = texpr.get()
    assert fits == [1]
    texpr.get()
    assert fits == [1]
    out = fitted.execute([data]).get().to_array()
    np.testing.assert_allclose(np.asarray(out), [[-1.0, -1.0], [1.0, 1.0]])


def test_delegating_operator():
    t = FunctionNode(batch_fn=lambda X: X + 1)
    texpr = TransformerExpression.now(t)
    data = DatasetExpression.now(Dataset.from_array(jnp.zeros((2, 2))))
    out = DelegatingOperator().execute([texpr, data])
    np.testing.assert_allclose(np.asarray(out.get().to_array()), 1.0)

    datum = DatumExpression.now(jnp.zeros(2))
    out_single = DelegatingOperator().execute([texpr, datum])
    np.testing.assert_allclose(np.asarray(out_single.get()), 1.0)

    with pytest.raises(ValueError):
        DelegatingOperator().execute([data])
    with pytest.raises(ValueError):
        DelegatingOperator().execute([data, data])


def test_expression_operator_passthrough():
    e = DatumExpression.now(7)
    assert ExpressionOperator(e).execute([]) is e


def test_gather_zip_batched():
    a = DatasetExpression.now(Dataset.from_array(jnp.ones((3, 2))))
    b = DatasetExpression.now(Dataset.from_array(jnp.zeros((3, 4))))
    out = GatherTransformerOperator().execute([a, b]).get()
    assert out.is_batched
    pa, pb = out.payload
    assert pa.shape == (3, 2) and pb.shape == (3, 4)


def test_gather_single():
    a = DatumExpression.now(1)
    b = DatumExpression.now(2)
    out = GatherTransformerOperator().execute([a, b]).get()
    assert out == [1, 2]
