"""Observability configuration (utils/obs.py + CLI --logLevel/--profile)."""

import logging
import threading

from keystone_tpu.utils import obs, timing


def test_configure_sets_level_and_format(capsys):
    obs.configure("info")
    logging.getLogger("keystone_tpu.test").info("hello obs")
    err = capsys.readouterr().err
    assert "hello obs" in err
    assert "keystone_tpu.test" in err
    obs.configure("warning")
    logging.getLogger("keystone_tpu.test").info("hidden")
    assert "hidden" not in capsys.readouterr().err


def test_configure_rejects_unknown_level():
    import pytest

    with pytest.raises(ValueError):
        obs.configure("loud")


def test_profile_enables_phase_logs(capsys):
    obs.configure("warning", profile=True)
    try:
        timing.reset()
        with timing.phase("obs.test_phase"):
            pass
        snap = timing.snapshot()
        assert "obs.test_phase" in snap
        assert "obs.test_phase" in capsys.readouterr().err
    finally:
        obs.configure("warning", profile=False)


def test_profile_env_parsing(monkeypatch):
    for raw, want in [("1", True), ("true", True), ("0", False),
                      ("false", False), ("", False), ("off", False)]:
        monkeypatch.setenv("KEYSTONE_PROFILE", raw)
        obs.configure("warning", profile=None)
        assert timing._profiling is want, (raw, want)
    monkeypatch.delenv("KEYSTONE_PROFILE")
    obs.configure("warning", profile=False)


def test_bad_env_level_falls_back(monkeypatch, capsys):
    monkeypatch.setenv("KEYSTONE_LOG", "trace")
    obs.configure(None)  # must not raise
    import logging

    assert logging.getLogger().level == logging.WARNING


def test_configure_is_idempotent_one_handler():
    """Repeated configure() must re-level, not stack stream handlers
    (stacked handlers double every log line)."""
    obs.configure("info")
    root = logging.getLogger()
    n_handlers = len(root.handlers)
    obs.configure("debug")
    obs.configure("warning")
    assert len(root.handlers) == n_handlers
    assert root.level == logging.WARNING


def test_every_under_concurrent_callers():
    """N threads racing one key: exactly one winner per window."""
    key = "test.concurrent.every"
    obs.reset_rate_limits()
    results = []
    barrier = threading.Barrier(8)

    def hit():
        barrier.wait(timeout=5)
        results.append(obs.every(key, 60.0))

    threads = [threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1


def test_timing_reset_clears_rate_limits():
    """Back-to-back bench runs in one process: timing.reset() must give
    the new run its FIRST periodic log instead of inheriting the old
    run's suppression window."""
    key = "test.reset.every"
    assert obs.every(key, 3600.0) is True
    assert obs.every(key, 3600.0) is False  # suppressed within the window
    timing.reset()
    assert obs.every(key, 3600.0) is True  # fresh epoch logs immediately


def test_phase_holder_sync_path():
    """A value appended to the yielded holder is what the phase blocks on
    at exit (the async-dispatch attribution contract)."""
    import jax.numpy as jnp

    obs.configure("warning", profile=True)
    try:
        timing.reset()
        with timing.phase("obs.holder_sync") as holder:
            holder.append(jnp.ones((4,)) * 2.0)
        snap = timing.snapshot()
        assert snap["obs.holder_sync"]["calls"] == 1
        assert snap["obs.holder_sync"]["seconds"] >= 0.0
    finally:
        obs.configure("warning", profile=False)


def test_phase_sync_failure_is_logged_not_swallowed(caplog):
    """A REAL device error during the phase-exit sync must surface at
    WARNING (the bare-except that ate stream failures is gone) while the
    phase still records; non-blockable values stay silent."""

    class _Boom:
        def block_until_ready(self):
            raise RuntimeError("sync exploded")

    obs.configure("warning", profile=True)
    try:
        timing.reset()
        with caplog.at_level(logging.WARNING, logger="keystone_tpu.utils.timing"):
            with timing.phase("obs.sync_fail", sync=_Boom()):
                pass
        assert "device sync failed" in caplog.text
        assert timing.snapshot()["obs.sync_fail"]["calls"] == 1

        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="keystone_tpu.utils.timing"):
            with timing.phase("obs.sync_plain", sync=object()):
                pass  # plain objects pass through jax untouched — no noise
        assert "device sync failed" not in caplog.text
    finally:
        obs.configure("warning", profile=False)
