"""Observability configuration (utils/obs.py + CLI --logLevel/--profile)."""

import logging

from keystone_tpu.utils import obs, timing


def test_configure_sets_level_and_format(capsys):
    obs.configure("info")
    logging.getLogger("keystone_tpu.test").info("hello obs")
    err = capsys.readouterr().err
    assert "hello obs" in err
    assert "keystone_tpu.test" in err
    obs.configure("warning")
    logging.getLogger("keystone_tpu.test").info("hidden")
    assert "hidden" not in capsys.readouterr().err


def test_configure_rejects_unknown_level():
    import pytest

    with pytest.raises(ValueError):
        obs.configure("loud")


def test_profile_enables_phase_logs(capsys):
    obs.configure("warning", profile=True)
    try:
        timing.reset()
        with timing.phase("obs.test_phase"):
            pass
        snap = timing.snapshot()
        assert "obs.test_phase" in snap
        assert "obs.test_phase" in capsys.readouterr().err
    finally:
        obs.configure("warning", profile=False)


def test_profile_env_parsing(monkeypatch):
    for raw, want in [("1", True), ("true", True), ("0", False),
                      ("false", False), ("", False), ("off", False)]:
        monkeypatch.setenv("KEYSTONE_PROFILE", raw)
        obs.configure("warning", profile=None)
        assert timing._profiling is want, (raw, want)
    monkeypatch.delenv("KEYSTONE_PROFILE")
    obs.configure("warning", profile=False)


def test_bad_env_level_falls_back(monkeypatch, capsys):
    monkeypatch.setenv("KEYSTONE_LOG", "trace")
    obs.configure(None)  # must not raise
    import logging

    assert logging.getLogger().level == logging.WARNING
