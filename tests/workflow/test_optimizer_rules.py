"""Optimizer subsystem tests against graph structure, mirroring
NodeOptimizationRuleSuite.scala:12-75 (sampled-execution operator choice)
and AutoCacheRuleSuite.scala:28-188 (hand-built DAG + synthetic profiles,
greedy budget sweep, aggressive policy, and recompute-vs-retain behavior)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import (
    ColumnPCAEstimator,
    LeastSquaresEstimator,
)
from keystone_tpu.nodes.util.core import Cacher
from keystone_tpu.workflow.autocache import (
    AutoCacheRule,
    Profile,
    estimate_runs,
    insert_cachers,
    profile_nodes,
)
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.executor import GraphExecutor
from keystone_tpu.workflow.graph import Graph, NodeId
from keystone_tpu.workflow.operators import EstimatorOperator
from keystone_tpu.workflow.optimizers import AutoCachingOptimizer
from keystone_tpu.workflow.transformer import Transformer


# ---- NodeOptimizationRule -------------------------------------------------

def _estimator_ops(graph):
    return [
        graph.get_operator(n)
        for n in graph.nodes
        if isinstance(graph.get_operator(n), EstimatorOperator)
    ]


def test_node_optimization_swaps_least_squares_solver():
    rng = np.random.default_rng(0)
    n, d, k = 512, 16, 4
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    auto = LeastSquaresEstimator(lam=1e-2)
    pipe = auto.with_data(Dataset.of(X), Dataset.of(Y))
    executor = GraphExecutor(pipe.graph)
    optimized = executor.graph  # triggers the rule stack
    est_ops = [
        op for op in _estimator_ops(optimized)
        if not isinstance(op, type(None))
    ]
    # the auto-solver node must have been replaced by a concrete solver
    assert not any(isinstance(op, LeastSquaresEstimator) for op in est_ops), \
        "NodeOptimizationRule did not fire"
    # and the replacement must be what the cost model picks at FULL n —
    # not at the 24-item sample size (the ADVICE regression)
    expected = auto.optimize(Dataset.of(X[:24]), Dataset.of(Y[:24]), total_n=n)
    assert any(type(op) is type(expected) for op in est_ops)


def test_node_optimization_uses_full_dataset_size():
    """Selection from a 24-row sample must match selection at full n — a
    small-n-regime solver choice would betray unscaled sample sizing."""
    rng = np.random.default_rng(1)
    auto = LeastSquaresEstimator(lam=1e-2)
    n_small, n_large = 32, 4096
    d, k = 8, 2
    X = rng.standard_normal((n_large, d)).astype(np.float32)
    Y = rng.standard_normal((n_large, k)).astype(np.float32)
    sample = (Dataset.of(X[:24]), Dataset.of(Y[:24]))
    small = auto.optimize(*sample, total_n=n_small)
    large = auto.optimize(*sample, total_n=n_large)
    # the decision is a function of the *claimed* n, proving the hint is used
    cost_small = [
        s.cost(n_small, d, k, 1.0, 8, auto.cpu_weight, auto.mem_weight,
               auto.network_weight) for s in auto.options
    ]
    cost_large = [
        s.cost(n_large, d, k, 1.0, 8, auto.cpu_weight, auto.mem_weight,
               auto.network_weight) for s in auto.options
    ]
    assert type(small) is type(auto.options[int(np.argmin(cost_small))])
    assert type(large) is type(auto.options[int(np.argmin(cost_large))])


def test_column_pca_estimator_sample_optimize_scales():
    est = ColumnPCAEstimator(4)
    sample = Dataset.of(
        np.random.default_rng(0).standard_normal((6, 16, 20)).astype(np.float32)
    )
    chosen_small = est.sample_optimize([sample], num_items=6)
    chosen_big = est.sample_optimize([sample], num_items=200_000)
    assert chosen_small in (est.local, est.distributed)
    assert chosen_big in (est.local, est.distributed)


# ---- AutoCacheRule: selection against hand-built DAGs ---------------------

class _T(Transformer):
    def __init__(self, tag):
        self.tag = tag

    def apply(self, x):
        return x


def _diamond_graph():
    """source → a → b → (c, d) → sink(c), sink(d): b is reused twice."""
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(_T("a"), [src])
    g, b = g.add_node(_T("b"), [a])
    g, c = g.add_node(_T("c"), [b])
    g, d = g.add_node(_T("d"), [b])
    g, s1 = g.add_sink(c)
    g, s2 = g.add_sink(d)
    return g, (a, b, c, d)


def _cacher_parents(graph):
    return {
        graph.get_dependencies(n)[0]
        for n in graph.nodes
        if isinstance(graph.get_operator(n), Cacher)
    }


def test_autocache_greedy_budget_sweep():
    g, (a, b, c, d) = _diamond_graph()
    profiles = {
        a: Profile(ns=1e6, mem_bytes=100),
        b: Profile(ns=5e6, mem_bytes=200),  # expensive + reused → best
        c: Profile(ns=1e3, mem_bytes=50),
        d: Profile(ns=1e3, mem_bytes=50),
    }
    # budget below the cheapest profile: nothing cached
    g0, _ = AutoCacheRule("greedy", 10, profiles).apply(g, {})
    assert _cacher_parents(g0) == set()
    # budget for exactly one: the reused expensive node wins
    g1, _ = AutoCacheRule("greedy", 250, profiles).apply(g, {})
    assert b in _cacher_parents(g1)
    # big budget: still only b — once b is cached, a runs once anyway, so
    # caching it saves nothing (greedy stops at zero marginal save)
    g2, _ = AutoCacheRule("greedy", 10_000, profiles).apply(g, {})
    assert _cacher_parents(g2) == {b}


def test_autocache_aggressive_caches_reused_nodes():
    g, (a, b, c, d) = _diamond_graph()
    g2, ann = AutoCacheRule("aggressive").apply(g, {})
    assert _cacher_parents(g2) == {b}  # only b has >1 children
    from keystone_tpu.workflow.autocache import AUTOCACHE_ACTIVE

    assert ann[AUTOCACHE_ACTIVE] is True


def test_insert_cachers_reroutes_consumers():
    g, (a, b, c, d) = _diamond_graph()
    g2 = insert_cachers(g, [b])
    cachers = [
        n for n in g2.nodes if isinstance(g2.get_operator(n), Cacher)
    ]
    assert len(cachers) == 1
    (cacher,) = cachers
    assert g2.get_dependencies(c) == (cacher,)
    assert g2.get_dependencies(d) == (cacher,)
    # double insertion is idempotent
    g3 = insert_cachers(g2, [b])
    assert len([
        n for n in g3.nodes if isinstance(g3.get_operator(n), Cacher)
    ]) == 1


def test_estimate_runs_respects_weights_and_cuts():
    g, (a, b, c, d) = _diamond_graph()
    runs = estimate_runs(g, {}, cached=set())
    assert runs[b] == 2  # two consumers
    assert runs[a] == 2  # flows through b
    runs_cut = estimate_runs(g, {}, cached={b})
    assert runs_cut[a] == 1  # cached b cuts the downstream fan-out
    # weighted consumer multiplies upstream runs (passes-over-input)
    runs_w = estimate_runs(g, {c: 3}, cached=set())
    assert runs_w[b] == 1 * 3 + 1


# ---- end-to-end: retention policy makes the budget real -------------------

class CountingNode(Transformer):
    count = 0

    def apply(self, x):
        CountingNode.count += 1
        return x

    def apply_batch(self, data):
        CountingNode.count += 1
        return Dataset.of(data)


def _counting_pipeline():
    CountingNode.count = 0
    return CountingNode().to_pipeline()


def test_budget_zero_recomputes_across_pulls():
    env = PipelineEnv.get_or_create()
    env.set_optimizer(AutoCachingOptimizer("greedy", mem_budget_bytes=0))
    try:
        pipe = _counting_pipeline()
        X = np.ones((4, 3), dtype=np.float32)
        executor = GraphExecutor(pipe.graph)
        sink = pipe.graph  # noqa: F841
        # two pulls through the same executor via the pipeline API
        r1 = pipe(X).get()
        r2 = pipe(X).get()
        # profiling runs the node a few times too; the salient check is that
        # the second pull recomputed (count grew between pulls)
        assert CountingNode.count >= 2
    finally:
        env.reset()


def test_cached_node_computes_once_across_pulls():
    env = PipelineEnv.get_or_create()
    env.set_optimizer(AutoCachingOptimizer("aggressive"))
    try:
        # diamond: counting node feeds two branches gathered together
        from keystone_tpu.workflow.pipeline import Pipeline

        counted = _counting_pipeline()
        branch = Pipeline.gather([
            counted.and_then(_T("x")), counted.and_then(_T("y")),
        ])
        X = np.ones((4, 3), dtype=np.float32)
        out = branch(X).get()
        # CSE merges the two counted nodes into one; aggressive caching
        # inserts a Cacher after it; one execution total
        assert CountingNode.count == 1
    finally:
        env.reset()


def test_insert_cachers_reuses_existing_cacher_for_bypass_consumers():
    # src → n → Cacher → c, plus a direct bypass edge n → e
    g = Graph()
    g, src = g.add_source()
    g, n = g.add_node(_T("n"), [src])
    g, cach = g.add_node(Cacher(), [n])
    g, c = g.add_node(_T("c"), [cach])
    g, e = g.add_node(_T("e"), [n])
    g, s1 = g.add_sink(c)
    g, s2 = g.add_sink(e)
    g2 = insert_cachers(g, [n])
    # no second cacher; the bypass consumer now rides the existing one
    assert len([
        x for x in g2.nodes if isinstance(g2.get_operator(x), Cacher)
    ]) == 1
    assert g2.get_dependencies(e) == (cach,)


def test_greedy_seeds_existing_cachers():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(_T("a"), [src])
    g, n = g.add_node(_T("n"), [a])
    g, cach = g.add_node(Cacher(), [n])
    g, c = g.add_node(_T("c"), [cach])
    g, d = g.add_node(_T("d"), [cach])
    g, s1 = g.add_sink(c)
    g, s2 = g.add_sink(d)
    profiles = {
        a: Profile(ns=1e6, mem_bytes=10),
        n: Profile(ns=1e6, mem_bytes=10),
    }
    rule = AutoCacheRule("greedy", 1000, profiles)
    selected = rule._select_greedy(g, profiles, 1000.0)
    # the existing cacher already cuts the fan-out: nothing upstream is
    # worth caching, and the preexisting cacher is not re-selected
    assert selected == set()
