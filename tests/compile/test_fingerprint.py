"""Pipeline fingerprinting: the cache key must be stable across processes
and across harmless runtime state, and must move when anything that
changes the compiled program moves."""

import os
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu import FunctionNode, Transformer
from keystone_tpu.compile import (
    FingerprintError,
    entry_key,
    pipeline_fingerprint,
)
from keystone_tpu.utils.params import as_param

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _double(X):
    return X * 2.0


class _Scale(Transformer):
    """Deterministic fitted-parameter stand-in (numpy state)."""

    def __init__(self, w):
        self.w = as_param(w)

    def trace_batch(self, X):
        return X * self.w


def build_toy(scale: float = 3.0):
    """Deterministic transformer-only chain, buildable identically in any
    process (module-level functions, content-known parameters)."""
    w = np.arange(8, dtype=np.float32) * scale + 1.0
    return (
        FunctionNode(batch_fn=_double, label="double") >> _Scale(w)
    ).fit()


def toy_digest(scale: float = 3.0) -> str:
    return pipeline_fingerprint(build_toy(scale))


def test_rebuild_gives_identical_digest():
    assert toy_digest() == toy_digest()


def test_digest_moves_with_parameters():
    assert toy_digest(3.0) != toy_digest(4.0)


def test_digest_stable_after_use():
    """Executing the pipeline populates memo state (the fused operator's
    ``_jit``); a warm pipeline must fingerprint like a fresh one."""
    fitted = build_toy()
    before = pipeline_fingerprint(fitted)
    fitted.apply(np.ones((4, 8), np.float32))
    fitted.compile(cache=None)(np.ones((4, 8), np.float32))
    assert pipeline_fingerprint(fitted) == before


def test_digest_survives_pickle_round_trip():
    from keystone_tpu.utils import serialization

    fitted = build_toy()
    clone = serialization.loads(serialization.dumps(fitted))
    assert pipeline_fingerprint(clone) == pipeline_fingerprint(fitted)


def test_digest_stable_across_processes():
    """The property the whole cache stands on: a DIFFERENT process
    building the same fitted pipeline derives the same key."""
    out = subprocess.run(
        [
            sys.executable, "-c",
            "from tests.compile.test_fingerprint import toy_digest;"
            "print(toy_digest())",
        ],
        cwd=_REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip().splitlines()[-1] == toy_digest()


def _inner3(X):
    return (lambda: 3.0)() * X


def _inner4(X):
    return (lambda: 4.0)() * X


def _kw2(X, *, s=2.0):
    return X * s


def _kw3(X, *, s=3.0):
    return X * s


def test_digest_sees_nested_code_and_kwdefaults():
    """Functions differing only in an inner lambda's body, or only in a
    keyword-only default, must not collide (a collision would serve one
    model's executable for the other)."""

    def fp(fn):
        return pipeline_fingerprint(
            FunctionNode(batch_fn=fn, label="f").to_pipeline().fit()
        )

    assert fp(_inner3) != fp(_inner4)
    assert fp(_kw2) != fp(_kw3)


def _with_global(scale: float):
    """Same code, different module-global value — only the global differs."""
    ns = {"SCALE": scale}
    exec("def f(X):\n    return X * SCALE", ns)
    return ns["f"]


def test_digest_sees_referenced_module_globals():
    """`def f(X): return X * SCALE` must re-key when SCALE changes, or an
    edited model would load the stale executable."""

    def fp(fn):
        return pipeline_fingerprint(
            FunctionNode(batch_fn=fn, label="f").to_pipeline().fit()
        )

    assert fp(_with_global(2.0)) != fp(_with_global(3.0))
    assert fp(_with_global(2.0)) == fp(_with_global(2.0))


def test_object_dtype_arrays_digest_by_content_not_pointers():
    """tobytes() on an object array would serialize PyObject pointers —
    process-unstable; elements must digest by content instead."""

    def fp(meta):
        fitted = build_toy()
        next(iter(fitted.graph.operators.values())).meta = np.array(
            meta, dtype=object
        )
        return pipeline_fingerprint(fitted)

    assert fp(["a", 1.5]) == fp(["a", 1.5])
    assert fp(["a", 1.5]) != fp(["b", 1.5])


def test_uncanonicalizable_state_raises():
    class Opaque(Transformer):
        def __init__(self):
            self.handle = object()  # no content-stable form

        def trace_batch(self, X):
            return X

    fitted = (FunctionNode(batch_fn=_double, label="double") >> Opaque()).fit()
    with pytest.raises(FingerprintError, match="handle"):
        pipeline_fingerprint(fitted)


def test_entry_key_separates_signature_and_environment():
    env = {"jax": "1", "backend": "cpu"}
    base = entry_key("a" * 64, (8, 4), "float32", env)
    assert entry_key("a" * 64, (16, 4), "float32", env) != base
    assert entry_key("a" * 64, (8, 4), "float64", env) != base
    assert entry_key("a" * 64, (8, 4), "float32", {**env, "jax": "2"}) != base
    assert entry_key("b" * 64, (8, 4), "float32", env) != base
    assert entry_key("a" * 64, (8, 4), "float32", dict(env)) == base
