"""AOT compile wiring: FittedPipeline.compile and the ServingEngine load
executables instead of tracing, fall back to live compiles on any cache
problem with bit-identical outputs, and invalidate on environment skew."""

import numpy as np
import pytest

import keystone_tpu.compile as cmod
from keystone_tpu import FunctionNode
from keystone_tpu.compile import AotDispatcher, ExecutableCache
from keystone_tpu.serving import ServingEngine
from keystone_tpu.utils import serialization

from .test_fingerprint import build_toy

DATUM = (8,)


@pytest.fixture(autouse=True)
def _isolate_global_cache():
    """These tests install a process-global cache; the rest of the suite
    must not inherit it (nor a dangling tmp dir)."""
    yield
    cmod.reset()


def _x(n=4):
    return np.linspace(0.0, 1.0, n * DATUM[0], dtype=np.float32).reshape(n, *DATUM)


# ---------------------------------------------------------------------------
# FittedPipeline.compile
# ---------------------------------------------------------------------------


def test_compile_exports_then_loads_with_zero_traces(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    fitted = build_toy()
    cold = fitted.compile(cache=cache)
    y_cold = np.asarray(cold(_x()))
    assert fitted.compile_count == 1  # the export's trace, counted
    assert len(cache.entries()) == 1

    clone = serialization.loads(serialization.dumps(fitted))
    warm = clone.compile(cache=cache)
    y_warm = np.asarray(warm(_x()))
    assert clone.compile_count == 0, "warm boot must pay zero traces"

    legacy = np.asarray(build_toy().compile(cache=None)(_x()))
    assert np.array_equal(y_cold, y_warm)
    assert np.array_equal(y_cold, legacy)


def test_corrupted_entry_falls_back_to_live_compile(tmp_path):
    import os

    cache = ExecutableCache(str(tmp_path))
    fitted = build_toy()
    y_ref = np.asarray(fitted.compile(cache=cache)(_x()))
    (key, size, _mtime), = cache.entries()
    with open(cache.entry_path(key), "r+b") as f:
        f.seek(size // 2)
        f.write(b"ROT!")

    clone = serialization.loads(serialization.dumps(fitted))
    y = np.asarray(clone.compile(cache=cache)(_x()))
    assert clone.compile_count == 1  # live compile paid, not a crash
    assert np.array_equal(y, y_ref), "fallback must not change results"
    assert len(cache.entries()) == 1  # re-exported over the corrupt entry


def test_environment_skew_is_a_miss_then_a_fresh_export(tmp_path):
    """A cache written by a different toolchain (simulated by skewing the
    dispatcher's environment key) never loads — the pipeline re-traces
    and re-exports under its own key."""
    cache = ExecutableCache(str(tmp_path))
    fitted = build_toy()
    fitted.compile(cache=cache)(_x())
    assert len(cache.entries()) == 1

    fn = fitted.trace_fn()
    traces = []
    disp = AotDispatcher(
        fn, fitted.fingerprint(), cache, on_trace=traces.append
    )
    disp._env = dict(disp._env, jax="0.0.0-skewed")
    y = np.asarray(disp(_x()))
    assert traces, "skewed environment must not load the old entry"
    assert np.array_equal(y, np.asarray(fitted.compile(cache=None)(_x())))
    assert len(cache.entries()) == 2  # old entry intact + new env's entry


def test_unfingerprintable_pipeline_compiles_without_cache(tmp_path):
    fitted = (
        FunctionNode(batch_fn=lambda X: X * 2.0, label="dbl").to_pipeline()
    ).fit()
    # a lambda fingerprints by code digest; sabotage with a live object
    next(iter(fitted.graph.operators.values())).opaque = object()
    cache = ExecutableCache(str(tmp_path))
    compiled = fitted.compile(cache=cache)
    y = np.asarray(compiled(_x()))
    assert fitted.compile_count == 1
    assert cache.entries() == []  # silently fell back to the legacy jit
    assert np.allclose(y, _x() * 2.0)


# ---------------------------------------------------------------------------
# ServingEngine warm boots
# ---------------------------------------------------------------------------


def _serve(engine, rows):
    with engine:
        return [engine.predict(r, timeout=60.0) for r in rows]


def test_engine_cold_then_warm_boot_zero_traces(tmp_path):
    cmod.configure(str(tmp_path))
    fitted = build_toy()
    rows = _x(6)

    cold = ServingEngine(fitted, buckets=(4, 8), datum_shape=DATUM)
    preds_cold = _serve(cold, rows)
    c = cold.metrics.snapshot()["counters"]
    assert c.get("compiles") == 2 and c.get("aot_loads", 0) == 0

    warm = ServingEngine(fitted, buckets=(4, 8), datum_shape=DATUM)
    preds_warm = _serve(warm, rows)
    c = warm.metrics.snapshot()["counters"]
    assert c.get("compiles", 0) == 0, "warm boot must pay zero traces"
    assert c.get("aot_loads") == 2
    assert np.array_equal(np.asarray(preds_cold), np.asarray(preds_warm))


def test_configure_relocates_default_xla_cache_but_not_a_chosen_one(tmp_path):
    """The whole warm-boot state must live in ONE mountable dir: the
    package-default XLA cache relocates under the AOT dir; an
    operator-chosen dir is respected. reset() restores either way."""
    import jax

    import keystone_tpu as pkg

    before = jax.config.jax_compilation_cache_dir
    cmod.configure(str(tmp_path))
    try:
        if before and before != getattr(pkg, "_default_xla_cache_dir", None):
            # operator-chosen (env/config): must be untouched
            assert jax.config.jax_compilation_cache_dir == before
        else:
            assert jax.config.jax_compilation_cache_dir == str(
                tmp_path / "xla"
            )
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    finally:
        cmod.reset()
    assert jax.config.jax_compilation_cache_dir == before


def test_engine_without_cache_behaves_exactly_as_before(tmp_path):
    cmod.configure(None)  # explicit: AOT off
    fitted = build_toy()
    engine = ServingEngine(fitted, buckets=(4,), datum_shape=DATUM)
    _serve(engine, _x(3))
    c = engine.metrics.snapshot()["counters"]
    assert c.get("compiles") == 1 and c.get("aot_loads", 0) == 0
