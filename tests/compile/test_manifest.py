"""The AOT bucket-signature manifest: exports are indexed per pipeline
digest, a booting fleet pre-warms every recorded signature, and corrupt
entries degrade to 'signature unknown'."""

import json
import os

import numpy as np
import pytest

import keystone_tpu.compile as compile_mod
from keystone_tpu.compile.cache import ExecutableCache
from keystone_tpu.compile.manifest import exported_signatures, record_export


@pytest.fixture
def cache(tmp_path):
    yield ExecutableCache(str(tmp_path / "aot"))


def test_record_and_list_round_trip(cache):
    record_export(cache, "digA", (8, 4), "float32")
    record_export(cache, "digA", (32, 4), "float32")
    record_export(cache, "digB", (8, 2), "float32")
    assert exported_signatures(cache, "digA") == [
        ((8, 4), "float32"),
        ((32, 4), "float32"),
    ]
    assert exported_signatures(cache, "digB") == [((8, 2), "float32")]
    assert exported_signatures(cache, "missing") == []


def test_record_is_idempotent(cache):
    for _ in range(3):
        record_export(cache, "digA", (8, 4), "float32")
    d = os.path.join(cache.root, "manifest", "digA")
    assert len(os.listdir(d)) == 1
    assert exported_signatures(cache, "digA") == [((8, 4), "float32")]


def test_corrupt_entry_skipped_not_fatal(cache):
    record_export(cache, "digA", (8, 4), "float32")
    d = os.path.join(cache.root, "manifest", "digA")
    with open(os.path.join(d, "garbage.json"), "w") as f:
        f.write("{not json")
    # a structurally valid but foreign record is also skipped
    with open(os.path.join(d, "foreign.json"), "w") as f:
        json.dump({"unexpected": True}, f)
    assert exported_signatures(cache, "digA") == [((8, 4), "float32")]


def _toy_fitted():
    from keystone_tpu.workflow.transformer import FunctionNode

    def double(X):
        return X * 2.0

    return FunctionNode(batch_fn=double, label="double").to_pipeline().fit()


def test_engine_export_records_manifest_and_fleet_prewarms_it(tmp_path):
    """The PR 6 follow-on, closed: process A's engine exports its buckets
    (manifest written); process-B's-stand-in fleet configured with FEWER
    buckets still pre-warms every manifest signature at start() — zero
    cold first-requests for shapes the pipeline has served before."""
    from keystone_tpu.serving import ServingEngine, ServingFleet

    cachedir = str(tmp_path / "aot")
    try:
        compile_mod.configure(cachedir)
        fitted = _toy_fitted()
        engine = ServingEngine(fitted, buckets=(2, 4), datum_shape=(3,))
        engine.start()
        engine.shutdown()
        assert engine.metrics.count("compiles") == 2

        digest = fitted.fingerprint()
        cache = compile_mod.get_cache()
        sigs = exported_signatures(cache, digest)
        assert ((2, 3), "float32") in sigs and ((4, 3), "float32") in sigs

        # the fleet asks for ONE bucket but pre-warms BOTH manifest
        # signatures — all loaded from the cache, zero traces
        fleet = ServingFleet(
            _toy_fitted(), replicas=2, buckets=(2,), datum_shape=(3,)
        )
        warmed = fleet.warm_up()
        assert warmed == 2  # bucket (2,) + the manifest's extra (4, 3)
        assert fleet.metrics.count("compiles") == 0
        assert fleet.metrics.count("aot_loads") == 2
        fleet.start(warmup=False)
        out = fleet.predict(np.ones(3, np.float32), timeout=30.0)
        np.testing.assert_allclose(np.asarray(out), 2 * np.ones(3), rtol=1e-6)
        fleet.shutdown()
        assert fleet.metrics.count("compiles") == 0
    finally:
        compile_mod.reset()


def test_manifest_filters_foreign_contracts(tmp_path):
    """Signatures whose per-item shape or dtype does not match the
    fleet's contract are not warmed (they would trace programs this
    fleet can never serve)."""
    from keystone_tpu.serving import ServingFleet

    cachedir = str(tmp_path / "aot")
    try:
        compile_mod.configure(cachedir)
        fitted = _toy_fitted()
        fleet = ServingFleet(
            fitted, replicas=1, buckets=(2,), datum_shape=(3,)
        )
        cache = compile_mod.get_cache()
        digest = fitted.fingerprint()
        record_export(cache, digest, (8, 7), "float32")   # wrong item shape
        record_export(cache, digest, (8, 3), "float64")   # wrong dtype
        assert fleet._manifest_signatures() == []
    finally:
        compile_mod.reset()
