"""Segment-compiled execution: lowering, dispatch, AOT round trips,
adaptive boundaries, and — above all — answer preservation.

The executor dispatches the SEGMENT graph when ``KEYSTONE_SEGMENT_COMPILE``
is on, so the load-bearing contract is bit-equality with node dispatch on
every path (compiled, chunked/ragged, fallback, kill-switched) plus the
warm-boot guarantee: a second process loads exported segment executables
and never re-traces.
"""

import numpy as np
import pytest

import keystone_tpu.compile as cmod
import keystone_tpu.cost as cost
from keystone_tpu.check import lattice
from keystone_tpu.check.segments import plan_segments
from keystone_tpu.compile import ExecutableCache
from keystone_tpu.compile import manifest as manifest_mod
from keystone_tpu.compile.fingerprint import segment_fingerprint
from keystone_tpu.compile.segment import (
    SegmentDispatcher,
    bind_segment,
    lower_segment,
    prewarm_segment_artifacts,
    reset_dispatchers,
)
from keystone_tpu.cost import segments as seg_cost
from keystone_tpu.data.chunked import ChunkedDataset
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.obs import tracer as tracer_mod
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.pipeline import FittedPipeline
from keystone_tpu.workflow.transformer import Transformer


@pytest.fixture(autouse=True)
def _isolate_segment_state():
    """Dispatchers are process-global (keyed by digest + cache root) and
    these tests install a process-global AOT cache; neither may leak."""
    reset_dispatchers()
    yield
    reset_dispatchers()
    cmod.reset()


class _Mul(Transformer):
    def __init__(self, k):
        self.k = k

    def trace_batch(self, X):
        return X * self.k


class _Add(Transformer):
    """Two-input traceable member: only constructible through the raw
    Graph API (and_then chains are unary), but the lowering must handle
    multi-dep members positionally. Asymmetric on purpose — a swapped
    argument order changes the answer."""

    def trace_batch(self, X, Y):
        return X + 2.0 * Y


class _HostOnly(Transformer):
    """No trace_batch — a segment barrier, like Cacher/Shuffler."""

    def apply(self, x):
        return x + 1.0


def _mul_chain_fitted():
    pipe = _Mul(2.0).and_then(_Mul(3.0)).and_then(_Mul(0.5))
    return FittedPipeline(pipe.graph, pipe.source, pipe.sink)


def _plan(graph):
    verdicts = {n: lattice.classify(graph.get_operator(n)) for n in graph.nodes}
    segments, barriers = plan_segments(graph, verdicts, {})
    return segments, barriers


def _two_input_graph():
    g = Graph()
    g, a = g.add_node(
        DatasetOperator(Dataset.of(np.ones((4, 3), np.float32))), []
    )
    g, b = g.add_node(
        DatasetOperator(Dataset.of(np.full((4, 3), 2.0, np.float32))), []
    )
    # deps deliberately NOT in graph-id order: the pinned inputs contract
    # must come from linearization, not from dependency iteration
    g, c = g.add_node(_Add(), [b, a])
    g, d = g.add_node(_Mul(3.0), [c])
    g, sink = g.add_sink(d)
    return g, (a, b, c, d)


X10 = np.arange(40, dtype=np.float32).reshape(10, 4)


# ---------------------------------------------------------------------------
# Planning contract + lowering
# ---------------------------------------------------------------------------


def test_segment_inputs_are_pinned_to_linearization_order():
    from keystone_tpu.workflow import analysis

    g, (a, b, c, d) = _two_input_graph()
    segments, _ = _plan(g)
    (seg,) = [s for s in segments if len(s.nodes) == 2]
    assert seg.nodes == [c, d] and seg.outputs == [d]
    assert set(seg.inputs) == {a, b}
    full_pos = {gid: i for i, gid in enumerate(analysis.linearize(g))}
    assert seg.inputs == sorted(seg.inputs, key=lambda i: full_pos[i])
    # the plan (and therefore the lowered signature) is deterministic
    segments2, _ = _plan(g)
    (seg2,) = [s for s in segments2 if len(s.nodes) == 2]
    assert seg2.inputs == seg.inputs and seg2.nodes == seg.nodes


def test_fingerprint_is_stable_and_state_sensitive():
    g, _ = _two_input_graph()
    (seg,) = [s for s in _plan(g)[0] if len(s.nodes) == 2]
    d1 = segment_fingerprint(g, seg)
    g2, _ = _two_input_graph()
    (seg2,) = [s for s in _plan(g2)[0] if len(s.nodes) == 2]
    assert segment_fingerprint(g2, seg2) == d1

    gk = Graph()
    gk, a = gk.add_node(
        DatasetOperator(Dataset.of(np.ones((4, 3), np.float32))), []
    )
    gk, b = gk.add_node(
        DatasetOperator(Dataset.of(np.full((4, 3), 2.0, np.float32))), []
    )
    gk, c = gk.add_node(_Add(), [b, a])
    gk, d = gk.add_node(_Mul(4.0), [c])  # different operator state
    gk, _sink = gk.add_sink(d)
    (segk,) = [s for s in _plan(gk)[0] if len(s.nodes) == 2]
    assert segment_fingerprint(gk, segk) != d1


def test_lower_segment_composes_members_positionally():
    g, (a, b, _c, _d) = _two_input_graph()
    (seg,) = [s for s in _plan(g)[0] if len(s.nodes) == 2]
    fn, steps, out_slots = lower_segment(g, seg)
    assert len(steps) == 2 and len(out_slots) == 1
    # feed by the pinned order: one value per segment input, positionally
    by_node = {
        a: np.ones((4, 3), np.float32),
        b: np.full((4, 3), 2.0, np.float32),
    }
    out = fn(*[by_node[i] for i in seg.inputs])
    # _Add's deps are (b, a): (2 + 2*1) * 3 — a swapped argument order
    # would produce (1 + 2*2) * 3 = 15 instead
    np.testing.assert_allclose(np.asarray(out[0]), 12.0)


def test_binding_dispatches_two_input_segment_compiled():
    g, (a, b, _c, _d) = _two_input_graph()
    (seg,) = [s for s in _plan(g)[0] if len(s.nodes) == 2]
    binding = bind_segment(g, seg)
    assert binding is not None and len(binding) == 2
    ins = {
        a: Dataset.of(np.ones((4, 3), np.float32)),
        b: Dataset.of(np.full((4, 3), 2.0, np.float32)),
    }
    outs, path = binding.run([ins[i] for i in binding.inputs])
    assert path == "compiled"
    np.testing.assert_allclose(np.asarray(outs[0].to_array()), 12.0)


def test_singleton_plain_node_is_not_bound():
    pipe = _Mul(2.0).and_then(_HostOnly()).and_then(_Mul(4.0))
    g, data_id = pipe.graph, None
    from keystone_tpu.workflow.pipeline import attach_data

    g, data_id = attach_data(g, Dataset.of(X10))
    g = g.replace_dependency(pipe.source, data_id)
    g = g.remove_source(pipe.source)
    segments, barriers = _plan(g)
    # the host node is a barrier; the _Mul singletons around it gain
    # nothing from segment dispatch and must not bind
    assert "host" in barriers.values()
    for seg in segments:
        assert bind_segment(g, seg) is None


# ---------------------------------------------------------------------------
# Executor dispatch: spans, kill switch, parity
# ---------------------------------------------------------------------------


def test_chain_applies_as_one_segment_span(monkeypatch):
    fitted = _mul_chain_fitted()
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        y = np.asarray(fitted.apply(Dataset.of(X10)).to_array())
        spans = tracer.spans()
    finally:
        tracer_mod.reset()
    np.testing.assert_allclose(y, X10 * 3.0)
    seg_spans = [sp for sp in spans if sp.name == "exec.segment"]
    assert len(seg_spans) == 1
    (sp,) = seg_spans
    assert sp.attrs["nodes"] == 3 and sp.attrs["path"] == "compiled"
    assert len(sp.attrs["node_ids"]) == 3
    # member nodes emit NO per-node spans — that is the dispatch saving
    assert not any("_Mul" in s.name for s in spans)

    monkeypatch.setenv("KEYSTONE_SEGMENT_COMPILE", "0")
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        y_node = np.asarray(fitted.apply(Dataset.of(X10)).to_array())
        node_spans = tracer.spans()
    finally:
        tracer_mod.reset()
    assert not any(s.name == "exec.segment" for s in node_spans)
    assert sum(1 for s in node_spans if "_Mul" in s.name) == 3
    assert np.array_equal(y, y_node), "kill switch must not change answers"


def test_ragged_final_chunk_rides_chunk_padder(monkeypatch):
    fitted = _mul_chain_fitted()
    chunked = ChunkedDataset.from_array(X10, 4)  # chunks of 4, 4, 2 rows
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        y = np.asarray(fitted.apply(chunked).to_array())
        spans = tracer.spans()
    finally:
        tracer_mod.reset()
    np.testing.assert_allclose(y, X10 * 3.0)
    (sp,) = [s for s in spans if s.name == "exec.segment"]
    assert sp.attrs["path"] == "chunked"

    monkeypatch.setenv("KEYSTONE_SEGMENT_COMPILE", "0")
    y_node = np.asarray(
        fitted.apply(ChunkedDataset.from_array(X10, 4)).to_array()
    )
    assert np.array_equal(y, y_node)


def test_host_callback_chain_degrades_to_node_dispatch():
    pipe = _Mul(2.0).and_then(_HostOnly()).and_then(_Mul(4.0))
    fitted = FittedPipeline(pipe.graph, pipe.source, pipe.sink)
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        y = np.asarray(fitted.apply(Dataset.of(X10)).to_array())
        spans = tracer.spans()
    finally:
        tracer_mod.reset()
    np.testing.assert_allclose(y, (X10 * 2.0 + 1.0) * 4.0)
    # no bindable segment around the host barrier: pure node dispatch,
    # no demotion warnings, no errors
    assert not any(s.name == "exec.segment" for s in spans)
    assert any("_HostOnly" in s.name for s in spans)


# ---------------------------------------------------------------------------
# End-to-end parity on the named pipelines (gather / diamond shapes)
# ---------------------------------------------------------------------------


def test_mnist_random_fft_segment_vs_node_bit_equality(monkeypatch):
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.mnist_random_fft import (
        NUM_CLASSES,
        MnistRandomFFTConfig,
        build_featurizer,
        synthetic_mnist,
    )

    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=10.0)
    train, test = synthetic_mnist(128, 32, seed=7)

    def fit():
        labels = ClassLabelIndicators(NUM_CLASSES).apply_batch(train.labels)
        return (
            build_featurizer(conf)
            .and_then(
                BlockLeastSquaresEstimator(
                    conf.block_size, 1, conf.lam or 0.0
                ),
                train.data,
                labels,
            )
            .and_then(MaxClassifier())
            .fit()
        )

    fitted = fit()
    tracer = tracer_mod.install(tracer_mod.Tracer())
    try:
        y_seg = np.asarray(fitted.apply(test.data).to_array())
        spans = tracer.spans()
    finally:
        tracer_mod.reset()
    assert any(s.name == "exec.segment" for s in spans)

    monkeypatch.setenv("KEYSTONE_SEGMENT_COMPILE", "0")
    y_node = np.asarray(fitted.apply(test.data).to_array())
    assert np.array_equal(y_seg, y_node)

    # a fit run entirely under node dispatch trains the same model
    fitted_off = fit()
    y_off = np.asarray(fitted_off.apply(test.data).to_array())
    assert np.array_equal(y_seg, y_off)


def test_timit_segment_vs_node_bit_equality(monkeypatch):
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.timit import (
        TimitConfig,
        build_featurizer,
        synthetic_timit,
    )

    conf = TimitConfig(
        num_cosines=3, cosine_features=64, input_dim=24, num_epochs=1,
        lam=1e-2, num_classes=4,
    )
    train = synthetic_timit(96, 4, dim=24, seed=0)
    test = synthetic_timit(24, 4, dim=24, seed=1)
    labels = ClassLabelIndicators(4).apply_batch(train.labels)
    fitted = (
        build_featurizer(conf)
        .and_then(
            BlockLeastSquaresEstimator(
                conf.cosine_features, conf.num_epochs, conf.lam
            ),
            train.data,
            labels,
        )
        .and_then(MaxClassifier())
        .fit()
    )
    y_seg = np.asarray(fitted.apply(test.data).to_array())
    monkeypatch.setenv("KEYSTONE_SEGMENT_COMPILE", "0")
    y_node = np.asarray(fitted.apply(test.data).to_array())
    assert np.array_equal(y_seg, y_node)


# ---------------------------------------------------------------------------
# AOT round trip: cold exports, warm loads, prewarm
# ---------------------------------------------------------------------------


def test_cold_run_exports_and_warm_run_loads_zero_trace(tmp_path):
    from keystone_tpu.compile import segment as segment_mod

    cache = cmod.configure(str(tmp_path))
    assert cache is not None
    y_cold = np.asarray(_mul_chain_fitted().apply(Dataset.of(X10)).to_array())
    (disp,) = list(segment_mod._DISPATCHERS.values())
    assert disp.traced_count == 1 and disp.loaded_count == 0
    digests = manifest_mod.segment_digests(cache)
    assert digests == [disp.digest]
    sigs = manifest_mod.segment_signatures(cache, disp.digest)
    assert sigs == [(((10, 4), "float32"),)]

    # "new process": dispatcher registry dropped, same pipeline rebuilt
    reset_dispatchers()
    y_warm = np.asarray(_mul_chain_fitted().apply(Dataset.of(X10)).to_array())
    (disp2,) = list(segment_mod._DISPATCHERS.values())
    assert disp2.digest == disp.digest
    assert disp2.loaded_count == 1 and disp2.traced_count == 0, (
        "a warm boot must load the exported segment, never re-trace"
    )
    assert np.array_equal(y_cold, y_warm)


def test_prewarm_warms_manifest_indexed_segments(tmp_path):
    cache = cmod.configure(str(tmp_path))
    _mul_chain_fitted().apply(Dataset.of(X10)).to_array()
    assert prewarm_segment_artifacts(cache) >= 1
    # an empty cache prewarms nothing and does not fail
    assert prewarm_segment_artifacts(ExecutableCache(str(tmp_path / "e"))) == 0


def test_dispatcher_without_cache_uses_structural_jit():
    disp = SegmentDispatcher(
        lambda x: (x * 2.0,), "ab" * 32, None, label="t", n_nodes=2
    )
    y = disp(np.ones((2, 2), np.float32))
    np.testing.assert_allclose(np.asarray(y[0]), 2.0)
    y = disp(np.ones((3, 2), np.float32))  # second signature, same jit
    np.testing.assert_allclose(np.asarray(y[0]), 2.0)
    assert disp.loaded_count == 0 and disp.traced_count == 0


def test_manifest_segment_records_roundtrip(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    digest = "c" * 64
    sigs = (((4, 3), "float32"), ((4, 1), "int32"))
    manifest_mod.record_segment(cache, digest, sigs)
    manifest_mod.record_segment(cache, digest, sigs)  # idempotent
    assert manifest_mod.segment_signatures(cache, digest) == [sigs]
    assert manifest_mod.segment_digests(cache) == [digest]
    manifest_mod.record_segment(cache, digest, (((8, 3), "float32"),))
    assert len(manifest_mod.segment_signatures(cache, digest)) == 2


# ---------------------------------------------------------------------------
# Adaptive boundaries: demotion policy + runtime-failure fallback
# ---------------------------------------------------------------------------


def test_compile_exceeding_savings_demotes_unexported_segment(tmp_path):
    cost.configure(str(tmp_path))
    digest = "a" * 64
    assert seg_cost.should_compile(digest, 3)
    seg_cost.record_compile(digest, 1.0, exported=False, n_nodes=3)
    for _ in range(seg_cost.MIN_RUNS_FOR_DEMOTION - 1):
        seg_cost.record_run(digest, 1e-5, n_nodes=3)
    assert seg_cost.should_compile(digest, 3)  # below the evidence floor
    seg_cost.record_run(digest, 1e-5, n_nodes=3)
    assert not seg_cost.should_compile(digest, 3)
    rec = cost.get_store().load("plan/segment/" + digest[:32])
    assert rec["why"] == "compile_exceeds_savings"


def test_exported_segment_never_demotes(tmp_path):
    cost.configure(str(tmp_path))
    digest = "b" * 64
    seg_cost.record_compile(digest, 100.0, exported=True, n_nodes=3)
    for _ in range(seg_cost.MIN_RUNS_FOR_DEMOTION * 2):
        seg_cost.record_run(digest, 1e-6, n_nodes=3)
    # the export amortizes across processes: a sunk compile is never
    # charged against this process's dispatch savings
    assert seg_cost.should_compile(digest, 3)


def test_runtime_failure_demotes_and_next_plan_splits(tmp_path):
    cost.configure(str(tmp_path))
    g, _ = _two_input_graph()
    (seg,) = [s for s in _plan(g)[0] if len(s.nodes) == 2]
    binding = bind_segment(g, seg)
    assert binding is not None
    seg_cost.record_failure(binding.digest)
    assert bind_segment(g, seg) is None, (
        "a demoted digest must split back to node dispatch at plan time"
    )


def test_failed_dispatch_falls_back_to_exact_node_semantics():
    pipe = _Mul(2.0).and_then(_Mul(3.0))
    fitted = FittedPipeline(pipe.graph, pipe.source, pipe.sink)
    from keystone_tpu.workflow.pipeline import attach_data

    g, data_id = attach_data(fitted.graph, Dataset.of(X10))
    g = g.replace_dependency(pipe.source, data_id)
    g = g.remove_source(pipe.source)
    (seg,) = [s for s in _plan(g)[0] if len(s.nodes) == 2]
    binding = bind_segment(g, seg)
    assert binding is not None

    def boom(*xs):
        raise RuntimeError("synthetic trace failure")

    binding.fn = boom
    binding.digest = "f" * 64  # fresh dispatcher, not the cached good one
    outs, path = binding.run([Dataset.of(X10)])
    assert path == "fallback" and binding._demoted
    np.testing.assert_allclose(np.asarray(outs[0].to_array()), X10 * 6.0)
    # subsequent runs stay demoted without retrying the broken program
    outs2, path2 = binding.run([Dataset.of(X10)])
    assert path2 == "fallback"
    np.testing.assert_allclose(np.asarray(outs2[0].to_array()), X10 * 6.0)


def test_cost_recording_is_noop_without_store():
    assert cost.get_store() is None
    digest = "d" * 64
    seg_cost.record_compile(digest, 1.0, exported=False, n_nodes=3)
    seg_cost.record_run(digest, 1.0, n_nodes=3)
    seg_cost.record_failure(digest)
    assert seg_cost.should_compile(digest, 3)
