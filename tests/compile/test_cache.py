"""ExecutableCache: the disk format degrades to a miss under every
failure mode, the LRU size bound holds, and concurrent processes can
hammer one directory safely."""

import os
import subprocess
import sys
import time

from keystone_tpu.compile.cache import ExecutableCache

ENV = {"jax": "0.0.1", "backend": "cpu"}


def _store(cache, key, payload=b"payload-bytes", env=ENV, **extra):
    return cache.store(key, payload, {"env": dict(env), **extra})


def test_round_trip(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    _store(cache, "k1", b"blob", trace_seconds=1.5)
    entry = cache.load("k1", expect_env=ENV)
    assert entry is not None
    assert entry.payload == b"blob"
    assert entry.header["trace_seconds"] == 1.5
    assert entry.header["env"] == ENV


def test_absent_key_is_a_miss(tmp_path):
    assert ExecutableCache(str(tmp_path)).load("nope", expect_env=ENV) is None


def test_corrupted_payload_is_discarded(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    path = _store(cache, "k1", b"x" * 256)
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        f.write(b"ROT!")
    assert cache.load("k1", expect_env=ENV) is None
    assert not os.path.exists(path)  # corrupt entries are removed


def test_truncated_entry_is_discarded(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    path = _store(cache, "k1", b"x" * 256)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 10)
    assert cache.load("k1", expect_env=ENV) is None
    assert not os.path.exists(path)


def test_environment_mismatch_is_a_miss_not_a_crash(tmp_path):
    """A stale-toolchain entry (e.g. written by another jax version) must
    never load — and must NOT be deleted: its own toolchain may still
    want it."""
    cache = ExecutableCache(str(tmp_path))
    path = _store(cache, "k1", env={"jax": "0.0.0", "backend": "cpu"})
    assert cache.load("k1", expect_env=ENV) is None
    assert os.path.exists(path)
    assert cache.load("k1", expect_env={"jax": "0.0.0", "backend": "cpu"})


def test_renamed_entry_is_rejected(tmp_path):
    """The header binds the file to its key — a copied/renamed entry
    cannot masquerade as a different pipeline's executable."""
    cache = ExecutableCache(str(tmp_path))
    _store(cache, "k1")
    os.rename(cache.entry_path("k1"), cache.entry_path("k2"))
    assert cache.load("k2", expect_env=ENV) is None


def test_lru_eviction_respects_recency_and_keeps_newest(tmp_path):
    payload = b"x" * 1000
    cache = ExecutableCache(str(tmp_path), max_bytes=2500)
    _store(cache, "a", payload)
    time.sleep(0.02)
    _store(cache, "b", payload)
    time.sleep(0.02)
    assert cache.load("a", expect_env=ENV)  # bump a's recency above b's
    time.sleep(0.02)
    _store(cache, "c", payload)  # over budget -> evict oldest mtime (b)
    keys = {k for k, _, _ in cache.entries()}
    assert "c" in keys, "the just-written entry must never be evicted"
    assert "a" in keys and "b" not in keys
    assert cache.total_bytes() <= 2500


def test_no_temp_files_left_behind(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    _store(cache, "k1")
    _store(cache, "k1", b"replacement")  # overwrite is atomic too
    leftovers = [n for n in os.listdir(cache.entries_dir) if n.startswith(".tmp")]
    assert leftovers == []
    assert cache.load("k1", expect_env=ENV).payload == b"replacement"


_WORKER = r"""
import os, sys
from keystone_tpu.compile.cache import ExecutableCache

root, seed = sys.argv[1], int(sys.argv[2])
cache = ExecutableCache(root, max_bytes=1 << 20)
env = {"jax": "0.0.1", "backend": "cpu"}
payload = (b"%d-" % seed) * 64
for i in range(40):
    key = "shared-%d" % (i % 4)
    cache.store(key, payload, {"env": env, "writer": seed})
    got = cache.load(key, expect_env=env)
    # a concurrent writer may have replaced it, but a load is either a
    # clean miss or a COMPLETE entry from some writer - never torn bytes
    if got is not None:
        first = got.payload[:2]
        assert first in (b"1-", b"2-"), got.payload[:8]
        assert got.payload == first * 64
print("OK")
"""


def test_two_process_concurrent_read_write(tmp_path):
    """Two processes store+load the same keys concurrently: every load
    sees a complete entry or a miss, and nobody crashes."""
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(tmp_path), str(seed)],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for seed in (1, 2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]
        assert out.strip() == "OK"
