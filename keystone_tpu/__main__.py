"""The CLI front door: ``python -m keystone_tpu <PipelineName> [args...]``.

Parity: ``bin/run-pipeline.sh:34-56`` + ``run-main.sh`` in the reference —
one entry point that dispatches a pipeline class name to its ``main``. The
reference's ``--master``/SPARK_HOME switch becomes ``--backend tpu|cpu``:
the jax platform is selected before any device is initialized, with the
CPU backend optionally widened to a virtual N-device mesh (the local-mode
stand-in for a slice, like ``local[n]``).

Pipeline names match the reference application objects, e.g.::

    python -m keystone_tpu MnistRandomFFT --numFFTs 4 --blockSize 2048
    python -m keystone_tpu RandomPatchCifar --numFilters 100
    python -m keystone_tpu LinearPixels          # cifar-extras family
    python -m keystone_tpu VOCSIFTFisher --trainLocation voc.tar ...
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable, List, Optional


def _mnist(argv):
    from .pipelines.mnist_random_fft import main

    return main(argv)


def _random_patch_cifar(argv):
    from .pipelines.random_patch_cifar import main

    return main(argv)


def _cifar_extra(app: str) -> Callable:
    def run(argv):
        from .pipelines.cifar_extras import main

        return main([app, *argv])

    return run


def _voc(argv):
    from .pipelines.voc_sift_fisher import main

    return main(argv)


def _imagenet(argv):
    from .pipelines.imagenet_sift_lcs_fv import main

    return main(argv)


def _timit(argv):
    from .pipelines.timit import main

    return main(argv)


def _newsgroups(argv):
    from .pipelines.newsgroups import main

    return main(argv)


def _amazon(argv):
    from .pipelines.amazon_reviews import main

    return main(argv)


def _stupid_backoff(argv):
    from .pipelines.stupid_backoff_pipeline import main

    return main(argv)


#: shorthand → reference application object name (the full names stay the
#: canonical registry keys; these are CLI conveniences only)
ALIASES = {
    "mnist": "MnistRandomFFT",
    "cifar": "RandomPatchCifar",
    "voc": "VOCSIFTFisher",
    "imagenet": "ImageNetSiftLcsFV",
    "timit": "TimitPipeline",
    "newsgroups": "NewsgroupsPipeline",
    "amazon": "AmazonReviewsPipeline",
}

#: reference application object name → runner
PIPELINES = {
    "MnistRandomFFT": _mnist,
    "LinearPixels": _cifar_extra("LinearPixels"),
    "RandomCifar": _cifar_extra("RandomCifar"),
    "RandomPatchCifar": _random_patch_cifar,
    "RandomPatchCifarAugmented": _cifar_extra("RandomPatchCifarAugmented"),
    "RandomPatchCifarKernel": _cifar_extra("RandomPatchCifarKernel"),
    "VOCSIFTFisher": _voc,
    "ImageNetSiftLcsFV": _imagenet,
    "TimitPipeline": _timit,
    "NewsgroupsPipeline": _newsgroups,
    "AmazonReviewsPipeline": _amazon,
    "StupidBackoffPipeline": _stupid_backoff,
}


def _select_backend(backend: Optional[str], cpu_devices: int) -> None:
    """Pick the jax platform BEFORE any device is touched. A sitecustomize
    may have pre-imported jax, so env vars are too late — use the config
    knob / virtual-device provisioner instead."""
    if cpu_devices > 1 and backend != "cpu":
        import logging

        logging.getLogger(__name__).warning(
            "--cpuDevices %d has no effect without --backend cpu "
            "(virtual devices exist only on the cpu backend)", cpu_devices,
        )
    if backend is None:
        return
    if backend == "cpu" and cpu_devices > 1:
        from .parallel.virtual import provision_virtual_devices

        provision_virtual_devices(cpu_devices)
        return
    import jax

    jax.config.update("jax_platforms", backend)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu",
        description="Run a pipeline (parity: bin/run-pipeline.sh).",
    )
    # Pre-scan for the demo modes: in demo mode there is no pipeline
    # positional, and the demo's own flags (--requests 64, ...) must pass
    # through parse_known_args without a positional slot swallowing their
    # values. Accept the same unambiguous prefix abbreviations argparse
    # would (--serve, --train, --sweep-d, ...); a prefix shared with ANY
    # other registered flag (--s, --tra vs --trace) matches no demo flag
    # and falls through to argparse's ambiguity error.
    _DEMO_FLAGS = ("--serve-demo", "--sweep-demo", "--trainer-demo")
    #: every other long option registered below — a demo abbreviation
    #: must be unambiguous against these too, exactly as argparse would
    #: treat it (--tra must stay an error between --trace/--trainer-demo)
    _OTHER_FLAGS = (
        "--backend", "--cpuDevices", "--log", "--logLevel", "--profile",
        "--check", "--trace", "--aot-cache", "--profiles",
    )

    def _is_demo_flag(a: str, flag: str) -> bool:
        return (
            len(a) > 2
            and flag.startswith(a)
            and sum(f.startswith(a) for f in _DEMO_FLAGS) == 1
            and not any(f.startswith(a) for f in _OTHER_FLAGS)
        )

    def _is_serve_demo_flag(a: str) -> bool:
        return _is_demo_flag(a, "--serve-demo")

    def _is_sweep_demo_flag(a: str) -> bool:
        return _is_demo_flag(a, "--sweep-demo")

    def _is_trainer_demo_flag(a: str) -> bool:
        return _is_demo_flag(a, "--trainer-demo")

    serve_demo = any(_is_serve_demo_flag(a) for a in argv)
    sweep_demo = any(_is_sweep_demo_flag(a) for a in argv)
    trainer_demo = any(_is_trainer_demo_flag(a) for a in argv)
    argv = [
        a for a in argv
        if not any(_is_demo_flag(a, f) for f in _DEMO_FLAGS)
    ]
    # registered for -h only; the flags themselves are consumed above
    p.add_argument(
        "--serve-demo", action="store_true", dest="serve_demo",
        help="smoke mode: fit a small pipeline and push synthetic traffic "
             "through the serving engine (see keystone_tpu/serving/); "
             "replaces the pipeline name. --replicas N serves from a "
             "continuous-batching ServingFleet of N workers instead of "
             "the single-worker engine; --workers N (or KEYSTONE_WORKERS) "
             "serves from a multi-process ClusterRouter of N worker "
             "processes sharing the AOT cache for warm boots "
             "(keystone_tpu/cluster/)",
    )
    p.add_argument(
        "--sweep-demo", action="store_true", dest="sweep_demo",
        help="smoke mode: fit a λ grid as ONE merged DAG "
             "(keystone_tpu/sweep/), absorb appended chunks into the best "
             "member, and hot-swap it into a live serving engine; "
             "replaces the pipeline name",
    )
    p.add_argument(
        "--trainer-demo", action="store_true", dest="trainer_demo",
        help="smoke mode: the closed continual-learning loop "
             "(keystone_tpu/trainer/) — boot a replica fleet + trainer "
             "daemon, append chunk batches under live traffic, and "
             "assert promoted refreshes, a clean canary rollback of a "
             "poisoned batch, and zero request failures; replaces the "
             "pipeline name",
    )
    if not (serve_demo or sweep_demo or trainer_demo):
        # validated by _resolve_pipeline, not choices=, so shorthand
        # aliases (mnist, cifar, ...) and any-case names resolve
        p.add_argument(
            "pipeline", metavar="pipeline",
            help="one of: " + ", ".join(sorted(PIPELINES))
                 + " (case-insensitive; shorthands: "
                 + ", ".join(sorted(ALIASES)) + ")",
        )
    p.add_argument(
        "--backend", choices=["tpu", "cpu"], default=None,
        help="jax platform; default = whatever jax picks",
    )
    p.add_argument(
        "--cpuDevices", type=int, default=1,
        help="with --backend cpu: virtual device count for a local mesh",
    )
    p.add_argument(
        "--log", "--logLevel", dest="log_level", default=None,
        choices=["debug", "info", "warning", "error"],
        help="log verbosity (default: $KEYSTONE_LOG or warning)",
    )
    p.add_argument(
        "--profile", action="store_true",
        help="per-phase device-time logs in the hot solvers "
             "(also: KEYSTONE_PROFILE=1)",
    )
    p.add_argument(
        "--check", action="store_true", dest="check_only",
        help="static-check mode: build the pipeline, run the whole-DAG "
             "shape/dtype/traceability checker and segment planner "
             "(keystone_tpu/check/) at fit entry, print the report, and "
             "exit WITHOUT executing a single chunk or sample; non-zero "
             "exit on a statically-proven defect",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="record a per-node execution trace and write Chrome-trace "
             "JSON to PATH — open in chrome://tracing or "
             "https://ui.perfetto.dev (also: KEYSTONE_TRACE=PATH)",
    )
    p.add_argument(
        "--aot-cache", default=None, metavar="DIR", dest="aot_cache",
        help="persistent AOT executable cache directory: fitted-pipeline "
             "compiles load previously exported executables instead of "
             "re-tracing, so warm boots skip every compile "
             "(also: KEYSTONE_AOT_CACHE=DIR)",
    )
    p.add_argument(
        "--profiles", default=None, metavar="DIR", dest="profiles",
        help="persistent operator-profile store directory: fits learn "
             "per-operator throughput from traced runs, and the second "
             "fit of a pipeline plans solver choice + caching from the "
             "stored evidence with zero sampling executions "
             "(also: KEYSTONE_PROFILE_DIR=DIR)",
    )
    args, rest = p.parse_known_args(argv)
    if not (serve_demo or sweep_demo or trainer_demo):
        name = _resolve_pipeline(p, args.pipeline)
    from .utils.obs import configure, export_trace

    configure(
        args.log_level, profile=args.profile or None, trace=args.trace,
        aot_cache=args.aot_cache, profiles=args.profiles,
    )
    _select_backend(args.backend, args.cpuDevices)
    if args.check_only:
        from . import check as check_mod

        check_mod.set_check_only(True)
    try:
        try:
            if serve_demo:
                from .serving.demo import main as serve_demo_main

                return serve_demo_main(rest)
            if sweep_demo:
                from .sweep.demo import main as sweep_demo_main

                return sweep_demo_main(rest)
            if trainer_demo:
                from .trainer.demo import main as trainer_demo_main

                return trainer_demo_main(rest)
            return PIPELINES[name](rest)
        except Exception as e:
            from . import check as check_mod

            if args.check_only and isinstance(e, check_mod.CheckOnlyExit):
                s = e.report.summary()
                print(
                    f"CHECK OK: {s['nodes']} nodes, {s['segments']} "
                    f"segment(s), {s['barriers']} barrier(s), "
                    f"0 executions"
                )
                return 0
            raise
    finally:
        if args.check_only:
            from . import check as check_mod

            # in-process callers (tests) must not leak check-only mode
            check_mod.set_check_only(False)
        # no-op unless --trace/KEYSTONE_TRACE configured tracing; writing
        # here (not only atexit) means in-process callers get the file too
        export_trace()


def _resolve_pipeline(parser: argparse.ArgumentParser, name: str) -> str:
    if name in PIPELINES:
        return name
    lowered = {k.lower(): k for k in PIPELINES}
    full = ALIASES.get(name.lower()) or lowered.get(name.lower())
    if full is None:
        parser.error(
            f"argument pipeline: invalid choice: {name!r} "
            f"(choose from {', '.join(sorted(PIPELINES))})"
        )
    return full


if __name__ == "__main__":
    raise SystemExit(main())
