"""Per-segment compile-vs-run evidence: the adaptive-boundary half of
segment compilation.

Every segment compile (trace+export) and every compiled dispatch lands
here under the profile store's ``plan/segment/<digest>`` namespace. The
policy question the evidence answers is the ISSUE's split rule: *has this
segment's compile cost exceeded the dispatch savings its runs have
earned?* Dispatch savings per run are modeled as
``(n_nodes - 1) * KEYSTONE_SEGMENT_DISPATCH_COST`` — the Python
thunk/span overhead a fused dispatch avoids per subsumed node (default
200µs, tunable per deployment).

Demotion only fires on *unexported* segments with at least
``MIN_RUNS_FOR_DEMOTION`` runs of evidence: an exported segment's compile
is a sunk, cross-process-amortized cost (warm boots load it for free), so
charging it against this process's runs would demote exactly the segments
the AOT plane makes cheap. A runtime failure demotes unconditionally.

Everything is best-effort: with no profile store configured
(``KEYSTONE_PROFILE_DIR`` unset) every function no-ops and
:func:`should_compile` says yes.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ..utils import env_float

logger = logging.getLogger(__name__)

#: runs of evidence required before compile-vs-savings can demote
MIN_RUNS_FOR_DEMOTION = 8


def dispatch_overhead_s() -> float:
    """Modeled per-node Python dispatch overhead a fused segment dispatch
    saves (seconds). ``KEYSTONE_SEGMENT_DISPATCH_COST`` overrides."""
    return env_float("KEYSTONE_SEGMENT_DISPATCH_COST", 2e-4)


def _key(digest: str) -> str:
    return "plan/segment/" + digest[:32]


def should_compile(digest: str, n_nodes: int) -> bool:
    """The next-fit policy read: False iff the evidence demoted this
    segment back to node dispatch. No store / no record ⇒ compile."""
    from . import get_store

    store = get_store()
    if store is None:
        return True
    rec = store.load(_key(digest))
    if rec is None:
        return True
    return not bool(rec.get("demoted"))


def record_compile(
    digest: str, seconds: float, *, exported: bool, n_nodes: int
) -> None:
    """One trace (+export when it landed) was paid for ``digest``."""
    from . import get_store

    store = get_store()
    if store is None:
        return

    def merge(rec: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        rec = dict(rec or {})
        rec["compiles"] = int(rec.get("compiles", 0)) + 1
        rec["compile_s"] = float(rec.get("compile_s", 0.0)) + float(seconds)
        rec["exported"] = bool(rec.get("exported")) or bool(exported)
        rec["nodes"] = int(n_nodes)
        return _evaluate(rec)

    store.update(_key(digest), merge)


def record_run(digest: str, seconds: float, *, n_nodes: int) -> None:
    """One compiled whole-segment dispatch ran for ``digest``."""
    from . import get_store

    store = get_store()
    if store is None:
        return

    def merge(rec: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        rec = dict(rec or {})
        rec["runs"] = int(rec.get("runs", 0)) + 1
        rec["run_s"] = float(rec.get("run_s", 0.0)) + float(seconds)
        rec["nodes"] = int(rec.get("nodes", n_nodes))
        return _evaluate(rec)

    store.update(_key(digest), merge)


def record_failure(digest: str, *, why: str = "runtime") -> None:
    """A compiled dispatch raised — demote unconditionally; the fallback
    already served the answer through node semantics."""
    from . import get_store

    store = get_store()
    if store is None:
        return

    def merge(rec: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        rec = dict(rec or {})
        rec["demoted"] = True
        rec["why"] = why
        return rec

    store.update(_key(digest), merge)


def _evaluate(rec: Dict[str, Any]) -> Dict[str, Any]:
    """The split rule, applied in place on every evidence update."""
    if rec.get("demoted") or rec.get("exported"):
        # exported ⇒ the compile amortizes across every future process
        # (warm boots load it); never demote on this process's ledger
        return rec
    runs = int(rec.get("runs", 0))
    if runs < MIN_RUNS_FOR_DEMOTION:
        return rec
    nodes = int(rec.get("nodes", 1))
    savings = runs * max(nodes - 1, 0) * dispatch_overhead_s()
    if float(rec.get("compile_s", 0.0)) > savings:
        rec["demoted"] = True
        rec["why"] = "compile_exceeds_savings"
    return rec
