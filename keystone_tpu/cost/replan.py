"""Trace-informed re-planning: the loop that turns plans into evidence.

``Pipeline.fit`` opens a :class:`PendingPlan` around the optimizer run;
the planning rules contribute what they decided (solver choice + shape,
per-node cost estimates, the cache plan and its budget). After the fit
executes, :func:`finalize` joins those decisions against the trace's
observed per-node costs (``obs/audit.py``) and

1. updates the profile store's ``op/<OperatorClass>`` throughput records
   (solver seconds-per-unit, per-item node seconds/bytes),
2. persists ``solver/<fp>`` and ``plan/<fp>`` records so the NEXT fit of
   the same pipeline plans from evidence with zero sampling executions,
3. re-derives the greedy cache plan from OBSERVED node costs and logs the
   delta against the sampled plan (a ``cost.replan`` span carries the
   added/removed node labels) — the KeystoneML loop closed: the planner
   is no longer blind to how its estimates held up.

Graph identity is a structural fingerprint (:func:`graph_fingerprint`):
operator class + label + topology over the deterministic linearization.
Node ids are never persisted — records address nodes by topological
index, which is stable across processes for the same pipeline build.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

logger = logging.getLogger(__name__)

#: plan-record schema version — bump to invalidate persisted plans
PLAN_VERSION = 1


def _leaf_signature(op) -> str:
    """A cheap data-shape signature for a DatasetOperator leaf. The
    operator's label only encodes n — but a stored solver shape replayed
    against a dataset with a different per-item width would be stale
    evidence, so the fingerprint must see d too. Batched array payloads
    expose .shape directly; chunked sources contribute their row count
    and label; item-list datasets fall back to n alone (their per-item
    shape is not knowable without compute)."""
    from ..data.chunked import ChunkedDataset
    from ..workflow.operators import DatasetOperator

    if not isinstance(op, DatasetOperator):
        return ""
    ds = op.dataset
    if isinstance(ds, ChunkedDataset):
        return f"|chunked[{len(ds)}]"
    shape = getattr(ds.payload, "shape", None)
    if shape is not None:
        return f"|shape{tuple(int(s) for s in shape)}"
    return f"|items[{len(ds)}]"


def graph_fingerprint(graph) -> str:
    """Process-stable sha256 of a workflow graph's structure: one line per
    linearized id (kind, operator class, label + leaf data shape,
    dependency indices)."""
    from ..workflow import analysis
    from ..workflow.graph import NodeId, SinkId, SourceId

    order = analysis.linearize(graph)
    index = {gid: i for i, gid in enumerate(order)}
    h = hashlib.sha256()
    for gid in order:
        if isinstance(gid, NodeId):
            op = graph.get_operator(gid)
            deps = ",".join(
                str(index[d]) for d in graph.get_dependencies(gid)
            )
            line = (
                f"N|{type(op).__module__}.{type(op).__qualname__}"
                f"|{op.label}{_leaf_signature(op)}|{deps}"
            )
        elif isinstance(gid, SourceId):
            line = "S"
        elif isinstance(gid, SinkId):
            line = f"K|{index[graph.get_sink_dependency(gid)]}"
        else:  # pragma: no cover - no other id kinds exist
            line = f"?|{gid!r}"
        h.update(line.encode())
        h.update(b"\n")
    return h.hexdigest()


def topo_node_index(graph) -> Dict[object, int]:
    """NodeId -> linearized index (the persistent node address)."""
    from ..workflow import analysis
    from ..workflow.graph import NodeId

    return {
        gid: i
        for i, gid in enumerate(analysis.linearize(graph))
        if isinstance(gid, NodeId)
    }


# ---------------------------------------------------------------------------
# The pending plan: rules deposit decisions here during one fit
# ---------------------------------------------------------------------------


@dataclass
class PendingPlan:
    store: object  # ProfileStore
    #: solver decision: {"fp", "node_idx", "node_id", "shape", "chosen",
    #:  "units", "sampled"}
    solver: Optional[Dict] = None
    #: cache plan: {"fp", "graph", "budget", "strategy", "selected",
    #:  "source", "nodes": {node_id_str: {...}}}
    autocache: Optional[Dict] = None
    #: sampling executions performed while planning this fit
    sampling_executions: int = 0
    #: tracer span count when this fit opened — finalize joins only
    #: against spans recorded after it, so a long-lived KEYSTONE_TRACE
    #: tracer doesn't leak earlier fits' observations (same small-int
    #: NodeIds) into this fit's evidence
    span_watermark: int = 0


_local = threading.local()


def current_plan() -> Optional[PendingPlan]:
    return getattr(_local, "plan", None)


@contextlib.contextmanager
def pending_plan(store):
    """Arm a PendingPlan for the calling thread's fit (no-op without a
    store). Yields the plan (or None)."""
    if store is None or current_plan() is not None:
        yield None
        return
    plan = PendingPlan(store=store)
    _local.plan = plan
    try:
        yield plan
    finally:
        _local.plan = None


# ---------------------------------------------------------------------------
# Finalize: join plan vs observation, update the store, re-plan
# ---------------------------------------------------------------------------


def finalize(plan: Optional[PendingPlan], tracer) -> None:
    """Close the loop after a fit. Never raises — a failed profile update
    must not fail a fit that already produced a model."""
    if plan is None or plan.store is None or tracer is None:
        return
    try:
        _finalize(plan, tracer)
    except Exception:
        logger.warning("cost: trace-informed re-plan failed", exc_info=True)


def _finalize(plan: PendingPlan, tracer) -> None:
    from ..obs.audit import observed_by_node
    from .model import CostEstimator

    observed = observed_by_node(tracer, start=plan.span_watermark)
    estimator = CostEstimator(plan.store)

    # -- solver evidence -------------------------------------------------
    if plan.solver is not None:
        sol = plan.solver
        obs = observed.get(str(sol["node_id"]))
        if obs is not None and obs["seconds"] > 0:
            estimator.observe_solver(
                sol["chosen"], float(sol["units"]), obs["seconds"]
            )
        plan.store.update(
            f"solver/{sol['fp']}",
            lambda rec: {
                "version": PLAN_VERSION,
                "node_idx": int(sol["node_idx"]),
                "shape": sol["shape"],
                "chosen": sol["chosen"],
                "observed_seconds": (
                    None if obs is None else round(obs["seconds"], 6)
                ),
            },
        )

    # -- per-node evidence + cache re-plan -------------------------------
    if plan.autocache is None:
        return
    ac = plan.autocache
    graph = ac["graph"]
    index = topo_node_index(graph)
    node_at = {i: n for n, i in index.items()}
    nodes_rec: Dict[str, Dict] = {}
    replan_input = {}
    class_obs: Dict[str, List] = {}
    n_full = max(int(ac.get("full_n", 1)), 1)
    for node_id_str, meta in ac["nodes"].items():
        obs = observed.get(node_id_str)
        est_ns = meta.get("est_ns")
        row = {
            "idx": meta["idx"],
            "label": meta["label"],
            "op_class": meta["op_class"],
            "n": n_full,
            "observed": obs is not None,
        }
        if obs is not None:
            row["seconds"] = round(obs["seconds"], 6)
            row["bytes"] = obs["bytes"] if obs["bytes"] is not None else (
                meta.get("est_bytes") or 0.0
            )
            if est_ns:
                # the measured sample-to-full ratio for THIS node — the
                # per-node correction the next sampled extrapolation applies
                row["ratio"] = round(obs["seconds"] * 1e9 / est_ns, 6)
            if not meta.get("leaf"):
                # fold per class AFTER the loop: one store round-trip per
                # operator class, not one per node
                class_obs.setdefault(meta["op_class"], []).append(
                    (n_full, obs["seconds"], obs["bytes"])
                )
        else:
            # fused away or never pulled: carry the estimate forward so
            # the next run's evidence plan still covers the node. A node
            # with NEITHER estimate nor observation stores 0.0 seconds —
            # deliberately equivalent to the sampled path, where a node
            # absent from the profiles is likewise never a cache
            # candidate (zero save == never selected by the greedy).
            row["seconds"] = (est_ns or 0.0) / 1e9
            row["bytes"] = meta.get("est_bytes") or 0.0
        nodes_rec[str(meta["idx"])] = row
        node = node_at.get(meta["idx"])
        if node is not None:
            from ..workflow.autocache import Profile

            replan_input[node] = Profile(
                float(row["seconds"]) * 1e9, float(row["bytes"] or 0.0)
            )

    for op_class, observations in class_obs.items():
        estimator.observe_nodes(op_class, observations)

    plan.store.update(
        f"plan/{ac['fp']}",
        lambda rec: {
            "version": PLAN_VERSION,
            "strategy": ac["strategy"],
            "budget": ac["budget"],
            "full_n": n_full,
            "source": ac["source"],
            "nodes": nodes_rec,
        },
    )

    _replan_cache(plan, tracer, graph, replan_input)


def _replan_cache(plan: PendingPlan, tracer, graph, profiles) -> None:
    """Re-run the greedy cache selection on observed costs and log the
    delta vs the plan that actually executed."""
    from ..workflow.autocache import AutoCacheRule

    ac = plan.autocache
    planned: Set = set(ac["selected"])
    if ac["strategy"] != "greedy" or not profiles:
        return
    rule = AutoCacheRule("greedy", ac["budget"])
    evidence = rule._select_greedy(graph, profiles, float(ac["budget"]))
    added = sorted(
        graph.get_operator(n).label for n in evidence - planned
    )
    removed = sorted(
        graph.get_operator(n).label for n in planned - evidence
    )
    changed = bool(added or removed)
    with tracer.span(
        "cost.replan",
        op_type="AutoCacheRule",
        plan_changed=changed,
        added=",".join(added),
        removed=",".join(removed),
        nodes=len(profiles),
    ):
        pass
    if changed:
        logger.info(
            "cost re-plan: observed costs change the cache plan "
            "(+%s / -%s) — next fit of this pipeline uses the evidence plan",
            added or "none", removed or "none",
        )
    else:
        logger.info(
            "cost re-plan: observed costs confirm the cache plan "
            "(%d nodes priced)", len(profiles),
        )


# ---------------------------------------------------------------------------
# Planning-side reads: evidence in, sampling out
# ---------------------------------------------------------------------------


def stored_solver_shape(store, fp: str, node_idx: int):
    """The shape signature observed for this pipeline's solver node in a
    previous run, or None."""
    from .model import ShapeSignature

    if store is None:
        return None
    rec = store.load(f"solver/{fp}")
    if not rec or rec.get("version") != PLAN_VERSION:
        return None
    if int(rec.get("node_idx", -1)) != int(node_idx):
        return None
    return ShapeSignature.from_record(rec.get("shape") or {})


#: sentinel: "caller did not preload the plan record" (None is a real miss)
_UNLOADED = object()


def load_plan_record(store, fp: str):
    """The raw ``plan/<fp>`` record (or None) — load once and hand to both
    :func:`stored_profiles` and :func:`stored_calibration` via ``rec=``."""
    return store.load(f"plan/{fp}") if store is not None else None


def stored_profiles(
    store, graph, full_n: int,
    fp: Optional[str] = None, index: Optional[Dict] = None, rec=_UNLOADED,
):
    """Per-node Profile dict for this graph from a persisted plan record,
    or None unless EVERY current node is covered (label-checked). Seconds
    scale by the current/recorded item-count ratio. ``fp``/``index``/``rec``
    skip re-fingerprinting/re-linearizing/re-loading when the caller
    already has them."""
    from ..workflow.autocache import Profile

    if store is None:
        return None
    fp = fp or graph_fingerprint(graph)
    if rec is _UNLOADED:
        rec = store.load(f"plan/{fp}")
    if not rec or rec.get("version") != PLAN_VERSION:
        return None
    nodes = rec.get("nodes") or {}
    if index is None:
        index = topo_node_index(graph)
    out = {}
    for node, idx in index.items():
        row = nodes.get(str(idx))
        if row is None:
            return None  # partial evidence — fall back to sampling
        if row.get("label") != graph.get_operator(node).label:
            return None  # structure drifted despite fp match (paranoia)
        n_rec = max(int(row.get("n", 1)), 1)
        scale = float(max(full_n, 1)) / n_rec
        out[node] = Profile(
            float(row.get("seconds", 0.0)) * 1e9 * scale,
            float(row.get("bytes", 0.0) or 0.0) * scale,
        )
    return out


def stored_calibration(
    store, graph, fp: Optional[str] = None, index: Optional[Dict] = None,
    rec=_UNLOADED,
) -> Dict[object, float]:
    """Per-node observed/estimated seconds ratios from the last traced
    run of this pipeline — the measured sample-to-full correction applied
    to a fresh sampled extrapolation (empty dict without evidence)."""
    if store is None:
        return {}
    if rec is _UNLOADED:
        rec = store.load(f"plan/{fp or graph_fingerprint(graph)}")
    if not rec or rec.get("version") != PLAN_VERSION:
        return {}
    nodes = rec.get("nodes") or {}
    out = {}
    if index is None:
        index = topo_node_index(graph)
    for node, idx in index.items():
        row = nodes.get(str(idx))
        if not row:
            continue
        ratio = row.get("ratio")
        if isinstance(ratio, (int, float)) and ratio > 0:
            out[node] = float(ratio)
    return out
