"""The cost model: predicted per-node execution time and memory from data
shape plus measured operator throughput.

KeystoneML's planner (PAPERS.md #1) prices each candidate physical
operator with ``max(cpu·flops, mem·bytes) + net·network`` using constants
fitted to the cluster once. Two problems carry over to any port: the
constants are global (one machine profile prices every operator), and they
never learn (a mis-priced operator stays mis-priced forever). This module
keeps the functional form — every solver still exposes
``cost(n, d, k, ...)`` work units — and closes both gaps with *learned
operator profiles*:

* **seconds-per-unit (spu)** — per solver class, the EWMA of
  ``observed fit seconds / predicted cost units`` from real traced runs.
  Predicted seconds for a candidate = its cost units × its class's spu.
  Classes without evidence borrow the geometric mean of the classes that
  have it, so one observed run calibrates the whole option set's scale
  while preserving the analytic relative ranking; with NO evidence the
  spu is 1.0 for everyone and the ranking is exactly the cold analytic
  one (backward compatible by construction).
* **per-item node throughput** — per operator class, EWMA
  seconds-per-item and bytes-per-item from executor span observations,
  replacing the flat sampled-seconds heuristic when the cache planner
  prices a node it has seen before.

Evidence lives in the :class:`~keystone_tpu.cost.store.ProfileStore`
under ``op/<OperatorClass>`` keys (backend + device-kind isolated).
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, replace
from typing import Dict, Optional, Sequence

logger = logging.getLogger(__name__)

#: EWMA weight of a NEW observation when merging into stored evidence.
#: High enough that a regressed operator re-prices within a few runs, low
#: enough that one noisy run cannot flip a stable plan.
EWMA_ALPHA = 0.5


@dataclass(frozen=True)
class ShapeSignature:
    """What the chooser needs to know about a solve: the design-matrix
    shape (n, d), label width k, sparsity, whether the input arrives as
    out-of-core chunks, and the mesh size."""

    n: int
    d: int
    k: int
    sparsity: float = 1.0
    chunked: bool = False
    machines: int = 1

    def with_n(self, n: int) -> "ShapeSignature":
        return replace(self, n=int(n))

    def to_record(self) -> Dict:
        return {
            "n": int(self.n), "d": int(self.d), "k": int(self.k),
            "sparsity": float(self.sparsity), "chunked": bool(self.chunked),
            "machines": int(self.machines),
        }

    @staticmethod
    def from_record(rec: Dict) -> Optional["ShapeSignature"]:
        try:
            return ShapeSignature(
                n=int(rec["n"]), d=int(rec["d"]), k=int(rec["k"]),
                sparsity=float(rec.get("sparsity", 1.0)),
                chunked=bool(rec.get("chunked", False)),
                machines=int(rec.get("machines", 1)),
            )
        except (KeyError, TypeError, ValueError):
            return None


def op_key(op_or_class) -> str:
    """Store key for one operator class: ``op/<ClassName>``."""
    cls = op_or_class if isinstance(op_or_class, type) else type(op_or_class)
    return f"op/{cls.__name__}"


def ewma(old: Optional[float], new: float, alpha: float = EWMA_ALPHA) -> float:
    if old is None or not math.isfinite(old):
        return float(new)
    return float(alpha * new + (1.0 - alpha) * old)


class CostEstimator:
    """Prices solver candidates and previously-seen nodes from the
    profile store; degrades to the analytic cost model when the store is
    absent or empty."""

    def __init__(self, store=None):
        self.store = store

    # -- solver pricing -------------------------------------------------

    def seconds_per_unit(self, op_class) -> Optional[float]:
        """Learned spu for one solver class, or None without evidence."""
        if self.store is None:
            return None
        rec = self.store.load(op_key(op_class))
        if not rec:
            return None
        spu = rec.get("spu")
        if isinstance(spu, (int, float)) and spu > 0 and math.isfinite(spu):
            return float(spu)
        return None

    def solver_costs(
        self,
        options: Sequence,
        shape: ShapeSignature,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
    ) -> Dict[str, Dict[str, Optional[float]]]:
        """Per-option pricing: analytic cost ``units`` (the reference's
        functional form, each option's own ``cost`` method) and predicted
        wall-clock ``seconds`` (units × learned spu; None when no option
        has evidence). Options that cannot fit the given shape (no
        streaming path for a chunked input) price to +inf units."""
        units: Dict[str, float] = {}
        spus: Dict[str, Optional[float]] = {}
        for opt in options:
            label = type(opt).__name__
            if shape.chunked and not getattr(opt, "supports_streaming", False):
                units[label] = math.inf
                spus[label] = None
                continue
            units[label] = float(
                opt.cost(
                    shape.n, shape.d, shape.k, shape.sparsity, shape.machines,
                    cpu_weight, mem_weight, network_weight,
                )
            )
            spus[label] = self.seconds_per_unit(type(opt))
        known = [s for s in spus.values() if s is not None]
        fallback = (
            math.exp(sum(math.log(s) for s in known) / len(known))
            if known else None
        )
        out: Dict[str, Dict[str, Optional[float]]] = {}
        for label, u in units.items():
            spu = spus[label] if spus[label] is not None else fallback
            out[label] = {
                "units": u,
                "spu": spus[label],
                "seconds": (
                    None if spu is None or not math.isfinite(u) else u * spu
                ),
                "learned": spus[label] is not None,
            }
        return out

    # -- node pricing ---------------------------------------------------

    def node_profile_ns(self, op_class_name: str, n_items: int):
        """(ns, bytes) for ``n_items`` through one operator class from
        stored per-item throughput, or None without evidence."""
        if self.store is None:
            return None
        rec = self.store.load(f"op/{op_class_name}")
        if not rec:
            return None
        spi = rec.get("seconds_per_item")
        bpi = rec.get("bytes_per_item")
        if not isinstance(spi, (int, float)) or spi < 0:
            return None
        if not isinstance(bpi, (int, float)) or bpi < 0:
            # no bytes evidence (the class's output was never observed
            # materialized): pricing it 0 bytes would hand the greedy
            # planner a "free" cache candidate it always selects — skip
            return None
        return (float(spi) * n_items * 1e9, float(bpi) * n_items)

    # -- evidence updates -----------------------------------------------

    def observe_solver(
        self, op_class_name: str, units: float, seconds: float
    ) -> None:
        """Fold one measured fit into the class's spu EWMA."""
        if self.store is None or units <= 0 or seconds <= 0:
            return

        def merge(rec):
            rec = dict(rec or {})
            rec["spu"] = ewma(rec.get("spu"), seconds / units)
            rec["solver_observations"] = int(
                rec.get("solver_observations", 0)
            ) + 1
            return rec

        self.store.update(f"op/{op_class_name}", merge)

    def observe_node(
        self,
        op_class_name: str,
        n_items: int,
        seconds: float,
        out_bytes: Optional[float],
    ) -> None:
        """Fold one observed node execution into the class's per-item
        throughput EWMA."""
        self.observe_nodes(op_class_name, [(n_items, seconds, out_bytes)])

    def observe_nodes(
        self,
        op_class_name: str,
        observations,
    ) -> None:
        """Fold several ``(n_items, seconds, out_bytes)`` observations into
        the class's per-item throughput EWMAs with ONE store round-trip —
        a pipeline often has many nodes of one class, and a per-node
        ``update()`` would re-read and atomically rewrite the same
        ``op/<Class>`` file once per node at the end of every fit."""
        if self.store is None:
            return
        obs = [
            (n, s, b) for n, s, b in observations if n > 0 and s >= 0
        ]
        if not obs:
            return

        def merge(rec):
            rec = dict(rec or {})
            for n_items, seconds, out_bytes in obs:
                rec["seconds_per_item"] = ewma(
                    rec.get("seconds_per_item"), seconds / n_items
                )
                if out_bytes is not None:
                    rec["bytes_per_item"] = ewma(
                        rec.get("bytes_per_item"), float(out_bytes) / n_items
                    )
                rec["node_observations"] = (
                    int(rec.get("node_observations", 0)) + 1
                )
            return rec

        self.store.update(f"op/{op_class_name}", merge)
