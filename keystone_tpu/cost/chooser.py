"""Per-node solver selection from predicted cost.

The chooser is the decision point between the cost model and the graph:
given a candidate option set (the auto-solver's physical implementations)
and a :class:`~keystone_tpu.cost.model.ShapeSignature`, it prices every
option through :class:`~keystone_tpu.cost.model.CostEstimator` and picks
the cheapest — analytic units when cold, predicted wall-clock seconds
once the profile store holds evidence. Chunked (out-of-core) inputs
restrict the field to options with a streaming fit path.

Every choice is observable: a ``cost.estimate`` span records the shape,
the winner, and whether evidence participated; when a DAG node id is
known the prediction is also recorded as a tracer *estimate* row, so the
estimate-vs-observed audit (``obs/audit.py``) covers solver nodes exactly
like Cacher-annotated ones.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

from ..obs import tracer as obs_tracer
from .model import CostEstimator, ShapeSignature

logger = logging.getLogger(__name__)


@dataclass
class SolverChoice:
    """One selection: the winning option plus the full pricing table."""

    chosen: object
    label: str
    shape: ShapeSignature
    #: per-option {"units", "spu", "seconds", "learned"} (see
    #: CostEstimator.solver_costs)
    costs: Dict[str, Dict[str, Optional[float]]] = field(default_factory=dict)
    #: "learned" when stored evidence priced at least one option,
    #: else "cold" (analytic units only)
    source: str = "cold"

    @property
    def est_seconds(self) -> Optional[float]:
        return self.costs.get(self.label, {}).get("seconds")


class SolverChooser:
    """Ranks solver options by predicted cost; see module docstring."""

    def __init__(self, estimator: Optional[CostEstimator] = None):
        if estimator is None:
            from . import get_estimator

            estimator = get_estimator()
        self.estimator = estimator

    def choose(
        self,
        options: Sequence,
        shape: ShapeSignature,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
        node_id: Optional[str] = None,
        owner_label: str = "solver",
    ) -> SolverChoice:
        if not options:
            raise ValueError("no solver options to choose from")
        costs = self.estimator.solver_costs(
            options, shape, cpu_weight, mem_weight, network_weight
        )

        def rank(opt) -> float:
            row = costs[type(opt).__name__]
            if row["seconds"] is not None:
                return row["seconds"]
            u = row["units"]
            return u if math.isfinite(u) else math.inf

        viable = [o for o in options if math.isfinite(rank(o))]
        if not viable:
            # every option priced out (e.g. chunked input, no streaming
            # solver registered) — keep the first option rather than fail;
            # its fit will raise a real error if it truly cannot run
            logger.warning(
                "%s: no viable solver for %s — keeping %s",
                owner_label, shape, type(options[0]).__name__,
            )
            viable = [options[0]]
        chosen = min(viable, key=rank)
        label = type(chosen).__name__
        learned = any(row["learned"] for row in costs.values())
        choice = SolverChoice(
            chosen=chosen,
            label=label,
            shape=shape,
            costs=costs,
            source="learned" if learned else "cold",
        )
        self._record(choice, node_id, owner_label)
        return choice

    @staticmethod
    def _record(
        choice: SolverChoice, node_id: Optional[str], owner_label: str
    ) -> None:
        tracer = obs_tracer.current()
        if tracer is None:
            return
        with tracer.span(
            "cost.estimate",
            node_id=node_id,
            op_type=owner_label,
            solver=choice.label,
            source=choice.source,
            n=choice.shape.n,
            d=choice.shape.d,
            k=choice.shape.k,
            chunked=choice.shape.chunked,
        ):
            pass
        if node_id is not None:
            est = choice.est_seconds
            tracer.record_node_estimate(
                node_id,
                choice.label,
                est_seconds=None if est is None else float(est),
                # the fitted model is the node's materialized result
                est_bytes=float(choice.shape.d * choice.shape.k * 4),
                cacher=False,
                kind="solver",
                solver=choice.label,
                # survives a later overwrite of est_seconds by the cache
                # planner's node-level extrapolation (extras are preserved)
                solver_est_seconds=None if est is None else float(est),
                source=choice.source,
                alternatives={
                    lbl: row["seconds"] if row["seconds"] is not None
                    else (row["units"] if math.isfinite(row["units"]) else None)
                    for lbl, row in choice.costs.items()
                },
            )
