"""The persistent operator-profile store: learned cost evidence on disk.

One record = one JSON file = one profile key for one environment
(backend + device kind). The same discipline as ``compile/cache.py``:

* **atomic writes** — records are written to a same-directory temp file
  and ``os.replace``d into place; a concurrent reader sees the old
  record, the new record, or a miss — never a torn file.
* **corruption tolerance** — a magic marker, the embedded key, and a
  sha256 checksum of the canonical record JSON are validated on load;
  any mismatch (truncation, bit rot, a foreign file) logs, best-effort
  deletes the file, and reports a miss so the caller falls back to
  sampling.
* **environment isolation** — the filename embeds a digest of the
  producing environment and the payload embeds the environment itself,
  so a CPU-backend profile can never be read as TPU evidence (and two
  backends' stores coexist in one directory).

Key namespaces (see ``keystone_tpu/cost/__init__.py`` for the layout):

* ``op/<OperatorClass>`` — class-level throughput evidence (EWMA
  seconds-per-cost-unit for solvers, seconds/bytes-per-item for
  transformers), the KeystoneML "operator profile".
* ``solver/<graph-fp>`` — the shape signature + chosen solver observed
  for one pipeline's auto-solver node.
* ``plan/<graph-fp>`` — per-node observed costs for one pipeline, the
  evidence the cache planner re-plans from without sampling.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

_MAGIC = "KSPROF1"
_SUFFIX = ".json"
_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def profile_environment() -> Dict[str, str]:
    """What must match for a profile to be applicable evidence: the
    backend and the device kind. Narrower than the AOT cache's key (jax
    version changes invalidate an executable, not a throughput
    measurement)."""
    import jax

    devices = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "unknown",
    }


def _canonical(record: Dict) -> str:
    return json.dumps(record, sort_keys=True, separators=(",", ":"))


class ProfileStore:
    """Directory-rooted, multi-process-safe profile record store."""

    def __init__(self, root: str, env: Optional[Dict[str, str]] = None):
        self.root = os.path.abspath(os.path.expanduser(root))
        # env resolves lazily: profile_environment() touches jax.devices(),
        # which initializes the backend — construction happens at
        # configure() time, BEFORE --backend/--cpuDevices pick a platform
        self._env = dict(env) if env is not None else None
        self._digest: Optional[str] = None
        os.makedirs(self.root, exist_ok=True)

    @property
    def env(self) -> Dict[str, str]:
        if self._env is None:
            self._env = profile_environment()
        return self._env

    @property
    def _env_digest(self) -> str:
        if self._digest is None:
            self._digest = hashlib.sha256(
                _canonical(self.env).encode()
            ).hexdigest()[:8]
        return self._digest

    # -- paths ----------------------------------------------------------

    def path(self, key: str) -> str:
        if not key:
            raise ValueError(f"invalid profile key {key!r}")
        safe = _SAFE.sub("_", key.replace("/", "."))
        digest = hashlib.sha256(key.encode()).hexdigest()[:12]
        return os.path.join(
            self.root, f"{safe}-{digest}-{self._env_digest}{_SUFFIX}"
        )

    # -- store ----------------------------------------------------------

    def store(self, key: str, record: Dict) -> str:
        """Atomically persist one record. IO failures propagate — callers
        treat a failed store as non-fatal (planning still works, it just
        stays sampled)."""
        doc = {
            "magic": _MAGIC,
            "key": key,
            "env": self.env,
            "record": record,
            "sha256": hashlib.sha256(_canonical(record).encode()).hexdigest(),
        }
        path = self.path(key)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp-prof-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, sort_keys=True, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic on POSIX: readers see old XOR new
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    # -- load -----------------------------------------------------------

    def load(self, key: str) -> Optional[Dict]:
        """Load + validate one record. Returns None on miss, corruption,
        or environment mismatch — never raises for on-disk problems."""
        path = self.path(key)
        try:
            with open(path, "r") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            self._discard(path, "unreadable/corrupt")
            return None
        record = self._validate(key, doc)
        if record is None:
            self._discard(path, "corrupt")
            return None
        if doc.get("env") != self.env:
            # evidence from another backend/device — stale, not corrupt;
            # unreachable through path() (the filename embeds the env
            # digest) but guards hand-copied files
            logger.info(
                "profile store: environment mismatch for %s (%s, want %s)",
                key, doc.get("env"), self.env,
            )
            return None
        return record

    @staticmethod
    def _validate(key: str, doc) -> Optional[Dict]:
        try:
            if not isinstance(doc, dict) or doc.get("magic") != _MAGIC:
                return None
            if doc.get("key") != key:
                return None  # renamed / foreign file
            record = doc.get("record")
            if not isinstance(record, dict):
                return None
            digest = hashlib.sha256(_canonical(record).encode()).hexdigest()
            if doc.get("sha256") != digest:
                return None  # bit rot / torn copy
            return record
        except Exception:
            # unreadable record degrades to a discarded miss by contract
            logger.debug("profile store: unreadable record", exc_info=True)
            return None

    def _discard(self, path: str, why: str) -> None:
        logger.warning("profile store: discarding %s record %s", why, path)
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- read-modify-write ---------------------------------------------

    def update(
        self, key: str, fn: Callable[[Optional[Dict]], Dict]
    ) -> Optional[Dict]:
        """Read-modify-write one record: ``fn`` receives the current
        record (or None on miss) and returns the replacement. Concurrent
        writers are safe (atomic replace; last writer wins per file).
        Store failures log and return None — profile updates must never
        fail a fit."""
        try:
            record = fn(self.load(key))
            self.store(key, record)
            return record
        except Exception:
            logger.warning("profile store: update of %s failed", key,
                           exc_info=True)
            return None

    # -- maintenance ----------------------------------------------------

    def keys(self) -> List[str]:
        """Embedded keys of every valid record in THIS environment."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if not name.endswith(_SUFFIX) or name.startswith("."):
                continue
            try:
                with open(os.path.join(self.root, name), "r") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            key = doc.get("key")
            if isinstance(key, str) and self._validate(key, doc) is not None \
                    and doc.get("env") == self.env:
                out.append(key)
        return sorted(out)
