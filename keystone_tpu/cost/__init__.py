"""Cost-model subsystem: learned operator profiles, per-node solver
selection, trace-informed re-planning.

KeystoneML's core contribution (PAPERS.md #1) is not the DAG executor —
it is the cost model that picks each node's physical implementation and
the caching plan from operator profiles. This package is that loop,
closed: the optimizer's decisions are priced by
:class:`~keystone_tpu.cost.model.CostEstimator`, chosen by
:class:`~keystone_tpu.cost.chooser.SolverChooser`, observed by the tracer
(``obs/``), and fed back through
:mod:`~keystone_tpu.cost.replan` into a persistent
:class:`~keystone_tpu.cost.store.ProfileStore` — so the second fit of any
pipeline is planned from evidence, not samples.

Store layout (one JSON record per file, atomic + checksummed +
backend/device-kind isolated; see ``cost/store.py``):

* ``op/<OperatorClass>`` — class-level throughput: ``spu`` (EWMA seconds
  per analytic cost unit, solvers), ``seconds_per_item`` /
  ``bytes_per_item`` (EWMA node throughput), observation counts.
* ``solver/<graph-fp>`` — the auto-solver node's observed shape
  signature + chosen implementation for one pipeline.
* ``plan/<graph-fp>`` — per-node observed seconds/bytes (+ the measured
  estimate-vs-observed ``ratio``) for one pipeline: the evidence the
  cache planner plans from with zero sampling executions.
* ``plan/segment/<segment-digest>`` — per-segment compile-vs-run
  evidence for segment-compiled execution (``cost/segments.py``): the
  adaptive-boundary policy that splits a segment back to node dispatch
  when its compile cost swamps its dispatch savings.

Knobs: ``KEYSTONE_PROFILE_DIR=<dir>`` (or ``--profiles`` on the CLI, or
``utils.obs.configure(profiles=...)``) enables the store. Without it the
subsystem stays cold: choices fall back to the analytic cost model and
nothing touches disk.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

from .chooser import SolverChoice, SolverChooser
from .model import CostEstimator, ShapeSignature, op_key
from .replan import (
    PendingPlan,
    current_plan,
    finalize,
    graph_fingerprint,
    pending_plan,
)
from .store import ProfileStore, profile_environment

__all__ = [
    "CostEstimator",
    "PendingPlan",
    "ProfileStore",
    "ShapeSignature",
    "SolverChoice",
    "SolverChooser",
    "configure",
    "current_plan",
    "finalize",
    "get_estimator",
    "get_store",
    "graph_fingerprint",
    "op_key",
    "pending_plan",
    "profile_environment",
    "reset",
    "sampling_executions",
    "count_sampling",
    "reset_sampling",
]

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_store: Optional[ProfileStore] = None
_initialized = False  # False => next get_store() reads KEYSTONE_PROFILE_DIR


def configure(path: Optional[str] = None) -> Optional[ProfileStore]:
    """Install the process-wide profile store. ``path=None`` follows
    ``KEYSTONE_PROFILE_DIR`` (unset or empty ⇒ profile learning off).
    An unusable directory degrades to off, never a crash."""
    global _store, _initialized
    with _lock:
        _initialized = True
        if path is None:
            from ..utils import env_str

            path = env_str("KEYSTONE_PROFILE_DIR")
        if not path:
            _store = None
            return None
        try:
            _store = ProfileStore(path)
        except Exception:
            logger.warning(
                "cost: profile dir %r unusable — profile learning disabled",
                path, exc_info=True,
            )
            _store = None
            return None
        return _store


def get_store() -> Optional[ProfileStore]:
    """The installed store, or None (cold). Lazily honors
    ``KEYSTONE_PROFILE_DIR`` like ``compile.get_cache``."""
    if not _initialized:
        return configure()
    return _store


def get_estimator() -> CostEstimator:
    """A CostEstimator over the installed store (store-less when cold)."""
    return CostEstimator(get_store())


def reset() -> None:
    """Forget the installed store AND the env memo (test hygiene)."""
    global _store, _initialized
    with _lock:
        _store = None
        _initialized = False
    reset_sampling()


# ---------------------------------------------------------------------------
# Sampling-execution accounting: how many sampled-scale executions the
# planner paid for this process (zero on an evidence-planned fit)
# ---------------------------------------------------------------------------

_sampling_lock = threading.Lock()
_sampling: Dict[str, int] = {}


def count_sampling(kind: str, n: int = 1) -> None:
    """Record ``n`` sampled-scale executions of ``kind`` (e.g.
    ``node_optimization``, ``autocache``)."""
    with _sampling_lock:
        _sampling[kind] = _sampling.get(kind, 0) + n
    plan = current_plan()
    if plan is not None:
        plan.sampling_executions += n


def sampling_executions() -> Dict[str, int]:
    """Per-kind counts of sampled-scale executions since the last reset
    (plus a ``"total"`` roll-up)."""
    with _sampling_lock:
        out = dict(_sampling)
    out["total"] = sum(out.values())
    return out


def reset_sampling() -> None:
    with _sampling_lock:
        _sampling.clear()
