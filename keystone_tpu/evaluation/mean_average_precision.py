"""VOC-style Mean Average Precision (11-point interpolation).

Parity: evaluation/MeanAveragePrecisionEvaluator.scala:13-96 (itself based on
the enceval toolkit MATLAB code). The reference's groupByKey-per-class
shuffle becomes a vectorized per-class sort on one host — the score matrix is
(n_images, n_classes), tiny by definition of the evaluator.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .base import Evaluator, resolve


class MeanAveragePrecisionEvaluator(Evaluator):
    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, predictions: Any, actuals: Any) -> np.ndarray:
        """predictions: (n, num_classes) scores; actuals: per-item label sets.
        Returns per-class AP vector (mean of it = MAP)."""
        scores = np.asarray(resolve(predictions), dtype=np.float64)
        actual_sets = [np.atleast_1d(np.asarray(a)) for a in actuals]
        n = scores.shape[0]
        if len(actual_sets) != n:
            raise ValueError("predictions and actuals differ in length")

        gt = np.zeros((n, self.num_classes))
        for i, labels in enumerate(actual_sets):
            gt[i, labels.astype(np.int64)] = 1.0

        aps = np.zeros(self.num_classes)
        for cl in range(self.num_classes):
            order = np.argsort(-scores[:, cl], kind="stable")
            g = gt[order, cl]
            tps = np.cumsum(g)
            fps = np.cumsum(1.0 - g)
            total = g.sum()
            if total == 0:
                aps[cl] = 0.0
                continue
            recalls = tps / total
            precisions = tps / (tps + fps)
            # 11-point interpolated AP (getAP, :84-96); exact x/10 levels —
            # np.arange drifts (0.30000000000000004) and misses exact recalls
            ap = 0.0
            for x in range(11):
                t = x / 10.0
                mask = recalls >= t
                ap += (precisions[mask].max() if mask.any() else 0.0) / 11.0
            aps[cl] = ap
        return aps
