"""Vote-merging evaluation over augmented examples (crops/flips of the same
source image scored separately, then aggregated per source).

Parity: evaluation/AugmentedExamplesEvaluator.scala:14-90 — group
predictions by source-image name, aggregate with the ``average`` or
``borda`` policy, argmax, then standard multiclass metrics. The reference's
groupByKey shuffle becomes a host-side index grouping plus one vectorized
aggregation per policy.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .base import Evaluator, resolve
from .multiclass import MulticlassClassifierEvaluator, MulticlassMetrics


class AugmentedExamplesEvaluator(Evaluator):
    """``names[i]`` identifies the source example of prediction row i."""

    def __init__(self, names: Sequence, num_classes: int,
                 policy: str = "average"):
        if policy not in ("average", "borda"):
            raise ValueError("policy must be 'average' or 'borda'")
        self.names = list(names)
        self.num_classes = num_classes
        self.policy = policy

    @staticmethod
    def _average(preds: np.ndarray) -> np.ndarray:
        return preds.mean(axis=0)

    @staticmethod
    def _borda(preds: np.ndarray) -> np.ndarray:
        # rank positions per augmented copy, summed
        # (AugmentedExamplesEvaluator.scala:30-38)
        order = np.argsort(preds, axis=1)
        ranks = np.empty_like(order)
        ncols = preds.shape[1]
        np.put_along_axis(
            ranks, order, np.broadcast_to(np.arange(ncols), preds.shape), axis=1
        )
        return ranks.sum(axis=0).astype(np.float64)

    def evaluate(self, predictions: Any, actuals: Any) -> MulticlassMetrics:
        preds = np.asarray(resolve(predictions), dtype=np.float64)
        acts = np.asarray(resolve(actuals)).ravel().astype(np.int64)
        if len(self.names) != preds.shape[0]:
            raise ValueError("names must align with predictions")
        agg = self._borda if self.policy == "borda" else self._average

        groups: dict = {}
        for i, name in enumerate(self.names):
            groups.setdefault(name, []).append(i)
        final_preds, final_actuals = [], []
        for name, idxs in groups.items():
            rows = preds[idxs]
            labels = acts[idxs]
            if len(set(labels.tolist())) != 1:
                raise AssertionError(
                    f"augmented copies of {name!r} have inconsistent labels"
                )
            final_preds.append(agg(rows))
            final_actuals.append(labels[0])
        final = np.argmax(np.stack(final_preds), axis=1)
        return MulticlassClassifierEvaluator(self.num_classes).evaluate(
            final, np.asarray(final_actuals)
        )
