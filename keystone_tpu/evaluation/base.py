"""Evaluator base (parity: evaluation/Evaluator.scala:19 — accepts any mix of
raw collections, Datasets and lazy PipelineDatasets for both arguments)."""

from __future__ import annotations

from typing import Any

import numpy as np


def resolve(x: Any) -> np.ndarray:
    """Materialize predictions/labels: PipelineDataset → Dataset → array."""
    from ..data.dataset import Dataset
    from ..workflow.pipeline import PipelineResult

    if isinstance(x, PipelineResult):
        x = x.get()
    if isinstance(x, Dataset):
        x = x.to_array()
    return np.asarray(x)


class Evaluator:
    def evaluate(self, predictions: Any, labels: Any):
        raise NotImplementedError
