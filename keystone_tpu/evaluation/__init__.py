from .base import Evaluator
from .mean_average_precision import MeanAveragePrecisionEvaluator
from .multiclass import MulticlassClassifierEvaluator, MulticlassMetrics

__all__ = ["Evaluator", "MeanAveragePrecisionEvaluator", "MulticlassClassifierEvaluator", "MulticlassMetrics"]
