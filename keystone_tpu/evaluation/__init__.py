from .base import Evaluator
from .multiclass import MulticlassClassifierEvaluator, MulticlassMetrics

__all__ = ["Evaluator", "MulticlassClassifierEvaluator", "MulticlassMetrics"]
