"""Multiclass classification metrics.

Parity: evaluation/MulticlassClassifierEvaluator.scala:23,130 — a one-pass
confusion matrix plus the per-class / micro / macro statistics derived from
it. The confusion-matrix build is a single device-side scatter-add (the
reference's map + reduce over (pred, actual) pairs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np

from .base import Evaluator, resolve


@dataclass
class BinaryMetrics:
    """Per-class one-vs-rest counts (parity: BinaryClassificationMetrics)."""

    tp: float
    fp: float
    tn: float
    fn: float

    @property
    def accuracy(self) -> float:
        tot = self.tp + self.fp + self.tn + self.fn
        return (self.tp + self.tn) / tot if tot else 0.0

    @property
    def error(self) -> float:
        return 1.0 - self.accuracy

    @property
    def precision(self) -> float:
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    @property
    def recall(self) -> float:
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def f_score(self, beta: float = 1.0) -> float:
        p, r = self.precision, self.recall
        b2 = beta * beta
        denom = b2 * p + r
        return (1 + b2) * p * r / denom if denom else 0.0

    def merge(self, other: "BinaryMetrics") -> "BinaryMetrics":
        return BinaryMetrics(
            self.tp + other.tp, self.fp + other.fp,
            self.tn + other.tn, self.fn + other.fn,
        )


class MulticlassMetrics:
    """Derived statistics over a (actual, predicted) confusion matrix
    (parity: MulticlassMetrics, MulticlassClassifierEvaluator.scala:23-121).
    ``confusion_matrix[actual, predicted]`` counts."""

    def __init__(self, confusion_matrix):
        self.confusion_matrix = np.asarray(confusion_matrix, dtype=np.float64)
        cm = self.confusion_matrix
        self.num_classes = cm.shape[0]
        total = cm.sum()
        actual_sums = cm.sum(axis=1)
        predicted_sums = cm.sum(axis=0)
        self.class_metrics: List[BinaryMetrics] = []
        for c in range(self.num_classes):
            tp = cm[c, c]
            fp = predicted_sums[c] - tp
            tn = total - actual_sums[c] - fp
            fn = total - tp - fp - tn
            self.class_metrics.append(BinaryMetrics(tp, fp, tn, fn))

    def _class_avg(self, f) -> float:
        return sum(f(m) for m in self.class_metrics) / self.num_classes

    def _micro(self, f) -> float:
        merged = self.class_metrics[0]
        for m in self.class_metrics[1:]:
            merged = merged.merge(m)
        return f(merged)

    @property
    def avg_accuracy(self) -> float:
        return self._class_avg(lambda m: m.accuracy)

    @property
    def macro_precision(self) -> float:
        return self._class_avg(lambda m: m.precision)

    @property
    def macro_recall(self) -> float:
        return self._class_avg(lambda m: m.recall)

    def macro_f_score(self, beta: float = 1.0) -> float:
        return self._class_avg(lambda m: m.f_score(beta))

    @property
    def total_accuracy(self) -> float:
        return self._micro(lambda m: m.precision)

    @property
    def total_error(self) -> float:
        return self._micro(
            lambda m: m.fn / (m.fn + m.tp) if (m.fn + m.tp) else 0.0
        )

    @property
    def micro_precision(self) -> float:
        return self._micro(lambda m: m.precision)

    @property
    def micro_recall(self) -> float:
        return self._micro(lambda m: m.recall)

    def micro_f_score(self, beta: float = 1.0) -> float:
        return self._micro(lambda m: m.f_score(beta))

    def summary(self, class_names=None) -> str:
        """Aggregate metrics; with ``class_names``, adds the per-class
        accuracy table (parity: MulticlassMetrics.summary(classLabels),
        MulticlassClassifierEvaluator.scala:130)."""
        lines = [
            f"total accuracy: {self.total_accuracy:.3f}",
            f"total error: {self.total_error:.3f}",
            f"macro precision: {self.macro_precision:.3f}",
            f"macro recall: {self.macro_recall:.3f}",
            f"macro f1: {self.macro_f_score():.3f}",
        ]
        if class_names is not None:
            for i, name in enumerate(class_names):
                if i >= len(self.class_metrics):
                    break
                m = self.class_metrics[i]
                lines.append(
                    f"  {name}: accuracy {m.accuracy:.3f} "
                    f"precision {m.precision:.3f} recall {m.recall:.3f}"
                )
        return "\n".join(lines)


@jax.jit
def _confusion(preds, actuals, cm0):
    idx = actuals * cm0.shape[0] + preds
    flat = jnp.zeros(cm0.shape[0] * cm0.shape[1], dtype=jnp.float32)
    flat = flat.at[idx].add(1.0)
    return flat.reshape(cm0.shape)


class MulticlassClassifierEvaluator(Evaluator):
    """Build MulticlassMetrics from predicted and actual int labels
    (parity: MulticlassClassifierEvaluator.scala:130-160)."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, predictions: Any, actuals: Any) -> MulticlassMetrics:
        preds = jnp.asarray(resolve(predictions), dtype=jnp.int32).ravel()
        acts = jnp.asarray(resolve(actuals), dtype=jnp.int32).ravel()
        if preds.shape[0] != acts.shape[0]:
            raise ValueError("predictions and actuals differ in length")
        cm0 = jnp.zeros((self.num_classes, self.num_classes))
        return MulticlassMetrics(_confusion(preds, acts, cm0))
