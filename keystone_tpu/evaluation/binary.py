"""Binary classifier evaluation.

Parity: evaluation/BinaryClassifierEvaluator.scala:17-82
(BinaryClassificationMetrics contingency table + one-pass evaluator). The
reference's per-item map + merge-reduce collapses into four vectorized
counts over the prediction arrays.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .base import Evaluator, resolve


@dataclass
class BinaryClassificationMetrics:
    """(parity: BinaryClassificationMetrics case class)."""

    tp: float
    fp: float
    tn: float
    fn: float

    def merge(self, other: "BinaryClassificationMetrics"):
        return BinaryClassificationMetrics(
            self.tp + other.tp, self.fp + other.fp,
            self.tn + other.tn, self.fn + other.fn,
        )

    @property
    def accuracy(self) -> float:
        return (self.tp + self.tn) / (self.tp + self.fp + self.tn + self.fn)

    @property
    def error(self) -> float:
        return (self.fp + self.fn) / (self.tp + self.fp + self.tn + self.fn)

    @property
    def recall(self) -> float:
        return self.tp / (self.tp + self.fn) if (self.tp + self.fn) else 0.0

    @property
    def precision(self) -> float:
        return self.tp / (self.tp + self.fp) if (self.tp + self.fp) else 0.0

    @property
    def specificity(self) -> float:
        return self.tn / (self.fp + self.tn) if (self.fp + self.tn) else 0.0

    def f_score(self, beta: float = 1.0) -> float:
        num = (1.0 + beta * beta) * self.tp
        denom = (1.0 + beta * beta) * self.tp + beta * beta * self.fn + self.fp
        return num / denom if denom else 0.0

    def summary(self) -> str:
        return (
            f"Accuracy:\t{self.accuracy:2.3f}\n"
            f"Precision:\t{self.precision:2.3f}\n"
            f"Recall:\t{self.recall:2.3f}\n"
            f"Specificity:\t{self.specificity:2.3f}\n"
            f"F1:\t{self.f_score():2.3f}"
        )


class BinaryClassifierEvaluator(Evaluator):
    """One-pass contingency table from boolean predictions/actuals."""

    def evaluate(self, predictions, actuals) -> BinaryClassificationMetrics:
        pred = np.asarray(resolve(predictions)).astype(bool).ravel()
        act = np.asarray(resolve(actuals)).astype(bool).ravel()
        if pred.shape != act.shape:
            raise ValueError("predictions and actuals must align")
        return BinaryClassificationMetrics(
            tp=float(np.sum(pred & act)),
            fp=float(np.sum(pred & ~act)),
            tn=float(np.sum(~pred & ~act)),
            fn=float(np.sum(~pred & act)),
        )
