"""Pickle helpers that survive jax arrays.

``FittedPipeline`` persistence (parity: java serialization of
``FittedPipeline.scala:12-22``) uses pickle; device arrays are converted to
numpy on the way out and restored as numpy (jax ops accept numpy inputs and
re-device-put on first use).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import numpy as np


class _JaxAwarePickler(pickle.Pickler):
    def persistent_id(self, obj: Any):
        return None

    def reducer_override(self, obj: Any):
        if isinstance(obj, jax.Array):
            return (np.asarray, (np.asarray(obj),))
        return NotImplemented


def dumps(obj: Any) -> bytes:
    buf = io.BytesIO()
    _JaxAwarePickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def save_pickle(obj: Any, path: str) -> None:
    with open(path, "wb") as f:
        f.write(dumps(obj))


def load_pickle(path: str) -> Any:
    with open(path, "rb") as f:
        return loads(f.read())
