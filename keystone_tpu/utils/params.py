"""Host-side storage for transformer parameters.

Fitted models and random projections are *parameters of traced programs*:
when a node's ``trace_batch`` closes over them, jit lowering embeds their
values into the XLA module. If they live on device, that embedding does a
device→host fetch per constant in the middle of lowering — measured at
seconds per constant through a tunneled TPU, and it defeats the persistent
compilation cache's warm path. Storing parameters as numpy makes lowering
pure host work; XLA ships the literals device-ward once per compiled
program.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def as_param(x: Any, dtype: Optional[Any] = None) -> Optional[np.ndarray]:
    """Materialize ``x`` on the host as the canonical parameter form."""
    if x is None:
        return None
    try:
        import jax

        if isinstance(x, jax.Array):
            x = jax.device_get(x)
    except ImportError:  # pragma: no cover
        pass
    arr = np.asarray(x)
    if dtype is not None and arr.dtype != np.dtype(dtype):
        arr = arr.astype(dtype)
    return arr
