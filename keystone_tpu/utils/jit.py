"""Jit that is safe to call from inside other traced code.

On the tunneled TPU platform this environment runs (experimental 'axon'
backend), a function decorated with ``jax.jit`` and then CALLED FROM INSIDE
another jitted computation can miscompile: the nested call's output was
measured wildly wrong (GMM posteriors flipping 0↔1 with an 18-llh-unit
error) while the SAME body inlined into the outer trace — or the decorated
function called at top level — is correct to float32 noise. See
tests/nodes/test_nested_jit.py for the pinned repro semantics.

``nestable_jit`` gives helpers the best of both: called eagerly (host code)
they run as one compiled program; called during tracing they inline their
body into the outer program instead of emitting a nested call.
"""

from __future__ import annotations

import functools


def nestable_jit(fn=None, **jit_kwargs):
    """Like ``jax.jit``, but inlines when already inside a trace."""
    if fn is None:
        return lambda f: nestable_jit(f, **jit_kwargs)

    import jax

    jitted = jax.jit(fn, **jit_kwargs)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return fn(*args, **kwargs)
        return jitted(*args, **kwargs)

    wrapper.__wrapped__ = fn
    return wrapper
