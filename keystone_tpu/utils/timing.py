"""Per-phase wall-clock instrumentation for the hot solvers.

Parity: the reference logs per-block phase times in its hot loops —
kernelGen/residual/collect/localSolve/modelUpdate in
``nodes/learning/KernelRidgeRegression.scala:216-224`` and pipeline totals in
``MnistRandomFFT.scala:31,66-67``. Here a process-global registry accumulates
named phase durations; solvers wrap their phases in :func:`phase`, the bench
reads :func:`snapshot`, and everything logs at INFO.

jax dispatch is asynchronous, so each phase exit synchronizes on the phase's
result (``block_until_ready``) when given one — otherwise device time would
be misattributed to whichever later phase first blocks.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_totals: Dict[str, float] = defaultdict(float)
_counts: Dict[str, int] = defaultdict(int)

# Profiling is OFF by default: a phase exit then only reads the wall clock
# (async dispatch keeps running ahead, so attribution is approximate but the
# hot loops stay sync-free). Enabling (KEYSTONE_PROFILE=1 or enable()) adds a
# block_until_ready per phase for accurate attribution + INFO logs.
import os as _os

_profiling = bool(_os.environ.get("KEYSTONE_PROFILE"))


def enable(on: bool = True) -> None:
    global _profiling
    _profiling = on


@contextlib.contextmanager
def phase(name: str, sync: Optional[Any] = None):
    """Time a named phase. Under profiling, ``sync`` (or a value appended to
    the yielded holder) is blocked on at exit so asynchronously-dispatched
    device work lands in the right bucket."""
    t0 = time.perf_counter()
    holder: list = []
    try:
        yield holder
    finally:
        if _profiling:
            target = holder[0] if holder else sync
            if target is not None:
                try:
                    import jax

                    jax.block_until_ready(target)
                except (ImportError, TypeError):
                    pass  # no jax / non-blockable value: nothing to sync
                except Exception:
                    # a REAL device error (stream failure, dead backend):
                    # swallowing it would silently misattribute every
                    # later phase — surface it, keep timing
                    logger.warning(
                        "phase %s: device sync failed", name, exc_info=True
                    )
        dt = time.perf_counter() - t0
        with _lock:
            _totals[name] += dt
            _counts[name] += 1
        if _profiling:
            logger.info("phase %-28s %8.4f s", name, dt)


def record(name: str, seconds: float) -> None:
    with _lock:
        _totals[name] += seconds
        _counts[name] += 1


def reset() -> None:
    """Clear phase totals AND the obs rate-limiter state: a fresh
    measurement epoch (back-to-back bench runs in one process) must get
    its first periodic log, not inherit the previous run's suppression
    window."""
    with _lock:
        _totals.clear()
        _counts.clear()
    from . import obs

    obs.reset_rate_limits()


def snapshot(prefix: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """{phase: {"seconds": total, "calls": n}} — what the bench embeds.

    ``prefix`` filters to one subsystem's phases (e.g. ``"serve."`` for
    the serving engine's metric snapshots), so a service's metrics export
    doesn't drag every solver phase of the process along."""
    with _lock:
        return {
            k: {"seconds": round(_totals[k], 4), "calls": _counts[k]}
            for k in sorted(_totals)
            if prefix is None or k.startswith(prefix)
        }
