"""Stats helpers (parity: utils/Stats.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def normalize_rows(mat, alpha: float = 1.0):
    """Subtract each row's mean and divide by sqrt(row variance + alpha);
    row variance uses ddof=1 (parity: Stats.normalizeRows,
    utils/Stats.scala:112-123)."""
    mat = jnp.asarray(mat)
    means = jnp.nan_to_num(jnp.mean(mat, axis=1, keepdims=True))
    var = jnp.sum((mat - means) ** 2, axis=1, keepdims=True) / (
        mat.shape[1] - 1.0
    )
    sds = jnp.sqrt(var + alpha)
    sds = jnp.where(jnp.isnan(sds), np.sqrt(alpha), sds)
    return (mat - means) / sds


def about_eq(a, b, thresh: float = 1e-8) -> bool:
    """Max-abs-difference approximate equality
    (parity: Stats.aboutEq, utils/Stats.scala:25-70)."""
    return bool(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b))) < thresh)


def classification_error(predicted, actual) -> float:
    """Percent mismatches (parity: Stats.classificationError,
    utils/Stats.scala:79-101)."""
    p = np.asarray(predicted).ravel()
    a = np.asarray(actual).ravel()
    return float((p != a).mean() * 100.0)
