import os

from .serialization import load_pickle, save_pickle

__all__ = ["env_flag", "env_int", "load_pickle", "save_pickle"]


def env_flag(name: str, default: bool = True) -> bool:
    """Shared truthy parsing for KEYSTONE_* switch env vars, so every knob
    (KEYSTONE_SCAN_PIPELINE, KEYSTONE_PAR_EXEC, ...) accepts the same
    spellings: unset -> ``default``; 0/false/no/off (any case) -> False."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Shared integer parsing for KEYSTONE_* sizing env vars (worker
    counts, depths): unset or unparsable -> ``default``; parsed values are
    clamped to ``minimum``."""
    raw = os.environ.get(name)
    if raw is not None:
        try:
            return max(minimum, int(raw))
        except ValueError:
            pass
    return default
