import os

from .serialization import load_pickle, save_pickle

__all__ = [
    "env_flag", "env_float", "env_int", "env_str",
    "load_pickle", "save_pickle",
]


def env_flag(name: str, default: bool = True) -> bool:
    """Shared truthy parsing for KEYSTONE_* switch env vars, so every knob
    (KEYSTONE_SCAN_PIPELINE, KEYSTONE_PAR_EXEC, ...) accepts the same
    spellings: unset -> ``default``; 0/false/no/off (any case) -> False."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


#: unparsable (name, raw) pairs already warned about — misconfiguration
#: is logged ONCE, not once per read of a hot-path knob
_warned_env: set = set()


def _warn_unparsable(name: str, raw: str, kind: str) -> None:
    import logging

    key = (name, raw)
    if key not in _warned_env:
        _warned_env.add(key)
        logging.getLogger(__name__).warning(
            "ignoring non-%s %s=%r; using the default", kind, name, raw
        )


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Shared integer parsing for KEYSTONE_* sizing env vars (worker
    counts, depths): unset or unparsable -> ``default`` (unparsable
    values are warned once); parsed values are clamped to ``minimum``."""
    raw = os.environ.get(name)
    if raw is not None:
        try:
            return max(minimum, int(raw))
        except ValueError:
            _warn_unparsable(name, raw, "integer")
    return default


def env_float(name: str, default: float, minimum: float = 0.0) -> float:
    """Shared float parsing for KEYSTONE_* knobs (backoffs, fractions):
    unset or unparsable -> ``default`` (unparsable values are warned
    once); clamped to ``minimum``."""
    raw = os.environ.get(name)
    if raw is not None:
        try:
            return max(minimum, float(raw))
        except ValueError:
            _warn_unparsable(name, raw, "float")
    return default


def env_str(name: str, default: str = None) -> str:
    """Shared string parsing for KEYSTONE_* value env vars (paths, spec
    strings): unset OR empty/whitespace -> ``default`` — so
    ``KEYSTONE_X=`` reliably means "off" instead of a confusing
    empty-string path."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    raw = raw.strip()
    return raw if raw else default
