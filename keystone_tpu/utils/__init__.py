from .serialization import load_pickle, save_pickle

__all__ = ["load_pickle", "save_pickle"]
