"""Observability configuration: one switch for logs + phase profiling.

Parity: the reference inherits its observability from Spark — log4j
config, per-stage timing in the Spark UI, and ad-hoc ``logInfo`` phase
logs in the hot solvers (e.g. KernelRidgeRegression.scala:216-224). The
counterparts here:

* ``configure(level)`` — process-wide stdlib logging with a timestamped
  single-line format (the log4j analogue). Every module already logs
  through ``logging.getLogger(__name__)``; this makes those logs visible
  and uniform.
* phase profiling — ``utils.timing`` accumulates named phase durations in
  every hot solver; under profiling each phase exit synchronizes the
  device stream so attribution is accurate, and phases log at INFO (the
  Spark-UI-stage-timing analogue).

Environment switches (read by the CLI and by ``configure(None)``):

* ``KEYSTONE_LOG=debug|info|warning|error`` — log level.
* ``KEYSTONE_PROFILE=1`` — enable phase profiling + phase logs.
* ``KEYSTONE_TRACE=/path/trace.json`` — install the pipeline tracer
  (``keystone_tpu.obs``) and export a Chrome-trace/Perfetto JSON at
  process exit (or explicitly via :func:`export_trace`).
* ``KEYSTONE_AOT_CACHE=/path/dir`` — install the persistent AOT
  executable cache (``keystone_tpu.compile``): fitted-pipeline compiles
  load previously exported executables instead of re-tracing, and jax's
  persistent compilation cache is layered underneath.
* ``KEYSTONE_PROFILE_DIR=/path/dir`` — install the persistent operator
  profile store (``keystone_tpu.cost``): fits learn per-operator
  throughput from traced runs and the second fit of any pipeline plans
  its solver choice + cache plan from evidence with zero sampling.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
_DATEFMT = "%H:%M:%S"

_configured = False

_every_lock = threading.Lock()
_every_last: Dict[str, float] = {}


def every(key: str, seconds: float) -> bool:
    """Process-wide rate limiter for periodic logs: True at most once per
    ``seconds`` for a given ``key`` (first call always True). Lets hot
    loops (the serving engine's worker, long solver scans) emit periodic
    INFO summaries without flooding at per-iteration rate."""
    now = time.monotonic()
    with _every_lock:
        last = _every_last.get(key)
        if last is not None and now - last < seconds:
            return False
        _every_last[key] = now
        return True


def reset_rate_limits() -> None:
    """Forget every :func:`every` key so the next call logs immediately.
    ``timing.reset()`` calls this: a new measurement epoch must not
    inherit the previous run's suppression windows (back-to-back bench
    runs in one process were losing their first periodic summary)."""
    with _every_lock:
        _every_last.clear()


def configure(
    level: Optional[str] = None,
    profile: Optional[bool] = None,
    trace: Optional[str] = None,
    aot_cache: Optional[str] = None,
    profiles: Optional[str] = None,
) -> None:
    """Configure logging (and optionally phase profiling) process-wide.

    ``level=None`` reads ``KEYSTONE_LOG`` (default: warning, stdlib's
    default visibility; unknown env values warn and fall back rather than
    crash the CLI). ``profile`` is the single profiling switch: True/False
    enable/disable phase syncs+logs, ``None`` follows ``KEYSTONE_PROFILE``
    (off unless set to something truthy). ``trace`` is a Chrome-trace
    output path enabling the pipeline tracer (``keystone_tpu.obs``);
    ``None`` follows ``KEYSTONE_TRACE`` (off unless set). ``aot_cache``
    is a directory path enabling the persistent AOT executable cache
    (``keystone_tpu.compile``); ``None`` follows ``KEYSTONE_AOT_CACHE``
    (off unless set). ``profiles`` is a directory path enabling the
    persistent operator profile store (``keystone_tpu.cost``); ``None``
    follows ``KEYSTONE_PROFILE_DIR`` (off unless set). Idempotent; later
    calls re-level the root handler and re-apply the profiling switch,
    and an already-installed tracer is kept (spans survive).
    """
    global _configured
    from_env = level is None
    if from_env:
        level = os.environ.get("KEYSTONE_LOG", "warning")
    lvl = getattr(logging, str(level).upper(), None)
    if not isinstance(lvl, int):
        if not from_env:
            raise ValueError(f"unknown log level: {level!r}")
        # a bad env var should not crash the CLI — warn and fall back
        logging.getLogger(__name__).warning(
            "ignoring unknown KEYSTONE_LOG=%r (use debug|info|warning|error)",
            level,
        )
        lvl = logging.WARNING
    root = logging.getLogger()
    if not _configured:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        root.addHandler(handler)
        _configured = True
    root.setLevel(lvl)

    if profile is None:
        raw = os.environ.get("KEYSTONE_PROFILE", "")
        profile = raw.strip().lower() not in ("", "0", "false", "no", "off")
    from . import timing

    timing.enable(bool(profile))
    if profile:
        # phase logs are INFO; make sure they are visible when profiling
        if lvl > logging.INFO:
            root.setLevel(logging.INFO)

    if trace is None:
        trace = os.environ.get("KEYSTONE_TRACE") or None
    if trace:
        from ..obs import tracer as _obs_tracer

        _obs_tracer.start(path=trace)

    # an explicit aot_cache path (or "" to disable) reconfigures the AOT
    # executable cache; aot_cache=None only ensures the KEYSTONE_AOT_CACHE
    # env default is honored — like the tracer, an already-installed cache
    # is KEPT, so a later configure("debug") call to re-level logging
    # cannot silently uninstall it
    from .. import compile as _compile_mod

    if aot_cache is not None:
        _compile_mod.configure(aot_cache)
    else:
        _compile_mod.get_cache()

    # profile store: same keep-unless-explicit contract as the AOT cache
    from .. import cost as _cost_mod

    if profiles is not None:
        _cost_mod.configure(profiles)
    else:
        _cost_mod.get_store()


def export_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the configured trace NOW (Chrome-trace JSON + top-N summary
    log + autocache audit log). Returns the path written, or None when
    tracing was never configured — callers (the CLI's ``finally``) can
    invoke it unconditionally."""
    from ..obs import tracer as _obs_tracer

    return _obs_tracer.export(path)
