"""The trainer's append-only chunk source.

A :class:`ChunkLog` is the minimal durable-feed stand-in the daemon
tails: producers ``append`` (data, labels) chunks from any thread, the
daemon reads strictly forward with :meth:`tail`, and a contiguous index
range converts to a :class:`~keystone_tpu.data.chunked.ChunkedDataset`
for the absorb pass via :meth:`as_chunked` — an INDEXABLE source
(``from_chunk_fn``), so a checkpointed absorb that resumes mid-batch
skips the folded prefix without producing it, and every production is
counted (:attr:`production_counts` is what the O(new chunks) bench gate
reads: a chunk whose batch resolved must never be produced again).

The log keeps chunks in host memory — it models the *interface* of an
append-only feed (object-store prefixes, a message log), not its
storage. Chunk shape/dtype is validated at append against the first
chunk, so a malformed producer fails at the door, not mid-absorb.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class AppendedChunk:
    """One appended (data, labels) pair; ``labels`` may be None — an
    unlabeled append still feeds the moment drift monitor, it just can't
    contribute residual evidence or be absorbed."""

    index: int
    data: Any
    labels: Optional[Any]

    @property
    def rows(self) -> int:
        return int(self.data.shape[0])


class ChunkLog:
    """Thread-safe append-only log of training chunks."""

    def __init__(self, label: str = "append-log"):
        self._lock = threading.Lock()
        self._chunks: List[AppendedChunk] = []
        self._label = label
        self._item_shape: Optional[tuple] = None
        self._dtype = None
        #: times each chunk index has been produced through
        #: :meth:`as_chunked` — the absorb work-gate evidence
        self.production_counts: dict = {}

    def append(self, data: Any, labels: Optional[Any] = None) -> int:
        """Append one chunk; returns its log index. Raises ``ValueError``
        on a shape/dtype mismatch with the first appended chunk."""
        data = np.asarray(data)
        if data.ndim < 2:
            raise ValueError(
                f"appended chunks must be batched (2-D+), got {data.shape}"
            )
        if labels is not None:
            labels = np.asarray(labels)
            if int(labels.shape[0]) != int(data.shape[0]):
                raise ValueError(
                    f"chunk has {data.shape[0]} rows, labels "
                    f"{labels.shape[0]}"
                )
        with self._lock:
            if self._item_shape is None:
                self._item_shape = tuple(int(d) for d in data.shape[1:])
                self._dtype = data.dtype
            else:
                if tuple(int(d) for d in data.shape[1:]) != self._item_shape:
                    raise ValueError(
                        f"appended chunk item shape {data.shape[1:]} does "
                        f"not match the log's {self._item_shape}"
                    )
                if data.dtype != self._dtype:
                    raise ValueError(
                        f"appended chunk dtype {data.dtype} does not "
                        f"match the log's {self._dtype}"
                    )
            index = len(self._chunks)
            self._chunks.append(AppendedChunk(index, data, labels))
            return index

    def __len__(self) -> int:
        with self._lock:
            return len(self._chunks)

    @property
    def total_rows(self) -> int:
        with self._lock:
            return sum(c.rows for c in self._chunks)

    def tail(self, cursor: int) -> List[AppendedChunk]:
        """Every chunk appended at or after ``cursor``, in order — the
        daemon's strictly-forward read. Never blocks."""
        with self._lock:
            return list(self._chunks[cursor:])

    def get(self, index: int) -> AppendedChunk:
        with self._lock:
            return self._chunks[index]

    def as_chunked(self, start: int, stop: int) -> Tuple[Any, np.ndarray]:
        """``(ChunkedDataset, stacked labels)`` over log indices
        ``[start, stop)`` — the absorb batch. Index-addressable
        (``from_chunk_fn``), so checkpoint resume skips folded chunks
        without producing them; every production bumps
        :attr:`production_counts`. Raises ``ValueError`` when any chunk
        in the range is unlabeled (absorb needs labels)."""
        from ..data.chunked import ChunkedDataset

        with self._lock:
            if not (0 <= start < stop <= len(self._chunks)):
                raise ValueError(
                    f"as_chunked range [{start}, {stop}) outside the "
                    f"log's {len(self._chunks)} chunk(s)"
                )
            batch = list(self._chunks[start:stop])
        unlabeled = [c.index for c in batch if c.labels is None]
        if unlabeled:
            raise ValueError(
                f"absorb batch contains unlabeled chunk(s) {unlabeled}"
            )
        rows = sum(c.rows for c in batch)
        counts = self.production_counts

        def chunk_fn(i: int):
            c = batch[i]
            with self._lock:
                counts[c.index] = counts.get(c.index, 0) + 1
            return c.data

        ds = ChunkedDataset.from_chunk_fn(
            chunk_fn, len(batch), rows,
            label=f"{self._label}[{start}:{stop}]",
        )
        labels = np.concatenate([np.asarray(c.labels) for c in batch])
        return ds, labels
