"""``--trainer-demo``: the closed continual-learning loop, end to end.

Boots a 2-replica :class:`~keystone_tpu.serving.fleet.ServingFleet` on a
small deterministic regression pipeline, starts the
:class:`~keystone_tpu.trainer.TrainerDaemon` against an append-only
:class:`~keystone_tpu.trainer.ChunkLog`, and — while closed-loop client
threads hammer the fleet — appends several good chunk batches (each must
canary-pass and PROMOTE a refreshed model) and one poisoned batch (which
must canary-FAIL, roll back, and be parked). The demo exits nonzero
unless: >= 1 refresh promoted, >= 1 clean rollback, the poisoned batch
parked, zero request failures, and zero replica version skew. The smoke
path behind ``bin/serve-smoke.sh``'s trainer stage and the CLI's
``--trainer-demo`` flag.
"""

from __future__ import annotations

import argparse
import threading
import time
from typing import List, Optional

import numpy as np


def build_trainer_fitted(d: int = 16, k: int = 3, n_train: int = 512,
                         chunk_rows: int = 64, lam: float = 1e-2):
    """A deterministic absorbable pipeline: tanh featurizer + snapshot
    Gram solve — regression SCORES at the sink (not an argmax), so the
    canary's allclose comparison measures how far a refreshed model
    moved, which is the whole promote/rollback signal."""
    import jax.numpy as jnp

    from ..data.chunked import ChunkedDataset
    from ..data.dataset import Dataset
    from ..nodes.learning import LinearMapEstimator
    from ..workflow.transformer import FunctionNode

    rng = np.random.RandomState(7)
    W_true = rng.randn(d, k).astype(np.float32)

    def make(n, seed, shift=0.0):
        r = np.random.RandomState(seed)
        X = (r.randn(n, d) + 1.0 + shift).astype(np.float32)
        Y = (np.tanh(X) @ W_true + 0.05 * r.randn(n, k)).astype(np.float32)
        return X, Y

    X0, Y0 = make(n_train, 0)
    fitted = (
        FunctionNode(batch_fn=lambda A: jnp.tanh(A), label="feat")
        .to_pipeline()
        .and_then(
            LinearMapEstimator(lam=lam, snapshot=True),
            ChunkedDataset.from_array(X0, chunk_rows),
            Dataset.of(Y0),
        )
        .fit()
    )
    return fitted, make, X0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser("keystone-tpu trainer-demo")
    p.add_argument("--nTrain", type=int, default=512)
    p.add_argument("--chunkRows", type=int, default=64)
    p.add_argument("--refreshes", type=int, default=2,
                   help="good chunk batches to append (each must promote)")
    p.add_argument("--chunksPerBatch", type=int, default=2)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--clients", type=int, default=4,
                   help="closed-loop traffic threads")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="per-phase wait budget (seconds)")
    args = p.parse_args(argv)

    from ..serving import ServingFleet
    from . import ChunkLog, TrainerDaemon

    d = 16
    fitted, make, X0 = build_trainer_fitted(
        d=d, n_train=args.nTrain, chunk_rows=args.chunkRows
    )
    fleet = ServingFleet(
        fitted, replicas=args.replicas, buckets=(8,), datum_shape=(d,),
        max_wait_ms=1.0, max_queue=1024,
    )
    log = ChunkLog()
    stop = threading.Event()
    failures: List[str] = []

    def client(tid: int) -> None:
        i = tid
        while not stop.is_set():
            try:
                fleet.predict(X0[i % args.nTrain], timeout=15.0)
            except Exception as e:  # every failure is a gate violation
                failures.append(f"{type(e).__name__}: {e}")
            i += args.clients

    def wait_for(pred, what: str) -> bool:
        t0 = time.time()
        while time.time() - t0 < args.timeout:
            if pred():
                return True
            time.sleep(0.05)
        print(f"TRAINER FAIL: timed out waiting for {what}")
        return False

    ok = True
    with fleet:
        threads = [
            threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(args.clients)
        ]
        for t in threads:
            t.start()
        daemon = TrainerDaemon(
            fleet, log,
            poll_interval_s=0.02, refit_interval_s=0.1,
            min_refit_chunks=args.chunksPerBatch,
            canary_fraction=1.0, canary_batches=2, canary_timeout_s=10.0,
            canary_atol=0.5, canary_rtol=0.5,
            max_batch_retries=0,
        )
        with daemon:
            for b in range(args.refreshes):
                for j in range(args.chunksPerBatch):
                    X, Y = make(args.chunkRows, 100 + 10 * b + j)
                    log.append(X, Y)
                ok = ok and wait_for(
                    lambda want=b + 1: fleet.metrics.count("refits") >= want,
                    f"promoted refresh {b + 1}",
                )
            # the poisoned batch: wildly off-distribution rows whose
            # refit moves the model far outside the canary tolerance
            for _ in range(args.chunksPerBatch):
                log.append(
                    np.full((args.chunkRows, d), 1e4, np.float32),
                    np.full((args.chunkRows, 3), -1e4, np.float32),
                )
            ok = ok and wait_for(
                lambda: fleet.metrics.count("rollbacks") >= 1
                and daemon.parked_batches,
                "canary rollback + parked batch",
            )
            parked = daemon.parked_batches
        stop.set()
        for t in threads:
            t.join(timeout=5)
        snap = fleet.metrics.snapshot()
        report = fleet.version_report()
    c = snap["counters"]
    lat = snap["latency"]
    print(
        f"TRAINER refits={c.get('refits', 0)} "
        f"rollbacks={c.get('rollbacks', 0)} parked={len(parked)} "
        f"version={report['version']} skew={report['skew']} "
        f"completed={c.get('completed', 0)} failures={len(failures)} "
        f"p50={lat.get('p50', 0):.4f}s p99={lat.get('p99', 0):.4f}s"
    )
    if c.get("refits", 0) < max(1, args.refreshes):
        print("TRAINER FAIL: expected every good batch to promote")
        ok = False
    if c.get("rollbacks", 0) < 1 or not parked:
        print("TRAINER FAIL: the poisoned batch must roll back and park")
        ok = False
    if failures:
        print(f"TRAINER FAIL: {len(failures)} request failure(s), e.g. "
              f"{failures[0]}")
        ok = False
    if report["skew"]:
        print(f"TRAINER FAIL: replica version skew: {report}")
        ok = False
    if c.get("completed", 0) != c.get("submitted", 0):
        print("TRAINER FAIL: submitted != completed")
        ok = False
    print("TRAINER " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
