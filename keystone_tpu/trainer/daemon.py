"""The supervised trainer daemon: the closed continual-learning loop.

One background thread turns "fit then serve" into a hands-free online
system by connecting machinery that already exists separately:

* **tail** — read the append-only :class:`~.source.ChunkLog` strictly
  forward (``trainer.ingest`` fault point; transient faults retry
  bounded, then escalate to the supervisor);
* **monitor** — featurize each appended chunk through the serving
  model's FROZEN prefix (``FittedPipeline.prefix_features``), fold it
  into the :class:`~.drift.DriftMonitor` against the fitted solver
  state's own moment snapshot, and score streaming residual error on
  labeled appends;
* **decide** — refit on a wall-clock cadence OR when a drift trigger
  trips (both observable as the ``drift_score`` gauge);
* **absorb** — fold the pending chunk batch into the model with
  ``FittedPipeline.absorb`` in O(new chunks), CHECKPOINTED through
  :class:`~keystone_tpu.faults.FitCheckpoint` (``trainer.absorb`` fires
  per folded chunk, so a kill mid-fold leaves the last completed block
  on disk and the retried attempt resumes bit-identically — served data
  is never rescanned);
* **canary + swap** — publish through
  :meth:`~keystone_tpu.serving.fleet.ServingFleet.swap` with a canary
  fraction: live traffic mirrors through the candidate, the evidence
  report promotes or auto-rolls-back (``trainer.canary`` fires before
  the swap; an injected transient there counts as canary failure);
* **survive** — every failure mode leaves the OLD model serving: an
  absorb crash or canary mismatch retries its chunk batch a bounded
  number of times and then PARKS it (quarantine + WARNING — never a
  poison-pill loop); the loop thread itself restarts within an explicit
  restart budget when something punches through (an injected kill, a
  real crash), with all cursor/batch state preserved on the object.

Metrics land in the fleet's registry (``refits``, ``rollbacks``,
``parked_batches``, ``absorb_failures``, ``absorbed_chunks``,
``absorbed_rows``, plus the ``drift_score`` / ``staleness_s`` /
``trainer_backlog`` gauges); promote/rollback/park/restart are trace
instants and each refit attempt is a ``trainer.refit`` span.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, List, Optional

import numpy as np

from ..faults import (
    TRAINER_ABSORB,
    TRAINER_CANARY,
    TRAINER_INGEST,
    fault_point,
    is_transient,
)
from ..obs.tracer import current as _trace_current
from ..serving.errors import CanaryMismatch, EngineStopped
from .drift import DriftMonitor
from .source import ChunkLog

logger = logging.getLogger(__name__)


class TrainerStopped(RuntimeError):
    """The daemon is not running (never started, stopped, or its restart
    budget is exhausted)."""


class _Attempt:
    """One frozen chunk batch being refit: ``[start, stop)`` log indices
    plus its bounded retry count. Frozen at first attempt so retries are
    deterministic and the absorb checkpoint key stays stable; chunks
    appended later join the NEXT batch."""

    def __init__(self, start: int, stop: int):
        self.start = start
        self.stop = stop
        self.retries = 0

    @property
    def key(self) -> str:
        return f"trainer-batch-{self.start}-{self.stop}"


class TrainerDaemon:
    """Supervised continual-learning loop over a fleet and a chunk log.

    Parameters (every knob is an explicit budget or threshold):

    fleet:
        The live :class:`~keystone_tpu.serving.fleet.ServingFleet`; its
        published model is the absorb base and swap target.
    source:
        The :class:`~.source.ChunkLog` to tail.
    poll_interval_s:
        Idle sleep between loop ticks.
    refit_interval_s:
        Cadence trigger: refit when this much wall clock passed since
        the last promoted refresh (None = drift-only).
    min_refit_chunks:
        Never refit on fewer pending chunks than this.
    drift:
        A pre-built :class:`~.drift.DriftMonitor`, or None to build one
        from the fitted solver state's moment snapshot with the monitor
        defaults (``drift_kwargs`` passes overrides).
    canary_fraction / canary_batches / canary_timeout_s / canary_atol /
    canary_rtol / max_latency_ratio:
        Forwarded to ``fleet.swap`` — the promote-or-rollback evidence.
        The tolerances are the "how different may a refreshed model be"
        knob: a healthy absorb moves outputs a little, a poisoned batch
        moves them wildly.
    max_batch_retries:
        Absorb crashes / canary rollbacks a chunk batch survives before
        it is parked (quarantined) and the loop moves on.
    max_restarts:
        Loop-thread restart budget (the daemon's own supervisor).
    max_ingest_failures:
        Consecutive transient ingest failures tolerated before the tick
        escalates to the supervisor.
    checkpoint_dir:
        Directory for absorb checkpoints (None = absorb is all-or-
        nothing per attempt; retries refold from the first chunk).
    """

    def __init__(
        self,
        fleet,
        source: ChunkLog,
        *,
        poll_interval_s: float = 0.05,
        refit_interval_s: Optional[float] = None,
        min_refit_chunks: int = 1,
        drift: Optional[DriftMonitor] = None,
        drift_kwargs: Optional[dict] = None,
        canary_fraction: float = 0.25,
        canary_batches: int = 2,
        canary_timeout_s: float = 5.0,
        canary_atol: float = 0.25,
        canary_rtol: float = 0.25,
        max_latency_ratio: Optional[float] = None,
        max_batch_retries: int = 1,
        max_restarts: int = 2,
        max_ingest_failures: int = 8,
        checkpoint_dir: Optional[str] = None,
        join_timeout_s: float = 10.0,
    ):
        self._fleet = fleet
        self._source = source
        self._fitted = fleet.fitted
        self.poll_interval_s = float(poll_interval_s)
        self.refit_interval_s = (
            None if refit_interval_s is None else float(refit_interval_s)
        )
        self.min_refit_chunks = int(min_refit_chunks)
        self.canary_fraction = float(canary_fraction)
        self.canary_batches = int(canary_batches)
        self.canary_timeout_s = float(canary_timeout_s)
        self.canary_atol = float(canary_atol)
        self.canary_rtol = float(canary_rtol)
        self.max_latency_ratio = max_latency_ratio
        self.max_batch_retries = int(max_batch_retries)
        self.max_restarts = int(max_restarts)
        self.max_ingest_failures = int(max_ingest_failures)
        self.checkpoint_dir = checkpoint_dir

        self._metrics = fleet.metrics
        self._monitor = drift or DriftMonitor(
            self._state_of(self._fitted).moments(), **(drift_kwargs or {})
        )
        #: log index up to which chunks are RESOLVED (promoted or parked)
        self._resolved = 0
        #: log index up to which chunks were ingested into the monitor
        self._ingested = 0
        self._attempt: Optional[_Attempt] = None
        self._parked: List[tuple] = []
        self._consecutive_ingest_failures = 0
        self._last_promote = time.monotonic()
        self._restarts_used = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._join_timeout_s = float(join_timeout_s)
        # drift/staleness are watermark-shaped (the WORST process is the
        # fleet's truth — summing two drift scores across a merge is
        # fiction); backlog is additive
        self._metrics.set_gauge(
            "drift_score",
            lambda: self._monitor.score()["drift_score"],
            merge="max",
        )
        self._metrics.set_gauge("staleness_s", self.staleness_s, merge="max")
        self._metrics.set_gauge(
            "trainer_backlog", lambda: len(self._source) - self._resolved
        )

    # -- introspection ---------------------------------------------------

    @property
    def fitted(self):
        """The daemon's view of the published model (moves only on a
        promoted refresh)."""
        return self._fitted

    @property
    def monitor(self) -> DriftMonitor:
        return self._monitor

    @property
    def parked_batches(self) -> List[tuple]:
        """Quarantined ``(start, stop)`` chunk-index ranges — appended
        data the loop gave up on after the bounded retries. Their chunks
        stay in the log untouched for offline forensics."""
        with self._lock:
            return list(self._parked)

    def staleness_s(self) -> float:
        """Seconds since the last promoted refresh (or daemon start)."""
        return time.monotonic() - self._last_promote

    @staticmethod
    def _state_of(fitted):
        node, mapper = fitted._absorb_node()
        return mapper.solver_state

    @staticmethod
    def _mapper_of(fitted):
        node, mapper = fitted._absorb_node()
        return mapper

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "TrainerDaemon":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("trainer already started")
            if self._stop.is_set():
                raise TrainerStopped("trainer was stopped")
            self._spawn_thread()
        return self

    def _spawn_thread(self) -> None:
        attempt = self._restarts_used
        self._thread = threading.Thread(
            target=self._run,
            name=(
                "keystone-trainer" + (f"-r{attempt}" if attempt else "")
            ),
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        """Idempotent bounded shutdown: the loop exits at the next tick
        boundary; a loop wedged inside a canary window is joined with a
        timeout, WARNed, and abandoned (daemon thread)."""
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self._join_timeout_s)
            if t.is_alive():
                logger.warning(
                    "trainer shutdown: thread %s did not exit within "
                    "%.1fs — abandoning it (daemon)",
                    t.name, self._join_timeout_s,
                )

    def __enter__(self) -> "TrainerDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- supervision -----------------------------------------------------

    def _run(self) -> None:
        """The thread target: the loop under its own supervisor. ANY
        escape (an injected kill at a trainer fault site, a real crash)
        restarts the loop within the restart budget — with all batch /
        cursor state preserved on the object, so a killed absorb's next
        attempt resumes from its checkpoint."""
        try:
            self._loop()
        except BaseException as e:  # noqa: BLE001 — the supervision seam
            if self._stop.is_set():
                return
            with self._lock:
                will_restart = self._restarts_used < self.max_restarts
                if will_restart:
                    self._restarts_used += 1
                self._metrics.inc("trainer_crashes")
            logger.warning(
                "trainer: loop died (%s: %s) — restart %s (budget %d/%d "
                "used)", type(e).__name__, e,
                "scheduled" if will_restart else "REFUSED",
                self._restarts_used, self.max_restarts,
            )
            self._instant(
                "trainer.restart" if will_restart else "trainer.dead",
                kind=type(e).__name__,
            )
            if will_restart:
                # a fresh loop gets a fresh ingest-fault budget — the
                # escalation that triggered this restart must not leave
                # the counter saturated (one more flake would otherwise
                # burn the next restart immediately)
                self._consecutive_ingest_failures = 0
                with self._lock:
                    self._spawn_thread()
                self._metrics.inc("trainer_restarts")
            else:
                self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            did_work = self._tick()
            if not did_work:
                self._stop.wait(self.poll_interval_s)

    # -- one tick --------------------------------------------------------

    def _tick(self) -> bool:
        """Ingest, decide, maybe refit. Returns True when it did real
        work (skip the idle sleep)."""
        new = self._ingest()
        for chunk in new:
            self._observe(chunk)
        if self._attempt is None and self._should_refit():
            self._attempt = _Attempt(self._resolved, self._ingested)
        if self._attempt is not None:
            self._refit(self._attempt)
            return True
        return bool(new)

    def _ingest(self) -> list:
        """Tail the source; transient faults (``trainer.ingest``) are
        tolerated up to ``max_ingest_failures`` consecutive times, then
        escalate to the supervisor."""
        try:
            fault_point(TRAINER_INGEST)
            new = self._source.tail(self._ingested)
        except Exception as e:
            if not is_transient(e):
                raise
            self._consecutive_ingest_failures += 1
            self._metrics.inc("ingest_failures")
            logger.warning(
                "trainer: transient ingest failure %d/%d (%s)",
                self._consecutive_ingest_failures,
                self.max_ingest_failures, e,
            )
            self._instant(
                "trainer.ingest_fault",
                consecutive=self._consecutive_ingest_failures,
                budget=self.max_ingest_failures,
            )
            if self._consecutive_ingest_failures >= self.max_ingest_failures:
                raise
            return []
        self._consecutive_ingest_failures = 0
        self._ingested += len(new)
        return new

    def _observe(self, chunk) -> None:
        """Monitor one appended chunk: featurize through the frozen
        prefix, score moment drift against the fitted snapshot, and (on
        labeled appends) the model's residual error. A chunk that fails
        monitoring is WARNed and still joins its batch — if it is
        genuinely poisoned, the absorb/canary path catches it and the
        bounded-retry-then-park discipline quarantines the batch."""
        from ..data.dataset import Dataset

        try:
            feats = np.asarray(
                Dataset.of(
                    self._fitted.prefix_features(Dataset.of(chunk.data))
                ).to_array()
            )
            residual = None
            if chunk.labels is not None:
                import jax.numpy as jnp

                preds = np.asarray(
                    self._mapper_of(self._fitted).trace_batch(
                        jnp.asarray(feats, dtype=jnp.float32)
                    )
                )
                residual = float(
                    np.mean(
                        (preds - np.asarray(chunk.labels, np.float64)) ** 2
                    )
                )
            self._monitor.observe(feats, residual)
        except Exception:
            self._metrics.inc("monitor_failures")
            logger.warning(
                "trainer: chunk %d failed featurize-for-monitoring "
                "(drift evidence skipped; the absorb path will judge it)",
                chunk.index, exc_info=True,
            )

    def _should_refit(self) -> bool:
        pending = self._ingested - self._resolved
        if pending < self.min_refit_chunks:
            return False
        reason = self._monitor.should_refit()
        if reason is not None:
            logger.info(
                "trainer: drift trigger (%s) — refitting %d pending "
                "chunk(s)", reason, pending,
            )
            return True
        if (
            self.refit_interval_s is not None
            and self.staleness_s() >= self.refit_interval_s
        ):
            return True
        return False

    # -- the refit attempt ----------------------------------------------

    def _refit(self, attempt: _Attempt) -> None:
        """One absorb → canary → swap attempt for the frozen batch.
        Every failure path leaves the old model serving; success
        publishes and re-baselines."""
        import contextlib

        tracer = _trace_current()
        with contextlib.ExitStack() as stack:
            if tracer is not None:
                stack.enter_context(
                    tracer.span(
                        "trainer.refit",
                        op_type=type(self).__name__,
                        batch_start=attempt.start,
                        batch_stop=attempt.stop,
                        retry=attempt.retries,
                    )
                )
            try:
                candidate = self._absorb(attempt)
            except Exception as e:
                self._metrics.inc("absorb_failures")
                self._batch_failed(attempt, e, phase="absorb")
                return
            try:
                fault_point(TRAINER_CANARY)
                report = self._fleet.swap(
                    candidate,
                    canary_fraction=self.canary_fraction,
                    canary_batches=self.canary_batches,
                    canary_timeout_s=self.canary_timeout_s,
                    atol=self.canary_atol,
                    rtol=self.canary_rtol,
                    max_latency_ratio=self.max_latency_ratio,
                )
            except EngineStopped:
                # the fleet is going away; nothing was promoted and the
                # loop has nothing left to publish to
                logger.info("trainer: fleet stopped — trainer stopping")
                self._stop.set()
                return
            except CanaryMismatch as e:
                self._metrics.inc("rollbacks")
                self._instant(
                    "trainer.rollback",
                    batch_start=attempt.start, batch_stop=attempt.stop,
                    evidence=str(e)[:200],
                )
                self._batch_failed(attempt, e, phase="canary")
                return
            except Exception as e:
                if is_transient(e):
                    # an injected/flaky canary failure: same verdict as a
                    # mismatch — no promotion happened, old model serves
                    self._metrics.inc("rollbacks")
                    self._instant(
                        "trainer.rollback",
                        batch_start=attempt.start,
                        batch_stop=attempt.stop,
                        evidence=f"canary fault: {e}",
                    )
                    self._batch_failed(attempt, e, phase="canary")
                    return
                raise
            self._promoted(attempt, candidate, report)

    def _absorb(self, attempt: _Attempt):
        """The checkpointed fold: ``trainer.absorb`` fires per folded
        chunk INSIDE the checkpoint discipline, so a kill here resumes
        from the last completed block on the next attempt."""
        ds, labels = self._source.as_chunked(attempt.start, attempt.stop)

        def on_chunk(i, _chunk):
            fault_point(TRAINER_ABSORB)

        candidate = self._fitted.absorb(
            ds, labels,
            checkpoint=self.checkpoint_dir,
            checkpoint_key=attempt.key,
            on_chunk=on_chunk,
        )
        self._metrics.inc("absorbed_chunks", attempt.stop - attempt.start)
        self._metrics.inc("absorbed_rows", int(labels.shape[0]))
        # fit seam of the device-memory watermark: absorb holds the
        # candidate's full accumulator state — a footprint peak
        from ..obs import resource as _resource

        _resource.sample_memory()
        return candidate

    def _batch_failed(self, attempt: _Attempt, exc, *, phase: str) -> None:
        attempt.retries += 1
        if attempt.retries > self.max_batch_retries:
            self._park(
                attempt.start, attempt.stop,
                f"{phase} failed {attempt.retries}x: {exc}",
            )
            self._resolved = attempt.stop
            self._attempt = None
            self._discard_checkpoint(attempt)
        else:
            self._metrics.inc("batch_retries")
            logger.warning(
                "trainer: %s failed for batch [%d, %d) (%s) — retry "
                "%d/%d%s",
                phase, attempt.start, attempt.stop, exc,
                attempt.retries, self.max_batch_retries,
                " (will resume from checkpoint)"
                if phase == "absorb" and self.checkpoint_dir
                else "",
            )

    def _park(self, start: int, stop: int, why: str) -> None:
        with self._lock:
            self._parked.append((start, stop))
        self._metrics.inc("parked_batches")
        logger.warning(
            "trainer: PARKING chunk batch [%d, %d) — %s. The old model "
            "keeps serving; the chunks stay in the log for forensics.",
            start, stop, why,
        )
        self._instant("trainer.park", batch_start=start, batch_stop=stop)
        # a parked batch is quarantined data: leave the post-mortem
        # artifact holding what the loop did on the way here
        from ..obs import flight as _flight

        _flight.dump("trainer_park")

    def _discard_checkpoint(self, attempt: _Attempt) -> None:
        """A parked batch's half-folded checkpoint must not survive: it
        would be garbage to any future key collision."""
        if self.checkpoint_dir is None:
            return
        from ..faults import FitCheckpoint

        FitCheckpoint(self.checkpoint_dir, attempt.key).complete()

    def _promoted(self, attempt: _Attempt, candidate, report) -> None:
        self._fitted = candidate
        self._resolved = attempt.stop
        self._attempt = None
        self._last_promote = time.monotonic()
        self._metrics.inc("refits")
        self._monitor.rebaseline(self._state_of(candidate).moments())
        canary = report.get("canary") or {}
        logger.info(
            "trainer: PROMOTED refresh v%s (batch [%d, %d), %d mirrored "
            "canary batch(es))",
            report.get("version"), attempt.start, attempt.stop,
            canary.get("batches_compared", 0),
        )
        self._instant(
            "trainer.promote",
            version=report.get("version"),
            batch_start=attempt.start, batch_stop=attempt.stop,
        )

    def _instant(self, name: str, **attrs) -> None:
        # every trainer verdict lands in the always-on flight ring too:
        # a promote/rollback/park/restart must be visible in a post-
        # mortem dump even when tracing was never configured
        from ..obs import flight as _flight

        _flight.record_instant(name, **attrs)
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(name, op_type=type(self).__name__, **attrs)
