"""Closed-loop continual learning: the supervised trainer daemon.

The robustness capstone over PRs 9-11: ``FittedPipeline.absorb`` folds
appended chunks in O(new), the fleet does canaried zero-downtime swaps,
and ``keystone_tpu/faults/`` provides seeded chaos + checkpoint/resume —
this package connects them into one hands-free loop that keeps serving
correctly while everything around it churns or fails.

* :class:`ChunkLog` (:mod:`.source`) — the append-only chunk feed the
  daemon tails;
* :class:`DriftMonitor` (:mod:`.drift`) — moment-shift and residual
  triggers against the fitted solver state's own moment snapshot;
* :class:`TrainerDaemon` (:mod:`.daemon`) — the supervised loop:
  tail → decide (cadence/drift) → checkpointed absorb → canary swap →
  promote or roll back, with chunk-batch quarantine and explicit
  restart budgets. Fault sites ``trainer.ingest`` / ``trainer.absorb``
  / ``trainer.canary`` ride the ``KEYSTONE_FAULTS`` plan so every
  failure path is deterministically testable.

``python -m keystone_tpu --trainer-demo`` runs the whole loop against a
live fleet with synthetic appends (including a poisoned batch that must
roll back).
"""

from .daemon import TrainerDaemon, TrainerStopped
from .drift import DriftMonitor
from .source import AppendedChunk, ChunkLog

__all__ = [
    "AppendedChunk",
    "ChunkLog",
    "DriftMonitor",
    "TrainerDaemon",
    "TrainerStopped",
]
