"""Drift detection: when has the world moved enough to refit?

The monitor compares the stream of appended FEATURE chunks against the
fitted model's own moment snapshot
(:meth:`~keystone_tpu.linalg.accumulators.GramSolverState.moments` —
derived from the solver state's raw sums, so the baseline costs no extra
statistics pass) and, on labeled appends, tracks the model's streaming
residual error. Three documented triggers, each with an explicit
false-positive bound:

* **mean shift** — per-column z-statistic of the recent mean against the
  baseline: ``z_j = |μ̂_j − μ_j| / sqrt(σ²_j / n_recent)``. Under the
  null (stationary stream) ``max_j z_j`` exceeds ``z_threshold`` with
  probability ≤ ``d · 2Φ(−z)`` per evaluation — the default ``z=6``
  bounds it below 2e-8 per check even at d=10⁴.
* **variance shift** — per-column ratio ``max(σ̂²/σ², σ²/σ̂²)`` against
  ``var_ratio``; the sample ratio concentrates as ``1 ± sqrt(2/n)``, so
  the default 4.0 with ``min_rows`` ≥ 64 is > 20 null standard
  deviations out.
* **residual shift** — EWMA of per-chunk mean-squared residual against
  the baseline EWMA established over the first ``residual_warmup``
  labeled chunks after each (re)baseline; trips when the ratio exceeds
  ``residual_ratio``. Skipped entirely when labels are absent — the
  moment triggers carry the decision alone (label-free streams still
  drift-trigger).

No trigger fires before ``min_rows`` recent rows have been observed:
tiny-sample moment estimates are noise, and the bound above assumes a
real n. ``rebaseline()`` is called by the daemon after every promoted
refresh so "drift" is always measured against what the serving model
actually absorbed.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Optional

import numpy as np

from ..linalg.accumulators import MomentsState


class DriftMonitor:
    """Feature-moment + residual drift triggers for the trainer daemon.

    Thread-safe: the daemon observes from its loop thread while tests
    and metrics gauges read scores from others.
    """

    def __init__(
        self,
        baseline: MomentsState,
        *,
        z_threshold: float = 6.0,
        var_ratio: float = 4.0,
        residual_ratio: float = 2.0,
        min_rows: int = 64,
        residual_warmup: int = 2,
        residual_alpha: float = 0.5,
    ):
        if baseline.mean is None or baseline.n <= 1:
            raise ValueError("drift baseline must hold fitted moments")
        self._lock = threading.Lock()
        self._base = baseline.snapshot()
        self.z_threshold = float(z_threshold)
        self.var_ratio = float(var_ratio)
        self.residual_ratio = float(residual_ratio)
        self.min_rows = int(min_rows)
        self.residual_warmup = int(residual_warmup)
        self.residual_alpha = float(residual_alpha)
        self._recent = MomentsState()
        self._resid_base: Optional[float] = None
        self._resid_base_chunks = 0
        self._resid_ewma: Optional[float] = None

    # -- observation -----------------------------------------------------

    def observe(self, feats: Any, residual_mse: Optional[float] = None) -> None:
        """Fold one featurized chunk (and optionally its model residual
        mean-squared error) into the recent window."""
        feats = np.asarray(feats, dtype=np.float64)
        with self._lock:
            if feats.ndim == 2 and feats.shape[0]:
                self._recent.update(feats)
            if residual_mse is not None and math.isfinite(residual_mse):
                if self._resid_base_chunks < self.residual_warmup:
                    # establish the post-(re)baseline residual level first
                    self._resid_base_chunks += 1
                    self._resid_base = (
                        residual_mse
                        if self._resid_base is None
                        else self._resid_base
                        + (residual_mse - self._resid_base)
                        / self._resid_base_chunks
                    )
                    self._resid_ewma = self._resid_base
                else:
                    a = self.residual_alpha
                    self._resid_ewma = (
                        residual_mse
                        if self._resid_ewma is None
                        else a * residual_mse + (1 - a) * self._resid_ewma
                    )

    # -- verdicts --------------------------------------------------------

    def score(self) -> dict:
        """The current evidence: max mean-shift z, max variance ratio,
        residual ratio (None before labeled warmup completes), recent
        row count, and the composite ``drift_score`` the metrics gauge
        exports (max of the trigger ratios, 1.0 = at threshold)."""
        with self._lock:
            out = {
                "rows": int(self._recent.n),
                "z_max": 0.0,
                "var_ratio_max": 1.0,
                "residual_ratio": None,
            }
            if (
                self._recent.mean is not None
                and self._recent.n >= max(2, self.min_rows)
            ):
                n = float(self._recent.n)
                base_var = np.maximum(
                    self._base.m2 / max(self._base.n - 1, 1), 1e-12
                )
                z = np.abs(self._recent.mean - self._base.mean) / np.sqrt(
                    base_var / n
                )
                out["z_max"] = float(np.max(z))
                recent_var = np.maximum(
                    self._recent.m2 / max(self._recent.n - 1, 1), 1e-12
                )
                ratio = recent_var / base_var
                out["var_ratio_max"] = float(
                    np.max(np.maximum(ratio, 1.0 / ratio))
                )
            if (
                self._resid_ewma is not None
                # "is not None", not truthiness: a perfectly-fitting
                # warmup (baseline mse exactly 0.0) must not disable
                # the trigger — the divide below is already floored
                and self._resid_base is not None
                and self._resid_base_chunks >= self.residual_warmup
            ):
                out["residual_ratio"] = float(
                    self._resid_ewma / max(self._resid_base, 1e-12)
                )
            ratios = [
                out["z_max"] / self.z_threshold,
                out["var_ratio_max"] / self.var_ratio,
            ]
            if out["residual_ratio"] is not None:
                ratios.append(out["residual_ratio"] / self.residual_ratio)
            out["drift_score"] = float(max(ratios))
            return out

    def should_refit(self) -> Optional[str]:
        """The trigger verdict: a human-readable reason string when any
        documented threshold is crossed, else None."""
        s = self.score()
        if s["rows"] < self.min_rows:
            return None
        if s["z_max"] > self.z_threshold:
            return f"mean shift z={s['z_max']:.1f} > {self.z_threshold}"
        if s["var_ratio_max"] > self.var_ratio:
            return (
                f"variance ratio {s['var_ratio_max']:.1f} > {self.var_ratio}"
            )
        r = s["residual_ratio"]
        if r is not None and r > self.residual_ratio:
            return f"residual ratio {r:.2f} > {self.residual_ratio}"
        return None

    # -- lifecycle -------------------------------------------------------

    def rebaseline(self, baseline: MomentsState) -> None:
        """Reset against a freshly-promoted model's moments: the recent
        window and the residual baseline start over (the new model was
        solved on the absorbed data, so the old residual level no longer
        describes it)."""
        if baseline.mean is None or baseline.n <= 1:
            raise ValueError("drift baseline must hold fitted moments")
        with self._lock:
            self._base = baseline.snapshot()
            self._recent = MomentsState()
            self._resid_base = None
            self._resid_base_chunks = 0
            self._resid_ewma = None
