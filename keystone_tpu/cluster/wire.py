"""The cluster wire protocol: length-framed messages over a local
socket, with deadlines and typed errors that survive the process
boundary.

Deliberately minimal — the router and its workers share one machine (a
host driving one accelerator slice), so the protocol optimizes for
correctness of the THREE things that must not be lost crossing a
process boundary:

* **Framing.** Every message is a ``>I`` length prefix + payload. The
  payload self-describes its encoding by first byte: hot ``req``/``res``
  frames ride the binary codec (:mod:`.codec` — fixed header + ndarray
  descriptors + raw bytes, :data:`~keystone_tpu.cluster.codec.MAGIC`
  leading), while CONTROL frames (hello/ready/ping/stats/stop/errors)
  stay pickle (protocol >= 2 payloads always lead with ``0x80``, so the
  receiver dispatches per frame and old peers interop).
  ``send_payload``/``send_msg`` hold the caller's per-connection lock
  (sockets interleave concurrent sends otherwise); ``recv_payload``/
  ``recv_msg`` read exactly one frame or raise :class:`ConnectionClosed`
  on EOF — a half-read frame (peer died mid-send) is indistinguishable
  from death and is treated as it. A malformed BINARY frame degrades
  typed too (:class:`~keystone_tpu.cluster.codec.CodecError`): hot-path
  bytes are never handed to ``pickle.loads`` on a parse failure.
* **Deadlines.** ``time.monotonic()`` is process-local, so absolute
  deadlines are meaningless on the wire. A request's deadline travels
  as its REMAINING budget (seconds), stamped at send time and
  re-anchored to the receiver's clock on arrival — the satellite
  contract: crossing the boundary never extends a deadline (transit
  time comes out of the budget, as it should: it is real latency).
* **Typed errors.** The serving layer's whole error discipline is that
  callers branch on types (:class:`~keystone_tpu.serving.errors.Shed`
  vs :class:`DeadlineExceeded` vs :class:`QueueFull`). Worker-side
  errors are encoded by REGISTERED name + message and re-raised as the
  same type router-side; an unregistered type degrades to
  :class:`WorkerError` carrying the original class name — never a
  pickle of an arbitrary exception object (which may not unpickle, or
  may execute reduction code we don't control).

Message payloads are plain dicts with a ``"type"`` key; both codecs
round-trip the same dicts, so ``KEYSTONE_WIRE_CODEC=pickle`` is a
frame-for-frame kill switch, not a different protocol.

**Trace propagation.** A sampled request's ``req`` frame additionally
carries ``"trace"`` — the :class:`~keystone_tpu.obs.context.TraceContext`
wire form (trace id, emitting hop, a ``time.time()`` send stamp) — and
every ``res`` frame carries ``"t_unix"``.

**QoS identity.** A ``req`` frame also carries ``"priority"`` and
``"tenant"`` (see :mod:`keystone_tpu.autoscale.qos`): the worker's
in-process fleet re-applies the same shedding class and weighted-fair
share the router admitted under, so crossing the process boundary never
launders a request into a better class. :func:`qos_to_wire` /
:func:`qos_from_wire` are the two ends; absent keys degrade to the
defaults (normal priority, the default tenant) so old frames decode. Monotonic clocks are
process-local, so cross-process latency attribution rides the HOST-shared
unix clock: the receiver prices each direction's transport as
``time.time() - stamp`` and records it on its hop span, which is how the
stitched trace (``obs/export.py``) shows per-hop serialize/transport/
queue time instead of one opaque round-trip.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import time
from typing import Any, Optional

_LEN = struct.Struct(">I")

#: one frame must fit comfortably in memory; a corrupt length prefix
#: (desynced stream) must not trigger a multi-GB allocation
MAX_FRAME_BYTES = 1 << 30


class ConnectionClosed(ConnectionError):
    """The peer's socket reached EOF (or died mid-frame). A
    ``ConnectionError`` so :func:`keystone_tpu.faults.is_transient`
    classifies it transient — a dead worker's requests are retried on
    peers, exactly like a dead replica thread's."""


class WorkerError(RuntimeError):
    """A worker-side failure whose type is not part of the serving
    error vocabulary. Carries the original class name."""

    def __init__(self, kind: str, message: str):
        super().__init__(f"{kind}: {message}")
        self.kind = kind


def _registry():
    from ..serving.errors import (
        CanaryMismatch,
        DeadlineExceeded,
        EngineClosed,
        EngineStopped,
        InvalidRequest,
        QueueFull,
        ServingError,
        Shed,
    )
    from ..check import ContractMismatchError, PipelineCheckError
    from ..workflow.pipeline import NotTraceableError

    types = (
        Shed,
        DeadlineExceeded,
        QueueFull,
        InvalidRequest,
        EngineStopped,
        EngineClosed,
        CanaryMismatch,
        ServingError,
        NotTraceableError,
        ContractMismatchError,
        PipelineCheckError,
        WorkerError,
    )
    return {t.__name__: t for t in types}


def _resolve_send_timeout() -> float:
    """The steady-state send timeout: ``KEYSTONE_WIRE_SEND_TIMEOUT``
    seconds (shared env accessor, warned once when unparsable), default
    15s, floored at 0.1s — a zero timeout would turn every full kernel
    buffer into an instant false death."""
    from ..utils import env_float

    return env_float("KEYSTONE_WIRE_SEND_TIMEOUT", 15.0, minimum=0.1)


#: steady-state socket timeout both sides run with: a SEND that cannot
#: make progress for this long means the peer stopped reading (wedged /
#: SIGSTOPped / dead) and is treated as down — a blocking sendall with
#: no timeout would otherwise hold the per-connection send lock forever
#: once the kernel buffer fills, unbounding the health loop and the
#: documented bounded shutdown. RECEIVES simply keep waiting across
#: timeouts (an idle connection is legitimate); only EOF/errors end them.
#: Configurable via ``KEYSTONE_WIRE_SEND_TIMEOUT`` (read once at import,
#: like every wire constant — both endpoint processes read their own
#: environment, which the router's spawn path propagates).
SEND_TIMEOUT_S = _resolve_send_timeout()


def send_payload(sock: socket.socket, payload: bytes) -> None:
    """Write one length-framed, already-encoded payload. Callers
    serialize access per socket (the router's per-worker send lock / the
    worker's reply lock). A ``socket.timeout`` from a full, unread
    buffer surfaces as :class:`ConnectionClosed` — the peer has
    effectively left, and a partially-sent frame has desynced the
    stream anyway."""
    try:
        sock.sendall(_LEN.pack(len(payload)) + payload)
    except socket.timeout as e:
        raise ConnectionClosed(
            f"peer stopped reading (send stalled {SEND_TIMEOUT_S:.0f}s)"
        ) from e


def send_msg(sock: socket.socket, msg: Any) -> None:
    """Write one framed CONTROL message (pickle). Hot-path senders
    encode explicitly (:func:`encode_msg`) and use :func:`send_payload`
    so encode time is attributable per frame."""
    send_payload(
        sock, pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    )


def encode_msg(
    msg: Any,
    codec: str = "pickle",
    shm=None,
    min_shm_bytes: int = 1 << 16,
    metrics=None,
) -> bytes:
    """One message as frame payload bytes. ``codec="binary"`` attempts
    the hot codec for member-list ``req``/``res`` dicts (with ``shm`` as
    this direction's TX ring) and falls back to pickle whenever the
    frame is not binary-describable — the receiver dispatches on the
    first payload byte, so the fallback needs no signalling."""
    if codec == "binary":
        from . import codec as codec_mod

        try:
            payload = codec_mod.encode(
                msg, shm=shm, min_shm_bytes=min_shm_bytes, metrics=metrics
            )
        except Exception:
            logging.getLogger(__name__).debug(
                "binary encode failed; falling back to pickle",
                exc_info=True,
            )
            payload = None
        if payload is not None:
            return payload
    return pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)


def decode_payload(payload: bytes, shm=None, copy: bool = True) -> Any:
    """One frame payload back into its message, dispatching per frame on
    the leading byte: the binary magic routes to :mod:`.codec` (which
    raises its typed :class:`~keystone_tpu.cluster.codec.CodecError` on
    any malformed frame — binary bytes are NEVER unpickled), anything
    else is a pickle control frame."""
    if payload[:1] and payload[0] != 0x80:
        from . import codec as codec_mod

        if payload[0] == codec_mod.MAGIC:
            return codec_mod.decode(payload, shm=shm, copy=copy)
    return pickle.loads(payload)


def recv_payload(
    sock: socket.socket, deadline: Optional[float] = None
) -> bytes:
    """Read exactly one frame's payload bytes; :class:`ConnectionClosed`
    on EOF or a torn frame. Socket timeouts while WAITING for a frame
    are not errors (idle peer) — the wait continues, unless ``deadline``
    (a ``time.monotonic()`` stamp; the handshake path) passes first."""
    header = _recv_exact(sock, _LEN.size, deadline)
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME_BYTES:
        raise ConnectionClosed(
            f"frame length {n} exceeds {MAX_FRAME_BYTES} — desynced stream"
        )
    return _recv_exact(sock, n, deadline)


def recv_msg(
    sock: socket.socket,
    deadline: Optional[float] = None,
    shm=None,
    copy: bool = True,
) -> Any:
    """Read + decode exactly one framed message (see
    :func:`recv_payload` / :func:`decode_payload`)."""
    return decode_payload(
        recv_payload(sock, deadline), shm=shm, copy=copy
    )


def _recv_exact(
    sock: socket.socket, n: int, deadline: Optional[float] = None
) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            part = sock.recv(n - len(buf))
        except socket.timeout:
            # idle is fine; only EOF/errors/an explicit deadline end it
            if deadline is not None and time.monotonic() >= deadline:
                raise ConnectionClosed(
                    "peer sent nothing before the deadline"
                ) from None
            continue
        except OSError as e:
            raise ConnectionClosed(f"socket error mid-frame: {e}") from e
        if not part:
            raise ConnectionClosed(
                "peer closed the connection"
                + (" mid-frame" if buf else "")
            )
        buf.extend(part)
    return bytes(buf)


# -- deadlines across the boundary -------------------------------------------


def deadline_to_wire(deadline: Optional[float]) -> Optional[float]:
    """Absolute ``time.monotonic()`` deadline → remaining-seconds budget
    (clamped at 0: an already-expired deadline stays expired, it does
    not wrap into a huge budget)."""
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


def deadline_from_wire(remaining: Optional[float]) -> Optional[float]:
    """Remaining budget → absolute deadline on THIS process's clock."""
    if remaining is None:
        return None
    return time.monotonic() + float(remaining)


# -- QoS identity across the boundary ----------------------------------------


def qos_to_wire(priority: Optional[str], tenant: Optional[str]) -> dict:
    """The ``req``-frame keys carrying a request's QoS identity; only
    non-default values are shipped (most traffic is default-class, and
    the frame stays minimal)."""
    out = {}
    if priority and priority != "normal":
        out["priority"] = str(priority)
    if tenant and tenant != "default":
        out["tenant"] = str(tenant)
    return out


def qos_from_wire(msg: dict) -> "tuple[str, str]":
    """``(priority, tenant)`` off a ``req`` frame, defaulting absent
    keys — frames from a pre-QoS peer decode as normal/default."""
    return (
        str(msg.get("priority") or "normal"),
        str(msg.get("tenant") or "default"),
    )


# -- cost accounting across the boundary -------------------------------------


def costs_to_wire(table: Optional[dict]) -> Optional[dict]:
    """A cost-table slice (``{tenant: {priority: {device_s, queue_s,
    payload_bytes, items}}}``) as the compact wire form ``{tenant:
    {priority: [device_s, queue_s, payload_bytes, items]}}`` — rows that
    charge nothing are dropped, and an empty table ships as None so the
    pong frame stays minimal on idle workers."""
    out: dict = {}
    for tenant, prios in (table or {}).items():
        for priority, row in (prios or {}).items():
            vals = [
                round(float(row.get("device_s") or 0.0), 6),
                round(float(row.get("queue_s") or 0.0), 6),
                int(row.get("payload_bytes") or 0),
                int(row.get("items") or 0),
            ]
            if any(vals):
                out.setdefault(str(tenant), {})[str(priority)] = vals
    return out or None


def costs_from_wire(payload: Optional[dict]) -> list:
    """Wire cost rows → ``[(tenant, priority, {field: value})]``;
    malformed rows (a pre-accounting peer, a truncated frame) decode as
    an empty list rather than poisoning the pong handler."""
    rows = []
    for tenant, prios in (payload or {}).items():
        if not isinstance(prios, dict):
            continue
        for priority, vals in prios.items():
            if not isinstance(vals, (list, tuple)) or len(vals) < 4:
                continue
            try:
                rows.append((
                    str(tenant),
                    str(priority),
                    {
                        "device_s": float(vals[0]),
                        "queue_s": float(vals[1]),
                        "payload_bytes": int(vals[2]),
                        "items": int(vals[3]),
                    },
                ))
            except (TypeError, ValueError):
                continue
    return rows


# -- typed errors across the boundary ----------------------------------------


def encode_error(exc: BaseException) -> dict:
    """One registered serving error (or anything else, degraded) as a
    wire-safe dict."""
    kind = type(exc).__name__
    if kind not in _registry():
        return {
            "kind": "WorkerError",
            "message": str(exc),
            "original": kind,
        }
    return {"kind": kind, "message": str(exc)}


def decode_error(enc: dict) -> BaseException:
    """Reconstruct the typed error; unknown kinds come back as
    :class:`WorkerError`."""
    kind = str(enc.get("kind", "WorkerError"))
    message = str(enc.get("message", ""))
    cls = _registry().get(kind)
    if cls is None or cls is WorkerError:
        return WorkerError(enc.get("original", kind), message)
    if cls.__name__ == "NotTraceableError":
        # its __init__ takes the label list, not a message
        return cls([message])
    try:
        return cls(message)
    except Exception:
        logging.getLogger(__name__).debug(
            "decoding %s with a message-only constructor failed; "
            "degrading to WorkerError", kind, exc_info=True,
        )
        return WorkerError(kind, message)
