"""The multi-process serving tier: a front-door router over worker
processes.

The serving package scales to N replica THREADS in one process — one
GIL, one host. This package is the layer above it, the shape the
KeystoneML premise (cluster-scale dataflow, PAPERS.md #1) and the
Spark-perf study's driver-bottleneck findings (PAPERS.md #3) call for:

* :class:`ClusterRouter` — admission control and deadline shedding at
  the front door (the fleet scheduler's learned batch-service EWMA,
  priced from aggregate queue depth ÷ fleet-wide capacity),
  least-outstanding load balancing, worker health checks,
  crash-respawn supervision within a restart budget, merged fleet-wide
  metrics, and bounded signal-safe shutdown.
* :mod:`~keystone_tpu.cluster.worker` — the worker process: owns a
  subset of the mesh data axis
  (:func:`~keystone_tpu.parallel.placement.worker_device_indices`),
  runs a local :class:`~keystone_tpu.serving.ServingFleet` over it, and
  boots WARM by sharing the AOT executable cache directory and
  bucket-signature manifest over the filesystem — a worker against a
  warm cache pays zero traces, reported in its ``ready`` message.
* :mod:`~keystone_tpu.cluster.wire` — the length-framed socket
  protocol: per-request deadlines cross the process boundary as
  remaining budget (never extended by the hop), and the serving layer's
  typed errors (``Shed``, ``DeadlineExceeded``, ``QueueFull``, …)
  arrive as the same types on the other side.
* The hot wire path — compatible admitted requests coalesce into ONE
  member-list frame (:meth:`ServiceEstimate.coalesce_window` prices the
  hold), hot frames ride the pickle-free binary codec
  (:mod:`~keystone_tpu.cluster.codec`, negotiated at handshake,
  ``KEYSTONE_WIRE_CODEC=pickle`` kills it), and same-host payloads
  above ``KEYSTONE_SHM_MIN_BYTES`` move zero-copy through
  :mod:`~keystone_tpu.cluster.shm` rings. See the README's "Hot wire
  path" subsection.

Sharded chunk PRODUCTION — the training-side half of the same
host-bottleneck story — lives with the data layer
(:mod:`keystone_tpu.data.shards`, ``KEYSTONE_SCAN_SHARDS``).

Knobs: ``--workers N`` on the serve demo / ``KEYSTONE_WORKERS`` size
the tier; see the README's "Multi-process serving" section for the
topology and the warm-boot contract.
"""

from .codec import CodecError
from .router import ClusterRouter, default_workers, format_status
from .shm import ShmRing
from .wire import ConnectionClosed, WorkerError

__all__ = [
    "ClusterRouter",
    "CodecError",
    "ConnectionClosed",
    "ShmRing",
    "WorkerError",
    "default_workers",
    "format_status",
]
