"""Same-host zero-copy payload transport: a shared-memory slot ring per
wire direction.

The binary codec (:mod:`.codec`) removed serialization from the hot
path; what remains for a large array is the MOVE — sender copies into a
kernel socket buffer, receiver copies back out. For peers on one host
that round trip is pure waste: ``multiprocessing.shared_memory`` maps
the same pages into both processes, so a payload written once is simply
THERE on the other side. This module is the minimal discipline that
makes that safe:

* **One ring per direction, single writer.** The router creates BOTH
  segments for a worker slot (it owns their lifetime — creation before
  spawn, unlink on death/retire/shutdown) and names them in the worker's
  spec; the worker attaches and confirms in its ``ready`` report (the
  negotiation: a worker that cannot attach — exotic platform, /dev/shm
  mounted noexec — answers ``shm: false`` and the router unlinks and
  runs inline, no retry loop). The router writes only the
  router→worker ring; the worker writes only worker→router.
* **In-segment slot states.** The first ``slots`` bytes are the state
  table (0 = FREE, 1 = BUSY); the rest is ``slots`` fixed-size payload
  regions. The WRITER flips FREE→BUSY under its local lock (it is the
  only allocator); the READER flips BUSY→FREE once the member the slot
  carried is answered — reply receipt IS the reclamation signal, so no
  ack traffic exists. A torn write cannot corrupt the protocol: the
  slot index travels inside the socket frame, which is itself
  length-framed and typed.
* **Degradation, counted.** A full ring (or a payload larger than a
  slot) falls back to inline frame bytes — ``shm.fallback`` counts it,
  the receiver never knows the difference. Worker death unlinks both
  segments (a respawn gets FRESH segments under a new generation name:
  slots a dead peer held never leak into the new incarnation).

Readers hand out zero-copy memoryviews; callers that keep data past the
slot's free (the router's reply path) copy first — :mod:`.codec` owns
that contract.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_FREE = 0
_BUSY = 1


def _unregister_tracker(name: str) -> None:
    """Detach this process's resource_tracker claim on an ATTACHED
    segment: before 3.13 the tracker registers attaches too, and would
    unlink the router-owned segment when the worker exits — exactly the
    double-unlink this guards."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(f"/{name}", "shared_memory")
    except Exception:
        logger.debug(
            "resource_tracker unregister for %s failed (harmless on "
            "newer Pythons)", name, exc_info=True,
        )


class ShmRing:
    """One direction's slot ring over a ``SharedMemory`` segment.

    ``create=True`` (the router) allocates and later :meth:`unlink`\\ s;
    ``create=False`` (the worker) attaches to the named segment. The
    writer side calls :meth:`alloc` + :meth:`write`; the reader side
    :meth:`view` + :meth:`free`."""

    def __init__(
        self,
        name: str,
        slots: int,
        slot_bytes: int,
        create: bool = False,
    ):
        from multiprocessing import shared_memory

        if slots < 1 or slot_bytes < 1:
            raise ValueError(
                f"need at least one slot of at least one byte, got "
                f"{slots}x{slot_bytes}"
            )
        self.name = name
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._created = bool(create)
        size = self.slots + self.slots * self.slot_bytes
        self._shm = shared_memory.SharedMemory(
            name=name, create=create, size=size
        )
        if create:
            self._shm.buf[: self.slots] = bytes(self.slots)
        else:
            _unregister_tracker(self._shm.name)
        #: serializes this PROCESS's concurrent allocators; cross-process
        #: safety needs no lock — each state byte has exactly one writer
        #: per transition direction (writer FREE→BUSY, reader BUSY→FREE)
        self._lock = threading.Lock()
        self._closed = False

    def _data_off(self, slot: int) -> int:
        return self.slots + slot * self.slot_bytes

    # -- writer side -----------------------------------------------------

    def alloc(self, nbytes: int) -> Optional[int]:
        """A FREE slot marked BUSY for ``nbytes`` of payload, or None
        (payload too large for any slot, ring exhausted, ring closed) —
        the caller degrades to inline bytes."""
        if nbytes > self.slot_bytes:
            return None
        with self._lock:
            if self._closed:
                return None
            buf = self._shm.buf
            for slot in range(self.slots):
                if buf[slot] == _FREE:
                    buf[slot] = _BUSY
                    return slot
        return None

    def write(self, slot: int, data) -> None:
        """Payload bytes into an :meth:`alloc`'d slot (the one memcpy
        this transport pays — into shared pages instead of the kernel)."""
        off = self._data_off(slot)
        n = len(data) if not isinstance(data, memoryview) else data.nbytes
        self._shm.buf[off: off + n] = data

    # -- reader side -----------------------------------------------------

    def view(self, slot: int, nbytes: int) -> memoryview:
        """Zero-copy read view of a slot's payload. The slot stays BUSY
        until :meth:`free` — callers keeping the data longer copy it."""
        if not (0 <= slot < self.slots) or nbytes > self.slot_bytes:
            from .codec import CodecError

            raise CodecError(
                f"shm descriptor out of range: slot {slot} ({nbytes} "
                f"byte(s)) in a {self.slots}x{self.slot_bytes} ring"
            )
        off = self._data_off(slot)
        return self._shm.buf[off: off + nbytes]

    def free(self, slot: int) -> None:
        """Reclaim a slot (reader side, after its member is answered)."""
        if 0 <= slot < self.slots and not self._closed:
            self._shm.buf[slot] = _FREE

    @property
    def in_use(self) -> int:
        return sum(
            1 for s in range(self.slots) if self._shm.buf[s] == _BUSY
        )

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Drop this process's mapping (both sides, idempotent)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        except BufferError:
            # a decoded view still aliases the buffer (e.g. an in-flight
            # request's datum) — the mapping lives until the view dies;
            # unlink below still removes the name
            logger.debug(
                "shm ring %s close deferred: exported views still alive",
                self.name,
            )
        except OSError:
            logger.debug(
                "shm ring %s close failed", self.name, exc_info=True
            )

    def unlink(self) -> None:
        """Remove the segment name (creator side — after this, only
        existing mappings keep the pages alive). Idempotent."""
        if not self._created:
            return
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        except OSError:
            logger.debug(
                "shm ring %s unlink failed", self.name, exc_info=True
            )


def make_ring_pair(base: str, slots: int, slot_bytes: int):
    """The router's creation helper: ``(c2w, w2c)`` rings under
    ``<base>c`` / ``<base>r``, or ``(None, None)`` when the platform
    refuses shared memory (the negotiation then settles on inline)."""
    try:
        c2w = ShmRing(base + "c", slots, slot_bytes, create=True)
    except Exception:
        logger.warning(
            "shared-memory ring %sc unavailable — wire payloads stay "
            "inline", base, exc_info=True,
        )
        return None, None
    try:
        w2c = ShmRing(base + "r", slots, slot_bytes, create=True)
    except Exception:
        logger.warning(
            "shared-memory ring %sr unavailable — wire payloads stay "
            "inline", base, exc_info=True,
        )
        c2w.close()
        c2w.unlink()
        return None, None
    return c2w, w2c
