"""The cluster worker process: one device subset, one local ServingFleet,
one socket back to the router.

``worker_main`` is the spawn target (module-level, picklable args). A
worker's life:

1. **Connect + hello.** Dial the router's listener, present the spawn
   token and worker id (the router refuses strangers — a stray process
   dialing the port cannot join the fleet).
2. **Warm boot.** Configure the SHARED AOT cache directory, build the
   model from the spec (a ``"module:callable"`` factory re-run
   deterministically, or an explicit pickle), carve this worker's device
   subset off the mesh data axis
   (:func:`~keystone_tpu.parallel.placement.worker_device_indices`), and
   start a local :class:`~keystone_tpu.serving.ServingFleet` over it.
   ``start()`` pre-warms every bucket AND every manifest signature from
   the shared cache (``compile/manifest.py`` reads are multi-process
   safe), so a worker booting against a warm cache pays ZERO traces —
   the warm-boot contract the ``ready`` message reports (``compiles`` /
   ``aot_loads``) and the smoke/bench gates assert.
3. **Serve.** One request message → one ``fleet.submit`` with the
   deadline re-anchored from its wire budget; the response rides back on
   the future's completion (replica threads answer out of order — the
   router matches by request id). Typed serving errors cross the wire by
   name (:mod:`.wire`), so a worker-side ``Shed`` is a router-side
   ``Shed``.
4. **Die loudly or drain cleanly.** ``stop`` drains the local fleet
   (bounded — the fleet's own shutdown discipline) and answers ``bye``;
   a dead router (EOF on the socket) shuts the fleet down and exits
   nonzero. SIGTERM gets the same bounded drain, so an operator's kill
   never strands in-flight requests silently.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import sys
import threading
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)


def resolve_model(model_spec: Any):
    """Build the FittedPipeline a worker serves.

    ``("factory", "module:callable", kwargs)`` imports and calls —
    the deterministic-rebuild path (same fit ⇒ same AOT fingerprint ⇒
    warm boot from the shared cache). ``("pickle", bytes)`` unpickles an
    explicitly shipped model."""
    kind = model_spec[0]
    if kind == "factory":
        import importlib

        path, kwargs = model_spec[1], model_spec[2] or {}
        mod_name, _, fn_name = path.partition(":")
        if not fn_name:
            raise ValueError(
                f"model factory {path!r} must be 'module:callable'"
            )
        fn = getattr(importlib.import_module(mod_name), fn_name)
        return fn(**kwargs)
    if kind == "pickle":
        import pickle

        return pickle.loads(model_spec[1])  # lint: allow-pickle -- explicit model artifact from the router's boot spec
    raise ValueError(f"unknown model spec kind {kind!r}")


def _worker_devices(worker_id: int, n_workers: int, replicas: Optional[int]):
    """This worker's replica→device list: its contiguous slice of the
    mesh data axis, round-robined up to ``replicas`` when more workers
    than devices (or an explicit replica count) ask for co-residents."""
    from ..parallel.placement import data_axis_devices, worker_device_indices

    devs = data_axis_devices()
    idxs = worker_device_indices(worker_id, n_workers)
    n = replicas if replicas is not None else len(idxs)
    return [devs[idxs[i % len(idxs)]] for i in range(max(1, n))]


def worker_main(host: str, port: int, token: str, worker_id: int,
                spec: dict) -> int:
    """Spawn-target entry point; returns the process exit code."""
    logging.basicConfig(
        level=getattr(
            logging, str(spec.get("log_level", "warning")).upper(),
            logging.WARNING,
        ),
        format=(
            f"[worker-{worker_id}] %(levelname)s %(name)s: %(message)s"
        ),
    )
    if spec.get("virtual_devices"):
        from ..parallel.virtual import provision_virtual_devices

        provision_virtual_devices(int(spec["virtual_devices"]))
    if spec.get("aot_cache"):
        from .. import compile as compile_mod

        compile_mod.configure(spec["aot_cache"])

    from ..obs import flight as _flight
    from ..obs import tracer as _obs_tracer
    from ..obs.context import TraceContext
    from ..obs.export import wire_spans
    from ..obs.span import Span
    from ..serving import ServingFleet
    from ..utils import env_int
    from .wire import (
        ConnectionClosed,
        costs_to_wire,
        deadline_from_wire,
        decode_payload,
        encode_error,
        encode_msg,
        qos_from_wire,
        recv_payload,
        send_payload,
    )

    from .wire import SEND_TIMEOUT_S

    # the flight recorder is always on; SIGQUIT gives operators an
    # on-demand post-mortem dump of a live worker
    _flight.install_sigquit_dump()
    # the router propagates its tracing decision: a traced router means
    # traced workers, whose spans ship back on stats replies and stitch
    # into ONE cross-process trace (obs/export.py)
    tracer = _obs_tracer.start() if spec.get("trace") else None
    process_name = f"keystone:worker-{worker_id}/{os.getpid()}"
    span_cursor = [0]  # spans_since bookmark: each span ships once
    # (tenant, priority) -> last-shipped cumulative cost row: pongs ship
    # deltas so the router can fold them additively without re-counting
    cost_cursor: dict = {}

    def _cost_deltas(cursor: dict, table: dict) -> dict:
        out: dict = {}
        for tenant, prios in table.items():
            for priority, row in prios.items():
                prev = cursor.get((tenant, priority)) or {}
                delta = {
                    k: row.get(k, 0) - prev.get(k, 0)
                    for k in ("device_s", "queue_s", "payload_bytes", "items")
                }
                cursor[(tenant, priority)] = dict(row)
                if any(v > 1e-9 if isinstance(v, float) else v
                       for v in delta.values()):
                    out.setdefault(tenant, {})[priority] = delta
        return out

    # the hot-wire negotiation: the router's spec names the codec it
    # will SEND (and expects back) and, when same-host zero-copy is on,
    # the shared-memory ring pair this worker should attach. An attach
    # failure is a negotiation answer, not an error: the ready report
    # says shm=false and everything stays inline.
    reply_codec = (
        "binary"
        if (spec.get("wire") or {}).get("codec") == "binary"
        else "pickle"
    )
    shm_min_bytes = env_int("KEYSTONE_SHM_MIN_BYTES", 1 << 16, minimum=1)
    shm_rx = shm_tx = None
    shm_cfg = spec.get("shm")
    if shm_cfg and reply_codec == "binary":
        from .shm import ShmRing

        try:
            shm_rx = ShmRing(
                shm_cfg["c2w"], shm_cfg["slots"], shm_cfg["slot_bytes"]
            )
            shm_tx = ShmRing(
                shm_cfg["w2c"], shm_cfg["slots"], shm_cfg["slot_bytes"]
            )
        except Exception:
            logger.warning(
                "worker %d: shared-memory attach failed — wire payloads "
                "stay inline", worker_id, exc_info=True,
            )
            if shm_rx is not None:
                shm_rx.close()
            shm_rx = shm_tx = None

    sock = socket.create_connection((host, port), timeout=30.0)
    # bounded sends, timeout-tolerant receives (see wire.SEND_TIMEOUT_S)
    sock.settimeout(SEND_TIMEOUT_S)
    send_lock = threading.Lock()
    # control replies go out before the fleet (and its registry) exists;
    # the wire counters attach once it does
    metrics_ref: list = [None]

    def reply(msg: dict) -> None:
        # control frames: always pickle, any dict shape
        payload = encode_msg(msg)
        with send_lock:
            send_payload(sock, payload)
        m = metrics_ref[0]
        if m is not None:
            kind = msg.get("type")
            m.inc(f"wire.frames.{kind}")
            m.inc(f"wire.bytes_sent.{kind}", len(payload))

    reply({
        "type": "hello", "token": token, "worker": worker_id,
        "pid": os.getpid(),
        # codec capability advertisement: the router sends binary hot
        # frames only to peers that claim at least this version
        "codec": 1,
    })

    fitted = resolve_model(spec["model"])
    # upfront contract validation: the router's spec'd datum shape/dtype
    # against the model's STATIC check report — a mis-deployed model
    # (wrong artifact for this topology) fails the boot with a typed,
    # node-attributed error instead of serving garbage or tracing a
    # doomed bucket set (the fleet constructor re-validates coupling)
    fitted.check(span=False).require_contract(
        spec.get("datum_shape"), spec.get("dtype"), verb="boot"
    )
    devices = _worker_devices(
        worker_id, int(spec.get("n_workers", 1)), spec.get("replicas")
    )
    fleet = ServingFleet(
        fitted,
        devices=devices,
        buckets=tuple(spec.get("buckets") or (1, 8, 32, 64)),
        datum_shape=spec.get("datum_shape"),
        dtype=spec.get("dtype"),
        max_queue=int(spec.get("max_queue", 1024)),
        max_wait_ms=float(spec.get("max_wait_ms", 2.0)),
        tenant_weights=spec.get("tenant_weights"),
    )
    fleet.start(warmup=spec.get("warmup"))
    metrics_ref[0] = fleet.metrics
    snap = fleet.metrics.snapshot()
    reply({
        "type": "ready",
        "worker": worker_id,
        "compiles": snap["counters"].get("compiles", 0),
        "aot_loads": snap["counters"].get("aot_loads", 0),
        "capacity": fleet.n_replicas * fleet.policy.max_size,
        "replicas": fleet.n_replicas,
        "devices": [str(d) for d in devices],
        # the shm negotiation's closing answer: true means both rings
        # attached and zero-copy payloads are live on this connection
        "shm": shm_rx is not None,
    })
    logger.info(
        "worker %d ready: %d replica(s) on %s (compiles=%d aot_loads=%d)",
        worker_id, fleet.n_replicas, [str(d) for d in devices],
        snap["counters"].get("compiles", 0),
        snap["counters"].get("aot_loads", 0),
    )

    stopping = threading.Event()

    def _drain_and_exit(signum, frame):
        # bounded by the fleet's own drain/join timeouts — and run on a
        # SPAWNED thread, never in the handler frame: the signal may
        # interrupt the main thread INSIDE fleet.submit holding the
        # scheduler's non-reentrant lock, and shutdown() takes that same
        # lock (the router's handler avoids the identical deadlock)
        if stopping.is_set():
            return
        stopping.set()

        def _stop():
            try:
                fleet.shutdown(drain=True)
            finally:
                os._exit(0)

        threading.Thread(
            target=_stop, name="ks-worker-sigterm", daemon=False
        ).start()

    try:
        signal.signal(signal.SIGTERM, _drain_and_exit)
    except ValueError:
        pass  # non-main thread (embedded use): router stop still works

    class _ReplyGroup:
        """One coalesced request frame's answer aggregator: members
        settle out of order on replica threads, ONE reply frame goes
        back when the last lands, and only then are the request frame's
        shm slots freed — reply receipt is the ring's reclamation
        signal, so a slot is never reused while its datum may still be
        read."""

        def __init__(self, n: int, legacy: bool, req_shm_slots):
            self._lock = threading.Lock()
            self._remaining = n
            self.members: list = [None] * n
            self.legacy = legacy
            self.req_shm_slots = tuple(req_shm_slots or ())
            #: first traced member's id — the reply-side wire.encode
            #: span hangs off it
            self.traced_id: Optional[str] = None

        def settle(self, pos: int, member: dict) -> None:
            with self._lock:
                self.members[pos] = member
                self._remaining -= 1
                done = self._remaining == 0
            if done:
                _send_res(self)

    def _send_res(group: "_ReplyGroup") -> None:
        # t_unix lets the router price the REPLY hop's transport (unix
        # clocks are host-shared; monotonic ones are not)
        t_unix = time.time()
        t0 = t1 = 0.0
        try:
            if group.legacy:
                # a legacy single-request frame gets the legacy reply
                # shape — old routers never see member lists
                msg = dict(group.members[0])
                msg["type"] = "res"
                msg["t_unix"] = t_unix
                payload = encode_msg(msg)
            else:
                t0 = time.perf_counter()
                payload = encode_msg(
                    {
                        "type": "res",
                        "members": group.members,
                        "t_unix": t_unix,
                    },
                    codec=reply_codec,
                    shm=shm_tx,
                    min_shm_bytes=shm_min_bytes,
                    metrics=fleet.metrics,
                )
                t1 = time.perf_counter()
            with send_lock:
                send_payload(sock, payload)
            fleet.metrics.inc("wire.frames.res")
            fleet.metrics.inc("wire.bytes_sent.res", len(payload))
            if (
                group.traced_id is not None and tracer is not None
                and not group.legacy
            ):
                tracer.record_complete(Span(
                    name="wire.encode", start=t0, end=t1,
                    op_type="ClusterWorker",
                    attrs={
                        "trace_id": group.traced_id,
                        "codec": reply_codec,
                        "bytes": len(payload),
                        "members": len(group.members),
                    },
                ))
        except Exception:
            # router gone; its death handling requeues
            logger.debug(
                "reply frame undeliverable (router gone?)", exc_info=True
            )
        finally:
            if shm_rx is not None:
                for s in group.req_shm_slots:
                    shm_rx.free(s)

    def _member_done(pos: int, req_id: int, fut, group, ctx=None,
                     t_recv_pc=None, transport_s=None) -> None:
        try:
            member = {"id": req_id, "ok": True, "value": fut.result()}
        except BaseException as e:  # noqa: BLE001 — typed over the wire
            member = {"id": req_id, "ok": False, "error": encode_error(e)}
        group.settle(pos, member)
        if ctx is not None and tracer is not None:
            # the worker-residency hop: wire arrival -> reply settled,
            # stitched under the request's cross-process identity with
            # the inbound transport it measured off the wire stamp
            tracer.record_complete(Span(
                name="cluster.handle",
                start=t_recv_pc,
                end=time.perf_counter(),
                op_type="ClusterWorker",
                attrs={
                    "trace_id": ctx.trace_id,
                    # the sender's hop: which edge this residency span
                    # hangs under in the stitched tree
                    "parent_hop": ctx.hop,
                    "worker": worker_id,
                    "transport_s": round(transport_s or 0.0, 6),
                },
            ))

    rc = 0
    try:
        while True:
            payload = recv_payload(sock)
            t_dec0 = time.perf_counter()
            # copy=False: member data may view shm ring slots directly —
            # the fleet consumes each datum before its reply frees the
            # slot, so the zero-copy view is safe for exactly that long
            msg = decode_payload(payload, shm=shm_rx, copy=False)
            t_recv_pc = time.perf_counter()
            kind = msg.get("type")
            if kind == "req":
                members = msg.get("members")
                legacy = members is None
                if legacy:
                    members = [msg]  # pre-coalescing router frame
                group = _ReplyGroup(
                    len(members), legacy, msg.get("_shm_slots")
                )
                for pos, m in enumerate(members):
                    req_id = m["id"]
                    deadline = deadline_from_wire(m.get("deadline_rem"))
                    ctx = TraceContext.from_wire(m.get("trace"))
                    transport_s = (
                        ctx.transport_seconds() if ctx is not None
                        else None
                    )
                    if ctx is not None and group.traced_id is None:
                        group.traced_id = ctx.trace_id
                    try:
                        timeout = (
                            None if deadline is None
                            else max(0.0, deadline - time.monotonic())
                        )
                        priority, tenant = qos_from_wire(m)
                        # every member keeps its own QoS/deadline/trace
                        # identity inside the fleet — coalescing shares
                        # the FRAME, never the scheduling class
                        fut = fleet.submit(
                            m["datum"], timeout=timeout, trace=ctx,
                            priority=priority, tenant=tenant,
                        )
                    except BaseException as e:  # Shed/QueueFull typed back
                        group.settle(pos, {
                            "id": req_id, "ok": False,
                            "error": encode_error(e),
                        })
                        continue
                    fut.add_done_callback(
                        lambda f, p=pos, rid=req_id, g=group, c=ctx,
                        t=t_recv_pc, tr=transport_s: _member_done(
                            p, rid, f, g, ctx=c, t_recv_pc=t,
                            transport_s=tr,
                        )
                    )
                if group.traced_id is not None and tracer is not None:
                    tracer.record_complete(Span(
                        name="wire.decode", start=t_dec0, end=t_recv_pc,
                        op_type="ClusterWorker",
                        attrs={
                            "trace_id": group.traced_id,
                            "codec": (
                                "pickle" if payload[:1] == b"\x80"
                                else "binary"
                            ),
                            "bytes": len(payload),
                            "members": len(members),
                        },
                    ))
            elif kind == "ping":
                # the router's health cadence doubles as the worker's
                # metrics-timeline sampler: one row per ping
                fleet.metrics.sample_timeline()
                pong = {
                    "type": "pong",
                    "t": msg.get("t"),
                    "service_estimate": fleet.scheduler.service_estimate,
                }
                # per-tenant cost DELTAS since the last pong ride the
                # health cadence, so the router's own timeline (and its
                # SloWatchdog's tenant-spend budget) tracks fleet-wide
                # spend continuously, not just on stats round-trips
                table = fleet.metrics.cost_table()
                deltas = _cost_deltas(cost_cursor, table)
                wired = costs_to_wire(deltas)
                if wired:
                    pong["costs"] = wired
                reply(pong)
            elif kind == "stats":
                # a stats round-trip always carries a fresh timeline row
                # (pings drive the steady cadence; an early status() call
                # must not render an empty worker timeline)
                fleet.metrics.sample_timeline()
                shipped = []
                spans_dropped = 0
                if tracer is not None:
                    fresh, span_cursor[0] = tracer.spans_since(
                        span_cursor[0]
                    )
                    # bounded shipping: a stats reply must stay a small
                    # frame even after a long untapped tracing window —
                    # overflow is DROPPED, but counted, never silent
                    spans_dropped = max(0, len(fresh) - 4096)
                    if spans_dropped:
                        _flight.record_instant(
                            "trace.spans_dropped", n=spans_dropped,
                            worker=worker_id,
                        )
                    shipped = wire_spans(
                        fresh[-4096:], tracer.epoch, tracer.epoch_unix,
                        process_name=process_name,
                    )
                    # the router now owns these spans — discarding them
                    # keeps an always-on traced worker's registry
                    # bounded by the stats cadence, not the uptime
                    tracer.discard_through(span_cursor[0])
                reply({
                    "type": "stats",
                    "worker": worker_id,
                    "seq": msg.get("seq"),
                    "snapshot": fleet.metrics.snapshot(sketches=True),
                    "qos": fleet.qos_snapshot(),
                    "spans": shipped,
                    "spans_dropped": spans_dropped,
                })
            elif kind == "stop":
                fleet.shutdown(drain=bool(msg.get("drain", True)))
                reply({"type": "bye", "worker": worker_id})
                break
            else:
                logger.warning("worker %d: unknown message %r", worker_id, kind)
    except ConnectionClosed:
        if not stopping.is_set():
            logger.warning(
                "worker %d: router connection lost — shutting down", worker_id
            )
            rc = 1
    finally:
        try:
            fleet.shutdown(drain=False)
        except Exception:
            logger.exception("worker %d: fleet shutdown failed", worker_id)
        try:
            sock.close()
        except OSError:
            pass
        # drop the shm mappings (the ROUTER owns unlink; a worker only
        # ever attaches)
        for ring in (shm_rx, shm_tx):
            if ring is not None:
                ring.close()
    return rc


def main(argv=None) -> int:  # pragma: no cover - exercised via spawn
    """Debug entry: ``python -m keystone_tpu.cluster.worker host port
    token worker_id`` with the spec pickled on stdin."""
    import pickle

    host, port, token, worker_id = argv or sys.argv[1:5]
    spec = pickle.load(sys.stdin.buffer)  # lint: allow-pickle -- boot spec from the parent router's stdin pipe
    return worker_main(host, int(port), token, int(worker_id), spec)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
