"""The binary hot codec: fixed-layout ``req``/``res`` frames with raw
ndarray bytes — no pickle anywhere on the serving hot path.

Pickle is a fine control-plane serializer (handshake, stats, errors ride
it still — see :mod:`.wire`), but on the per-request path it is pure
interpretive overhead: every frame re-describes its own schema, every
array round-trips through pickle's buffer machinery, and the receiver
runs a stack VM to rebuild a dict whose shape never changes. This module
replaces that with a self-describing fixed layout:

``MAGIC VERSION KIND FLAGS COUNT`` (header) then ``COUNT`` members, each
a fixed per-member header (id, flags, deadline budget, QoS identity,
trace context) followed by one ndarray descriptor — dtype code, ndim,
dims, byte length — and the raw C-contiguous bytes, either INLINE in the
frame or as a (slot, nbytes) descriptor into a negotiated shared-memory
ring (:mod:`.shm`), in which case the socket frame carries only the
header and the bytes never cross the kernel at all.

Interop is per-frame, not per-connection: a pickle payload (protocol
>= 2) always begins with ``0x80``, so :data:`MAGIC` is simply a first
byte no pickle payload can start with — a receiver dispatches on it and
accepts either encoding regardless of what it negotiated to SEND. The
kill switch ``KEYSTONE_WIRE_CODEC=pickle`` therefore needs no protocol
reset, and a version-skewed peer degrades typed: any malformed, torn, or
future-versioned binary frame raises :class:`CodecError` (a
:class:`~keystone_tpu.cluster.wire.ConnectionClosed` — a desynced hot
stream is indistinguishable from a dead peer) and is NEVER handed to
``pickle.loads`` — arbitrary unpickling of hot-path bytes is exactly the
attack surface this module closes.

A frame whose members cannot be described by the dtype table (object
arrays, exotic extension dtypes, non-array payloads) is not encodable;
:func:`encode` returns None and the caller falls back to the pickle
control path — correctness never depends on the fast path applying.
"""

from __future__ import annotations

import struct
from typing import Any, List, Optional

import numpy as np

from .wire import ConnectionClosed

#: first payload byte of a binary frame. Pickle protocol >= 2 payloads
#: always begin with 0x80 (the PROTO opcode), so any value != 0x80
#: discriminates per-frame; 0xB5 also cannot begin a protocol-0/1 text
#: pickle (those start with ASCII opcodes).
MAGIC = 0xB5
VERSION = 1

KIND_REQ = 1
KIND_RES = 2
_KIND_NAMES = {KIND_REQ: "req", KIND_RES: "res"}

# member flag bits
_MF_DEADLINE = 0x01
_MF_TRACE = 0x02
_MF_SHM = 0x04
_MF_ERROR = 0x08

_HDR = struct.Struct(">BBBBH")  # magic, version, kind, flags, count
_MEMBER = struct.Struct(">QB")  # id, member flags
_F64 = struct.Struct(">d")
_STR = struct.Struct(">I")  # utf-8 byte length prefix
_ARR = struct.Struct(">BB")  # dtype code, ndim
_DIM = struct.Struct(">I")
_NBYTES = struct.Struct(">Q")
_SLOT = struct.Struct(">IQ")  # shm slot index, byte length

#: the closed dtype vocabulary — codes are WIRE FORMAT, append-only.
#: bfloat16 joins when ml_dtypes is importable (it is wherever jax is);
#: a peer without it simply never sees code 14 because the sender's own
#: table gates what it emits.
_DTYPE_NAMES = [
    "bool", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64", "complex64", "complex128",
]
try:  # pragma: no cover - environment-dependent
    import ml_dtypes as _ml_dtypes

    _BF16: Optional[np.dtype] = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None
except TypeError:  # pragma: no cover - ml_dtypes/numpy skew
    _BF16 = None

_CODE_TO_DTYPE = {i: np.dtype(n) for i, n in enumerate(_DTYPE_NAMES)}
if _BF16 is not None:  # pragma: no branch
    _CODE_TO_DTYPE[len(_DTYPE_NAMES)] = _BF16
_DTYPE_TO_CODE = {dt: code for code, dt in _CODE_TO_DTYPE.items()}

#: a corrupt dim count must not drive a giant allocation before the
#: nbytes cross-check catches it
_MAX_NDIM = 32


class CodecError(ConnectionClosed):
    """A binary frame that cannot be decoded: truncated, corrupt, or
    from a future codec version. Subclasses
    :class:`~keystone_tpu.cluster.wire.ConnectionClosed` because a hot
    stream that produced it is desynced — the connection is treated as
    down and the requests it carried requeue on peers, typed."""


def _as_wire_array(value: Any) -> Optional[np.ndarray]:
    """``value`` as a C-contiguous ndarray the dtype table can describe,
    or None (caller falls back to pickle). Only array-shaped values are
    eligible — a Python scalar stays a scalar through the pickle path so
    the two codecs return bit-identical result OBJECTS, not just bytes."""
    if not (hasattr(value, "shape") and hasattr(value, "dtype")):
        return None
    try:
        arr = np.asarray(value)
    except Exception:  # lint: allow-silent -- unconvertible: pickle path
        return None
    if arr.dtype not in _DTYPE_TO_CODE:
        return None
    if not arr.flags["C_CONTIGUOUS"]:
        # NB: guarded — np.ascontiguousarray promotes 0-d to 1-d, and a
        # 0-d array is always contiguous, so it never reaches this
        arr = np.ascontiguousarray(arr)
    return arr


def _put_str(parts: List[Any], s: Optional[str]) -> None:
    raw = (s or "").encode("utf-8")
    parts.append(_STR.pack(len(raw)))
    parts.append(raw)


def _put_array(
    parts: List[Any],
    arr: np.ndarray,
    shm=None,
    min_shm_bytes: int = 1 << 16,
    metrics=None,
) -> None:
    """One ndarray descriptor + its bytes: into a ring slot when the
    payload clears the threshold and a slot is free, inline otherwise
    (counted — ring exhaustion degrades, never blocks)."""
    parts.append(_ARR.pack(_DTYPE_TO_CODE[arr.dtype], arr.ndim))
    for dim in arr.shape:
        parts.append(_DIM.pack(dim))
    nbytes = arr.nbytes
    try:
        # zero-copy byte view; extension dtypes (bfloat16) don't export
        # the buffer protocol and take the one-copy tobytes path
        view: Any = memoryview(arr).cast("B")
    except (ValueError, TypeError):
        view = arr.tobytes()
    if shm is not None and nbytes >= min_shm_bytes:
        slot = shm.alloc(nbytes)
        if slot is not None:
            shm.write(slot, view)
            parts.append(b"\x01")
            parts.append(_SLOT.pack(slot, nbytes))
            if metrics is not None:
                metrics.inc("shm.payloads")
                metrics.inc("shm.bytes", nbytes)
            return
        if metrics is not None:
            metrics.inc("shm.fallback")
    parts.append(b"\x00")
    parts.append(_NBYTES.pack(nbytes))
    parts.append(view)


def encode(
    msg: dict,
    shm=None,
    min_shm_bytes: int = 1 << 16,
    metrics=None,
) -> Optional[bytes]:
    """``msg`` (a member-list ``req``/``res`` dict — the wire schema
    :mod:`.router` and :mod:`.worker` speak) as one binary frame, or
    None when any member's payload falls outside the dtype table (the
    caller then pickles the SAME dict: the two encodings are
    interchangeable per frame).

    ``shm`` is this direction's TX ring; payloads of at least
    ``min_shm_bytes`` land in slots when one is free. The sender must
    not touch a written slot again — the receiver frees it once the
    member is answered (reply receipt IS the reclamation signal)."""
    kind = msg.get("type")
    if kind == "req":
        return _encode_req(msg, shm, min_shm_bytes, metrics)
    if kind == "res":
        return _encode_res(msg, shm, min_shm_bytes, metrics)
    return None


def _encode_req(msg, shm, min_shm_bytes, metrics) -> Optional[bytes]:
    from ..autoscale.qos import PRIORITY_RANK

    members = msg.get("members")
    if not isinstance(members, list) or len(members) > 0xFFFF:
        return None
    arrays = []
    for m in members:
        arr = _as_wire_array(m.get("datum"))
        if arr is None:
            return None
        prio = m.get("priority") or "normal"
        if prio not in PRIORITY_RANK:
            return None
        arrays.append(arr)
    parts: List[Any] = [_HDR.pack(MAGIC, VERSION, KIND_REQ, 0, len(members))]
    for m, arr in zip(members, arrays):
        mflags = 0
        deadline_rem = m.get("deadline_rem")
        trace = m.get("trace")
        if deadline_rem is not None:
            mflags |= _MF_DEADLINE
        if trace is not None:
            mflags |= _MF_TRACE
        parts.append(_MEMBER.pack(int(m["id"]), mflags))
        if deadline_rem is not None:
            parts.append(_F64.pack(float(deadline_rem)))
        parts.append(bytes([PRIORITY_RANK[m.get("priority") or "normal"]]))
        _put_str(parts, m.get("tenant") or "")
        if trace is not None:
            _put_str(parts, str(trace.get("id") or ""))
            _put_str(parts, trace.get("hop"))
            parts.append(_F64.pack(float(trace.get("sent_unix") or 0.0)))
        _put_array(parts, arr, shm, min_shm_bytes, metrics)
    return b"".join(parts)


def _encode_res(msg, shm, min_shm_bytes, metrics) -> Optional[bytes]:
    members = msg.get("members")
    if not isinstance(members, list) or len(members) > 0xFFFF:
        return None
    arrays: List[Optional[np.ndarray]] = []
    for m in members:
        if m.get("ok"):
            arr = _as_wire_array(m.get("value"))
            if arr is None:
                return None
            arrays.append(arr)
        else:
            if not isinstance(m.get("error"), dict):
                return None
            arrays.append(None)
    parts: List[Any] = [_HDR.pack(MAGIC, VERSION, KIND_RES, 0, len(members))]
    parts.append(_F64.pack(float(msg.get("t_unix") or 0.0)))
    for m, arr in zip(members, arrays):
        if arr is None:
            parts.append(_MEMBER.pack(int(m["id"]), _MF_ERROR))
            err = m["error"]
            _put_str(parts, str(err.get("kind") or "WorkerError"))
            _put_str(parts, str(err.get("message") or ""))
            _put_str(parts, err.get("original"))
        else:
            parts.append(_MEMBER.pack(int(m["id"]), 0))
            _put_array(parts, arr, shm, min_shm_bytes, metrics)
    return b"".join(parts)


class _Reader:
    """Bounds-checked cursor over one frame's bytes; every overrun is a
    :class:`CodecError` (torn frame), never an IndexError."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if n < 0 or end > len(self.buf):
            raise CodecError(
                f"binary frame truncated: wanted {n} byte(s) at offset "
                f"{self.pos}, frame is {len(self.buf)}"
            )
        out = self.buf[self.pos:end]
        self.pos = end
        return out

    def unpack(self, st: struct.Struct):
        return st.unpack(self.take(st.size))

    def string(self) -> str:
        (n,) = self.unpack(_STR)
        try:
            return self.take(n).decode("utf-8")
        except UnicodeDecodeError as e:
            raise CodecError(f"binary frame corrupt: bad utf-8 ({e})") from e


def _read_array(r: _Reader, shm, copy: bool, slots: List[int]) -> np.ndarray:
    code, ndim = r.unpack(_ARR)
    dtype = _CODE_TO_DTYPE.get(code)
    if dtype is None:
        raise CodecError(f"binary frame corrupt: unknown dtype code {code}")
    if ndim > _MAX_NDIM:
        raise CodecError(f"binary frame corrupt: ndim {ndim}")
    shape = tuple(r.unpack(_DIM)[0] for _ in range(ndim))
    placement = r.take(1)[0]
    expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if placement == 0:
        (nbytes,) = r.unpack(_NBYTES)
        if nbytes != expect:
            raise CodecError(
                f"binary frame corrupt: {nbytes} payload byte(s) for "
                f"shape {shape} dtype {dtype} (expected {expect})"
            )
        raw: Any = r.take(nbytes)
    elif placement == 1:
        slot, nbytes = r.unpack(_SLOT)
        if nbytes != expect:
            raise CodecError(
                f"binary frame corrupt: shm slot {slot} carries {nbytes} "
                f"byte(s) for shape {shape} dtype {dtype} "
                f"(expected {expect})"
            )
        if shm is None:
            raise CodecError(
                f"frame references shm slot {slot} but no ring is "
                "attached on this connection"
            )
        raw = shm.view(slot, nbytes)
        if copy:
            arr = np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
            shm.free(slot)
            return arr
        slots.append(slot)
    else:
        raise CodecError(
            f"binary frame corrupt: payload placement {placement}"
        )
    arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return arr.copy() if copy and placement == 0 else arr


def decode(payload: bytes, shm=None, copy: bool = True) -> dict:
    """One binary frame back into the member-list dict :func:`encode`
    took. ``shm`` is this direction's RX ring (required iff the sender
    negotiated one). ``copy=True`` (the router's reply path) detaches
    every array from the frame/ring — slots are freed HERE, before any
    caller-visible object can alias reusable memory. ``copy=False`` (the
    worker's request path) hands out zero-copy read-only views; the
    frame's ring slots ride out under ``msg["_shm_slots"]`` and the
    caller frees them when the members are answered."""
    from ..autoscale.qos import PRIORITIES

    r = _Reader(payload)
    magic, version, kind, _flags, count = r.unpack(_HDR)
    if magic != MAGIC:
        raise CodecError(f"not a binary frame (first byte {magic:#x})")
    if version != VERSION:
        raise CodecError(
            f"binary codec version skew: frame v{version}, this peer "
            f"speaks v{VERSION} — negotiate pickle or upgrade"
        )
    if kind not in _KIND_NAMES:
        raise CodecError(f"binary frame corrupt: unknown kind {kind}")
    slots: List[int] = []
    members = []
    if kind == KIND_RES:
        (t_unix,) = r.unpack(_F64)
    for _ in range(count):
        member_id, mflags = r.unpack(_MEMBER)
        if kind == KIND_REQ:
            m: dict = {"id": member_id}
            if mflags & _MF_DEADLINE:
                (m["deadline_rem"],) = r.unpack(_F64)
            rank = r.take(1)[0]
            if rank >= len(PRIORITIES):
                raise CodecError(
                    f"binary frame corrupt: priority rank {rank}"
                )
            prio = PRIORITIES[rank]
            if prio != "normal":
                m["priority"] = prio
            tenant = r.string()
            if tenant:
                m["tenant"] = tenant
            if mflags & _MF_TRACE:
                trace_id = r.string()
                hop = r.string()
                (sent_unix,) = r.unpack(_F64)
                m["trace"] = {
                    "id": trace_id, "hop": hop or None,
                    "sent_unix": sent_unix,
                }
            m["datum"] = _read_array(r, shm, copy, slots)
        else:
            if mflags & _MF_ERROR:
                m = {
                    "id": member_id, "ok": False,
                    "error": {"kind": r.string(), "message": r.string()},
                }
                original = r.string()
                if original:
                    m["error"]["original"] = original
            else:
                m = {
                    "id": member_id, "ok": True,
                    "value": _read_array(r, shm, copy, slots),
                }
        members.append(m)
    if r.pos != len(payload):
        raise CodecError(
            f"binary frame corrupt: {len(payload) - r.pos} trailing "
            "byte(s)"
        )
    msg: dict = {"type": _KIND_NAMES[kind], "members": members}
    if kind == KIND_RES:
        msg["t_unix"] = t_unix
    if slots:
        msg["_shm_slots"] = slots
    return msg
