"""Module-level model factories for the cluster tier's smoke/bench paths.

Worker processes rebuild their model from a ``"module:callable"``
factory spec — these are the canonical ones. They MUST be deterministic:
two processes calling the same factory with the same kwargs get the same
fitted parameters and therefore the same AOT fingerprint, which is what
lets every worker warm-boot from executables any one process exported
(the same trick the cold-start bench plays with two processes).
"""

from __future__ import annotations


def build_demo_model(**kwargs):
    """The serve-demo pipeline (synthetic MNIST + random-FFT + block
    least squares + argmax) — fitted only, for worker processes."""
    from ..serving.demo import build_demo_fitted

    fitted, _ = build_demo_fitted(**kwargs)
    return fitted


def _sleep_stall(x, stall_s):
    """Module-level on purpose: the batch fn must stay content-
    fingerprintable for the shared-AOT-cache warm-boot contract, so its
    closures hold only arrays and floats, never modules."""
    import time

    time.sleep(float(stall_s))
    return x


def build_stall_model(
    d: int = 256, k: int = 16, stall_s: float = 0.004, scale: float = 1.0,
    seed: int = 7,
):
    """The bench pipeline: a per-batch host stall (``pure_callback``
    sleep — the stand-in for feature-fetch / IO work real serving does
    per batch) in front of a small matmul. On shared vCPUs pure compute
    cannot parallelize, but stalls overlap perfectly across processes —
    so a 2-worker-over-1-worker throughput gate measures the process
    tier's real mechanism, not a fantasy of spare cores. Deterministic
    in ``seed`` for the shared-AOT-cache warm-boot gate."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..workflow.transformer import FunctionNode

    rng = np.random.RandomState(seed)
    W = jnp.asarray(rng.randn(d, k).astype(np.float32) / np.sqrt(d))

    def body(X, s=float(scale), stall=float(stall_s)):
        import functools

        import jax as _jax

        X = _jax.pure_callback(
            functools.partial(_sleep_stall, stall_s=stall),
            _jax.ShapeDtypeStruct(X.shape, X.dtype), X,
        )
        import jax.numpy as _jnp

        return _jnp.tanh((X * s) @ W)

    return FunctionNode(batch_fn=body, label="stall_matmul").to_pipeline().fit()


def build_wide_model(d: int = 16384, k: int = 16, seed: int = 7):
    """The hot-wire bench pipeline: a single small matmul over a WIDE
    datum and no host callback — per-request cost is then dominated by
    moving the payload across the process boundary, which is exactly
    the axis the binary codec + shm ring attack. (The stall model can't
    play this role: its ``pure_callback`` caps usable batch bytes, and
    its stall would mask wire time.) Deterministic in ``seed`` for the
    warm-boot contract, like every factory here."""
    import jax.numpy as jnp
    import numpy as np

    from ..workflow.transformer import FunctionNode

    rng = np.random.RandomState(seed)
    W = jnp.asarray(rng.randn(d, k).astype(np.float32) / np.sqrt(d))

    def body(X):
        return jnp.tanh(X @ W)

    return FunctionNode(batch_fn=body, label="wide_matmul").to_pipeline().fit()
