"""The cluster front door: admission control and load balancing over
worker PROCESSES.

``ClusterRouter`` lifts the ServingFleet's disciplines one level up the
topology — the fleet schedules N replica *threads* on one GIL; the
router schedules N worker *processes*, each running a fleet of its own
over its slice of the mesh:

* **Admission + deadline shedding at the front door.** The same learned
  batch-service EWMA the in-process scheduler uses
  (:class:`~keystone_tpu.serving.scheduler.ServiceEstimate` — one
  class, two tiers), priced from AGGREGATE queue depth ÷ fleet-wide
  capacity: a request whose deadline the estimate says cannot be met is
  refused with the typed :class:`~keystone_tpu.serving.errors.Shed`
  before it crosses a process boundary. Evidence flows back from worker
  health pongs (each worker's own learned estimate) and an
  ``observe_service`` seam for tests/benches to seed. A cold router
  never sheds.
* **Load balancing.** Least-outstanding placement over live workers —
  the process-tier analogue of the scheduler's shallowest-queue
  placement; drain-rate imbalance self-corrects because a slow worker's
  outstanding count stays high.
* **Supervision.** A worker whose socket drops (killed process, crash,
  wedge) has its in-flight requests REQUEUED to live peers with
  deadlines intact (unmeetable ones answered with the typed ``Shed``,
  hop-bounded like the fleet's requeue), and is respawned within a
  per-slot restart budget — the ``faults/`` restart-budget pattern at
  process scope. ``restarts``/``requeues`` land in the metrics,
  ``fault.worker_down``/``fault.worker_restart`` instants in the trace.
* **Warm boots.** Workers share one AOT cache directory + bucket
  manifest over the filesystem; every worker's ``ready`` message
  reports the compiles/aot_loads it paid, surfaced in
  :attr:`worker_reports` (the bench gate: a warm fleet boots with ZERO
  compiles in every worker).
* **Merged observability.** ``snapshot()`` pulls each worker's metrics
  snapshot (with raw quantile sketches) and folds them through
  :meth:`MetricsRegistry.merge` — the periodic INFO line reports
  fleet-wide shed/occupancy/queue-age, not per-process shards.
* **Bounded, signal-safe shutdown.** ``shutdown`` (and the SIGTERM
  handler ``install_signal_handlers`` registers) drains with a bounded
  wait, stops workers with per-process join timeouts, WARNs and
  force-kills a wedged worker, and answers every admitted request typed
  — mirroring the fleet's bounded thread shutdown at process scope, so
  demo and smoke runs never hang.
* **QoS + autoscaling.** ``submit`` takes ``priority``/``tenant``
  (:mod:`keystone_tpu.autoscale.qos`): the front-door shed estimate is
  scaled by the priority's :data:`~keystone_tpu.autoscale.qos.SHED_BIAS`
  (low sheds strictly before high) and both identities ride the wire to
  the worker fleet's weighted-fair queues. With ``autoscale=ScalePolicy``
  an :class:`~keystone_tpu.autoscale.Autoscaler` rides the health loop:
  SLO breach rows buy worker slots (spawned through the same
  ``_spawn_worker`` path — warm-booted zero-compile from the shared AOT
  cache), sustained idle drains the highest slot (stop admitting, wait
  out its in-flight work, stop, join, retire — orphans requeue with
  deadlines intact), and every decision lands as counters, flight
  instants, and ``scale.*`` spans. The router implements the scaler's
  actuator verbs (``scale_view``/``scale_up_slot``/
  ``pick_drain_candidate``/``begin_drain``/``reap_slot``).
"""

from __future__ import annotations

import itertools
import logging
import os
import secrets
import signal
import socket
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..autoscale import Autoscaler, ScalePolicy
from ..autoscale.qos import (
    DEFAULT_TENANT,
    PRIORITIES,
    SHED_BIAS,
    normalize_priority,
)
from ..faults import WORKER_SPAWN, fault_point
from ..obs import flight as _flight
from ..obs.context import Sampler, TraceContext, new_trace_id
from ..obs.span import Span
from ..obs.tracer import current as _trace_current
from ..serving.errors import EngineStopped, QueueFull, Shed
from ..serving.metrics import MetricsRegistry
from ..serving.scheduler import ServiceEstimate
from ..serving.replica import settle_future
from ..serving.slo import SloPolicy, SloWatchdog
from ..utils import (
    env_flag as _env_flag,
    env_int as _env_int,
    env_str as _env_str,
)
from . import shm as shm_mod
from . import wire as wire_mod
from .wire import (
    ConnectionClosed,
    costs_from_wire,
    deadline_to_wire,
    decode_error,
    qos_to_wire,
    recv_msg,
)

logger = logging.getLogger(__name__)

_SPAWN_TIMEOUT_S = 180.0
_JOIN_TIMEOUT_S = 10.0
_DRAIN_TIMEOUT_S = 60.0


def default_workers() -> int:
    """Worker-process count: ``KEYSTONE_WORKERS``, default 2 (the
    smallest fleet that is actually a fleet)."""
    return _env_int("KEYSTONE_WORKERS", 2)


@dataclass
class _PendingReq:
    datum: Any
    deadline: Optional[float]  # router-clock monotonic, or None
    enqueued: float
    future: Future = field(default_factory=Future)
    hops: int = 0
    #: cross-process trace identity for a sampled request (None when
    #: tracing is off or the request lost the sampling draw)
    trace: Optional[TraceContext] = None
    #: perf_counter at admission — the rpc.request span's start
    t_submit_pc: float = 0.0
    #: QoS identity (autoscale/qos.py) — preserved across requeues and
    #: shipped on the wire so the worker fleet re-applies the same class
    priority: str = "normal"
    tenant: str = DEFAULT_TENANT


class _WorkerSlot:
    """Router-side state for one worker process slot (the slot survives
    respawns; the process and socket are replaced)."""

    def __init__(self, index: int):
        self.index = index
        self.proc = None
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.alive = False
        self.capacity = 0
        self.restarts = 0
        #: a respawn is scheduled/booting: requests may PARK awaiting it
        #: (set by the down-handler, cleared on ready or failed respawn)
        self.respawning = False
        #: autoscale lifecycle: a spawned-but-not-ready scale-up slot
        #: (booting), a slot no longer admitting while its outstanding
        #: work finishes (draining), and a slot given back (retired —
        #: terminal until the scaler re-arms it for a later scale-up)
        self.booting = False
        self.draining = False
        self.retired = False
        self.outstanding: set = set()
        self.depth = 0  # worker-reported local queue depth (pongs)
        self.ready_report: Optional[dict] = None
        self.last_snapshot: Optional[dict] = None
        #: stats request/reply matching: a stats reply only lands if it
        #: echoes the CURRENT sequence — a late reply from a previous
        #: cycle (wedged worker) can neither satisfy this cycle's wait
        #: nor masquerade stale counters as fresh
        self.stats_seq = 0
        self.stats_event = threading.Event()
        self.recv_thread: Optional[threading.Thread] = None
        #: negotiated per connection: binary hot frames only when the
        #: router wants them AND the worker's hello advertised the codec
        #: (an old peer keeps pickle — version skew degrades, not breaks)
        self.codec_binary = False
        #: same-host zero-copy rings (router→worker tx, worker→router
        #: rx), generation-named so a respawn gets fresh segments
        self.shm_tx = None
        self.shm_rx = None
        self.shm_gen = 0
        #: worker spans accumulated off stats replies (each worker ships
        #: its fresh spans exactly once, cursor-tracked worker-side) —
        #: what export_trace stitches into cross-process tracks. Kept
        #: across respawns: a dead worker's spans are the evidence.
        self.trace_spans: List[dict] = []


class ClusterRouter:
    """Front-door router over worker processes. ``model`` is either a
    :class:`~keystone_tpu.workflow.pipeline.FittedPipeline` (pickled to
    the workers) or a ``"module:callable"`` factory string (each worker
    rebuilds deterministically — the warm-boot-friendly spelling),
    optionally ``(path, kwargs)``."""

    MAX_REQUEUE_HOPS = 3

    def __init__(
        self,
        model: Any,
        *,
        workers: Optional[int] = None,
        replicas_per_worker: Optional[int] = None,
        buckets: Sequence[int] = (1, 8, 32, 64),
        datum_shape: Optional[Sequence[int]] = None,
        dtype: Any = None,
        max_queue: int = 4096,
        worker_max_queue: int = 1024,
        max_wait_ms: float = 2.0,
        aot_cache: Optional[str] = None,
        warmup: Optional[bool] = None,
        metrics: Optional[MetricsRegistry] = None,
        max_restarts: int = 2,
        spawn_timeout_s: float = _SPAWN_TIMEOUT_S,
        join_timeout_s: float = _JOIN_TIMEOUT_S,
        drain_timeout_s: float = _DRAIN_TIMEOUT_S,
        health_interval_s: float = 2.0,
        log_interval_s: float = 10.0,
        virtual_devices: Optional[int] = None,
        log_level: Optional[str] = None,
        slo: Optional[SloPolicy] = None,
        trace_sample: Optional[float] = None,
        autoscale: Optional[ScalePolicy] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        metrics_port: Optional[int] = None,
        wire_codec: Optional[str] = None,
        wire_shm: Optional[bool] = None,
        coalesce: Optional[bool] = None,
    ):
        self._n = workers if workers is not None else default_workers()
        if self._n < 1:
            raise ValueError(f"need at least one worker, got {self._n}")
        self._model_spec = self._resolve_model_spec(model)
        self._spec = {
            "model": self._model_spec,
            "n_workers": self._n,
            "replicas": replicas_per_worker,
            "buckets": tuple(buckets),
            "datum_shape": (
                tuple(datum_shape) if datum_shape is not None else None
            ),
            "dtype": str(dtype) if dtype is not None else None,
            "max_queue": int(worker_max_queue),
            "max_wait_ms": float(max_wait_ms),
            "aot_cache": aot_cache,
            "warmup": warmup,
            "virtual_devices": virtual_devices,
            "log_level": log_level,
            "tenant_weights": (
                dict(tenant_weights) if tenant_weights else None
            ),
        }
        # hot-wire negotiation knobs: the codec the router WANTS (the
        # worker's hello must still advertise it — version skew keeps
        # pickle), whether same-host shm rings are offered, and whether
        # the front door coalesces compatible requests into one frame.
        # KEYSTONE_WIRE_CODEC=pickle is the kill switch for all three
        # hot-path layers at once (shm and member framing only ride the
        # binary codec).
        codec = (
            wire_codec if wire_codec is not None
            else _env_str("KEYSTONE_WIRE_CODEC", "binary")
        )
        self._codec = (
            "pickle" if str(codec).lower() == "pickle" else "binary"
        )
        self._spec["wire"] = {"codec": self._codec}
        self._shm_enabled = self._codec == "binary" and (
            wire_shm if wire_shm is not None
            else _env_flag("KEYSTONE_WIRE_SHM", True)
        )
        self._shm_slots = _env_int("KEYSTONE_SHM_SLOTS", 8, minimum=1)
        self._shm_slot_bytes = _env_int(
            "KEYSTONE_SHM_SLOT_BYTES", 1 << 20, minimum=1024
        )
        self._shm_min_bytes = _env_int(
            "KEYSTONE_SHM_MIN_BYTES", 1 << 16, minimum=1
        )
        self._coalesce = (
            coalesce if coalesce is not None
            else _env_flag("KEYSTONE_COALESCE", True)
        )
        #: members per coalesced frame: the largest bucket (one full
        #: worker batch) unless KEYSTONE_COALESCE_MAX overrides
        cap = _env_int("KEYSTONE_COALESCE_MAX", 0, minimum=0)
        self._coalesce_cap = cap or max(
            int(b) for b in (tuple(buckets) or (1,))
        )
        #: the operator ceiling on the coalesce hold (the same knob the
        #: worker scheduler's batch window uses), in seconds
        self._max_coalesce_wait_s = float(max_wait_ms) / 1e3
        self._metrics = metrics or MetricsRegistry(name="cluster-router")
        self._max_queue = int(max_queue)
        self._max_restarts = int(max_restarts)
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._join_timeout_s = float(join_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._health_interval_s = float(health_interval_s)
        self._log_interval_s = float(log_interval_s)
        self._service = ServiceEstimate()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._slots = [_WorkerSlot(i) for i in range(self._n)]
        self._pending: Dict[int, _PendingReq] = {}
        self._parked: List[_PendingReq] = []
        #: admitted, not yet placed: the coalescer's intake (admission
        #: already priced these — the dispatch thread only groups and
        #: sends, it never re-admits)
        self._coalesce_q: deque = deque()
        self._dispatch_thread: Optional[threading.Thread] = None
        self._req_ids = itertools.count()
        self._token = secrets.token_hex(16)
        self._listener: Optional[socket.socket] = None
        self._port: Optional[int] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._started = False
        self._closed = False
        self._prev_sigterm = None
        self._metrics.set_gauge("queue_depth", lambda: self.outstanding)
        #: per-request trace sampling (KEYSTONE_TRACE_SAMPLE unless the
        #: trace_sample arg overrides); drawn under the admission lock
        self._sampler = Sampler(trace_sample)
        self._trace_seq = itertools.count()
        #: the SLO watchdog rides the health loop's cadence; without a
        #: policy the loop still samples the metrics timeline
        self._watchdog = (
            SloWatchdog(self._metrics, slo, source="cluster-router")
            if slo is not None else None
        )
        #: the breach-driven scaler rides the health loop; the router is
        #: its actuator (scale_view / scale_up_slot / begin_drain / ...)
        self._autoscaler = (
            Autoscaler(autoscale, self, metrics=self._metrics)
            if autoscale is not None else None
        )
        #: the router's own spans, moved out of the process tracer into
        #: this bounded buffer at each collect_trace (mirrors the
        #: per-slot worker buffers) — a long-lived traced router that
        #: exports periodically stays bounded instead of holding every
        #: sampled request's spans for its whole uptime
        self._own_trace_spans: List[dict] = []
        self._own_span_cursor = 0
        self._own_trace_lock = threading.Lock()
        #: Prometheus scrape plane: metrics_port= wins, else
        #: KEYSTONE_METRICS_PORT; 0 binds an ephemeral port, unset (or a
        #: negative env value) disables the endpoint entirely
        if metrics_port is None:
            env_port = _env_int("KEYSTONE_METRICS_PORT", -1, minimum=-1)
            metrics_port = env_port if env_port >= 0 else None
        self._metrics_port = metrics_port
        self._exporter = None

    @staticmethod
    def _resolve_model_spec(model) -> tuple:
        if isinstance(model, tuple) and model and model[0] in (
            "factory", "pickle"
        ):
            return model
        if isinstance(model, str):
            return ("factory", model, {})
        from ..workflow.pipeline import FittedPipeline

        if isinstance(model, FittedPipeline):
            import pickle

            try:
                return (
                    "pickle",
                    pickle.dumps(model, protocol=5),  # lint: allow-pickle -- boot-path model shipping, never a wire frame
                )
            except Exception as e:
                raise ValueError(
                    "this FittedPipeline cannot be pickled to worker "
                    "processes — pass a 'module:callable' factory string "
                    f"that rebuilds it instead ({e})"
                ) from e
        raise TypeError(
            f"model must be a FittedPipeline, 'module:callable' string, "
            f"or ('factory'|'pickle', ...) tuple — got {type(model).__name__}"
        )

    # -- introspection ---------------------------------------------------

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    @property
    def n_workers(self) -> int:
        return self._n

    @property
    def outstanding(self) -> int:
        """Requests admitted and not yet answered — the aggregate queue
        depth the shed pricing divides by fleet capacity."""
        with self._lock:
            return (
                len(self._pending) + len(self._parked)
                + len(self._coalesce_q)
            )

    @property
    def capacity(self) -> int:
        """Fleet-wide concurrent batch capacity (admitting workers only
        — a draining slot finishes its outstanding work but takes no
        more, so it no longer backs the shed pricing)."""
        with self._lock:
            return sum(
                s.capacity for s in self._slots
                if s.alive and not s.draining
            )

    @property
    def autoscaler(self) -> Optional[Autoscaler]:
        """The riding scaler, None without an ``autoscale`` policy."""
        return self._autoscaler

    @property
    def metrics_address(self) -> Optional[tuple]:
        """``(host, port)`` of the Prometheus scrape endpoint, None when
        the export plane is disabled (no ``metrics_port`` and no
        ``KEYSTONE_METRICS_PORT``)."""
        exporter = self._exporter
        return exporter.address if exporter is not None else None

    @property
    def live_workers(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if s.alive)

    @property
    def worker_reports(self) -> List[Optional[dict]]:
        """Each slot's latest ``ready`` report (compiles/aot_loads paid
        at boot, replica count, devices) — the warm-boot evidence."""
        with self._lock:
            return [
                dict(s.ready_report) if s.ready_report else None
                for s in self._slots
            ]

    @property
    def worker_pids(self) -> List[Optional[int]]:
        with self._lock:
            return [
                s.proc.pid if s.proc is not None else None
                for s in self._slots
            ]

    def observe_service(self, seconds: float) -> None:
        """Seed/fold one batch-service observation (the test/bench seam,
        same name as the fleet scheduler's)."""
        with self._lock:
            self._service.observe(seconds)

    @property
    def service_estimate(self) -> Optional[float]:
        return self._service.estimate

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ClusterRouter":
        with self._lock:
            if self._started:
                raise RuntimeError("router already started")
            if self._closed:
                raise EngineStopped("router was shut down")
            self._started = True
            # tracing propagates at boot: a traced router asks its
            # workers to install tracers too, so their spans ship back
            # and stitch (decided here, not __init__, because configure/
            # --trace may install the tracer between construct and start)
            self._spec["trace"] = _trace_current() is not None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(self._n + 4)
        self._listener.settimeout(0.5)
        self._port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ks-router-accept", daemon=True
        )
        self._accept_thread.start()
        for slot in self._slots:
            self._spawn_worker(slot)
        deadline = time.monotonic() + self._spawn_timeout_s
        with self._cond:
            while not all(s.alive for s in self._slots):
                if self._closed:
                    raise EngineStopped("router shut down during start")
                dead = [
                    s.index for s in self._slots
                    if s.proc is not None and not s.alive
                    and s.proc.poll() is not None
                ]
                if dead:
                    break
                if not self._cond.wait(timeout=0.2):
                    if time.monotonic() >= deadline:
                        break
        missing = [s.index for s in self._slots if not s.alive]
        if missing:
            self.shutdown(drain=False)
            raise RuntimeError(
                f"cluster workers {missing} failed to boot within "
                f"{self._spawn_timeout_s:.0f}s — check worker stderr "
                "(spawned processes inherit this process's streams)"
            )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="ks-router-health", daemon=True
        )
        self._health_thread.start()
        if self._coalesce:
            self._dispatch_thread = threading.Thread(
                target=self._dispatch_loop,
                name="ks-router-dispatch", daemon=True,
            )
            self._dispatch_thread.start()
        if self._metrics_port is not None:
            # the scrape plane serves the MERGED fleet snapshot the
            # router already computes: a scrape is one stats round-trip,
            # never a touch on the request path
            from ..obs.prom import PrometheusExporter

            self._exporter = PrometheusExporter(
                lambda: self.snapshot(timeout=2.0),
                port=self._metrics_port,
            )
            self._exporter.start()
        logger.info(
            "cluster router up on 127.0.0.1:%d — %d worker(s), "
            "capacity %d", self._port, self._n, self.capacity,
        )
        return self

    def _spawn_worker(self, slot: _WorkerSlot) -> None:
        """Launch one worker as a FRESH interpreter running ``python -m
        keystone_tpu.cluster.worker`` (spec pickled over stdin) — not a
        ``multiprocessing`` fork/spawn of this process: a fork would
        share initialized XLA runtime state, and spawn re-executes the
        parent's ``__main__``; a clean exec does neither."""
        import pickle
        import subprocess
        import sys

        fault_point(WORKER_SPAWN, replica=slot.index)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_root
        )
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "keystone_tpu.cluster.worker",
                "127.0.0.1", str(self._port), self._token,
                str(slot.index),
            ],
            stdin=subprocess.PIPE,
            env=env,
        )
        # a scaled-up slot's index can exceed the boot-time worker count;
        # device carving (worker_device_indices) needs n_workers to cover
        # it, so the slot ships a widened per-slot spec (co-residency on
        # the shared mesh is placement's round-robin job)
        spec = self._spec
        if slot.index >= int(spec.get("n_workers") or 1):
            spec = dict(spec)
            spec["n_workers"] = slot.index + 1
        if self._shm_enabled:
            # fresh generation-named segments per spawn: slots a dead
            # incarnation held can never leak into the new one
            self._release_rings(slot)
            slot.shm_gen += 1
            base = f"ks{os.getpid():x}w{slot.index}g{slot.shm_gen}"
            tx, rx = shm_mod.make_ring_pair(
                base, self._shm_slots, self._shm_slot_bytes
            )
            slot.shm_tx, slot.shm_rx = tx, rx
            if tx is not None:
                spec = dict(spec)
                spec["shm"] = {
                    "c2w": tx.name,
                    "w2c": rx.name,
                    "slots": self._shm_slots,
                    "slot_bytes": self._shm_slot_bytes,
                }
        try:
            proc.stdin.write(
                pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL)  # lint: allow-pickle -- boot spec over stdin, not a wire frame
            )
            proc.stdin.close()
        except BrokenPipeError:
            pass  # instant death: start()/down-handler reports it
        slot.proc = proc
        logger.info(
            "cluster: spawned worker %d (pid %s)", slot.index, proc.pid
        )

    def _release_rings(self, slot: _WorkerSlot) -> None:
        """Close + unlink a slot's shm rings (idempotent). Called on
        every death/retire path AND before a respawn's fresh pair — the
        router owns ring lifetime, the worker only attaches."""
        tx, rx = slot.shm_tx, slot.shm_rx
        slot.shm_tx = slot.shm_rx = None
        for ring in (tx, rx):
            if ring is not None:
                ring.close()
                ring.unlink()

    def _accept_loop(self) -> None:
        """Match incoming worker connections (hello + ready, token
        checked) to their slots — runs for the router's life so
        respawned workers re-register through the same door."""
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: shutdown
            try:
                # short poll interval + an explicit overall deadline:
                # receives ride out socket timeouts by design, so the
                # handshake bounds itself with the deadline instead
                conn.settimeout(1.0)
                handshake_by = time.monotonic() + self._spawn_timeout_s
                hello = recv_msg(conn, deadline=handshake_by)
                if (
                    hello.get("type") != "hello"
                    or hello.get("token") != self._token
                ):
                    raise ConnectionClosed("bad hello")
                ready = recv_msg(conn, deadline=handshake_by)
                if ready.get("type") != "ready":
                    raise ConnectionClosed(
                        f"expected ready, got {ready.get('type')!r}"
                    )
                # steady state: bounded SENDS (a wedged worker's full
                # buffer must not hold the send lock forever); receives
                # ride out timeouts (wire._recv_exact)
                conn.settimeout(wire_mod.SEND_TIMEOUT_S)
            except Exception:
                logger.warning(
                    "cluster: rejected connection during handshake",
                    exc_info=True,
                )
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._register_ready(int(hello["worker"]), conn, hello, ready)

    def _register_ready(
        self, index: int, conn, hello: dict, ready: dict
    ) -> None:
        slot = self._slots[index]
        with self._cond:
            if slot.retired:
                # reaped while booting (an aborted scale-up): the process
                # was told to die; refuse the late registration
                try:
                    conn.close()
                except OSError:
                    pass
                return
            slot.sock = conn
            slot.alive = True
            slot.respawning = False
            slot.booting = False
            slot.capacity = int(ready.get("capacity", 1))
            slot.ready_report = dict(ready)
            slot.outstanding = set()
            # codec negotiation: binary only when this router wants it
            # AND the hello advertised it — an old worker that never
            # heard of the codec keeps receiving pickle frames
            try:
                peer_codec = int(hello.get("codec") or 0)
            except (TypeError, ValueError):
                peer_codec = 0
            slot.codec_binary = self._codec == "binary" and peer_codec >= 1
            # shm negotiation closes on the ready report: a worker that
            # could not attach (or predates the rings) answers without
            # shm=true and the router tears the segments down — payloads
            # stay inline, nothing leaks
            if slot.shm_tx is not None and not ready.get("shm"):
                logger.info(
                    "cluster: worker %d did not attach shared-memory "
                    "rings — payloads stay inline", index,
                )
                self._release_rings(slot)
            slot.recv_thread = threading.Thread(
                target=self._recv_loop, args=(slot, conn),
                name=f"ks-router-recv-{index}", daemon=True,
            )
            slot.recv_thread.start()
            parked, self._parked = self._parked, []
            self._cond.notify_all()
        logger.info(
            "cluster: worker %d ready (capacity %d, compiles %s, "
            "aot_loads %s)", index, slot.capacity,
            ready.get("compiles"), ready.get("aot_loads"),
        )
        # flush requests parked while no worker was live
        for req in parked:
            self._route(req, from_requeue=True)

    # -- receive path ----------------------------------------------------

    def _recv_loop(self, slot: _WorkerSlot, conn) -> None:
        try:
            while True:
                payload = wire_mod.recv_payload(conn)
                t_dec0 = time.perf_counter()
                # copy=True: decoded values must survive the shm slot's
                # reclamation (the worker reuses it for the next reply),
                # so anything slot-backed is copied out and freed HERE —
                # user-visible results never alias reusable memory
                msg = wire_mod.decode_payload(
                    payload, shm=slot.shm_rx, copy=True
                )
                t_dec1 = time.perf_counter()
                kind = msg.get("type")
                if kind == "res":
                    members = msg.get("members")
                    if members is None:
                        members = [msg]  # legacy single-request reply
                    t_unix = msg.get("t_unix")
                    traced_id = None
                    for member in members:
                        tid = self._settle_member(slot, member, t_unix)
                        if traced_id is None:
                            traced_id = tid
                    if traced_id is not None:
                        tracer = _trace_current()
                        if tracer is not None:
                            tracer.record_complete(Span(
                                name="wire.decode", start=t_dec0,
                                end=t_dec1, op_type="ClusterRouter",
                                attrs={
                                    "trace_id": traced_id,
                                    "codec": (
                                        "pickle"
                                        if payload[:1] == b"\x80"
                                        else "binary"
                                    ),
                                    "bytes": len(payload),
                                    "members": len(members),
                                },
                            ))
                elif kind == "pong":
                    with self._lock:
                        est = msg.get("service_estimate")
                        if est is not None:
                            self._service.observe(float(est))
                    # fold the worker's cost DELTAS into the router's own
                    # registry: the health-loop timeline (and the SLO
                    # watchdog's per-tenant spend budget) then sees
                    # fleet-wide charges continuously. snapshot() strips
                    # this mirror before merging so worker tables stay
                    # the single authoritative count.
                    for tenant, priority, cost in costs_from_wire(
                        msg.get("costs")
                    ):
                        self._metrics.observe_cost(tenant, priority, **cost)
                elif kind == "stats":
                    if msg.get("spans_dropped"):
                        logger.warning(
                            "cluster: worker %d overflowed its span "
                            "shipping window — %s span(s) lost to the "
                            "stitched trace (collect more often)",
                            slot.index, msg["spans_dropped"],
                        )
                    spans = msg.get("spans")
                    if spans:
                        # accumulate every worker's shipped spans for
                        # stitching (cursor-tracked worker-side, so this
                        # never double-counts); bounded like a ring
                        slot.trace_spans.extend(spans)
                        del slot.trace_spans[:-8192]
                    if msg.get("seq") == slot.stats_seq:
                        slot.last_snapshot = msg.get("snapshot")
                        slot.stats_event.set()
                elif kind == "bye":
                    return
        except ConnectionClosed as e:
            self._on_worker_down(slot, e)
        except Exception:
            logger.exception(
                "cluster: receive loop for worker %d failed", slot.index
            )
            self._on_worker_down(
                slot, ConnectionClosed("receive loop failed")
            )

    def _settle_member(
        self,
        slot: _WorkerSlot,
        msg: dict,
        frame_t_unix: Optional[float] = None,
    ) -> Optional[str]:
        """Settle ONE answered member (coalesced frames carry several;
        legacy replies are a one-member frame). Returns the member's
        trace_id when it was traced — the caller hangs the frame-level
        wire.decode span off the first one."""
        req_id = msg.get("id")
        with self._lock:
            req = self._pending.pop(req_id, None)
            if req is not None:
                slot.outstanding.discard(req_id)
            self._cond.notify_all()
        if req is None:
            return None  # already settled (requeue raced a late answer)
        latency = time.monotonic() - req.enqueued
        ok = bool(msg.get("ok"))
        # the always-on flight ring: every answered request leaves a
        # round-trip summary regardless of sampling, so a worker-death
        # dump shows exactly what the tier was serving when it happened
        _flight.record_span(
            "rpc.request", latency, worker=slot.index, ok=ok,
        )
        if req.trace is not None:
            tracer = _trace_current()
            if tracer is not None:
                end_pc = time.perf_counter()
                reply_unix = msg.get("t_unix", frame_t_unix)
                tracer.record_complete(Span(
                    name="rpc.request",
                    start=req.t_submit_pc,
                    end=end_pc,
                    op_type="ClusterRouter",
                    attrs={
                        "trace_id": req.trace.trace_id,
                        "worker": slot.index,
                        "ok": ok,
                        "hops": req.hops,
                        "reply_transport_s": (
                            round(max(0.0, time.time() - reply_unix), 6)
                            if reply_unix is not None else None
                        ),
                    },
                ))
        if ok:
            if settle_result(req.future, msg.get("value")):
                self._metrics.inc("completed")
                self._metrics.observe_latency(latency, priority=req.priority)
        else:
            exc = decode_error(msg.get("error") or {})
            # a decoded worker-side Shed is NOT counted here: the worker
            # fleet's own registry already counted it, and the merged
            # snapshot sums both registries — the router's 'shed' means
            # front-door sheds (its own refusals), nothing else
            if not isinstance(exc, Shed):
                self._metrics.inc("worker_errors")
            settle_future(req.future, exc)
        return req.trace.trace_id if req.trace is not None else None

    # -- worker failure --------------------------------------------------

    def _on_worker_down(self, slot: _WorkerSlot, exc: Exception) -> None:
        with self._lock:
            if not slot.alive:
                return  # double report (send failure + recv EOF)
            slot.alive = False
            try:
                if slot.sock is not None:
                    slot.sock.close()
            except OSError:
                pass
            slot.sock = None
            # a dead peer's mappings die with it: tear the rings down
            # (a respawn creates a fresh generation pair)
            self._release_rings(slot)
            orphans = [
                self._pending.pop(rid)
                for rid in sorted(slot.outstanding)
                if rid in self._pending
            ]
            slot.outstanding = set()
            # a draining slot's death IS its drain finishing early; a
            # retired slot never comes back by itself — neither respawns
            # (the scaler owns their lifecycle, the restart budget does
            # not)
            if slot.draining or slot.retired:
                slot.draining = False
                slot.retired = True
                will_restart = False
            else:
                will_restart = (
                    not self._closed and slot.restarts < self._max_restarts
                )
            if will_restart:
                slot.restarts += 1
                slot.respawning = True
                self._metrics.inc("restarts")
            self._cond.notify_all()
        if self._closed:
            for req in orphans:
                settle_future(
                    req.future,
                    EngineStopped("router shut down while this request's "
                                  "worker was down"),
                )
            return
        logger.warning(
            "cluster: worker %d down (%s) — rerouting %d in-flight "
            "request(s); restart %s (budget %d/%d used)",
            slot.index, exc, len(orphans),
            "scheduled" if will_restart else "refused",
            slot.restarts, self._max_restarts,
        )
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(
                "fault.worker_down", op_type="ClusterRouter",
                worker=slot.index, requeued=len(orphans),
                restarting=will_restart,
            )
        # the post-mortem artifact: the kill instant plus the last ring
        # of span summaries — always on, sampling does not apply
        _flight.record_instant(
            "fault.worker_down", worker=slot.index,
            requeued=len(orphans), restarting=will_restart,
            cause=str(exc)[:200],
        )
        _flight.dump("worker_down")
        moved = 0
        for req in orphans:
            if req.future.done():
                continue
            req.hops += 1
            if req.hops > self.MAX_REQUEUE_HOPS:
                settle_future(req.future, exc)
                continue
            if self._route(req, from_requeue=True):
                moved += 1
        if moved:
            self._metrics.inc("requeues", moved)
        if will_restart:
            try:
                self._spawn_worker(slot)
            except Exception:
                logger.exception(
                    "cluster: respawn of worker %d failed", slot.index
                )
            else:
                _flight.record_instant(
                    "fault.worker_restart", worker=slot.index,
                    attempt=slot.restarts,
                )
                if tracer is not None:
                    tracer.instant(
                        "fault.worker_restart", op_type="ClusterRouter",
                        worker=slot.index, attempt=slot.restarts,
                    )

    # -- admission -------------------------------------------------------

    def submit(
        self,
        datum: Any,
        timeout: Optional[float] = None,
        priority: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Future:
        """Enqueue one datum; returns a Future of its prediction row.
        Raises typed: :class:`QueueFull` at capacity, :class:`Shed` when
        the learned estimate says the deadline cannot be met given the
        aggregate queue depth ÷ fleet capacity, :class:`EngineStopped`
        after shutdown.

        ``priority`` (``high``/``normal``/``low``) scales the shed
        estimate by its :data:`~keystone_tpu.autoscale.qos.SHED_BIAS` —
        the router cannot see inside worker queues, so the bias is the
        coarse front-door form of the worker scheduler's exact per-rank
        pricing; both orderings shed low strictly before high at equal
        deadline slack. ``tenant`` names the weighted-fair share the
        worker fleet serves the request from. Both ride the wire."""
        now = time.monotonic()
        priority = normalize_priority(priority)
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        with self._lock:
            if self._closed:
                raise EngineStopped("cluster router is shut down")
            if not self._started:
                raise RuntimeError(
                    "submit() needs a started router (call start() or "
                    "use the context manager)"
                )
            depth = (
                len(self._pending) + len(self._parked)
                + len(self._coalesce_q)
            )
            if depth >= self._max_queue:
                self._metrics.inc("rejected")
                raise QueueFull(
                    f"router queue at capacity ({self._max_queue})"
                )
            if timeout is not None:
                cap = sum(
                    s.capacity for s in self._slots
                    if s.alive and not s.draining
                )
                est = self._service.wait(depth, cap) * SHED_BIAS[priority]
                if now + est > now + timeout:
                    self._metrics.inc("shed")
                    self._metrics.inc(f"shed.{priority}")
                    raise Shed(
                        f"deadline unmeetable at the front door: "
                        f"estimated wait {est:.4f}s (at priority "
                        f"{priority!r}) exceeds the request's "
                        f"{timeout:.4f}s budget "
                        f"(depth {depth} / capacity {cap})"
                    )
            req = _PendingReq(
                datum=datum,
                deadline=(now + timeout) if timeout is not None else None,
                enqueued=now,
                t_submit_pc=time.perf_counter(),
                priority=priority,
                tenant=tenant,
            )
            self._metrics.inc("submitted")
            # the sampling draw happens under the admission lock (the
            # sampler is a plain counter); an unsampled request pays
            # exactly this one modulo check
            if self._sampler.admit() and _trace_current() is not None:
                req.trace = TraceContext(
                    trace_id=new_trace_id(next(self._trace_seq)),
                    hop="rpc.request",
                )
            if self._coalesce:
                # hand off to the coalescer: compatible neighbors already
                # waiting (or arriving within the priced window) share
                # one wire frame. Admission is done — the dispatch thread
                # only groups and places.
                self._coalesce_q.append(req)
                self._cond.notify_all()
                return req.future
        self._route(req)
        return req.future

    def predict(self, datum: Any, timeout: Optional[float] = None) -> Any:
        return self.submit(datum, timeout=timeout).result()

    def _route(self, req: _PendingReq, from_requeue: bool = False) -> bool:
        """Single-request dispatch (requeues, parked flushes, and the
        ``coalesce=False`` spelling) — one member, no coalesce wait."""
        return self._dispatch([req], from_requeue=from_requeue)

    @staticmethod
    def _compat_key(req: _PendingReq) -> tuple:
        """Requests that may share a wire frame: same priority class and
        the same bucket signature (shape + dtype — what the worker's
        bucket ladder pads against). The model digest needs no key
        component: one router serves one model."""
        d = req.datum
        return (
            req.priority,
            tuple(getattr(d, "shape", ()) or ()),
            str(getattr(d, "dtype", type(d).__name__)),
        )

    def _drain_compatible(self, batch: list, key: tuple, cap: int) -> None:
        """Move every queued compatible request into ``batch`` (up to
        ``cap``), preserving queue order for the rest. Lock held."""
        if len(batch) >= cap or not self._coalesce_q:
            return
        kept: deque = deque()
        while self._coalesce_q and len(batch) < cap:
            r = self._coalesce_q.popleft()
            if self._compat_key(r) == key:
                batch.append(r)
            else:
                kept.append(r)
        kept.extend(self._coalesce_q)
        self._coalesce_q = kept

    def _dispatch_loop(self) -> None:
        """The coalescer: pop the queue head, drain everything
        compatible, and — only for a PARTIAL batch with nothing else
        waiting — hold the frame open for the priced window
        (:meth:`ServiceEstimate.coalesce_window`: a fraction of one
        learned batch-service time, capped by the operator's max-wait
        and the tightest member deadline; zero while cold). A lone
        request with an empty queue dispatches immediately, and any
        incompatible arrival closes the window early — coalescing never
        buys head-of-line blocking."""
        while True:
            with self._cond:
                while not self._coalesce_q and not self._closed:
                    self._cond.wait(timeout=0.5)
                if self._closed:
                    return  # shutdown flushed/swept the queue
                batch = [self._coalesce_q.popleft()]
                key = self._compat_key(batch[0])
                cap = self._coalesce_cap
                self._drain_compatible(batch, key, cap)
                if 1 < len(batch) < cap and not self._coalesce_q:
                    now = time.monotonic()
                    tightest = min(
                        (
                            r.deadline for r in batch
                            if r.deadline is not None
                        ),
                        default=None,
                    )
                    until = now + self._service.coalesce_window(
                        now, tightest, cap=self._max_coalesce_wait_s
                    )
                    while len(batch) < cap and not self._closed:
                        remaining = until - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                        self._drain_compatible(batch, key, cap)
                        if self._coalesce_q:
                            break  # other traffic waits for no window
            self._dispatch(batch)

    def _dispatch(
        self,
        reqs: List[_PendingReq],
        from_requeue: bool = False,
        during_shutdown: bool = False,
    ) -> bool:
        """Place a compatible group on the least-outstanding live worker
        and send it as ONE wire frame; every member keeps its own
        pending entry (and so its own identity through requeues — a
        worker death mid-frame re-places members individually). Returns
        True when the group was handed to a worker (or parked); settles
        futures typed otherwise."""
        reqs = [r for r in reqs if not r.future.done()]
        if not reqs:
            return True
        while True:
            with self._lock:
                if self._closed and not during_shutdown:
                    for r in reqs:
                        settle_future(
                            r.future,
                            EngineStopped(
                                "router shut down before dispatch"
                            ),
                        )
                    return False
                if from_requeue:
                    survivors = []
                    for r in reqs:
                        if r.deadline is None:
                            survivors.append(r)
                            continue
                        cap = sum(
                            s.capacity for s in self._slots
                            if s.alive and not s.draining
                        )
                        est = (
                            self._service.wait(len(self._pending), cap)
                            * SHED_BIAS[r.priority]
                        )
                        if time.monotonic() + est > r.deadline:
                            self._metrics.inc("shed")
                            self._metrics.inc(f"shed.{r.priority}")
                            settle_future(
                                r.future,
                                Shed(
                                    "deadline unmeetable after worker "
                                    f"failure: estimated wait {est:.4f}s "
                                    "exceeds the remaining budget"
                                ),
                            )
                            continue
                        survivors.append(r)
                    reqs = survivors
                    if not reqs:
                        return False
                live = [
                    s for s in self._slots if s.alive and not s.draining
                ]
                if not live:
                    if any(
                        s.respawning or s.booting for s in self._slots
                    ):
                        self._parked.extend(reqs)
                        return True
                    for r in reqs:
                        settle_future(
                            r.future,
                            EngineStopped(
                                "no live workers (restart budget "
                                "exhausted)"
                            ),
                        )
                    return False
                slot = min(live, key=lambda s: len(s.outstanding))
                ids = []
                for r in reqs:
                    rid = next(self._req_ids)
                    self._pending[rid] = r
                    slot.outstanding.add(rid)
                    ids.append(rid)
            try:
                members = []
                for rid, r in zip(ids, reqs):
                    members.append({
                        "id": rid,
                        "datum": r.datum,
                        "deadline_rem": deadline_to_wire(r.deadline),
                        **qos_to_wire(r.priority, r.tenant),
                    })
                traced = [r for r in reqs if r.trace is not None]
                tracer = _trace_current() if traced else None
                # the stamp necessarily precedes encoding (it rides the
                # frame), so the receiver's transport_s INCLUDES
                # serialize + send — consumers summing hops must use
                # transport_s OR the rpc.send span, never both
                t_send_pc = time.perf_counter()
                for m, r in zip(members, reqs):
                    if r.trace is not None:
                        m["trace"] = r.trace.to_wire()
                payload = wire_mod.encode_msg(
                    {"type": "req", "members": members},
                    codec=(
                        "binary" if slot.codec_binary else "pickle"
                    ),
                    shm=slot.shm_tx,
                    min_shm_bytes=self._shm_min_bytes,
                    metrics=self._metrics,
                )
                t_enc_pc = time.perf_counter()
                with slot.send_lock:
                    wire_mod.send_payload(slot.sock, payload)
                done_pc = time.perf_counter()
                self._count_frame("req", len(payload))
                if len(members) > 1:
                    self._metrics.inc("coalesce.frames")
                    self._metrics.inc("coalesce.members", len(members))
                if tracer is not None:
                    # the admission hop (submit -> send start:
                    # front-door pricing + coalescing + placement) and
                    # the wire-send hop (encode + sendall) per traced
                    # member, plus ONE nested wire.encode span for the
                    # frame — recorded completed: the dispatch thread
                    # cannot hold spans open across the reply
                    for r in traced:
                        attrs = {
                            "trace_id": r.trace.trace_id,
                            "worker": slot.index,
                            "hops": r.hops,
                            "members": len(members),
                        }
                        tracer.record_complete(Span(
                            name="rpc.admission", start=r.t_submit_pc,
                            end=t_send_pc, op_type="ClusterRouter",
                            attrs=dict(attrs),
                        ))
                        tracer.record_complete(Span(
                            name="rpc.send", start=t_send_pc,
                            end=done_pc, op_type="ClusterRouter",
                            attrs=dict(attrs),
                        ))
                    tracer.record_complete(Span(
                        name="wire.encode", start=t_send_pc,
                        end=t_enc_pc, op_type="ClusterRouter",
                        attrs={
                            "trace_id": traced[0].trace.trace_id,
                            "codec": (
                                "binary" if slot.codec_binary
                                else "pickle"
                            ),
                            "bytes": len(payload),
                            "members": len(members),
                        },
                    ))
                return True
            except Exception as e:
                # the worker died under us: undo the bookkeeping for the
                # whole group and let the down-handler (idempotent) run,
                # then try a peer with whoever is still unanswered
                with self._lock:
                    for rid in ids:
                        self._pending.pop(rid, None)
                        slot.outstanding.discard(rid)
                self._on_worker_down(
                    slot, ConnectionClosed(f"send failed: {e}")
                )
                reqs = [r for r in reqs if not r.future.done()]
                if not reqs:
                    return False

    def _count_frame(self, kind: str, nbytes: int) -> None:
        """Per-kind wire accounting (frames out + payload bytes out) —
        what the hot-wire bench reads to show the codec shrinking the
        hop."""
        self._metrics.inc(f"wire.frames.{kind}")
        self._metrics.inc(f"wire.bytes_sent.{kind}", nbytes)

    def _send_control(self, slot: _WorkerSlot, msg: dict) -> None:
        """Send one control frame (always pickle — control dicts carry
        arbitrary values and never ride the hot path) with per-kind wire
        accounting. Raises on a dead socket like ``send_msg``."""
        payload = wire_mod.encode_msg(msg)
        with slot.send_lock:
            wire_mod.send_payload(slot.sock, payload)
        self._count_frame(str(msg.get("type")), len(payload))

    # -- health + merged metrics ----------------------------------------

    def _health_loop(self) -> None:
        last_log = 0.0
        while not self._closed:
            time.sleep(self._health_interval_s)
            if self._closed:
                return
            self._reap_failed_respawns()
            with self._lock:
                live = [s for s in self._slots if s.alive]
            for slot in live:
                try:
                    self._send_control(
                        slot, {"type": "ping", "t": time.monotonic()}
                    )
                except Exception as e:
                    self._on_worker_down(
                        slot, ConnectionClosed(f"ping failed: {e}")
                    )
            fresh: List = []
            row: Optional[dict] = None
            try:
                # one timeline row per health tick; with a policy set the
                # watchdog samples AND judges it (breaches land in the
                # flight ring + counters), without one the row still
                # accumulates for status()/snapshot() readers
                if self._watchdog is not None:
                    fresh = self._watchdog.tick()
                    rows = self._metrics.timeline()
                    row = rows[-1] if rows else None
                else:
                    row = self._metrics.sample_timeline()
            except Exception:
                logger.exception("cluster: timeline sample failed")
            if self._autoscaler is not None:
                try:
                    # the closed control loop: this tick's breach rows +
                    # timeline row become scale decisions, applied through
                    # the actuator verbs below
                    self._autoscaler.tick(fresh, row=row)
                except Exception:
                    logger.exception("cluster: autoscaler tick failed")
            now = time.monotonic()
            if now - last_log >= self._log_interval_s:
                last_log = now
                try:
                    self._log_merged()
                except Exception:
                    logger.exception("cluster: merged metrics log failed")

    def _reap_failed_respawns(self) -> None:
        """A respawned worker whose process died BEFORE registering
        (boot crash) would otherwise leave its slot 'respawning' and
        parked requests waiting forever: retry within the budget, else
        give the slot up — and if nobody is left to come back, answer
        everything parked typed."""
        retry: List[_WorkerSlot] = []
        with self._lock:
            for s in self._slots:
                if not (
                    s.respawning and s.proc is not None
                    and s.proc.poll() is not None
                ):
                    continue
                if s.restarts < self._max_restarts and not self._closed:
                    s.restarts += 1
                    self._metrics.inc("restarts")
                    retry.append(s)
                else:
                    s.respawning = False
                    logger.warning(
                        "cluster: worker %d died during respawn boot "
                        "and its restart budget is exhausted — giving "
                        "the slot up", s.index,
                    )
            give_up = (
                not any(s.alive or s.respawning for s in self._slots)
                and not retry
            )
            failed = self._parked if give_up else []
            if give_up:
                self._parked = []
        for req in failed:
            settle_future(
                req.future,
                EngineStopped(
                    "no live workers remain and the restart budget is "
                    "exhausted"
                ),
            )
        for s in retry:
            try:
                self._spawn_worker(s)
            except Exception:
                logger.exception(
                    "cluster: re-spawn of worker %d failed", s.index
                )

    # -- autoscale actuator (driven by Autoscaler.tick) ------------------

    def scale_view(self) -> Dict[str, int]:
        """The slot census the scaler budgets against: ``admitting``
        (alive, taking traffic), ``booting`` (spawned or respawning, not
        ready yet — already-committed capacity, so the scaler must not
        buy it twice), ``draining`` (finishing, no longer admitting)."""
        with self._lock:
            admitting = booting = draining = 0
            for s in self._slots:
                if s.retired:
                    continue
                if s.alive:
                    if s.draining:
                        draining += 1
                    else:
                        admitting += 1
                elif s.booting or s.respawning:
                    booting += 1
        return {
            "admitting": admitting,
            "booting": booting,
            "draining": draining,
        }

    def scale_up_slot(self) -> int:
        """Add one worker slot and spawn its process through the same
        ``_spawn_worker`` path boot uses — against a warm shared AOT
        cache the new worker pre-warms every manifest signature and
        boots with ZERO compiles. Returns the slot index; the slot takes
        no traffic until its ``ready`` registers (``_register_ready``),
        so a death mid-boot can never fail an admitted request.
        Retired slots are re-armed before the list grows (indices must
        stay stable — ``_register_ready`` addresses ``_slots[index]``)."""
        with self._lock:
            if self._closed:
                raise EngineStopped("router is shut down")
            slot = next(
                (
                    s for s in reversed(self._slots)
                    if s.retired and (
                        s.proc is None or s.proc.poll() is not None
                    )
                ),
                None,
            )
            if slot is not None:
                slot.retired = False
                slot.draining = False
                slot.respawning = False
                slot.restarts = 0
                slot.ready_report = None
            else:
                slot = _WorkerSlot(len(self._slots))
                self._slots.append(slot)
            slot.booting = True
        try:
            self._spawn_worker(slot)
        except BaseException:
            with self._lock:
                slot.booting = False
                slot.retired = True
            raise
        return slot.index

    def pick_drain_candidate(self) -> Optional[int]:
        """The slot a scale-down should release: the HIGHEST-index
        admitting worker (LIFO — scale-ups appended it last, and the
        boot-time slots keep the stable low indices), or None when no
        slot can drain."""
        with self._lock:
            for s in reversed(self._slots):
                if s.alive and not s.draining and not s.retired:
                    return s.index
        return None

    def begin_drain(self, index: int) -> None:
        """Stop admitting to slot ``index`` and retire it off-thread:
        wait (bounded) for its outstanding requests to finish, send the
        worker a draining stop, join the process, release the slot. A
        drain that times out terminates the process — the down-handler
        then requeues whatever was left with deadlines intact, so the
        slow path strands nothing either."""
        with self._lock:
            slot = self._slots[index]
            if not slot.alive or slot.draining or slot.retired:
                raise RuntimeError(
                    f"worker {index} cannot drain (alive={slot.alive}, "
                    f"draining={slot.draining}, retired={slot.retired})"
                )
            slot.draining = True
            self._cond.notify_all()
        threading.Thread(
            target=self._drain_worker, args=(slot,),
            name=f"ks-router-drain-{index}", daemon=True,
        ).start()

    def _drain_worker(self, slot: _WorkerSlot) -> None:
        import subprocess

        deadline = time.monotonic() + self._drain_timeout_s
        with self._cond:
            while (
                slot.outstanding and slot.alive and not self._closed
                and time.monotonic() < deadline
            ):
                self._cond.wait(timeout=0.2)
            timed_out = bool(slot.outstanding) and slot.alive
        if slot.alive and slot.sock is not None:
            try:
                self._send_control(slot, {"type": "stop", "drain": True})
            except Exception:
                logger.debug(
                    "drain stop to worker %d failed (already dead?)",
                    slot.index, exc_info=True,
                )
        proc = slot.proc
        if proc is not None:
            try:
                proc.wait(timeout=self._join_timeout_s)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "cluster: draining worker %d did not exit within "
                    "%.1fs — terminating it (its in-flight work "
                    "requeues)", slot.index, self._join_timeout_s,
                )
                proc.terminate()
                try:
                    proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
        # the socket death has (or will have) run the down-handler for
        # any stranded work; all that is left is releasing the slot
        with self._cond:
            slot.alive = False
            slot.draining = False
            slot.retired = True
            try:
                if slot.sock is not None:
                    slot.sock.close()
            except OSError:
                pass
            slot.sock = None
            self._release_rings(slot)
            self._cond.notify_all()
        _flight.record_instant(
            "scale.drained", worker=slot.index, timed_out=timed_out,
        )
        logger.info(
            "cluster: worker %d drained and released%s", slot.index,
            " (drain timed out; process terminated)" if timed_out else "",
        )

    def reap_slot(self, index: int) -> None:
        """Force-retire slot ``index`` — the scaler's abort path for a
        half-born (killed mid-scale-up) or half-drained slot. Kills the
        process if still up and requeues anything outstanding; the slot
        stays retired until a later scale-up re-arms it."""
        import subprocess

        with self._lock:
            slot = self._slots[index]
            slot.booting = False
            slot.respawning = False
            slot.draining = False
            slot.retired = True
            slot.alive = False
            sock, slot.sock = slot.sock, None
            self._release_rings(slot)
            proc = slot.proc
            orphans = [
                self._pending.pop(rid)
                for rid in sorted(slot.outstanding)
                if rid in self._pending
            ]
            slot.outstanding = set()
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                proc.kill()
        moved = 0
        for req in orphans:
            if not req.future.done() and self._route(req, from_requeue=True):
                moved += 1
        if moved:
            self._metrics.inc("requeues", moved)

    def _log_merged(self) -> None:
        snap = self.snapshot(timeout=1.0)
        c = snap.get("counters", {})
        lat = snap.get("latency", {})
        age = snap.get("queue_age", {})
        occ = (snap.get("batch_occupancy") or {}).get("ratio")
        logger.info(
            "cluster-router: workers=%d/%d outstanding=%d counters=%s "
            "occupancy=%s shed=%s p99=%s queue_age_p99=%s slo_breaches=%s",
            sum(1 for s in self._slots if s.alive), len(self._slots),
            self.outstanding, c,
            None if occ is None else round(occ, 3),
            c.get("shed", 0),
            round(lat["p99"], 4) if "p99" in lat else None,
            round(age["p99"], 4) if "p99" in age else None,
            c.get("slo_breaches", 0),
        )

    def worker_snapshots(self, timeout: float = 2.0) -> List[dict]:
        """Fresh metrics snapshots (with quantile sketches) from every
        live worker, named ``worker-<i>`` — the worker-tier-only view
        (benches gate on worker-measured latency: it excludes the
        CLIENT process's own scheduling noise)."""
        with self._lock:
            live = [s for s in self._slots if s.alive]
            for slot in live:
                slot.stats_seq += 1
                slot.last_snapshot = None  # stale data never re-served
                slot.stats_event.clear()
        for slot in live:
            try:
                self._send_control(
                    slot, {"type": "stats", "seq": slot.stats_seq}
                )
            except Exception:
                logger.debug(
                    "stats request to worker %d failed", slot.index,
                    exc_info=True,
                )
        deadline = time.monotonic() + timeout
        out = []
        for slot in live:
            slot.stats_event.wait(
                timeout=max(0.0, deadline - time.monotonic())
            )
            if slot.last_snapshot is not None:
                snap = dict(slot.last_snapshot)
                snap["name"] = f"worker-{slot.index}"
                out.append(snap)
        return out

    def snapshot(self, timeout: float = 2.0) -> dict:
        """ONE fleet-wide view: the router's own registry (submissions,
        front-door sheds, restarts, end-to-end latency) merged with
        every live worker's snapshot (batches, occupancy, worker-side
        sheds, queue-age sketches) via :meth:`MetricsRegistry.merge`."""
        own = self._metrics.snapshot(sketches=True)
        # the router's cost table is a pong-fed MIRROR of the workers'
        # (kept so the router-side timeline/watchdog track spend live);
        # merging it alongside the authoritative worker tables would
        # double every charge
        own.pop("costs", None)
        workers = self.worker_snapshots(timeout=timeout)
        # every completed request has a latency sample in BOTH tiers
        # (router end-to-end, worker-internal) — merging both sketches
        # into one quantile pool would double the count and blend two
        # populations. The merged 'latency' is the END-TO-END tier;
        # worker-internal latency stays readable via worker_snapshots().
        # Worker queue-age sketches have no router counterpart and merge
        # as-is.
        workers = [
            (
                {**snap, "sketch": {
                    k: v for k, v in (snap.get("sketch") or {}).items()
                    if k != "latencies"
                }}
                if snap.get("sketch") else snap
            )
            for snap in workers
        ]
        merged = MetricsRegistry.merge([own] + workers, name="cluster")
        # 'submitted'/'completed' exist at BOTH tiers for the same
        # requests (front door and worker fleet) — a blind sum double
        # counts. The fleet-wide truth is the router's own count; the
        # worker-tier sum (which can exceed it under requeues) keeps its
        # own key.
        c = merged["counters"]
        for key in ("submitted", "completed"):
            total, mine = c.get(key, 0), own["counters"].get(key, 0)
            if total - mine:
                c[f"worker_{key}"] = total - mine
            c[key] = mine
        return merged

    # -- cross-process trace stitching + fleet status --------------------

    def collect_trace(self, timeout: float = 2.0) -> List[List[dict]]:
        """Every process's span set in wire form: the router's own spans
        plus what each worker has shipped (a stats round-trip first, so
        fresh worker spans arrive). Ready for
        :func:`keystone_tpu.obs.export.stitch_chrome_trace`.

        Collection COMPACTS: the router's fresh spans move from the
        process tracer into a bounded buffer (and workers discard what
        they ship), so a deployment that exports periodically holds a
        bounded window per process — the stitched file is the archive.
        A traced router that never collects keeps the ordinary
        process-tracer contract (spans retained for the atexit export)."""
        from ..obs.export import wire_spans

        # a stats request makes every live worker ship its fresh spans;
        # the reply handler accumulates them on the slots
        self.worker_snapshots(timeout=timeout)
        sets: List[List[dict]] = []
        tracer = _trace_current()
        if tracer is not None:
            # serialized OUTSIDE the admission lock (a first collect
            # after a long traced window may hold many spans, and
            # submit()/answer settlement must not stall behind it);
            # _own_trace_lock serializes concurrent collectors
            with self._own_trace_lock:
                fresh, self._own_span_cursor = tracer.spans_since(
                    self._own_span_cursor
                )
                # only what the bounded buffer will keep gets serialized
                self._own_trace_spans.extend(wire_spans(
                    fresh[-8192:], tracer.epoch, tracer.epoch_unix,
                    process_name=f"keystone:router/{os.getpid()}",
                ))
                del self._own_trace_spans[:-8192]
                tracer.discard_through(self._own_span_cursor)
                if self._own_trace_spans:
                    sets.append(list(self._own_trace_spans))
        with self._lock:
            for slot in self._slots:
                if slot.trace_spans:
                    sets.append(list(slot.trace_spans))
        return sets

    def export_trace(self, path: str, timeout: float = 2.0) -> str:
        """Write ONE stitched Chrome-trace/Perfetto JSON covering the
        whole process tier: real per-pid process tracks, worker spans
        rebased onto the shared unix clock, and each sampled request's
        hops tied together by its ``trace_id`` attr."""
        from ..obs.export import write_stitched_trace

        return write_stitched_trace(self.collect_trace(timeout=timeout), path)

    @staticmethod
    def _qos_view(snap: dict) -> dict:
        """The QoS digest off a merged snapshot: per-tenant served
        counts (and their share of total service — the weighted-fair
        convergence evidence, summed across worker processes), sheds by
        priority class (all tiers), and per-priority latency
        quantiles."""
        c = snap.get("counters") or {}
        served = {
            k[len("tenant.served."):]: int(v)
            for k, v in c.items()
            if k.startswith("tenant.served.")
        }
        total = sum(served.values())
        return {
            "tenant_served": served,
            "tenant_share": (
                {t: round(n / total, 4) for t, n in sorted(served.items())}
                if total else {}
            ),
            "shed_by_priority": {
                p: int(c.get(f"shed.{p}", 0)) for p in PRIORITIES
            },
            "priority_latency": snap.get("priority_latency") or {},
        }

    def status(self, timeout: float = 2.0, snap: Optional[dict] = None) -> dict:
        """The fleet-wide timeline view: liveness + capacity, the merged
        counters/quantiles, each tier's bounded metrics timeline (kept
        per-process — see ``MetricsRegistry.merge``), restart budgets,
        and the SLO verdicts. The programmatic form behind the demo
        CLI's ``--status`` rendering (:func:`format_status`). ``snap``
        reuses a merged snapshot the caller already paid the worker
        stats round-trip for."""
        if snap is None:
            snap = self.snapshot(timeout=timeout)
        with self._lock:
            workers = [
                {
                    "index": s.index,
                    "alive": s.alive,
                    "pid": s.proc.pid if s.proc is not None else None,
                    "capacity": s.capacity,
                    "restarts": s.restarts,
                    "outstanding": len(s.outstanding),
                    "respawning": s.respawning,
                    "booting": s.booting,
                    "draining": s.draining,
                    "retired": s.retired,
                }
                for s in self._slots
            ]
        timelines = dict(snap.get("timelines") or {})
        # the router's own rows ride under its registry name so the view
        # shows every tier side by side, never blended; a status read
        # before the first health tick samples one row rather than
        # rendering an empty tier
        own_rows = self._metrics.timeline()
        if not own_rows:
            own_rows = [self._metrics.sample_timeline()]
        timelines.setdefault(self._metrics.name, own_rows)
        out = {
            "workers": workers,
            "live_workers": sum(1 for w in workers if w["alive"]),
            "outstanding": self.outstanding,
            "capacity": self.capacity,
            "counters": snap.get("counters", {}),
            "costs": snap.get("costs", {}),
            "latency": snap.get("latency", {}),
            "queue_age": snap.get("queue_age", {}),
            "batch_occupancy": snap.get("batch_occupancy"),
            "timelines": timelines,
            "slo": None,
            "qos": self._qos_view(snap),
            "autoscale": (
                dict(
                    self._autoscaler.describe(),
                    view=self.scale_view(),
                )
                if self._autoscaler is not None else None
            ),
        }
        if self._watchdog is not None:
            from dataclasses import asdict

            out["slo"] = {
                "policy": {
                    k: v
                    for k, v in asdict(self._watchdog.policy).items()
                    if v is not None
                },
                "breaches": [
                    b.as_attrs() | {"ts": b.ts}
                    for b in self._watchdog.breaches[-32:]
                ],
            }
        return out

    # -- shutdown --------------------------------------------------------

    def install_signal_handlers(self) -> None:
        """SIGTERM → bounded drain-and-stop (satellite contract: a
        TERM'd router drains workers with per-process join timeouts and
        never hangs a smoke run).

        The handler only SPAWNS the shutdown thread: it may interrupt
        the main thread INSIDE a router critical section, and calling
        ``shutdown`` (which takes the same non-reentrant lock) from the
        handler frame would deadlock exactly the path this exists to
        keep bounded."""

        def _on_term(signum, frame):
            logger.warning(
                "cluster: SIGTERM — draining and shutting down"
            )

            def _stop():
                self.shutdown(drain=True)
                if callable(self._prev_sigterm):
                    self._prev_sigterm(signum, frame)

            threading.Thread(
                target=_stop, name="ks-router-sigterm", daemon=False
            ).start()

        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)

    def shutdown(self, drain: bool = True) -> None:
        """Stop the tier. Bounded: the drain wait, every worker stop,
        every process join, and every receive-thread join have timeouts;
        a wedged worker is WARNed, force-killed, and its in-flight
        requests failed typed. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            flush: List[_PendingReq] = list(self._coalesce_q)
            self._coalesce_q = deque()
            self._cond.notify_all()
        exporter, self._exporter = self._exporter, None
        if exporter is not None:
            exporter.stop()
        if drain and flush:
            # admitted but not yet placed when the shutdown hit: a
            # draining shutdown still owes these real answers — dispatch
            # the tail now (workers are stopped only after the drain
            # wait), single frames, no coalesce window
            for req in flush:
                self._dispatch([req], during_shutdown=True)
            flush = []
        if drain:
            deadline = time.monotonic() + self._drain_timeout_s
            with self._cond:
                while self._pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        logger.warning(
                            "cluster shutdown: drain did not finish "
                            "within %.1fs (%d request(s) in flight; "
                            "wedged worker?) — failing the remainder",
                            self._drain_timeout_s, len(self._pending),
                        )
                        break
                    self._cond.wait(timeout=min(0.2, remaining))
        for slot in self._slots:
            if slot.alive and slot.sock is not None:
                try:
                    self._send_control(
                        slot, {"type": "stop", "drain": drain}
                    )
                except Exception:
                    logger.debug(
                        "stop message to worker %d failed (already dead?)",
                        slot.index, exc_info=True,
                    )
        import subprocess

        for slot in self._slots:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=self._join_timeout_s)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "cluster shutdown: worker %d (pid %s) did not exit "
                    "within %.1fs — terminating it and failing its "
                    "in-flight work", slot.index, proc.pid,
                    self._join_timeout_s,
                )
                proc.terminate()
                try:
                    proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    try:
                        proc.wait(timeout=2.0)
                    except subprocess.TimeoutExpired:
                        pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for slot in self._slots:
            slot.alive = False
            t = slot.recv_thread
            if t is not None and t is not threading.current_thread():
                t.join(timeout=2.0)
                if t.is_alive():
                    logger.warning(
                        "cluster shutdown: receive thread for worker %d "
                        "did not exit — abandoning it (daemon)",
                        slot.index,
                    )
        # the belt-and-braces sweep: every admitted request gets an
        # answer, typed — including anything a non-draining shutdown
        # left in the coalesce queue
        with self._lock:
            remaining = (
                list(self._pending.values()) + self._parked
                + flush + list(self._coalesce_q)
            )
            self._pending.clear()
            self._parked = []
            self._coalesce_q = deque()
            for slot in self._slots:
                self._release_rings(slot)
        for req in remaining:
            settle_future(
                req.future, EngineStopped("cluster router is shut down")
            )
        if remaining:
            logger.warning(
                "cluster shutdown: failed %d unanswered request(s) typed",
                len(remaining),
            )

    def __enter__(self) -> "ClusterRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)


def format_status(status: dict) -> str:
    """Render :meth:`ClusterRouter.status` as the operator-facing text
    view: a worker table, headline counters, and each tier's metrics
    timeline as one line per sample (windowed counters + p99s) — the
    queue-age-over-time picture a point snapshot cannot give."""
    lines = [
        "cluster status: workers {}/{} capacity {} outstanding {}".format(
            status.get("live_workers", 0),
            len(status.get("workers") or []),
            status.get("capacity", 0),
            status.get("outstanding", 0),
        )
    ]
    for w in status.get("workers") or []:
        lines.append(
            "  worker {index}: {state} pid={pid} capacity={capacity} "
            "restarts={restarts} outstanding={outstanding}".format(
                state=(
                    "draining" if w.get("draining")
                    else "retired" if w.get("retired")
                    else "booting" if w.get("booting")
                    else "respawning" if w.get("respawning")
                    else "up" if w.get("alive") else "DOWN"
                ),
                **{k: w.get(k) for k in (
                    "index", "pid", "capacity", "restarts", "outstanding"
                )},
            )
        )
    c = status.get("counters") or {}
    lat = status.get("latency") or {}
    lines.append(
        "  counters: completed={} shed={} rejected={} restarts={} "
        "requeues={} slo_breaches={} p99={}".format(
            c.get("completed", 0), c.get("shed", 0), c.get("rejected", 0),
            c.get("restarts", 0), c.get("requeues", 0),
            c.get("slo_breaches", 0),
            round(lat["p99"], 4) if "p99" in lat else None,
        )
    )
    wire = {
        k[len("wire.frames."):]: v for k, v in c.items()
        if k.startswith("wire.frames.")
    }
    if wire:
        sent = {
            k[len("wire.bytes_sent."):]: v for k, v in c.items()
            if k.startswith("wire.bytes_sent.")
        }
        lines.append(
            "  wire: " + " ".join(
                "{}={}f/{}B".format(kind, n, sent.get(kind, 0))
                for kind, n in sorted(wire.items())
            ) + " coalesce_frames={} coalesce_members={} "
            "shm_payloads={} shm_fallback={}".format(
                c.get("coalesce.frames", 0),
                c.get("coalesce.members", 0),
                c.get("shm.payloads", 0),
                c.get("shm.fallback", 0),
            )
        )
    qos = status.get("qos") or {}
    served = qos.get("tenant_served") or {}
    sheds = qos.get("shed_by_priority") or {}
    if served:
        shares = qos.get("tenant_share") or {}
        lines.append(
            "  qos tenants: " + ", ".join(
                "{}: served={} share={}".format(t, n, shares.get(t))
                for t, n in sorted(served.items())
            )
        )
    if any(sheds.values()):
        lines.append(
            "  qos shed by priority: " + " ".join(
                f"{p}={sheds.get(p, 0)}" for p in ("high", "normal", "low")
            )
        )
    costs = status.get("costs") or {}
    if costs:
        for tenant, prios in sorted(costs.items()):
            total = {
                "device_s": 0.0, "queue_s": 0.0,
                "payload_bytes": 0, "items": 0,
            }
            for row in prios.values():
                for k in total:
                    total[k] += row.get(k) or 0
            split = " ".join(
                f"{p}={round(r.get('device_s') or 0.0, 4)}s"
                for p, r in sorted(prios.items())
            )
            lines.append(
                "  cost [{}]: device_s={} queue_s={} payload_mb={} "
                "items={} ({})".format(
                    tenant,
                    round(total["device_s"], 4),
                    round(total["queue_s"], 4),
                    round(total["payload_bytes"] / 1e6, 3),
                    int(total["items"]),
                    split,
                )
            )
    plat = qos.get("priority_latency") or {}
    if plat:
        lines.append(
            "  qos p99 by priority: " + " ".join(
                "{}={}".format(
                    p, round(q["p99"], 4) if "p99" in q else None
                )
                for p, q in sorted(plat.items())
            )
        )
    asc = status.get("autoscale")
    if asc:
        view = asc.get("view") or {}
        lines.append(
            "  autoscale: target={} admitting={} booting={} draining={} "
            "policy={}".format(
                asc.get("target"), view.get("admitting"),
                view.get("booting"), view.get("draining"),
                asc.get("policy"),
            )
        )
        for d in (asc.get("decisions") or [])[-8:]:
            lines.append(
                "    SCALE {action} {from_workers}->{to_workers} "
                "[{verdict}] worker={worker} reason={reason}{trig}".format(
                    action=d.get("action"),
                    from_workers=d.get("from_workers"),
                    to_workers=d.get("to_workers"),
                    verdict="ok" if d.get("ok") else "ABORTED",
                    worker=d.get("worker"),
                    reason=d.get("reason"),
                    trig=(
                        f" trigger={d.get('trigger')}"
                        if d.get("trigger") else ""
                    ),
                )
            )
    slo = status.get("slo")
    if slo:
        lines.append(f"  slo policy: {slo.get('policy')}")
        for b in (slo.get("breaches") or [])[-8:]:
            lines.append(
                "    BREACH {objective}{who}: observed {observed} vs "
                "budget {budget}".format(
                    who=(
                        " [{}]".format(b["detail"]) if b.get("detail") else ""
                    ),
                    **{k: v for k, v in b.items() if k != "detail"},
                )
            )
    for name, rows in sorted((status.get("timelines") or {}).items()):
        lines.append(f"  timeline [{name}] ({len(rows)} samples):")
        for row in rows[-10:]:
            lat = row.get("latency") or {}
            age = row.get("queue_age") or {}
            lines.append(
                "    t={:.1f} counters={} p99={} queue_age_p99={}".format(
                    row.get("ts", 0.0),
                    row.get("counters") or {},
                    round(lat["p99"], 4) if "p99" in lat else None,
                    round(age["p99"], 4) if "p99" in age else None,
                )
            )
    return "\n".join(lines)


def settle_result(fut: Future, value: Any) -> bool:
    """set_result regardless of PENDING/RUNNING state; False when the
    future was already settled (a requeue raced the original answer)."""
    if fut.done():
        return False
    try:
        try:
            if not fut.set_running_or_notify_cancel():
                return False
        except Exception:  # lint: allow-silent -- already RUNNING by design
            pass
        fut.set_result(value)
        return True
    except Exception:  # lint: allow-silent -- lost the set-once race: fine
        return False
