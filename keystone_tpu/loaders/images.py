"""Image ingestion: tar-of-JPEG streaming + host-side decode.

Parity targets: ``loaders/ImageLoaderUtils.scala:22-117`` (tar streaming +
per-entry decode + label-from-entry-name), ``loaders/ImageNetLoader.scala:11``
(directory-name → class id via a labels map file), ``loaders/VOCLoader.scala:15``
(filename → multi-label via a CSV), ``utils/images/ImageUtils.scala:16-46``
(decode rules: skip images with either side < 36 px, accept RGB or grayscale,
skip anything else or undecodable).

Design notes (TPU-first, intentionally different from the reference):

* The reference keeps every image at its native size as an ``Image`` object
  per RDD row; SIFT/DAISY then run per-image on ragged shapes. XLA wants
  static shapes, so this loader takes an explicit **size policy**:

  - ``size=None`` — parity mode: a Dataset of per-item ``(x, y, c)`` float
    arrays at native sizes (host list payload). Batched featurizers fall
    back to their per-item path.
  - ``size=(X, Y)`` — canonical mode: bilinear-resize every image to one
    shape and return a single ``(n, X, Y, C)`` batch ready for HBM. This is
    the documented deviation that makes the featurizers one fused program.

* Decode runs on host (PIL); this is the host data plane that Spark gave
  the reference for free (SURVEY §5.8). Arrays are float32 in [0, 255],
  channel order RGB, axes (x=row, y=col, c) matching nodes/images/core.py.
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Dataset

logger = logging.getLogger(__name__)

#: reference's minimum acceptable side (ImageUtils.scala:20-23)
MIN_DIM = 36


def decode_image_bytes(
    data: bytes,
    min_dim: int = MIN_DIM,
    size: Optional[Tuple[int, int]] = None,
    dtype=np.uint8,
) -> Optional[np.ndarray]:
    """JPEG/PNG bytes → (x, y, c) array in [0,255], or None.

    .. warning:: BEHAVIOR CHANGE (round 4): the default return dtype is
       ``uint8``, not ``float32``. Host-side float arithmetic on the
       result (mean subtraction, scaling) silently wraps around at 8 bits
       — pass ``dtype=np.float32`` explicitly if you compute on the host.

    uint8 by default — a TPU-first ingestion decision, not an accident:
    decoded pixels ARE bytes, and keeping them so until the device means
    4× less host RAM and 4× less host→device transfer than the
    reference's double-matrix images (`ImageUtils.scala`); the image
    pipelines' entry transformers (PixelScaler/GrayScaler/LCSExtractor)
    cast to f32 on device, inside the fused serve program.

    Mirrors ImageUtils.loadImage: undecodable → None; either side < min_dim
    → None; modes other than RGB/grayscale are converted rather than
    dropped (PIL can, ImageIO couldn't). ``size=(X, Y)`` bilinear-resizes.
    """
    from PIL import Image as PILImage

    try:
        img = PILImage.open(io.BytesIO(data))
        img.load()
    except Exception as e:  # undecodable — reference logs + skips
        logger.warning("failed to parse image: %s", e)
        return None
    if img.height < min_dim or img.width < min_dim:
        logger.warning("ignoring small image %dx%d", img.height, img.width)
        return None
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    if size is not None:
        # canonical-batch mode must yield uniform (X, Y, 3): real tars mix
        # grayscale and RGB JPEGs, and np.stack needs one channel count
        if img.mode != "RGB":
            img = img.convert("RGB")
        # PIL size is (width, height) = (y, x)
        img = img.resize((size[1], size[0]), PILImage.BILINEAR)
    arr = np.asarray(img, dtype=dtype)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def iter_tar_images(
    tar_path: str,
    name_prefix: Optional[str] = None,
    min_dim: int = MIN_DIM,
    size: Optional[Tuple[int, int]] = None,
) -> Iterator[Tuple[str, np.ndarray]]:
    """Stream (entry_name, image array) from a tar of image files
    (parity: ImageLoaderUtils.loadFile's TarArchiveInputStream walk)."""
    with tarfile.open(tar_path, "r:*") as tf:
        for entry in tf:
            if not entry.isfile():
                continue
            if name_prefix and not entry.name.startswith(name_prefix):
                continue
            fobj = tf.extractfile(entry)
            if fobj is None:
                continue
            arr = decode_image_bytes(fobj.read(), min_dim=min_dim, size=size)
            if arr is not None:
                yield entry.name, arr


def _tar_paths(data_path: str) -> List[str]:
    """A tar file, or every non-directory file in a directory of tars
    (parity: getFilePathsRDD listing the data dir)."""
    if os.path.isdir(data_path):
        return sorted(
            os.path.join(data_path, f)
            for f in os.listdir(data_path)
            if os.path.isfile(os.path.join(data_path, f))
            and tarfile.is_tarfile(os.path.join(data_path, f))
        )
    return [data_path]


def _package(images: List[np.ndarray], size) -> Dataset:
    if size is not None and images:
        return Dataset(np.stack(images), batched=True)
    return Dataset.from_items(images)


class LabeledImages:
    """Images + int labels (+ entry names). ``data`` is a Dataset of images
    (batched under a size policy, per-item list otherwise)."""

    def __init__(self, images: List[np.ndarray], labels, names: List[str], size):
        self.data = _package(images, size)
        self.labels = np.asarray(labels)
        self.names = names

    def __len__(self) -> int:
        return len(self.names)


def read_labels_map(labels_path: str) -> Dict[str, int]:
    """'<dirname> <int>' per line (parity: ImageNetLoader.scala:27-32)."""
    out: Dict[str, int] = {}
    with open(labels_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            parts = line.split()  # any whitespace, tolerant of runs/tabs
            if len(parts) < 2:
                raise ValueError(
                    f"{labels_path}:{lineno}: expected '<classdir> <int>', "
                    f"got {line!r}"
                )
            out[parts[0]] = int(parts[1])
    return out


def load_imagenet(
    data_path: str,
    labels_path: str,
    size: Optional[Tuple[int, int]] = None,
    min_dim: int = MIN_DIM,
) -> LabeledImages:
    """Tar(s) of images under class-named directories; label = map[dirname]
    (parity: ImageNetLoader.apply + labelsMapF splitting on '/')."""
    labels_map = read_labels_map(labels_path)
    images, labels, names = [], [], []
    unmapped = set()
    for tar_path in _tar_paths(data_path):
        for name, arr in iter_tar_images(tar_path, min_dim=min_dim, size=size):
            class_dir = name.lstrip("./").split("/")[0]
            if class_dir not in labels_map:
                unmapped.add(class_dir)
                continue
            images.append(arr)
            labels.append(labels_map[class_dir])
            names.append(name)
    if unmapped:
        logger.warning("skipped entries from unmapped class dirs: %s",
                       sorted(unmapped))
    return LabeledImages(images, np.asarray(labels, dtype=np.int32), names, size)


def read_voc_labels(labels_path: str) -> Dict[str, List[int]]:
    """VOC label CSV: header row, columns where parts[4] is the quoted file
    name and parts[1] the 1-indexed class (parity: VOCLoader.scala:33-48;
    a file appears once per object instance → multi-label)."""
    import csv

    out: Dict[str, List[int]] = {}
    with open(labels_path, newline="") as f:
        reader = csv.reader(f)  # honors quoted fields containing commas
        rows = list(reader)
    for lineno, parts in enumerate(rows[1:], 2):
        if not parts or not any(p.strip() for p in parts):
            continue
        if len(parts) < 5:
            raise ValueError(
                f"{labels_path}:{lineno}: expected >=5 CSV columns "
                f"(VOCLoader format), got {len(parts)}"
            )
        fname = parts[4]
        label = int(parts[1]) - 1
        out.setdefault(fname, []).append(label)
    return out


class MultiLabeledImages:
    """Images + per-image label lists (VOC: multiple objects per image)."""

    def __init__(self, images: List[np.ndarray], labels: List[List[int]],
                 names: List[str], size):
        self.data = _package(images, size)
        self.labels = labels
        self.names = names

    def label_matrix(self, num_classes: int) -> np.ndarray:
        """±1 multi-label indicator matrix (the solver-facing form),
        via the canonical MultiClassLabelIndicators node."""
        from ..nodes.util import MultiClassLabelIndicators

        ds = MultiClassLabelIndicators(num_classes).apply_batch(
            Dataset.from_items(list(self.labels))
        )
        return np.asarray(ds.to_array(), dtype=np.float32)

    def __len__(self) -> int:
        return len(self.names)


def load_voc(
    data_path: str,
    labels_path: str,
    name_prefix: Optional[str] = None,
    size: Optional[Tuple[int, int]] = None,
    min_dim: int = MIN_DIM,
) -> MultiLabeledImages:
    """VOC tar + label CSV → multi-labeled images (parity:
    VOCLoader.apply; the basename keys the label map)."""
    labels_map = read_voc_labels(labels_path)
    images, labels, names = [], [], []
    for tar_path in _tar_paths(data_path):
        for name, arr in iter_tar_images(
            tar_path, name_prefix=name_prefix, min_dim=min_dim, size=size
        ):
            # the CSV keys are full tar-entry paths (VOCLoader.scala:41
            # builds the map from parts(4) verbatim); accept a basename
            # match as a convenience for hand-built fixtures
            key = name if name in labels_map else os.path.basename(name)
            if key not in labels_map:
                continue
            images.append(arr)
            labels.append(labels_map[key])
            names.append(name)
    return MultiLabeledImages(images, labels, names, size)
