"""CSV loading (parity: loaders/CsvDataLoader.scala:10-34 — comma/space
split rows → vectors) plus the (label, features) convention used by the MNIST
pipeline (pipelines/images/mnist/MnistRandomFFT.scala:35-38: column 0 is a
1-indexed class label)."""

from __future__ import annotations

import numpy as np

from ..data.dataset import Dataset


def load_csv(path: str, dtype=np.float32) -> Dataset:
    """Load a numeric CSV (comma or whitespace separated) as a batched
    Dataset of rows."""
    try:
        arr = np.loadtxt(path, delimiter=",", dtype=dtype, ndmin=2)
    except ValueError:
        arr = np.loadtxt(path, dtype=dtype, ndmin=2)
    return Dataset.from_array(arr)


def load_labeled_csv(
    path: str, label_offset: int = 0, dtype=np.float32
) -> "LabeledData":
    """Column 0 = class label (minus ``label_offset``), rest = features."""
    arr = np.asarray(load_csv(path, dtype=dtype).payload)
    labels = arr[:, 0].astype(np.int32) - label_offset
    return LabeledData(labels, arr[:, 1:])


class LabeledData:
    """A labeled dataset: ``.data`` and ``.labels`` (parity:
    loaders/LabeledData.scala:12)."""

    def __init__(self, labels, data):
        self.labels = Dataset.of(labels)
        self.data = Dataset.of(data)

    def __len__(self) -> int:
        return len(self.data)
