from .csv_loader import LabeledData, load_csv, load_labeled_csv
from .text import (
    NEWSGROUPS_CLASSES,
    TimitFeaturesData,
    load_amazon_reviews,
    load_newsgroups,
    load_timit_features,
)

__all__ = [
    "LabeledData",
    "load_csv",
    "load_labeled_csv",
    "NEWSGROUPS_CLASSES",
    "TimitFeaturesData",
    "load_amazon_reviews",
    "load_newsgroups",
    "load_timit_features",
]
