from .csv_loader import LabeledData, load_csv, load_labeled_csv

__all__ = ["LabeledData", "load_csv", "load_labeled_csv"]
