"""Text and speech-feature loaders.

Parity: loaders/NewsgroupsDataLoader.scala:9-52 (class-per-directory
plaintext docs), loaders/AmazonReviewsDataLoader.scala:7-29 (JSON reviews →
binary labels by rating threshold), loaders/TimitFeaturesDataLoader.scala:15-75
(pre-featurized CSV + "row label" sparse label files).

All host-side filesystem work — the reference used Spark's wholeTextFiles /
Spark SQL JSON; here plain directory walks and json-lines parsing feed the
same LabeledData shape.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..data.dataset import Dataset
from .csv_loader import LabeledData, load_csv

# The 20 Newsgroups class labels / directory names
# (NewsgroupsDataLoader.scala:11-32)
NEWSGROUPS_CLASSES = (
    "comp.graphics",
    "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware",
    "comp.sys.mac.hardware",
    "comp.windows.x",
    "rec.autos",
    "rec.motorcycles",
    "rec.sport.baseball",
    "rec.sport.hockey",
    "sci.crypt",
    "sci.electronics",
    "sci.med",
    "sci.space",
    "misc.forsale",
    "talk.politics.misc",
    "talk.politics.guns",
    "talk.politics.mideast",
    "talk.religion.misc",
    "alt.atheism",
    "soc.religion.christian",
)


def load_newsgroups(data_dir: str) -> LabeledData:
    """``data_dir/<class_name>/<doc files>`` → (int labels, doc strings)
    (parity: NewsgroupsDataLoader.apply). Classes absent on disk are
    skipped, matching wholeTextFiles over missing dirs yielding nothing."""
    labels, docs = [], []
    for index, class_name in enumerate(NEWSGROUPS_CLASSES):
        class_dir = os.path.join(data_dir, class_name)
        if not os.path.isdir(class_dir):
            continue
        for fname in sorted(os.listdir(class_dir)):
            fpath = os.path.join(class_dir, fname)
            if not os.path.isfile(fpath):
                continue
            with open(fpath, "r", encoding="utf-8", errors="replace") as f:
                docs.append(f.read())
            labels.append(index)
    return LabeledData(
        np.asarray(labels, dtype=np.int32), Dataset.from_items(docs)
    )


def load_amazon_reviews(path: str, threshold: float = 3.5) -> LabeledData:
    """JSON-lines reviews with "overall" rating and "reviewText" →
    binary labels (rating ≥ threshold ⇒ 1)
    (parity: AmazonReviewsDataLoader.apply)."""
    labels, docs = [], []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            labels.append(1 if float(rec["overall"]) >= threshold else 0)
            docs.append(rec.get("reviewText", ""))
    return LabeledData(
        np.asarray(labels, dtype=np.int32), Dataset.from_items(docs)
    )


TIMIT_DIMENSION = 440  # TimitFeaturesDataLoader.timitDimension
TIMIT_NUM_CLASSES = 147  # TimitFeaturesDataLoader.numClasses


class TimitFeaturesData:
    """(parity: TimitFeaturesData case class)."""

    def __init__(self, train: LabeledData, test: LabeledData):
        self.train = train
        self.test = test


def _parse_sparse_labels(path: str) -> dict:
    """Lines "row label" (1-indexed rows)
    (parity: parseSparseLabels, TimitFeaturesDataLoader.scala:22-33)."""
    out = {}
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                out[int(parts[0]) - 1] = int(parts[1])
    return out


def load_timit_features(
    train_data: str,
    train_labels: str,
    test_data: str,
    test_labels: Optional[str] = None,
) -> TimitFeaturesData:
    """Pre-featurized TIMIT CSVs + sparse label files; labels are shifted
    to 0-indexed classes (parity: TimitFeaturesDataLoader.apply — the
    ``labelsMap(row) - 1``)."""

    def one(data_path, labels_path):
        X = np.asarray(load_csv(data_path).payload)
        lmap = _parse_sparse_labels(labels_path)
        y = np.asarray(
            [lmap[i] - 1 for i in range(X.shape[0])], dtype=np.int32
        )
        return LabeledData(y, X)

    return TimitFeaturesData(
        one(train_data, train_labels),
        one(test_data, test_labels or train_labels),
    )
